"""Runtime concurrency sanitizer: lock-order cycles and unguarded access.

The dynamic half of the concurrency checker (static half: the H7xx
rules in :mod:`~heat_tpu.analysis.ast_lint`).  Every lock in
:data:`~heat_tpu.analysis.concurrency.LOCK_REGISTRY` is created through
:func:`register_lock`, which returns an instrumented proxy.  Disarmed
(the production default) the proxy costs one module-global read per
acquire/release.  Armed (``HEAT_TPU_TSAN=1``, or :func:`arm`), every
acquisition records a compact per-thread stack and feeds the global
**lock-order graph**; every :func:`note_access` checkpoint at a
registered shared structure verifies the accessing thread either holds
the structure's registered lock or is the main thread.  Two finding
kinds result, reported as structured
:class:`~heat_tpu.analysis.diagnostics.Diagnostic` records (rule IDs
``tsan.lock_cycle`` / ``tsan.unguarded_access``) that flow into the
telemetry registry (``analysis.diags.{rule}`` counters), the
recent-diagnostics ring, and the flight-recorder crash bundle:

* **lock_cycle** — the lock-order graph acquired a cycle: some thread
  took A then B while another path takes B then A.  Both acquisition
  stacks (the edge that closed the cycle and the recorded reverse
  path) are attached.  This is a *potential deadlock* even if the run
  never wedged — the interleaving that deadlocks is a scheduler
  accident away.
* **unguarded_access** — a registered shared structure (metrics
  registry, dispatch cache, span ring, fault-site counters,
  async-writer state) was touched from a non-main thread without its
  registered lock held.  The accessing stack and the most recent
  recorded access stack are both attached.

``HEAT_TPU_TSAN=raise`` additionally raises
:class:`~heat_tpu.analysis.diagnostics.ProgramLintError` at the finding
site (the sanitized CI lane's mode); ``HEAT_TPU_TSAN_DUMP=<path>``
writes the findings list as JSON at process exit so a test-runner
subprocess can be audited from outside.

Findings are kept in a process-lifetime list (:func:`findings`) that
``telemetry.reset_all()`` does NOT clear — a sanitized test lane counts
them across the whole run.  This module is pure stdlib at import time
(telemetry/diagnostics are imported lazily at the first finding), so
the low-level modules that create locks at import — ``telemetry.
metrics`` is among the first modules the package loads — can depend on
it without cycles.
"""

from __future__ import annotations

import atexit
import json
import os
import sys
import threading
from typing import Any, Dict, List, Optional, Tuple

from .concurrency import LOCK_REGISTRY, registered_structures

__all__ = [
    "TsanLock",
    "arm",
    "clear_findings",
    "disarm",
    "enabled",
    "finding_count",
    "findings",
    "lock_graph",
    "mode",
    "note_access",
    "refresh_env",
    "register_lock",
    "register_structure",
]

MODE_OFF = "off"
MODE_WARN = "warn"
MODE_RAISE = "raise"

_MODE_ALIASES = {
    "0": MODE_OFF, "off": MODE_OFF, "false": MODE_OFF, "no": MODE_OFF,
    "1": MODE_WARN, "on": MODE_WARN, "warn": MODE_WARN, "true": MODE_WARN,
    "raise": MODE_RAISE, "error": MODE_RAISE, "2": MODE_RAISE,
}

#: findings list bound (a runaway finding loop must not grow unbounded)
_MAX_FINDINGS = 256


def _parse_mode(raw: Optional[str]) -> str:
    if raw is None:
        raw = "0"
    m = _MODE_ALIASES.get(str(raw).strip().lower())
    if m is None:
        raise ValueError(f"HEAT_TPU_TSAN={raw!r}: expected one of 0/1/raise")
    return m


# direct environ reads (the knobs ARE registered in core/_env.py KNOBS):
# this module must import without jax, which core._env pulls in
_MODE = _parse_mode(os.environ.get("HEAT_TPU_TSAN"))
_ARMED = _MODE != MODE_OFF
_STACK_DEPTH = int(os.environ.get("HEAT_TPU_TSAN_STACK_DEPTH", "10") or "10")

_TLS = threading.local()

#: internal bookkeeping lock — deliberately a RAW lock, not a TsanLock:
#: the sanitizer must not sanitize itself
_STATE_LOCK = threading.Lock()

#: (a, b) -> edge record: lock a was held while lock b was acquired
_EDGES: Dict[Tuple[str, str], Dict[str, Any]] = {}

#: cycles already reported (frozenset of member locks) — report once
_REPORTED_CYCLES: set = set()

#: (structure, location) pairs already reported — report once per site
_REPORTED_ACCESS: set = set()

#: process-lifetime findings (NOT cleared by telemetry.reset_all)
_FINDINGS: List[Dict[str, Any]] = []

#: structure name -> owning lock name (registry + test additions)
_STRUCTS: Dict[str, str] = registered_structures()

#: most recent access stack per structure (attached to unguarded reports)
_LAST_ACCESS: Dict[str, Tuple[str, ...]] = {}


def mode() -> str:
    """Current sanitizer mode: ``"off"``, ``"warn"`` or ``"raise"``."""
    return _MODE


def enabled() -> bool:
    """Whether the sanitizer is armed (recording)."""
    return _ARMED


def arm(new_mode: str = "1") -> str:
    """Arm the sanitizer at runtime (overrides the env var); accepts the
    env spellings (``1``/``raise``); returns the previous mode."""
    global _MODE, _ARMED
    prev = _MODE
    _MODE = _parse_mode(new_mode)
    if _MODE == MODE_OFF:
        raise ValueError("arm() needs an armed mode (1/raise); use disarm()")
    _ARMED = True
    return prev


def disarm() -> str:
    """Disarm the sanitizer; held-lock bookkeeping stops immediately
    (per-thread held lists are cleared lazily); returns the previous
    mode."""
    global _MODE, _ARMED
    prev = _MODE
    _MODE = MODE_OFF
    _ARMED = False
    return prev


def refresh_env() -> str:
    """Re-read ``HEAT_TPU_TSAN`` (tests that flip the env var
    mid-process); returns the new mode."""
    global _MODE, _ARMED
    _MODE = _parse_mode(os.environ.get("HEAT_TPU_TSAN"))
    _ARMED = _MODE != MODE_OFF
    return _MODE


def findings() -> List[Dict[str, Any]]:
    """Every finding recorded this process (bounded), oldest first."""
    with _STATE_LOCK:
        return [dict(f) for f in _FINDINGS]


def finding_count() -> int:
    """Number of findings recorded this process."""
    with _STATE_LOCK:
        return len(_FINDINGS)


def clear_findings() -> None:
    """Drop recorded findings, the lock-order graph, and the
    report-once dedup state (test isolation)."""
    with _STATE_LOCK:
        _FINDINGS.clear()
        _EDGES.clear()
        _REPORTED_CYCLES.clear()
        _REPORTED_ACCESS.clear()
        _LAST_ACCESS.clear()


def lock_graph() -> Dict[Tuple[str, str], Dict[str, Any]]:
    """Copy of the recorded lock-order edges: ``(held, acquired) ->
    {stacks, threads, count}``."""
    with _STATE_LOCK:
        return {k: dict(v) for k, v in _EDGES.items()}


# ----------------------------------------------------------------------
# registration
# ----------------------------------------------------------------------
def register_structure(name: str, lock_name: str) -> None:
    """Register an extra guarded structure at runtime (tests; production
    structures belong in ``concurrency.LOCK_REGISTRY``)."""
    _STRUCTS[name] = lock_name


def register_lock(name: str, lock=None) -> "TsanLock":
    """Create the registered lock ``name`` as an instrumented proxy.

    ``name`` must appear in ``concurrency.LOCK_REGISTRY`` (names under
    ``test.`` are exempt, for fixtures) — mirroring how the typed env
    accessors refuse unregistered knobs.  ``lock`` defaults to a fresh
    ``threading.Lock``; pass a ``threading.RLock()`` for re-entrant
    guards."""
    if name not in LOCK_REGISTRY and not name.startswith("test."):
        raise KeyError(
            f"{name!r} is not a registered lock; add it to heat_tpu."
            "analysis.concurrency.LOCK_REGISTRY (file, spellings, "
            "structures, doc) — the H7xx lint rules and the sanitizer "
            "share that one table"
        )
    return TsanLock(name, lock)


# ----------------------------------------------------------------------
# per-thread state + stack capture
# ----------------------------------------------------------------------
def _held() -> List[Tuple[str, Tuple[str, ...]]]:
    h = getattr(_TLS, "held", None)
    if h is None:
        h = _TLS.held = []
    return h


def _capture(skip: int = 2) -> Tuple[str, ...]:
    """Compact acquisition stack: ``file:line:function`` per frame,
    innermost first, without line-text extraction (cheap enough to pay
    per acquire while armed)."""
    try:
        f = sys._getframe(skip)
    except ValueError:  # pragma: no cover - shallow stack
        return ()
    out: List[str] = []
    while f is not None and len(out) < _STACK_DEPTH:
        co = f.f_code
        out.append(f"{co.co_filename}:{f.f_lineno}:{co.co_name}")
        f = f.f_back
    return tuple(out)


def _reporting() -> bool:
    return getattr(_TLS, "reporting", False)


# ----------------------------------------------------------------------
# reporting
# ----------------------------------------------------------------------
def _report(rule: str, message: str, details: Dict[str, Any]) -> None:
    """Record one finding and surface it through the shared diagnostics
    pipeline.  Re-entrancy-guarded: the telemetry counters the pipeline
    bumps take registered locks themselves."""
    rec = {"rule": rule, "message": message, **details}
    with _STATE_LOCK:
        if len(_FINDINGS) < _MAX_FINDINGS:
            _FINDINGS.append(rec)
    _TLS.reporting = True
    try:
        from . import diagnostics as _diag

        _diag.emit(
            _diag.Diagnostic(
                rule=rule, message=message, source="tsan", details=details
            ),
            mode=_diag.MODE_RAISE if _MODE == MODE_RAISE else _diag.MODE_WARN,
        )
    finally:
        _TLS.reporting = False


def _note_edge(
    held_name: str,
    held_stack: Tuple[str, ...],
    acq_name: str,
    acq_stack: Tuple[str, ...],
) -> None:
    """Record the order edge held_name -> acq_name; on a NEW edge, look
    for a reverse path (a cycle = a potential deadlock)."""
    key = (held_name, acq_name)
    cycle_path = None
    with _STATE_LOCK:
        rec = _EDGES.get(key)
        if rec is not None:
            rec["count"] += 1
            return
        _EDGES[key] = {
            "held_stack": held_stack,
            "acquire_stack": acq_stack,
            "thread": threading.current_thread().name,
            "count": 1,
        }
        # DFS: does acq_name already reach held_name?
        path = _find_path(acq_name, held_name)
        if path is not None:
            members = frozenset(path + [acq_name])
            if members not in _REPORTED_CYCLES:
                _REPORTED_CYCLES.add(members)
                cycle_path = path
    if cycle_path is not None:
        edges = []
        with _STATE_LOCK:
            chain = [acq_name] + cycle_path
            for a, b in zip(chain, chain[1:]):
                e = _EDGES.get((a, b))
                edges.append(
                    {
                        "held": a,
                        "acquired": b,
                        "held_stack": list(e["held_stack"]) if e else [],
                        "acquire_stack": list(e["acquire_stack"]) if e else [],
                        "thread": e["thread"] if e else "?",
                    }
                )
        # full chain: held -> acquired -> ... -> held (cycle_path ends at
        # held_name, closing the loop)
        chain_nodes = [held_name, acq_name] + cycle_path
        _report(
            "tsan.lock_cycle",
            f"lock-order cycle: {' -> '.join(chain_nodes)}"
            f" (some thread holds {held_name!r} while acquiring {acq_name!r};"
            f" another path acquires them in the reverse order) — a"
            f" scheduler-dependent deadlock",
            {
                "cycle": chain_nodes,
                "closing_edge": {
                    "held": held_name,
                    "acquired": acq_name,
                    "held_stack": list(held_stack),
                    "acquire_stack": list(acq_stack),
                    "thread": threading.current_thread().name,
                },
                "reverse_path": edges,
            },
        )


def _find_path(src: str, dst: str) -> Optional[List[str]]:
    """DFS over _EDGES from ``src`` to ``dst`` (caller holds
    _STATE_LOCK); returns the node path [next, ..., dst] or None."""
    stack = [(src, [])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        for (a, b) in _EDGES:
            if a != node or b in seen:
                continue
            if b == dst:
                return path + [b]
            seen.add(b)
            stack.append((b, path + [b]))
    return None


# ----------------------------------------------------------------------
# the instrumented lock
# ----------------------------------------------------------------------
class TsanLock:
    """Instrumented proxy over a ``threading.Lock``/``RLock``.

    Disarmed: acquire/release delegate after one module-global read.
    Armed: acquisition order feeds the global lock-order graph with a
    compact stack per hold.  The proxy is what ``with`` statements over
    registered locks actually hold; create via :func:`register_lock`."""

    __slots__ = ("name", "_lock")

    def __init__(self, name: str, lock=None):
        self.name = name
        self._lock = lock if lock is not None else threading.Lock()

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._lock.acquire(blocking, timeout)
        if ok and _ARMED and not _reporting():
            held = _held()
            stack = _capture()
            for held_name, held_stack in held:
                if held_name != self.name:
                    _note_edge(held_name, held_stack, self.name, stack)
            held.append((self.name, stack))
        return ok

    def release(self) -> None:
        if _ARMED and not _reporting():
            held = _held()
            for i in range(len(held) - 1, -1, -1):
                if held[i][0] == self.name:
                    del held[i]
                    break
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def held_by_current_thread(self) -> bool:
        """Whether the current thread is (tsan-)tracked as holding this
        lock.  Only meaningful while armed."""
        return any(n == self.name for n, _ in _held())

    def __enter__(self) -> "TsanLock":
        self.acquire()
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        self.release()

    def __repr__(self) -> str:
        return f"TsanLock({self.name!r})"


# ----------------------------------------------------------------------
# guarded-structure access checkpoints
# ----------------------------------------------------------------------
def note_access(structure: str, write: bool = True) -> None:
    """Checkpoint one access to a registered shared structure.

    Free (one global read) while disarmed.  Armed: the access is OK when
    the current thread holds the structure's registered lock, or when it
    is the main thread (single-writer-main is the framework's sanctioned
    lock-free pattern — the GIL orders main-thread access against
    *nothing*, which is exactly why off-main access needs the lock).
    Anything else is a ``tsan.unguarded_access`` finding carrying both
    stacks."""
    if not _ARMED or _reporting():
        return
    lock_name = _STRUCTS.get(structure)
    if lock_name is None:
        raise KeyError(
            f"{structure!r} is not a registered guarded structure; add it "
            "to a lock's 'structures' tuple in heat_tpu.analysis."
            "concurrency.LOCK_REGISTRY (or tsan.register_structure for "
            "test fixtures)"
        )
    stack = _capture()
    if any(n == lock_name for n, _ in _held()):
        with _STATE_LOCK:
            _LAST_ACCESS[structure] = stack
        return
    if threading.current_thread() is threading.main_thread():
        with _STATE_LOCK:
            _LAST_ACCESS[structure] = stack
        return
    loc = stack[0] if stack else "?"
    with _STATE_LOCK:
        key = (structure, loc)
        if key in _REPORTED_ACCESS:
            return
        _REPORTED_ACCESS.add(key)
        last = list(_LAST_ACCESS.get(structure, ()))
    _report(
        "tsan.unguarded_access",
        f"shared structure {structure!r} {'written' if write else 'read'} "
        f"from thread {threading.current_thread().name!r} without holding "
        f"its registered lock {lock_name!r}",
        {
            "structure": structure,
            "lock": lock_name,
            "write": bool(write),
            "thread": threading.current_thread().name,
            "access_stack": list(stack),
            "last_access_stack": last,
        },
    )


# ----------------------------------------------------------------------
# exit dump (the sanitized CI lane's audit artifact)
# ----------------------------------------------------------------------
@atexit.register
def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess
    """``HEAT_TPU_TSAN_DUMP=<path>``: write the findings list as JSON at
    interpreter exit (checked at exit time).  Plain json.dump — the
    atomic writer lives above this module in the import graph and the
    consumer (scripts/tsan_lane.py) treats a missing/torn file as a
    lane failure anyway."""
    path = os.environ.get("HEAT_TPU_TSAN_DUMP")
    if not path:
        return
    try:
        doc = {"pid": os.getpid(), "mode": _MODE, "findings": findings()}
        with open(path, "w") as f:  # lint: allow H101(atexit dump below the atomic layer in the import graph)
            json.dump(doc, f, indent=1, default=str)
    except Exception:  # lint: allow H501(best-effort exit dump)
        pass
