"""Tune the planar FFT (VERDICT r3 #3): sweep matmul precision and the
four-step radix cutoff on the attached chip, validating accuracy against
numpy at 128^3 before timing 512^3.

Each config runs in a subprocess (the cutoff is an import-time constant,
and complex-capability probing must not poison the parent stream — see
the complex-less runtime notes).  Prints one JSON line per config.

    python scripts/tune_fft.py            # full sweep
"""

import json
import os
import subprocess
import sys

WORKER = r"""
import json, os, sys, time
import numpy as np
import jax, jax.numpy as jnp

sys.path.insert(0, os.environ["REPO"])
import heat_tpu as ht

prec = os.environ["HEAT_TPU_FFT_PRECISION"]
cut = os.environ["HEAT_TPU_FFT_CUTOFF"]

# accuracy gate at 128^3 vs numpy (planar path forced)
os.environ["HEAT_TPU_PLANAR"] = "1"
rng = np.random.default_rng(0)
xa = rng.standard_normal((128, 128, 128)).astype(np.float32)
fa = ht.fft.fftn(ht.array(xa))
re, im = fa._planar
got = np.asarray(re) + 1j * np.asarray(im)
want = np.fft.fftn(xa)
rel = float(np.linalg.norm(got - want) / np.linalg.norm(want))

# timing at 512^3: the window must DOMINATE the link's per-program
# dispatch floor (~0.09 s observed in some sessions) — an undersized
# window reads ~2x slower than the device truth (r4 lesson; see
# bench._time_amortized's floor-ratio growth)
s = 512
x = ht.random.randn(s, s, s, split=0).astype(ht.float32)
float(x.sum())
def fft():
    return ht.fft.fftn(x)
r = fft()
rre, rim = r._planar
float(rre[0, 0, 0])  # compile + drain
f0 = jax.jit(lambda v: v + 1.0)
z = jnp.zeros(())
float(f0(z))
floor = float("inf")
for _ in range(5):
    t0 = time.perf_counter()
    float(f0(z))
    floor = min(floor, time.perf_counter() - t0)
n_iter = 32
best = float("inf")
for _ in range(3):
    t0 = time.perf_counter()
    out = None
    for _ in range(n_iter):
        out = fft()
    orr, ori = out._planar
    float(orr[0, 0, 0])
    best = min(best, (time.perf_counter() - t0 - floor) / n_iter)
n = s ** 3
print(json.dumps({
    "precision": prec, "cutoff": int(cut), "rel_err_128": rel,
    "sec_per_fft3d_512": round(best, 4),
    "nominal_gflops": round(5.0 * n * np.log2(n) / best / 1e9, 1),
}))
"""


def main():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for prec in ("highest", "high", "default"):
        for cut in ("32", "64", "128"):
            env = dict(os.environ)
            env.update(
                REPO=repo,
                HEAT_TPU_FFT_PRECISION=prec,
                HEAT_TPU_FFT_CUTOFF=cut,
            )
            r = subprocess.run(
                [sys.executable, "-c", WORKER], env=env, capture_output=True,
                text=True, timeout=1800,
            )
            line = (r.stdout.strip().splitlines() or ["{}"])[-1]
            if r.returncode != 0:
                line = json.dumps({
                    "precision": prec, "cutoff": int(cut),
                    "error": r.stderr.strip()[-300:],
                })
            print(line, flush=True)


if __name__ == "__main__":
    main()
