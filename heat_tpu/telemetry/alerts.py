"""Alert/event subsystem: deduplicated fired/resolved quality events.

The SLO monitors (:mod:`~heat_tpu.telemetry.slo`) and drift checks
(:mod:`~heat_tpu.telemetry.sketch`) need somewhere to *put* a verdict —
"this replica is burning its latency budget", "this model's input
distribution left its baseline" — that an operator (or ROADMAP item 4's
canary auto-promote) can consume without scraping raw metrics.  This
module is that sink:

* an **active table** of currently-firing alerts, deduplicated by
  ``(name, labels)`` — re-firing an already-active alert only refreshes
  its observed value, it never produces a second event;
* a bounded **event ring** (``HEAT_TPU_ALERT_RING``) recording only the
  *transitions* — ``fired`` and ``resolved`` — so a flapping monitor
  produces a readable timeline instead of a firehose;
* each alert carries a **severity** (``page`` > ``warn`` > ``info``),
  the observed value vs its threshold, and — when the firing monitor
  could find one — the nearest **exemplar trace_id**, the link from an
  aggregate verdict back to one concrete request retained in
  ``/tracez``.

Alerts surface on ``/sloz`` / ``/driftz`` / ``/statusz``, travel in
cross-worker snapshots (``aggregate.tag_snapshot`` ships them;
``merge_snapshots`` folds every worker's view into one deterministic
timeline), and land in crash flight-recorder bundles rendered by the
inspect CLI.

Thread-safety: monitors fire from the SLO tick thread, drift checks
from batcher threads, and readers are HTTP handler threads — every
structure below is only touched under the registered
``telemetry.alerts`` lock.
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan as _tsan
from ..analysis.protocols import ACTOR_ALERTS, ALERT_FIRE, ALERT_RESOLVE
from . import journal as _journal
from . import metrics as _metrics

__all__ = [
    "Alert",
    "SEVERITIES",
    "active_alerts",
    "alert_events",
    "alerts_snapshot",
    "clear_alerts",
    "fire",
    "is_firing",
    "merge_alert_snapshots",
    "resolve",
]

#: severities in escalation order (index = rank; higher is worse)
SEVERITIES = ("info", "warn", "page")

# knob IS registered in core/_env.py KNOBS; read directly because this
# module loads at `heat_tpu.telemetry` import, before core._env is safe
_RING_SIZE = int(os.environ.get("HEAT_TPU_ALERT_RING", "256"))

_FIRED_C = _metrics.counter("alerts.fired", "alert fired transitions recorded")
_RESOLVED_C = _metrics.counter("alerts.resolved", "alert resolved transitions recorded")
_ACTIVE_G = _metrics.gauge("alerts.active", "alerts currently firing")


class Alert:
    """One deduplicated alert: identity, severity, live state.

    ``key`` is the dedup identity: the alert name plus its sorted
    labels.  ``value``/``threshold`` are the monitor's observed number
    vs its objective at the last (re-)fire; ``trace_id`` the nearest
    exemplar the monitor could attach."""

    __slots__ = ("name", "labels", "severity", "message", "value",
                 "threshold", "trace_id", "fired_ts", "updated_ts")

    def __init__(
        self,
        name: str,
        labels: Dict[str, str],
        severity: str,
        message: str,
        value: Optional[float],
        threshold: Optional[float],
        trace_id: Optional[str],
        fired_ts: float,
    ):
        self.name = name
        self.labels = dict(labels)
        self.severity = severity
        self.message = message
        self.value = value
        self.threshold = threshold
        self.trace_id = trace_id
        self.fired_ts = fired_ts
        self.updated_ts = fired_ts

    @property
    def key(self) -> str:
        return alert_key(self.name, self.labels)

    def doc(self) -> Dict[str, Any]:
        return {
            "name": self.name,
            "labels": dict(sorted(self.labels.items())),
            "severity": self.severity,
            "message": self.message,
            "value": self.value,
            "threshold": self.threshold,
            "trace_id": self.trace_id,
            "fired_ts": self.fired_ts,
            "updated_ts": self.updated_ts,
        }


def alert_key(name: str, labels: Optional[Dict[str, str]] = None) -> str:
    """The dedup identity of an alert: ``name{k=v,...}`` (keys sorted)."""
    if not labels:
        return name
    inner = ",".join(f"{k}={v}" for k, v in sorted(labels.items()))
    return f"{name}{{{inner}}}"


#: active table + transition ring, both under the registered lock
_LOCK = _tsan.register_lock("telemetry.alerts")
_ACTIVE: Dict[str, Alert] = {}
_EVENTS: "deque[Dict[str, Any]]" = deque(maxlen=max(1, _RING_SIZE))


def refresh_env() -> None:
    """Re-read ``HEAT_TPU_ALERT_RING`` (tests that flip the env
    mid-process); resizes the event ring, keeping the newest events."""
    global _RING_SIZE, _EVENTS
    _RING_SIZE = int(os.environ.get("HEAT_TPU_ALERT_RING", "256"))
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state")
        _EVENTS = deque(_EVENTS, maxlen=max(1, _RING_SIZE))


def fire(
    name: str,
    severity: str = "warn",
    message: str = "",
    value: Optional[float] = None,
    threshold: Optional[float] = None,
    trace_id: Optional[str] = None,
    labels: Optional[Dict[str, str]] = None,
    cause: Optional[str] = None,
    evidence: Optional[Dict[str, Any]] = None,
) -> bool:
    """Fire (or refresh) an alert; returns True on the fired *transition*.

    A first fire for ``(name, labels)`` records a ``fired`` event in the
    ring and counts in ``alerts.fired``; re-firing an active alert only
    updates its observed value/message/exemplar (dedup — no event).

    The fired transition also lands in the control-plane **decision
    journal** (actor ``alerts``, action ``fire``), carrying the firing
    monitor's ``evidence`` — by convention the exact metric values it
    compared plus the TSDB ``series`` names whose samples are
    resolvable via ``/queryz`` — and an optional ``cause`` event_id
    linking this alert to the upstream decision that provoked it."""
    if severity not in SEVERITIES:
        raise ValueError(f"severity must be one of {SEVERITIES}, got {severity!r}")
    key = alert_key(name, labels)
    now = time.time()
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state")
        a = _ACTIVE.get(key)
        if a is not None:
            a.value = value
            a.message = message or a.message
            a.severity = severity
            a.updated_ts = now
            if trace_id is not None:
                a.trace_id = trace_id
            return False
        a = Alert(
            name, labels or {}, severity, message, value, threshold,
            trace_id, now,
        )
        _ACTIVE[key] = a
        _EVENTS.append(dict(a.doc(), event="fired", ts=now))
        _ACTIVE_G.set(len(_ACTIVE))
    _FIRED_C.inc()
    # journal after the alert lock is released: emit takes the journal
    # lock (and may append a durable segment) — never nested under ours
    ev = {"alert": key, "value": value, "threshold": threshold}
    ev.update(evidence or {})
    _journal.emit(
        ACTOR_ALERTS, ALERT_FIRE,
        model=(labels or {}).get("model"),
        tenant=(labels or {}).get("tenant"),
        severity=severity,
        message=message or f"alert {key} fired",
        cause=cause,
        trace_id=trace_id,
        evidence=ev,
    )
    return True


def resolve(name: str, labels: Optional[Dict[str, str]] = None) -> bool:
    """Resolve an active alert; returns True on the resolved
    *transition* (False when it was not firing — resolving is
    idempotent, quiet monitors can call it every tick).  The resolved
    transition is journaled (actor ``alerts``, action ``resolve``) with
    its cause linked back to the retained fire event, so an incident's
    timeline shows how long the condition held."""
    key = alert_key(name, labels)
    now = time.time()
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state")
        a = _ACTIVE.pop(key, None)
        if a is None:
            return False
        doc = a.doc()
        active_s = round(now - a.fired_ts, 3)
        _EVENTS.append(dict(doc, event="resolved", ts=now, active_s=active_s))
        _ACTIVE_G.set(len(_ACTIVE))
    _RESOLVED_C.inc()
    fired_id = None
    for e in reversed(_journal.journal_events()):
        if (e.get("actor") == "alerts" and e.get("action") == "fire"
                and (e.get("evidence") or {}).get("alert") == key):
            fired_id = e.get("event_id")
            break
    _journal.emit(
        ACTOR_ALERTS, ALERT_RESOLVE,
        model=doc["labels"].get("model"),
        tenant=doc["labels"].get("tenant"),
        severity="info",
        message=f"alert {key} resolved after {active_s}s",
        cause=fired_id,
        trace_id=doc.get("trace_id"),
        evidence={"alert": key, "active_s": active_s},
    )
    return True


def is_firing(name: str, labels: Optional[Dict[str, str]] = None) -> bool:
    """Whether the alert is currently active."""
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state", write=False)
        return alert_key(name, labels) in _ACTIVE


def active_alerts() -> List[Dict[str, Any]]:
    """Currently-firing alerts, worst severity first then by key."""
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state", write=False)
        docs = [a.doc() for a in _ACTIVE.values()]
    return sorted(
        docs, key=lambda d: (-SEVERITIES.index(d["severity"]), d["name"],
                             tuple(sorted(d["labels"].items())))
    )


def alert_events(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The transition ring, oldest first (``limit`` trims to the newest)."""
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state", write=False)
        events = list(_EVENTS)
    return events[-limit:] if limit else events


def alerts_snapshot() -> Dict[str, Any]:
    """Active table + transition ring as one JSON-safe document — the
    form that travels in cross-worker snapshots and crash bundles."""
    return {
        "ring": _RING_SIZE,
        "active": active_alerts(),
        "events": alert_events(),
    }


def clear_alerts() -> None:
    """Drop every active alert and ring event (tests, ``reset_all``)."""
    with _LOCK:
        _tsan.note_access("telemetry.alerts.state")
        _ACTIVE.clear()
        _EVENTS.clear()
        _ACTIVE_G.set(0)


def merge_alert_snapshots(
    tagged: Sequence[Tuple[str, Dict[str, Any]]]
) -> Dict[str, Any]:
    """Fold per-worker alert snapshots into one deterministic view.

    ``tagged`` is ``[(worker_index, alerts_snapshot_doc), ...]``.
    Active alerts union by ``(key, worker)`` — the same SLO firing on
    two workers stays two rows, because it *is* two replicas burning
    budget; events interleave ordered by ``(ts, worker)``.  Pure
    function of its inputs (``aggregate.merge_snapshots`` calls it)."""
    active: List[Dict[str, Any]] = []
    events: List[Dict[str, Any]] = []
    for ix, snap in sorted(tagged, key=lambda t: str(t[0])):
        for a in (snap or {}).get("active") or []:
            active.append(dict(a, worker=str(ix)))
        for e in (snap or {}).get("events") or []:
            events.append(dict(e, worker=str(ix)))
    active.sort(
        key=lambda d: (-SEVERITIES.index(d.get("severity", "info")),
                       d.get("name", ""), d.get("worker", ""))
    )
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("worker", ""),
                               e.get("name", "")))
    return {
        "active": active,
        "events": events,
        "active_count": len(active),
        "worst_severity": active[0]["severity"] if active else None,
    }
