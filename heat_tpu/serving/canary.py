"""Canary decision plane: shadow traffic, online comparison, evented verdicts.

The registry can hold a **canary** version (``ModelRegistry.load(...,
activate=False)``) and swap it live with one pointer — but nothing yet
*observes a canary under real traffic and decides*.  This module is
that control loop, in three parts:

* **Shadow traffic mirroring** — the coalescer's scatter path offers
  every admitted batch's TRUE rows + primary outputs to
  :meth:`CanaryController.offer` *after* the waiting callers are woken
  (the ``on_mirror`` hook, same placement as the drift-sketch fold):
  a configurable fraction (``HEAT_TPU_SHADOW_FRACTION``, systematic
  per-batch sampling) is copied into a **bounded** queue a dedicated
  shadow thread drains — a full queue drops the batch (counted), so
  mirroring can never back-pressure the primary path.  The shadow
  inference pads to the SAME power-of-two buckets as the primary
  (:func:`heat_tpu.core.dispatch.batch_bucket`), so the executable-cache
  key set stays finite and steady-state shadowing compiles **nothing**
  (cache keys are shapes, not weights).

* **Online comparison** — each mirrored batch's canary outputs are
  scored against the primary's per the estimator kind's
  :data:`~heat_tpu.analysis.precision_policy.POLICIES` contract:
  ``bitwise`` kinds must match exactly (any differing row is a
  mismatch), ``tolerance`` kinds may diverge within the declared
  ``rtol`` (float outputs: element excess over ``rtol`` x the batch's
  magnitude scale; integer labels: plain disagreement) with a mismatch
  budget (``HEAT_TPU_CANARY_MAX_MISMATCH_PCT``).  Latency rides along:
  the canary's per-row inference time is compared to the primary's own
  measured time *on the same batch* (``HEAT_TPU_CANARY_LATENCY_X``),
  and the shadow drop rate is reported as the canary lane's shed rate.

* **The decision engine** — evidence accumulates per model until
  ``HEAT_TPU_CANARY_MIN_ROWS`` rows have been compared, then every
  further batch re-evaluates the verdict:

  - **fail** (contract violated, latency blown, or the canary
    *raised*) → auto-rollback: the canary version is discarded (or, if
    it had been promoted mid-window, ``registry.rollback``), a
    page-severity ``canary:<model>`` alert fires, and — when the
    flight recorder is armed — a crash bundle records the failed
    comparison for the post-mortem;
  - **pass** → promotion is first offered to the **veto gate**: an
    active ``drift:<model>`` alert, any firing ``slo:*`` burn alert, or
    any page-severity alert holds the promotion (verdict ``held``,
    reasons retained) until the signal clears;
  - **pass + no veto** → auto-promote (one registry pointer swap).

  ``HEAT_TPU_CANARY_AUTO=0`` keeps the engine observe-only: verdicts
  and events are recorded but the registry is never touched.

Every comparison summary and every decision is a **severity-tagged
retained event** carrying the nearest exemplar ``trace_id`` (the
mirrored batch's primary trace), rendered on ``/canaryz`` (HTML +
``?format=json``), embedded in ``/statusz``, shipped in cross-worker
snapshots (``aggregate.tag_snapshot``/``merge_snapshots`` — a model
whose replicas disagree is *divergent*), and written into crash
flight-recorder bundles — the full audit trail of why a version went
live (or didn't).

Thread-safety: module-level state (per-model windows, the event ring)
and each controller's queue are only touched under the registered
``serving.canary`` lock; the shadow inference itself always runs
outside it.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..analysis import tsan as _tsan
from ..analysis.precision_policy import POLICIES
from ..analysis.protocols import (
    ACTOR_CANARY, ACTOR_FLIGHT_RECORDER, ACTOR_REFRESH, CANARY_STAGE,
    CANARY_VETO, FLIGHT_RECORDER_BUNDLE, REFRESH_TRIGGER,
)
from ..resilience.faults import inject as _inject
from ..telemetry import alerts as _alerts
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry import tsdb as _tsdb

__all__ = [
    "CanaryController",
    "canary_events",
    "canary_snapshot",
    "canaryz_report",
    "compare_batch",
    "record_event",
    "render_canaryz_html",
    "reset_canary_state",
    "status",
]

_OFFERED_C = _tm.counter("canary.offered", "batches offered to the shadow sampler")
_SAMPLED_C = _tm.counter("canary.sampled", "batches mirrored to a canary version")
_SAMPLED_ROWS_C = _tm.counter("canary.sampled_rows", "true rows mirrored to a canary")
_DROPPED_C = _tm.counter(
    "canary.dropped", "mirrored batches dropped at the bounded shadow queue"
)
_COMPARISONS_C = _tm.counter("canary.comparisons", "primary-vs-canary batch comparisons")
_PROMOTIONS_C = _tm.counter("canary.promotions", "canary versions auto-promoted")
_ROLLBACKS_C = _tm.counter("canary.rollbacks", "canary versions auto-rolled-back")
_ERRORS_C = _tm.counter("canary.errors", "canary shadow inferences that raised")


def _env():
    from ..core import _env as envmod

    return envmod


# ----------------------------------------------------------------------
# module-level state: per-model evidence windows + the retained event
# ring (what /canaryz, /statusz, snapshots and crash bundles read)
# ----------------------------------------------------------------------
_LOCK = _tsan.register_lock("serving.canary")
_STATE: Dict[str, Dict[str, Any]] = {}
_EVENTS: "deque[Dict[str, Any]]" = deque(maxlen=128)
#: bounded per-model decision history (the inspect CLI's audit trail)
_HISTORY_KEEP = 8


def _ring_size() -> int:
    try:
        return max(1, _env().env_int("HEAT_TPU_CANARY_RING"))
    except Exception:  # lint: allow H501(pre-env-import readers fall back to the default)
        return 128


def refresh_env() -> None:
    """Re-read ``HEAT_TPU_CANARY_RING`` (tests that flip the env
    mid-process); resizes the event ring keeping the newest events."""
    global _EVENTS
    with _LOCK:
        _tsan.note_access("serving.canary.state")
        _EVENTS = deque(_EVENTS, maxlen=_ring_size())


def record_event(
    model: str,
    kind: str,
    severity: str,
    message: str,
    trace_id: Optional[str] = None,
    **stats,
) -> Dict[str, Any]:
    """Append one retained canary event (``kind`` is ``comparison`` /
    ``decision`` / ``error``); returns the event document."""
    ev = {
        "ts": time.time(),
        "model": model,
        "kind": kind,
        "severity": severity,
        "message": message,
        "trace_id": trace_id,
    }
    ev.update(stats)
    with _LOCK:
        _tsan.note_access("serving.canary.state")
        _EVENTS.append(ev)
    return ev


def canary_events(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The retained event ring, oldest first (``limit`` trims to the
    newest)."""
    with _LOCK:
        _tsan.note_access("serving.canary.state", write=False)
        events = list(_EVENTS)
    return events[-limit:] if limit else events


def status(model: str) -> Optional[Dict[str, Any]]:
    """One model's canary state document (None when no canary has ever
    been observed for it) — the per-model ``/healthz`` fields read this."""
    with _LOCK:
        _tsan.note_access("serving.canary.state", write=False)
        st = _STATE.get(model)
        return _state_doc(st) if st is not None else None


def reset_canary_state() -> None:
    """Drop every model window and retained event (tests)."""
    with _LOCK:
        _tsan.note_access("serving.canary.state")
        _STATE.clear()
        _EVENTS.clear()


def _state_doc(st: Dict[str, Any]) -> Dict[str, Any]:
    """JSON-safe view of one model's evidence window (caller holds the
    lock)."""
    rows = st["rows"]
    p_ms, c_ms = st["primary_ms"], st["canary_ms"]
    return {
        "model": st["model"],
        "kind": st["kind"],
        "mode": st["mode"],
        "canary_version": st["canary_version"],
        "active_version": st["active_version"],
        "started_ts": st["started_ts"],
        "batches": st["batches"],
        "rows": rows,
        "min_rows": st["min_rows"],
        "mismatched_rows": st["mismatched"],
        "mismatch_pct": round(100.0 * st["mismatched"] / rows, 4) if rows else 0.0,
        "max_rel_err": round(st["max_rel_err"], 6),
        "primary_ms_per_row": round(p_ms / rows, 6) if rows else None,
        "canary_ms_per_row": round(c_ms / rows, 6) if rows else None,
        "latency_ratio": round(c_ms / p_ms, 4) if p_ms > 0 else None,
        "shadow_dropped": st["dropped"],
        "shed_rate": round(
            st["dropped"] / (st["dropped"] + st["batches"]), 4
        ) if (st["dropped"] + st["batches"]) else 0.0,
        "errors": st["errors"],
        "verdict": st["verdict"],
        "vetoes": list(st["vetoes"]),
        "last_trace_id": st["last_trace_id"],
        "decision": dict(st["decision"]) if st["decision"] else None,
        "history": [dict(d) for d in st["history"]],
    }


def _new_state(model: str, kind: str, canary_version: int,
               active_version: Optional[int], min_rows: int) -> Dict[str, Any]:
    pol = POLICIES.get(kind)
    return {
        "model": model,
        "kind": kind,
        "mode": pol["mode"] if pol else "bitwise",
        "rtol": float(pol.get("rtol", 0.0)) if pol else 0.0,
        "canary_version": canary_version,
        "active_version": active_version,
        "started_ts": time.time(),
        "min_rows": min_rows,
        "batches": 0,
        "rows": 0,
        "mismatched": 0,
        "max_rel_err": 0.0,
        "primary_ms": 0.0,
        "canary_ms": 0.0,
        "dropped": 0,
        "errors": 0,
        "acc": 0.0,  # systematic-sampling accumulator
        "verdict": "collecting",
        "vetoes": [],
        "last_trace_id": None,
        "decision": None,
        "history": [],
    }


# ----------------------------------------------------------------------
# the comparator
# ----------------------------------------------------------------------
#: incomparable outputs (shape/dtype change, label flips) score this
#: instead of inf: finite, JSON-safe, unmistakable (the aggregate
#: layer's _SCORE_CAP convention)
_ERR_CAP = 1e9


def compare_batch(
    kind: str,
    primary: np.ndarray,
    canary: np.ndarray,
    rtol: Optional[float] = None,
) -> Dict[str, Any]:
    """Score one batch of canary outputs against the primary's, per the
    kind's :data:`POLICIES` contract.

    Returns ``{rows, mismatched, max_rel_err, mode}`` where
    ``mismatched`` counts the rows outside the contract: for a
    ``bitwise`` kind any row with a differing element (or a dtype
    change — bitwise means *bytes*); for a ``tolerance`` kind a float
    row whose worst element exceeds ``rtol`` x the batch's magnitude
    scale, or an integer (label) row that simply disagrees."""
    pol = POLICIES.get(kind)
    mode = pol["mode"] if pol else "bitwise"
    if rtol is None:
        rtol = float(pol.get("rtol", 0.0)) if pol else 0.0
    p = np.asarray(primary)
    c = np.asarray(canary)
    n = int(p.shape[0])
    out = {"rows": n, "mismatched": 0, "max_rel_err": 0.0, "mode": mode}
    if c.shape != p.shape or (mode == "bitwise" and c.dtype != p.dtype):
        out["mismatched"] = n
        out["max_rel_err"] = _ERR_CAP
        return out
    p2 = p.reshape(n, -1)
    c2 = c.reshape(n, -1).astype(p2.dtype, copy=False)
    if mode == "tolerance" and np.issubdtype(p2.dtype, np.floating):
        diff = np.abs(p2.astype(np.float64) - c2.astype(np.float64))
        scale = float(np.abs(p2).max()) or 1.0
        rel = diff / scale
        out["max_rel_err"] = float(rel.max()) if rel.size else 0.0
        out["mismatched"] = int((rel > rtol).any(axis=1).sum())
    else:
        # bitwise kinds, and tolerance kinds whose predictions are
        # discrete labels: equality is the contract (NaN counts as a
        # mismatch — a NaN prediction is never "equal enough")
        eq = p2 == c2
        out["mismatched"] = int((~eq.all(axis=1)).sum())
        if np.issubdtype(p2.dtype, np.floating) and out["mismatched"]:
            diff = np.abs(p2.astype(np.float64) - c2.astype(np.float64))
            scale = float(np.abs(p2).max()) or 1.0
            out["max_rel_err"] = float((diff / scale).max())
        elif out["mismatched"]:
            out["max_rel_err"] = _ERR_CAP
    return out


def _upstream_alert_cause(model: str) -> Optional[str]:
    """The journal event_id of the newest quality-signal alert fire for
    this model (drift/SLO/page — NOT a previous ``canary:*`` alert): the
    upstream cause a canary decision links to, so ``/decisionz?event_id=``
    walks from the rollback back to the evidence that provoked it."""
    for e in reversed(_journal.journal_events()):
        if e.get("actor") != "alerts" or e.get("action") != "fire":
            continue
        alert = (e.get("evidence") or {}).get("alert") or ""
        if alert.startswith("canary:"):
            continue
        if e.get("model") == model or alert.startswith("slo:") \
                or e.get("severity") == "page":
            return e.get("event_id")
    return None


def _collect_vetoes(model: str) -> List[str]:
    """Quality signals that veto a promotion right now: an active drift
    alert for THIS model, any firing SLO burn alert, any page-severity
    alert at all (an HBM watermark page is not the moment to go live)."""
    vetoes: List[str] = []
    for a in _alerts.active_alerts():
        name = a.get("name", "")
        if name == f"drift:{model}":
            vetoes.append(f"drift alert firing for {model!r} (score {a.get('value')})")
        elif name.startswith("slo:"):
            vetoes.append(f"SLO burn alert {name} firing (value {a.get('value')})")
        elif a.get("severity") == "page" and not name.startswith("canary:"):
            vetoes.append(f"page-severity alert {name} active")
    return vetoes


# ----------------------------------------------------------------------
# the controller
# ----------------------------------------------------------------------
class _Mirror:
    """One queued shadow job: a batch's true rows + primary outputs."""

    __slots__ = ("model", "version", "rows", "out", "trace_id", "primary_ms")

    def __init__(self, model, version, rows, out, trace_id, primary_ms):
        self.model = model
        self.version = version
        self.rows = rows
        self.out = out
        self.trace_id = trace_id
        self.primary_ms = primary_ms


class CanaryController:
    """The per-service shadow-traffic decision actor.

    ``offer`` runs on the batcher thread (cheap: one canary-version
    lookup, the sampling accumulator, one bounded enqueue); the shadow
    thread — started lazily on the first sampled batch — does the
    inference, comparison and decisions.  Knobs default from the
    registry (``HEAT_TPU_SHADOW_FRACTION`` / ``HEAT_TPU_CANARY_*``);
    tests override the public attributes directly."""

    def __init__(self, service):
        env = _env()
        self.service = service
        #: fraction of admitted batches mirrored (0 = shadowing off)
        self.fraction = env.env_float("HEAT_TPU_SHADOW_FRACTION")
        #: bounded shadow-queue depth (batches); full queue drops
        self.queue_depth = max(1, env.env_int("HEAT_TPU_SHADOW_QUEUE"))
        #: rows compared before the first verdict
        self.min_rows = max(1, env.env_int("HEAT_TPU_CANARY_MIN_ROWS"))
        #: mismatch budget (%) for tolerance kinds (bitwise allows none)
        self.max_mismatch_pct = env.env_float("HEAT_TPU_CANARY_MAX_MISMATCH_PCT")
        #: canary per-row latency budget as a multiple of the primary's
        self.latency_x = env.env_float("HEAT_TPU_CANARY_LATENCY_X")
        #: False = observe-only (verdicts recorded, registry untouched)
        self.auto = env.env_flag("HEAT_TPU_CANARY_AUTO")
        self._queue: List[_Mirror] = []
        self._open = True
        self._busy = False
        # ONE lock instance guards the module state (_STATE/_EVENTS) and
        # every controller's queue: the /canaryz readers, the batcher
        # threads offering and the shadow thread deciding all serialize
        # on the same registered ``serving.canary`` lock
        self._lock = _LOCK
        self._cond = threading.Condition(_LOCK)
        self._thread: Optional[threading.Thread] = None

    # -- batcher-thread side -------------------------------------------
    def offer(
        self,
        model: str,
        rows: np.ndarray,
        out: np.ndarray,
        trace_id: Optional[str],
        primary_ms: float,
    ) -> bool:
        """Offer one completed primary batch for mirroring; returns True
        when it was enqueued.  Runs on the batcher thread AFTER the
        callers were woken — never on any caller's latency path."""
        if self.fraction <= 0.0 or not self._open:
            return False
        try:
            version = self.service.registry.canary_version(model)
        except KeyError:
            return False
        if version is None:
            return False
        _OFFERED_C.inc()
        # version metadata fetched BEFORE the canary lock (the registry
        # has its own; no nesting) — only needed on a window reset
        try:
            kind = self.service.registry.record(model, version).get("kind") or "?"
            active = self.service.registry.active_version(model)
        except KeyError:
            return False
        enqueued = False
        with self._cond:
            _tsan.note_access("serving.canary.state")
            prev = _STATE.get(model)
            fresh = prev is None or prev["canary_version"] != version
            st = self._window(model, version, kind, active)
            started = self._thread is not None
            if st["decision"] is not None:
                pass  # this canary version is already judged
            else:
                st["acc"] += self.fraction
                if st["acc"] >= 1.0:
                    st["acc"] -= 1.0
                    if len(self._queue) >= self.queue_depth:
                        st["dropped"] += 1
                        _DROPPED_C.inc()
                    else:
                        self._queue.append(
                            _Mirror(model, version, rows, out, trace_id,
                                    primary_ms)
                        )
                        self._cond.notify_all()
                        enqueued = True
        if fresh:
            # journal the residency transition AFTER the lock (emit
            # takes its own; first offer against a new canary version
            # marks the window opening)
            self._journal_stage(model, version, active)
        if not enqueued:
            return False
        _SAMPLED_C.inc()
        _SAMPLED_ROWS_C.inc(int(rows.shape[0]))
        if not started:
            self._start()
        return True

    def _journal_stage(self, model: str, version: int,
                       active: Optional[int]) -> None:
        """Registered transition helper (PROTOCOLS ``canary``): a staged
        version entering shadow residency, cause-linked to the refresh
        trigger that staged it when there is one."""
        trig = _journal.find_last(actor=ACTOR_REFRESH, action=REFRESH_TRIGGER)
        _journal.emit(
            ACTOR_CANARY, CANARY_STAGE, model=model, severity="info",
            message=(
                f"canary v{version} resident; shadow window open against "
                f"active v{active}"
            ),
            cause=(
                trig["event_id"]
                if trig and trig.get("model") == model else None
            ),
            evidence={"canary_version": version, "active_version": active},
        )

    def _window(self, model: str, version: int, kind: str,
                active: Optional[int]) -> Dict[str, Any]:
        """The model's evidence window in the module state, reset when a
        NEW canary version appears (caller holds the lock — module state
        and the queue share the registered ``serving.canary`` lock)."""
        st = _STATE.get(model)
        if st is None or st["canary_version"] != version:
            history = st["history"] if st is not None else []
            st = _new_state(model, kind, version, active, self.min_rows)
            st["history"] = history
            _STATE[model] = st
        return st

    def _start(self) -> None:
        with self._cond:
            _tsan.note_access("serving.canary.state")
            if self._thread is not None or not self._open:
                return
            self._thread = threading.Thread(
                target=self._run, name="heat-tpu-canary-shadow", daemon=True
            )
            self._thread.start()

    # -- shadow-thread side --------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                _tsan.note_access("serving.canary.state")
                self._busy = False
                self._cond.notify_all()  # wait_idle barriers wake here
                while self._open and not self._queue:
                    self._cond.wait()
                if not self._open and not self._queue:
                    return
                job = self._queue.pop(0)
                self._busy = True
            try:
                self._shadow_one(job)  # inference outside the lock
            except Exception as e:  # lint: allow H501(a canary bug must never kill the shadow thread; the failure IS the verdict)
                self._record_error(job, e)

    def _shadow_infer(self, job: _Mirror) -> Tuple[np.ndarray, float]:
        """One canary inference over the mirrored batch, padded to the
        SAME bucket shape the primary dispatched — the finite-key-set
        property shadowing inherits; returns ``(outputs, elapsed_ms)``
        for the TRUE rows only."""
        from ..core import dispatch as _dispatch
        from ..core import factories
        from .model_io import infer as _infer

        _inject("serve.shadow", model=job.model, version=job.version)
        est = self.service.registry.get(job.model, job.version)
        rows = job.rows
        n = int(rows.shape[0])
        bucket = _dispatch.batch_bucket(n, self.service.max_batch)
        if bucket > n:
            pad = np.zeros((bucket - n,) + rows.shape[1:], rows.dtype)
            rows = np.concatenate([rows, pad], axis=0)
        t0 = time.perf_counter_ns()
        x = factories.array(
            rows, split=self.service.split, comm=self.service.registry.comm
        )
        y = np.asarray(_infer(est, x).numpy())
        elapsed_ms = (time.perf_counter_ns() - t0) / 1e6
        return y[:n], elapsed_ms

    def _shadow_one(self, job: _Mirror) -> None:
        canary_out, canary_ms = self._shadow_infer(job)
        with self._lock:
            _tsan.note_access("serving.canary.state", write=False)
            st = _STATE.get(job.model)
        if st is None or st["canary_version"] != job.version or st["decision"]:
            return  # the window moved on (new canary, or already decided)
        cmp = compare_batch(st["kind"], job.out, canary_out, rtol=st["rtol"])
        _COMPARISONS_C.inc()
        with self._lock:
            _tsan.note_access("serving.canary.state")
            st["batches"] += 1
            st["rows"] += cmp["rows"]
            st["mismatched"] += cmp["mismatched"]
            if cmp["max_rel_err"] > st["max_rel_err"]:
                st["max_rel_err"] = cmp["max_rel_err"]
            st["primary_ms"] += float(job.primary_ms)
            st["canary_ms"] += canary_ms
            if job.trace_id:
                st["last_trace_id"] = job.trace_id
        record_event(
            job.model, "comparison",
            "warn" if cmp["mismatched"] else "info",
            f"batch of {cmp['rows']} rows vs canary v{job.version}: "
            f"{cmp['mismatched']} outside the {cmp['mode']} contract",
            trace_id=job.trace_id,
            canary_version=job.version,
            rows=cmp["rows"],
            mismatched=cmp["mismatched"],
            max_rel_err=round(cmp["max_rel_err"], 6),
            canary_ms=round(canary_ms, 3),
            primary_ms=round(float(job.primary_ms), 3),
        )
        self._maybe_decide(job.model)

    def _record_error(self, job: _Mirror, exc: BaseException) -> None:
        """A canary inference that raises is itself a terminal verdict:
        the version cannot serve this traffic."""
        _ERRORS_C.inc()
        with self._lock:
            _tsan.note_access("serving.canary.state")
            st = _STATE.get(job.model)
            if st is None or st["canary_version"] != job.version or st["decision"]:
                return
            st["errors"] += 1
            if job.trace_id:
                st["last_trace_id"] = job.trace_id
        record_event(
            job.model, "error", "page",
            f"canary v{job.version} inference raised "
            f"{type(exc).__name__}: {exc}",
            trace_id=job.trace_id, canary_version=job.version,
        )
        self._decide(job.model, "fail", [f"canary inference raised {type(exc).__name__}: {exc}"])

    # -- the decision engine -------------------------------------------
    def _evaluate(self, st: Dict[str, Any]) -> Tuple[str, List[str]]:
        """(verdict, reasons) over the accumulated window: ``collecting``
        below min_rows, else ``fail`` with every violated clause, else
        ``pass``."""
        if st["rows"] < st["min_rows"]:
            return "collecting", []
        reasons: List[str] = []
        if st["mode"] == "bitwise":
            if st["mismatched"] > 0:
                reasons.append(
                    f"{st['mismatched']}/{st['rows']} rows differ on a "
                    f"bitwise-contract kind ({st['kind']})"
                )
        else:
            pct = 100.0 * st["mismatched"] / st["rows"]
            if pct > self.max_mismatch_pct:
                reasons.append(
                    f"{pct:.2f}% of rows outside rtol={st['rtol']:g} "
                    f"(budget {self.max_mismatch_pct:g}%)"
                )
        if st["primary_ms"] > 0 and st["canary_ms"] > self.latency_x * st["primary_ms"]:
            reasons.append(
                f"canary latency {st['canary_ms'] / st['primary_ms']:.2f}x the "
                f"primary's on the same batches (budget {self.latency_x:g}x)"
            )
        return ("fail", reasons) if reasons else ("pass", [])

    def _maybe_decide(self, model: str) -> None:
        with self._lock:
            _tsan.note_access("serving.canary.state", write=False)
            st = _STATE.get(model)
            if st is None or st["decision"]:
                return
            verdict, reasons = self._evaluate(st)
        if verdict == "collecting":
            return
        if verdict == "fail":
            self._decide(model, "fail", reasons)
            return
        vetoes = _collect_vetoes(model)
        if vetoes:
            self._hold(model, vetoes)
            return
        self._decide(model, "pass", [])

    def _hold(self, model: str, vetoes: List[str]) -> None:
        """Registered transition helper (PROTOCOLS ``canary``): the veto
        self-loop — a passing window held resident by a firing quality
        alert, journaled once per hold streak, never terminal."""
        with self._lock:
            _tsan.note_access("serving.canary.state")
            st = _STATE.get(model)
            if st is None or st["decision"]:
                return
            first_hold = st["verdict"] != "held"
            st["verdict"] = "held"
            st["vetoes"] = vetoes
            tid = st["last_trace_id"]
        if first_hold:
            record_event(
                model, "decision", "warn",
                "promotion held by veto: " + "; ".join(vetoes),
                trace_id=tid, action="held", vetoes=vetoes,
            )
            _journal.emit(
                ACTOR_CANARY, CANARY_VETO, model=model, severity="warn",
                message="promotion held by veto: " + "; ".join(vetoes),
                cause=_upstream_alert_cause(model), trace_id=tid,
                evidence={"vetoes": vetoes},
            )

    def _decide(self, model: str, verdict: str, reasons: List[str]) -> None:
        """Commit one decision: mutate the registry (when ``auto``),
        record the retained decision event + per-model history, fire or
        resolve the ``canary:<model>`` alert, and — on a rollback — dump
        a flight-recorder bundle so the failed comparison survives."""
        with self._lock:
            _tsan.note_access("serving.canary.state")
            st = _STATE.get(model)
            if st is None or st["decision"]:
                return
            st["verdict"] = verdict
            version = st["canary_version"]
            tid = st["last_trace_id"]
            summary = _state_doc(st)
        action = "observed"
        registry = self.service.registry
        if verdict == "pass":
            if self.auto:
                try:
                    registry.promote(model, version)
                    action = "promoted"
                    _PROMOTIONS_C.inc()
                except (KeyError, ValueError) as e:
                    action = "observed"
                    reasons = [f"promote failed: {e}"]
            _alerts.resolve(f"canary:{model}", labels={"model": model})
            severity, msg = "info", (
                f"canary v{version} passed over {summary['rows']} shadow rows "
                f"({summary['mismatch_pct']}% mismatch, "
                f"latency {summary['latency_ratio']}x)"
            )
        else:
            if self.auto:
                action = "rolled_back"
                _ROLLBACKS_C.inc()
                try:
                    if registry.active_version(model) == version:
                        # the canary had been promoted mid-window (an
                        # operator jumped the gun): real rollback
                        registry.rollback(model)
                    else:
                        registry.unload(model, version)
                except (KeyError, ValueError):
                    pass  # version already gone; the verdict still stands
            severity, msg = "page", (
                f"canary v{version} FAILED over {summary['rows']} shadow rows: "
                + "; ".join(reasons)
            )
        decision = {
            "ts": time.time(),
            "model": model,
            "canary_version": version,
            "verdict": verdict,
            "action": action,
            "reasons": reasons,
            "trace_id": tid,
            "rows": summary["rows"],
            "mismatch_pct": summary["mismatch_pct"],
            "max_rel_err": summary["max_rel_err"],
            "latency_ratio": summary["latency_ratio"],
        }
        with self._lock:
            _tsan.note_access("serving.canary.state")
            st = _STATE.get(model)
            if st is not None:
                st["decision"] = decision
                st["history"].append(decision)
                del st["history"][:-_HISTORY_KEEP]
        record_event(model, "decision", severity, msg, trace_id=tid, **{
            k: decision[k] for k in (
                "canary_version", "verdict", "action", "reasons",
                "rows", "mismatch_pct", "latency_ratio",
            )
        })
        # journal the decision: evidence is the exact window the engine
        # judged, recorded into the TSDB so /queryz can resolve the very
        # samples the event cites; a failing verdict links back to the
        # quality-signal alert that preceded it (drift/SLO), and the
        # page alert + flight-recorder bundle chain off the decision
        if summary["mismatch_pct"] is not None:
            _tsdb.record("canary.mismatch_pct", summary["mismatch_pct"])
        if summary["latency_ratio"] is not None:
            _tsdb.record("canary.latency_ratio", summary["latency_ratio"])
        jev = _journal.emit(
            ACTOR_CANARY, action, model=model, severity=severity, message=msg,
            cause=_upstream_alert_cause(model) if verdict == "fail" else None,
            trace_id=tid,
            evidence={
                "canary_version": version,
                "verdict": verdict,
                "reasons": reasons,
                "rows": summary["rows"],
                "mismatch_pct": summary["mismatch_pct"],
                "max_rel_err": summary["max_rel_err"],
                "latency_ratio": summary["latency_ratio"],
                "series": ["canary.mismatch_pct", "canary.latency_ratio"],
            },
        )
        if verdict == "fail":
            _alerts.fire(
                f"canary:{model}", severity="page", message=msg,
                value=summary["mismatch_pct"], threshold=self.max_mismatch_pct,
                trace_id=tid, labels={"model": model},
                cause=jev["event_id"],
                evidence={"series": ["canary.mismatch_pct"],
                          "mismatch_pct": summary["mismatch_pct"]},
            )
            self._dump_bundle(model, decision, cause=jev["event_id"])

    def _dump_bundle(self, model: str, decision: Dict[str, Any],
                     cause: Optional[str] = None) -> None:
        """Best-effort flight-recorder bundle on a rollback: the failed
        comparison stats ride in the bundle's canary section (the module
        state the recorder snapshots) — a rollback must be explainable
        after the process is gone.  The bundle write itself is journaled
        with its cause linked to the rollback decision, closing the
        ``evidence → rollback → page → bundle`` causal chain."""
        from ..telemetry import flight_recorder as _fr

        if not _fr.installed():
            return
        try:
            path = _fr.dump_bundle(reason=f"canary_rollback:{model}")
            _journal.emit(
                ACTOR_FLIGHT_RECORDER, FLIGHT_RECORDER_BUNDLE, model=model,
                severity="info",
                message="forensic bundle written for canary rollback",
                cause=cause, trace_id=decision.get("trace_id"),
                evidence={"path": path, "reason": f"canary_rollback:{model}"},
            )
        except Exception:  # lint: allow H501(a bundle-write failure must never mask the rollback itself)
            pass

    # -- shutdown -------------------------------------------------------
    def close(self, timeout: float = 10.0) -> None:
        """Stop mirroring, drain the queue, join the shadow thread.
        Idempotent."""
        with self._cond:
            _tsan.note_access("serving.canary.state")
            self._open = False
            self._cond.notify_all()
            t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join(timeout)

    def wait_idle(self, timeout: float = 30.0) -> bool:
        """Block until the shadow queue is drained AND no job is in
        flight (tests: a deterministic 'every mirrored batch has been
        judged' barrier)."""
        deadline = time.monotonic() + timeout
        with self._cond:
            _tsan.note_access("serving.canary.state", write=False)
            while self._queue or self._busy:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(remaining)
            return True


# ----------------------------------------------------------------------
# reports: /canaryz, snapshots, crash bundles
# ----------------------------------------------------------------------
def canaryz_report() -> Dict[str, Any]:
    """The machine form of ``/canaryz``: every model's evidence window
    + decision, the retained event ring, and the shadow-lane counters."""
    with _LOCK:
        _tsan.note_access("serving.canary.state", write=False)
        models = {name: _state_doc(st) for name, st in sorted(_STATE.items())}
    return {
        "timestamp": time.time(),
        "shadow": {
            "offered": _OFFERED_C.value,
            "sampled": _SAMPLED_C.value,
            "sampled_rows": _SAMPLED_ROWS_C.value,
            "dropped": _DROPPED_C.value,
            "comparisons": _COMPARISONS_C.value,
            "errors": _ERRORS_C.value,
            "promotions": _PROMOTIONS_C.value,
            "rollbacks": _ROLLBACKS_C.value,
        },
        "models": models,
        "events": canary_events(),
    }


def canary_snapshot() -> Dict[str, Any]:
    """Compact canary state for cross-worker snapshots and crash
    bundles: the model windows + the newest retained events."""
    with _LOCK:
        _tsan.note_access("serving.canary.state", write=False)
        models = {name: _state_doc(st) for name, st in sorted(_STATE.items())}
    return {"models": models, "events": canary_events(limit=32)}


_SEV_COLOR = {"page": "#ffd6d6", "warn": "#ffe9c6", "info": ""}


def render_canaryz_html() -> str:
    """The human form of ``/canaryz``: per-model verdict table + the
    retained event timeline (severity-tinted, exemplar trace_id linked
    to ``/tracez``)."""
    import html as _html

    def esc(v) -> str:
        return _html.escape(str(v), quote=True)

    rep = canaryz_report()
    sh = rep["shadow"]
    parts = [
        "<html><head><title>/canaryz</title><style>"
        "table{border-collapse:collapse}td,th{border:1px solid #999;"
        "padding:3px 6px;font:12px monospace}</style></head><body>",
        "<h1>/canaryz — canary decision plane</h1>",
        f"<p>shadow lane: {sh['sampled']} of {sh['offered']} batches mirrored "
        f"({sh['sampled_rows']} rows), {sh['dropped']} dropped at the bounded "
        f"queue, {sh['comparisons']} comparisons, {sh['errors']} canary "
        f"errors — {sh['promotions']} promoted / {sh['rollbacks']} rolled "
        "back</p>",
    ]
    if rep["models"]:
        parts.append(
            "<table><tr><th>model</th><th>canary</th><th>active</th>"
            "<th>mode</th><th>verdict</th><th>rows</th><th>mismatch %</th>"
            "<th>max rel err</th><th>latency x</th><th>shed</th>"
            "<th>decision</th><th>exemplar</th></tr>"
        )
        for name, st in rep["models"].items():
            dec = st.get("decision") or {}
            verdict = st.get("verdict")
            color = (
                "#ffd6d6" if verdict == "fail"
                else "#ffe9c6" if verdict == "held"
                else "#d8f5d8" if verdict == "pass"
                else ""
            )
            tid = st.get("last_trace_id")
            parts.append(
                f"<tr style='background:{color}'>"
                f"<td>{esc(name)}</td><td>v{esc(st['canary_version'])}</td>"
                f"<td>v{esc(st['active_version'])}</td><td>{esc(st['mode'])}</td>"
                f"<td><b>{esc(verdict)}</b></td>"
                f"<td>{esc(st['rows'])}/{esc(st['min_rows'])}</td>"
                f"<td>{esc(st['mismatch_pct'])}</td>"
                f"<td>{esc(st['max_rel_err'])}</td>"
                f"<td>{esc(st['latency_ratio'])}</td>"
                f"<td>{esc(st['shed_rate'])}</td>"
                f"<td>{esc(dec.get('action', '—'))}"
                + (f": {esc('; '.join(dec.get('reasons') or []))}" if dec.get("reasons") else "")
                + (f"<br>vetoes: {esc('; '.join(st['vetoes']))}" if st.get("vetoes") else "")
                + "</td>"
                f"<td>{f'<a href=/tracez?trace_id={esc(tid)}>{esc(tid)}</a>' if tid else '—'}</td>"
                "</tr>"
            )
        parts.append("</table>")
    else:
        parts.append("<p>no canary has been observed yet "
                     "(load one with activate=False and arm "
                     "HEAT_TPU_SHADOW_FRACTION)</p>")
    parts.append("<h2>retained events</h2>")
    events = rep["events"]
    if events:
        parts.append(
            "<table><tr><th>ts</th><th>model</th><th>kind</th><th>sev</th>"
            "<th>message</th><th>exemplar</th></tr>"
        )
        for ev in reversed(events):
            tid = ev.get("trace_id")
            parts.append(
                f"<tr style='background:{_SEV_COLOR.get(ev.get('severity'), '')}'>"
                f"<td>{esc(round(ev.get('ts', 0), 3))}</td>"
                f"<td>{esc(ev.get('model'))}</td><td>{esc(ev.get('kind'))}</td>"
                f"<td>{esc(ev.get('severity'))}</td>"
                f"<td>{esc(ev.get('message'))}</td>"
                f"<td>{f'<a href=/tracez?trace_id={esc(tid)}>{esc(tid)}</a>' if tid else '—'}</td>"
                "</tr>"
            )
        parts.append("</table>")
    else:
        parts.append("<p>(no events retained)</p>")
    parts.append("</body></html>")
    return "".join(parts)
