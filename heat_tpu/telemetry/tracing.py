"""Request-scoped distributed tracing: one trace_id from request to device.

PR 4 gave the framework spans and PR 6 aggregate metrics, but nothing
connected one slow ``/v1/predict`` call to the coalesced batch, the
dispatch, and the collectives that served it — a p99 spike in
``serving.latency_ms`` was undebuggable.  This module is the Dapper-style
answer for the serving pipeline's multi-stage, cross-thread shape:

* a **trace context** (:class:`TraceContext`: ``trace_id`` + current
  span id) carried in a :mod:`contextvars` variable — every
  :class:`~heat_tpu.telemetry.spans.span` opened while a context is
  active stamps ``trace_id`` / ``span_id`` / ``parent_id`` into its
  :class:`~heat_tpu.telemetry.spans.SpanRecord`, so dispatch-compile and
  comm-collective spans inherit the request that triggered them with
  zero changes at their call sites;
* **handoff helpers** (:func:`current_context`, :func:`use_context`,
  :func:`bind_context`) so the context survives the pipeline's thread
  hops: request thread → coalescer batcher thread → scatter, the
  introspection server's handler threads, and the async
  checkpoint-writer / model-loader workers;
* a **tail-sampled trace store**: the span ring is a bounded window, so
  the slow request you want to debug has usually rotated out by the time
  you look.  The store keeps *complete span trees* — its own copies,
  immune to ring rotation — for the ``HEAT_TPU_TRACE_KEEP`` most recent
  requests per route, the slowest-k requests overall, and **every**
  shed or errored request, bounded in every dimension
  (``HEAT_TPU_TRACE_MAX_SPANS`` spans per trace).  ``/tracez`` renders
  it; crash flight-recorder bundles carry it (including the requests
  in flight at crash time); :func:`trace_digest` ships a compact form
  in cross-worker snapshots so ``telemetry.aggregate`` can stitch one
  request's work across processes by trace_id.

The tracer itself stays ~free when idle: with no active context a span
pays one ``ContextVar.get`` over the PR 4 cost, and with
``HEAT_TPU_TRACE=0`` this module records **nothing** — no store entry,
no registry write (the disabled-mode zero-write property
``tests/test_tracing.py`` asserts).
"""

from __future__ import annotations

import bisect
import contextvars
import itertools
import os
import threading
import time
from collections import deque, namedtuple
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..analysis import tsan as _tsan
from . import metrics as _metrics

__all__ = [
    "TraceContext",
    "bind_context",
    "current_context",
    "current_trace_id",
    "exemplars_enabled",
    "get_trace",
    "link_spans",
    "new_trace_id",
    "next_span_id",
    "request_span",
    "reset_store",
    "retained_traces",
    "set_exemplars",
    "trace_digest",
    "traces_snapshot",
    "tracez_report",
    "use_context",
]


def _env_on(name: str, default: bool = True) -> bool:
    v = os.environ.get(name)
    if v is None:
        return default
    return v.strip().lower() not in ("0", "false", "no", "off")


# knobs ARE registered in core/_env.py KNOBS; read directly because this
# module loads at `heat_tpu.telemetry` import, before core._env is safe
_KEEP = int(os.environ.get("HEAT_TPU_TRACE_KEEP", "32"))
_MAX_SPANS = int(os.environ.get("HEAT_TPU_TRACE_MAX_SPANS", "256"))
_EXEMPLARS = _env_on("HEAT_TPU_TRACE_EXEMPLARS", True)

#: the ambient trace context of the current thread/task.  ``None`` means
#: "not inside a traced request" — the state every non-serving code path
#: stays in, paying one ContextVar read per span.
TraceContext = namedtuple("TraceContext", ["trace_id", "span_id"])
_CTX: "contextvars.ContextVar[Optional[TraceContext]]" = contextvars.ContextVar(
    "heat_tpu_trace_context", default=None
)

#: process-unique span ids (CPython's count.__next__ is atomic)
_SPAN_IDS = itertools.count(1)
_TRACE_SEQ = itertools.count(1)


#: per-process 64-bit base; trace ids are base+counter so allocation is
#: one atomic counter step, while ids stay unique across pod workers
#: (urandom base) — a clock-seeded base would collide on same-tick starts
_TRACE_ID_BASE = int.from_bytes(os.urandom(8), "big")


def new_trace_id() -> str:
    """A fresh 64-bit trace id (16 hex chars, urandom-based — unique
    across pod workers, unlike a clock)."""
    return f"{(_TRACE_ID_BASE + next(_TRACE_SEQ)) & 0xFFFFFFFFFFFFFFFF:016x}"


def next_span_id() -> int:
    """Allocate a process-unique span id."""
    return next(_SPAN_IDS)


def current_context() -> Optional[TraceContext]:
    """The active :class:`TraceContext` of this thread (None outside a
    traced request) — capture it before handing work to another thread."""
    return _CTX.get()


def current_trace_id() -> Optional[str]:
    """The active trace id, or None outside a traced request."""
    ctx = _CTX.get()
    return ctx.trace_id if ctx is not None else None


class use_context:
    """Attach a captured context on *this* thread for the enclosed block
    — the explicit handoff helper for thread hops (coalescer batcher,
    async checkpoint writer, model-loader worker).  ``None`` is a no-op
    so call sites need no branching.  A plain slotted context manager
    (not a generator) — it sits on the serving batcher's per-batch path."""

    __slots__ = ("ctx", "_token")

    def __init__(self, ctx: Optional[TraceContext]):
        self.ctx = ctx
        self._token = None

    def __enter__(self) -> Optional[TraceContext]:
        if self.ctx is not None:
            self._token = _CTX.set(self.ctx)
        return self.ctx

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self._token is not None:
            _CTX.reset(self._token)
            self._token = None
        return False


def bind_context(fn: Callable, ctx: Optional[TraceContext] = None) -> Callable:
    """Wrap ``fn`` so it runs under the given (default: current) trace
    context wherever it is later called — the handoff helper for thread
    targets and callbacks."""
    bound = current_context() if ctx is None else ctx

    def wrapped(*args, **kwargs):
        with use_context(bound):
            return fn(*args, **kwargs)

    return wrapped


def exemplars_enabled() -> bool:
    """Whether histogram exemplars are being recorded
    (``HEAT_TPU_TRACE_EXEMPLARS``, default on; meaningful only while a
    trace context is active anyway)."""
    return _EXEMPLARS


def set_exemplars(enabled: bool) -> bool:
    """Enable/disable exemplar recording at runtime; returns the
    previous state (the ``tracing_overhead`` perf gate's toggle)."""
    global _EXEMPLARS
    prev = _EXEMPLARS
    _EXEMPLARS = bool(enabled)
    return prev


def refresh_env() -> None:
    """Re-read the tracing knobs (tests that flip the env mid-process);
    resizes the retention deques, keeping the newest entries."""
    global _KEEP, _MAX_SPANS, _EXEMPLARS, _RECENT, _ERRORS
    _KEEP = int(os.environ.get("HEAT_TPU_TRACE_KEEP", "32"))
    _MAX_SPANS = int(os.environ.get("HEAT_TPU_TRACE_MAX_SPANS", "256"))
    _EXEMPLARS = _env_on("HEAT_TPU_TRACE_EXEMPLARS", True)
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store")
        _RECENT = deque(_RECENT, maxlen=max(1, _KEEP))
        _ERRORS = deque(_ERRORS, maxlen=max(1, _KEEP))
        # ascending by duration: drop from the fast end down to keep
        n_drop = max(0, len(_SLOWEST) - max(1, _KEEP))
        del _SLOWEST[:n_drop]
        del _SLOWEST_DURS[:n_drop]


# ----------------------------------------------------------------------
# the tail-sampled trace store
# ----------------------------------------------------------------------
class _Trace:
    """One request's span tree while in flight and after retention.

    Two collection forms, both appended lock-free on hot paths:
    ``spans`` holds full :class:`SpanRecord`\\ s (from ``span()`` /
    ``record_span``), ``batches`` holds *raw note batches* —
    ``(thread_id, depth, parent_id, notes)`` tuples handed over by
    ``flush_notes`` — that are materialized into records only when a
    view asks (``/tracez``, digests, crash bundles).  A co-batched
    request's trace shares the SAME batch tuple as the primary
    (zero-copy mirroring); materialization stamps each consumer's own
    trace_id.  ``n_spans`` tracks the combined count for the span cap."""

    __slots__ = (
        "trace_id", "route", "start_ts", "start_pc",
        "duration_ms", "status", "spans", "batches", "n_spans",
        "dropped", "seq",
    )

    def __init__(self, trace_id: str, route: str):
        self.trace_id = trace_id
        self.route = route
        self.start_ts = time.time()
        self.start_pc = time.perf_counter()
        self.duration_ms: Optional[float] = None
        self.status = "active"
        self.spans: List[Any] = []
        self.batches: List[tuple] = []
        self.n_spans = 0
        self.dropped = 0
        self.seq = next(_TRACE_SEQ)


#: in-flight traces + the three retention classes; every structure below
#: is only touched under the registered store lock
_STORE_LOCK = _tsan.register_lock("telemetry.tracing.store")
_ACTIVE: Dict[str, _Trace] = {}
_RECENT: "deque[_Trace]" = deque(maxlen=max(1, _KEEP))
#: slowest-k kept sorted ascending by duration; index 0 is the eviction
#: candidate (the *fastest* of the retained slow set).  _SLOWEST_DURS
#: mirrors the durations so the per-request insertion bisects a plain
#: float list instead of rebuilding one from the trace objects
_SLOWEST: List[_Trace] = []
_SLOWEST_DURS: List[float] = []
_ERRORS: "deque[_Trace]" = deque(maxlen=max(1, _KEEP))

_TRACES_C = _metrics.counter(
    "tracing.traces", "request traces finished through the tail store"
)
_SHED_ERR_C = _metrics.counter(
    "tracing.traces_shed_or_error", "finished traces retained as shed/errored"
)
_SPAN_DROP_C = _metrics.counter(
    "tracing.spans_dropped", "spans dropped by the per-trace span cap"
)


def _on_span(rec) -> None:
    """Collect one completed SpanRecord into its in-flight trace (called
    by the span tracer only when ``rec.trace_id`` is set).

    Deliberately lock-free: this sits on the serving hot path once per
    stamped span, from every traced thread at once.  The ``_ACTIVE``
    dict is only *read* here (``dict.get`` is atomic under the GIL, and
    the begin/finish mutations hold the store lock), and each trace's
    ``spans`` list is a per-trace leaf structure appended with the
    GIL-atomic ``list.append`` — the same leaf-structure carve-out the
    per-metric value locks use (LOCK_REGISTRY notes).  The span cap is
    enforced approximately under a race (bounded overshoot of at most
    one record per concurrent thread); a record landing just as its
    trace finishes is either retained with it or dropped — both fine."""
    tr = _ACTIVE.get(rec.trace_id)
    if tr is None:
        return
    if tr.n_spans < _MAX_SPANS:
        tr.spans.append(rec)
        tr.n_spans += 1
    else:
        tr.dropped += 1
        _SPAN_DROP_C.inc()


def _on_notes(trace_id: str, batch: tuple) -> None:
    """Hand one raw note batch (``(thread_id, depth, parent_id,
    notes)``) to an in-flight trace: a single lock-free append covers
    every stage in the batch — record materialization is deferred to
    view time, off the request path entirely."""
    tr = _ACTIVE.get(trace_id)
    if tr is None:
        return
    n = len(batch[3])
    if tr.n_spans + n <= _MAX_SPANS:
        tr.batches.append(batch)
        tr.n_spans += n
    else:
        tr.dropped += n
        _SPAN_DROP_C.inc(n)


def link_batch(trace_ids: Sequence[str], batch: Optional[tuple]) -> None:
    """Mirror a flushed note batch into other in-flight traces by
    reference (zero copy) — how a co-batched request's trace acquires
    the batch-level stages the primary context recorded."""
    if not batch:
        return
    for tid in trace_ids:
        _on_notes(tid, batch)


def link_spans(trace_ids: Sequence[str], records: Sequence[Any]) -> None:
    """Attach already-materialized span records to every listed
    in-flight trace, re-stamped per trace (hot paths use
    :func:`link_batch` with a raw note batch instead)."""
    if not trace_ids or not records:
        return
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store")
        for tid in trace_ids:
            tr = _ACTIVE.get(tid)
            if tr is None:
                continue
            for rec in records:
                if rec is None or rec.trace_id == tid:
                    continue  # the primary trace got it via _on_span
                if tr.n_spans < _MAX_SPANS:
                    tr.spans.append(rec._replace(trace_id=tid))
                    tr.n_spans += 1
                else:
                    tr.dropped += 1
                    _SPAN_DROP_C.inc()


def _begin(trace_id: str, route: str) -> _Trace:
    tr = _Trace(trace_id, route)
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store")
        _ACTIVE[trace_id] = tr
    return tr


def _finish(tr: _Trace, status: str, duration_ms: float) -> None:
    tr.status = status
    tr.duration_ms = duration_ms
    keep = max(1, _KEEP)
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store")
        _ACTIVE.pop(tr.trace_id, None)
        _RECENT.append(tr)
        # slowest-k: insert sorted by duration, evict the fastest
        ix = bisect.bisect_left(_SLOWEST_DURS, duration_ms)
        _SLOWEST.insert(ix, tr)
        _SLOWEST_DURS.insert(ix, duration_ms)
        if len(_SLOWEST) > keep:
            _SLOWEST.pop(0)
            _SLOWEST_DURS.pop(0)
        if status != "ok":
            _ERRORS.append(tr)
    _TRACES_C.inc()
    if status != "ok":
        _SHED_ERR_C.inc()


def reset_store() -> None:
    """Drop every retained and in-flight trace (tests, ``reset_all``)."""
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store")
        _ACTIVE.clear()
        _RECENT.clear()
        _SLOWEST.clear()
        _SLOWEST_DURS.clear()
        _ERRORS.clear()


# ----------------------------------------------------------------------
# the request root: one trace per request
# ----------------------------------------------------------------------
class request_span:
    """Open (or join) a request trace for the enclosed block.

    The serving layer's entry points wrap each request in one of these::

        with tracing.request_span("/v1/predict/km") as req:
            ...admission, coalesce, dispatch...
        latency_ms = req.duration_ms        # the ONE timing source

    * outermost use creates a fresh ``trace_id``, registers the trace as
      in-flight in the tail store, opens a ``serve.request`` root span,
      and — on exit — finishes the trace with a status derived from the
      exception (`ok`; :class:`OverloadedError` → ``shed``; anything
      else → ``error``), so shed and errored requests are *always*
      retained;
    * nested use (an HTTP handler calling the Python API) joins the
      active trace with a child span instead of starting a second trace;
    * with tracing disabled the block is still *timed* — callers keep
      one timing source — but nothing is recorded anywhere.

    ``duration_ms`` and ``trace_id`` stay readable after exit."""

    __slots__ = ("route", "attrs", "trace_id", "duration_ms", "status",
                 "_t0", "_trace", "_token", "_root", "_sid", "_depth")

    def __init__(self, route: str, trace_id: Optional[str] = None, **attrs):
        self.route = route
        self.attrs = attrs
        self.trace_id = trace_id
        self.duration_ms: Optional[float] = None
        self.status: Optional[str] = None
        self._trace: Optional[_Trace] = None
        self._token = None
        self._root = None

    def __enter__(self) -> "request_span":
        from . import spans as _spans  # lazy: spans imports this module

        self._t0 = time.perf_counter_ns()
        if not _spans.tracing_enabled():
            self.trace_id = None
            return self
        existing = _CTX.get()
        if existing is not None:
            # nested: join the active trace with a child span only
            self.trace_id = existing.trace_id
            self._root = _spans.span("serve.request", route=self.route, **self.attrs)
            self._root.__enter__()
            return self
        if self.trace_id is None:
            self.trace_id = new_trace_id()
        self._trace = _begin(self.trace_id, self.route)
        # the root span is synthesized at exit (one ring append instead
        # of the full span protocol — the serving hot path pays this per
        # request); the context carries its id so children parent to it
        self._sid = next_span_id()
        self._token = _CTX.set(TraceContext(self.trace_id, self._sid))
        tls = _spans._TLS
        self._depth = getattr(tls, "depth", 0)
        tls.depth = self._depth + 1
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        dur_ns = time.perf_counter_ns() - self._t0
        self.duration_ms = dur_ns / 1e6
        if exc_type is None:
            self.status = "ok"
        elif any(c.__name__ == "OverloadedError" for c in exc_type.__mro__):
            self.status = "shed"
        else:
            self.status = "error"
        if self._root is not None:  # joined a pre-existing trace
            self._root.__exit__(exc_type, exc, tb)
            return False
        if self._token is None:  # disabled mode: timing only
            return False
        from . import spans as _spans

        rec = _spans.SpanRecord(
            "serve.request", self._t0, dur_ns, threading.get_ident(),
            self._depth, dict(self.attrs, route=self.route),
            self.trace_id, self._sid, 0,
        )
        # caller-side stage notes + the root land in ONE ring acquisition
        _spans.flush_notes(extra=rec)
        _on_span(rec)
        _spans._TLS.depth = self._depth
        _CTX.reset(self._token)
        self._token = None
        if self._trace is not None:
            _finish(self._trace, self.status, self.duration_ms)
            self._trace = None
        return False


# ----------------------------------------------------------------------
# views: /tracez, cross-worker digests, crash bundles
# ----------------------------------------------------------------------
def _span_doc(rec) -> Dict[str, Any]:
    return {
        "name": rec.name,
        "start_ns": rec.start_ns,
        "duration_ms": round(rec.duration_ns / 1e6, 6),
        "thread_id": rec.thread_id,
        "depth": rec.depth,
        "span_id": rec.span_id,
        "parent_id": rec.parent_id,
        "attrs": {k: str(v) for k, v in rec.attrs.items()},
    }


def _materialize(tr: _Trace) -> List[Any]:
    """One record list for a trace: the collected SpanRecords plus the
    raw note batches materialized NOW (view time), each note stamped
    with THIS trace's id — the deferred half of the hot-path design."""
    from . import spans as _spans

    recs = list(tr.spans)
    for ident, depth, parent, notes in tr.batches:
        for name, t0, dur, attrs in notes:
            recs.append(
                _spans.SpanRecord(
                    name, int(t0), int(dur), ident, depth, attrs,
                    tr.trace_id, None, parent,
                )
            )
    return recs


def _stage_breakdown(tr: _Trace) -> Dict[str, Dict[str, float]]:
    out: Dict[str, Dict[str, float]] = {}

    def add(name: str, dur_ns: int) -> None:
        d = out.get(name)
        ms = dur_ns / 1e6
        if d is None:
            out[name] = {"count": 1, "total_ms": round(ms, 6)}
        else:
            d["count"] += 1
            d["total_ms"] = round(d["total_ms"] + ms, 6)

    for rec in tr.spans:
        add(rec.name, rec.duration_ns)
    for _ident, _depth, _parent, notes in tr.batches:
        for name, _t0, dur, _attrs in notes:
            add(name, dur)
    return dict(sorted(out.items()))


def _digest(tr: _Trace) -> Dict[str, Any]:
    threads = {r.thread_id for r in tr.spans} | {b[0] for b in tr.batches}
    return {
        "trace_id": tr.trace_id,
        "route": tr.route,
        "status": tr.status,
        "start_ts": tr.start_ts,
        "duration_ms": round(tr.duration_ms, 3) if tr.duration_ms is not None else None,
        "n_spans": tr.n_spans,
        "n_threads": len(threads),
        "dropped_spans": tr.dropped,
        "stages": _stage_breakdown(tr),
    }


def _full_doc(tr: _Trace) -> Dict[str, Any]:
    doc = _digest(tr)
    doc["spans"] = [
        _span_doc(r) for r in sorted(_materialize(tr), key=lambda r: r.start_ns)
    ]
    return doc


def note_records() -> List[Any]:
    """Materialized records of every retained + in-flight trace's note
    batches (NOT the full-span records — those live in the ring).  The
    Chrome export merges these so stage spans draw even though the hot
    path never wrote them to the ring; a batch mirrored into several
    co-batched traces materializes once per trace, each under its own
    trace_id."""
    active, recent, slowest, errors = _store_view()
    seen: Dict[str, _Trace] = {}
    for tr in active + list(recent) + slowest + list(errors):
        seen.setdefault(tr.trace_id, tr)
    out: List[Any] = []
    for tid in sorted(seen):
        tr = seen[tid]
        recs = _materialize(tr)
        out.extend(recs[len(tr.spans):])  # note-batch records only
    return out


def _store_view():
    with _STORE_LOCK:
        _tsan.note_access("telemetry.tracing.store", write=False)
        return (
            list(_ACTIVE.values()),
            list(_RECENT),
            list(reversed(_SLOWEST)),  # slowest first
            list(_ERRORS),
        )


def get_trace(trace_id: str) -> Optional[Dict[str, Any]]:
    """Full span tree of one retained or in-flight trace (None when the
    store never saw it or has evicted it everywhere)."""
    active, recent, slowest, errors = _store_view()
    for tr in active + list(recent) + slowest + list(errors):
        if tr.trace_id == trace_id:
            return _full_doc(tr)
    return None


def retained_traces() -> Dict[str, List[Dict[str, Any]]]:
    """The tail store's current contents as digests:
    ``{"active", "recent", "slowest", "errors"}`` (newest last in
    ``recent``/``errors``, slowest first in ``slowest``)."""
    active, recent, slowest, errors = _store_view()
    return {
        "active": [_digest(t) for t in active],
        "recent": [_digest(t) for t in recent],
        "slowest": [_digest(t) for t in slowest],
        "errors": [_digest(t) for t in errors],
    }


def trace_digest() -> List[Dict[str, Any]]:
    """Compact digests of every retained + in-flight trace, deduplicated
    by trace_id — the form that travels in a cross-worker snapshot so
    :func:`heat_tpu.telemetry.aggregate.merge_snapshots` can stitch one
    request across processes."""
    active, recent, slowest, errors = _store_view()
    seen: Dict[str, _Trace] = {}
    for tr in active + list(recent) + slowest + list(errors):
        seen.setdefault(tr.trace_id, tr)
    return [_digest(seen[tid]) for tid in sorted(seen)]


def traces_snapshot(max_spans: int = 2000) -> Dict[str, Any]:
    """The store as one JSON-safe document for crash bundles: in-flight
    traces with FULL span trees (what the process was serving when it
    died), retained classes as digests; ``max_spans`` bounds the bundle
    size."""
    active, recent, slowest, errors = _store_view()
    budget = max_spans

    def full_or_digest(tr: _Trace) -> Dict[str, Any]:
        nonlocal budget
        if budget - tr.n_spans >= 0:
            budget -= tr.n_spans
            return _full_doc(tr)
        return _digest(tr)

    return {
        "keep": _KEEP,
        "active": [full_or_digest(t) for t in active],
        "recent": [_digest(t) for t in recent],
        "slowest": [_digest(t) for t in slowest],
        "errors": [full_or_digest(t) for t in errors],
    }


def tracez_report() -> Dict[str, Any]:
    """The ``/tracez`` payload: retained traces grouped per route with a
    stage-breakdown digest each, plus the in-flight set."""
    active, recent, slowest, errors = _store_view()
    routes: Dict[str, Dict[str, Any]] = {}

    def bucket(name: str, traces: Sequence[_Trace]):
        for tr in traces:
            r = routes.setdefault(
                tr.route,
                {"recent": [], "slowest": [], "errors": [], "count": 0, "error_count": 0},
            )
            r[name].append(_digest(tr))

    bucket("recent", recent)
    bucket("slowest", slowest)
    bucket("errors", errors)
    for r in routes.values():
        r["count"] = len(r["recent"])
        r["error_count"] = len(r["errors"])
    return {
        "timestamp": time.time(),
        "keep": _KEEP,
        "max_spans_per_trace": _MAX_SPANS,
        "active": [_digest(t) for t in active],
        "routes": dict(sorted(routes.items())),
    }


#: the stage columns the /tracez HTML table shows, in pipeline order
_TRACEZ_STAGES = (
    "serve.admission",
    "serve.coalesce_wait",
    "serve.pad",
    "serve.dispatch",
    "serve.execute",
    "serve.scatter",
)


def render_tracez_html() -> str:
    """``/tracez`` as a small dependency-free HTML page: per route, the
    recent / slowest / shed+errored traces with a per-stage latency
    table (the columns are the serving pipeline's stages, in order)."""
    import html as _html

    rep = tracez_report()
    # EVERY user-influenced string (model/route names arrive verbatim
    # from request bodies; tenant/status/attrs ride along) goes through
    # html.escape — quote=True included, since several land inside
    # attribute values.  A hand-rolled &/</> replacement is not enough.
    esc = lambda s: _html.escape(str(s), quote=True)
    head = (
        "<!doctype html><html><head><title>heat_tpu /tracez</title><style>"
        "body{font-family:monospace;margin:1.5em}table{border-collapse:collapse;margin:.5em 0 1.5em}"
        "td,th{border:1px solid #999;padding:2px 8px;text-align:right}"
        "th{background:#eee}td.l,th.l{text-align:left}"
        ".shed{background:#ffe9c6}.error{background:#ffd6d6}</style></head><body>"
    )
    parts = [head, "<h1>/tracez — tail-sampled request traces</h1>"]
    parts.append(
        f"<p>keep={rep['keep']} per class · max {rep['max_spans_per_trace']} spans/trace · "
        f"{len(rep['active'])} in flight · generated {time.strftime('%H:%M:%S')}</p>"
    )

    def table(title: str, digests: List[Dict[str, Any]]) -> None:
        if not digests:
            return
        parts.append(f"<h3>{esc(title)}</h3><table><tr><th class=l>trace_id</th>"
                     "<th>status</th><th>total ms</th><th>spans</th><th>threads</th>")
        for st in _TRACEZ_STAGES:
            parts.append(f"<th>{esc(st.split('.', 1)[1])} ms</th>")
        parts.append("</tr>")
        for d in digests:
            cls = d["status"] if d["status"] in ("shed", "error") else ""
            parts.append(
                f'<tr class="{esc(cls)}"><td class=l>{esc(d["trace_id"])}</td>'
                f'<td>{esc(d["status"])}</td><td>{esc(d["duration_ms"])}</td>'
                f'<td>{esc(d["n_spans"])}</td><td>{esc(d["n_threads"])}</td>'
            )
            for st in _TRACEZ_STAGES:
                cell = d["stages"].get(st)
                parts.append(f"<td>{esc(cell['total_ms']) if cell else '·'}</td>")
            parts.append("</tr>")
        parts.append("</table>")

    table("in flight", rep["active"])
    for route, r in rep["routes"].items():
        parts.append(f"<h2>{esc(route)}</h2>")
        table("slowest", r["slowest"])
        table("shed / errored", r["errors"])
        table("recent", list(reversed(r["recent"])))
    if not rep["routes"] and not rep["active"]:
        parts.append("<p>(no traces retained yet — issue a traced request)</p>")
    parts.append("<p>JSON form: <a href='/tracez?format=json'>/tracez?format=json</a> · "
                 "span ring Chrome trace: <a href='/trace'>/trace</a></p></body></html>")
    return "".join(parts)
