"""Unified observability: metrics registry, structured spans, device traces.

The framework's answer to three production questions the reference
(instrumented only from the outside by ``perun``, SURVEY.md §5) cannot
ask: *how many bytes crossed ICI/DCN this fit, how long did we spend in
XLA compiles, and where did the wall-clock go?*

* :mod:`~heat_tpu.telemetry.metrics` — process-global named counters,
  gauges and bounded histograms.  The four legacy counter islands
  (``core.dispatch``, ``resilience``, ``utils.overlap``,
  ``nn.data_parallel``) register into it; their ``*_stats()`` functions
  are thin views; :func:`snapshot` returns everything in one document
  and :func:`expose` emits Prometheus text for scrape-based
  deployments.
* :mod:`~heat_tpu.telemetry.spans` — nestable host-side spans in a
  bounded ring buffer (``HEAT_TPU_TRACE=0`` disables), each doubling as
  a ``jax.profiler.TraceAnnotation`` so Xprof/perfetto device timelines
  attribute ops to framework operations;
  :func:`export_chrome_trace` writes ``chrome://tracing``-loadable JSON
  with zero extra deps.
* :mod:`~heat_tpu.telemetry.profiling` — ``start_trace``/``stop_trace``
  /``monitor`` device-trace hooks (moved from ``utils.profiling``,
  which re-exports them).
* :mod:`~heat_tpu.telemetry.server` — runtime-introspection HTTP
  endpoint (``HEAT_TPU_HTTP_PORT``; ``/metrics`` ``/varz`` ``/healthz``
  ``/trace`` ``/statusz`` on a daemon thread, off by default).
* :mod:`~heat_tpu.telemetry.slo` — declarative SLO monitors with
  multi-window burn-rate alerting over the bounded histograms
  (``/sloz``; ``HEAT_TPU_SLO_*``).
* :mod:`~heat_tpu.telemetry.sketch` — streaming input-drift sketches
  (per-feature moments + log-bucket histograms, PSI/KL vs a persisted
  baseline) for the serving path (``/driftz``; ``HEAT_TPU_SKETCH``).
* :mod:`~heat_tpu.telemetry.alerts` — deduplicated, severity-tagged
  fired/resolved alert events in a bounded ring, carrying exemplar
  trace ids (``HEAT_TPU_ALERT_RING``).
* :mod:`~heat_tpu.telemetry.aggregate` — cross-worker snapshot
  tagging/merging with straggler/skew gauges
  (``telemetry.straggler_score``).
* :mod:`~heat_tpu.telemetry.flight_recorder` — crash flight recorder
  (``HEAT_TPU_FLIGHT_RECORDER``): atomic CRC32-checksummed forensic
  bundles on unhandled exceptions, rendered by
  ``python -m heat_tpu.telemetry.inspect``.

Instrumentation wired through the stack: ``parallel.comm`` collectives
account trace-time payload bytes x participants into
``comm.bytes.{op}`` / ``comm.calls.{op}``; ``core.dispatch`` records
per-compile wall time into the ``dispatch.compile_ms`` histogram;
``core.base.resumable_fit_loop`` emits heartbeat spans and the
``fit.iter_rate`` gauge; checkpoint save/restore and the async writer
drain are spanned so ``overlap.ckpt_stall_ms`` is attributable.

``HEAT_TPU_METRICS_DUMP=<path>`` writes the final snapshot as JSON at
process exit (CI scraping).  See ``docs/observability.md``.
"""

from __future__ import annotations

import atexit
import os
import time as _time
from typing import Any, Dict, Optional

from . import metrics
from . import journal
from . import tsdb
from . import tracing
from . import spans
from . import profiling
from . import alerts
from . import slo
from . import sketch
from . import aggregate
from . import flight_recorder
from . import observatory
from . import server
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    REGISTRY,
    counter,
    dump_json,
    expose,
    gauge,
    histogram,
    snapshot,
)
from .spans import (
    SpanRecord,
    chrome_trace_doc,
    clear_spans,
    export_chrome_trace,
    get_spans,
    record_span,
    set_tracing,
    span,
    tracing_enabled,
)
from .tracing import (
    TraceContext,
    bind_context,
    current_context,
    current_trace_id,
    request_span,
    tracez_report,
    use_context,
)
from .profiling import annotate, monitor, start_trace, stop_trace, trace
from .aggregate import (
    gather_snapshots,
    merge_snapshots,
    tag_snapshot,
    write_worker_snapshot,
)
from .flight_recorder import dump_bundle
from .observatory import (
    device_peaks,
    rooflinez_report,
    start_capture,
    stop_capture,
    watermark_tick,
)
from .server import start_server, stop_server
from .alerts import active_alerts, alert_events, alerts_snapshot
from .journal import (
    DecisionEvent,
    causal_chain,
    decisionz_report,
    emit,
    journal_events,
    read_journal,
)
from .tsdb import (
    query,
    queryz_report,
    record,
    sample_once,
    start_sampler,
    stop_sampler,
    window_stats,
)
from .slo import (
    SLO,
    install_default_slos,
    parse_slo,
    register_slo,
    slo_report,
    start_monitor,
    stop_monitor,
)
from .sketch import SKETCHES, check_drift, drift_report, record_batch

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "MetricsRegistry",
    "REGISTRY",
    "DecisionEvent",
    "SKETCHES",
    "SLO",
    "SpanRecord",
    "TraceContext",
    "active_alerts",
    "alert_events",
    "alerts_snapshot",
    "annotate",
    "causal_chain",
    "check_drift",
    "decisionz_report",
    "emit",
    "journal_events",
    "query",
    "queryz_report",
    "read_journal",
    "record",
    "sample_once",
    "start_sampler",
    "stop_sampler",
    "window_stats",
    "drift_report",
    "install_default_slos",
    "parse_slo",
    "record_batch",
    "register_slo",
    "slo_report",
    "start_monitor",
    "stop_monitor",
    "bind_context",
    "chrome_trace_doc",
    "clear_spans",
    "counter",
    "current_context",
    "current_trace_id",
    "device_peaks",
    "dump_bundle",
    "dump_json",
    "expose",
    "export_chrome_trace",
    "gather_snapshots",
    "gauge",
    "get_spans",
    "histogram",
    "merge_snapshots",
    "monitor",
    "record_span",
    "request_span",
    "reset_all",
    "rooflinez_report",
    "set_tracing",
    "snapshot",
    "span",
    "start_capture",
    "start_server",
    "start_trace",
    "stop_capture",
    "stop_server",
    "stop_trace",
    "summary_line",
    "tag_snapshot",
    "trace",
    "tracez_report",
    "tracing_enabled",
    "use_context",
    "watermark_tick",
    "write_worker_snapshot",
]

#: legacy per-domain reset functions delegate here with these names;
#: a domain maps to the registry prefixes it owns
_DOMAIN_PREFIXES = {
    "dispatch": ("dispatch.",),
    "faults": ("fault.",),
    "retry": ("retry.",),
    "resilience": ("fault.", "retry."),
    "overlap": ("overlap.",),
    "comm": ("comm.",),
    "fit": ("fit.",),
    "spans": ("spans.",),
    "tracing": ("tracing.",),
    "flight": ("flight.",),
    "checkpoint": ("checkpoint.",),
    "alerts": ("alerts.",),
    "slo": ("slo.",),
    "drift": ("drift.",),
    "observatory": ("observatory.",),
    "journal": ("journal.",),
    "tsdb": ("tsdb.",),
    "telemetry": ("spans.", "tracing.", "fit.", "telemetry.", "flight.",
                  "checkpoint.", "alerts.", "slo.", "drift.", "observatory.",
                  "journal.", "tsdb."),
}


def reset_all(domain: Optional[str] = None) -> None:
    """Zero telemetry state in one call.

    With no argument: every registered metric (dispatch, resilience,
    overlap, comm, fit, ...) AND the span ring buffer AND the tail-
    sampled trace store — the single replacement for the four legacy
    reset conventions.  With a domain name (``"dispatch"``,
    ``"resilience"``, ``"overlap"``, ``"comm"``, ...), only that
    island's metrics; the legacy ``reset_stats`` /
    ``reset_fault_stats`` / ``reset_retry_stats`` /
    ``reset_overlap_stats`` functions delegate here per-domain."""
    if domain is None:
        metrics.reset(None)
        spans.clear_spans()
        tracing.reset_store()
        alerts.clear_alerts()
        slo.reset_monitors()
        sketch.SKETCHES.clear()
        observatory.reset()
        journal.reset_journal()
        tsdb.reset_tsdb()
        return
    prefixes = _DOMAIN_PREFIXES.get(domain)
    if prefixes is None:
        raise ValueError(
            f"unknown telemetry domain {domain!r}; known: {sorted(_DOMAIN_PREFIXES)}"
        )
    for p in prefixes:
        metrics.reset(p)
    if domain in ("spans", "telemetry"):
        spans.clear_spans()
    if domain in ("tracing", "telemetry"):
        tracing.reset_store()
    if domain in ("alerts", "telemetry"):
        alerts.clear_alerts()
    if domain in ("slo", "telemetry"):
        slo.reset_monitors()
    if domain in ("drift", "telemetry"):
        sketch.SKETCHES.clear()
    if domain in ("observatory", "telemetry"):
        observatory.reset()
    if domain in ("journal", "telemetry"):
        journal.reset_journal()
    if domain in ("tsdb", "telemetry"):
        tsdb.reset_tsdb()


def summary_line(iter_rate: Optional[float] = None) -> str:
    """One-line human summary of the headline metrics — the string the
    example scripts print after a fit: cumulative collective traffic
    (trace-time model, bytes x participants), total XLA compile wall
    time, and the last fit iteration rate (``fit.iter_rate`` gauge, or
    the explicit ``iter_rate`` argument for fast-path fits that never
    touch the gauge)."""
    snap = metrics.snapshot()
    comm_bytes = sum(
        v for k, v in snap.items()
        if k.startswith("comm.bytes.") and isinstance(v, (int, float))
    )
    compile_doc = snap.get("dispatch.compile_ms") or {}
    compile_ms = float(compile_doc.get("sum") or 0.0)
    if iter_rate is None:
        rate = snap.get("fit.iter_rate") or 0.0
    else:
        rate = iter_rate
    rate_s = f"{rate:.1f} iter/s" if rate else "n/a"
    return (
        f"telemetry: comm {comm_bytes / 2**30:.4f} GiB · "
        f"compile {compile_ms:.0f} ms · iter rate {rate_s}"
    )


@atexit.register
def _dump_at_exit() -> None:  # pragma: no cover - exercised via subprocess
    """``HEAT_TPU_METRICS_DUMP=<path>``: write the final metrics snapshot
    as JSON at interpreter exit (checked at exit time, so setting the
    variable after import still works).  The write goes through the
    resilience atomic+CRC32 writer, so a crash mid-dump can never leave
    a truncated artifact."""
    path = os.environ.get("HEAT_TPU_METRICS_DUMP")
    if not path:
        return
    try:
        metrics.dump_json(path)
    except Exception:  # lint: allow H501(best-effort metrics dump at interpreter exit)
        pass


def build_info_labels() -> Dict[str, str]:
    """The binary's identity labels: heat_tpu version, jax/jaxlib
    versions, the active backend and device kind.  Resolved lazily by
    the ``build_info`` metric on its first read (``jax.devices()``
    initializes the backend; an import must not)."""
    from ..version import __version__ as _v

    labels: Dict[str, str] = {"version": str(_v)}
    try:
        import jax
        import jaxlib

        labels["jax"] = str(jax.__version__)
        labels["jaxlib"] = str(getattr(jaxlib, "__version__", "?"))
        labels["backend"] = str(jax.default_backend())
        devs = jax.devices()
        labels["device_kind"] = str(devs[0].device_kind) if devs else "none"
    except Exception:  # lint: allow H501(no working backend: identity degrades to the version labels)
        labels.setdefault("backend", "unavailable")
    return labels


#: satellite identity metrics on every scrape surface (/metrics, /varz,
#: /statusz): which binary produced these numbers, and since when.  The
#: start timestamp is a callback gauge so ``reset_all()`` cannot zero
#: the process's birth time.
_PROCESS_START_TS = _time.time()
metrics.info(
    "build_info",
    "binary identity: heat_tpu/jax/jaxlib versions, backend, device kind",
    fn=build_info_labels,
)
metrics.gauge(
    "process.start_ts",
    "unix timestamp this process imported heat_tpu.telemetry",
    fn=lambda: _PROCESS_START_TS,
)

# runtime introspection: HEAT_TPU_HTTP_PORT starts the HTTP endpoint,
# HEAT_TPU_FLIGHT_RECORDER arms the crash recorder — both off by
# default, both zero-cost when off (docs/observability.md)
server.maybe_start_from_env()
flight_recorder.maybe_install_from_env()
