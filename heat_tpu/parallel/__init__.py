"""Mesh/communication layer (the TPU-native analog of heat/core/communication.py)."""

from .comm import (
    Communication,
    WORLD,
    SELF,
    get_comm,
    sanitize_comm,
    use_comm,
    init,
    is_initialized,
    finalize,
)

__all__ = [
    "Communication",
    "WORLD",
    "SELF",
    "get_comm",
    "sanitize_comm",
    "use_comm",
    "init",
    "is_initialized",
    "finalize",
]
