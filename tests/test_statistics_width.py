"""Statistics edge matrix at reference width (heat/core/tests/
test_statistics.py family): weighted averages, ddof variance, nan
variants, cov/corrcoef options, histogram weights/density/ranges,
quantile interpolations, argmin/argmax ties — numpy ground truth across
splits on the 8-device mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]


@pytest.fixture(scope="module")
def vec():
    return np.random.default_rng(1).standard_normal(37)


@pytest.fixture(scope="module")
def mat():
    return np.random.default_rng(2).standard_normal((11, 5))


@pytest.mark.parametrize("split", SPLITS)
def test_average_weighted(vec, split):
    w = np.abs(np.random.default_rng(3).standard_normal(37)) + 0.1
    x = ht.array(vec, split=split)
    hw = ht.array(w, split=split)
    np.testing.assert_allclose(float(ht.average(x, weights=hw)), np.average(vec, weights=w), rtol=1e-12)
    got, wsum = ht.average(x, weights=hw, returned=True)
    want, wsum_np = np.average(vec, weights=w, returned=True)
    np.testing.assert_allclose(float(got), want, rtol=1e-12)
    np.testing.assert_allclose(float(wsum), wsum_np, rtol=1e-12)


@pytest.mark.parametrize("split", SPLITS)
def test_average_axis_weights(mat, split):
    w = np.arange(1.0, 12.0)
    x = ht.array(mat, split=split)
    np.testing.assert_allclose(
        ht.average(x, axis=0, weights=ht.array(w, split=split)).numpy(),
        np.average(mat, axis=0, weights=w),
        rtol=1e-12,
    )


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("ddof", [0, 1, 3])
def test_var_std_ddof(mat, split, ddof):
    x = ht.array(mat, split=split)
    np.testing.assert_allclose(float(ht.var(x, ddof=ddof)), np.var(mat, ddof=ddof), rtol=1e-12)
    np.testing.assert_allclose(
        ht.std(x, axis=0, ddof=ddof).numpy(), np.std(mat, axis=0, ddof=ddof), rtol=1e-12
    )


@pytest.mark.parametrize("split", SPLITS)
def test_nan_statistics(split):
    a = np.array([1.0, np.nan, 3.0, 4.0, np.nan, 6.0, 7.5, -2.0], np.float64)
    x = ht.array(a, split=split)
    np.testing.assert_allclose(float(ht.nanmean(x)), np.nanmean(a), rtol=1e-12)
    np.testing.assert_allclose(float(ht.nanvar(x)), np.nanvar(a), rtol=1e-12)
    np.testing.assert_allclose(float(ht.nanstd(x)), np.nanstd(a), rtol=1e-12)
    np.testing.assert_allclose(float(ht.nanmedian(x)), np.nanmedian(a), rtol=1e-12)
    np.testing.assert_allclose(float(ht.nanmax(x)), np.nanmax(a))
    np.testing.assert_allclose(float(ht.nanmin(x)), np.nanmin(a))
    np.testing.assert_allclose(
        float(ht.nanpercentile(x, 60.0)), np.nanpercentile(a, 60.0), rtol=1e-12
    )


@pytest.mark.parametrize("split", SPLITS)
def test_cov_corrcoef_options(mat, split):
    x = ht.array(mat, split=split)
    np.testing.assert_allclose(ht.cov(x).numpy(), np.cov(mat), rtol=1e-10)
    np.testing.assert_allclose(
        ht.cov(x, rowvar=False).numpy(), np.cov(mat, rowvar=False), rtol=1e-10
    )
    np.testing.assert_allclose(ht.cov(x, ddof=0).numpy(), np.cov(mat, ddof=0), rtol=1e-10)
    np.testing.assert_allclose(ht.corrcoef(x).numpy(), np.corrcoef(mat), rtol=1e-10)


@pytest.mark.parametrize("split", SPLITS)
def test_histogram_options(vec, split):
    x = ht.array(vec, split=split)
    w = np.abs(vec) + 0.5
    for kwargs in (
        {"bins": 7},
        {"bins": 12, "range": (-1.5, 1.5)},
        {"bins": 5, "density": True},
        {"bins": 6, "weights": w},
    ):
        hk = dict(kwargs)
        if "weights" in hk:
            hk["weights"] = ht.array(hk["weights"], split=split)
        h, e = ht.histogram(x, **hk)
        hn, en = np.histogram(vec, **kwargs)
        np.testing.assert_allclose(np.asarray(h.numpy()), hn, rtol=1e-10)
        np.testing.assert_allclose(np.asarray(e.numpy()), en, rtol=1e-10)


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("interp", ["linear", "lower", "higher", "nearest", "midpoint"])
def test_quantile_interpolations(vec, split, interp):
    x = ht.array(vec, split=split)
    q = [0.0, 0.25, 0.5, 0.9, 1.0]
    got = ht.quantile(x, q, interpolation=interp)
    want = np.quantile(vec, q, method=interp)
    np.testing.assert_allclose(np.asarray(got.numpy()), want, rtol=1e-12)


@pytest.mark.parametrize("split", SPLITS)
def test_argminmax_ties_first_index(split):
    a = np.array([3.0, 1.0, 1.0, 5.0, 5.0, 1.0], np.float32)
    x = ht.array(a, split=split)
    assert int(ht.argmin(x)) == int(np.argmin(a))
    assert int(ht.argmax(x)) == int(np.argmax(a))
    m = np.array([[2.0, 2.0], [1.0, 3.0], [1.0, 0.5]], np.float32)
    xm = ht.array(m, split=0 if split == 0 else None)
    np.testing.assert_array_equal(ht.argmin(xm, axis=0).numpy(), np.argmin(m, axis=0))
    np.testing.assert_array_equal(ht.argmax(xm, axis=1).numpy(), np.argmax(m, axis=1))


@pytest.mark.parametrize("split", SPLITS)
def test_ptp_and_moments(mat, split):
    x = ht.array(mat, split=split)
    np.testing.assert_allclose(float(ht.ptp(x)), np.ptp(mat), rtol=1e-12)
    np.testing.assert_allclose(ht.ptp(x, axis=0).numpy(), np.ptp(mat, axis=0), rtol=1e-12)
    from scipy import stats as sps

    # heat's default is the unbiased estimator == scipy bias=False
    np.testing.assert_allclose(
        float(ht.skew(ht.array(mat[:, 0], split=split))),
        sps.skew(mat[:, 0], bias=False),
        rtol=1e-10,
    )
    np.testing.assert_allclose(
        float(ht.kurtosis(ht.array(mat[:, 0], split=split))),
        sps.kurtosis(mat[:, 0], bias=False),
        rtol=1e-10,
    )
    np.testing.assert_allclose(
        float(ht.skew(ht.array(mat[:, 0], split=split), unbiased=False)),
        sps.skew(mat[:, 0], bias=True),
        rtol=1e-10,
    )


@pytest.mark.parametrize("split", SPLITS)
def test_bincount_weights_minlength(split):
    a = np.array([0, 1, 1, 3, 2, 1, 7], np.int32)
    w = np.linspace(0.5, 2.0, 7)
    x = ht.array(a, split=split)
    np.testing.assert_array_equal(ht.bincount(x).numpy(), np.bincount(a))
    np.testing.assert_array_equal(
        ht.bincount(x, minlength=12).numpy(), np.bincount(a, minlength=12)
    )
    np.testing.assert_allclose(
        ht.bincount(x, weights=ht.array(w, split=split)).numpy(),
        np.bincount(a, weights=w),
        rtol=1e-12,
    )


@pytest.mark.parametrize("split", SPLITS)
def test_digitize_right(vec, split):
    bins = np.linspace(-2.0, 2.0, 9)
    x = ht.array(vec, split=split)
    for right in (False, True):
        np.testing.assert_array_equal(
            ht.digitize(x, ht.array(bins), right=right).numpy(),
            np.digitize(vec, bins, right=right),
        )


def test_keepdims_median_mean_uneven():
    a = np.random.default_rng(4).standard_normal((13, 3))
    x = ht.array(a, split=0)  # 13 rows over 8 devices: empty high shards
    np.testing.assert_allclose(
        ht.mean(x, axis=0).numpy(), a.mean(axis=0), rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(ht.median(x, axis=0).numpy()), np.median(a, axis=0), rtol=1e-12
    )
    got = ht.mean(x, axis=1, keepdims=True)
    assert got.shape == (13, 1)
    np.testing.assert_allclose(got.numpy(), a.mean(axis=1, keepdims=True), rtol=1e-12)
