"""Sparse elementwise arithmetic, analog of heat/sparse/arithmetics.py
(add :17, mul :58 via ``__binary_op_csx``, sparse/_operations.py:17-209).

The reference applies local torch sparse ops per chunk and re-syncs nnz;
here the global BCOO op (union for add, intersection for mul) is one XLA
expression.
"""

from __future__ import annotations

from jax.experimental import sparse as jsparse

from ..core.dndarray import DNDarray
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix

__all__ = ["add", "mul"]


def _binary_op_csx(op_name, t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Generic sparse-sparse elementwise op (sparse/_operations.py:17)."""
    if not isinstance(t1, DCSX_matrix) or not isinstance(t2, DCSX_matrix):
        raise TypeError(f"both operands must be sparse matrices, got {type(t1)}, {type(t2)}")
    if type(t1) is not type(t2):
        raise TypeError(f"operands must share the sparse format, got {type(t1).__name__} and {type(t2).__name__}")
    if t1.shape != t2.shape:
        raise ValueError(f"shapes must match, got {t1.shape} and {t2.shape}")
    a, b = t1.larray, t2.larray
    if op_name == "add":
        res = jsparse.bcoo_sum_duplicates(_bcoo_union_add(a, b))
    else:
        res = jsparse.bcoo_sum_duplicates(jsparse.bcoo_sort_indices(jsparse.bcoo_multiply_sparse(a, b)))
    from ..core import types

    dtype = types.canonical_heat_type(res.data.dtype)
    return type(t1)(res, int(res.nse), t1.shape, dtype, t1.split, t1.device, t1.comm)


def _bcoo_union_add(a, b):
    import jax.numpy as jnp

    data = jnp.concatenate([a.data, b.data])
    idx = jnp.concatenate([a.indices, b.indices], axis=0)
    return jsparse.bcoo_sort_indices(jsparse.BCOO((data, idx), shape=a.shape))


def add(t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Element-wise sparse addition (sparse/arithmetics.py:17)."""
    return _binary_op_csx("add", t1, t2)


def mul(t1: DCSX_matrix, t2: DCSX_matrix) -> DCSX_matrix:
    """Element-wise sparse multiplication (sparse/arithmetics.py:58)."""
    return _binary_op_csx("mul", t1, t2)
