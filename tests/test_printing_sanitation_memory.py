"""Printing / sanitation / stride-tricks / constants / memory battery —
the small reference families (heat/core/tests/test_printing.py,
test_sanitation.py, test_stride_tricks.py, test_constants.py,
test_memory.py) that previously only had incidental coverage.
"""

import numpy as np
import pytest

import heat_tpu as ht


class TestPrinting:
    def test_repr_and_str_small(self):
        a = ht.arange(6, split=0)
        s = str(a)
        assert "0" in s and "5" in s
        r = repr(a)
        assert "DNDarray" in r or "[" in r

    def test_printoptions_threshold(self):
        big = ht.arange(10_000, split=0)
        with ht.printoptions(threshold=10):
            s = str(big)
        assert "..." in s  # summarized like numpy
        # the temporary options must not leak into numpy's globals
        assert np.get_printoptions()["threshold"] != 10

    def test_set_get_printoptions_roundtrip(self):
        saved = ht.get_printoptions()
        try:
            ht.set_printoptions(precision=2)
            assert ht.get_printoptions()["precision"] == 2
            s = str(ht.array(np.array([1.23456789], np.float64), split=0))
            assert "1.23456789" not in s
        finally:
            ht.set_printoptions(**saved)

    def test_array2string_and_repr_funcs(self):
        a = ht.array(np.eye(2, dtype=np.float32), split=0)
        assert "1." in ht.array2string(a)
        assert "1." in ht.array_str(a)


class TestSanitation:
    def test_sanitize_axis_rules(self):
        from heat_tpu.core.stride_tricks import sanitize_axis

        assert sanitize_axis((4, 5), 1) == 1
        assert sanitize_axis((4, 5), -1) == 1
        assert sanitize_axis((4, 5), None) is None
        with pytest.raises(ValueError):
            sanitize_axis((4, 5), 2)
        with pytest.raises(ValueError):
            sanitize_axis((4, 5), -3)

    def test_broadcast_shape_rules(self):
        from heat_tpu.core.stride_tricks import broadcast_shape

        assert broadcast_shape((8, 1), (1, 5)) == (8, 5)
        assert broadcast_shape((3,), (4, 3)) == (4, 3)
        assert broadcast_shape((), (2, 2)) == (2, 2)
        with pytest.raises(ValueError):
            broadcast_shape((3,), (4,))

    def test_sanitize_out_shape_mismatch(self):
        out = ht.zeros((3,), split=0)
        with pytest.raises((ValueError, TypeError)):
            ht.add(ht.arange(4, split=0), 1, out=out)

    def test_binary_op_comm_mismatch(self):
        if ht.get_comm().size < 2:
            pytest.skip("needs a mesh to build a differing sub-communicator")
        sub = ht.get_comm().split(list(range(ht.get_comm().size // 2)))
        a = ht.arange(4, split=0)
        b = ht.arange(4, split=0, comm=sub)
        with pytest.raises((NotImplementedError, ValueError)):
            a + b


class TestConstants:
    def test_values_match_numpy(self):
        assert ht.pi == np.pi
        assert ht.e == np.e
        assert ht.inf == np.inf
        assert np.isnan(ht.nan)

    def test_constants_in_expressions(self):
        a = ht.array(np.array([0.0, ht.pi / 2], np.float64), split=0)
        np.testing.assert_allclose(ht.sin(a).numpy(), [0.0, 1.0], atol=1e-12)


class TestMemory:
    def test_copy_is_independent(self):
        a = ht.arange(8, dtype=ht.float32, split=0)
        b = ht.copy(a)
        b[0] = 99.0
        assert float(a[0]) == 0.0 and float(b[0]) == 99.0
        assert b.split == a.split and b.dtype == a.dtype

    def test_sanitize_memory_layout_noop(self):
        # layouts belong to XLA; the API accepts order= and ignores C/F
        from heat_tpu.core.memory import sanitize_memory_layout

        want = np.arange(6).reshape(2, 3)
        for order in ("C", "F"):
            a = ht.array(want, split=0, order=order)
            np.testing.assert_array_equal(a.numpy(), want)
            buf = a.larray_padded
            assert sanitize_memory_layout(buf, order=order) is buf


class TestStrideTricks:
    def test_broadcast_arrays_shapes(self):
        a = ht.arange(3, split=0).reshape((1, 3))
        b = ht.arange(4, split=0).reshape((4, 1))
        x, y = ht.broadcast_arrays(a, b)
        assert x.shape == (4, 3) and y.shape == (4, 3)
        np.testing.assert_array_equal(
            (x + y).numpy(), np.arange(3)[None] + np.arange(4)[:, None]
        )

    def test_broadcast_to_readonly_semantics(self):
        a = ht.arange(3, split=0)
        t = ht.broadcast_to(a, (5, 3))
        assert t.shape == (5, 3)
        np.testing.assert_array_equal(t.numpy(), np.broadcast_to(np.arange(3), (5, 3)))
