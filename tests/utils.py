"""Shared test helpers, analog of the reference's test_suites/basic_test.py.

The central idiom is kept: compare the distributed result against a
single-process NumPy ground truth, for every split (basic_test.py:77+).
"""

import numpy as np


def assert_array_equal(ht_array, expected, rtol=0, atol=0):
    """Gathered global result must equal the numpy ground truth."""
    expected = np.asarray(expected)
    got = ht_array.numpy()
    assert got.shape == expected.shape, f"shape {got.shape} != expected {expected.shape}"
    if rtol or atol:
        np.testing.assert_allclose(got, expected, rtol=rtol, atol=atol)
    else:
        np.testing.assert_array_equal(got, expected)


def assert_func_equal(ht_func, np_func, np_args, splits=(None, 0), rtol=1e-6, atol=1e-6, **kwargs):
    """Run a heat function against its numpy counterpart over all splits."""
    import heat_tpu as ht

    expected = np_func(*np_args)
    for split in splits:
        ht_args = [ht.array(a, split=split) for a in np_args]
        result = ht_func(*ht_args, **kwargs)
        np.testing.assert_allclose(result.numpy(), expected, rtol=rtol, atol=atol, err_msg=f"split={split}")
