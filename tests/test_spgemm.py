"""Output-sparse SpGEMM tests (ISSUE 16 tentpole 1 + test satellite).

The contract under test (sparse/arithmetics.py ``_spgemm``):

* sparse @ sparse values match the scipy reference across a
  density x split x world-size grid, for CSR and CSC compressions and
  mixed formats, through the triplet ring (``todense()`` never touches
  a full operand);
* route selection follows the estimated output density: below
  ``HEAT_TPU_SPGEMM_DENSE_DENSITY`` the ring runs (the dense fallback is
  never called), at dense-regime densities the GEMM-style fallback is;
* the OOM regime: a product whose dense row block cannot fit the armed
  ``HEAT_TPU_HBM_BUDGET_BYTES`` raises MemoryError on the dense route
  while the output-sparse ring completes it — the allocation asymmetry
  the tentpole exists for;
* resumability: a transient fault at the ring's ``comm.collective``
  nnz re-sync aborts the matmul cleanly (operands unmutated), and a
  plain retry reproduces the reference values exactly.
"""

import numpy as np
import pytest
import scipy.sparse as sp

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.sparse import arithmetics as sa


def _pair(m, k, n, da, db, seed, fmt="csr"):
    a = sp.random(m, k, density=da, random_state=seed, format=fmt, dtype=np.float64)
    b = sp.random(k, n, density=db, random_state=seed + 1, format=fmt, dtype=np.float64)
    return a, b


def _as_ht(mat, split):
    if mat.format == "csc":
        return ht.sparse.sparse_csc_matrix(mat, split=split)
    return ht.sparse.sparse_csr_matrix(mat, split=split)


def _est_density(a, b):
    cells = float(a.shape[0]) * float(a.shape[1]) * float(b.shape[1])
    return 1.0 - float(np.exp(-float(a.nnz) * float(b.nnz) / cells))


# ----------------------------------------------------------------------
# value grid: density x split (world-size P vs 1) x format
# ----------------------------------------------------------------------
class TestValueGrid:
    # split=0 runs the full P-device ring (world size = the conftest
    # mesh), split=None the single-shard program — the two world sizes
    # a virtual-device session can drive
    @pytest.mark.parametrize("split", [0, None])
    @pytest.mark.parametrize("density", [0.001, 0.02, 0.2])
    def test_csr_csr(self, density, split, monkeypatch):
        a, b = _pair(240, 168, 200, density, density, seed=int(density * 1000))
        if _est_density(a, b) < 0.5:
            # sub-threshold: the ring must carry it alone
            monkeypatch.setattr(sa, "_spgemm_dense", _forbidden_dense)
        c = _as_ht(a, split) @ _as_ht(b, split)
        assert isinstance(c, ht.sparse.DCSR_matrix)
        np.testing.assert_allclose(c.toarray(), (a @ b).toarray(), rtol=1e-10, atol=1e-12)

    @pytest.mark.parametrize("density", [0.002, 0.05])
    def test_csc_csc(self, density):
        a, b = _pair(150, 200, 120, density, density, seed=29, fmt="csc")
        c = _as_ht(a, 1) @ _as_ht(b, 1)
        assert isinstance(c, ht.sparse.DCSC_matrix)
        np.testing.assert_allclose(c.toarray(), (a @ b).toarray(), rtol=1e-10, atol=1e-12)

    def test_mixed_formats(self):
        a = sp.random(130, 170, density=0.02, random_state=5, format="csr", dtype=np.float64)
        b = sp.random(170, 90, density=0.02, random_state=6, format="csc", dtype=np.float64)
        ref = (a @ b).toarray()
        got = _as_ht(a, 0) @ _as_ht(b, 1)
        np.testing.assert_allclose(got.toarray(), ref, rtol=1e-10, atol=1e-12)
        got2 = _as_ht(b.tocsc().T.tocsr(), 0)  # sanity: transpose identity
        np.testing.assert_allclose(got2.toarray(), b.toarray().T, rtol=1e-12)

    def test_dense_regime_takes_fallback(self, monkeypatch):
        # 0.3 x 0.3 on a small cube -> estimated density ~1: the ring's
        # partial-triplet traffic loses and the GEMM route must run
        a, b = _pair(64, 64, 64, 0.3, 0.3, seed=41)
        assert _est_density(a, b) >= 0.5
        calls = []
        orig = sa._spgemm_dense
        monkeypatch.setattr(
            sa, "_spgemm_dense", lambda x, y: calls.append(1) or orig(x, y)
        )
        c = _as_ht(a, 0) @ _as_ht(b, 0)
        assert calls == [1]
        np.testing.assert_allclose(c.toarray(), (a @ b).toarray(), rtol=1e-10, atol=1e-12)


def _forbidden_dense(a, b):  # pragma: no cover - failure path
    raise AssertionError("dense fallback reached below the density threshold")


# ----------------------------------------------------------------------
# the OOM regime (acceptance: dense raises, output-sparse succeeds)
# ----------------------------------------------------------------------
def test_oom_regime_dense_raises_output_sparse_succeeds(monkeypatch):
    # 2^20 x 2^20 output: the dense fallback's per-device row block is
    # ~2 TiB -- unallocatable under any real budget -- while the ring's
    # peak is O(Ca * r_max) partial triplets
    m = k = n = 1 << 20

    def _rand_coo(rows, cols, nnz, seed):
        # sp.random can't sample 2^40 cells without replacement; explicit
        # deduped COO triplets sidestep the dense index permutation
        rng = np.random.default_rng(seed)
        r = rng.integers(0, rows, nnz)
        c = rng.integers(0, cols, nnz)
        keep = np.unique(np.stack([r, c], 1), axis=0)
        v = rng.standard_normal(len(keep))
        return sp.coo_matrix(
            (v, (keep[:, 0], keep[:, 1])), shape=(rows, cols)
        ).tocsr()

    a = _rand_coo(m, k, 2000, seed=3)
    # share index space so the product has nonzeros to check
    b = sp.csr_matrix(
        (np.abs(a.tocoo().data) + 0.5, (a.tocoo().col, a.tocoo().row)),
        shape=(k, n),
    )
    A, B = _as_ht(a, 0), _as_ht(b, 0)
    monkeypatch.setenv("HEAT_TPU_HBM_BUDGET_BYTES", str(1 << 30))  # 1 GiB
    with pytest.raises(MemoryError, match="dense SpGEMM fallback"):
        sa._spgemm_dense(A, B)
    C = A @ B  # the auto route: est density ~0 -> ring, budget still armed
    ref = (a @ b).tocsr()
    ref.sum_duplicates()
    np.testing.assert_array_equal(np.asarray(C.indptr), ref.indptr)
    np.testing.assert_array_equal(np.asarray(C.indices), ref.indices)
    np.testing.assert_allclose(np.asarray(C.data), ref.data, rtol=1e-10)


# ----------------------------------------------------------------------
# resumability under the existing fault sites
# ----------------------------------------------------------------------
def test_spgemm_resumes_after_transient_collective_fault():
    a, b = _pair(200, 160, 180, 0.02, 0.02, seed=17)
    A, B = _as_ht(a, 0), _as_ht(b, 0)
    ref = (a @ b).toarray()
    with rz.fault_plan(
        {"comm.collective": [{"at": 1, "kind": "transient"}]}
    ) as inj:
        with pytest.raises(rz.TransientFault) as e:
            A @ B
        assert e.value.site == "comm.collective"
        # the ring loop holds no mutable operand state: the same call
        # retried inside the same plan reproduces the reference exactly
        c = A @ B
        np.testing.assert_allclose(c.toarray(), ref, rtol=1e-10, atol=1e-12)
    assert inj.injected["comm.collective"] == [(1, "transient")]
    # operands survived the abort untouched
    np.testing.assert_allclose(A.toarray(), a.toarray(), rtol=1e-12)
    np.testing.assert_allclose((A @ B).toarray(), ref, rtol=1e-10, atol=1e-12)
