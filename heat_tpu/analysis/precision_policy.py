"""Per-estimator precision policies: the contract mixed precision serves under.

ROADMAP item 2 asks for bf16/int8 inference paths "gated by a
bitwise-vs-tolerance policy per estimator".  This module is that gate's
source of truth: every served estimator kind declares ONCE, in the
:data:`POLICIES` table below, whether its predictions are

* ``bitwise`` — byte-identical to the reference fit/predict path; the
  compute dtype set is exactly the native one and any low-precision
  compute is a policy violation (**J204**); or
* ``tolerance`` — allowed to run lower-precision compute (the listed
  ``compute_dtypes``) as long as predictions stay within ``rtol`` of the
  native path — the contract the bf16 KMeans/cdist predict core serves
  under, and what tests/benches assert.

Like ``KNOBS`` / ``KNOWN_SITES`` / ``LOCK_REGISTRY``, the table is a
**pure literal** (``ast.literal_eval``-parseable, no imports needed to
read it).  It is enforced at three choke points:

1. **the dispatch analyze hook** — predict paths enter
   :func:`scope`, and the jaxpr dtype-flow walker
   (:mod:`~heat_tpu.analysis.dtype_flow`) checks every compiled
   program's float compute dtypes against the active scope's policy
   (J204), and sanctions narrowing casts into a tolerance policy's
   allowed dtypes (J201);
2. **the model store** — :func:`~heat_tpu.serving.model_io.save_model`
   records the declared policy and the export's effective compute dtype
   in the version metadata, and
   :meth:`~heat_tpu.serving.registry.ModelRegistry.load` REFUSES to
   activate a version whose recorded compute dtype (or the serving
   process's current one) violates the recorded policy
   (:class:`PrecisionPolicyError`);
3. **the batch CLI** — ``python -m heat_tpu.analysis --rules J2,J3``
   traces every served estimator's predict program and runs the full
   J2xx/J301 check set over it.

``HEAT_TPU_PREDICT_DTYPE`` selects the low-precision compute dtype for
*tolerance*-policy estimators (empty = native float32 everywhere); a
dtype a kind's policy does not allow is ignored for that kind with a
J204 diagnostic, never silently served.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
from typing import Any, Dict, Iterable, List, Optional

from ..core import _env
from .diagnostics import Diagnostic, ProgramLintError, emit

__all__ = [
    "POLICIES",
    "PrecisionPolicyError",
    "active_compute_dtype",
    "active_policy",
    "check_load",
    "compute_dtype",
    "policy_for",
    "refresh_env",
    "scope",
    "set_predict_dtype",
    "validate_policy",
]

#: Every served estimator kind's precision contract: kind -> {mode,
#: compute_dtypes[, rtol]}.  ``mode`` is "bitwise" (predictions must be
#: byte-identical to the native path; compute_dtypes is exactly the
#: native dtype) or "tolerance" (low-precision compute from
#: ``compute_dtypes`` is allowed; predictions must stay within ``rtol``
#: of the native path).  ``compute_dtypes`` lists the allowed float
#: compute dtypes, native first.  PURE LITERAL — readable with
#: ast.literal_eval, like KNOBS / KNOWN_SITES / LOCK_REGISTRY.
POLICIES = {
    # KMeans predict is an argmin over euclidean distances: tolerant to
    # bf16 rounding of the cross term (norms and accumulation stay f32 —
    # see spatial/distance.py), so it serves under a tolerance contract
    "KMeans": {"mode": "tolerance", "rtol": 0.02, "compute_dtypes": ("float32", "bfloat16")},
    # median/medoid geometry ties break on exact comparisons; low
    # precision can flip a tie permanently -> bitwise only
    "KMedians": {"mode": "bitwise", "compute_dtypes": ("float32",)},
    "KMedoids": {"mode": "bitwise", "compute_dtypes": ("float32",)},
    # PCA transform is one projection matmul: bf16 operands with f32
    # accumulation keep the coordinates within rtol of the native path
    # (tests/test_precision.py measures the bound on fitted components)
    "PCA": {"mode": "tolerance", "rtol": 0.02, "compute_dtypes": ("float32", "bfloat16")},
    "Lasso": {"mode": "bitwise", "compute_dtypes": ("float32",)},
    # KNN serves under a tolerance contract on the DISTANCE stage only
    # (same bf16 cross-term core as KMeans); the predicted labels stay
    # bitwise — votes are argmax over discrete counts, and the tests
    # assert exact label agreement on margin-separated data, so a bf16
    # rounding that flips the k-th neighbor set is a test failure, not
    # an accepted tolerance
    "KNeighborsClassifier": {"mode": "tolerance", "rtol": 0.02, "compute_dtypes": ("float32", "bfloat16")},
}

_MODES = ("bitwise", "tolerance")

#: dtype names a policy may list / the predict knob may select
_KNOWN_DTYPES = ("float32", "bfloat16", "float16", "float64")


class PrecisionPolicyError(ProgramLintError):
    """A precision-policy violation surfaced at an enforcement point
    (registry load refusal, a J204 verdict in raise mode).  Carries the
    J204 :class:`~.diagnostics.Diagnostic` like every program-lint
    error."""


def policy_for(kind: str) -> Optional[Dict[str, Any]]:
    """The declared policy of estimator ``kind`` (None if undeclared)."""
    return POLICIES.get(kind)


def validate_policy(policy: Dict[str, Any]) -> Dict[str, Any]:
    """Shape-check a policy document (the ``save_model(policy=...)``
    override); returns it normalized (compute_dtypes as a tuple)."""
    if not isinstance(policy, dict):
        raise TypeError(f"policy must be a dict, got {type(policy).__name__}")
    mode = policy.get("mode")
    if mode not in _MODES:
        raise ValueError(f"policy mode must be one of {_MODES}, got {mode!r}")
    dtypes = tuple(policy.get("compute_dtypes") or ())
    if not dtypes:
        raise ValueError("policy must list at least one compute dtype")
    unknown = [d for d in dtypes if d not in _KNOWN_DTYPES]
    if unknown:
        raise ValueError(
            f"unknown compute dtype(s) {unknown}; expected from {_KNOWN_DTYPES}"
        )
    out = dict(policy)
    out["compute_dtypes"] = dtypes
    if mode == "tolerance":
        rtol = float(policy.get("rtol", 0.0))
        if rtol <= 0.0:
            raise ValueError("a tolerance policy needs rtol > 0")
        out["rtol"] = rtol
    return out


# ----------------------------------------------------------------------
# the predict compute dtype (HEAT_TPU_PREDICT_DTYPE)
# ----------------------------------------------------------------------
def _parse_predict_dtype(raw: Optional[str]) -> str:
    if raw is None:
        raw = _env.knob_default("HEAT_TPU_PREDICT_DTYPE")
    raw = str(raw).strip().lower()
    if raw in ("", "0", "off", "float32", "f32", "native"):
        return ""
    aliases = {"bf16": "bfloat16", "f16": "float16"}
    raw = aliases.get(raw, raw)
    if raw not in _KNOWN_DTYPES:
        raise ValueError(
            f"HEAT_TPU_PREDICT_DTYPE={raw!r}: expected one of "
            f"{('',) + _KNOWN_DTYPES}"
        )
    return raw


_PREDICT_DTYPE = _parse_predict_dtype(os.environ.get("HEAT_TPU_PREDICT_DTYPE"))

#: kinds whose disallowed knob override already emitted a J204 (warn once)
_WARNED_KINDS: set = set()


def set_predict_dtype(name: str) -> str:
    """Set the low-precision predict compute dtype at runtime (overrides
    the env knob; ``""`` restores native f32); returns the previous
    setting.  Bench/test hook."""
    global _PREDICT_DTYPE
    prev = _PREDICT_DTYPE
    _PREDICT_DTYPE = _parse_predict_dtype(name)
    _WARNED_KINDS.clear()
    return prev


def refresh_env() -> str:
    """Re-read ``HEAT_TPU_PREDICT_DTYPE`` (tests that flip the env var
    mid-process); returns the new setting."""
    global _PREDICT_DTYPE
    _PREDICT_DTYPE = _parse_predict_dtype(os.environ.get("HEAT_TPU_PREDICT_DTYPE"))
    _WARNED_KINDS.clear()
    return _PREDICT_DTYPE


def compute_dtype(kind: str) -> str:
    """The effective predict compute dtype name for estimator ``kind``.

    The requested low-precision dtype (``HEAT_TPU_PREDICT_DTYPE`` /
    :func:`set_predict_dtype`) applies only when ``kind``'s declared
    policy is ``tolerance`` AND lists it; any other combination serves
    native (``compute_dtypes[0]``, f32 for undeclared kinds) — a
    disallowed request additionally emits one J204 diagnostic per kind,
    so a mis-set knob is visible, never silently obeyed."""
    pol = POLICIES.get(kind)
    native = pol["compute_dtypes"][0] if pol else "float32"
    req = _PREDICT_DTYPE
    if not req or req == native:
        return native
    if pol is not None and pol["mode"] == "tolerance" and req in pol["compute_dtypes"]:
        return req
    if kind not in _WARNED_KINDS:
        _WARNED_KINDS.add(kind)
        emit(Diagnostic(
            rule="J204",
            message=(
                f"HEAT_TPU_PREDICT_DTYPE={req} is not allowed by the "
                f"{kind} precision policy "
                f"({'undeclared' if pol is None else pol['mode']}) — "
                f"serving native {native} instead; widen the POLICIES "
                "entry (with a tolerance bench) to opt this kind in"
            ),
            location=kind,
            details={"requested": req, "policy": dict(pol) if pol else None},
        ))
    return native


# ----------------------------------------------------------------------
# the active predict scope (the dispatch-hook enforcement point)
# ----------------------------------------------------------------------
#: (kind, policy dict, effective compute dtype name) of the innermost
#: active predict scope; contextvars survive the same-thread dispatch
#: compile the scope's ops trigger
_SCOPE: contextvars.ContextVar = contextvars.ContextVar(
    "heat_tpu_precision_scope", default=None
)


@contextlib.contextmanager
def scope(kind: str):
    """Declare that ops issued inside the block implement ``kind``'s
    predict path: the dispatch analyze hook checks every program
    compiled in here against ``kind``'s policy (J204), sanctions
    tolerance-mode narrowing (J201), and the cdist low-precision path
    reads the effective compute dtype from here."""
    pol = POLICIES.get(kind)
    token = _SCOPE.set((kind, pol, compute_dtype(kind)))
    try:
        yield
    finally:
        _SCOPE.reset(token)


def active_policy() -> Optional[Dict[str, Any]]:
    """The innermost active scope's policy document (None outside any
    predict scope or for an undeclared kind)."""
    s = _SCOPE.get()
    return s[1] if s is not None else None


def active_compute_dtype() -> Optional[str]:
    """The active scope's effective LOW-PRECISION compute dtype name, or
    None when unscoped / serving native — the one cheap query the cdist
    hot path makes per call."""
    s = _SCOPE.get()
    if s is None:
        return None
    dt = s[2]
    return dt if dt not in ("", "float32", "float64") else None


# ----------------------------------------------------------------------
# the registry enforcement point
# ----------------------------------------------------------------------
def _allowed(policy: Dict[str, Any], dtype_name: str) -> bool:
    dtypes = tuple(policy.get("compute_dtypes") or ())
    if policy.get("mode") == "bitwise":
        # bitwise = exactly the native dtype; a second listed dtype
        # would make "bitwise" unfalsifiable
        return bool(dtypes) and dtype_name == dtypes[0]
    return dtype_name in dtypes


def check_load(
    kind: str,
    policy: Optional[Dict[str, Any]],
    recorded_dtype: Optional[str],
    label: str = "registry.load",
) -> None:
    """Registry-load choke point: raise :class:`PrecisionPolicyError`
    when the version's recorded compute dtype, or the serving process's
    current effective one, violates the version's recorded policy.

    ``policy``/``recorded_dtype`` come from the version metadata
    ``save_model`` wrote; versions saved before the policy layer (both
    None) load unchecked.  The refusal is unconditional — unlike the
    analyzers it does NOT honor ``HEAT_TPU_ANALYZE=off``: activating a
    version that cannot meet its own declared contract is never a
    warning."""
    if policy is None:
        return
    violations: List[str] = []
    if recorded_dtype and not _allowed(policy, str(recorded_dtype)):
        violations.append(
            f"exported with compute dtype {recorded_dtype} but declares "
            f"{policy.get('mode')} over {tuple(policy.get('compute_dtypes') or ())}"
        )
    # the dtype the predict path will ACTUALLY use in this process
    # (knob gated by the global POLICIES table), checked against the
    # VERSION'S recorded policy: a version declaring bitwise must not
    # activate into a process whose knob serves it low-precision
    serving_dtype = compute_dtype(kind)
    if not _allowed(policy, serving_dtype):
        violations.append(
            f"serving process computes {kind} predictions in "
            f"{serving_dtype} (HEAT_TPU_PREDICT_DTYPE) but the version "
            f"declares {policy.get('mode')} over "
            f"{tuple(policy.get('compute_dtypes') or ())}"
        )
    if not violations:
        return
    diag = Diagnostic(
        rule="J204",
        message=(
            f"refusing to activate {kind} model version: "
            + "; ".join(violations)
        ),
        location=label,
        source="dispatch",
        details={"kind": kind, "policy": dict(policy),
                 "recorded_dtype": recorded_dtype},
    )
    emit(diag, mode="off")  # count + ring; the refusal below is the verdict
    raise PrecisionPolicyError(diag)


def policies_for_kinds(kinds: Iterable[str]) -> Dict[str, Dict[str, Any]]:
    """Declared policies for the given kinds (the CLI batch report)."""
    return {k: dict(POLICIES[k]) for k in kinds if k in POLICIES}
