"""DNDarray object-surface battery at reference width (heat/core/tests/
test_dndarray.py idiom): properties, conversions, scalar protocols,
in-place semantics, and local views — every claim against numpy ground
truth on the 8-device mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.fixture(scope="module")
def a_np():
    return np.arange(24, dtype=np.float32).reshape(4, 6)


@pytest.mark.parametrize("split", SPLITS)
def test_size_byte_properties(a_np, split):
    x = ht.array(a_np, split=split)
    assert x.size == a_np.size == x.gnumel
    assert x.ndim == 2
    assert len(x) == 4
    assert x.nbytes == a_np.nbytes == x.gnbytes
    assert x.lnumel <= x.size and x.lnbytes == x.lnumel * 4
    assert x.stride == (6, 1)
    assert x.strides == (24, 4)  # bytes, numpy convention


@pytest.mark.parametrize("split", SPLITS)
def test_shape_after_moves(a_np, split):
    x = ht.array(a_np, split=split)
    assert x.T.shape == (6, 4)
    np.testing.assert_array_equal(x.T.numpy(), a_np.T)
    assert x.flatten().shape == (24,)
    assert x.ravel().shape == (24,)


def test_scalar_protocols():
    one = ht.array(np.array([3.5], np.float32), split=0)
    zero_d = ht.array(np.float32(2.25))
    assert float(zero_d) == 2.25
    assert int(ht.array(np.int32(7))) == 7
    assert bool(ht.array(True))
    assert one.item() == pytest.approx(3.5)
    with pytest.raises((ValueError, TypeError)):
        bool(ht.arange(4, split=0))  # ambiguous like numpy


@pytest.mark.parametrize("split", [None, 0])
def test_tolist_roundtrip(a_np, split):
    x = ht.array(a_np, split=split)
    assert x.tolist() == a_np.tolist()
    v = ht.arange(5, split=split)
    assert v.tolist() == list(range(5))


@pytest.mark.parametrize("split", SPLITS)
def test_astype_copy_semantics(a_np, split):
    x = ht.array(a_np, split=split)
    y = x.astype(ht.int32)
    assert y.dtype == ht.int32 and x.dtype == ht.float32  # copy by default
    np.testing.assert_array_equal(y.numpy(), a_np.astype(np.int32))
    z = x.astype(ht.float64, copy=False)
    assert z is x and x.dtype == ht.float64


@pytest.mark.parametrize("split", [None, 0])
def test_fill_diagonal(split):
    a = np.zeros((5, 5), np.float32)
    x = ht.array(a, split=split)
    x.fill_diagonal(2.5)
    want = a.copy()
    np.fill_diagonal(want, 2.5)
    np.testing.assert_array_equal(x.numpy(), want)


@pytest.mark.parametrize("split", SPLITS)
def test_rich_comparisons_return_dndarrays(a_np, split):
    x = ht.array(a_np, split=split)
    mask = x > 10.0
    assert isinstance(mask, ht.DNDarray)
    np.testing.assert_array_equal(mask.numpy(), a_np > 10.0)
    np.testing.assert_array_equal((x == x).numpy(), np.ones_like(a_np, bool))


@pytest.mark.parametrize("split", [None, 0])
def test_reduction_methods_match_functions(a_np, split):
    x = ht.array(a_np, split=split)
    assert float(x.sum()) == a_np.sum()
    assert float(x.prod()) == pytest.approx(np.prod(a_np, dtype=np.float64), rel=1e-5)
    assert float(x.mean()) == pytest.approx(a_np.mean())
    assert float(x.max()) == a_np.max() and float(x.min()) == a_np.min()
    assert bool((x >= 0).all()) and bool((x > 22).any())
    np.testing.assert_array_equal(x.argmax(axis=1).numpy(), a_np.argmax(axis=1))
    np.testing.assert_allclose(
        x.clip(3.0, 17.0).numpy(), a_np.clip(3.0, 17.0), rtol=1e-6
    )
    np.testing.assert_allclose(x.round().numpy(), a_np.round())
    np.testing.assert_allclose(x.abs().numpy(), np.abs(a_np))


@pytest.mark.parametrize("split", [None, 0])
def test_lloc_read_write(split):
    a = np.arange(16, dtype=np.float32)
    x = ht.array(a, split=split)
    # single controller: local == global
    assert float(x.lloc[3]) == 3.0
    x.lloc[0] = 99.0
    assert float(x[0]) == 99.0


@pytest.mark.parametrize("split", SPLITS)
def test_real_imag_on_real_input(a_np, split):
    x = ht.array(a_np, split=split)
    np.testing.assert_array_equal(x.real.numpy(), a_np)
    np.testing.assert_array_equal(x.imag.numpy(), np.zeros_like(a_np))


def test_len_and_iteration_semantics():
    x = ht.array(np.arange(6, dtype=np.float32).reshape(3, 2), split=0)
    rows = [r.numpy() for r in x]
    assert len(rows) == 3
    np.testing.assert_array_equal(np.stack(rows), np.arange(6).reshape(3, 2))


@pytest.mark.parametrize("split", [None, 0])
def test_partition_interface_shape_consistency(a_np, split):
    x = ht.array(a_np, split=split)
    parts = x.__partitioned__
    assert tuple(parts["shape"]) == x.shape
    total = 0
    for key, p in parts["partitions"].items():
        data = parts["get"](p["data"])
        assert tuple(p["shape"]) == data.shape
        total += data.shape[0] if split == 0 else 0
    if split == 0:
        assert total == x.shape[0]


def test_collect_and_resplit_roundtrip(a_np):
    x = ht.array(a_np, split=0)
    x.collect_()
    assert x.split is None
    np.testing.assert_array_equal(x.numpy(), a_np)
    x.resplit_(1)
    assert x.split == 1
    np.testing.assert_array_equal(x.numpy(), a_np)


def test_flat_property(a_np):
    x = ht.array(a_np, split=0)
    np.testing.assert_array_equal(np.asarray(list(x.flat)), a_np.ravel())


def test_contains_and_divmod_numpy_parity():
    """numpy membership and divmod semantics (r5 surface additions)."""
    a = ht.arange(12, split=0).reshape((3, 4))
    an = np.arange(12).reshape(3, 4)
    assert (5 in a) is True and (99 in a) is False
    q, r = divmod(a, 3)
    qn, rn = divmod(an, 3)
    np.testing.assert_array_equal(q.numpy(), qn)
    np.testing.assert_array_equal(r.numpy(), rn)
    q2, r2 = divmod(20, ht.array([3, 6]))
    np.testing.assert_array_equal(q2.numpy(), [6, 3])
    np.testing.assert_array_equal(r2.numpy(), [2, 2])
    assert ("foo" in a) is False  # non-comparable items: False like numpy
