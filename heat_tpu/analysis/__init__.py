"""Static analysis: SPMD program lint + framework-invariant AST lint.

Two cooperating analyzers (docs/static_analysis.md):

* :mod:`~heat_tpu.analysis.program_lint` — walks the jaxpr and compiled
  (post-GSPMD) HLO of a program for SPMD hazards the type system cannot
  see: implicit unaccounted collectives (J101), accidental full gathers
  of the split axis (J102), weak-type / python-scalar recompile hazards
  (J103), donation misses (J104) and silent dtype promotion (J105).
  Hooked into the ``core/dispatch.py`` compile path
  (``HEAT_TPU_ANALYZE=0/1/raise`` — off/warn/error) and callable
  standalone via :func:`analyze`.  Diagnostics flow into the telemetry
  registry (``analysis.diags.{rule}`` counters) and a bounded ring
  (:func:`recent_diagnostics`).
* :mod:`~heat_tpu.analysis.ast_lint` — custom AST visitors enforcing
  the repo's own invariants with stable rule IDs (H101 raw writes, H201
  unregistered env knobs, H301 unaccounted collectives, H302
  unregistered fault sites, H401 host syncs in chunk bodies, H501
  fault-swallowing broad excepts, H601 host-entropy seeding).  Run as
  ``python -m heat_tpu.analysis <paths>``; ``scripts/lint_gate.py``
  gates CI against ``scripts/lint_baseline.json``.
"""

from __future__ import annotations

from .ast_lint import RULES, Violation, lint_file, lint_paths
from .diagnostics import (
    AnalysisWarning,
    Diagnostic,
    ProgramLintError,
    analysis_mode,
    clear_diagnostics,
    recent_diagnostics,
    set_analysis_mode,
)
from .program_lint import analyze, analyze_compiled_text, analyze_jaxpr

__all__ = [
    "AnalysisWarning",
    "Diagnostic",
    "ProgramLintError",
    "RULES",
    "Violation",
    "analysis_mode",
    "analyze",
    "analyze_compiled_text",
    "analyze_jaxpr",
    "clear_diagnostics",
    "lint_file",
    "lint_paths",
    "recent_diagnostics",
    "set_analysis_mode",
]
