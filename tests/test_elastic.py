"""Elastic multi-host execution: mesh reshape, cross-world checkpoint
restore, and the detect -> reshape -> resume supervision loop.

The acceptance property (ISSUE 8): a fit killed at world size P resumes
and converges at world size Q < P with the result matching the
uninterrupted fit within floating-point tolerance, and a same-size
resume (Q = P) stays bitwise identical.  Worker loss is simulated two
ways — an in-process typed exception (ElasticSupervisor) and a real
``os._exit``-killed subprocess (ProcessSupervisor), mirroring the PR 2
kill-and-resume harness.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.elastic import (
    ElasticSupervisor,
    HeartbeatMonitor,
    ProcessSupervisor,
    ReshapeError,
    WorkerLostError,
    elastic_state,
    kmeans_worker_source,
)
from heat_tpu.parallel.comm import Communication, HierarchicalCommunication
from heat_tpu.telemetry import metrics as tm
from heat_tpu.utils.checkpoint import Checkpointer


def _world():
    return ht.get_comm()


def _data(n=240, f=6, seed=13):
    ht.random.seed(seed)
    return np.asarray(ht.random.randn(n, f, split=0).astype(ht.float32).numpy())


KW = dict(n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3)


# ----------------------------------------------------------------------
# comm.reshape
# ----------------------------------------------------------------------
class TestReshape:
    def test_shrink_rebuilds_canonical_metadata(self):
        w = _world()
        c5 = w.reshape(5)
        assert c5.size == 5 and isinstance(c5, Communication)
        assert w.retired and not c5.retired
        # lshape_map/chunk/sharding recompute for the new world
        lm = c5.lshape_map((13,), 0)[:, 0]
        assert lm.sum() == 13 and lm.max() == 3  # ceil(13/5)=3 with padding
        offs = [c5.chunk((13,), 0, rank=r)[0] for r in range(5)]
        assert offs == sorted(offs)
        counts, displs, _ = c5.counts_displs_shape((13,), 0)
        assert sum(counts) == 13
        assert list(displs) == list(np.cumsum((0,) + counts[:-1]))
        sh = c5.sharding(0)
        assert sh.mesh.devices.size == 5

    def test_same_size_and_grow_within_inventory(self):
        w = _world()
        n = w.size
        same = w.reshape(n)
        assert same.size == n
        small = same.reshape(3)
        regrown = small.reshape(n)  # capacity came back
        assert regrown.size == n

    def test_invalid_targets_raise_typed(self):
        w = _world()
        with pytest.raises(ReshapeError):
            w.reshape(0)
        with pytest.raises(ReshapeError):
            w.reshape(w.size + 1000)
        with pytest.raises(ReshapeError):
            w.reshape()  # neither n_devices nor devices
        with pytest.raises(ReshapeError):
            w.reshape(devices=[])

    def test_explicit_device_list(self):
        import jax

        w = _world()
        devs = jax.devices()[:3]
        c = w.reshape(devices=devs)
        assert c.size == 3 and c.devices == list(devs)

    def test_hierarchical_reshape_reinfers_grid(self):
        hc = HierarchicalCommunication(grid=(2, 4))
        assert (hc.num_nodes, hc.node_size) == (2, 4)
        h6 = hc.reshape(6)
        assert isinstance(h6, HierarchicalCommunication)
        assert h6.size == 6
        # single host: survivors re-infer to one node
        assert (h6.num_nodes, h6.node_size) == (1, 6)
        assert hc.retired

    def test_reshape_error_is_never_retried(self):
        pol = rz.RetryPolicy(max_attempts=5, no_sleep=True, retryable=(Exception,))
        calls = {"n": 0}

        def bad():
            calls["n"] += 1
            raise ReshapeError("no")

        with pytest.raises(ReshapeError):
            pol.call(bad)
        assert calls["n"] == 1


# ----------------------------------------------------------------------
# finalize() + re-init() cycles (the elastic restart path)
# ----------------------------------------------------------------------
class TestFinalizeInitCycles:
    def test_repeated_cycles_keep_world_usable(self):
        from heat_tpu.parallel import comm as C

        e0 = C.comm_epoch()
        for _ in range(2):
            ht.parallel.finalize()
            ht.parallel.init()
        assert C.comm_epoch() > e0
        w = ht.get_comm()
        assert w.size >= 1
        a = ht.arange(13, split=0)
        assert float(a.sum()) == 78.0

    def test_finalize_drops_mesh_keyed_dispatch_cache(self):
        from heat_tpu.core import dispatch

        a = ht.arange(16, split=0).astype(ht.float32)
        _ = float((a * 2.0 + 1.0).sum())
        ht.parallel.finalize()
        assert dispatch.cache_stats()["cache_size"] == 0
        ht.parallel.init()
        b = ht.arange(16, split=0).astype(ht.float32)
        assert float((b * 2.0 + 1.0).sum()) == float((np.arange(16) * 2.0 + 1.0).sum())


# ----------------------------------------------------------------------
# DNDarray.reshard_
# ----------------------------------------------------------------------
class TestReshard:
    @pytest.mark.parametrize("split", [0, 1, None])
    @pytest.mark.parametrize("shape", [(13, 4), (16, 3), (7, 5)])
    def test_values_preserved_across_worlds(self, split, shape):
        w = _world()
        vals = np.arange(np.prod(shape), dtype=np.float32).reshape(shape)
        x = ht.array(vals, split=split)
        for target in (5, 3, w.size):
            c = w.reshape(target)
            x.reshard_(c)
            assert x.comm.size == target
            assert x.split == split
            assert np.array_equal(x.numpy(), vals)
            if split is not None:
                pad = c.pad_amount(shape[split])
                assert x.larray_padded.shape[split] == shape[split] + pad

    def test_reshard_noop_on_same_comm(self):
        x = ht.arange(8, split=0)
        buf = x.larray_padded
        x.reshard_(x.comm)
        assert x.larray_padded is buf

    def test_reshard_then_ops_match_numpy(self):
        w = _world()
        vals = np.arange(26, dtype=np.float64).reshape(13, 2)
        x = ht.array(vals, split=0)
        x.reshard_(w.reshape(3))
        assert float(x.sum()) == vals.sum()
        assert float(x.max()) == vals.max()
        y = (x * 2.0 + 1.0).numpy()
        assert np.allclose(y, vals * 2.0 + 1.0)


# ----------------------------------------------------------------------
# cross-world checkpoint restore
# ----------------------------------------------------------------------
class TestCrossWorldRestore:
    def test_world_size_recorded_and_crossworld_counted(self, tmp_path):
        w = _world()
        x = ht.array(np.arange(26, dtype=np.float32).reshape(13, 2), split=0)
        ck = Checkpointer(str(tmp_path))
        ck.save(1, {"x": x, "n_iter": 4})
        assert ck.world_size(1) == w.size
        before = tm.counter("checkpoint.crossworld_restores").value
        st = ck.restore(1, comm=w.reshape(5))
        assert tm.counter("checkpoint.crossworld_restores").value == before + 1
        assert st["x"].comm.size == 5 and st["x"].split == 0
        assert np.array_equal(st["x"].numpy(), np.arange(26, dtype=np.float32).reshape(13, 2))

    def test_restore_without_comm_keeps_host_arrays(self, tmp_path):
        x = ht.array(np.arange(10, dtype=np.float32), split=0)
        ck = Checkpointer(str(tmp_path))
        ck.save(0, {"x": x})
        st = ck.restore(0)
        assert isinstance(st["x"], np.ndarray)
        assert np.array_equal(st["x"], np.arange(10, dtype=np.float32))

    def test_split_none_leaf_restores_replicated(self, tmp_path):
        x = ht.array(np.ones((4, 4), np.float32))  # split=None
        ck = Checkpointer(str(tmp_path))
        ck.save(0, {"x": x})
        st = ck.restore(0, comm=_world().reshape(3))
        assert st["x"].split is None and st["x"].comm.size == 3

    def test_template_validation_raises_typed(self, tmp_path):
        ck = Checkpointer(str(tmp_path))
        ck.save(0, {"c": np.ones((4, 2), np.float32), "n": 1})
        ck.restore(0, template={"c": np.zeros((4, 2), np.float32), "n": 0})
        with pytest.raises(ReshapeError):  # shape drift
            ck.restore(0, template={"c": np.zeros((5, 2), np.float32), "n": 0})
        with pytest.raises(ReshapeError):  # dtype drift
            ck.restore(0, template={"c": np.zeros((4, 2), np.float64), "n": 0})
        with pytest.raises(ReshapeError):  # structure drift
            ck.restore(0, template={"other": np.zeros((4, 2), np.float32)})

    def test_async_checkpointer_crossworld_passthrough(self, tmp_path):
        w = _world()
        x = ht.array(np.arange(12, dtype=np.float32), split=0)
        ack = Checkpointer(str(tmp_path)).as_async()
        ack.save(2, {"x": x})
        st = ack.restore(comm=w.reshape(3))
        assert st["x"].comm.size == 3
        assert ack.world_size(2) == w.size
        ack.close()

    def test_orbax_comm_rejected_without_orbax_import(self, tmp_path):
        ck = Checkpointer(str(tmp_path))
        ck.save(0, {"a": np.ones(3)})
        ck.backend = "orbax"  # simulate: the check precedes any orbax use
        with pytest.raises(ValueError):
            ck.restore(0, comm=_world())
        ck.backend = "native"


# ----------------------------------------------------------------------
# heartbeat monitor
# ----------------------------------------------------------------------
class TestHeartbeatMonitor:
    def test_gauge_staleness(self):
        clock = {"t": 1000.0}
        mon = HeartbeatMonitor(timeout_s=5.0, clock=lambda: clock["t"])
        prev = tm.gauge("fit.heartbeat_ts").value
        try:
            tm.gauge("fit.heartbeat_ts").set(1000.0)
            clock["t"] = 1003.0
            mon.check()  # fresh
            clock["t"] = 1006.5
            with pytest.raises(WorkerLostError) as ei:
                mon.check()
            assert ei.value.heartbeat_age == pytest.approx(6.5)
        finally:
            tm.gauge("fit.heartbeat_ts").set(prev)

    def test_never_beaten_counts_from_arming(self):
        clock = {"t": 50.0}
        mon = HeartbeatMonitor(
            timeout_s=2.0, heartbeat_file="/nonexistent/hb", clock=lambda: clock["t"]
        )
        mon.check()
        clock["t"] = 53.0
        with pytest.raises(WorkerLostError):
            mon.check()

    def test_file_mtime_source(self, tmp_path):
        hb = tmp_path / "hb"
        hb.touch()
        mon = HeartbeatMonitor(timeout_s=3600.0, heartbeat_file=str(hb))
        mon.check()
        assert mon.age() < 60.0

    def test_detect_site_scriptable(self):
        mon = HeartbeatMonitor(timeout_s=0.0)
        with rz.fault_plan({"elastic.detect": [{"at": 0, "kind": "transient"}]}) as inj:
            with pytest.raises(rz.TransientFault):
                mon.check()
        assert inj.hits["elastic.detect"] == 1


# ----------------------------------------------------------------------
# in-process elastic supervisor
# ----------------------------------------------------------------------
class TestElasticSupervisor:
    def _fit_fn(self, x_np, d):
        def fit_fn(comm, resume_from):
            x = ht.array(x_np, split=0, comm=comm)
            km = ht.cluster.KMeans(
                **KW, checkpoint_every=2, checkpoint_dir=d, resume_from=resume_from
            )
            km.fit(x)
            return km

        return fit_fn

    def test_lose_one_worker_resume_smaller_matches(self, tmp_path):
        x_np = _data()
        plain = ht.cluster.KMeans(**KW).fit(ht.array(x_np, split=0))
        d = str(tmp_path / "ck")
        sup = ElasticSupervisor(
            self._fit_fn(x_np, d), d,
            loss_types=(WorkerLostError, rz.TransientFault),
        )
        losses0 = tm.counter("elastic.worker_losses").value
        with rz.fault_plan({"kmeans.iter": [{"at": 1, "kind": "transient"}]}):
            km = sup.run()
        assert sup.recoveries == 1
        assert sup.world.size == _world().size - 1
        assert tm.counter("elastic.worker_losses").value == losses0 + 1
        assert elastic_state()["world_size"] == sup.world.size
        assert km.n_iter_ == plain.n_iter_
        assert np.allclose(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(km.cluster_centers_._dense()),
            atol=1e-4,
        )

    def test_same_size_resume_is_bitwise(self, tmp_path):
        x_np = _data()
        plain = ht.cluster.KMeans(**KW).fit(ht.array(x_np, split=0))
        d = str(tmp_path / "ck")
        sup = ElasticSupervisor(
            self._fit_fn(x_np, d), d, shrink_by=0,
            loss_types=(WorkerLostError, rz.TransientFault),
        )
        with rz.fault_plan({"kmeans.iter": [{"at": 1, "kind": "transient"}]}):
            km = sup.run()
        assert sup.recoveries == 1 and sup.world.size == _world().size
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(km.cluster_centers_._dense()),
        )
        assert km.n_iter_ == plain.n_iter_

    def test_recovery_budget_exhaustion_reraises(self, tmp_path):
        d = str(tmp_path / "ck")

        def always_lost(comm, resume_from):
            raise WorkerLostError("gone", lost=1)

        sup = ElasticSupervisor(always_lost, d, max_recoveries=2)
        with pytest.raises(WorkerLostError):
            sup.run()
        assert sup.recoveries == 3  # 2 recoveries + the budget-blowing 3rd

    def test_min_world_floor(self, tmp_path):
        d = str(tmp_path / "ck")

        def always_lost(comm, resume_from):
            raise WorkerLostError("gone", lost=comm.size - 1)

        sup = ElasticSupervisor(always_lost, d, min_world=4, max_recoveries=5)
        with pytest.raises(ReshapeError):
            sup.run()

    def test_on_world_change_reshards_live_arrays(self, tmp_path):
        x_np = _data(64, 3)
        x = ht.array(x_np, split=0)
        d = str(tmp_path / "ck")
        seen = []

        def fit_fn(comm, resume_from):
            if not seen:
                raise WorkerLostError("first pass dies", lost=2)
            assert x.comm.size == comm.size  # resharded before resume
            return float(x.sum())

        sup = ElasticSupervisor(
            fit_fn, d,
            on_world_change=lambda c: (seen.append(c), x.reshard_(c)),
        )
        total = sup.run()
        assert len(seen) == 1 and seen[0].size == _world().size - 2
        assert total == pytest.approx(float(x_np.sum()), rel=1e-6)

    def test_recovery_sites_scriptable(self, tmp_path):
        """A transient fault at elastic.reshape is absorbed by the retry
        policy; the recovery still completes."""
        x_np = _data(64, 3)
        d = str(tmp_path / "ck")
        calls = {"n": 0}

        def fit_fn(comm, resume_from):
            calls["n"] += 1
            if calls["n"] == 1:
                raise WorkerLostError("die once")
            return comm.size

        pol = rz.RetryPolicy(max_attempts=3, no_sleep=True)
        sup = ElasticSupervisor(fit_fn, d, retry_policy=pol)
        with rz.fault_plan(
            {"elastic.reshape": [{"at": 0, "kind": "transient"}]}
        ) as inj:
            size = sup.run()
        assert size == _world().size - 1
        assert inj.hits["elastic.reshape"] == 2  # failed once, retried


# ----------------------------------------------------------------------
# subprocess supervision: real os._exit preemption (the acceptance test)
# ----------------------------------------------------------------------
@pytest.mark.multiprocess
class TestProcessSupervisor:
    def _run(self, tmp_path, name, world, shrink_by, max_recoveries=2):
        d = str(tmp_path / name)
        kill_plan = json.dumps(
            {"plan": {"kmeans.iter": [{"at": 1, "kind": "kill", "exit_code": 137}]}}
        )

        def build(ws, resume, attempt):
            src = kmeans_worker_source(d, resume_from=resume, x64=True)
            extra = {"HEAT_TPU_FAULT_PLAN": kill_plan if attempt == 0 else ""}
            return [sys.executable, "-c", src], extra

        sup = ProcessSupervisor(
            build, d, world_size=world, shrink_by=shrink_by,
            max_recoveries=max_recoveries, poll_s=0.2, attempt_timeout_s=280,
        )
        return d, sup.run()

    def test_kill_at_p_resume_at_q_converges(self, tmp_path):
        """Worker killed at P=4 mid-fit; the supervisor reshapes to Q=3
        and the resumed fit converges to the uninterrupted result within
        float32 reduction-order tolerance."""
        x_np = _data()
        plain = ht.cluster.KMeans(**KW).fit(ht.array(x_np, split=0))
        d, out = self._run(tmp_path, "pq", world=4, shrink_by=1)
        assert out["recoveries"] == 1 and out["world_size"] == 3
        assert out["attempts"][0]["returncode"] == 137
        assert out["attempts"][1]["returncode"] == 0
        assert len(out["recovery_s"]) == 1 and out["recovery_s"][0] < 280
        st = Checkpointer(d).restore()
        assert st["converged"]
        assert st["n_iter"] == plain.n_iter_
        assert np.allclose(
            st["state"], np.asarray(plain.cluster_centers_._dense()), atol=1e-4
        )

    def test_same_size_resume_bitwise(self, tmp_path):
        """Q = P: the resumed fit must reproduce the uninterrupted fit
        at the same world size BITWISE (the PR 2/3 resume property,
        now through the elastic supervisor)."""
        d, out = self._run(tmp_path, "same", world=4, shrink_by=0)
        assert out["recoveries"] == 1 and out["world_size"] == 4
        # uninterrupted reference at the same world size
        ref_dir = str(tmp_path / "ref")

        def build_ref(ws, resume, attempt):
            return (
                [sys.executable, "-c", kmeans_worker_source(ref_dir, x64=True)],
                {"HEAT_TPU_FAULT_PLAN": ""},
            )

        ref = ProcessSupervisor(
            build_ref, ref_dir, world_size=4, poll_s=0.2, attempt_timeout_s=280
        ).run()
        assert ref["recoveries"] == 0
        a = Checkpointer(d).restore()
        b = Checkpointer(ref_dir).restore()
        assert a["n_iter"] == b["n_iter"]
        assert np.array_equal(a["state"], b["state"])

    def test_recovery_budget_exhaustion(self, tmp_path):
        d = str(tmp_path / "budget")
        always_kill = json.dumps(
            {"plan": {"kmeans.iter": [{"at": 0, "kind": "kill", "exit_code": 137}]}}
        )

        def build(ws, resume, attempt):
            src = kmeans_worker_source(d, resume_from=resume, x64=True)
            return [sys.executable, "-c", src], {"HEAT_TPU_FAULT_PLAN": always_kill}

        sup = ProcessSupervisor(
            build, d, world_size=3, shrink_by=0, max_recoveries=1,
            poll_s=0.2, attempt_timeout_s=280,
        )
        with pytest.raises(WorkerLostError):
            sup.run()
