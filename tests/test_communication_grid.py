"""Per-collective dtype x shape x world-size grid over parallel/comm.py.

The analog of the reference's ``test_communication.py`` (VERDICT item
7): every explicit collective wrapper checked against a numpy model,
swept over dtypes and world sizes — including worlds produced by
``comm.reshape`` (the post-reshape shard layouts of the elastic path)
— and the chunk/lshape/counts-displs metadata swept over uneven
extents that leave ragged true shards under the pad-and-mask canonical
distribution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core._compat import shard_map
from heat_tpu.parallel.comm import Communication

#: world sizes: the full test mesh plus two reshaped (surviving) worlds
SIZES = [8, 5, 3]


def _comm(size: int) -> Communication:
    w = ht.get_comm()
    if size == w.size:
        return w
    return w.reshape(size)


def _run_collective(comm, fn, *arrs):
    """Run ``fn`` (collective calls on ``comm``) under shard_map over
    the comm's mesh; each operand's leading axis is the split axis."""
    from jax.sharding import PartitionSpec as P

    spec = P(comm.axis_name)
    prog = jax.jit(
        shard_map(
            fn, mesh=comm.mesh,
            in_specs=(spec,) * len(arrs), out_specs=spec,
        )
    )
    return np.asarray(prog(*[jnp.asarray(a) for a in arrs]))


# ----------------------------------------------------------------------
# metadata: chunk / lshape_map / counts_displs over uneven extents
# ----------------------------------------------------------------------
class TestChunkMetadataGrid:
    @pytest.mark.parametrize("size", SIZES + [1])
    @pytest.mark.parametrize("shape,split", [
        ((13,), 0), ((16,), 0), ((5,), 0),        # uneven / even / fewer rows than devices
        ((13, 4), 0), ((7, 5), 1), ((8, 3), 0),
        ((4, 4), None),
    ])
    def test_partition_is_exact_and_ordered(self, size, shape, split):
        c = _comm(size)
        lm = c.lshape_map(shape, split)
        assert lm.shape == (size, len(shape))
        if split is None:
            assert all(tuple(r) == shape for r in lm)
            return
        # true local shapes tile the extent exactly, high ranks own the
        # (possibly empty) remainder
        assert lm[:, split].sum() == shape[split]
        per = c.padded_extent(shape[split]) // size
        offs, stops = [], []
        for r in range(size):
            off, lsh, slices = c.chunk(shape, split, rank=r)
            assert lsh == tuple(lm[r])
            assert slices[split] == slice(off, off + lsh[split])
            for d, s in enumerate(shape):
                if d != split:
                    assert slices[d] == slice(0, s)
            assert lsh[split] <= per
            offs.append(off)
            stops.append(off + lsh[split])
        assert offs == sorted(offs)
        assert stops[-1] == shape[split]
        counts, displs, local = c.counts_displs_shape(shape, split)
        assert sum(counts) == shape[split]
        assert list(displs) == [int(x) for x in np.cumsum((0,) + counts[:-1])]

    @pytest.mark.parametrize("size", SIZES)
    def test_padding_arithmetic(self, size):
        c = _comm(size)
        for extent in range(1, 3 * size + 2):
            assert c.padded_extent(extent) % size == 0
            assert 0 <= c.pad_amount(extent) < size
            assert c.padded_extent(extent) - c.pad_amount(extent) == extent


# ----------------------------------------------------------------------
# data ops on reshaped worlds with uneven shards
# ----------------------------------------------------------------------
class TestRaggedDataOnReshapedWorlds:
    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", [np.float32, np.float64, np.int32])
    @pytest.mark.parametrize("extent", [13, 16, 5])
    def test_reductions_match_numpy(self, size, dtype, extent):
        c = _comm(size)
        vals = (np.arange(extent * 3) % 17).astype(dtype).reshape(extent, 3)
        x = ht.array(vals, split=0, comm=c)
        assert float(x.sum()) == float(vals.sum())
        assert float(x.max()) == float(vals.max())
        assert float(x.min()) == float(vals.min())
        assert np.allclose(x.numpy(), vals)

    @pytest.mark.parametrize("size", SIZES)
    def test_matmul_across_split(self, size):
        c = _comm(size)
        a = np.arange(13 * 4, dtype=np.float64).reshape(13, 4)
        b = np.arange(4 * 2, dtype=np.float64).reshape(4, 2)
        out = ht.array(a, split=0, comm=c) @ ht.array(b, comm=c)
        assert np.allclose(out.numpy(), a @ b)


# ----------------------------------------------------------------------
# explicit collectives vs numpy models
# ----------------------------------------------------------------------
DTYPES = [np.float32, np.int32, np.float64]


class TestCollectiveGrid:
    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", DTYPES)
    @pytest.mark.parametrize("op", ["psum", "pmax", "pmin"])
    def test_reductions(self, size, dtype, op):
        c = _comm(size)
        vals = ((np.arange(size * 2) * 7) % 23 - 5).astype(dtype)
        out = _run_collective(c, getattr(c, op), vals)
        model = {
            "psum": lambda v: v.reshape(size, -1).sum(0),
            "pmax": lambda v: v.reshape(size, -1).max(0),
            "pmin": lambda v: v.reshape(size, -1).min(0),
        }[op](vals)
        # result is replicated per shard -> concatenated back: tile
        assert np.array_equal(out, np.tile(model, size))

    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", [np.float32, np.int32])
    def test_all_gather_tiled(self, size, dtype):
        c = _comm(size)
        vals = np.arange(size * 3, dtype=dtype)
        out = _run_collective(c, lambda v: c.all_gather(v), vals)
        # tiled gather of each 3-row shard -> every shard holds the full
        # vector; shard_map concatenates the replicas
        assert np.array_equal(out, np.tile(vals, size))

    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", [np.float32, np.float64])
    def test_psum_scatter(self, size, dtype):
        c = _comm(size)
        vals = np.arange(size * size, dtype=dtype)
        out = _run_collective(c, lambda v: c.psum_scatter(v), vals)
        assert np.allclose(out, vals.reshape(size, size).sum(0))

    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", [np.float32, np.int32])
    def test_all_to_all(self, size, dtype):
        c = _comm(size)
        # (size*size) rows: shard r holds rows [r*size, (r+1)*size);
        # all_to_all(split 0, concat 0) transposes the block matrix
        vals = np.arange(size * size, dtype=dtype)
        out = _run_collective(c, lambda v: c.all_to_all(v, 0, 0), vals)
        want = vals.reshape(size, size).T.reshape(-1)
        assert np.array_equal(out, want)

    @pytest.mark.parametrize("size", SIZES)
    @pytest.mark.parametrize("dtype", [np.int32, np.float32])
    def test_exscan_and_pscan(self, size, dtype):
        c = _comm(size)
        counts = (np.arange(size) + 1).astype(dtype)
        ex = _run_collective(c, lambda v: c.exscan(v), counts)
        assert np.array_equal(ex, np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(dtype))
        inc = _run_collective(c, lambda v: c.pscan(v), counts)
        assert np.array_equal(inc, np.cumsum(counts).astype(dtype))

    @pytest.mark.parametrize("size", SIZES)
    def test_ring_shift_and_ppermute(self, size):
        c = _comm(size)
        vals = np.arange(size, dtype=np.float32)
        out = _run_collective(c, lambda v: c.ring_shift(v, 1), vals)
        want = np.roll(vals, 1)
        assert np.array_equal(out, want)
        perm = [(i, (i + 2) % size) for i in range(size)]
        out2 = _run_collective(c, lambda v: c.ppermute(v, perm), vals)
        assert np.array_equal(out2, np.roll(vals, 2))

    @pytest.mark.parametrize("size", SIZES)
    def test_axis_index(self, size):
        c = _comm(size)
        vals = np.zeros(size, dtype=np.int32)
        out = _run_collective(
            c, lambda v: v + c.axis_index(c.axis_name).astype(jnp.int32), vals
        )
        assert np.array_equal(out, np.arange(size, dtype=np.int32))


# ----------------------------------------------------------------------
# comm-volume accounting stays live on reshaped comms
# ----------------------------------------------------------------------
class TestAccountingOnReshapedComms:
    def test_collective_counters_increment(self):
        from heat_tpu.telemetry import metrics as tm

        c = _comm(3)
        before = tm.counter("comm.calls.psum").value
        vals = np.ones(3, dtype=np.float32)
        _run_collective(c, c.psum, vals)
        assert tm.counter("comm.calls.psum").value >= before + 1
