"""Dedicated DNDarray behavior tests (reference: heat/core/tests/
test_dndarray.py, 1767 LoC) — properties, operator protocol, indexing
matrix, distribution management, conversions, halos."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture
def np2d():
    rng = np.random.default_rng(42)
    return rng.standard_normal((11, 7))  # non-divisible by 8 on purpose


# ---------------------------------------------------------------- properties


def test_basic_properties(ht, np2d):
    for split in (None, 0, 1):
        a = ht.array(np2d, split=split)
        assert a.shape == (11, 7)
        assert a.gshape == (11, 7)
        assert a.ndim == 2
        assert a.size == 77
        assert a.gnumel == 77
        assert a.dtype == ht.float64
        assert a.split == split
        assert a.balanced
        assert a.is_balanced()
        assert (a.comm.size > 1) == (a.is_distributed() if split is not None else False) or split is None
        np.testing.assert_allclose(a.numpy(), np2d)


def test_lshape_map_matches_local_shapes(ht, np2d):
    a = ht.array(np2d, split=0)
    m = a.lshape_map
    assert m.shape[0] == a.comm.size
    assert int(m[:, 0].sum()) == 11
    assert (m[:, 1] == 7).all()


def test_nbytes_itemsize(ht):
    a = ht.zeros((4, 4), dtype=ht.float32, split=0)
    assert a.itemsize == 4
    assert a.nbytes == 64


# ------------------------------------------------------------ operator protocol


def test_arithmetic_operators_match_numpy(ht, np2d):
    b_np = np.abs(np2d) + 1.0
    for split in (None, 0, 1):
        a = ht.array(np2d, split=split)
        b = ht.array(b_np, split=split)
        np.testing.assert_allclose((a + b).numpy(), np2d + b_np)
        np.testing.assert_allclose((a - b).numpy(), np2d - b_np)
        np.testing.assert_allclose((a * b).numpy(), np2d * b_np)
        np.testing.assert_allclose((a / b).numpy(), np2d / b_np)
        np.testing.assert_allclose((a // b).numpy(), np2d // b_np)
        np.testing.assert_allclose((a % b).numpy(), np2d % b_np)
        np.testing.assert_allclose((a**2).numpy(), np2d**2)
        np.testing.assert_allclose((-a).numpy(), -np2d)
        np.testing.assert_allclose((+a).numpy(), np2d)
        np.testing.assert_allclose(abs(a).numpy(), np.abs(np2d))


def test_reflected_operators(ht, np2d):
    a = ht.array(np2d, split=0)
    np.testing.assert_allclose((2.0 + a).numpy(), 2.0 + np2d)
    np.testing.assert_allclose((2.0 - a).numpy(), 2.0 - np2d)
    np.testing.assert_allclose((2.0 * a).numpy(), 2.0 * np2d)
    np.testing.assert_allclose((2.0 / (a + 10)).numpy(), 2.0 / (np2d + 10))
    np.testing.assert_allclose((2.0 ** ht.array([1.0, 2.0], split=0)).numpy(), [2.0, 4.0])


def test_matmul_operator(ht, np2d):
    for split in (None, 0, 1):
        a = ht.array(np2d, split=split)
        b = ht.array(np2d.T, split=split)
        np.testing.assert_allclose((a @ b).numpy(), np2d @ np2d.T, atol=1e-10)


def test_comparison_operators(ht, np2d):
    a = ht.array(np2d, split=0)
    assert ((a > 0).numpy() == (np2d > 0)).all()
    assert ((a <= 0.5).numpy() == (np2d <= 0.5)).all()
    assert ((a == a).numpy()).all()
    assert not ((a != a).numpy()).any()


def test_inplace_operators_preserve_identity(ht):
    a = ht.arange(10, dtype=ht.float32, split=0)
    orig = a
    a += 1
    a *= 2
    a -= 2
    a /= 2
    assert a is orig
    np.testing.assert_allclose(a.numpy(), np.arange(10.0))


def test_contains(ht):
    a = ht.arange(10, split=0)
    assert 5 in a
    assert not (99 in a)


# ---------------------------------------------------------------- indexing


@pytest.mark.parametrize("split", [None, 0, 1])
def test_getitem_matrix(ht, np2d, split):
    a = ht.array(np2d, split=split)
    cases = [
        (slice(None), slice(None)),
        (3, slice(None)),
        (slice(1, 9, 2), slice(None)),
        (slice(None), 2),
        (slice(None), slice(1, 6, 2)),
        (slice(None, None, -1), slice(None)),
        (-1, -1),
        (Ellipsis, 0),
        (slice(2, 5), slice(3, 7)),
    ]
    for key in cases:
        got = a[key]
        want = np2d[key]
        got_np = got.numpy() if hasattr(got, "numpy") else np.asarray(got)
        np.testing.assert_allclose(got_np, want, err_msg=str(key))


@pytest.mark.parametrize("split", [None, 0])
def test_getitem_newaxis_and_masks(ht, np2d, split):
    a = ht.array(np2d, split=split)
    np.testing.assert_allclose(a[None, :, :].numpy(), np2d[None])
    mask = np2d[:, 0] > 0
    np.testing.assert_allclose(a[ht.array(mask, split=split)].numpy(), np2d[mask])
    idx = np.array([0, 3, 5])
    np.testing.assert_allclose(a[ht.array(idx, split=split)].numpy(), np2d[idx])


@pytest.mark.parametrize("split", [None, 0, 1])
def test_setitem_matrix(ht, np2d, split):
    a = ht.array(np2d.copy(), split=split)
    ref = np2d.copy()
    a[0] = 7.0
    ref[0] = 7.0
    a[:, 1] = -1.0
    ref[:, 1] = -1.0
    a[2:5, 2:4] = 0.0
    ref[2:5, 2:4] = 0.0
    a[-1] = ht.arange(7, dtype=ht.float64)
    ref[-1] = np.arange(7)
    np.testing.assert_allclose(a.numpy(), ref)


def test_setitem_bool_mask(ht, np2d):
    a = ht.array(np2d.copy(), split=0)
    ref = np2d.copy()
    a[a < 0] = 0.0
    ref[ref < 0] = 0.0
    np.testing.assert_allclose(a.numpy(), ref)


# ------------------------------------------------------- distribution management


def test_resplit_all_pairs(ht, np2d):
    for src in (None, 0, 1):
        for dst in (None, 0, 1):
            a = ht.array(np2d, split=src)
            b = ht.resplit(a, dst)
            assert b.split == dst
            np.testing.assert_allclose(b.numpy(), np2d)
            # in-place variant
            c = ht.array(np2d, split=src)
            c.resplit_(dst)
            assert c.split == dst
            np.testing.assert_allclose(c.numpy(), np2d)


def test_balance_and_collect(ht, np2d):
    a = ht.array(np2d, split=0)
    a.balance_()
    assert a.is_balanced()
    np.testing.assert_allclose(a.numpy(), np2d)
    a.collect_(0)
    np.testing.assert_allclose(a.numpy(), np2d)


def test_redistribute_noop_roundtrip(ht, np2d):
    a = ht.array(np2d, split=0)
    a.redistribute_(target_map=a.lshape_map)
    np.testing.assert_allclose(a.numpy(), np2d)


# ---------------------------------------------------------------- conversions


def test_conversions(ht):
    a = ht.array([[1.5]])
    assert float(a) == 1.5
    assert int(a) == 1
    assert complex(a) == 1.5 + 0j
    b = ht.arange(6, split=0)
    assert b.tolist() == [0, 1, 2, 3, 4, 5]
    assert b.item() if b.size == 1 else True
    with pytest.raises((ValueError, TypeError)):
        b.item()


def test_numpy_and_array_protocol(ht, np2d):
    a = ht.array(np2d, split=1)
    np.testing.assert_allclose(np.asarray(a), np2d)
    assert isinstance(a.numpy(), np.ndarray)


def test_cpu_noop(ht):
    a = ht.arange(4, split=0)
    assert a.cpu() is not None


# -------------------------------------------------------------------- halos


@pytest.mark.parametrize("halo", [1, 2])
def test_halo_exchange(ht, halo):
    n = 16
    x = ht.arange(n, dtype=ht.float32, split=0)
    x.get_halo(halo)
    aug = x.array_with_halos
    # global correctness is covered by convolve; here: shape monotonicity
    assert aug.shape[0] >= x.lshape[0]


def test_halo_used_by_convolve(ht):
    sig = np.arange(20.0)
    ker = np.array([1.0, 2.0, 1.0])
    a = ht.array(sig, split=0)
    v = ht.array(ker)
    np.testing.assert_allclose(
        ht.convolve(a, v, mode="same").numpy(), np.convolve(sig, ker, mode="same")
    )


# ---------------------------------------------------------------- misc parity


def test_rounding_methods(ht):
    a = ht.array([1.4, 1.6, -1.4], split=0)
    np.testing.assert_allclose(a.round().numpy(), [1.0, 2.0, -1.0])
    np.testing.assert_allclose(a.floor().numpy(), [1.0, 1.0, -2.0])
    np.testing.assert_allclose(a.ceil().numpy(), [2.0, 2.0, -1.0])
    np.testing.assert_allclose(a.trunc().numpy(), [1.0, 1.0, -1.0])


def test_reduction_methods(ht, np2d):
    a = ht.array(np2d, split=0)
    np.testing.assert_allclose(float(a.max()), np2d.max())
    np.testing.assert_allclose(float(a.min()), np2d.min())
    np.testing.assert_allclose(float(a.mean()), np2d.mean())
    np.testing.assert_allclose(float(a.std()), np2d.std(), rtol=1e-10)
    np.testing.assert_allclose(a.argmax(), np2d.argmax())
    np.testing.assert_allclose(a.sum(axis=1).numpy(), np2d.sum(1))


# ------------------------------------------------- setitem padded fast path


def test_setitem_padded_int_row(ht):
    # 11 rows over 8 devices -> padded to 16; int-key write must stay in bounds
    x = np.arange(11 * 3, dtype=np.float64).reshape(11, 3)
    a = ht.array(x, split=0)
    a[10] = np.array([1.0, 2.0, 3.0])
    x[10] = [1.0, 2.0, 3.0]
    np.testing.assert_allclose(a.numpy(), x)
    a[-1] = 7.0  # negative index resolves against the TRUE extent (11)
    x[-1] = 7.0
    np.testing.assert_allclose(a.numpy(), x)


def test_setitem_padded_slice(ht):
    x = np.arange(11 * 3, dtype=np.float64).reshape(11, 3)
    a = ht.array(x, split=0)
    a[3:9] = 0.5
    x[3:9] = 0.5
    np.testing.assert_allclose(a.numpy(), x)
    a[9:] = -1.0  # open-ended slice clamps to the true extent, not the pad
    x[9:] = -1.0
    np.testing.assert_allclose(a.numpy(), x)


def test_setitem_padded_split1_col(ht):
    x = np.arange(4 * 11, dtype=np.float64).reshape(4, 11)
    a = ht.array(x, split=1)
    a[:, 10] = 9.0
    x[:, 10] = 9.0
    np.testing.assert_allclose(a.numpy(), x)
    a[1, 2:7] = 3.0
    x[1, 2:7] = 3.0
    np.testing.assert_allclose(a.numpy(), x)


def test_setitem_full_overwrite_padded(ht):
    x = np.zeros((11, 2))
    a = ht.array(x, split=0)
    a[:] = np.ones((11, 2))
    np.testing.assert_allclose(a.numpy(), np.ones((11, 2)))


def test_setitem_bool_scalar_key_falls_back(ht):
    # bool is an int subclass; the padded fast path must not treat it as a
    # row index (numpy bool-scalar semantics add an axis)
    x = np.zeros((11, 3))
    a = ht.array(x, split=0)
    assert a._padded_safe_key(True) is None
    assert a._padded_safe_key((True, slice(None))) is None


def test_setitem_replicated_keeps_canonical_sharding(ht):
    # split=None setitem from a split operand must not leak the operand's
    # sharding into the replicated buffer
    a = ht.ones((8, 4), split=None)
    b = ht.zeros((8, 4), split=0)
    a[:] = b
    want = a.comm.sharding(None, 2)
    assert a.larray_padded.sharding.is_equivalent_to(want, 2)


def test_redistribute_honors_noncanonical(ht, np2d):
    # r4: arbitrary ragged targets are applied (metadata + physical
    # placement), no longer rejected — full coverage in test_redistribute.py
    a = ht.array(np2d, split=0)
    tgt = a.lshape_map.copy()
    tgt[0, 0] += 1
    tgt[1, 0] -= 1
    a.redistribute_(target_map=tgt)
    assert tuple(a.lshape_map[:, 0]) == tuple(tgt[:, 0])
    assert not a.is_balanced()
    np.testing.assert_array_equal(a.numpy(), np2d)
    a.balance_()
    assert a.is_balanced()
