"""numpy.linalg block, random extras, and text-IO extensions vs numpy."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture(scope="module")
def spd():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((5, 5))
    return a @ a.T + 5 * np.eye(5)


def test_cholesky_solve_pinv(spd):
    a = ht.array(spd, split=0)
    L = ht.linalg.cholesky(a).numpy()
    np.testing.assert_allclose(L @ L.T, spd, rtol=1e-10)
    b = np.arange(5.0)
    np.testing.assert_allclose(
        ht.linalg.solve(a, ht.array(b)).numpy(), np.linalg.solve(spd, b), rtol=1e-8
    )
    np.testing.assert_allclose(ht.linalg.pinv(a).numpy(), np.linalg.pinv(spd), rtol=1e-6, atol=1e-8)


def test_eigh_eig_family(spd):
    a = ht.array(spd)
    w, v = ht.linalg.eigh(a)
    np.testing.assert_allclose(np.sort(w.numpy()), np.sort(np.linalg.eigvalsh(spd)), rtol=1e-10)
    np.testing.assert_allclose(
        np.sort(ht.linalg.eigvalsh(a).numpy()), np.sort(np.linalg.eigvalsh(spd)), rtol=1e-10
    )
    g = np.random.default_rng(1).standard_normal((4, 4))
    wg, vg = ht.linalg.eig(ht.array(g))
    np.testing.assert_allclose(
        np.sort_complex(wg.numpy()), np.sort_complex(np.linalg.eigvals(g)), rtol=1e-4, atol=1e-5
    )
    np.testing.assert_allclose(
        np.sort_complex(ht.linalg.eigvals(ht.array(g)).numpy()),
        np.sort_complex(np.linalg.eigvals(g)),
        rtol=1e-4,
        atol=1e-5,
    )


def test_lstsq_rank_cond_slogdet_power(spd):
    rng = np.random.default_rng(2)
    A = rng.standard_normal((8, 3))
    b = rng.standard_normal(8)
    x, resid, rank, sv = ht.linalg.lstsq(ht.array(A, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(x.numpy(), np.linalg.lstsq(A, b, rcond=None)[0], rtol=1e-8)
    assert rank == 3
    assert ht.linalg.matrix_rank(ht.array(spd)) == 5
    np.testing.assert_allclose(float(ht.linalg.cond(ht.array(spd))), np.linalg.cond(spd), rtol=1e-6)
    s, ld = ht.linalg.slogdet(ht.array(spd))
    sn, ldn = np.linalg.slogdet(spd)
    assert float(s) == sn
    np.testing.assert_allclose(float(ld), ldn, rtol=1e-10)
    np.testing.assert_allclose(
        ht.linalg.matrix_power(ht.array(spd), 3).numpy(), np.linalg.matrix_power(spd, 3), rtol=1e-10
    )


def test_multi_dot_tensor_solve():
    rng = np.random.default_rng(3)
    A, B, C = rng.standard_normal((3, 5)), rng.standard_normal((5, 7)), rng.standard_normal((7, 2))
    np.testing.assert_allclose(
        ht.linalg.multi_dot([ht.array(A), ht.array(B), ht.array(C)]).numpy(),
        np.linalg.multi_dot([A, B, C]),
        rtol=1e-10,
    )
    T = rng.standard_normal((2, 3, 6))
    bb = rng.standard_normal((2, 3))
    np.testing.assert_allclose(
        ht.linalg.tensorsolve(ht.array(T), ht.array(bb)).numpy(),
        np.linalg.tensorsolve(T, bb),
        rtol=1e-8,
    )
    Ti = rng.standard_normal((4, 6, 8, 3))
    np.testing.assert_allclose(
        ht.linalg.tensorinv(ht.array(Ti), ind=2).numpy(), np.linalg.tensorinv(Ti, ind=2), rtol=1e-6
    )


def test_random_extras():
    ht.random.seed(0)
    c = ht.random.choice(10, size=(20,))
    assert c.numpy().min() >= 0 and c.numpy().max() < 10
    c2 = ht.random.choice(ht.array([5.0, 6.0]), size=(8,), replace=True)
    assert set(np.unique(c2.numpy())).issubset({5.0, 6.0})
    x = ht.arange(12, split=0)
    ht.random.shuffle(x)
    assert sorted(x.numpy().tolist()) == list(range(12))
    b = ht.random.bytes(16)
    assert isinstance(b, bytes) and len(b) == 16
    ri = ht.random.random_integers(1, 6, size=(200,)).numpy()
    assert ri.min() >= 1 and ri.max() <= 6 and ri.max() == 6  # closed interval


def test_text_io_roundtrips(tmp_path):
    m = np.arange(12.0).reshape(4, 3)
    p = tmp_path / "t.txt"
    ht.savetxt(str(p), ht.array(m, split=0))
    np.testing.assert_allclose(ht.loadtxt(str(p), split=0).numpy(), m)
    np.testing.assert_allclose(ht.genfromtxt(str(p), split=0).numpy(), m)
    pz = tmp_path / "t.npz"
    ht.savez(str(pz), a=ht.array(m), b=ht.arange(5))
    z = np.load(pz)
    np.testing.assert_allclose(z["a"], m)
    ht.savez_compressed(str(tmp_path / "tc.npz"), x=ht.array(m))
    np.testing.assert_allclose(np.load(tmp_path / "tc.npz")["x"], m)


def test_from_family():
    np.testing.assert_allclose(
        ht.fromfunction(lambda i, j: i + 10 * j, (3, 4), dtype=ht.float64).numpy(),
        np.fromfunction(lambda i, j: i + 10 * j, (3, 4)),
    )
    assert ht.fromiter(range(6), ht.int32).numpy().tolist() == list(range(6))
    np.testing.assert_allclose(
        ht.frombuffer(np.arange(4.0).tobytes(), dtype=ht.float64).numpy(), np.arange(4.0)
    )
    np.testing.assert_allclose(ht.fromstring("1 2 3", dtype=ht.float32).numpy(), [1.0, 2.0, 3.0])


def test_io_stragglers(tmp_path):
    p = tmp_path / "raw.bin"
    np.arange(6.0).tofile(p)
    np.testing.assert_allclose(ht.fromfile(str(p), dtype=ht.float64).numpy(), np.arange(6.0))
    x = ht.arange(4, dtype=ht.float32)
    ht.tofile(x, str(tmp_path / "o.bin"))
    np.testing.assert_allclose(np.fromfile(tmp_path / "o.bin", np.float32), np.arange(4.0))
    (tmp_path / "t.txt").write_text("a=1.5\nb=2.5\n")
    np.testing.assert_allclose(
        ht.fromregex(str(tmp_path / "t.txt"), r"\w+=([\d.]+)", [("v", np.float64)]).numpy(),
        [1.5, 2.5],
    )
    mp = tmp_path / "m.dat"
    np.memmap(mp, dtype=np.float32, mode="w+", shape=(4,))[:] = [1, 2, 3, 4]
    np.testing.assert_allclose(ht.memmap(str(mp), dtype=ht.float32, shape=(4,)).numpy(), [1, 2, 3, 4])
    npy = tmp_path / "a.npy"
    np.save(npy, np.arange(5.0))
    np.testing.assert_allclose(ht.open_memmap(str(npy)).numpy(), np.arange(5.0))
    assert ht.DataSource(str(tmp_path)).exists(str(npy))


def test_printing_stragglers():
    a = ht.array([1.23456789])
    with ht.printoptions(precision=2):
        assert "1.23]" in str(a)
    assert "1.2346" in str(a)  # restored
    ht.set_string_function(lambda arr: f"<custom {arr.shape}>")
    try:
        assert repr(a) == "<custom (1,)>"
    finally:
        ht.set_string_function(None)
    assert "DNDarray" in repr(a)


def test_napi_stragglers():
    a = ht.array([1.5])
    np.testing.assert_allclose(ht.from_dlpack(np.arange(3.0)).numpy(), np.arange(3.0))
    assert not ht.isfortran(a)
    with pytest.raises(TypeError):
        ht.isnat(a)
    assert ht.require([1, 2], dtype=ht.float32).dtype == ht.float32
    b = ht.broadcast(ht.ones((3, 1)), ht.ones((1, 4)))
    assert b.shape == (3, 4) and b.size == 12
    assert ht.asmatrix([1.0, 2.0]).shape == (1, 2)
    assert ht.mat([[1.0, 2.0], [3.0, 4.0]]).shape == (2, 2)
    assert ht.bmat([[ht.ones((2, 2)), ht.zeros((2, 2))]]).shape == (2, 4)
    assert [int(v) for v in ht.arange(4).flat] == [0, 1, 2, 3]


def test_save_load_extension_dispatch(tmp_path):
    m = np.arange(12.0).reshape(4, 3)
    x = ht.array(m, split=0)
    for name in ("a.npy", "a.txt"):
        p = str(tmp_path / name)
        ht.save(x, p)
        np.testing.assert_allclose(ht.load(p, split=0).numpy(), m)
    ht.save(x, str(tmp_path / "a.npz"))
    z = np.load(tmp_path / "a.npz")
    np.testing.assert_allclose(z[z.files[0]], m)
    with pytest.raises(ValueError):
        ht.save(x, str(tmp_path / "a.unknown"))
