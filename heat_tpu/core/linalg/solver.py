"""Iterative/triangular solvers, analog of heat/core/linalg/solver.py.

``cg`` (solver.py:16-66) and ``lanczos`` (:69-274) are compositions of the
distributed ops API and port structurally; ``solve_triangular`` (:275-463)
— blocked backward substitution with Bcasts in the reference — lowers to
XLA's triangular solve over the sharded operand.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from .basics import matmul, transpose

__all__ = ["cg", "lanczos", "solve_triangular"]


from functools import partial


@partial(jax.jit, static_argnames=("max_iter",))
def _cg_loop(Ad: jax.Array, bd: jax.Array, x0d: jax.Array, max_iter: int) -> jax.Array:
    """Conjugate-gradient iteration compiled as one program (tol 1e-10 on
    the residual norm, matching the reference's stop test solver.py:46)."""
    hp = jax.lax.Precision.HIGHEST

    r0 = bd - jnp.matmul(Ad, x0d, precision=hp)
    init = (x0d, r0, r0, jnp.vdot(r0, r0), jnp.int32(0))

    def cond(carry):
        x, r, p, rs, it = carry
        return jnp.logical_and(it < max_iter, jnp.sqrt(rs) >= 1e-10)

    def body(carry):
        x, r, p, rs, it = carry
        Ap = jnp.matmul(Ad, p, precision=hp)
        alpha = rs / jnp.vdot(p, Ap)
        x = x + alpha * p
        r = r - alpha * Ap
        rsnew = jnp.vdot(r, r)
        p = r + (rsnew / rs) * p
        return x, r, p, rsnew, it + 1

    x, _, _, _, _ = jax.lax.while_loop(cond, body, init)
    return x


def cg(A: DNDarray, b: DNDarray, x0: DNDarray, out: Optional[DNDarray] = None) -> DNDarray:
    """Conjugate gradients for SPD systems (solver.py:16)."""
    if not isinstance(A, DNDarray) or not isinstance(b, DNDarray) or not isinstance(x0, DNDarray):
        raise TypeError(f"A, b and x0 need to be DNDarrays, but were {type(A)}, {type(b)}, {type(x0)}")
    if A.ndim != 2:
        raise RuntimeError("A needs to be a 2D matrix")
    if b.ndim != 1:
        raise RuntimeError("b needs to be a 1D vector")
    if x0.ndim != 1:
        raise RuntimeError("c needs to be a 1D vector")

    # whole Krylov iteration as one on-device while_loop: a Python loop
    # with a float() residual check costs one device->host round trip per
    # step (a full link RTT on a tunneled chip)
    Ad = A._dense()
    if not types.heat_type_is_inexact(A.dtype):
        Ad = Ad.astype(jnp.float32)
    bd = b._dense().astype(Ad.dtype)
    x0d = x0._dense().astype(Ad.dtype)
    xd = _cg_loop(Ad, bd, x0d, len(b))
    result = DNDarray.from_dense(xd, b.split, b.device, b.comm)
    if out is not None:
        out._replace(result.larray_padded)
        return out
    return result


def lanczos(
    A: DNDarray,
    m: int,
    v0: Optional[DNDarray] = None,
    V_out: Optional[DNDarray] = None,
    T_out: Optional[DNDarray] = None,
) -> Tuple[DNDarray, DNDarray]:
    """Lanczos tridiagonalization of a symmetric/Hermitian matrix
    (solver.py:69): m Krylov steps with full reorthogonalization.
    """
    if not isinstance(A, DNDarray):
        raise TypeError(f"A needs to be a DNDarray, but was {type(A)}")
    if not isinstance(m, int) or m <= 0:
        raise TypeError(f"m must be a positive integer, got {m}")
    if A.ndim != 2 or A.shape[0] != A.shape[1]:
        raise RuntimeError("A needs to be a square matrix")

    n = A.shape[0]
    dense_A = A._dense()
    dtype = dense_A.dtype
    is_complex = types.heat_type_is_complexfloating(A.dtype)

    from .. import random as ht_random

    if v0 is None:
        v = ht_random.randn(n, dtype=types.canonical_heat_type(jnp.float32), comm=A.comm)._dense().astype(dtype)
        v = v / jnp.linalg.norm(v)
    else:
        v = v0._dense().astype(dtype)

    V, T = _lanczos_impl(dense_A, v, m, is_complex)

    V_res = DNDarray.from_dense(V, A.split, A.device, A.comm)
    T_res = DNDarray.from_dense(T, None, A.device, A.comm)
    if V_out is not None:
        V_out._replace(V_res.larray_padded)
        V_res = V_out
    if T_out is not None:
        T_out._replace(T_res.larray_padded)
        T_res = T_out
    return V_res, T_res


from functools import partial as _partial


@_partial(jax.jit, static_argnames=("m", "is_complex"))
def _lanczos_impl(dense_A: jax.Array, v: jax.Array, m: int, is_complex: bool):
    """Krylov loop with static shapes, compiled once.

    Reorthogonalization projects against the FULL (n, m) basis every step:
    the not-yet-filled columns are zero, so ``V (V^H w)`` is identical to
    the reference's growing ``V[:, :j+1]`` product (solver.py:153+) while
    keeping every iteration the same shape — one compilation instead of m.
    """
    n = dense_A.shape[0]
    dtype = dense_A.dtype
    hi = jax.lax.Precision.HIGHEST

    V0 = jnp.zeros((n, m), dtype=dtype).at[:, 0].set(v)
    T0 = jnp.zeros((m, m), dtype=jnp.float32)

    def alpha_of(vj, w):
        a = jnp.vdot(vj, w)
        return jnp.real(a) if is_complex else a

    def body(j, carry):
        V, T, beta, v_prev = carry
        vj = jax.lax.dynamic_slice_in_dim(V, j, 1, axis=1)[:, 0]
        w = jnp.matmul(dense_A, vj, precision=hi)
        alpha = alpha_of(vj, w)
        w = w - alpha * vj - beta * v_prev
        w = w - jnp.matmul(V, jnp.matmul(jnp.conj(V).T, w, precision=hi), precision=hi)
        T = T.at[j, j].set(alpha.astype(jnp.float32))
        beta_new = jnp.linalg.norm(w)
        T = T.at[j, j + 1].set(beta_new.astype(jnp.float32))
        T = T.at[j + 1, j].set(beta_new.astype(jnp.float32))
        v_next = jnp.where(beta_new > 1e-10, w / jnp.maximum(beta_new, 1e-30).astype(dtype), w)
        V = V.at[:, j + 1].set(v_next)
        return V, T, beta_new.astype(dtype if not is_complex else jnp.float32), vj

    beta0 = jnp.zeros((), jnp.float32 if is_complex else dtype)
    V, T, beta, v_prev = jax.lax.fori_loop(
        0, m - 1, body, (V0, T0, beta0, jnp.zeros_like(v))
    )
    # final step: diagonal entry only (no j+1 column to fill)
    vj = V[:, m - 1]
    w = jnp.matmul(dense_A, vj, precision=hi)
    T = T.at[m - 1, m - 1].set(alpha_of(vj, w).astype(jnp.float32))
    return V, T


def solve_triangular(A: DNDarray, b: DNDarray) -> DNDarray:
    """Solve A x = b for upper-triangular A (solver.py:275)."""
    sanitize_in(A)
    sanitize_in(b)
    if A.ndim < 2 or A.shape[-1] != A.shape[-2]:
        raise ValueError("A must be a (batch of) square upper triangular matrix")
    import jax.scipy.linalg as jsl

    a_dense = A._dense()
    b_dense = b._dense()
    if not types.heat_type_is_inexact(A.dtype):
        a_dense = a_dense.astype(jnp.float32)
        b_dense = b_dense.astype(jnp.float32)
    result = jsl.solve_triangular(a_dense, b_dense, lower=False)
    return DNDarray.from_dense(result, b.split, b.device, b.comm)
