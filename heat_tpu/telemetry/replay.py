"""Offline incident replay: rebuild the decision timeline from disk.

``python -m heat_tpu.telemetry.replay <journal-dir>`` reads the durable
decision-journal segments (committed by :mod:`heat_tpu.telemetry.
journal` under ``HEAT_TPU_JOURNAL_DIR``), verifies every CRC sidecar,
and reconstructs the incident timeline **after the process is gone** —
the serving replica crashed or was killed, the hot rings died with it,
and the postmortem starts from this directory alone.

    python -m heat_tpu.telemetry.replay /var/log/heat_tpu/journal
    python -m heat_tpu.telemetry.replay /var/log/heat_tpu/journal \
        --event-id 3f21-18c9a2b4e01-000007      # causal-chain explain
    python -m heat_tpu.telemetry.replay /var/log/heat_tpu/journal --json

The default rendering is the chronological timeline with cause links
resolved inline; ``--event-id`` walks one decision's causal chain to
its root and lists its downstream effects (the offline twin of
``/decisionz?event_id=``); ``--check`` steps the timeline through the
declared control-plane protocols (:mod:`heat_tpu.analysis.protocols`)
and reports every H805 conformance violation, exiting non-zero if any;
``--json`` emits the machine form.  :func:`replay_report` is the pure
core the tests drive.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional

from ..analysis import conformance as _conformance
from .journal import causal_chain, read_journal

__all__ = ["format_replay", "main", "replay_report"]


def replay_report(
    directory: str,
    event_id: Optional[str] = None,
    check: bool = False,
) -> Dict[str, Any]:
    """The machine form of a replay: the full durable timeline, per-actor
    counts, root events (no retained cause), and — when ``event_id`` is
    given — that event's causal chain and effects.  With ``check`` the
    timeline is stepped through the declared control-plane protocols and
    the violations land under ``"check"``."""
    events = read_journal(directory)
    actors: Dict[str, int] = {}
    for e in events:
        actors[e.get("actor", "?")] = actors.get(e.get("actor", "?"), 0) + 1
    ids = {e.get("event_id") for e in events}
    roots = [e for e in events if not e.get("cause") or e["cause"] not in ids]
    doc: Dict[str, Any] = {
        "dir": directory,
        "event_count": len(events),
        "actors": dict(sorted(actors.items())),
        "roots": [e.get("event_id") for e in roots],
        "events": events,
    }
    if event_id is not None:
        doc["explain"] = causal_chain(event_id, events=events)
    if check:
        annotations = _conformance.annotate(events)
        stepped = sum(1 for a in annotations.values())
        bad = [
            {"event_id": eid, "protocol": a.get("protocol"),
             "scope_key": a.get("scope_key"), "from": a.get("from"),
             "message": a.get("message")}
            for eid, a in annotations.items() if not a.get("ok")
        ]
        doc["check"] = {
            "stepped": stepped,
            "violations": bad,
            "violation_count": len(bad),
        }
    return doc


def _fmt_event(e: Dict[str, Any], indent: str = "") -> str:
    ev = ", ".join(f"{k}={e['evidence'][k]}" for k in sorted(e.get("evidence") or {}))
    bits = [
        f"{indent}{e.get('ts', 0):.3f} [{e.get('severity', '?'):4s}] "
        f"{e.get('actor')}/{e.get('action')}"
    ]
    if e.get("model") or e.get("tenant"):
        bits.append(f"({e.get('model') or e.get('tenant')})")
    if e.get("message"):
        bits.append(f"— {e['message']}")
    lines = [" ".join(bits), f"{indent}    event_id={e.get('event_id')}"]
    if e.get("cause"):
        lines.append(f"{indent}    cause={e['cause']}")
    if e.get("trace_id"):
        lines.append(f"{indent}    exemplar trace_id={e['trace_id']}")
    if ev:
        lines.append(f"{indent}    evidence: {ev}")
    return "\n".join(lines)


def format_replay(doc: Dict[str, Any]) -> str:
    """Human rendering of :func:`replay_report`."""
    out: List[str] = [
        f"decision journal replay: {doc['dir']}",
        f"{doc['event_count']} event(s), "
        + ", ".join(f"{a}×{n}" for a, n in doc["actors"].items()),
        "",
    ]
    explain = doc.get("explain")
    if explain is not None:
        if not explain["found"]:
            out.append(f"event {explain['event_id']} not found in the durable log")
            return "\n".join(out)
        out.append(
            f"causal chain for {explain['event_id']} "
            f"({len(explain['chain'])} event(s), root first):"
        )
        for i, e in enumerate(explain["chain"]):
            out.append(_fmt_event(e, indent="  " * i))
        out.append("")
        out.append(f"downstream effects ({len(explain['effects'])}):")
        for e in explain["effects"]:
            out.append(_fmt_event(e, indent="  "))
        return "\n".join(out)
    check = doc.get("check")
    if check is not None:
        out.append(
            f"protocol conformance: {check['stepped']} protocol event(s) "
            f"stepped, {check['violation_count']} violation(s)"
        )
        for v in check["violations"]:
            out.append(f"  H805 {v['event_id']}: {v['message']}")
        out.append("")
    out.append("timeline (oldest first):")
    for e in doc["events"]:
        out.append(_fmt_event(e))
    return "\n".join(out)


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m heat_tpu.telemetry.replay",
        description="reconstruct the control-plane incident timeline "
        "from a durable decision-journal directory",
    )
    ap.add_argument("directory", help="HEAT_TPU_JOURNAL_DIR of the dead process")
    ap.add_argument("--event-id", default=None,
                    help="explain one decision: causal chain + effects")
    ap.add_argument("--check", action="store_true",
                    help="step the timeline through the declared control-"
                    "plane protocols; non-zero exit on any H805 violation")
    ap.add_argument("--json", action="store_true", help="machine-readable output")
    args = ap.parse_args(argv)

    doc = replay_report(args.directory, event_id=args.event_id, check=args.check)
    if args.json:
        print(json.dumps(doc, indent=1, default=str))
    else:
        print(format_replay(doc))
    if args.check and doc["check"]["violation_count"]:
        return 2
    return 0 if doc["event_count"] else 1


if __name__ == "__main__":
    sys.exit(main())
