"""Static docs site builder (VERDICT r4 missing #3).

The reference ships a Sphinx tree (doc/source/conf.py); this environment
has no sphinx/mkdocs, so the site is built with the stdlib-adjacent
pieces that ARE here: ``markdown`` (+fenced code & tables extensions,
pygments highlighting) for the guides, ``nbconvert`` for the tutorial
notebooks.  One nav sidebar across every page; internal ``.md`` links
are rewritten to ``.html``.

    python scripts/build_docs.py [--out site] [--skip-notebooks]

CI builds the site on every push (docs job in .github/workflows/ci.yaml).
"""

import argparse
import os
import re
import shutil
import sys

import markdown

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: nav: (section, [(title, source path relative to repo)])
NAV = [
    ("Start", [
        ("Overview", "README.md"),
        ("30-minute tour", "docs/tutorial_30min.md"),
    ]),
    ("Guides", [
        ("Design", "docs/design.md"),
        ("Parallelism", "docs/tutorial_parallel.md"),
        ("Clustering", "docs/tutorial_clustering.md"),
        ("Data-parallel NN", "docs/tutorial_dpnn.md"),
        ("Planar complex ops", "docs/planar_ops.md"),
        ("FFT roofline", "docs/fft_roofline.md"),
    ]),
    ("Multi-host (pod) track", [
        ("Overview", "tutorials/hpc/README.md"),
        ("1. Pod bring-up", "tutorials/hpc/01_pod_bringup.md"),
        ("2. Distributed data", "tutorials/hpc/02_distributed_data.md"),
        ("3. Training at scale", "tutorials/hpc/03_training_at_scale.md"),
    ]),
    ("Internals", [
        ("Dispatch layer", "docs/dispatch.md"),
        ("Resilience", "docs/resilience.md"),
        ("Elasticity", "docs/elasticity.md"),
        ("Serving", "docs/serving.md"),
        ("Fleet serving", "docs/fleet.md"),
        ("Streaming", "docs/streaming.md"),
        ("Overlap layer", "docs/overlap.md"),
        ("Observability", "docs/observability.md"),
        ("Static analysis", "docs/static_analysis.md"),
        ("Environment variables", "docs/env_vars.md"),
    ]),
    ("Reference", [
        ("API reference", "docs/api_reference.md"),
        ("Perf history", "docs/perf_history.md"),
        ("API coverage", "coverage_tables.md"),
        ("Changelog", "CHANGELOG.md"),
        ("Round 5 notes", "docs/round5_notes.md"),
    ]),
]

NOTEBOOKS = [
    ("Notebook: intro", "tutorials/local/1_intro.ipynb"),
    ("Notebook: basics", "tutorials/local/2_basics.ipynb"),
    ("Notebook: internals", "tutorials/local/3_internals.ipynb"),
    ("Notebook: loading & preprocessing", "tutorials/local/4_loading_preprocessing.ipynb"),
    ("Notebook: matrix factorizations", "tutorials/local/5_matrix_factorizations.ipynb"),
    ("Notebook: clustering", "tutorials/local/6_clustering.ipynb"),
]

CSS = """
body { font-family: -apple-system, 'Segoe UI', Roboto, sans-serif; margin: 0;
       display: flex; color: #1a1a2e; }
nav { width: 250px; min-height: 100vh; background: #f4f4f8; padding: 1.2rem;
      box-sizing: border-box; flex-shrink: 0; }
nav h3 { font-size: .8rem; text-transform: uppercase; letter-spacing: .05em;
         color: #666; margin: 1.2rem 0 .3rem; }
nav a { display: block; padding: .15rem 0; color: #2a4d8f; text-decoration: none;
        font-size: .92rem; }
nav a.active { font-weight: 700; }
main { padding: 2rem 3rem; max-width: 54rem; box-sizing: border-box; }
pre { background: #f6f8fa; padding: .8rem 1rem; overflow-x: auto;
      border-radius: 6px; font-size: .88rem; }
code { background: #f6f8fa; padding: .1em .3em; border-radius: 3px;
       font-size: .92em; }
pre code { background: none; padding: 0; }
table { border-collapse: collapse; margin: 1rem 0; }
th, td { border: 1px solid #ddd; padding: .35rem .7rem; font-size: .9rem;
         text-align: left; }
th { background: #f4f4f8; }
h1, h2 { border-bottom: 1px solid #eee; padding-bottom: .3rem; }
"""


def _slug(path: str) -> str:
    return path.replace("/", "_").rsplit(".", 1)[0] + ".html"


def _nav_html(active_src: str, entries) -> str:
    parts = ["<nav>"]
    for section, items in entries:
        parts.append(f"<h3>{section}</h3>")
        for title, src in items:
            cls = ' class="active"' if src == active_src else ""
            parts.append(f'<a href="{_slug(src)}"{cls}>{title}</a>')
    parts.append("</nav>")
    return "\n".join(parts)


def _rewrite_links(html: str, src: str) -> str:
    """Point intra-repo .md links at their built .html pages."""
    def sub(m):
        href = m.group(1)
        if href.startswith(("http://", "https://", "#", "mailto:")):
            return m.group(0)
        target = os.path.normpath(os.path.join(os.path.dirname(src), href))
        if target.endswith(".md"):
            return f'href="{_slug(target)}"'
        return m.group(0)

    return re.sub(r'href="([^"]+)"', sub, html)


def build(out_dir: str, skip_notebooks: bool) -> int:
    md = markdown.Markdown(
        extensions=["fenced_code", "tables", "codehilite", "toc"],
        extension_configs={"codehilite": {"guess_lang": False}},
    )
    os.makedirs(out_dir, exist_ok=True)
    with open(os.path.join(out_dir, "style.css"), "w") as f:
        f.write(CSS)
        try:
            from pygments.formatters import HtmlFormatter

            f.write(HtmlFormatter().get_style_defs(".codehilite"))
        except ImportError:
            pass

    entries = [s for s in NAV]
    if not skip_notebooks:
        entries = entries + [("Notebooks", NOTEBOOKS)]

    api_md = os.path.join(REPO, "docs", "api_reference.md")
    env_md = os.path.join(REPO, "docs", "env_vars.md")
    if not (os.path.exists(api_md) and os.path.exists(env_md)):
        # the API reference and env-var pages are generated artifacts:
        # produce them on demand so the documented one-command invocation
        # works on a fresh clone
        import subprocess

        subprocess.run(
            [sys.executable, os.path.join(REPO, "scripts", "build_api_docs.py")],
            check=True,
        )

    built = 0
    for section, items in entries:
        for title, src in items:
            path = os.path.join(REPO, src)
            if not os.path.exists(path):
                print(f"MISSING source: {src}", file=sys.stderr)
                return 1
            if src.endswith(".ipynb"):
                from nbconvert import HTMLExporter

                body, _ = HTMLExporter(template_name="classic").from_filename(path)
                # notebook pages keep their own styling; just drop them in
                with open(os.path.join(out_dir, _slug(src)), "w") as f:
                    f.write(body)
            else:
                with open(path) as f:
                    text = f.read()
                md.reset()
                body = _rewrite_links(md.convert(text), src)
                page = (
                    "<!doctype html><html><head><meta charset='utf-8'>"
                    f"<title>{title} — heat_tpu</title>"
                    "<link rel='stylesheet' href='style.css'></head><body>"
                    + _nav_html(src, entries)
                    + f"<main>{body}</main></body></html>"
                )
                with open(os.path.join(out_dir, _slug(src)), "w") as f:
                    f.write(page)
            built += 1

    # the landing page is the README build
    shutil.copyfile(
        os.path.join(out_dir, _slug("README.md")), os.path.join(out_dir, "index.html")
    )
    print(f"built {built} pages -> {out_dir}/")
    return 0


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "site"))
    ap.add_argument("--skip-notebooks", action="store_true")
    args = ap.parse_args()
    sys.exit(build(args.out, args.skip_notebooks))


if __name__ == "__main__":
    main()
