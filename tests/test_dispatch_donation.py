"""Buffer-donation tests (ISSUE 1 tentpole piece 2).

In-place ops (``resplit_``, ``out=`` stores, ``__iadd__``-style dunders)
donate the target's dead backing buffer to the compiled program so XLA
can reuse the allocation.  Two properties are pinned here:

* in-place paths do not GROW the live device-buffer population
  (``jax.live_arrays()`` before/after on the CPU backend);
* donation NEVER fires when the buffer is shared — another DNDarray,
  a pending chain elsewhere, or a user-held ``larray_padded`` — and the
  sharing holder stays readable afterwards.
"""

import gc

import jax
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu.core import dispatch


def _live_count() -> int:
    gc.collect()
    return len(jax.live_arrays())


def test_iadd_does_not_grow_live_buffers():
    x = ht.arange(64, split=0).astype(ht.float32)
    y = ht.ones(64, split=0)
    x += y  # warm the executable
    before = _live_count()
    for _ in range(10):
        x += y
    after = _live_count()
    assert after <= before, f"live buffers grew {before} -> {after}"
    np.testing.assert_allclose(x.numpy(), np.arange(64) + 11.0, rtol=1e-6)


def test_resplit_does_not_grow_live_buffers():
    x = ht.arange(65, split=0).astype(ht.float32)  # indivisible: padded
    want = x.numpy().copy()
    x.resplit_(None)  # warm both directions
    x.resplit_(0)
    before = _live_count()
    for _ in range(5):
        x.resplit_(None)
        x.resplit_(0)
    after = _live_count()
    assert after <= before, f"live buffers grew {before} -> {after}"
    np.testing.assert_allclose(x.numpy(), want, rtol=1e-6)


def test_out_store_does_not_grow_live_buffers():
    a = ht.arange(64, split=0).astype(ht.float32)
    b = ht.full((64,), 2.0, split=0)
    out = ht.zeros(64, split=0)
    ht.mul(a, b, out=out)  # warm
    before = _live_count()
    for _ in range(10):
        ht.mul(a, b, out=out)
        ht.add(a, b, out=out)
    after = _live_count()
    assert after <= before, f"live buffers grew {before} -> {after}"
    np.testing.assert_allclose(out.numpy(), np.arange(64) + 2.0, rtol=1e-6)


def test_iadd_donates_when_unshared():
    x = ht.arange(64, split=0).astype(ht.float32)
    x += 1.0  # warm
    dispatch.reset_stats()
    x += 1.0
    if dispatch._DONATE_ENABLED:
        assert dispatch.cache_stats()["donations"] >= 1
    np.testing.assert_allclose(x.numpy(), np.arange(64) + 2.0, rtol=1e-6)


def test_no_donation_when_chain_references_buffer():
    """tmp = x + y keeps x's buffer as a chain leaf: x += tmp must NOT
    donate, and tmp must stay readable afterwards."""
    x = ht.arange(32, split=0).astype(ht.float32)
    y = ht.ones(32, split=0)
    tmp = x + y  # pending chain, leaf = x's buffer
    dispatch.reset_stats()
    x += tmp
    if dispatch.fusion_enabled():
        # with fusion off tmp is already concrete, so donating x's old
        # buffer is safe and allowed — the refusal only applies to a
        # LIVE chain that still references the buffer
        assert dispatch.cache_stats()["donations"] == 0
    np.testing.assert_allclose(tmp.numpy(), np.arange(32) + 1.0, rtol=1e-6)
    np.testing.assert_allclose(x.numpy(), 2 * np.arange(32) + 1.0, rtol=1e-6)


def test_no_donation_when_user_holds_buffer():
    x = ht.arange(32, split=0).astype(ht.float32)
    held = x.larray_padded
    dispatch.reset_stats()
    x += 1.0
    assert dispatch.cache_stats()["donations"] == 0
    assert float(np.asarray(held)[5]) == 5.0  # old buffer untouched


def test_no_donation_when_backing_is_shared():
    x = ht.arange(32, split=0).astype(ht.float32)
    alias = x.resplit(0)  # same-axis resplit shares the backing buffer
    dispatch.reset_stats()
    x += 1.0
    assert dispatch.cache_stats()["donations"] == 0
    np.testing.assert_allclose(alias.numpy(), np.arange(32), rtol=1e-6)
    np.testing.assert_allclose(x.numpy(), np.arange(32) + 1.0, rtol=1e-6)


def test_no_donation_on_resplit_with_shared_backing():
    x = ht.arange(32, split=0).astype(ht.float32)
    alias = x.resplit(0)
    dispatch.reset_stats()
    x.resplit_(None)
    assert dispatch.cache_stats()["donations"] == 0
    np.testing.assert_allclose(alias.numpy(), np.arange(32), rtol=1e-6)


def test_inplace_loop_values_stay_correct():
    """The full ML-loop shape: repeated donating += with a warm cache."""
    w = ht.zeros(128, split=0)
    g = ht.ones(128, split=0)
    for _ in range(25):
        w += g * 0.5
    np.testing.assert_allclose(w.numpy(), 12.5, rtol=1e-5)
