"""The reference's arithmetics width grid (VERDICT r4 #6, first family):
op x dtype x split against numpy ground truth, the analog of
heat/core/tests/test_arithmetics.py's per-op batteries compressed into
table-driven sweeps.  Complements tests/test_arithmetics_edges.py (sharp
corners) with breadth: every binary op over the dtype-pair grid at every
split, scalar operands both sides, broadcasting shapes, unary sweeps,
and result-dtype promotion checks.
"""

import numpy as np
import pytest

import heat_tpu as ht

# (name, numpy fn, integer_ok, needs_positive_rhs)
BINARY_OPS = [
    ("add", np.add, True, False),
    ("sub", np.subtract, True, False),
    ("mul", np.multiply, True, False),
    ("div", np.divide, False, True),
    ("floordiv", np.floor_divide, True, True),
    ("mod", np.mod, True, True),
    ("fmod", np.fmod, True, True),
    ("pow", np.power, False, False),
    ("maximum", np.maximum, True, False),
    ("minimum", np.minimum, True, False),
    ("copysign", np.copysign, False, False),
    ("hypot", np.hypot, False, False),
    ("arctan2", np.arctan2, False, False),
    ("remainder", np.remainder, True, True),
]

INT_OPS = [
    ("bitwise_and", np.bitwise_and),
    ("bitwise_or", np.bitwise_or),
    ("bitwise_xor", np.bitwise_xor),
    ("left_shift", np.left_shift),
    ("right_shift", np.right_shift),
    ("gcd", np.gcd),
    ("lcm", np.lcm),
]

UNARY_OPS = [
    ("abs", np.abs), ("exp", np.exp), ("expm1", np.expm1), ("log", np.log),
    ("log2", np.log2), ("log10", np.log10), ("log1p", np.log1p),
    ("sqrt", np.sqrt), ("sin", np.sin), ("cos", np.cos), ("tan", np.tan),
    ("sinh", np.sinh), ("cosh", np.cosh), ("tanh", np.tanh),
    ("arcsin", np.arcsin), ("arctan", np.arctan),
    ("floor", np.floor), ("ceil", np.ceil), ("trunc", np.trunc),
    ("round", np.round), ("sign", np.sign), ("negative", np.negative),
    ("positive", np.positive), ("square", np.square),
    ("reciprocal", np.reciprocal), ("cbrt", np.cbrt),
]

FLOAT_DTYPES = [(ht.float32, np.float32), (ht.float64, np.float64)]
INT_DTYPES = [(ht.int32, np.int32), (ht.int64, np.int64), (ht.uint8, np.uint8)]
SPLITS = [None, 0, 1]


def _operands(np_dtype, positive_rhs):
    rng = np.random.default_rng(42)
    if np.issubdtype(np_dtype, np.floating):
        a = rng.standard_normal((7, 10)).astype(np_dtype) * 3
        b = rng.standard_normal((7, 10)).astype(np_dtype) * 2
        if positive_rhs:
            b = np.abs(b) + 0.5
    else:
        a = rng.integers(1, 50, (7, 10)).astype(np_dtype)
        b = rng.integers(1, 9, (7, 10)).astype(np_dtype)
    return a, b


@pytest.mark.parametrize("split", SPLITS)
def test_binary_float_grid(split):
    for name, np_fn, _, pos in BINARY_OPS:
        fn = getattr(ht, name)
        for hdt, ndt in FLOAT_DTYPES:
            a, b = _operands(ndt, pos)
            if name == "pow":
                b = np.abs(b)  # numpy float pow of negatives -> nan grid noise
            want = np_fn(a, b)
            got = fn(ht.array(a, split=split), ht.array(b, split=split))
            assert got.split == split, (name, hdt)
            np.testing.assert_allclose(
                got.numpy(), want, rtol=2e-5 if ndt == np.float32 else 1e-12,
                atol=1e-6, err_msg=f"{name}[{ndt}] split={split}",
            )


@pytest.mark.parametrize("split", SPLITS)
def test_binary_int_grid(split):
    for name, np_fn, int_ok, pos in BINARY_OPS:
        if not int_ok:
            continue
        fn = getattr(ht, name)
        for hdt, ndt in INT_DTYPES:
            a, b = _operands(ndt, pos)
            want = np_fn(a, b)
            got = fn(ht.array(a, split=split), ht.array(b, split=split))
            np.testing.assert_allclose(
                got.numpy().astype(np.float64), want.astype(np.float64),
                err_msg=f"{name}[{ndt}] split={split}",
            )
    for name, np_fn in INT_OPS:
        fn = getattr(ht, name)
        a, b = _operands(np.int32, True)
        b = b % 8
        want = np_fn(a, b)
        got = fn(ht.array(a, split=split), ht.array(b, split=split))
        np.testing.assert_array_equal(got.numpy(), want, err_msg=f"{name} split={split}")


@pytest.mark.parametrize("split", SPLITS)
def test_scalar_both_sides(split):
    a, _ = _operands(np.float32, False)
    x = ht.array(a, split=split)
    for name, np_fn, _, pos in BINARY_OPS:
        s = 2.5 if not pos else 1.5
        fn = getattr(ht, name)
        np.testing.assert_allclose(
            fn(x, s).numpy(), np_fn(a, np.float32(s)), rtol=2e-5, atol=1e-6,
            err_msg=f"{name}(arr, scalar) split={split}",
        )
        np.testing.assert_allclose(
            fn(s, x).numpy(), np_fn(np.float32(s), a), rtol=2e-5, atol=1e-6,
            err_msg=f"{name}(scalar, arr) split={split}",
        )


@pytest.mark.parametrize("split", SPLITS)
def test_broadcasting_shapes(split):
    rng = np.random.default_rng(3)
    a = rng.standard_normal((6, 9)).astype(np.float32)
    row = rng.standard_normal((1, 9)).astype(np.float32)
    col = rng.standard_normal((6, 1)).astype(np.float32)
    vec = rng.standard_normal((9,)).astype(np.float32)
    x = ht.array(a, split=split)
    for other, label in ((row, "row"), (col, "col"), (vec, "vec")):
        for name in ("add", "mul", "sub", "maximum"):
            fn = getattr(ht, name)
            np.testing.assert_allclose(
                fn(x, ht.array(other)).numpy(), getattr(np, {"sub": "subtract", "mul": "multiply"}.get(name, name))(a, other),
                rtol=2e-5, err_msg=f"{name} vs {label} split={split}",
            )


@pytest.mark.parametrize("split", SPLITS)
def test_unary_grid(split):
    rng = np.random.default_rng(9)
    a = (rng.random((8, 11)).astype(np.float32) * 0.8 + 0.1)  # (0.1, 0.9)
    x = ht.array(a, split=split)
    for name, np_fn in UNARY_OPS:
        fn = getattr(ht, name)
        np.testing.assert_allclose(
            fn(x).numpy(), np_fn(a), rtol=3e-5, atol=1e-6,
            err_msg=f"{name} split={split}",
        )


def test_promotion_grid():
    pairs = [
        (np.float32, np.float64, np.float64),
        (np.int32, np.float32, np.float32),
        (np.int32, np.int64, np.int64),
        (np.uint8, np.int32, np.int32),
        (np.float32, np.float32, np.float32),
    ]
    for da, db, want in pairs:
        a = ht.array(np.ones((3, 3), da))
        b = ht.array(np.ones((3, 3), db))
        got = (a + b).dtype.jax_type()
        assert np.dtype(got) == np.dtype(want), (da, db, got)


@pytest.mark.parametrize("split", [None, 0, 1])
def test_uneven_extents_match_numpy(split):
    # 13 and 10 do not divide the 8-device mesh: pad-and-mask correctness
    rng = np.random.default_rng(5)
    a = rng.standard_normal((13, 10)).astype(np.float32)
    b = rng.standard_normal((13, 10)).astype(np.float32)
    for name in ("add", "mul", "div", "pow"):
        bb = np.abs(b) + 0.5 if name in ("div", "pow") else b
        got = getattr(ht, name)(ht.array(a, split=split), ht.array(bb, split=split))
        np.testing.assert_allclose(
            got.numpy(), getattr(np, {"div": "divide", "mul": "multiply"}.get(name, name))(a, bb),
            rtol=3e-5, err_msg=f"{name} split={split}",
        )
