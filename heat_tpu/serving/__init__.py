"""Online serving layer: fitted estimators as a low-latency service.

The heat L5 estimator API (PAPER.md §1) fits and predicts inside one
batch program; this subsystem turns a *fitted* estimator into an online
inference service — the "heavy traffic from millions of users" scenario
the north star names — by composing four existing layers that had never
met:

* the **dispatch executable cache** (PR 1) makes a repeated predict
  shape an amortized-zero-compile launch; the request **coalescer**
  (:mod:`~heat_tpu.serving.coalescer`) + pad-to-bucket shapes
  (:func:`heat_tpu.core.dispatch.batch_bucket`) make every traffic mix
  a repeated shape;
* the **Checkpointer** (PR 2/8) is the model store; the **registry**
  (:mod:`~heat_tpu.serving.registry`) hot-loads named, versioned
  estimators from it — asynchronously, cross-world (fit at world P,
  serve at world Q), with atomic zero-downtime promote/rollback;
* the **metrics registry** (PR 4) drives **admission control**
  (:mod:`~heat_tpu.serving.admission`): per-tenant token buckets and a
  bounded queue shed overload with a typed
  :class:`~heat_tpu.resilience.errors.OverloadedError` (429) instead
  of collapsing, with p50/p99 latency and queue-depth gauges scraped
  from ``/metrics``;
* the **introspection HTTP server** (PR 6) carries the service's
  ``/v1/models``, ``/v1/predict`` and per-model ``/healthz`` routes
  (:mod:`~heat_tpu.serving.service`) through the new route-registry
  hook — one process, one port.

Quick start::

    import heat_tpu as ht
    from heat_tpu import serving

    km = ht.cluster.KMeans(n_clusters=8).fit(x)
    serving.save_model(km, "/models/segmenter", version=1)

    svc = serving.InferenceService()
    svc.load("segmenter", "/models/segmenter")
    labels = svc.predict("segmenter", rows)        # coalesced + cached
    url = svc.serve(8080)                          # ...or over HTTP

See ``docs/serving.md`` for the registry lifecycle, coalescing
semantics, quota knobs and curl examples.
"""

from __future__ import annotations

from ..resilience.errors import OverloadedError, PreemptedError
from .admission import QOS_CLASSES, AdmissionController, TokenBucket
from .canary import CanaryController
from .coalescer import ModelBatcher, effective_deadline, take_edf_batch
from .model_io import (
    SUPPORTED_KINDS,
    build_estimator,
    export_state,
    save_model,
)
from .registry import ModelRegistry, PendingLoad
from .service import (
    InferenceService,
    default_service,
    start_serving,
    stop_serving,
)

__all__ = [
    "AdmissionController",
    "CanaryController",
    "InferenceService",
    "ModelBatcher",
    "ModelRegistry",
    "OverloadedError",
    "PendingLoad",
    "PreemptedError",
    "QOS_CLASSES",
    "SUPPORTED_KINDS",
    "TokenBucket",
    "build_estimator",
    "default_service",
    "effective_deadline",
    "export_state",
    "save_model",
    "start_serving",
    "stop_serving",
    "take_edf_batch",
]
