"""Continuous-benchmark runner (reference: benchmarks/cb/main.py).

Usage::

    python benchmarks/cb/main.py              # full suite on the default device
    BENCH_SCALE=0.1 python benchmarks/cb/main.py   # scaled-down smoke run

Emits one JSON line per benchmark ({"bench", "seconds"}) plus a final
summary line; the reference pushes the same workloads through perun to a
Grafana dashboard (README.md:24).
"""

# flake8: noqa
import json
import os
import sys

_here = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, _here)
sys.path.insert(0, os.path.dirname(os.path.dirname(_here)))  # repo root

import heat_tpu as ht

ht.random.seed(12345)

from cluster import run_cluster_benchmarks
from linalg import run_linalg_benchmarks
from manipulations import run_manipulation_benchmarks
from monitor import RESULTS, sync_floor
from attention import run_attention_benchmarks
from fft import run_fft_benchmarks
from nn import run_nn_benchmarks
from preprocessing import run_preprocessing_benchmarks
from sparse import run_sparse_benchmarks


def main():
    scale = float(os.environ.get("BENCH_SCALE", "1.0"))
    print(json.dumps({"bench": "SYNC_FLOOR", "seconds": round(sync_floor(), 6)}))
    run_linalg_benchmarks(scale)
    run_cluster_benchmarks(scale)
    run_manipulation_benchmarks(scale)
    run_preprocessing_benchmarks(scale)
    run_nn_benchmarks(scale)
    run_attention_benchmarks(scale)
    run_fft_benchmarks(scale)
    run_sparse_benchmarks(scale)
    total = sum(r["seconds"] for r in RESULTS)
    print(json.dumps({"bench": "TOTAL", "seconds": round(total, 3), "count": len(RESULTS)}))


if __name__ == "__main__":
    main()
