"""Fused Pallas axis-pass for the planar FFT: both four-step DFT stages
plus the twiddle in ONE VMEM round-trip per tile.

Motivation (docs/fft_roofline.md): XLA's compiled 512³ planar fftn is
bandwidth-saturated — its own cost analysis reports 43.1 GB accessed per
transform (6.7× the 48 B/element minimum) and the measured time matches
that traffic at the measured stream rate, while the MXU idles at ~1% and
the (precision × radix) sweep moves the time ≤ 12%.  The only lever left
is moving fewer bytes.  This kernel reads each tile of the two planes
from HBM once, runs stage-A DFT → twiddle → stage-B DFT entirely in
VMEM, and writes once.

Mosaic layout discipline (a lane-moving reshape is not compilable):
``n = n1·n2`` picks ``n1`` = largest divisor ≤ 128 so the HBM view
``(B, n) -> (B, n2, n1)`` is a pure C-order view with n1 on the lanes,
j = j1 + n1·j2.  Writing the output index k = k2 + n2·k1:

    stage A (VPU): Y[b, k2, j1] = Σ_j2 x[b, j2, j1]·W_n2^{j2·k2}
        — an unrolled radix-n2 butterfly over the sublane groups
          (scalar complex constants; n2 ≤ 8)
    twiddle (VPU): Y *= W_n^{j1·k2}   (a (n2, n1) lane-vector constant)
    stage B (MXU): Z[b, k2, k1] = Σ_j1 Y[b, k2, j1]·W_n1[j1, k1]
        — contracts the LANE dim, K = n1 ≤ 128 deep, Karatsuba 3-mult

    Z's (k2, k1) block order is fixed OUTSIDE by one XLA transpose
    (flat(k1, k2) = n2·k1 + k2 = k), which the compiler can fuse with
    the surrounding axis moveaxis.

Real-input passes (the first axis of a real transform) never read or
fabricate an imaginary plane in HBM — stage A is the 2-mult form.
On non-TPU backends the kernel runs through the Pallas interpreter, so
the suite exercises the identical code path.  OPT-IN via
``HEAT_TPU_FFT_PALLAS=1`` — see :func:`_enabled` for the measured story.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

__all__ = ["eligible", "fused_axis_pass"]

_LANES = 128
_MAX_RADIX = 8  # stage-A unroll bound


def _enabled() -> bool:
    # OPT-IN (HEAT_TPU_FFT_PALLAS=1): measured on the bench v5e the fused
    # kernel moves 34% fewer bytes (XLA cost analysis 28.5 vs 43.1 GB per
    # 512^3 transform) but lands time-neutral (0.068 vs 0.065 s) — the
    # radix-n2 stage-A butterflies are VPU-bound on this chip's ~5-ops/
    # element-lane budget (the same balance that parks the Lloyd kernel,
    # core/kernels.py).  Kept correctness-tested for hardware with a
    # higher VPU:HBM ratio, per the "Pallas only if profiling demands"
    # policy; docs/fft_roofline.md carries the measurements.
    return os.environ.get("HEAT_TPU_FFT_PALLAS", "0") == "1"


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=512)
def _split_factors(n: int):
    """(n1, n2): n1 = largest divisor <= 128 (lane dim), n2 = n/n1 (the
    small stage-A radix); None when the pair does not exist."""
    best = None
    d = 1
    while d * d <= n:
        if n % d == 0:
            for f in (d, n // d):
                if f <= _LANES and (best is None or f > best):
                    best = f
        d += 1
    if best is None or best < 2:
        return None
    n1 = best
    n2 = n // n1
    if n2 > _MAX_RADIX:
        return None
    return n1, n2


def _tile_rows(batch: int) -> int:
    for bb in (256, 128, 64, 32, 16, 8, 4, 2, 1):
        if batch % bb == 0:
            return bb
    return 1


def eligible(n: int, batch: int, dtype) -> bool:
    """f32 planes, a (lane, small-radix) factor pair, non-empty batch."""
    return (
        _enabled()
        and dtype == jnp.float32
        and batch > 0
        and n >= 2
        and _split_factors(n) is not None
    )


def _consts(n: int, inverse: bool):
    n1, n2 = _split_factors(n)
    sign = 1.0 if inverse else -1.0
    # stage-A scalar butterfly constants W_n2^{j2 k2}
    ang2 = 2.0 * np.pi * (np.outer(np.arange(n2), np.arange(n2)) % n2) / max(n2, 1)
    c2re = np.cos(ang2)
    c2im = sign * np.sin(ang2)
    # lane twiddle W_n^{j1 k2}: shape (n2, n1), row k2
    angt = 2.0 * np.pi * (np.outer(np.arange(n2), np.arange(n1)) % n) / n
    twr = np.asarray(np.cos(angt), np.float32)
    twi = np.asarray(sign * np.sin(angt), np.float32)
    # stage-B DFT matrix (n1, n1)
    ang1 = 2.0 * np.pi * (np.outer(np.arange(n1), np.arange(n1)) % n1) / n1
    w1re = np.cos(ang1)
    w1im = sign * np.sin(ang1)
    w1 = (
        np.asarray(w1re, np.float32),
        np.asarray(w1im, np.float32),
        np.asarray(w1re + w1im, np.float32),
    )
    return n1, n2, c2re, c2im, (twr, twi), w1


def _dot_last(x, w, precision):
    """(bb, n2, n1) · (n1, m) contracting the LANE dim -> (bb, n2, m)."""
    return jax.lax.dot_general(
        x, w,
        dimension_numbers=(((2,), (0,)), ((), ())),
        precision=precision,
        preferred_element_type=jnp.float32,
    )


@functools.lru_cache(maxsize=128)
def _axis_pass_fn(n: int, batch: int, inverse: bool, have_im: bool, prec_name: str):
    n1, n2, c2re, c2im, tw, w1 = _consts(n, inverse)
    bb = _tile_rows(batch)
    precision = getattr(jax.lax.Precision, prec_name.upper())

    def kernel(*refs):
        if have_im:
            re_ref, im_ref, twr, twi, w1r, w1i, w1s, ore, oim = refs
        else:
            re_ref, twr, twi, w1r, w1i, w1s, ore, oim = refs
        xre = re_ref[...]  # (bb, n2, n1)
        xim = im_ref[...] if have_im else None

        # stage A: radix-n2 butterflies over the sublane groups, fused
        # with the lane twiddle; scalar constants fold at trace time
        rows_re, rows_im = [], []
        for k2 in range(n2):
            acc_re = acc_im = None
            for j2 in range(n2):
                cr = float(c2re[j2, k2])
                ci = float(c2im[j2, k2])
                xr = xre[:, j2, :]
                t_re = xr * cr
                t_im = xr * ci
                if have_im:
                    xi = xim[:, j2, :]
                    t_re = t_re - xi * ci
                    t_im = t_im + xi * cr
                acc_re = t_re if acc_re is None else acc_re + t_re
                acc_im = t_im if acc_im is None else acc_im + t_im
            tr = twr[k2, :]
            ti = twi[k2, :]
            rows_re.append((acc_re * tr - acc_im * ti)[:, None, :])
            rows_im.append((acc_re * ti + acc_im * tr)[:, None, :])
        yre = jnp.concatenate(rows_re, axis=1) if n2 > 1 else rows_re[0]
        yim = jnp.concatenate(rows_im, axis=1) if n2 > 1 else rows_im[0]

        # stage B: full-lane-depth MXU contraction (Karatsuba 3-mult)
        t1 = _dot_last(yre, w1r[...], precision)
        t2 = _dot_last(yim, w1i[...], precision)
        t3 = _dot_last(yre + yim, w1s[...], precision)
        ore[...] = t1 - t2
        oim[...] = t3 - t1 - t2

    grid = (batch // bb,)
    tile = pl.BlockSpec((bb, n2, n1), lambda i: (i, 0, 0))
    tw_spec = pl.BlockSpec((n2, n1), lambda i: (0, 0))
    w_spec = pl.BlockSpec((n1, n1), lambda i: (0, 0))
    in_specs = ([tile, tile] if have_im else [tile]) + [tw_spec, tw_spec, w_spec, w_spec, w_spec]
    out_shape = (
        jax.ShapeDtypeStruct((batch, n2, n1), jnp.float32),
        jax.ShapeDtypeStruct((batch, n2, n1), jnp.float32),
    )
    call = pl.pallas_call(
        kernel,
        out_shape=out_shape,
        grid=grid,
        in_specs=in_specs,
        out_specs=(tile, tile),
        interpret=_interpret(),
    )
    consts = (tw[0], tw[1], w1[0], w1[1], w1[2])

    def run(re, im=None):
        args = (re, im) if have_im else (re,)
        return call(*args, *consts)

    return run


def fused_axis_pass(re, im, inverse: bool, prec_name: str):
    """Last-axis planar DFT of (batch..., n) f32 planes through the fused
    kernel.  ``im=None`` means real input (no imaginary plane is read)."""
    n = int(re.shape[-1])
    n1, n2 = _split_factors(n)
    batch_dims = re.shape[:-1]
    batch = 1
    for s in batch_dims:
        batch *= int(s)
    r2 = re.reshape(batch, n2, n1)  # pure view: j = j1 + n1*j2
    i2 = im.reshape(batch, n2, n1) if im is not None else None
    fn = _axis_pass_fn(n, batch, bool(inverse), im is not None, prec_name)
    zre, zim = fn(r2, i2) if im is not None else fn(r2)
    # Z[b, k2, k1] -> X[k2 + n2*k1]: one transpose, fusable by XLA
    ore = zre.transpose(0, 2, 1).reshape(*batch_dims, n)
    oim = zim.transpose(0, 2, 1).reshape(*batch_dims, n)
    return ore, oim
