"""Real-pair (planar) FFT kernels: complex transforms on complex-less TPUs.

The tunneled TPU runtime rejects every complex64 op (see
``core.dndarray._tpu_complex_ok``), so the reference's transform semantics
(heat/fft/fft.py:40-298) are re-expressed over two REAL planes (re, im).
The transform itself is built to ride the MXU instead of translating a
butterfly network:

* length ``n <= _cutoff()``: the DFT is a literal matrix product with the
  (symmetric) DFT matrix — ``(batch, n) @ (n, n)`` per plane, a shape the
  systolic array is built for.  A complex matmul uses the 3-multiplication
  (Karatsuba) identity, and a purely real input (rfft, the first axis of a
  real fftn) needs only 2 products.
* larger ``n = n1 * n2``: Bailey's four-step factorization — reshape to
  ``(n2, n1)``, DFT the columns, twiddle, DFT the rows, transpose-ravel.
  Each factor recurses until it fits the matmul base case, so every FLOP
  is still a matrix product.
* prime ``n > _cutoff()``: Bluestein's chirp-z algorithm turns the DFT into
  a circular convolution of power-of-two length, which the four-step path
  handles; the chirp filter's spectrum is a host-precomputed constant.

Everything here is pure jnp on real dtypes — traceable, jittable, and
usable inside ``shard_map`` bodies (the pencil program in fft.py).
Accuracy: DFT matrices are built in float64 on the host and applied with a
precision-policy matmul (HIGHEST for f32 planes) — verified against
``np.fft.fftn`` to ~1e-4 relative for float32, full precision for float64.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ._weight_cache import byte_lru as _byte_lru

__all__ = [
    "fft_planes",
    "fftn_planes",
    "real_fftn",
    "scale_factor",
    "fft1",
    "rfft1",
    "irfft1",
    "hfft1",
    "ihfft1",
]

def _cutoff() -> int:
    """Largest DFT applied as one literal matrix product.  The r4
    floor-aware sweep (scripts/tune_fft.py, docs/fft_roofline.md) shows
    the 512³ transform is HBM-bound: the whole (precision × cutoff) grid
    spans only ±12%.  64 is kept for its MXU-friendly K-depth and 1.7e-7
    accuracy at the HIGHEST default; overridable by env for re-tuning on
    other hardware.  Read at call time so the knob participates in
    fft.py's program-cache key (a module-load snapshot would make the
    keyed retrace trace the stale value)."""
    return int(os.environ.get("HEAT_TPU_FFT_CUTOFF", "64"))


def _precision_name() -> str:
    return os.environ.get("HEAT_TPU_FFT_PRECISION", "highest").lower()


def _precision():
    # f32 planes want the 6-pass f32-accurate matmul; f64 planes hit the
    # (software) f64 path where precision flags do not apply
    return {
        "default": jax.lax.Precision.DEFAULT,
        "high": jax.lax.Precision.HIGH,
        "highest": jax.lax.Precision.HIGHEST,
    }[_precision_name()]


def _mm(a: jax.Array, w: jax.Array) -> jax.Array:
    return jnp.matmul(a, w, precision=_precision())


@_byte_lru
def _dft_w(n: int, inverse: bool, dtype: str):
    """(W_re, W_im, W_re+W_im) for the symmetric n-point DFT matrix."""
    j = np.arange(n, dtype=np.float64)
    # angle built from jk mod n keeps the argument small — cos/sin of huge
    # arguments lose the low bits that ARE the answer
    jk = np.outer(j, j) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    wre = np.cos(ang)
    wim = sign * np.sin(ang)
    # NUMPY constants: a jnp array built during a jit trace is a tracer,
    # and caching a tracer poisons every later trace (leak errors); numpy
    # operands are lifted fresh into whichever trace uses them
    return (
        np.asarray(wre, dtype),
        np.asarray(wim, dtype),
        np.asarray(wre + wim, dtype),
    )


@_byte_lru
def _dft_w2(n: int, inverse: bool, dtype: str):
    """(W_re, W_im) only — the direct-dot branch never needs the
    Karatsuba wsum plane, and at the 1024-point cap each cached wsum
    would be ~4 MB of never-read host memory."""
    j = np.arange(n, dtype=np.float64)
    jk = np.outer(j, j) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    return np.asarray(np.cos(ang), dtype), np.asarray(sign * np.sin(ang), dtype)


@_byte_lru
def _twiddle(n1: int, n2: int, n: int, inverse: bool, dtype: str):
    """T[j1, k2] = exp(sign * 2*pi*i * j1*k2 / n) for the four-step."""
    j1 = np.arange(n1, dtype=np.float64)
    k2 = np.arange(n2, dtype=np.float64)
    jk = np.outer(j1, k2) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    # numpy constants — see _dft_w for why
    return np.asarray(np.cos(ang), dtype), np.asarray(sign * np.sin(ang), dtype)


def _cmul(are, aim, bre, bim):
    """Elementwise planar complex multiply (a may have aim None == real)."""
    if aim is None:
        return are * bre, are * bim
    return are * bre - aim * bim, are * bim + aim * bre


def _apply_w(re, im, w) -> Tuple[jax.Array, jax.Array]:
    """(..., n) @ DFT matrix, 3-mult complex or 2-mult real-input."""
    wre, wim, wsum = w
    if im is None:
        return _mm(re, wre), _mm(re, wim)
    t1 = _mm(re, wre)
    t2 = _mm(im, wim)
    t3 = _mm(re + im, wsum)
    return t1 - t2, t3 - t1 - t2


@functools.lru_cache(maxsize=512)
def _largest_factor(n: int, cap: int) -> int:
    """Largest divisor of n that is <= cap (1 if n is prime past cap)."""
    best = 1
    d = 1
    while d * d <= n:
        if n % d == 0:
            if d <= cap:
                best = max(best, d)
            q = n // d
            if q <= cap:
                best = max(best, q)
        d += 1
    return best


def _next_pow2(n: int) -> int:
    m = 1
    while m < n:
        m <<= 1
    return m


def _einsum_w(spec: str, re, im, w) -> Tuple[jax.Array, jax.Array]:
    """Karatsuba complex DFT through an einsum spec (transpose folded
    into the dot_general instead of materialized between stages)."""
    wre, wim, wsum = w
    ein = functools.partial(jnp.einsum, spec, precision=_precision())
    if im is None:
        return ein(re, wre), ein(re, wim)
    t1 = ein(re, wre)
    t2 = ein(im, wim)
    t3 = ein(re + im, wsum)
    return t1 - t2, t3 - t1 - t2


def _direct_cap() -> int:
    """Largest n transformed as ONE direct DFT dot per plane (r5).

    The four-step chain materializes many intermediate passes; on the
    bench v5e a (16384, 1024) batched rfft measured 0.60 ms as two
    direct plane dots vs 2.18 ms through the chain (complex: 4-dot
    schoolbook beat the Karatsuba chain 3.11 -> ~1.3).  The O(n^2)
    extra MXU work is invisible below this cap because the transform is
    bandwidth-bound; the (n, n) plane matrices stay <= 4 MB."""
    return int(os.environ.get("HEAT_TPU_FFT_DIRECT_CAP", "1024"))


def _fft_last(re, im, inverse: bool) -> Tuple[jax.Array, jax.Array]:
    """Unscaled DFT along the LAST axis; im may be None (real input)."""
    n = re.shape[-1]
    dt = str(re.dtype)
    cutoff = _cutoff()
    if n == 1:
        return re, jnp.zeros_like(re) if im is None else im
    if n <= cutoff:
        return _apply_w(re, im, _dft_w(n, inverse, dt))
    use_direct = n <= _direct_cap() and re.dtype == jnp.float32
    if use_direct and os.environ.get("HEAT_TPU_FFT_PALLAS", "0") != "1":
        # direct plane dots (any n, primes included — below the cap the
        # Bluestein machinery is never needed): real input 2 dots,
        # complex 4-dot schoolbook — fewer materialized passes than
        # Karatsuba's triple + combines for batched minor-axis work.
        # An explicit HEAT_TPU_FFT_PALLAS=1 opt-in outranks this branch
        # (the fused-kernel path below must stay measurable).
        wre, wim = _dft_w2(n, inverse, dt)
        if im is None:
            return _mm(re, wre), _mm(re, wim)
        return _mm(re, wre) - _mm(im, wim), _mm(re, wim) + _mm(im, wre)
    n1 = _largest_factor(n, cutoff)
    if n1 == 1:
        return _bluestein_last(re, im, inverse)
    # fused Pallas axis pass (OPT-IN, time-neutral on the bench v5e —
    # docs/fft_roofline.md): both stages + twiddle in one VMEM round-trip;
    # import only behind the env gate so the XLA path never depends on
    # the pallas module being importable
    if re.dtype == jnp.float32 and os.environ.get("HEAT_TPU_FFT_PALLAS", "0") == "1":
        try:
            from . import _pallas_fft as _pf
        except ImportError:  # pragma: no cover - pallas-less jax build
            _pf = None
        b_el = 1
        for s in re.shape[:-1]:
            b_el *= int(s)
        if _pf is not None and b_el > 0 and _pf.eligible(n, b_el, re.dtype):
            return _pf.fused_axis_pass(re, im, inverse, _precision_name())
    n2 = n // n1
    batch = re.shape[:-1]
    if n2 <= cutoff:
        # single-level four-step fully inside two einsums: the stage
        # transposes ride the dot_general layouts instead of separate
        # transpose passes — the transform is HBM-bound on the bench chip
        # (see the _cutoff note), so bytes not moved are time saved.
        # j = j1 + n1*j2: x[..., j2, j1]; A: DFT over j2 -> [..., k2, j1]
        re = re.reshape(*batch, n2, n1)
        im = im.reshape(*batch, n2, n1) if im is not None else None
        re, im = _einsum_w("...ji,jk->...ki", re, im, _dft_w(n2, inverse, dt))
        tw_re, tw_im = _twiddle(n1, n2, n, inverse, dt)  # [j1, k2]
        re, im = _cmul(re, im, tw_re.T, tw_im.T)  # planes are [..., k2, j1]
        # B: DFT over j1, output laid out [..., k1, k2] so the C-order
        # ravel IS the k = k2 + n2*k1 output order
        re, im = _einsum_w("...kj,jl->...lk", re, im, _dft_w(n1, inverse, dt))
        return re.reshape(*batch, n), im.reshape(*batch, n)
    # deep factorization: recursive swapaxes formulation
    # j = j1 + n1*j2: C-order reshape puts x[j] at [..., j2, j1]
    re = re.reshape(*batch, n2, n1).swapaxes(-1, -2)  # (..., j1, j2)
    im = im.reshape(*batch, n2, n1).swapaxes(-1, -2) if im is not None else None
    re, im = _fft_last(re, im, inverse)  # DFT over j2 -> (..., j1, k2)
    re, im = _cmul(re, im, *_twiddle(n1, n2, n, inverse, dt))
    re = re.swapaxes(-1, -2)  # (..., k2, j1)
    im = im.swapaxes(-1, -2)
    re, im = _fft_last(re, im, inverse)  # DFT over j1 -> (..., k2, k1)
    # output index k = k2 + n2*k1: ravel of the (k1, k2) layout
    re = re.swapaxes(-1, -2).reshape(*batch, n)
    im = im.swapaxes(-1, -2).reshape(*batch, n)
    return re, im


@_byte_lru
def _bluestein_consts(n: int, inverse: bool, dtype: str):
    """Chirp and the precomputed spectrum of the chirp filter."""
    m = _next_pow2(2 * n - 1)
    j = np.arange(n, dtype=np.int64)
    # j^2 mod 2n keeps the chirp angle small and exact
    ang = np.pi * ((j * j) % (2 * n)).astype(np.float64) / n
    sign = 1.0 if inverse else -1.0
    # c[j] = e^{sign*i*pi*j^2/n}: c[j]*c[k]*conj(c[k-j]) = e^{sign*2*pi*i*jk/n}
    chirp = np.cos(ang) + 1j * sign * np.sin(ang)
    a_mul = chirp  # applied to the input and to the output
    b = np.zeros(m, dtype=np.complex128)
    conj_c = np.conj(chirp)
    b[:n] = conj_c
    b[m - n + 1:] = conj_c[1:n][::-1]  # b[m-j] = conj(c[j])
    B = np.fft.fft(b)  # host constant — never touches the device
    # numpy constants — see _dft_w for why
    return (
        np.asarray(a_mul.real, dtype),
        np.asarray(a_mul.imag, dtype),
        np.asarray(B.real, dtype),
        np.asarray(B.imag, dtype),
        m,
    )


def _bluestein_last(re, im, inverse: bool) -> Tuple[jax.Array, jax.Array]:
    """Chirp-z DFT for prime n past the matmul cutoff (last axis)."""
    n = re.shape[-1]
    are, aim, Bre, Bim, m = _bluestein_consts(n, inverse, str(re.dtype))
    xre, xim = _cmul(re, im, are, aim)
    pad = [(0, 0)] * (xre.ndim - 1) + [(0, m - n)]
    xre, xim = jnp.pad(xre, pad), jnp.pad(xim, pad)
    Xre, Xim = _fft_last(xre, xim, False)  # m is a power of two -> four-step
    Cre, Cim = _cmul(Xre, Xim, Bre, Bim)
    cre, cim = _fft_last(Cre, Cim, True)
    cre, cim = cre[..., :n] / m, cim[..., :n] / m  # unscaled inverse
    return _cmul(cre, cim, are, aim)


def fft_planes(
    re: jax.Array,
    im: Optional[jax.Array],
    axis: int,
    inverse: bool,
) -> Tuple[jax.Array, jax.Array]:
    """Unscaled planar DFT along ``axis``; ``im=None`` means real input."""
    axis = axis % re.ndim
    last = re.ndim - 1
    if axis != last:
        re = jnp.moveaxis(re, axis, last)
        im = jnp.moveaxis(im, axis, last) if im is not None else None
    re, im = _fft_last(re, im, inverse)
    if axis != last:
        re = jnp.moveaxis(re, last, axis)
        im = jnp.moveaxis(im, last, axis)
    return re, im


def scale_factor(lengths: Sequence[int], norm: Optional[str], inverse: bool) -> float:
    """Composite normalization over the transformed axis lengths."""
    total = 1.0
    for n in lengths:
        total *= float(n)
    if norm in (None, "backward"):
        return 1.0 / total if inverse else 1.0
    if norm == "ortho":
        return total ** -0.5
    if norm == "forward":
        return 1.0 if inverse else 1.0 / total
    raise ValueError(f'norm must be None, "ortho", "backward" or "forward", got {norm!r}')


def fftn_planes(
    re: jax.Array,
    im: Optional[jax.Array],
    axes: Sequence[int],
    inverse: bool,
    norm: Optional[str],
) -> Tuple[jax.Array, jax.Array]:
    """Planar N-D DFT over ``axes`` with numpy norm semantics applied."""
    for ax in axes:
        re, im = fft_planes(re, im, ax, inverse)
    s = scale_factor([re.shape[a] for a in axes], norm, inverse)
    if s != 1.0:
        re, im = re * re.dtype.type(s), im * im.dtype.type(s)
    return re, im


# ----------------------------------------------------------------------
# interleaved-minor 3-D real FFT (r5).  The r4 roofline showed the planar
# Karatsuba path schedules 43.1 GB for a 512^3 transform (6.7x the 48 B/el
# minimal model): every DFT stage was 3 dots + combines + a twiddle pass.
# This path stores the complex pair INSIDE the minor dim — z[..., 2k+c] —
# so one real matmul against the 2x2-block DFT matrix IS the whole stage:
#
#   pass Z   x (n0,n1,n2) @ Wr(n2, 2m2)          -> (n0, n1, 2m2)
#   T1       re-pair transpose                   -> (m2, n1, 2n0)
#   pass X   @ W2(2n0, 2n0)                      -> (m2, n1, 2k0)
#   T2       swap middle/minor pairs             -> (m2, k0, 2n1)
#   pass Y   @ W2re / @ W2im (two dots)          -> re, im (m2, k0, k1)
#   final    rotate to (k0, k1, m2) + Hermitian upper half (flip/concat)
#
# Measured on the bench v5e at 512^3 f32: 16.7 GB scheduled (vs 43.1),
# 34.6 ms (vs 65.4) — and the 2x2-block form never materializes a
# trailing dim of 2 (TPU tiling pads minor dims to 128 lanes: a (...,2)
# tensor occupies 64x its logical bytes; round-A experiments died on it).
# Matmul precision: HIGH (compensated bf16x3, ~2.5e-5 relative at 512^3)
# unless HEAT_TPU_FFT_PRECISION overrides — the 6-pass HIGHEST policy
# doubles MXU time for accuracy below the truncation any consumer of a
# single-precision transform already accepts.
# ----------------------------------------------------------------------
@_byte_lru
def _w2_full(n: int, inverse: bool, dtype: str):
    """(2n, 2n) interleaved real form of the complex DFT matrix."""
    wre, wim = _dft_w(n, inverse, "float64")[:2]
    W = np.zeros((n, 2, n, 2), np.float64)
    W[:, 0, :, 0] = wre
    W[:, 1, :, 0] = -wim
    W[:, 0, :, 1] = wim
    W[:, 1, :, 1] = wre
    return np.asarray(W.reshape(2 * n, 2 * n), dtype)


@_byte_lru
def _w2_real_in(n: int, m: int, dtype: str):
    """(n, 2m) real-input DFT matrix truncated at the Nyquist bin."""
    wre, wim = _dft_w(n, False, "float64")[:2]
    W = np.stack([wre[:, :m], wim[:, :m]], axis=-1)  # (n, m, 2)
    return np.asarray(W.reshape(n, 2 * m), dtype)


@_byte_lru
def _w2_split(n: int, dtype: str, inverse: bool = False):
    """(2n, n) re and im column blocks of the full interleaved matrix."""
    W = _w2_full(n, inverse, dtype)
    return (
        np.ascontiguousarray(W[:, 0::2]),
        np.ascontiguousarray(W[:, 1::2]),
    )


@_byte_lru
def _w2_row_split(n: int, dtype: str, inverse: bool = False):
    """(n, 2n) row blocks applying the DFT to a SEPARATE re / im plane:
    out_interleaved = re @ rows_re + im @ rows_im — the plane pair enters
    the interleaved representation through the first dot, never through a
    materialized (..., 2) stack (the tiling trap)."""
    W = _w2_full(n, inverse, dtype)
    return (
        np.ascontiguousarray(W[0::2, :]),
        np.ascontiguousarray(W[1::2, :]),
    )


def _interleaved_precision():
    from ..core._env import precision_from_env

    return precision_from_env("HEAT_TPU_FFT_PRECISION", "high")


def _revax(a: jax.Array, ax: int) -> jax.Array:
    """Index map i -> (-i) mod n along ``ax``."""
    return jnp.concatenate(
        [
            jax.lax.slice_in_dim(a, 0, 1, axis=ax),
            jnp.flip(jax.lax.slice_in_dim(a, 1, a.shape[ax], axis=ax), ax),
        ],
        ax,
    )


def hermitian_upper(p: jax.Array, rows: int) -> jax.Array:
    """Upper-half mirror of a leading-axis half spectrum: rows 1..rows of
    ``p`` evaluated at ``p[n0-k0, (n1-k1)%n1, (n2-k2)%n2]`` — one roll +
    one multi-axis ``lax.rev`` (rev = roll o flip; the chained
    revax/concat formulation measured 1.8x slower on the bench chip).
    Negate the result for the imaginary plane.  Shared by the
    interleaved engine and the leading engine's XLA extension fallback."""
    u = p[1 : rows + 1]
    return jax.lax.rev(jnp.roll(u, (-1, -1), (1, 2)), (0, 1, 2))


def _mm_merged(a: jax.Array, w, prec) -> jax.Array:
    """One matmul along the merged minor dim (the whole DFT stage)."""
    return jax.lax.dot_general(
        a.reshape(-1, a.shape[-1]), jnp.asarray(w), (((1,), (0,)), ((), ())),
        precision=prec,
    ).reshape(*a.shape[:-1], w.shape[1])


def _mid_and_exit(z, n0: int, n1: int, inverse: bool, dt: str, prec):
    """Shared stage-X / stage-Y / exit pipeline of both interleaved
    engines: z (lead, n1, 2n0) -> re, im planes (k0, k1, lead)."""
    lead = int(z.shape[0])
    z = _mm_merged(z, _w2_full(n0, inverse, dt), prec)  # (lead, n1, 2k0)
    z = z.reshape(lead, n1, n0, 2).transpose(0, 2, 1, 3).reshape(lead, n0, 2 * n1)
    wre, wim = _w2_split(n1, dt, inverse)
    re = _mm_merged(z, wre, prec).transpose(1, 2, 0)  # (k0, k1, lead)
    im = _mm_merged(z, wim, prec).transpose(1, 2, 0)
    return re, im


def _rfft3_half(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """Half spectrum (k0, k1, n2//2+1) of a real (n0, n1, n2) array —
    the shared core of fftn (extension follows) and rfftn (this IS the
    result).  Scaling commutes with the linear Hermitian extension, so
    it is applied here once."""
    n0, n1, n2 = (int(s) for s in x.shape)
    m2 = n2 // 2 + 1
    dt = str(x.dtype)
    prec = _interleaved_precision()
    z = _mm_merged(x, _w2_real_in(n2, m2, dt), prec)  # (n0, n1, 2m2)
    z = z.reshape(n0, n1, m2, 2).transpose(2, 1, 0, 3).reshape(m2, n1, 2 * n0)
    re, im = _mid_and_exit(z, n0, n1, False, dt, prec)  # (k0, k1, m2)
    return _scaled(re, im, scale_factor([n0, n1, n2], norm, False))


def _rfft3_interleaved(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """Full 3-D spectrum of a real (n0, n1, n2) array, all axes.

    Unlike :func:`_rfft3_half` (numpy rfftn halves the LAST axis), the
    full transform may halve ANY axis — halving axis 0 lets the exit
    dots land the final (k0, k1, k2) orientation directly (no rotate
    transpose) and turns the Hermitian extension into a LEADING-axis
    slab concat.  Measured on the bench chip at 512^3: 27.6 ms vs the
    shared-core-then-extend formulation's 30.5 (13.5 GB scheduled vs
    16.7); a variant absorbing the k2 reversal into extra rev-column
    exit dots measured 28.8 — the extra MXU passes cost more than the
    saved relayout (docs/round5_notes.md)."""
    n0, n1, n2 = (int(s) for s in x.shape)
    m0 = n0 // 2 + 1
    dt = str(x.dtype)
    prec = _interleaved_precision()
    W = jnp.asarray(_w2_real_in(n0, m0, dt))
    z = jax.lax.dot_general(x, W, (((0,), (0,)), ((), ())), precision=prec)
    z = z.reshape(n1, n2, m0, 2).transpose(2, 1, 0, 3).reshape(m0, n2, 2 * n1)
    z = _mm_merged(z, _w2_full(n1, False, dt), prec)  # (m0, n2, 2k1)
    z = z.reshape(m0, n2, n1, 2).transpose(0, 2, 1, 3).reshape(m0, n1, 2 * n2)
    wre, wim = _w2_split(n2, dt)
    re_lo = _mm_merged(z, wre, prec)  # (m0, k1, k2)
    im_lo = _mm_merged(z, wim, prec)

    def upper(p):
        return hermitian_upper(p, n0 - m0)

    re = jnp.concatenate([re_lo, upper(re_lo)], 0)
    im = jnp.concatenate([im_lo, -upper(im_lo)], 0)
    return _scaled(re, im, scale_factor([n0, n1, n2], norm, False))


def rfft3_half_interleaved(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """numpy ``rfftn`` semantics for 3-D real input, all axes: the
    shared half-spectrum core (:func:`_rfft3_half`) — rfftn stops where
    fftn's Hermitian extension would begin, so it is strictly cheaper."""
    return _rfft3_half(x, norm)


@_byte_lru
def _w_irfft_exit(m_used: int, n_out: int, dtype: str):
    """(2*m_used, n_out) c2r exit matrix: the Hermitian extension IS the
    matrix.  out[x] = sum_k w_k (re_k cos(2pi k x / n) - im_k sin(...))
    with w_k = 2 for interior bins (each conjugate pair contributes
    twice) and 1 for DC and (even n) Nyquist; the sin rows are zero at
    DC/Nyquist, reproducing numpy's c2r indifference to those bins'
    imaginary parts.  Unscaled (norm handled by scale_factor)."""
    k = np.arange(m_used, dtype=np.float64)
    x = np.arange(n_out, dtype=np.float64)
    ang = 2.0 * np.pi * np.outer(k, x) / n_out
    w = np.full(m_used, 2.0)
    w[0] = 1.0
    if n_out % 2 == 0 and m_used == n_out // 2 + 1:
        w[-1] = 1.0
    W = np.zeros((m_used, 2, n_out), np.float64)
    W[:, 0, :] = w[:, None] * np.cos(ang)
    W[:, 1, :] = -w[:, None] * np.sin(ang)
    return np.asarray(W.reshape(2 * m_used, n_out), dtype)


def irfft3_interleaved(
    re: jax.Array, im: jax.Array, n_out: int, norm
) -> jax.Array:
    """numpy ``irfftn`` semantics: half spectrum (n0, n1, m2) -> real
    (n0, n1, n_out).

    numpy's own composition order (inverse transforms over axes 0, 1
    FIRST — on the thin half spectrum, half the traffic of extending
    first — then the 1-D c2r along axis 2), with the Hermitian extension
    folded into the exit MATRIX (`_w_irfft_exit`): no extension pass, no
    final rotate, and the real-only output falls out of one dot."""
    n0, n1, m2 = (int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _interleaved_precision()
    m_used = n_out // 2 + 1
    re, im = _fit(re, im, 2, m_used)
    # axis-0 inverse: entry over the minor after a thin pre-transpose
    reT = re.transpose(1, 2, 0)  # (n1, mu, n0)
    imT = im.transpose(1, 2, 0)
    rrow, irow = _w2_row_split(n0, dt, True)
    z = _mm_merged(reT, rrow, prec) + _mm_merged(imT, irow, prec)  # (n1, mu, 2k0)
    z = z.reshape(n1, m_used, n0, 2).transpose(2, 1, 0, 3).reshape(n0, m_used, 2 * n1)
    z = _mm_merged(z, _w2_full(n1, True, dt), prec)  # (k0, mu, 2k1)
    z = z.reshape(n0, m_used, n1, 2).transpose(0, 2, 1, 3).reshape(n0, n1, 2 * m_used)
    out = _mm_merged(z, _w_irfft_exit(m_used, n_out, dt), prec)  # (k0, k1, n_out)
    return _scaled(out, None, scale_factor([n0, n1, n_out], norm, True))[0]


def cfft3_interleaved(
    re: jax.Array, im: jax.Array, inverse: bool, norm
) -> Tuple[jax.Array, jax.Array]:
    """Full 3-D transform of a COMPLEX (re, im) plane pair, all axes.

    Same engine as :func:`_rfft3_interleaved` without the Hermitian
    half-spectrum: the planes enter the interleaved representation
    through the first dot's row-split matrices and leave it through the
    last dot's column-split matrices, so no (..., 2) tensor ever
    materializes."""
    n0, n1, n2 = (int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _interleaved_precision()

    rrow, irow = _w2_row_split(n2, dt, inverse)
    z = _mm_merged(re, rrow, prec) + _mm_merged(im, irow, prec)  # (n0, n1, 2k2)
    z = z.reshape(n0, n1, n2, 2).transpose(2, 1, 0, 3).reshape(n2, n1, 2 * n0)
    re_o, im_o = _mid_and_exit(z, n0, n1, inverse, dt, prec)  # (k0, k1, k2)
    return _scaled(re_o, im_o, scale_factor([n0, n1, n2], norm, inverse))


# ----------------------------------------------------------------------
# 2-D variants of the same engine (entry dot -> one re-pair transpose ->
# exit dots; extension/c2r folded like the 3-D paths)
# ----------------------------------------------------------------------
def cfft2_interleaved(re, im, inverse: bool, norm):
    """Full 2-D transform of a complex plane pair, both axes."""
    n0, n1 = (int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _interleaved_precision()
    reT, imT = re.T, im.T  # (n1, n0): entry over axis 0
    rrow, irow = _w2_row_split(n0, dt, inverse)
    z = _mm_merged(reT, rrow, prec) + _mm_merged(imT, irow, prec)  # (n1, 2k0)
    z = z.reshape(n1, n0, 2).transpose(1, 0, 2).reshape(n0, 2 * n1)
    wre, wim = _w2_split(n1, dt, inverse)
    re_o = _mm_merged(z, wre, prec)  # (k0, k1)
    im_o = _mm_merged(z, wim, prec)
    return _scaled(re_o, im_o, scale_factor([n0, n1], norm, inverse))


def rfft2_half_interleaved(x, norm):
    """numpy ``rfft2``: real (n0, n1) -> (k0, n1//2+1)."""
    n0, n1 = (int(s) for s in x.shape)
    m1 = n1 // 2 + 1
    dt = str(x.dtype)
    prec = _interleaved_precision()
    z = _mm_merged(x, _w2_real_in(n1, m1, dt), prec)  # (n0, 2m1)
    z = z.reshape(n0, m1, 2).transpose(1, 0, 2).reshape(m1, 2 * n0)
    wre, wim = _w2_split(n0, dt)
    re = _mm_merged(z, wre, prec).T  # (k0, m1)
    im = _mm_merged(z, wim, prec).T
    return _scaled(re, im, scale_factor([n0, n1], norm, False))


def rfft2_full_interleaved(x, norm):
    """Full 2-D spectrum of a real array: half + Hermitian extension
    along the minor axis (full[x, k] = conj(full[rev x, n1-k]))."""
    n0, n1 = (int(s) for s in x.shape)
    m1 = n1 // 2 + 1
    re_lo, im_lo = rfft2_half_interleaved(x, norm)

    def upper(p):
        u = p[:, 1 : n1 - m1 + 1]
        return jax.lax.rev(jnp.roll(u, -1, 0), (0, 1))

    re = jnp.concatenate([re_lo, upper(re_lo)], 1)
    im = jnp.concatenate([im_lo, -upper(im_lo)], 1)
    return re, im


def irfft2_interleaved(re, im, n_out: int, norm):
    """numpy ``irfft2``: half spectrum (n0, m1) -> real (n0, n_out),
    numpy's inverse-then-c2r order with the c2r exit matrix."""
    n0, m1 = (int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _interleaved_precision()
    m_used = n_out // 2 + 1
    re, im = _fit(re, im, 1, m_used)
    reT, imT = re.T, im.T  # (mu, n0): entry over axis 0
    rrow, irow = _w2_row_split(n0, dt, True)
    z = _mm_merged(reT, rrow, prec) + _mm_merged(imT, irow, prec)  # (mu, 2k0)
    z = z.reshape(m_used, n0, 2).transpose(1, 0, 2).reshape(n0, 2 * m_used)
    out = _mm_merged(z, _w_irfft_exit(m_used, n_out, dt), prec)  # (k0, n_out)
    return _scaled(out, None, scale_factor([n0, n_out], norm, True))[0]


def _interleaved_eligible(re: jax.Array, axes) -> bool:
    if os.environ.get("HEAT_TPU_FFT_INTERLEAVED", "1") != "1":
        return False
    nd = re.ndim
    # every engine below builds its weights from a dtype string, so f64
    # rides the same dots (native on CPU/GPU, hi/lo split in _leading on
    # TPU); other dtypes keep the per-axis fallback
    return (
        nd in (2, 3)
        and len(axes) == nd
        and re.dtype in (jnp.float32, jnp.float64)
        and sorted(a % nd for a in axes) == list(range(nd))
        and all(int(s) >= 2 for s in re.shape)
    )


def real_fftn(re: jax.Array, axes: Sequence[int], norm) -> Tuple[jax.Array, jax.Array]:
    """Full N-D FFT of a REAL array via half-spectrum + Hermitian extension.

    A real input's spectrum obeys X[k] = conj(X[-k]) over the transformed
    axes, so only n//2+1 bins of the last axis are computed through the
    remaining axes (~40% less MXU work for 3-D) and the upper half is a
    conjugated reverse-gather — one bandwidth pass.  The 3-D all-axes f32
    case takes the interleaved one-dot-per-stage path above (2.6x fewer
    scheduled bytes, measured; axis order is irrelevant for a separable
    full-length transform); the 2-D all-axes case its two-stage variant."""
    if _interleaved_eligible(re, axes):
        from . import _leading

        if _leading.leading_eligible(re, axes, False):
            if re.ndim == 3:
                return _leading.rfft3_leading(re, norm)
            return _leading.rfft2_leading(re, norm)
        if re.ndim == 3:
            return _rfft3_interleaved(re, norm)
        return rfft2_full_interleaved(re, norm)
    axes = [a % re.ndim for a in axes]
    al = axes[-1]
    n = re.shape[al]
    m = n // 2 + 1
    fre, fim = fft_planes(re, None, al, False)
    sl = tuple(slice(0, m) if d == al else slice(None) for d in range(re.ndim))
    fre, fim = fre[sl], fim[sl]
    for ax in axes[:-1]:
        fre, fim = fft_planes(fre, fim, ax, False)
    # upper half along the last axis: X[.., k] = conj(X[rev(..), n-k])
    src_last = np.asarray(n - np.arange(m, n))  # in [1, n-m]
    sub_re = jnp.take(fre, src_last, axis=al)
    sub_im = jnp.take(fim, src_last, axis=al)
    for ax in axes[:-1]:
        length = fre.shape[ax]
        rev = np.concatenate([[0], np.arange(length - 1, 0, -1)])
        sub_re = jnp.take(sub_re, rev, axis=ax)
        sub_im = jnp.take(sub_im, rev, axis=ax)
    full_re = jnp.concatenate([fre, sub_re], axis=al)
    full_im = jnp.concatenate([fim, -sub_im], axis=al)
    lengths = [re.shape[a] for a in axes]
    return _scaled(full_re, full_im, scale_factor(lengths, norm, False))


# ----------------------------------------------------------------------
# numpy-semantics 1-D ops on planes (fitting, real/Hermitian kinds, norms)
# ----------------------------------------------------------------------
def _fit(re, im, axis: int, n: int):
    """Truncate / zero-pad planes along ``axis`` to length ``n`` (numpy's
    pre-transform ``n`` semantics)."""
    axis = axis % re.ndim
    cur = re.shape[axis]
    if n == cur:
        return re, im
    if n < cur:
        sl = tuple(slice(0, n) if d == axis else slice(None) for d in range(re.ndim))
        return re[sl], None if im is None else im[sl]
    widths = [(0, n - cur) if d == axis else (0, 0) for d in range(re.ndim)]
    return jnp.pad(re, widths), None if im is None else jnp.pad(im, widths)


def _scaled(re, im, s: float):
    if s == 1.0:
        return re, im
    return re * re.dtype.type(s), None if im is None else im * im.dtype.type(s)


def _take(plane, axis: int, idx):
    return jnp.take(plane, idx, axis=axis)


def _hermitian_extend(re, im, axis: int, n_out: int):
    """Full-length spectrum from its first ``n_out//2+1`` bins.

    b[k] = a[k] for k < m, b[k] = conj(a[n_out-k]) above — numpy's implicit
    extension in irfft/hfft."""
    axis = axis % re.ndim
    m = n_out // 2 + 1
    re, im = _fit(re, im, axis, m)
    if im is None:
        im = jnp.zeros_like(re)
    ext_idx = jnp.arange(1, n_out - m + 1)[::-1]
    re_full = jnp.concatenate([re, _take(re, axis, ext_idx)], axis=axis)
    im_full = jnp.concatenate([im, -_take(im, axis, ext_idx)], axis=axis)
    return re_full, im_full


def fft1(re, im, axis: int, n: Optional[int], norm, inverse: bool):
    """numpy fft/ifft semantics on planes (complex in, complex out)."""
    n = n if n is not None else re.shape[axis]
    re, im = _fit(re, im, axis, n)
    re, im = fft_planes(re, im, axis, inverse)
    return _scaled(re, im, scale_factor([n], norm, inverse))


def rfft1(re, axis: int, n: Optional[int], norm):
    """numpy rfft: real input, spectrum truncated at Nyquist."""
    axis = axis % re.ndim
    n = n if n is not None else re.shape[axis]
    re, _ = _fit(re, None, axis, n)
    fre, fim = fft_planes(re, None, axis, False)
    m = n // 2 + 1
    sl = tuple(slice(0, m) if d == axis else slice(None) for d in range(fre.ndim))
    return _scaled(fre[sl], fim[sl], scale_factor([n], norm, False))


def irfft1(re, im, axis: int, n: Optional[int], norm):
    """numpy irfft: Hermitian-extend, inverse transform, real output."""
    n_out = n if n is not None else 2 * (re.shape[axis] - 1)
    re_f, im_f = _hermitian_extend(re, im, axis, n_out)
    ore, _ = fft_planes(re_f, im_f, axis, True)
    s = scale_factor([n_out], norm, True)
    return ore * ore.dtype.type(s) if s != 1.0 else ore


def hfft1(re, im, axis: int, n: Optional[int], norm):
    """numpy hfft: forward transform of the Hermitian-extended signal,
    real output, forward-family norm scaling (None->1, ortho->1/sqrt,
    forward->1/n — verified against np.fft.hfft)."""
    n_out = n if n is not None else 2 * (re.shape[axis] - 1)
    re_f, im_f = _hermitian_extend(re, im, axis, n_out)
    ore, _ = fft_planes(re_f, im_f, axis, False)
    s = scale_factor([n_out], norm, False)
    return ore * ore.dtype.type(s) if s != 1.0 else ore


def ihfft1(re, axis: int, n: Optional[int], norm):
    """numpy ihfft == conj(rfft)/n with inverse-family norm scaling."""
    n_in = n if n is not None else re.shape[axis]
    fre, fim = rfft1(re, axis, n_in, None)
    fre, fim = _scaled(fre, fim, scale_factor([n_in], norm, True))
    return fre, -fim
