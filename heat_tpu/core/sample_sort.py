"""Distributed sample-sort (PSRS) as a shard_map collective program.

The analog of the reference's parallel sample-sort behind ``ht.sort``
(heat/core/manipulations.py:2497-2750: local sort -> gathered pivots ->
Alltoallv exchange -> local merge).  The TPU-native formulation keeps every
buffer statically shaped and carries TWO planes per element:

* a **key plane** of order bits — a uint32/uint64 whose unsigned order
  equals the value order (sign-flip trick for floats, sign-bit XOR for
  ints; every NaN pattern maps to the max key so NaNs sort last like
  numpy), inverted for descending sorts;
* a **gid plane** of global indices — the tie-breaker that makes every
  (key, gid) pair DISTINCT, so the classic PSRS bucket bound (no bucket
  exceeds 2B for distinct keys, Shi & Schaeffer 1992) holds
  unconditionally, even for all-equal inputs, and ties resolve exactly
  like a stable sort.

Compared to round 2's single-u64 packing, the pair representation needs
no 64-bit integer type for 32-bit dtypes (the x64 gate is gone), covers
f64/i64/u64 (64-bit keys, x64 on) and f16/bf16 (via f32 keys), supports
descending, and batches over trailing dims (n-D arrays split along the
sort axis), per VERDICT r2 #4.

Pipeline (per batch column, all columns vectorized in one program):
1. pack -> 2. local stable sort by (key, gid) -> 3. p regular samples,
one all_gather, replicated pivot pairs -> 4. lexicographic bucketing +
scatter into a (p, B) send buffer, one ``all_to_all`` -> 5. merge via
``top_k`` on the order-reversed key plane (2B bound) + an LSD two-pass
argsort for pair order -> 6. exact-rank rebalance via a second
``all_to_all`` and a per-plane column min-fold -> 7. unpack.

Total traffic: two all_to_alls of the two planes + two small all_gathers,
against the gather path's full replication of the array on every device.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P
from ._compat import shard_map as _shard_map

__all__ = [
    "sample_sort_1d",
    "sample_sort_along",
    "select_global_ranks",
    "supports_sample_sort",
    "SAMPLE_SORT_THRESHOLD",
]

#: Global element count (along the sort axis) above which ``ht.sort``
#: prefers the PSRS collective over the gather path (tests lower it).
#:
#: Measured data (scripts/measure_sort_crossover.py, r4, virtual 8-device
#: CPU mesh): on a SINGLE-HOST mesh the dense path wins at every size
#: (PSRS/gather wall-clock ratio 1.2-2.0x from 2^14 through 2^22) —
#: collectives there are memcpys, so gather's one fused sort beats four
#: collectives.  The gate is nevertheless set at 2^17, far below the old
#: 2^22, because the framework's target is real multi-chip meshes where
#: the tradeoff inverts on the two axes a single-host measurement cannot
#: see: (a) per-device MEMORY — the gather path replicates all n elements
#: (key+index planes) on every device, so a split array anywhere near
#: device capacity cannot take it at all, while PSRS peaks at O(n/p);
#: (b) link TRAFFIC — O(n) per device through the all-gather vs PSRS's
#: two all_to_alls of O(n/p) per device over ICI.  Below 2^17 both paths
#: fit trivially everywhere and dispatch latency dominates, so the
#: simpler program keeps the job.
SAMPLE_SORT_THRESHOLD = 1 << 17

_KEY32 = ("float32", "int32", "uint32", "float16", "bfloat16")
_KEY64 = ("float64", "int64", "uint64")


def supports_sample_sort(a, axis: int, descending: bool) -> bool:
    """Whether the PSRS fast path applies to this sort call: the sort
    axis must be the split axis (axis != 0 rides a local moveaxis — the
    sharding follows the dimension, no resharding traffic)."""
    name = np.dtype(a.dtype.jax_type()).name
    if a.split is None or a.split != axis or a.comm.size <= 1:
        return False
    n = a.shape[axis]
    if n < SAMPLE_SORT_THRESHOLD:
        return False
    if name in _KEY32:
        return n < (1 << 31)
    if name in _KEY64:
        return bool(jax.config.read("jax_enable_x64")) and n < (1 << 62)
    return False


def _order_bits(vals, descending: bool):
    """Unsigned bits whose order equals the value order (NaNs last)."""
    dt = vals.dtype
    if dt in (jnp.dtype("float16"), jnp.dtype(jnp.bfloat16)):
        vals, dt = vals.astype(jnp.float32), jnp.dtype("float32")
    if dt == jnp.dtype("float32"):
        u = jax.lax.bitcast_convert_type(vals, jnp.uint32)
        mask = jnp.where(u >> 31 == 1, jnp.uint32(0xFFFFFFFF), jnp.uint32(0x80000000))
        u = jnp.where(jnp.isnan(vals), jnp.uint32(0xFFFFFFFF), u ^ mask)
    elif dt == jnp.dtype("float64"):
        u = jax.lax.bitcast_convert_type(vals, jnp.uint64)
        mask = jnp.where(
            u >> 63 == 1, jnp.uint64(0xFFFFFFFFFFFFFFFF), jnp.uint64(0x8000000000000000)
        )
        u = jnp.where(jnp.isnan(vals), jnp.uint64(0xFFFFFFFFFFFFFFFF), u ^ mask)
    elif dt == jnp.dtype("int32"):
        u = jax.lax.bitcast_convert_type(vals, jnp.uint32) ^ jnp.uint32(0x80000000)
    elif dt == jnp.dtype("int64"):
        u = jax.lax.bitcast_convert_type(vals, jnp.uint64) ^ jnp.uint64(0x8000000000000000)
    elif dt == jnp.dtype("uint32"):
        u = vals
    elif dt == jnp.dtype("uint64"):
        u = vals
    else:  # pragma: no cover - guarded by supports_sample_sort
        raise TypeError(f"unsupported sort dtype {dt}")
    return ~u if descending else u


def _unorder_bits(u, dtype, descending: bool):
    """Inverse of :func:`_order_bits`."""
    if descending:
        u = ~u
    dt = jnp.dtype(dtype)
    if dt in (jnp.dtype("float16"), jnp.dtype(jnp.bfloat16)):
        mask = jnp.where(u >> 31 == 1, jnp.uint32(0x80000000), jnp.uint32(0xFFFFFFFF))
        return jax.lax.bitcast_convert_type(u ^ mask, jnp.float32).astype(dt)
    if dt == jnp.dtype("float32"):
        mask = jnp.where(u >> 31 == 1, jnp.uint32(0x80000000), jnp.uint32(0xFFFFFFFF))
        return jax.lax.bitcast_convert_type(u ^ mask, jnp.float32)
    if dt == jnp.dtype("float64"):
        mask = jnp.where(
            u >> 63 == 1, jnp.uint64(0x8000000000000000), jnp.uint64(0xFFFFFFFFFFFFFFFF)
        )
        return jax.lax.bitcast_convert_type(u ^ mask, jnp.float64)
    if dt == jnp.dtype("int32"):
        return jax.lax.bitcast_convert_type(u ^ jnp.uint32(0x80000000), jnp.int32)
    if dt == jnp.dtype("int64"):
        return jax.lax.bitcast_convert_type(u ^ jnp.uint64(0x8000000000000000), jnp.int64)
    return u.astype(dt)


def _pair_sort(keys, gids):
    """Stable lexicographic (key, gid) sort along axis 0 — LSD two-pass:
    gids are already in ascending order per construction after packing, so
    one stable argsort by key preserves the gid tie order; after merges
    (arbitrary tie order) the explicit two-pass variant is used instead."""
    pos = jnp.argsort(keys, axis=0, stable=True)
    return jnp.take_along_axis(keys, pos, axis=0), jnp.take_along_axis(gids, pos, axis=0)


def _pair_sort_lsd(keys, gids):
    """Full lexicographic sort when the incoming tie order is arbitrary."""
    pos = jnp.argsort(gids, axis=0, stable=True)
    keys = jnp.take_along_axis(keys, pos, axis=0)
    gids = jnp.take_along_axis(gids, pos, axis=0)
    return _pair_sort(keys, gids)


def _batch_iotas(shape, skip: int):
    """Broadcasted iota index arrays for every dim except the first ``skip``."""
    return tuple(
        jax.lax.broadcasted_iota(jnp.int32, shape, d) for d in range(skip, len(shape))
    )


@functools.lru_cache(maxsize=32)
def _psrs_fn(comm, m: int, b: int, batch: tuple, dtype_name: str, descending: bool):
    """Jitted, cached PSRS executable.

    ``m``: true global extent along axis 0; ``b``: padded block size per
    device; ``batch``: trailing (non-sort) dims, sorted independently."""
    mesh = comm.mesh
    axis = comm.axis_name
    p = comm.size
    dtype = jnp.dtype(dtype_name)
    wide = np.dtype(dtype).name in _KEY64
    kdt = jnp.uint64 if wide else jnp.uint32
    gdt = jnp.int64 if (wide or m >= (1 << 31)) else jnp.int32
    KSENT = np.uint64(~np.uint64(0)) if wide else np.uint32(~np.uint32(0))
    GSENT = np.int64(np.iinfo(np.int64).max) if gdt == jnp.int64 else np.int32(np.iinfo(np.int32).max)
    nb = len(batch)
    ex = (slice(None),) + (None,) * nb  # broadcast a (x,) to (x, *batch)

    def lex_lt(ka, ga, kb, gb):
        return (ka < kb) | ((ka == kb) & (ga < gb))

    def body(a_loc):
        # ---- 1. pack
        r = jax.lax.axis_index(axis)
        row = jnp.arange(b, dtype=gdt)
        gid0 = (r.astype(gdt) * b + row)[ex]  # (b, 1...*nb)
        gids = jnp.broadcast_to(gid0, (b, *batch))
        keys = _order_bits(a_loc, descending).astype(kdt)
        pad = gids >= m
        keys = jnp.where(pad, KSENT, keys)
        gids = jnp.where(pad, GSENT, gids)

        # ---- 2. local stable sort (gids ascending per column already)
        keys, gids = _pair_sort(keys, gids)

        # ---- 3. regular samples -> replicated pivot pairs
        sample_pos = ((jnp.arange(p) + 1) * b) // (p + 1)
        sk = keys[sample_pos]  # (p, *batch)
        sg = gids[sample_pos]
        ak = jax.lax.all_gather(sk, axis, axis=0, tiled=True)  # (p*p, *batch)
        ag = jax.lax.all_gather(sg, axis, axis=0, tiled=True)
        ak, ag = _pair_sort_lsd(ak, ag)
        piv_pos = (jnp.arange(p - 1) + 1) * p
        pk, pg = ak[piv_pos], ag[piv_pos]  # (p-1, *batch)

        # ---- 4. lexicographic bucketing + scatter + all_to_all
        # bkt[i] = number of pivots strictly less than element i
        lt = lex_lt(pk[:, None], pg[:, None], keys[None], gids[None])  # (p-1, b, *batch)
        bkt = jnp.sum(lt.astype(jnp.int32), axis=0)  # (b, *batch)
        # run_start[j] = number of elements in buckets BELOW j (elements
        # sorted => bkt monotone => this is bucket j's first position)
        below = bkt[None] < jnp.arange(p, dtype=jnp.int32)[ex + (None,)]  # (p, b, *batch)
        run_start = jnp.sum(below.astype(jnp.int32), axis=1)  # (p, *batch)
        col = jnp.broadcast_to(
            jnp.arange(b, dtype=jnp.int32)[ex], (b, *batch)
        ) - jnp.take_along_axis(run_start, bkt, axis=0)
        bi = _batch_iotas((b, *batch), 1)
        send_k = jnp.full((p, b, *batch), KSENT, kdt).at[(bkt, col, *bi)].set(keys, mode="drop")
        send_g = jnp.full((p, b, *batch), GSENT, gdt).at[(bkt, col, *bi)].set(gids, mode="drop")
        recv_k = jax.lax.all_to_all(send_k, axis, split_axis=0, concat_axis=0, tiled=True)
        recv_g = jax.lax.all_to_all(send_g, axis, split_axis=0, concat_axis=0, tiled=True)

        # ---- 5. merge: top_k on order-reversed keys (2B bound), then an
        # LSD pass to restore exact (key, gid) order among ties.
        #
        # A real key CAN equal the scatter-fill sentinel KSENT (float NaN,
        # INT_MAX ascending, INT_MIN descending, unsigned max): the
        # key-only top_k would tie such elements against fill sentinels
        # and may pick the fill.  A second, gid-keyed top_k over exactly
        # the KSENT-keyed REAL entries rescues them; both candidate sets
        # are concatenated and pair-sorted, reals strictly before fills.
        cap = min(2 * b, p * b)
        flat_k = jnp.moveaxis(recv_k.reshape(p * b, *batch), 0, -1)  # (*batch, p*b)
        flat_g = jnp.moveaxis(recv_g.reshape(p * b, *batch), 0, -1)
        top, pos = jax.lax.top_k(~flat_k, cap)  # (*batch, cap)
        c1k = ~top
        c1g = jnp.take_along_axis(flat_g, pos, axis=-1)
        # neutralize any sentinel-keyed pick from pass 1 (real or fill —
        # the rescue pass below re-adds the real ones unambiguously)
        c1g = jnp.where(c1k == KSENT, GSENT, c1g)
        udt = jnp.uint64 if gdt == jnp.int64 else jnp.uint32
        ug = flat_g.astype(udt)
        rescue_score = jnp.where(
            (flat_k == KSENT) & (flat_g != GSENT), ~ug, jnp.asarray(0, udt)
        )
        top2, _ = jax.lax.top_k(rescue_score, cap)  # largest ~gid = smallest gids
        c2g = jnp.where(top2 != 0, (~top2).astype(gdt), GSENT)
        c2k = jnp.full_like(top2, KSENT).astype(kdt)
        mk = jnp.moveaxis(jnp.concatenate([c1k, c2k], axis=-1), -1, 0)  # (2cap, *batch)
        mg = jnp.moveaxis(jnp.concatenate([c1g, c2g], axis=-1), -1, 0)
        mk, mg = _pair_sort_lsd(mk, mg)
        mk, mg = mk[:cap], mg[:cap]  # all reals fit (2B bound)
        k_real = jnp.sum((mg != GSENT).astype(gdt), axis=0)  # (*batch,)

        # ---- 6. exact-rank rebalance (int64-safe counts, ADVICE r2)
        counts = jax.lax.all_gather(k_real[None], axis, axis=0, tiled=True)  # (p, *batch)
        offset = jnp.cumsum(counts, axis=0) - counts
        my_off = jax.lax.dynamic_index_in_dim(offset, r, axis=0, keepdims=False)
        rank = my_off.astype(gdt)[None] + jnp.arange(cap, dtype=gdt)[ex]
        valid = jnp.arange(cap, dtype=gdt)[ex] < k_real[None]
        dest = jnp.where(valid, (rank // b).astype(jnp.int32), p)
        dcol = jnp.where(valid, (rank % b).astype(jnp.int32), 0)
        bi2 = _batch_iotas((cap, *batch), 1)
        send2k = jnp.full((p, b, *batch), KSENT, kdt).at[(dest, dcol, *bi2)].set(mk, mode="drop")
        send2g = jnp.full((p, b, *batch), GSENT, gdt).at[(dest, dcol, *bi2)].set(mg, mode="drop")
        recv2k = jax.lax.all_to_all(send2k, axis, split_axis=0, concat_axis=0, tiled=True)
        recv2g = jax.lax.all_to_all(send2g, axis, split_axis=0, concat_axis=0, tiled=True)
        fk = jnp.min(recv2k, axis=0)  # one real pair per column slot
        fg = jnp.min(recv2g, axis=0)

        # ---- 7. unpack
        vals = _unorder_bits(fk, dtype, descending)
        return vals.astype(dtype), fg.astype(
            jnp.int64 if jax.config.read("jax_enable_x64") else jnp.int32
        )

    return jax.jit(
        _shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis),
            out_specs=(P(axis), P(axis)),
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _select_fn(comm, b: int, k: int, dtype_name: str):
    """Fetch ``k`` global positions from a split-0 array WITHOUT gathering:
    each device contributes the positions it owns, a pmax folds them.
    The order-statistics backbone (reference percentile's fractional-index
    gather, statistics.py:1443)."""
    axis = comm.axis_name

    def body(blk, idx):
        r = jax.lax.axis_index(axis)
        local = idx - r.astype(idx.dtype) * b
        owned = (local >= 0) & (local < b)
        vals = blk[jnp.clip(local, 0, b - 1)]
        contrib = jnp.where(owned, vals, -jnp.inf)
        return jax.lax.pmax(contrib, axis)

    return jax.jit(
        _shard_map(
            body,
            mesh=comm.mesh,
            in_specs=(P(axis), P()),
            out_specs=P(),
            check_vma=False,
        )
    )


def select_global_ranks(values, positions) -> jax.Array:
    """Values at ``positions`` of a 1-D split-0 float DNDarray, replicated.

    One shard_map + pmax; traffic O(len(positions)), never the array."""
    comm = values.comm
    blk = values.larray_padded
    idx = jnp.asarray(np.asarray(positions))
    fn = _select_fn(comm, blk.shape[0] // comm.size, int(idx.shape[0]), str(blk.dtype))
    return fn(blk, idx)


def sample_sort_along(a, axis: int, descending: bool = False):
    """PSRS sort along any split axis: for ``axis != 0`` the padded buffer
    is moveaxis'd so the split dimension leads — a per-device transpose
    whose sharding follows the moved dimension (no collective) — sorted
    with the axis-0 program, and moved back.  Returns (values, indices)
    split along ``axis``; the gids are positions along the original axis,
    exactly argsort's semantics."""
    if axis == 0:
        return sample_sort_1d(a, descending)
    from .dndarray import DNDarray
    from . import types

    comm = a.comm
    moved = jnp.moveaxis(a.larray_padded, axis, 0)
    moved = jax.device_put(moved, comm.sharding(0))
    gshape = (a.shape[axis],) + tuple(s for i, s in enumerate(a.shape) if i != axis)
    am = DNDarray(moved, gshape, a.dtype, 0, a.device, comm)
    v, g = sample_sort_1d(am, descending)
    back_v = jax.device_put(jnp.moveaxis(v.larray_padded, 0, axis), comm.sharding(axis))
    back_g = jax.device_put(jnp.moveaxis(g.larray_padded, 0, axis), comm.sharding(axis))
    idx_t = types.int64 if jax.config.read("jax_enable_x64") else types.int32
    return (
        DNDarray(back_v, a.shape, a.dtype, axis, a.device, comm),
        DNDarray(back_g, a.shape, idx_t, axis, a.device, comm),
    )


def sample_sort_1d(a, descending: bool = False):
    """Sort a split-0 DNDarray along axis 0 via the PSRS collective.

    Trailing dims are independent batch columns.  Returns ``(values,
    indices)`` as DNDarrays with the input's split — the backing arrays
    come straight out of the shard_map in canonical layout; nothing is
    gathered."""
    from .dndarray import DNDarray

    comm = a.comm
    m = a.shape[0]
    blk = a.larray_padded
    b = blk.shape[0] // comm.size
    batch = tuple(int(s) for s in blk.shape[1:])
    name = "bfloat16" if a.dtype.jax_type() == jnp.bfloat16 else str(np.dtype(a.dtype.jax_type()))
    fn = _psrs_fn(comm, m, b, batch, name, bool(descending))
    vals, gids = fn(blk)
    values = DNDarray(vals, a.shape, a.dtype, 0, a.device, a.comm)
    from . import types

    idx_t = types.int64 if jax.config.read("jax_enable_x64") else types.int32
    indices = DNDarray(gids, a.shape, idx_t, 0, a.device, a.comm)
    return values, indices
