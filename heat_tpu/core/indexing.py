"""Indexing helpers, analog of heat/core/indexing.py."""

from __future__ import annotations

import jax.numpy as jnp

from . import types
from .dndarray import DNDarray

__all__ = ["nonzero", "where"]


def nonzero(x: DNDarray) -> DNDarray:
    """Indices of non-zero elements as an (nnz, ndim) array
    (indexing.py:16; the reference offsets local results by the chunk
    offset — the global jnp.nonzero already yields global indices)."""
    dense = x._dense()
    idx = jnp.nonzero(dense)
    stacked = jnp.stack(idx, axis=1) if x.ndim > 1 else idx[0]
    split = 0 if x.split is not None else None
    return DNDarray.from_dense(stacked.astype(types.canonical_dtype(jnp.int64)), split, x.device, x.comm)


def where(cond: DNDarray, x=None, y=None) -> DNDarray:
    """Ternary select / nonzero (indexing.py:91)."""
    if x is None and y is None:
        return nonzero(cond)
    if x is None or y is None:
        raise TypeError("either both or neither of x and y must be given")
    cd = cond._dense()
    xd = x._dense() if isinstance(x, DNDarray) else jnp.asarray(x)
    yd = y._dense() if isinstance(y, DNDarray) else jnp.asarray(y)
    result = jnp.where(cd, xd, yd)
    out_split = cond.split
    if out_split is not None and (result.ndim != cond.ndim or out_split >= result.ndim):
        out_split = 0 if result.ndim > 0 else None
    return DNDarray.from_dense(result, out_split, cond.device, cond.comm)
