"""Fitted-estimator <-> checkpoint codec: the model-store format.

A served model is a fitted estimator reduced to three world-size
invariant pieces — its kind (class name), its constructor params
(scalars only), and its fitted state (the arrays ``predict``/
``transform`` actually read) — written through the existing
:class:`~heat_tpu.utils.checkpoint.Checkpointer` (atomic directory
commit, CRC32 sidecars, io retry policy).  A checkpoint **step** is a
model **version**; ``meta_<version>.json`` carries the listing metadata
(kind, name, save time) so a registry can enumerate a model directory
without decoding array payloads.

Because the payload is the native codec's dense-global-array format, a
model fitted at world size P hot-loads at world size Q through the
cross-world restore path (``Checkpointer.restore(comm=...)``) with each
DNDarray leaf re-split onto the serving mesh — the elastic layer's
restore guarantee, inherited for free.

Supported estimator kinds and their state:

==================== ==============================================
kind                 fitted state (array leaves)
==================== ==============================================
KMeans/KMedians/     ``cluster_centers`` (the full predict surface of
KMedoids             the `_KCluster` family)
PCA                  ``mean``, ``components``, ``singular_values``,
                     ``explained_variance(_ratio)``, ``tevr``,
                     ``n_components``
Lasso                ``theta`` (intercept + coefficients)
KNeighborsClassifier ``x`` (train points), ``y`` (one-hot labels)
==================== ==============================================
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional

import jax.numpy as jnp

from ..core.dndarray import DNDarray

__all__ = [
    "SUPPORTED_KINDS",
    "build_estimator",
    "export_state",
    "infer",
    "save_model",
]

#: estimator class names the codec round-trips (the heat L5 surface
#: turned serveable)
SUPPORTED_KINDS = (
    "KMeans",
    "KMedians",
    "KMedoids",
    "PCA",
    "Lasso",
    "KNeighborsClassifier",
)

_KCLUSTER_KINDS = ("KMeans", "KMedians", "KMedoids")

#: codec version stamped into every exported doc; a future layout change
#: bumps it and keeps old models loadable
CODEC_VERSION = 1


def _estimator_classes() -> Dict[str, type]:
    # lazy: the estimator modules import the full core stack
    from ..classification import KNeighborsClassifier
    from ..cluster import KMeans, KMedians, KMedoids
    from ..decomposition import PCA
    from ..regression import Lasso

    return {
        "KMeans": KMeans,
        "KMedians": KMedians,
        "KMedoids": KMedoids,
        "PCA": PCA,
        "Lasso": Lasso,
        "KNeighborsClassifier": KNeighborsClassifier,
    }


class NotFittedError(ValueError):
    """The estimator has no fitted state to export."""


def _require(cond: bool, kind: str) -> None:
    if not cond:
        raise NotFittedError(
            f"{kind} estimator is not fitted; call fit() before save_model()"
        )


def _scalar_params(est) -> Dict[str, Any]:
    """JSON-safe constructor params: scalars/strings/None only.  Array
    params (a DNDarray ``init=``) and resume plumbing are irrelevant to
    a *fitted* model's predict path and are dropped."""
    out: Dict[str, Any] = {}
    for k, v in est.get_params(deep=False).items():
        if k in ("checkpoint_every", "checkpoint_dir", "resume_from"):
            continue
        if v is None or isinstance(v, (bool, int, float, str)):
            out[k] = v
    return out


def export_state(est) -> Dict[str, Any]:
    """Fitted estimator -> checkpointable document (pure pytree of
    scalars and array leaves; DNDarray leaves keep their split intent
    through the native codec)."""
    kind = type(est).__name__
    if kind not in SUPPORTED_KINDS:
        raise TypeError(
            f"cannot serve a {kind}; supported estimator kinds: "
            f"{', '.join(SUPPORTED_KINDS)}"
        )
    state: Dict[str, Any]
    if kind in _KCLUSTER_KINDS:
        _require(est._cluster_centers is not None, kind)
        state = {"cluster_centers": est._cluster_centers}
    elif kind == "PCA":
        _require(getattr(est, "components_", None) is not None, kind)
        state = {
            "mean": est.mean_,
            "components": est.components_,
            "singular_values": est.singular_values_,
            "explained_variance": est.explained_variance_,
            "explained_variance_ratio": est.explained_variance_ratio_,
            "tevr": float(est._tevr),
            "n_components": int(est.n_components_),
        }
    elif kind == "Lasso":
        _require(est.theta is not None, kind)
        state = {"theta": est.theta}
    else:  # KNeighborsClassifier
        _require(est.x is not None and est.y is not None, kind)
        state = {"x": est.x, "y": est.y}
    return {
        "serving_codec": CODEC_VERSION,
        "kind": kind,
        "params": _scalar_params(est),
        "state": state,
    }


def _as_dnd(leaf, comm, split=None) -> DNDarray:
    """Array leaf -> DNDarray on ``comm``.  Restores through the
    cross-world path already hand back DNDarrays (split re-applied);
    a comm-less restore hands back host arrays, wrapped replicated."""
    if isinstance(leaf, DNDarray):
        return leaf
    return DNDarray.from_dense(jnp.asarray(leaf), split, None, comm)


def build_estimator(doc: Dict[str, Any], comm=None):
    """Checkpoint document -> fitted estimator ready to ``predict``.

    ``comm`` wraps any host-array leaves (comm-less restore); leaves the
    cross-world restore already re-split are used as-is."""
    if comm is None:
        from ..parallel import get_comm

        comm = get_comm()
    try:
        kind = doc["kind"]
        params = doc["params"]
        state = doc["state"]
    except (TypeError, KeyError):
        raise ValueError(
            "checkpoint does not hold a serving model document "
            "(missing kind/params/state — was it written by save_model?)"
        ) from None
    classes = _estimator_classes()
    if kind not in classes:
        raise ValueError(f"unknown estimator kind {kind!r} in model document")
    est = classes[kind](**params)
    if kind in _KCLUSTER_KINDS:
        est._cluster_centers = _as_dnd(state["cluster_centers"], comm)
    elif kind == "PCA":
        est.mean_ = _as_dnd(state["mean"], comm)
        est.components_ = _as_dnd(state["components"], comm)
        est.singular_values_ = _as_dnd(state["singular_values"], comm)
        est.explained_variance_ = _as_dnd(state["explained_variance"], comm)
        est.explained_variance_ratio_ = _as_dnd(state["explained_variance_ratio"], comm)
        est._tevr = float(state["tevr"])
        est.n_components_ = int(state["n_components"])
    elif kind == "Lasso":
        est._Lasso__theta = _as_dnd(state["theta"], comm)
    else:  # KNeighborsClassifier
        est.x = _as_dnd(state["x"], comm)
        est.y = _as_dnd(state["y"], comm)
    return est


def infer(est, x: DNDarray) -> DNDarray:
    """The estimator's inference surface: ``predict`` where it exists
    (clustering/regression/classification), else ``transform`` (PCA).

    Runs under the estimator kind's precision-policy scope — the
    serving choke point of the J204 enforcement: every program the
    coalesced batch compiles is checked against the kind's declared
    precision contract by the dispatch analyze hook."""
    from ..analysis import precision_policy as _pp

    with _pp.scope(type(est).__name__):
        fn = getattr(est, "predict", None)
        if fn is None:
            fn = est.transform
        return fn(x)


def save_model(
    est,
    directory: str,
    version: int = 0,
    name: Optional[str] = None,
    checkpointer=None,
    async_: bool = False,
    baseline: Optional[Dict[str, Any]] = None,
    policy: Optional[Dict[str, Any]] = None,
) -> int:
    """Export a fitted estimator as model ``version`` in ``directory``.

    The write is the Checkpointer's native path — staged directory,
    CRC32 sidecars, one atomic rename — so a model directory only ever
    holds complete versions.  ``async_=True`` routes through the bounded
    background writer; pass your own ``checkpointer`` to keep the write
    in flight past this call (and ``close()`` it for durability) —
    without one, the internal checkpointer is drained before returning
    so the version is durable either way.

    ``baseline`` is an input-distribution sketch document
    (:meth:`heat_tpu.telemetry.sketch.ModelSketch.doc`, typically the
    training data's) persisted INSIDE the version: the model and the
    distribution it expects travel as one atomic artifact, and the
    registry re-attaches the baseline to the drift monitor on every
    hot-load — no side-channel file to lose.

    ``policy`` overrides the estimator kind's declared precision policy
    (default: its :data:`~heat_tpu.analysis.precision_policy.POLICIES`
    entry).  The version metadata records the policy AND the effective
    predict compute dtype at export time;
    :meth:`~heat_tpu.serving.registry.ModelRegistry.load` refuses to
    activate a version whose recorded dtype (or the serving process's
    current one) violates the recorded policy.  Returns the version
    written."""
    import json as _json

    from ..analysis import precision_policy as _pp
    from ..utils.checkpoint import Checkpointer

    doc = export_state(est)
    if baseline is not None:
        # JSON-encoded string leaf: the sketch document is pure scalars
        # and (stringified) bucket tables, and a string leaf rides the
        # checkpoint codec untouched — no array-leaf shape to validate
        doc["baseline_json"] = _json.dumps(baseline, sort_keys=True)
    pol = (
        _pp.validate_policy(policy) if policy is not None
        else _pp.policy_for(doc["kind"])
    )
    meta = {
        "serving_codec": CODEC_VERSION,
        "kind": doc["kind"],
        "name": name if name is not None else doc["kind"].lower(),
        "saved_at": time.time(),
        # the precision contract this version serves under, and the
        # compute dtype its predictions actually use in this process —
        # the registry's load-time refusal checks the pair
        "policy": dict(pol) if pol is not None else None,
        "compute_dtype": _pp.compute_dtype(doc["kind"]),
    }
    ck = checkpointer if checkpointer is not None else Checkpointer(directory)
    try:
        ck.save(int(version), doc, extra_metadata=meta, async_=async_)
    finally:
        if async_ and checkpointer is None:
            ck.close()  # internal checkpointer: drain so the write is durable
    return int(version)
