"""Signal processing, analog of heat/core/signal.py.

The reference's distributed 1-D convolution (signal.py:16-318) computes
``halo_size = kernel//2`` neighbor rows via paired Isend/Irecv and, for a
distributed kernel, Bcasts each rank's kernel chunk in turn while summing
partial results.  Here the convolution is expressed once on the global
sharded signal via ``jax.lax.conv_general_dilated``; XLA materializes the
boundary (halo) exchange between shards over ICI.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from .dndarray import DNDarray

__all__ = ["convolve"]


def convolve(a, v, mode: str = "full") -> DNDarray:
    """1-D discrete convolution of ``a`` with kernel ``v`` (signal.py:16).

    Modes: 'full' (default), 'same', 'valid'.  ``same`` requires an odd
    kernel, matching the reference (signal.py:84).
    """
    from . import factories, types

    if not isinstance(a, DNDarray):
        a = factories.array(a)
    if not isinstance(v, DNDarray):
        v = factories.array(v, comm=a.comm)
    if a.ndim != 1 or v.ndim != 1:
        raise ValueError("only 1-dimensional input DNDarrays are allowed")
    if mode == "same" and v.shape[0] % 2 == 0:
        raise ValueError("Mode 'same' cannot be used with even-sized kernel")
    if mode not in ("full", "same", "valid"):
        raise ValueError(f"Supported modes are 'full', 'same', 'valid', got {mode!r}")
    if v.shape[0] > a.shape[0]:
        if mode == "full":
            a, v = v, a
        else:
            raise ValueError("filter size must not be greater than the signal size in mode 'same'/'valid'")

    promoted = types.promote_types(a.dtype, v.dtype)
    # the conv engine needs a floating compute type; exact (int/bool) inputs
    # compute in f32 and are rounded back (matching the reference's
    # cast-through-float behavior, signal.py:200)
    if types.heat_type_is_exact(promoted):
        compute_jdt = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    else:
        compute_jdt = promoted.jax_type()
    signal = a._dense().astype(compute_jdt)

    if v.split is not None and v.comm.size > 1:
        # distributed-kernel mode (reference signal.py:267+): the split
        # kernel is STREAMED — each round replicates one participant's
        # chunk (the reference's Bcast) and accumulates its shifted
        # partial convolution; no device ever holds the whole kernel
        out = _streamed_kernel_conv(signal, v, mode, compute_jdt)
    else:
        kernel = v._dense().astype(compute_jdt)
        k = kernel.shape[0]
        if mode == "full":
            pad_l = pad_r = k - 1
        elif mode == "same":
            pad_l = pad_r = k // 2
        else:
            pad_l = pad_r = 0
        out = _conv1d_valid(jnp.pad(signal, (pad_l, pad_r)), kernel)
    if types.heat_type_is_exact(promoted):
        out = jnp.round(out)
    out = out.astype(promoted.jax_type())
    return DNDarray.from_dense(out, a.split, a.device, a.comm)


def _conv1d_valid(signal, kernel):
    """VALID correlation with the flipped kernel == convolution."""
    lhs = signal[None, None, :]
    rhs = jnp.flip(kernel)[None, None, :]
    return jax.lax.conv_general_dilated(
        lhs, rhs, window_strides=(1,), padding="VALID",
        precision=jax.lax.Precision.HIGHEST,
    )[0, 0]


def _streamed_kernel_conv(signal, v, mode, compute_jdt):
    """Bcast-round convolution with a split kernel (signal.py:267+).

    full(a, v) = sum over kernel chunks c of full(a, chunk_c) shifted by
    the chunk offset; each round handles one (k/p)-sized chunk, and the
    mode slice is applied to the accumulated full-length result."""
    comm = v.comm
    p = comm.size
    n = signal.shape[0]
    k = v.shape[0]
    kp = v.larray_padded.astype(compute_jdt)
    b = kp.shape[0] // p
    out = jnp.zeros((n + k - 1,), compute_jdt)
    for r in range(p):
        s = r * b
        w = min(k, s + b) - s
        if w <= 0:
            break
        chunk = kp[s : s + w]  # one chunk in flight (the Bcast round)
        part = _conv1d_valid(jnp.pad(signal, (w - 1, w - 1)), chunk)
        out = out.at[s : s + n + w - 1].add(part)
    if mode == "full":
        return out
    if mode == "same":
        return out[k // 2 : k // 2 + n]
    return out[k - 1 : n]
