"""Manipulations edge matrix at reference width (heat/core/tests/
test_manipulations.py, 3,816 LoC): the corner cases the basic sweeps in
test_statistics_manipulations.py don't reach — empty slices, size-1 and
uneven split extents, negative/rolled axes, multi-section splits, pad
modes, insert/delete/append/resize, trim_zeros, ediff1d — all against
numpy ground truth across splits on the 8-device mesh.
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0, 1]


@pytest.fixture(scope="module")
def m2d():
    return np.arange(48, dtype=np.float32).reshape(8, 6)


@pytest.mark.parametrize("split", SPLITS)
def test_reshape_order_preserved_uneven(split):
    a = np.arange(91, dtype=np.float32).reshape(13, 7)  # 13, 7 vs 8 devices
    x = ht.array(a, split=split if split != 1 else 1)
    np.testing.assert_array_equal(x.reshape((7, 13)).numpy(), a.reshape(7, 13))
    np.testing.assert_array_equal(x.reshape((91,)).numpy(), a.reshape(91))
    np.testing.assert_array_equal(x.reshape((13, 7, 1)).numpy(), a.reshape(13, 7, 1))
    with pytest.raises((ValueError, TypeError)):
        x.reshape((12, 7))
    # -1 inference
    np.testing.assert_array_equal(x.reshape((-1, 13)).numpy(), a.reshape(-1, 13))


@pytest.mark.parametrize("split", SPLITS)
def test_concatenate_axis_and_mixed_splits(m2d, split):
    b = (m2d * 2.0)[:5]
    x = ht.array(m2d, split=split)
    for bsplit in SPLITS:
        y = ht.array(b, split=bsplit)
        got = ht.concatenate([x, y], axis=0)
        np.testing.assert_array_equal(got.numpy(), np.concatenate([m2d, b], 0))
    got1 = ht.concatenate([x, x, x], axis=1)
    np.testing.assert_array_equal(got1.numpy(), np.concatenate([m2d] * 3, 1))
    got_neg = ht.concatenate([x, x], axis=-1)
    np.testing.assert_array_equal(got_neg.numpy(), np.concatenate([m2d] * 2, -1))


@pytest.mark.parametrize("split", [None, 0])
def test_concatenate_empty_operand(split):
    a = np.arange(12, dtype=np.float32).reshape(4, 3)
    empty = np.zeros((0, 3), np.float32)
    got = ht.concatenate([ht.array(a, split=split), ht.array(empty, split=split)], axis=0)
    np.testing.assert_array_equal(got.numpy(), a)


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("shift,axis", [(3, 0), (-2, 1), (100, 0), ((1, 2), (0, 1)), (5, None)])
def test_roll_matrix(m2d, split, shift, axis):
    x = ht.array(m2d, split=split)
    np.testing.assert_array_equal(
        ht.roll(x, shift, axis=axis).numpy(), np.roll(m2d, shift, axis=axis)
    )


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("k", [0, 1, 2, 3, -1])
def test_rot90_all_k(m2d, split, k):
    x = ht.array(m2d, split=split)
    np.testing.assert_array_equal(ht.rot90(x, k=k).numpy(), np.rot90(m2d, k=k))


@pytest.mark.parametrize("split", [None, 0])
@pytest.mark.parametrize(
    "mode,kw",
    [
        ("constant", {"constant_values": 3.5}),
        ("edge", {}),
        ("reflect", {}),
        ("wrap", {}),
    ],
)
def test_pad_modes(m2d, split, mode, kw):
    x = ht.array(m2d, split=split)
    widths = ((2, 1), (0, 3))
    got = ht.pad(x, widths, mode=mode, **kw)
    np.testing.assert_array_equal(got.numpy(), np.pad(m2d, widths, mode=mode, **kw))


@pytest.mark.parametrize(
    "mode,kw",
    [
        ("reflect", {"reflect_type": "odd"}),
        ("symmetric", {"reflect_type": "odd"}),
        ("maximum", {"stat_length": 2}),
        ("minimum", {"stat_length": ((2, 1), (1, 2))}),
        ("mean", {"stat_length": 2}),
        ("linear_ramp", {"end_values": 5.0}),
        ("linear_ramp", {"end_values": ((1.0, 2.0), (3.0, 4.0))}),
    ],
)
@pytest.mark.parametrize("split", [None, 0])
def test_pad_mode_specific_kwargs_forwarded(m2d, split, mode, kw):
    """Non-constant modes forward their mode-specific kwargs to jnp.pad
    (ISSUE 1 satellite: they used to be dropped silently)."""
    x = ht.array(m2d, split=split)
    widths = ((2, 1), (0, 3))
    got = ht.pad(x, widths, mode=mode, **kw)
    np.testing.assert_allclose(
        got.numpy(), np.pad(m2d, widths, mode=mode, **kw), rtol=1e-6
    )


def test_pad_kwargs_validated_against_mode(m2d):
    x = ht.array(m2d)
    with pytest.raises(ValueError, match="reflect_type"):
        ht.pad(x, ((1, 1), (1, 1)), mode="edge", reflect_type="odd")
    with pytest.raises(ValueError, match="stat_length"):
        ht.pad(x, ((1, 1), (1, 1)), mode="constant", stat_length=2)


@pytest.mark.parametrize("split", [None, 0])
def test_insert_delete_append(split):
    a = np.arange(20, dtype=np.float32)
    x = ht.array(a, split=split)
    np.testing.assert_array_equal(
        ht.insert(x, 5, 99.0).numpy(), np.insert(a, 5, 99.0)
    )
    np.testing.assert_array_equal(
        ht.delete(x, [0, 3, 19]).numpy(), np.delete(a, [0, 3, 19])
    )
    np.testing.assert_array_equal(
        ht.append(x, ht.array(np.array([77.0, 88.0], np.float32))).numpy(),
        np.append(a, [77.0, 88.0]),
    )
    m = np.arange(12, dtype=np.float32).reshape(3, 4)
    xm = ht.array(m, split=split)
    np.testing.assert_array_equal(
        ht.delete(xm, 1, axis=0).numpy(), np.delete(m, 1, axis=0)
    )
    np.testing.assert_array_equal(
        ht.insert(xm, 2, 5.0, axis=1).numpy(), np.insert(m, 2, 5.0, axis=1)
    )


@pytest.mark.parametrize("split", [None, 0])
def test_resize_trim_ediff1d(split):
    a = np.arange(10, dtype=np.float32)
    x = ht.array(a, split=split)
    np.testing.assert_array_equal(ht.resize(x, (3, 5)).numpy(), np.resize(a, (3, 5)))
    np.testing.assert_array_equal(ht.resize(x, (4,)).numpy(), np.resize(a, (4,)))
    z = np.array([0.0, 0.0, 1.0, 2.0, 0.0, 3.0, 0.0], np.float32)
    np.testing.assert_array_equal(
        ht.trim_zeros(ht.array(z, split=split)).numpy(), np.trim_zeros(z)
    )
    np.testing.assert_array_equal(
        ht.ediff1d(x, to_begin=ht.array(np.array([-9.0], np.float32))).numpy(),
        np.ediff1d(a, to_begin=[-9.0]),
    )


@pytest.mark.parametrize("split", SPLITS)
def test_tile_and_repeat_axes(m2d, split):
    x = ht.array(m2d, split=split)
    np.testing.assert_array_equal(ht.tile(x, (2, 3)).numpy(), np.tile(m2d, (2, 3)))
    np.testing.assert_array_equal(ht.tile(x, 2).numpy(), np.tile(m2d, 2))
    np.testing.assert_array_equal(
        ht.repeat(x, 3, axis=1).numpy(), np.repeat(m2d, 3, axis=1)
    )
    np.testing.assert_array_equal(ht.repeat(x, 2).numpy(), np.repeat(m2d, 2))
    reps = np.array([1, 2, 1, 3, 1, 1, 2, 1])
    np.testing.assert_array_equal(
        ht.repeat(x, ht.array(reps), axis=0).numpy(), np.repeat(m2d, reps, axis=0)
    )


@pytest.mark.parametrize("split", [None, 0])
def test_array_split_ragged(split):
    a = np.arange(23, dtype=np.float32)
    x = ht.array(a, split=split)
    got = ht.array_split(x, 5)
    want = np.array_split(a, 5)
    assert len(got) == len(want)
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g.numpy(), w)
    got_idx = ht.split(x, [3, 9, 20])
    for g, w in zip(got_idx, np.split(a, [3, 9, 20])):
        np.testing.assert_array_equal(g.numpy(), w)


@pytest.mark.parametrize("split", SPLITS)
def test_stack_new_axis_positions(m2d, split):
    x = ht.array(m2d, split=split)
    for axis in (0, 1, 2, -1):
        np.testing.assert_array_equal(
            ht.stack([x, x], axis=axis).numpy(), np.stack([m2d, m2d], axis=axis)
        )
    np.testing.assert_array_equal(ht.dstack([x, x]).numpy(), np.dstack([m2d, m2d]))
    np.testing.assert_array_equal(
        ht.column_stack([x, x]).numpy(), np.column_stack([m2d, m2d])
    )


@pytest.mark.parametrize("split", SPLITS)
def test_moveaxis_swapaxes_3d(split):
    a = np.arange(60, dtype=np.float32).reshape(3, 4, 5)
    x = ht.array(a, split=0 if split == 1 else split)
    np.testing.assert_array_equal(
        ht.moveaxis(x, 0, -1).numpy(), np.moveaxis(a, 0, -1)
    )
    np.testing.assert_array_equal(ht.swapaxes(x, 0, 2).numpy(), np.swapaxes(a, 0, 2))
    np.testing.assert_array_equal(ht.rollaxis(x, 2).numpy(), np.rollaxis(a, 2))
    np.testing.assert_array_equal(
        ht.transpose(x, (1, 2, 0)).numpy(), np.transpose(a, (1, 2, 0))
    )


@pytest.mark.parametrize("split", [None, 0])
def test_expand_squeeze_atleast(split):
    a = np.arange(8, dtype=np.float32)
    x = ht.array(a, split=split)
    e = ht.expand_dims(x, 1)
    np.testing.assert_array_equal(e.numpy(), a[:, None])
    np.testing.assert_array_equal(ht.squeeze(e).numpy(), a)
    m = np.arange(6, dtype=np.float32).reshape(1, 6, 1)
    xm = ht.array(m, split=None)
    np.testing.assert_array_equal(ht.squeeze(xm, axis=0).numpy(), np.squeeze(m, 0))
    np.testing.assert_array_equal(ht.atleast_2d(x).numpy(), np.atleast_2d(a))
    np.testing.assert_array_equal(ht.atleast_3d(x).numpy(), np.atleast_3d(a))


def test_flip_empty_and_single():
    for a in (np.zeros((0, 3), np.float32), np.ones((1, 1), np.float32)):
        x = ht.array(a, split=0)
        np.testing.assert_array_equal(ht.flipud(x).numpy(), np.flipud(a))
        np.testing.assert_array_equal(ht.fliplr(x).numpy(), np.fliplr(a))


@pytest.mark.parametrize("split", [None, 0])
def test_searchsorted_sides(split):
    a = np.array([1.0, 2.0, 2.0, 3.0, 5.0], np.float32)
    v = np.array([0.0, 2.0, 4.0, 6.0], np.float32)
    x = ht.array(a, split=split)
    for side in ("left", "right"):
        np.testing.assert_array_equal(
            ht.searchsorted(x, ht.array(v), side=side).numpy(),
            np.searchsorted(a, v, side=side),
        )
