"""Pallas kernel tests (core/kernels.py) — run through the Pallas
interpreter on the virtual CPU mesh, same code path as Mosaic on TPU."""

import numpy as np
import pytest

import jax.numpy as jnp


def _numpy_lloyd(x, c):
    d = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    lbl = d.argmin(1)
    new = np.stack([x[lbl == j].mean(0) if (lbl == j).any() else c[j] for j in range(c.shape[0])])
    return new, d.min(1).sum()


@pytest.mark.parametrize(
    "n,f,k",
    [(1003, 16, 8), (517, 8, 5), (130, 4, 7), (999, 16, 12), (96, 128, 8), (64, 64, 2)],
)
def test_lloyd_kernel_single(ht, n, f, k):
    from heat_tpu.core import kernels

    assert kernels.lloyd_supported(f, k)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, f)).astype(np.float32)
    c = rng.standard_normal((k, f)).astype(np.float32)
    npad = -(-n // 32) * 32
    xp = np.zeros((npad, f), np.float32)
    xp[:n] = x
    new, shift, inertia = kernels._lloyd_single(jnp.asarray(xp), jnp.asarray(c), n)
    ref, ref_inertia = _numpy_lloyd(x, c)
    np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5)
    np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)


def test_lloyd_kernel_sharded(ht):
    from heat_tpu.core import kernels

    ht.random.seed(5)
    x = ht.random.randn(1003, 16, split=0)  # uneven over 8 devices
    rng = np.random.default_rng(1)
    c = rng.standard_normal((8, 16)).astype(np.float32)
    new, shift, inertia = kernels.lloyd_update(x, jnp.asarray(c))
    ref, ref_inertia = _numpy_lloyd(x.numpy().astype(np.float32), c)
    np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5)
    np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)


def test_lloyd_unsupported_shapes(ht):
    from heat_tpu.core import kernels

    assert not kernels.lloyd_supported(17, 8)  # f does not divide 128
    assert not kernels.lloyd_supported(4, 30)  # packed space too wide
    assert not kernels.lloyd_supported(0, 8)


def test_kmeans_kernel_flag_end_to_end(ht, monkeypatch):
    """KMeans produces the same clustering through both step paths."""
    from heat_tpu.core import kernels

    ht.random.seed(7)
    x = ht.random.randn(500, 16, split=0)
    km_xla = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=30, random_state=0)
    km_xla.fit(x)
    monkeypatch.setattr(kernels, "LLOYD_KERNEL", True)
    km_pal = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=30, random_state=0)
    km_pal.fit(x)
    np.testing.assert_allclose(
        km_xla.cluster_centers_.numpy(), km_pal.cluster_centers_.numpy(), atol=1e-4
    )
