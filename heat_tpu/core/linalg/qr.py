"""QR decomposition, analog of heat/core/linalg/qr.py (qr.py:17-310).

Reference algorithms: split=0 tall-skinny -> TS-QR with a tree merge of
stacked R factors (procs_to_merge fan-in, Demmel et al. 2012, qr.py:64);
split=1 -> block-wise stabilized Gram-Schmidt with Bcasts of the current
column block.

TPU-native: the TS-QR tree is expressed as a shard_map collective program —
each shard takes a local QR, all-gathers the small R factors over ICI, and
(redundantly, replicated across shards) merges them with one more QR; the
local Q is then corrected by its block of the merge Q.  One ICI all-gather
of p×(n×n) floats replaces the reference's log-p rounds of paired
send/recvs.  Falls back to a global XLA QR when shards are ragged or wide.
"""

from __future__ import annotations

import collections
from typing import Optional

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")


def qr(
    A: DNDarray,
    mode: str = "reduced",
    procs_to_merge: int = 2,
) -> QR:
    """Reduced QR decomposition of a 2-D (or batched) array.

    Returns the namedtuple ``QR(Q, R)``; with ``mode='r'`` the Q factor is
    ``None`` (matching qr.py:33-40).
    """
    sanitize_in(A)
    if mode not in ("reduced", "r"):
        raise ValueError(f"mode must be 'reduced' or 'r', got {mode!r}")
    if A.ndim < 2:
        raise ValueError(f"Array A must be at least two-dimensional, but is {A.ndim}-dimensional")
    if not types.heat_type_is_realfloating(A.dtype) and not types.heat_type_is_complexfloating(A.dtype):
        A = A.astype(types.float32)

    m, n = A.shape[-2], A.shape[-1]
    comm = A.comm
    p = comm.size

    use_tsqr = (
        A.ndim == 2
        and A.split == 0
        and p > 1
        and m % p == 0
        and (m // p) >= n
    )
    if use_tsqr:
        q_pad, r = _tsqr_shard_map(A, compute_q=(mode == "reduced"))
        R = DNDarray.from_dense(r, None, A.device, A.comm)
        if mode == "r":
            return QR(None, R)
        Q = DNDarray(
            jax.device_put(q_pad, comm.sharding(0)),
            (m, n),
            A.dtype,
            0,
            A.device,
            A.comm,
        )
        return QR(Q, R)

    # general path: XLA's QR over the (sharded) dense view
    dense = A._dense()
    if mode == "r":
        r = jnp.linalg.qr(dense, mode="r")
        return QR(None, DNDarray.from_dense(r, None if A.ndim == 2 else A.split, A.device, A.comm))
    q, r = jnp.linalg.qr(dense, mode="reduced")
    q_split = A.split
    r_split = None if A.ndim == 2 and A.split == 0 else A.split
    if A.ndim == 2 and A.split == 1:
        r_split = 1
    return QR(
        DNDarray.from_dense(q, q_split, A.device, A.comm),
        DNDarray.from_dense(r, r_split, A.device, A.comm),
    )


def _tsqr_shard_map(A: DNDarray, compute_q: bool = True):
    """Single-level TS-QR as a shard_map collective (see module docstring).

    Requires m divisible by p and m/p >= n (caller checks).
    """
    comm = A.comm
    q, r = _tsqr_fn(comm, compute_q)(A.larray_padded)
    # r is replicated identically on all shards; take it as the global R
    return q, r


@functools.lru_cache(maxsize=64)
def _tsqr_fn(comm, compute_q: bool):
    """Jitted, cached TS-QR executable — rebuilding the shard_map per call
    would retrace (and through a remote compile service, recompile) on
    every invocation."""
    mesh = comm.mesh
    axis = comm.axis_name

    def body(a_loc):
        # a_loc: (m/p, n) local block
        n = a_loc.shape[1]
        q1, r1 = jnp.linalg.qr(a_loc, mode="reduced")  # (m/p, n), (n, n)
        r_all = jax.lax.all_gather(r1, axis, axis=0, tiled=True)  # (p*n, n)
        q2, r2 = jnp.linalg.qr(r_all, mode="reduced")  # (p*n, n), (n, n)
        idx = jax.lax.axis_index(axis)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, idx * n, n, axis=0)  # (n, n)
        q_loc = jnp.matmul(q1, q2_block, precision=jax.lax.Precision.HIGHEST) if compute_q else q1
        return q_loc, r2

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=(P(axis, None), P(None, None)),
            # r2 is computed redundantly from the all-gathered R stack, so it
            # is replicated by construction; the static analyzer cannot see
            # through the QR call to prove it
            check_vma=False,
        )
    )
