"""Data tooling (analog of heat/utils/data)."""

from . import matrixgallery
from .datatools import DataLoader, Dataset, dataset_irecv, dataset_ishuffle, dataset_shuffle
from .mnist import MNISTDataset, synthetic_mnist
from .partial_dataset import PartialH5DataLoaderIter, PartialH5Dataset
from .prefetch import prefetch_to_device, sharding_for_batch
from .spherical import create_clusters, create_spherical_dataset

__all__ = [
    "DataLoader",
    "Dataset",
    "MNISTDataset",
    "PartialH5DataLoaderIter",
    "PartialH5Dataset",
    "create_clusters",
    "create_spherical_dataset",
    "dataset_irecv",
    "dataset_ishuffle",
    "dataset_shuffle",
    "matrixgallery",
    "prefetch_to_device",
    "sharding_for_batch",
    "synthetic_mnist",
]
