"""Shared environment-knob parsing (single source for the precision
tables that the FFT and hsvd layers both expose)."""

from __future__ import annotations

import os

import jax

_PRECISION_TABLE = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}


def precision_name_from_env(var: str, default: str) -> str:
    """Normalized precision name from an env var with a diagnostic error."""
    name = os.environ.get(var, default).strip().lower()
    if name not in _PRECISION_TABLE:
        raise ValueError(
            f"{var}={os.environ.get(var)!r}: expected one of {sorted(_PRECISION_TABLE)}"
        )
    return name


def precision_from_env(var: str, default: str):
    """``jax.lax.Precision`` from an env var with a diagnostic error."""
    return _PRECISION_TABLE[precision_name_from_env(var, default)]
