"""Estimator base classes, analog of heat/core/base.py (base.py:13-321),
plus the shared resumable-fit machinery (checkpoint_every / resume_from)
the iterative estimators build on."""

from __future__ import annotations

import inspect
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_clusterer",
    "is_regressor",
    "is_transformer",
    "lazy_scalar_property",
    "resumable_fit_loop",
    "validate_resume_params",
]


def validate_resume_params(
    checkpoint_every: Optional[int],
    checkpoint_dir: Optional[str],
    resume_from: Optional[str],
) -> None:
    """Shared constructor validation for the resumable-fit parameters."""
    if checkpoint_every is not None:
        if not isinstance(checkpoint_every, int) or checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be a positive int, got {checkpoint_every!r}"
            )
        if checkpoint_dir is None and resume_from is None:
            raise ValueError(
                "checkpoint_every requires checkpoint_dir (or resume_from) "
                "to name the checkpoint directory"
            )


def resumable_fit_loop(
    run_chunk: Callable,
    init_state: Callable,
    max_iter: int,
    tol: float,
    checkpoint_every: Optional[int] = None,
    checkpoint_dir: Optional[str] = None,
    resume_from: Optional[str] = None,
    site: str = "estimator.iter",
    what: str = "iterate",
    converged_when: Optional[Callable[[float, float], bool]] = None,
    exhausted_converges: bool = True,
) -> Tuple[object, int]:
    """Drive an on-device fit loop in resumable, divergence-guarded chunks.

    The fast paths of the iterative estimators run their whole fit as ONE
    on-device ``lax.while_loop`` (zero host syncs).  With
    ``checkpoint_every=N`` the same loop body runs in chunks of N
    iterations — each chunk is still one device program — and between
    chunks the iterate is (a) checked finite (:class:`DivergenceError`
    carrying the last good iterate on NaN/Inf), (b) offered to the fault
    injector (site ``<estimator>.iter`` — the hook kill-and-resume tests
    script), and (c) checkpointed through the filesystem-native
    :class:`~heat_tpu.utils.checkpoint.Checkpointer`.  The iteration
    sequence is identical to the uninterrupted loop, so a killed fit
    resumed from its last checkpoint reproduces the uninterrupted result
    exactly.

    ``run_chunk(state, n)`` runs at most ``n`` iterations from ``state``
    and returns ``(new_state, iters_run, shift)`` (device values);
    ``init_state()`` builds the initial iterate (only called when not
    resuming, so RNG draws consumed by initialization are not replayed
    on resume).  ``converged_when(shift, tol)`` must mirror the device
    loop's own stop test (default ``shift <= tol``) so a chunk boundary
    never stops the fit one iteration early or late relative to the
    uninterrupted loop.  Returns ``(final_state, total_iterations)``.

    Checkpoint writes are **asynchronous** by default (overlap layer,
    docs/overlap.md): chunk *k*'s atomic write runs on a background
    writer while chunk *k+1* computes on device, and the loop drains it
    (``wait()``) before evaluating the next chunk boundary — so the
    fault/kill semantics are unchanged (a kill at boundary *k+1* always
    finds chunk *k* durable, exactly like the synchronous loop) and the
    loop never returns before its final checkpoint is committed.
    ``HEAT_TPU_ASYNC_CKPT=0`` restores fully synchronous saves.

    ``exhausted_converges`` controls what a short chunk (``iters_run <
    n``) means.  For the finite fits it means the device loop's own stop
    test fired inside the chunk — converged (the default).  The online
    estimators (heat_tpu/streaming, chunk = stream window) set it False:
    a short chunk there means the stream head ran dry, so the loop
    checkpoints ``converged=False`` and returns — a later call with the
    same directory resumes and keeps consuming where the committed
    offset (inside ``state``) left off, instead of early-returning on a
    fit that never actually converged.
    """
    import os as _os
    import sys as _sys
    import time as _time

    from ..resilience.errors import DivergenceError, PreemptedError  # lazy: avoid import cycles
    from ..resilience.faults import inject
    from ..resilience.guard import all_finite
    from ..telemetry import metrics as _tm
    from ..telemetry.spans import span as _span
    from ..utils.checkpoint import Checkpointer
    from ..utils.overlap import async_checkpoint_enabled
    from ._env import env_str
    from .preempt import preemption_gate

    # fit heartbeat: iterations/s of the most recent chunk and its
    # convergence delta, refreshed at every chunk boundary so a stalled
    # or diverging long fit is visible from telemetry.snapshot();
    # fit.heartbeat_ts is the liveness signal /healthz judges staleness
    # against (HEAT_TPU_HEALTH_MAX_AGE_S, telemetry/server.py)
    iter_rate_g = _tm.gauge("fit.iter_rate", "iterations/s of the last fit chunk")
    shift_g = _tm.gauge("fit.shift", "convergence delta of the last fit chunk")
    heartbeat_g = _tm.gauge(
        "fit.heartbeat_ts", "unix time of the last resumable-fit chunk boundary"
    )
    # cross-process liveness: with HEAT_TPU_HEARTBEAT_FILE set, every
    # chunk boundary also touches a file, so an external supervisor (the
    # elastic process supervisor, docs/elasticity.md) can distinguish a
    # computing worker from a hung one without an HTTP scrape
    hb_file = env_str("HEAT_TPU_HEARTBEAT_FILE")

    def _beat() -> None:
        heartbeat_g.set(_time.time())
        if hb_file:
            try:
                _os.close(_os.open(hb_file, _os.O_CREAT | _os.O_WRONLY, 0o644))
                _os.utime(hb_file, None)
            except OSError:
                pass  # liveness signal is best-effort; never fail the fit

    ckpt = None
    directory = checkpoint_dir or resume_from
    if directory is not None and checkpoint_every is not None:
        ckpt = Checkpointer(directory)
        if async_checkpoint_enabled():
            ckpt = ckpt.as_async()

    state = None
    total = 0
    if resume_from is not None:
        reader = ckpt if ckpt is not None else Checkpointer(resume_from)
        step = reader.latest_step()
        if step is not None:
            saved = reader.restore(step)
            state = saved["state"]
            total = int(saved["n_iter"])
            if saved.get("converged") or total >= max_iter:
                return state, total
    if state is None:
        state = init_state()

    chunk = checkpoint_every if checkpoint_every is not None else max_iter
    # device references, not host copies: the last-good iterate only
    # converts to a host array if a DivergenceError actually needs it
    last_good = (state, total)
    try:
        while total < max_iter:
            n = min(chunk, max_iter - total)
            _beat()  # entering a chunk counts as alive
            t0 = _time.perf_counter()
            # heartbeat span: one per chunk, attrs filled in once the
            # chunk's device values are known
            with _span("fit.chunk", site=site) as sp:
                new_state, iters_dev, shift_dev = run_chunk(state, n)
                iters = int(iters_dev)
                shift = float(shift_dev)
            elapsed = _time.perf_counter() - t0
            sp.attrs.update(iters=iters, shift=shift, total=total + iters)
            _beat()
            iter_rate_g.set(iters / elapsed if elapsed > 0 else 0.0)
            shift_g.set(shift)
            total += iters
            if ckpt is not None:
                # the previous chunk's async write overlapped this
                # chunk's compute; drain it before the boundary so a
                # scripted kill/fault here sees it durable (sync: no-op)
                ckpt.wait()
            inject(site, iteration=total)
            if not all_finite(new_state):
                raise DivergenceError(
                    f"non-finite values in {what} at iteration {total} — the fit "
                    f"has diverged; last finite {what} is at iteration {last_good[1]}",
                    iteration=total,
                    # dict (pytree) states pass through structured; array
                    # states convert like before
                    last_good=(
                        last_good[0]
                        if isinstance(last_good[0], dict)
                        else np.asarray(last_good[0])
                    ),
                    last_good_iteration=last_good[1],
                )
            state = new_state
            stop_test = converged_when if converged_when is not None else (lambda s, t: s <= t)
            short_chunk = iters < n
            converged = stop_test(shift, tol) or (exhausted_converges and short_chunk)
            if ckpt is not None:
                ckpt.save(
                    total,
                    {
                        "state": state,
                        "n_iter": total,
                        "shift": shift,
                        "converged": bool(converged),
                    },
                )
            if converged or short_chunk:
                # a short chunk always ends the loop; with
                # exhausted_converges=False it ends it PAUSED (the
                # checkpoint above committed converged=False, so a
                # resume keeps going when more stream data arrives)
                break
            # QoS preemption poll — after the boundary checkpoint is
            # scheduled, so the pause is durable, and only for fits
            # that actually checkpoint (take(durable=False) refuses and
            # counts the refusal).  The qos.preempt site fires only
            # when the gate is honored, so a scripted kill here lands
            # at the exact yield moment; raising instead pauses
            # cooperatively — either way a resume_from the same
            # directory reproduces the uninterrupted result bitwise.
            preempt_reason = preemption_gate().take(durable=ckpt is not None)
            if preempt_reason is not None:
                inject("qos.preempt", iteration=total, reason=preempt_reason)
                raise PreemptedError(
                    f"{what} fit preempted at iteration {total} "
                    f"({preempt_reason}); resume from {directory!r} to "
                    "continue the identical iteration sequence",
                    iteration=total,
                    checkpoint_dir=directory,
                    reason=preempt_reason,
                )
            last_good = (state, total)
    finally:
        if ckpt is not None:
            if _sys.exc_info()[0] is None:
                ckpt.close()  # final write durable before the fit returns
            else:
                try:  # body exception wins over a late writer error
                    ckpt.close()
                except BaseException:  # lint: allow H501(body exception wins over a late writer error)
                    pass
    return state, total


def lazy_scalar_property(attr: str, kind: type = float, doc: Optional[str] = None) -> property:
    """Property converting a stored device scalar to a host ``kind`` lazily.

    Fits store 0-d device values in ``attr`` so they never block on the
    device link; the host conversion happens once, on first access, and the
    converted value is cached back.  Shared by the cluster/PCA/Lasso/
    GaussianNB estimators (one pattern, one implementation)."""

    def fget(self):
        v = getattr(self, attr)
        if v is not None and not isinstance(v, kind):
            v = kind(v)
            setattr(self, attr, v)
        return v

    def fset(self, value):
        setattr(self, attr, value)

    return property(fget, fset, doc=doc or f"Lazy host {kind.__name__} of ``{attr}``.")


class BaseEstimator:
    """sklearn-compatible estimator base (base.py:13-95)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return [
            name
            for name, p in sig.parameters.items()
            if name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]

    def get_params(self, deep: bool = True) -> Dict:
        """Parameters of this estimator (base.py:30)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set estimator parameters (base.py:60)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        for key, value in params.items():
            key, _, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}.")
            if sub_key:
                valid[key].set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params(deep=False).items())
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """fit/predict protocol for classifiers (base.py:96)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class TransformMixin:
    """fit/transform protocol (base.py:143)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        return self.fit(x).transform(x)

    def transform(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    """fit/fit_predict protocol for clusterers (base.py:184)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """fit/predict protocol for regressors (base.py:215)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


def is_classifier(estimator) -> bool:
    """True for classifiers (base.py:260)."""
    return isinstance(estimator, ClassificationMixin)


def is_estimator(estimator) -> bool:
    """True for estimators (base.py:275)."""
    return isinstance(estimator, BaseEstimator)


def is_clusterer(estimator) -> bool:
    """True for clusterers (base.py:290)."""
    return isinstance(estimator, ClusteringMixin)


def is_regressor(estimator) -> bool:
    """True for regressors (base.py:305)."""
    return isinstance(estimator, RegressionMixin)


def is_transformer(estimator) -> bool:
    """True for transformers (base.py:320)."""
    return isinstance(estimator, TransformMixin)
