"""Generate the API reference markdown from live docstrings.

The reference ships a Sphinx autodoc tree; this environment has no
sphinx, so the same information — every public export per module with
its signature and summary line — is extracted with ``inspect`` into one
markdown page that ``build_docs.py`` renders into the site.

    python scripts/build_api_docs.py [--out docs/api_reference.md]
"""

import argparse
import inspect
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")

import jax

jax.config.update("jax_platforms", "cpu")

#: (section title, module path, note)
MODULES = [
    ("Top level", "heat_tpu", "factories, arithmetics, manipulations and the rest of the numpy-style surface"),
    ("Dispatch", "heat_tpu.core.dispatch", "cached-executable dispatch, chain fusion, buffer donation (docs/dispatch.md)"),
    ("Resilience", "heat_tpu.resilience", "fault injection, retry policies, atomic IO, divergence guards (docs/resilience.md)"),
    ("Overlap", "heat_tpu.utils.overlap", "async checkpointing, device prefetch + bucketed gradient-reduction counters (docs/overlap.md)"),
    ("Observability", "heat_tpu.telemetry", "unified metrics registry, structured spans, comm-volume accounting (docs/observability.md)"),
    ("Request tracing", "heat_tpu.telemetry.tracing", "request-scoped distributed tracing: trace context + handoff helpers, tail-sampled trace store, /tracez + exemplars (docs/observability.md)"),
    ("SLO monitors", "heat_tpu.telemetry.slo", "declarative objectives with multi-window burn-rate alerting over the bounded histograms (/sloz; docs/observability.md)"),
    ("Input-drift sketches", "heat_tpu.telemetry.sketch", "streaming per-feature moment + log-bucket sketches, PSI/KL divergence vs persisted baselines (/driftz; docs/observability.md)"),
    ("Alerts", "heat_tpu.telemetry.alerts", "deduplicated severity-tagged fired/resolved alert events with exemplar trace ids (docs/observability.md)"),
    ("Decision journal", "heat_tpu.telemetry.journal", "typed control-plane decision events with causal links + evidence, bounded hot ring + durable atomic/CRC segment log (/decisionz; docs/observability.md)"),
    ("Metric history (TSDB)", "heat_tpu.telemetry.tsdb", "embedded fixed-interval metric history: allowlisted series sampled into bounded rings, range queries + window stats (/queryz; docs/observability.md)"),
    ("Journal replay", "heat_tpu.telemetry.replay", "offline reconstruction of the decision timeline and causal chains from a durable journal directory (python -m heat_tpu.telemetry.replay; docs/observability.md)"),
    ("Roofline observatory", "heat_tpu.telemetry.observatory", "per-executable runtime attribution: sampled execution ledger, device-peak calibration, live HBM watermarks, on-demand profiler capture (/rooflinez + /profilez; docs/observability.md)"),
    ("Static analysis", "heat_tpu.analysis", "SPMD program lint (J101-J105) + framework-invariant AST lint (H101-H601, H701-H705) (docs/static_analysis.md)"),
    ("Dtype-flow lint", "heat_tpu.analysis.dtype_flow", "jaxpr precision lint: silent truncation, low-precision accumulation, unpinned contractions, policy violations (J201-J204; docs/static_analysis.md)"),
    ("Peak-HBM estimator", "heat_tpu.analysis.memory_model", "static per-device peak-memory prediction from the jaxpr (liveness + donation + sharding), J301 against HEAT_TPU_HBM_BUDGET_BYTES (docs/static_analysis.md)"),
    ("Precision policies", "heat_tpu.analysis.precision_policy", "the per-estimator bitwise/tolerance POLICIES registry and its three enforcement choke points (docs/static_analysis.md)"),
    ("Concurrency sanitizer", "heat_tpu.analysis.tsan", "runtime lock-order/unguarded-access sanitizer over the central LOCK_REGISTRY (HEAT_TPU_TSAN; docs/static_analysis.md)"),
    ("Control-plane protocols", "heat_tpu.analysis.protocols", "pure-literal PROTOCOLS registry: every controller's declared state machine, journal vocabulary constants, temporal PROPERTIES (docs/static_analysis.md)"),
    ("Protocol model checker", "heat_tpu.analysis.model_check", "bounded exhaustive check of the declared machines against the adversarial environment; counterexamples as synthetic causal journal chains (python -m heat_tpu.analysis.model_check; docs/static_analysis.md)"),
    ("Protocol conformance", "heat_tpu.analysis.conformance", "runtime stepping of live journal events through the declared machines, H805 on illegal transitions (HEAT_TPU_PROTOCOL_CHECK; docs/static_analysis.md)"),
    ("Elastic", "heat_tpu.elastic", "worker-loss detection, mesh reshape + cross-world resume supervision (docs/elasticity.md)"),
    ("Serving", "heat_tpu.serving", "online inference: model registry + hot-load, request coalescing with pad-to-bucket dispatch, per-tenant admission control, /v1 HTTP endpoints (docs/serving.md)"),
    ("Fleet", "heat_tpu.fleet", "fleet-scale serving: fault-tolerant replica router (consistent-hash affinity, circuit breakers, bounded-retry failover), replica process management, load-driven elastic autoscaling (docs/fleet.md)"),
    ("Streaming", "heat_tpu.streaming", "streaming continuous learning: replayable sources (durable segment log), windowed exactly-once consumer, online fits with bitwise kill+resume, drift-triggered refresh driver (docs/streaming.md)"),
    ("AOT cache", "heat_tpu.core.aot_cache", "persistent on-disk AOT executable cache: serialized compiled artifacts keyed by the dispatch operand-spec keys, fingerprint-invalidated (docs/fleet.md)"),
    ("Lock registry", "heat_tpu.analysis.concurrency", "central registry of cross-thread locks and the structures they guard (the H7xx rules and the sanitizer share it)"),
    ("Communication", "heat_tpu.parallel.comm", "mesh/communication layer"),
    ("Linear algebra", "heat_tpu.core.linalg.basics", None),
    ("QR / SVD / solvers", "heat_tpu.core.linalg.qr", None),
    ("Hierarchical SVD", "heat_tpu.core.linalg.svdtools", None),
    ("Solvers", "heat_tpu.core.linalg.solver", None),
    ("FFT", "heat_tpu.fft.fft", None),
    ("Sparse", "heat_tpu.sparse", None),
    ("Clustering", "heat_tpu.cluster", None),
    ("Classification", "heat_tpu.classification", None),
    ("Decomposition", "heat_tpu.decomposition", None),
    ("Preprocessing", "heat_tpu.preprocessing", None),
    ("Regression", "heat_tpu.regression", None),
    ("Naive Bayes", "heat_tpu.naive_bayes", None),
    ("Spatial", "heat_tpu.spatial", None),
    ("Graph", "heat_tpu.graph", None),
    ("Neural nets", "heat_tpu.nn", None),
    ("Optimizers", "heat_tpu.optim", None),
    ("IO", "heat_tpu.core.io", None),
    ("Random", "heat_tpu.core.random", None),
    ("Statistics", "heat_tpu.core.statistics", None),
    ("Signal", "heat_tpu.core.signal", None),
    ("Data utilities", "heat_tpu.utils.data", None),
    ("Checkpointing", "heat_tpu.utils.checkpoint", None),
    ("Profiling", "heat_tpu.utils.profiling", None),
]


def _sig(obj) -> str:
    try:
        return str(inspect.signature(obj))
    except (TypeError, ValueError):
        return "(...)"


def _summary(obj) -> str:
    doc = inspect.getdoc(obj) or ""
    line = doc.strip().split("\n", 1)[0].strip()
    return line


def document_module(modpath: str):
    import importlib

    mod = importlib.import_module(modpath)
    names = getattr(mod, "__all__", None)
    if not names:
        names = [n for n in dir(mod) if not n.startswith("_")]
        names = [
            n for n in names
            if getattr(getattr(mod, n, None), "__module__", "").startswith("heat_tpu")
            or inspect.isroutine(getattr(mod, n, None))
        ]
    rows = []
    for n in sorted(set(names)):
        obj = getattr(mod, n, None)
        if obj is None or inspect.ismodule(obj):
            continue
        if inspect.isclass(obj):
            rows.append((f"class {n}", _summary(obj)))
            for mn, mobj in sorted(inspect.getmembers(obj, inspect.isfunction)):
                if mn.startswith("_"):
                    continue
                rows.append((f"{n}.{mn}{_sig(mobj)}", _summary(mobj)))
        elif inspect.isroutine(obj):
            rows.append((f"{n}{_sig(obj)}", _summary(obj)))
        else:
            rows.append((n, type(obj).__name__))
    return rows


def build_env_vars(out_path: str) -> int:
    """Generate ``docs/env_vars.md`` from the central knob registry
    (``heat_tpu.core._env.KNOBS``) — the same table the typed accessors
    and the H201 lint rule enforce, so the docs cannot drift from the
    code.  Returns the number of documented knobs."""
    from heat_tpu.core._env import KNOBS

    lines = [
        "# Environment variables",
        "",
        "Generated from the central knob registry (`heat_tpu/core/_env.py"
        " KNOBS`) by `scripts/build_api_docs.py` — do not edit.",
        "",
        "Every `HEAT_TPU_*` knob the framework reads is registered in that"
        " one table (name, type, default, doc); the typed accessors"
        " (`env_flag`/`env_int`/`env_float`/`env_str`) refuse unregistered"
        " names and the AST linter's [H201 rule](static_analysis.md) flags"
        " any direct `os.environ` read of an unregistered `HEAT_TPU_*`"
        " literal — so this page is complete by construction.",
        "",
        "Boolean knobs treat `0/false/no/off` (any case) as off and"
        " anything else as on.  An empty default means *unset* (the"
        " consumer auto-detects).",
        "",
        "| variable | type | default | effect |",
        "|---|---|---|---|",
    ]
    for name in sorted(KNOBS):
        typ, default, doc = KNOBS[name]
        shown = f"`{default}`" if default != "" else "*(unset)*"
        lines.append(f"| `{name}` | {typ} | {shown} | {doc} |")
    lines += [
        "",
        "See also: [static analysis](static_analysis.md),"
        " [dispatch layer](dispatch.md), [resilience](resilience.md),"
        " [overlap layer](overlap.md), [observability](observability.md).",
        "",
    ]
    with open(out_path, "w") as f:
        f.write("\n".join(lines))
    return len(KNOBS)


#: markers bounding the generated endpoint-index block inside
#: docs/observability.md (everything between them is regenerated)
ENDPOINT_BEGIN = "<!-- BEGIN GENERATED: endpoint-index (scripts/build_api_docs.py) -->"
ENDPOINT_END = "<!-- END GENERATED: endpoint-index -->"


def build_endpoint_index(doc_path: str) -> int:
    """Regenerate the endpoint-index table in ``docs/observability.md``
    from the server's declarative route registry
    (``heat_tpu.telemetry.server.BUILTIN_ROUTES``) — one source of
    truth, so a new route cannot ship without its docs row.  Returns the
    number of routes written."""
    from heat_tpu.telemetry.server import BUILTIN_ROUTES

    rows = [
        "| route | purpose | knobs |",
        "|---|---|---|",
    ]
    for r in BUILTIN_ROUTES:
        knobs = ", ".join(f"`{k}`" for k in r["knobs"]) or "—"
        purpose = str(r["purpose"]).replace("|", "\\|")
        rows.append(f"| `{r['route']}` | {purpose} | {knobs} |")
    with open(doc_path) as f:
        text = f.read()
    try:
        head, rest = text.split(ENDPOINT_BEGIN, 1)
        _, tail = rest.split(ENDPOINT_END, 1)
    except ValueError:
        raise SystemExit(
            f"{doc_path} is missing the endpoint-index markers "
            f"({ENDPOINT_BEGIN!r} ... {ENDPOINT_END!r})"
        )
    block = ENDPOINT_BEGIN + "\n" + "\n".join(rows) + "\n" + ENDPOINT_END
    with open(doc_path, "w") as f:
        f.write(head + block + tail)
    return len(BUILTIN_ROUTES)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(REPO, "docs", "api_reference.md"))
    ap.add_argument("--env-out", default=os.path.join(REPO, "docs", "env_vars.md"))
    ap.add_argument(
        "--endpoints-doc",
        default=os.path.join(REPO, "docs", "observability.md"),
    )
    args = ap.parse_args()

    n_knobs = build_env_vars(args.env_out)
    print(f"env vars: {n_knobs} knobs -> {args.env_out}")

    n_routes = build_endpoint_index(args.endpoints_doc)
    print(f"endpoint index: {n_routes} routes -> {args.endpoints_doc}")

    parts = [
        "# API reference",
        "",
        "Generated from live docstrings by `scripts/build_api_docs.py` — do not edit.",
        "Reference `file:line` citations inside each docstring point at the",
        "upstream component the export mirrors.",
        "",
        "> **Note for `ht.jit` users:** executable caching and elementwise chain",
        "> fusion are now the DEFAULT behavior of the eager op surface — every op",
        "> dispatches through a cached compiled executable, and elementwise",
        "> chains defer and fuse into one XLA computation automatically (see",
        "> [dispatch.md](dispatch.md)).  `ht.jit` is still worth reaching for",
        "> when you want a whole pipeline — reductions, matmuls, control flow —",
        "> fused into a single program; for plain elementwise chains feeding a",
        "> reduction it no longer buys anything over the default path.",
        "",
    ]
    total = 0
    failures = []
    for title, modpath, note in MODULES:
        try:
            rows = document_module(modpath)
        except Exception as e:
            # a module that fails to import means a GUTTED reference —
            # record it and fail the build below instead of silently
            # publishing an incomplete page
            failures.append(f"{modpath}: {type(e).__name__}: {e}")
            continue
        parts.append(f"## {title} (`{modpath}`)")
        if note:
            parts.append(f"\n{note}\n")
        parts.append("")
        parts.append("| export | summary |")
        parts.append("|---|---|")
        for sig, summ in rows:
            sig_md = sig.replace("|", "\\|")
            summ_md = (summ or "").replace("|", "\\|")
            parts.append(f"| `{sig_md}` | {summ_md} |")
            total += 1
        parts.append("")
    with open(args.out, "w") as f:
        f.write("\n".join(parts))
    print(f"api reference: {total} entries -> {args.out}")
    if failures or total == 0:
        for msg in failures:
            print(f"FAILED module: {msg}", file=sys.stderr)
        sys.exit(1)


if __name__ == "__main__":
    main()
