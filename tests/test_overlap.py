"""Overlap-layer tests (ISSUE 3 tentpole).

The contract under test (docs/overlap.md):

* AsyncCheckpointer writes asynchronously with at most one save in
  flight (back-pressure on overrun), re-raises writer errors at the next
  save()/wait()/close(), and keeps every atomicity guarantee of the
  synchronous checkpointer — a subprocess killed mid-async-write leaves
  no partial step and resumes to the uninterrupted result bitwise;
* resumable fits overlap checkpoint writes with the next on-device
  chunk and still match the uninterrupted fit bitwise (sync fallback
  via HEAT_TPU_ASYNC_CKPT=0 included);
* prefetch_to_device preserves order, stages with the requested
  sharding, propagates StopIteration, and feeds the shared
  prefetch_hits/misses counters;
* the windowed loader iterator works without h5py through the
  read_window hook (tuple windows, transforms, error propagation via
  the BaseException put path) and close() retires the worker thread
  even when the ready queue is full (the PR 2 leak);
* bucketed and fused gradient-reduction schedules produce identical
  parameter updates (flat and hierarchical two-stage meshes), and
  DataParallelOptimizer.blocking routes schedule selection.
"""

import json
import os
import subprocess
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import resilience as rz
from heat_tpu.utils import overlap as ov
from heat_tpu.utils.checkpoint import Checkpointer
from heat_tpu.utils.data import prefetch_to_device, sharding_for_batch
from heat_tpu.utils.data.partial_dataset import PartialH5DataLoaderIter


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    monkeypatch.setenv("HEAT_TPU_RETRY_NO_SLEEP", "1")


# ----------------------------------------------------------------------
# async checkpointing
# ----------------------------------------------------------------------
class TestAsyncCheckpointer:
    def test_roundtrip_and_counters(self, tmp_path):
        ov.reset_overlap_stats()
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        state = {"state": np.arange(32, dtype=np.float32), "n_iter": 3}
        ack.save(3, state)
        ack.save(7, {"state": np.arange(32, dtype=np.float32) * 2, "n_iter": 7})
        assert ack.all_steps() == [3, 7]
        got = ack.restore(7)
        np.testing.assert_array_equal(got["state"], np.arange(32, dtype=np.float32) * 2)
        ack.close()
        s = ov.overlap_stats()
        assert s["async_saves"] == 2

    def test_snapshot_isolated_from_caller_mutation(self, tmp_path):
        """The snapshot is consistent even if the caller mutates its numpy
        state right after save() returns (the fit-loop contract)."""
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        arr = np.arange(16, dtype=np.float32)
        ack.save(0, {"state": arr})
        arr[:] = -1.0  # mutate while the write may still be in flight
        ack.close()
        np.testing.assert_array_equal(
            ack.restore(0)["state"], np.arange(16, dtype=np.float32)
        )

    def test_device_state_snapshots_nonblocking(self, tmp_path):
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        dev = jnp.arange(64, dtype=jnp.float32) * 3
        ack.save(1, {"state": dev, "n_iter": 1})
        ack.close()
        np.testing.assert_array_equal(ack.restore(1)["state"], np.asarray(dev))

    def test_at_most_one_in_flight_backpressure(self, tmp_path, monkeypatch):
        """A second save() during a slow write blocks until the first
        completes — saves are never reordered or dropped."""
        ck = Checkpointer(str(tmp_path / "ck"))
        gate = threading.Event()
        orig = ck.save
        order = []

        def slow_save(step, state, extra_metadata=None, async_=False):
            gate.wait(timeout=10)
            order.append(step)
            return orig(step, state, extra_metadata)

        monkeypatch.setattr(ck, "save", slow_save)
        ack = ov.AsyncCheckpointer(ck)
        ack.save(0, {"v": np.arange(4)})  # writer now blocked on the gate
        t0 = time.perf_counter()
        release = threading.Timer(0.2, gate.set)
        release.start()
        ack.save(1, {"v": np.arange(4)})  # must back-pressure on save 0
        waited = time.perf_counter() - t0
        ack.close()
        release.cancel()
        assert order == [0, 1]
        assert waited >= 0.15  # blocked until the gate released save 0

    def test_writer_error_reraised_at_next_call(self, tmp_path):
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        with rz.fault_plan({"checkpoint.async_write": [{"at": 0, "kind": "permanent"}]}) as inj:
            ack.save(0, {"v": np.arange(4)})
            with pytest.raises(rz.PermanentFault):
                ack.wait()
        assert inj.injected["checkpoint.async_write"] == [(0, "permanent")]
        # the error was consumed; the checkpointer is usable again
        ack.save(1, {"v": np.arange(4)})
        ack.close()
        assert ack.all_steps() == [1]

    def test_writer_error_surfaces_at_next_save_and_close(self, tmp_path):
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        with rz.fault_plan({"checkpoint.async_write": [0, 1]}) as inj:
            # transient faults are NOT retried across the async boundary
            # transparently swallowed — they surface to the caller
            ack.save(0, {"v": np.arange(4)})
            with pytest.raises(rz.TransientFault):
                ack.save(1, {"v": np.arange(4)})
            ack.wait()  # save 1's write was never enqueued; nothing pending
        assert inj.injected["checkpoint.async_write"] == [(0, "transient")]

    def test_save_async_param_on_checkpointer(self, tmp_path):
        ck = Checkpointer(str(tmp_path / "ck"))
        ck.save(2, {"v": np.arange(6)}, async_=True)
        # read side drains the internal async front end
        assert ck.latest_step() == 2
        np.testing.assert_array_equal(ck.restore(2)["v"], np.arange(6))
        ck.close()

    def test_transient_fault_in_write_path_still_retried(self, tmp_path):
        """The writer thread runs the same io retry policy: a transient
        checkpoint.save fault is absorbed, not surfaced."""
        ack = Checkpointer(str(tmp_path / "ck")).as_async()
        with rz.fault_plan({"checkpoint.save": [0]}) as inj:
            ack.save(4, {"v": np.arange(3)})
            ack.wait()  # no raise: retry absorbed the transient
        assert inj.injected["checkpoint.save"] == [(0, "transient")]
        assert ack.all_steps() == [4]

    def test_context_manager(self, tmp_path):
        with Checkpointer(str(tmp_path / "ck")).as_async() as ack:
            ack.save(0, {"v": np.arange(2)})
        assert Checkpointer(str(tmp_path / "ck")).all_steps() == [0]


# ----------------------------------------------------------------------
# async resumable fits
# ----------------------------------------------------------------------
def _data(n=240, f=6, seed=13):
    ht.random.seed(seed)
    return ht.random.randn(n, f, split=0).astype(ht.float32)


class TestAsyncResumableFits:
    def test_chunked_fit_uses_async_saves_and_matches_plain(self, tmp_path):
        ov.reset_overlap_stats()
        x = _data()
        kw = dict(n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3)
        plain = ht.cluster.KMeans(**kw).fit(x)
        ck = ht.cluster.KMeans(**kw, checkpoint_every=5, checkpoint_dir=str(tmp_path)).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(ck.cluster_centers_._dense()),
        )
        assert Checkpointer(str(tmp_path)).latest_step() == ck.n_iter_
        assert ov.overlap_stats()["async_saves"] > 0  # the overlap path ran

    def test_sync_fallback_env(self, tmp_path, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_ASYNC_CKPT", "0")
        ov.reset_overlap_stats()
        x = _data()
        kw = dict(n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3)
        plain = ht.cluster.KMeans(**kw).fit(x)
        ck = ht.cluster.KMeans(**kw, checkpoint_every=5, checkpoint_dir=str(tmp_path)).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(ck.cluster_centers_._dense()),
        )
        assert ov.overlap_stats()["async_saves"] == 0

    def test_async_write_fault_surfaces_from_fit(self, tmp_path):
        x = _data()
        with rz.fault_plan({"checkpoint.async_write": [{"at": 0, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ht.cluster.KMeans(
                    n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3,
                    checkpoint_every=2, checkpoint_dir=str(tmp_path),
                ).fit(x)

    def test_subprocess_kill_mid_async_write_no_partial_step(self, tmp_path):
        """Real preemption DURING an overlapped write: the env fault plan
        os._exit-kills the child on the background writer thread inside
        the second checkpoint's staged write (`checkpoint.write` fires
        per file; index 2 is step 4's state.json).  No partial step may
        be visible, and resuming must reproduce the uninterrupted fit
        bitwise — extends the PR 2 kill test to the async path."""
        d = str(tmp_path / "ck")
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"  # mirror conftest
            "import heat_tpu as ht\n"
            "ht.random.seed(13)\n"
            "x = ht.random.randn(240, 6, split=0).astype(ht.float32)\n"
            f"ht.cluster.KMeans(n_clusters=4, init='random', max_iter=40, tol=1e-4,\n"
            f"                  random_state=3, checkpoint_every=2, checkpoint_dir={d!r}).fit(x)\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env.pop("HEAT_TPU_ASYNC_CKPT", None)  # async on (the default)
        env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
            {"plan": {"checkpoint.write": [{"at": 2, "kind": "kill", "exit_code": 137}]}}
        )
        proc = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True, timeout=300
        )
        assert proc.returncode == 137, proc.stderr.decode()[-2000:]
        # the interrupted write left no torn step directory behind
        steps = Checkpointer(d).all_steps()
        assert steps == [2], steps
        x = _data()
        plain = ht.cluster.KMeans(
            n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3
        ).fit(x)
        resumed = ht.cluster.KMeans(
            n_clusters=4, init="random", max_iter=40, tol=1e-4, random_state=3,
            checkpoint_every=2, resume_from=d,
        ).fit(x)
        assert np.array_equal(
            np.asarray(plain.cluster_centers_._dense()),
            np.asarray(resumed.cluster_centers_._dense()),
        )

    def test_pca_stage_writes_drained_on_fault(self, tmp_path):
        """PCA's mean-stage write runs on the async writer; a solver-stage
        fault must still leave the mean checkpoint durable (the fit
        drains the writer on every exit path)."""
        x = _data(64, 12, seed=11)
        kw = dict(n_components=4, svd_solver="hierarchical", random_state=5)
        d = str(tmp_path / "ck")
        with rz.fault_plan({"pca.stage": [{"at": 1, "kind": "permanent"}]}):
            with pytest.raises(rz.PermanentFault):
                ht.decomposition.PCA(**kw, checkpoint_every=1, checkpoint_dir=d).fit(x)
        assert Checkpointer(d).all_steps() == [0]
        plain = ht.decomposition.PCA(**kw).fit(x)
        resumed = ht.decomposition.PCA(**kw, checkpoint_every=1, resume_from=d).fit(x)
        assert np.array_equal(
            np.asarray(plain.components_._dense()),
            np.asarray(resumed.components_._dense()),
        )


# ----------------------------------------------------------------------
# device prefetch
# ----------------------------------------------------------------------
class TestPrefetchToDevice:
    def test_order_and_stop_iteration(self):
        src = (np.full((8, 2), i, np.float32) for i in range(7))
        it = prefetch_to_device(src, size=2)
        got = [float(b[0, 0]) for b in it]
        assert got == [float(i) for i in range(7)]
        with pytest.raises(StopIteration):
            next(it)

    def test_sharding_applied(self):
        comm = ht.get_comm()
        sh = sharding_for_batch(comm.size * 2, comm)
        assert sh is not None
        out = list(prefetch_to_device(
            (np.ones((comm.size * 2, 3), np.float32) for _ in range(3)),
            size=2, sharding=sh,
        ))
        assert all(b.sharding == sh for b in out)

    def test_ragged_batch_has_no_canonical_sharding(self):
        comm = ht.get_comm()
        if comm.size == 1:
            pytest.skip("every extent tiles a single-device mesh")
        assert sharding_for_batch(comm.size + 1, comm) is None

    def test_counters_and_pytree_batches(self):
        ov.reset_overlap_stats()
        src = ({"x": np.full((4,), i, np.float32), "label": i} for i in range(5))
        out = list(prefetch_to_device(src, size=2))
        assert [b["label"] for b in out] == list(range(5))
        assert float(out[2]["x"][0]) == 2.0
        s = ov.overlap_stats()
        assert s["prefetch_hits"] == 5  # all staged ahead by the look-ahead
        assert s["prefetch_hit_rate"] == 1.0

    def test_empty_iterator_and_bad_size(self):
        assert list(prefetch_to_device(iter([]), size=2)) == []
        with pytest.raises(ValueError):
            prefetch_to_device(iter([]), size=0)

    def test_dataloader_prefetch_wiring(self):
        x = ht.arange(40, dtype=ht.float32, split=0).reshape((20, 2))
        loader = ht.utils.data.DataLoader(x, batch_size=4, shuffle=False, prefetch=2)
        seen = [np.asarray(b)[:, 0].tolist() for b in loader]
        flat = [v for b in seen for v in b]
        assert flat == [float(v) for v in range(0, 40, 2)]


# ----------------------------------------------------------------------
# windowed loader without h5py (synthetic read_window backend)
# ----------------------------------------------------------------------
class _SyntheticWindowed:
    """PartialH5Dataset stand-in: the loader-iterator protocol (length /
    load_length / transforms / dataset_names / comm / read_window)
    backed by in-memory arrays — no h5py anywhere."""

    def __init__(self, arrays, load_length=4, transforms=None, comm=None,
                 fail_at_window=None, fail_with=None):
        self.arrays = list(arrays)
        self.dataset_names = [f"d{i}" for i in range(len(self.arrays))]
        self.length = self.arrays[0].shape[0]
        self.load_length = load_length
        self.transforms = transforms
        self.comm = comm
        self.fail_at_window = fail_at_window
        self.fail_with = fail_with or RuntimeError("backing store exploded")
        self.reads = []

    def read_window(self, start, stop):
        self.reads.append((start, stop))
        if self.fail_at_window is not None and start >= self.fail_at_window * self.load_length:
            raise self.fail_with
        return [np.asarray(a[start:stop]) for a in self.arrays]

    def __iter__(self):
        return PartialH5DataLoaderIter(self)


class TestSyntheticWindowedLoader:
    def test_single_dataset_windows_in_order(self):
        data = np.arange(20, dtype=np.float32).reshape(10, 2)
        ds = _SyntheticWindowed([data], load_length=4)
        out = [np.asarray(w) for w in ds]
        assert [w.shape[0] for w in out] == [4, 4, 2]
        np.testing.assert_array_equal(np.concatenate(out), data)

    def test_multi_dataset_tuple_windows_and_transforms(self):
        xa = np.arange(12, dtype=np.float32).reshape(6, 2)
        ya = np.arange(6, dtype=np.float32)
        ds = _SyntheticWindowed([xa, ya], load_length=3, transforms=lambda a: a * 2)
        wins = list(ds)
        assert all(isinstance(w, tuple) and len(w) == 2 for w in wins)
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(w[0]) for w in wins]), xa * 2
        )
        np.testing.assert_array_equal(
            np.concatenate([np.asarray(w[1]) for w in wins]), ya * 2
        )

    def test_windows_staged_with_split_sharding(self):
        comm = ht.get_comm()
        data = np.ones((comm.size * 4, 3), np.float32)
        ds = _SyntheticWindowed([data], load_length=comm.size * 2, comm=comm)
        wins = list(ds)
        assert all(w.sharding == comm.sharding(0) for w in wins)

    def test_loader_error_propagates_to_consumer(self):
        data = np.zeros((12, 2), np.float32)
        ds = _SyntheticWindowed([data], load_length=4, fail_at_window=1)
        it = iter(ds)
        assert np.asarray(next(it)).shape == (4, 2)
        with pytest.raises(RuntimeError, match="backing store exploded"):
            for _ in it:
                pass
        assert it._thread is None  # errored iterator retired its worker

    def test_base_exception_path(self):
        """Even a KeyboardInterrupt on the loader thread surfaces at the
        consumer instead of dying silently on the daemon thread."""
        data = np.zeros((8, 2), np.float32)
        ds = _SyntheticWindowed(
            [data], load_length=4, fail_at_window=0, fail_with=KeyboardInterrupt()
        )
        with pytest.raises(KeyboardInterrupt):
            next(iter(ds))

    def test_close_with_full_ready_queue_retires_thread(self):
        """PR 2 leak regression: with the ready queue full (maxsize=2)
        the loader thread blocks in _ready.put and can never consume the
        bare None sentinel; close() must drain pending windows until the
        worker exits."""
        data = np.zeros((64, 2), np.float32)
        ds = _SyntheticWindowed([data], load_length=4)
        it = iter(ds)  # window 0 read is queued on the worker
        # fill both ready slots so the worker's put blocks (the state a
        # stalled consumer reaches with staged windows it never takes)
        it._ready.put(np.zeros((4, 2), np.float32))
        it._ready.put(np.zeros((4, 2), np.float32))
        deadline = time.monotonic() + 5
        while not ds.reads and time.monotonic() < deadline:
            time.sleep(0.01)  # worker picked up the read, heading for put
        worker = it._thread
        assert worker is not None and worker.is_alive()
        it.close()
        worker.join(timeout=5)
        assert not worker.is_alive()
        # idempotent + iteration after close terminates cleanly
        it.close()
        with pytest.raises(StopIteration):
            next(it)

    def test_close_unconsumed_iterator(self):
        data = np.zeros((40, 2), np.float32)
        ds = _SyntheticWindowed([data], load_length=4)
        it = iter(ds)  # primed, never consumed
        worker = it._thread
        it.close()
        worker.join(timeout=5)
        assert not worker.is_alive()


# ----------------------------------------------------------------------
# bucketed / fused gradient reduction
# ----------------------------------------------------------------------
def _mlp_setup(seed=0):
    rng = np.random.default_rng(seed)
    params = {
        "w1": jnp.asarray(rng.normal(size=(8, 16)) * 0.1, jnp.float32),
        "b1": jnp.zeros((16,), jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(16, 2)) * 0.1, jnp.float32),
        "b2": jnp.zeros((2,), jnp.float32),
    }
    x = jnp.asarray(rng.normal(size=(32, 8)), jnp.float32)
    y = jnp.asarray(rng.normal(size=(32, 2)), jnp.float32)

    def apply(p, xb):
        h = jnp.tanh(xb @ p["w1"] + p["b1"])
        return h @ p["w2"] + p["b2"]

    def loss_fn(pred, target):
        return jnp.mean((pred - target) ** 2)

    return params, x, y, apply, loss_fn


class TestBucketedGradReduction:
    def test_bucket_partition_reverse_order_byte_bound_and_dtype(self):
        from heat_tpu.nn.data_parallel import bucket_partition

        leaves = [
            np.zeros((64,), np.float32),   # 256 B
            np.zeros((8,), np.float32),    # 32 B
            np.zeros((8,), np.float64),    # 64 B, different dtype
            np.zeros((4,), np.float32),    # 16 B
        ]
        buckets = bucket_partition(leaves, 128)
        # reverse order; dtype change splits; byte bound splits
        assert buckets == [[3], [2], [1], [0]] or buckets[0][0] == 3
        flat = [i for b in buckets for i in b]
        assert flat == [3, 2, 1, 0]
        for b in buckets:
            assert len({str(leaves[i].dtype) for i in b}) == 1
            assert sum(leaves[i].nbytes for i in b) <= 128 or len(b) == 1
        # fused: unbounded, still dtype-pure
        fused = bucket_partition(leaves, None)
        assert [i for b in fused for i in b] == [3, 2, 1, 0]
        assert all(len({str(leaves[i].dtype) for i in b}) == 1 for b in fused)

    def test_bucketed_equals_fused_bitwise(self, monkeypatch):
        import optax

        monkeypatch.setenv("HEAT_TPU_GRAD_BUCKET_MB", "0.0001")  # force many buckets
        params, x, y, apply, loss_fn = _mlp_setup()

        def run(schedule):
            dp = ht.nn.DataParallel(apply, optimizer=optax.sgd(0.1), grad_reduction=schedule)
            dp.set_params(jax.tree_util.tree_map(lambda a: a.copy(), params))
            losses = [dp.step(loss_fn, x, y) for _ in range(3)]
            return losses, dp.params

        ov.reset_overlap_stats()
        loss_b, p_b = run("bucketed")
        assert ov.overlap_stats()["grad_buckets"] > 1  # really bucketed
        loss_f, p_f = run("fused")
        assert loss_b == loss_f
        for k in params:
            assert np.array_equal(np.asarray(p_b[k]), np.asarray(p_f[k])), k

    def test_explicit_matches_implicit_numerically(self):
        import optax

        params, x, y, apply, loss_fn = _mlp_setup()

        def run(**kw):
            dp = ht.nn.DataParallel(apply, optimizer=optax.sgd(0.1), **kw)
            dp.set_params(jax.tree_util.tree_map(lambda a: a.copy(), params))
            dp.step(loss_fn, x, y)
            return dp.params

        p_i, p_b = run(), run(grad_reduction="bucketed")
        for k in params:
            np.testing.assert_allclose(
                np.asarray(p_i[k]), np.asarray(p_b[k]), rtol=2e-5, atol=1e-7
            )

    def test_hierarchical_two_stage_schedules_match(self):
        import optax

        comm = ht.parallel.HierarchicalCommunication()
        if comm.num_nodes * comm.node_size < 2:
            pytest.skip("needs a multi-device mesh")
        params, x, y, apply, loss_fn = _mlp_setup()

        def run(schedule):
            dp = ht.nn.DataParallel(
                apply, comm=comm, optimizer=optax.sgd(0.1), grad_reduction=schedule
            )
            dp.set_params(jax.tree_util.tree_map(lambda a: a.copy(), params))
            dp.step(loss_fn, x, y)
            return dp.params

        p_b, p_f = run("bucketed"), run("fused")
        for k in params:
            assert np.array_equal(np.asarray(p_b[k]), np.asarray(p_f[k])), k

    def test_dp_optimizer_blocking_routes_schedule(self):
        import optax

        apply = lambda p, xb: xb @ p["w"]
        fused = ht.nn.DataParallel(
            apply, optimizer=ht.optim.DataParallelOptimizer(optax.sgd(0.1), blocking=True)
        )
        assert fused.grad_reduction == "fused"
        bucketed = ht.nn.DataParallel(
            apply, optimizer=ht.optim.DataParallelOptimizer(optax.sgd(0.1))
        )
        assert bucketed.grad_reduction == "bucketed"
        # plain optax transform keeps the implicit schedule
        assert ht.nn.DataParallel(apply, optimizer=optax.sgd(0.1)).grad_reduction == "implicit"
        # blocking_parameter_updates maps to the fused explicit schedule
        assert ht.nn.DataParallel(
            apply, optimizer=optax.sgd(0.1), blocking_parameter_updates=True
        ).grad_reduction == "fused"

    def test_unknown_values_rejected(self):
        import optax

        with pytest.raises(ValueError):
            ht.optim.DataParallelOptimizer(optax.sgd(0.1), blocking="yes")
        with pytest.raises(ValueError):
            ht.nn.DataParallel(lambda p, x: x, optimizer=optax.sgd(0.1), grad_reduction="wat")

    def test_ragged_batch_falls_back_to_implicit_body(self):
        import optax

        comm = ht.get_comm()
        if comm.size == 1:
            pytest.skip("every batch tiles a single-device mesh")
        params, _, _, apply, loss_fn = _mlp_setup()
        rng = np.random.default_rng(1)
        x = jnp.asarray(rng.normal(size=(comm.size + 1, 8)), jnp.float32)
        y = jnp.asarray(rng.normal(size=(comm.size + 1, 2)), jnp.float32)
        dp = ht.nn.DataParallel(apply, optimizer=optax.sgd(0.1), grad_reduction="bucketed")
        dp.set_params(params)
        loss = dp.step(loss_fn, x, y)  # must not crash in shard_map
        assert np.isfinite(loss)


class TestOverlapStats:
    def test_reset_and_derived_rate(self):
        ov.reset_overlap_stats()
        s = ov.overlap_stats()
        assert s["async_saves"] == 0 and s["prefetch_hit_rate"] == 0.0
        list(prefetch_to_device(iter([np.zeros(2)]), size=1))
        assert ov.overlap_stats()["prefetch_hits"] == 1
        ov.reset_overlap_stats()
        assert ov.overlap_stats()["prefetch_hits"] == 0
