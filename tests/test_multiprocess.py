"""Multi-process execution lane: spawn N controller processes over a shared
gloo-backed device mesh, the analog of the reference's ``mpirun -n 3`` /
``-n 4`` CI jobs (/root/reference/.github/workflows/ci.yaml:58-61).

Each worker (tests/multiprocess/mp_worker.py) drives the same SPMD program
on its own 4 virtual CPU devices; collectives cross the process boundary
through jax.distributed + gloo exactly as they would cross hosts over DCN.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "multiprocess", "mp_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_lane(nproc: int, dev_per_proc: int, timeout: int = 300):
    port = _free_port()
    env = {k: v for k, v in os.environ.items() if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(nproc), str(port), str(dev_per_proc)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {pid} failed:\n{out}"
        assert "MP-OK" in out, f"worker {pid} did not finish:\n{out}"
    return outs


@pytest.mark.multiprocess
def test_two_processes_four_devices_each():
    _run_lane(nproc=2, dev_per_proc=4)


@pytest.mark.multiprocess
def test_three_processes_uneven_mesh():
    # the reference's -n 3 lane: odd process count, 2 devices each
    _run_lane(nproc=3, dev_per_proc=2)
