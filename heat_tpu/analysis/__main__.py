"""CLI for the AST-level framework-invariant linter.

    python -m heat_tpu.analysis heat_tpu/ [more paths...]
        [--baseline scripts/lint_baseline.json] [--no-baseline]
        [--format text|json] [--list-rules]

Exit status: 0 when every violation is covered by the baseline (or none
exist), 1 when new violations are present.  With no ``--baseline``
argument the checked-in ``scripts/lint_baseline.json`` next to the repo
root is used when it exists — so ``python -m heat_tpu.analysis
heat_tpu/`` run from a checkout gates exactly like CI.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .ast_lint import (
    RULES,
    lint_paths,
    violations_to_json,
    _find_repo_root,
)


def _load_baseline(path: str):
    with open(path) as f:
        doc = json.load(f)
    entries = doc["violations"] if isinstance(doc, dict) else doc
    return {(e["rule"], e["file"], e["line"]) for e in entries}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m heat_tpu.analysis",
        description="heat_tpu framework-invariant AST linter",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint (default: heat_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of accepted violations "
                         "(default: <repo>/scripts/lint_baseline.json if present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring any baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    paths = args.paths
    repo_root = _find_repo_root(paths[0] if paths else os.getcwd())
    if not paths:
        paths = [os.path.join(repo_root, "heat_tpu")]

    violations = lint_paths(paths, repo_root=repo_root)

    baseline = set()
    if not args.no_baseline:
        bpath = args.baseline
        if bpath is None:
            cand = os.path.join(repo_root, "scripts", "lint_baseline.json")
            bpath = cand if os.path.exists(cand) else None
        if bpath is not None:
            baseline = _load_baseline(bpath)

    new = [v for v in violations if v.key() not in baseline]
    accepted = len(violations) - len(new)

    if args.format == "json":
        print(json.dumps({
            "violations": violations_to_json(new),
            "accepted_baseline": accepted,
            "total": len(violations),
        }, indent=1))
    else:
        for v in new:
            print(v)
        note = f" ({accepted} accepted by baseline)" if accepted else ""
        print(
            f"lint: {len(new)} new violation(s), {len(violations)} total{note}",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
