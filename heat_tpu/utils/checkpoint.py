"""Checkpoint/resume for sharded arrays and training state.

The reference has no dedicated checkpoint subsystem (SURVEY.md §5):
persistence is the io layer writing global arrays, plus
``DetectMetricPlateau.get_state/set_state`` for optimizer state
(optim/utils.py:72-108).  The TPU-native equivalent is orbax-backed
checkpointing of sharded jax arrays — each host writes its own shards,
restore re-places them on the mesh — exposed here for DNDarrays, pytrees
(model params / optax state), and DASO's state dicts.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..core.dndarray import DNDarray

__all__ = ["save_checkpoint", "load_checkpoint", "Checkpointer"]


def _orbax():
    import orbax.checkpoint as ocp

    return ocp


class Checkpointer:
    """Directory-per-step checkpoint manager over orbax."""

    def __init__(self, directory: str, max_to_keep: Optional[int] = None):
        ocp = _orbax()
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep),
        )

    def save(self, step: int, state: Any, extra_metadata: Optional[Dict] = None) -> None:
        """Save a pytree (params/opt state/DNDarray-free metadata)."""
        ocp = _orbax()
        state = _strip_dndarrays(state)
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        self._mngr.wait_until_finished()
        if extra_metadata is not None:
            with open(os.path.join(self.directory, f"meta_{step}.json"), "w") as f:
                json.dump(extra_metadata, f)

    def restore(self, step: Optional[int] = None, template: Any = None) -> Any:
        ocp = _orbax()
        step = self.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints in {self.directory}")
        if template is not None:
            template = _strip_dndarrays(template)
            return self._mngr.restore(step, args=ocp.args.StandardRestore(template))
        return self._mngr.restore(step)

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def metadata(self, step: int) -> Optional[Dict]:
        path = os.path.join(self.directory, f"meta_{step}.json")
        if os.path.exists(path):
            with open(path) as f:
                return json.load(f)
        return None


def _strip_dndarrays(tree: Any) -> Any:
    """DNDarrays are stored as their dense global arrays (sharding is a
    property of the restoring mesh, not the payload)."""
    return jax.tree_util.tree_map(
        lambda x: x._dense() if isinstance(x, DNDarray) else x,
        tree,
        is_leaf=lambda x: isinstance(x, DNDarray),
    )


def save_checkpoint(path: str, state: Any, step: int = 0) -> None:
    """One-shot checkpoint save (convenience wrapper)."""
    Checkpointer(path).save(step, state)


def load_checkpoint(path: str, step: Optional[int] = None, template: Any = None) -> Any:
    """One-shot checkpoint restore."""
    return Checkpointer(path).restore(step, template)
