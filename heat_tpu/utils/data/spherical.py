"""Synthetic clustered-data generators, analog of heat/utils/data/spherical.py."""

from __future__ import annotations

import jax.numpy as jnp

from ...core import types
from ...core.dndarray import DNDarray
from ...core import random as ht_random

__all__ = ["create_spherical_dataset", "create_clusters"]


def create_spherical_dataset(
    num_samples_cluster: int,
    radius: float = 1.0,
    offset: float = 4.0,
    dtype=types.float32,
    random_state: int = 1,
) -> DNDarray:
    """Four Gaussian clusters at +-offset on the diagonal (spherical.py:7)."""
    ht_random.seed(random_state)
    dtype = types.canonical_heat_type(dtype)
    centers = jnp.asarray(
        [[-offset, -offset, -offset], [-offset, offset, -offset], [offset, -offset, offset], [offset, offset, offset]],
        dtype=dtype.jax_type(),
    )
    parts = []
    for c in range(4):
        pts = ht_random.randn(num_samples_cluster, 3, dtype=dtype)._dense() * radius + centers[c]
        parts.append(pts)
    data = jnp.concatenate(parts, axis=0)
    return DNDarray.from_dense(data, 0, None, None) if False else _wrap0(data)


def _wrap0(data):
    from ...core import factories

    return factories.array(data, split=0)


def create_clusters(
    n_samples: int,
    n_features: int,
    n_clusters: int,
    cluster_mean,
    cluster_std,
    cluster_weight=None,
    device=None,
    random_state: int = 1,
) -> DNDarray:
    """Gaussian clusters with given means/stds/weights (spherical.py:57)."""
    import numpy as np

    ht_random.seed(random_state)
    means = jnp.asarray(cluster_mean._dense() if isinstance(cluster_mean, DNDarray) else cluster_mean)
    stds = jnp.asarray(cluster_std._dense() if isinstance(cluster_std, DNDarray) else cluster_std)
    if cluster_weight is None:
        counts = [n_samples // n_clusters] * n_clusters
        counts[-1] += n_samples - sum(counts)
    else:
        w = np.asarray(cluster_weight, dtype=np.float64)
        counts = (w / w.sum() * n_samples).astype(int).tolist()
        counts[-1] += n_samples - sum(counts)
    parts = []
    for c in range(n_clusters):
        std_c = stds[c]
        pts = ht_random.randn(counts[c], n_features)._dense()
        if std_c.ndim == 2:
            pts = pts @ std_c
        else:
            pts = pts * std_c
        parts.append(pts + means[c])
    data = jnp.concatenate(parts, axis=0)
    return _wrap0(data)
