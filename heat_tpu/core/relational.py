"""Relational operations, analog of heat/core/relational.py (12 exports)."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __binary_op as _binary_op
from .dndarray import DNDarray

__all__ = [
    "eq",
    "equal",
    "ge",
    "greater_equal",
    "gt",
    "greater",
    "le",
    "less_equal",
    "lt",
    "less",
    "ne",
    "not_equal",
]


def eq(t1, t2):
    """Element-wise == (relational.py:23)."""
    return _binary_op(jnp.equal, t1, t2)


def equal(t1, t2) -> bool:
    """True iff both arrays are entirely equal (global scalar; relational.py:73).

    The reference reduces a local comparison with MPI.LAND; here the global
    jnp comparison + all() spans shards directly.
    """
    if isinstance(t1, DNDarray):
        a = t1._dense()
    else:
        a = jnp.asarray(t1)
    if isinstance(t2, DNDarray):
        b = t2._dense()
    else:
        b = jnp.asarray(t2)
    if tuple(a.shape) != tuple(b.shape):
        try:
            jnp.broadcast_shapes(a.shape, b.shape)
        except ValueError:
            return False
    return bool(jnp.all(a == b))


def ge(t1, t2):
    """Element-wise >= (relational.py:150)."""
    return _binary_op(jnp.greater_equal, t1, t2)


greater_equal = ge


def gt(t1, t2):
    """Element-wise > (relational.py:201)."""
    return _binary_op(jnp.greater, t1, t2)


greater = gt


def le(t1, t2):
    """Element-wise <= (relational.py:252)."""
    return _binary_op(jnp.less_equal, t1, t2)


less_equal = le


def lt(t1, t2):
    """Element-wise < (relational.py:303)."""
    return _binary_op(jnp.less, t1, t2)


less = lt


def ne(t1, t2):
    """Element-wise != (relational.py:354)."""
    return _binary_op(jnp.not_equal, t1, t2)


not_equal = ne
