"""Shared k-clustering base, analog of heat/cluster/_kcluster.py.

``_KCluster`` (_kcluster.py:10) holds the iteration loop and the two
initializations: random sampling and kmeans++ (``probability_based``,
_kcluster.py:97-207).  All distributed behavior rides on the ops layer
(cdist + argmin + masked reductions over the sharded sample axis).
"""

from __future__ import annotations

import functools
from typing import Callable, Optional, Union

import jax
import jax.numpy as jnp

_jit_partial = functools.partial(jax.jit, static_argnames=("k",))

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin, lazy_scalar_property
from ..core.dndarray import DNDarray

__all__ = ["_KCluster"]


@_jit_partial
def _kmeanspp_init(dense: jax.Array, first_idx: jax.Array, u_all: jax.Array, k: int) -> jax.Array:
    """Greedy D^2-weighted kmeans++ seeding as one compiled program.

    ``u_all`` holds the k-1 pre-drawn uniforms (one per added center), so
    the library RNG stream is consumed outside and the loop is pure.
    """
    n, f = dense.shape
    x2 = jnp.sum(dense * dense, axis=1)
    centers0 = jnp.zeros((k, f), dense.dtype).at[0].set(dense[first_idx])

    def body(i, centers):
        c2 = jnp.sum(centers * centers, axis=1)
        d_all = x2[:, None] + c2[None, :] - 2.0 * (dense @ centers.T)
        d_all = d_all + jnp.where(jnp.arange(k)[None, :] >= i, jnp.inf, 0.0)
        d2 = jnp.maximum(jnp.min(d_all, axis=1), 0.0)
        probs = d2 / jnp.maximum(jnp.sum(d2), 1e-30)
        u = u_all[i - 1]
        next_idx = jnp.clip(jnp.searchsorted(jnp.cumsum(probs), u), 0, n - 1)
        return centers.at[i].set(dense[next_idx])

    return jax.lax.fori_loop(1, k, body, centers0)


class _KCluster(BaseEstimator, ClusteringMixin):
    """Base class for k-statistics clustering (_kcluster.py:10).

    ``checkpoint_every=N`` + ``checkpoint_dir`` make the fit resumable:
    every N iterations the centers are checkpointed through the
    filesystem-native :class:`~heat_tpu.utils.checkpoint.Checkpointer`,
    and ``resume_from=dir`` continues a killed fit from its last
    checkpoint, reproducing the uninterrupted result exactly (the
    chunked loop runs the identical iteration sequence).  The chunked
    path also guards against NaN/Inf divergence
    (:class:`~heat_tpu.resilience.DivergenceError` carrying the last
    finite centers)."""

    def __init__(
        self,
        metric: Callable,
        n_clusters: int,
        init: Union[str, DNDarray],
        max_iter: int,
        tol: float,
        random_state: Optional[int],
        checkpoint_every: Optional[int] = None,
        checkpoint_dir: Optional[str] = None,
        resume_from: Optional[str] = None,
    ):
        from ..core.base import validate_resume_params

        validate_resume_params(checkpoint_every, checkpoint_dir, resume_from)
        self.n_clusters = n_clusters
        self.init = init
        self.max_iter = max_iter
        self.tol = tol
        self.random_state = random_state
        self.checkpoint_every = checkpoint_every
        self.checkpoint_dir = checkpoint_dir
        self.resume_from = resume_from

        self._metric = metric
        self._cluster_centers = None
        self._labels = None
        self._inertia = None
        self._n_iter = None

    @property
    def _resumable(self) -> bool:
        """Whether the fit must take the chunked checkpoint/resume path."""
        return self.checkpoint_every is not None or self.resume_from is not None

    @property
    def cluster_centers_(self) -> DNDarray:
        return self._cluster_centers

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    # fits store device scalars so fit() never blocks on the link; the
    # host conversion happens (once) on first access
    inertia_ = lazy_scalar_property("_inertia", float)
    n_iter_ = lazy_scalar_property("_n_iter", int)

    def _initialize_cluster_centers(self, x: DNDarray, oversampling: float = None, iter_multiplier: float = None):
        """Random / kmeans++ / explicit initialization (_kcluster.py:97)."""
        if self.random_state is not None:
            from ..core import random as ht_random

            ht_random.seed(self.random_state)
        from ..core import random as ht_random

        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        n, f = dense.shape
        k = self.n_clusters

        if isinstance(self.init, DNDarray):
            if self.init.shape != (k, f):
                raise ValueError(f"passed centroids need to be of shape ({k}, {f}), but are {self.init.shape}")
            centers = self.init._dense().astype(dense.dtype)
        elif self.init == "random":
            # k DISTINCT data points (argsort of one uniform draw = a
            # random sample without replacement).  Sampling indices WITH
            # replacement could seed two centers on the same point — a
            # state the median/medoid update can never leave (their
            # clusters tie forever), and which cost the KMedians/
            # KMedoids blob fits a whole blob at unlucky seeds
            u = ht_random.rand(n, comm=x.comm)._dense()
            idx = jnp.argsort(u)[:k]
            centers = dense[idx]
        elif self.init in ("kmeans++", "probability_based", "++"):
            # kmeans++ sampling (_kcluster.py:112-180): greedy D^2 weighting.
            # The uniforms are pre-drawn one call per added center — the
            # exact draw sequence of the release before the loop was fused,
            # so seeded results are stable — then the greedy loop compiles
            # as one program: centers preallocated at (k, f) with unfilled
            # slots masked to +inf so every round has identical shapes.
            key_arr = ht_random.randint(0, n, size=(1,), comm=x.comm)._dense()
            if k > 1:
                u_all = jnp.concatenate(
                    [ht_random.rand(1, comm=x.comm)._dense() for _ in range(k - 1)]
                )
            else:
                u_all = jnp.zeros((1,), jnp.float32)
            centers = _kmeanspp_init(dense, key_arr[0], u_all, k)
        elif self.init == "batchparallel":
            raise NotImplementedError("batchparallel init: use BatchParallelKMeans")
        else:
            raise ValueError(
                f'init needs to be one of "random", ht.DNDarray or "kmeans++", but was {self.init}'
            )
        self._cluster_centers = DNDarray.from_dense(centers, None, x.device, x.comm)

    def _assign_to_cluster(self, x: DNDarray, eval_functional_value: bool = False):
        """Label each sample with its nearest center (_kcluster.py:208)."""
        distances = self._metric(x, self._cluster_centers)
        from ..core import statistics

        labels = statistics.argmin(distances, axis=1)
        if eval_functional_value:
            from ..core import arithmetics

            # stays a lazy 0-d value; inertia_ converts on first access
            self._inertia = arithmetics.sum(statistics.min(distances, axis=1) ** 2)._dense()
        return labels

    def _run_resumable(self, run_chunk, init_centers, site: str):
        """Chunked checkpoint/resume driver around the jitted fit loop
        (see :func:`heat_tpu.core.base.resumable_fit_loop`)."""
        from ..core.base import resumable_fit_loop

        return resumable_fit_loop(
            run_chunk,
            init_centers,
            self.max_iter,
            float(self.tol),
            checkpoint_every=self.checkpoint_every,
            checkpoint_dir=self.checkpoint_dir,
            resume_from=self.resume_from,
            site=site,
            what="cluster centers",
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray):
        raise NotImplementedError()

    def fit(self, x: DNDarray):
        raise NotImplementedError()

    def predict(self, x: DNDarray) -> DNDarray:
        """Nearest learned center for each sample (_kcluster.py:268).

        Runs under this kind's precision-policy scope
        (:mod:`heat_tpu.analysis.precision_policy`): the dispatch
        analyze hook checks the compiled program against the declared
        policy, and a ``tolerance`` policy + ``HEAT_TPU_PREDICT_DTYPE``
        flips the cdist cross term to bf16 compute (KMeans; the
        ``bitwise`` kinds always serve native f32)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        from ..analysis import precision_policy as _pp

        with _pp.scope(type(self).__name__):
            return self._assign_to_cluster(x)
