"""K-nearest-neighbors classifier, analog of
heat/classification/kneighborsclassifier.py (kneighborsclassifier.py:10).

Predict pipeline matches the reference (:114-132): cdist to the training
set -> topk smallest -> gather one-hot labels -> sum over neighbors ->
argmax.  All of it is sharded jnp; the MXU does the distance matrix.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin
from ..core.dndarray import DNDarray
from ..spatial import distance

__all__ = ["KNeighborsClassifier"]


def one_hot_encoding(labels: DNDarray, num_classes: Optional[int] = None) -> DNDarray:
    """One-hot encode integer labels (kneighborsclassifier.py:46)."""
    dense = labels._dense().astype(jnp.int32)
    if num_classes is None:
        num_classes = int(jnp.max(dense)) + 1
    encoded = jax.nn.one_hot(dense, num_classes, dtype=jnp.float32)
    return DNDarray.from_dense(encoded, labels.split, labels.device, labels.comm)


class KNeighborsClassifier(BaseEstimator, ClassificationMixin):
    """Vote of the k nearest training samples (kneighborsclassifier.py:10)."""

    def __init__(self, n_neighbors: int = 5):
        self.n_neighbors = n_neighbors
        self.x = None
        self.y = None

    def fit(self, x: DNDarray, y: DNDarray) -> "KNeighborsClassifier":
        """Store the training set (kneighborsclassifier.py:95)."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        self.x = x
        if y.ndim == 1:
            y = one_hot_encoding(y)
        self.y = y
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Majority vote over the k nearest neighbors
        (kneighborsclassifier.py:114-132).

        The neighbor search is the ring-fused distance->top-k program
        (spatial.distance.cdist_topk): the (n_test, n_train) matrix is
        never materialized — peak memory is O(n_test * k) plus one
        circulating train block (reference materializes the matrix).

        Runs under the KNeighborsClassifier precision scope: a
        tolerance-policy bf16 request narrows the distance cross term
        only (f32 accumulation); the vote/argmax stage — and thus the
        predicted labels — stays native."""
        if self.x is None:
            raise RuntimeError("fit needs to be called before predict")
        from ..analysis import precision_policy as _pp

        with _pp.scope("KNeighborsClassifier"):
            _, idx_arr = distance.cdist_topk(x, self.x, self.n_neighbors)
        idx = idx_arr._dense()
        labels_oh = self.y._dense()
        votes = jnp.sum(labels_oh[idx], axis=1)
        pred = jnp.argmax(votes, axis=1).astype(types.canonical_dtype(jnp.int64))
        return DNDarray.from_dense(pred, x.split, x.device, x.comm)
