"""SLO burn-rate monitors + alert/event subsystem (ISSUE 11 tentpole).

The contract under test (docs/observability.md "SLOs & alerting"):

* declarative objectives parse from the string grammar and evaluate as
  windowed burn-rate math over the CUMULATIVE bounded structures —
  bucket-state deltas between ticks, O(windows x buckets) memory,
  reset-safe (a counter/histogram reset restarts the window's delta
  from zero, never a negative phantom);
* alerting is multi-window: an alert fires only when BOTH the fast and
  the slow window burn above their factors, resolves once the fast
  window drops under 1.0, and the transition carries the nearest
  exemplar trace_id above the violated threshold;
* alerts deduplicate by (name, labels): re-firing refreshes, only
  fired/resolved transitions land in the bounded event ring, and
  cross-worker merging is a pure deterministic fold;
* the histogram edge cases the windowed math leans on: quantile at
  q=0/1, single-bucket occupancy, exemplar survival through reset()
  and merge_snapshots.
"""

import threading
import time

import pytest

from heat_tpu import telemetry
from heat_tpu.telemetry import aggregate
from heat_tpu.telemetry import alerts
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import slo
from heat_tpu.telemetry import tracing


@pytest.fixture(autouse=True)
def _clean_quality_signals():
    """Every test starts with no objectives, no alerts, fresh metrics
    under the test's own names."""
    slo.reset_monitors()
    alerts.clear_alerts()
    yield
    slo.reset_monitors()
    alerts.clear_alerts()
    tm.reset("slotest.")


def _fresh_hist(name):
    h = tm.histogram(name)
    h.reset()
    return h


# ----------------------------------------------------------------------
# histogram edge cases the windowed math leans on
# ----------------------------------------------------------------------
class TestHistogramEdges:
    def test_quantile_q0_q1_clamp_to_observed_extremes(self):
        h = _fresh_hist("slotest.h_q01")
        for v in (3.0, 5.0, 40.0):
            h.observe(v)
        assert h.quantile(0.0) == 3.0
        assert h.quantile(1.0) == 40.0
        with pytest.raises(ValueError):
            h.quantile(1.5)
        with pytest.raises(ValueError):
            h.quantile(-0.1)

    def test_quantile_empty_is_none(self):
        h = _fresh_hist("slotest.h_empty")
        for q in (0.0, 0.5, 1.0):
            assert h.quantile(q) is None

    def test_single_bucket_occupancy_every_quantile_inside(self):
        # all mass in ONE geometric bucket: every quantile must land in
        # the exact observed [min, max], not at a bucket edge outside it
        h = _fresh_hist("slotest.h_single")
        for _ in range(100):
            h.observe(7.0)
        for q in (0.0, 0.01, 0.5, 0.99, 1.0):
            assert h.quantile(q) == 7.0

    def test_single_low_bucket_nonpositive_observations(self):
        h = _fresh_hist("slotest.h_low")
        for v in (0.0, -1.0, 0.0):
            h.observe(v)
        assert h.quantile(0.5) == -1.0  # clamped to the observed min
        assert h.quantile(1.0) == 0.0

    def test_exemplar_cleared_by_reset(self):
        h = _fresh_hist("slotest.h_exreset")
        h.observe(5.0, exemplar="aa11")
        assert h.exemplars()
        assert "exemplars" in h.snapshot()
        h.reset()
        assert h.exemplars() == {}
        assert "exemplars" not in h.snapshot()
        assert h.count == 0

    def test_exemplar_survives_merge_snapshots(self):
        h = _fresh_hist("slotest.h_exmerge")
        h.observe(5.0, exemplar="feedc0de00000001")
        snap = aggregate.tag_snapshot()
        other = dict(snap, process_index=1)
        merged = aggregate.merge_snapshots([snap, other], publish=False)
        sub = merged["merged"]["slotest.h_exmerge"]["per_worker"]
        for ix in ("0", "1"):
            ex = sub[ix]["exemplars"]
            assert any(
                rec["trace_id"] == "feedc0de00000001" for rec in ex.values()
            ), ex

    def test_bucket_counts_is_cumulative_and_consistent(self):
        h = _fresh_hist("slotest.h_state")
        for v in (0.5, 0.5, 200.0):
            h.observe(v)
        low, buckets, count, total = h.bucket_counts()
        assert count == 3 and low == 0
        assert sum(buckets.values()) == 3
        assert total == pytest.approx(201.0)


# ----------------------------------------------------------------------
# windowed math: deltas, rates, reset safety
# ----------------------------------------------------------------------
class TestWindowedMath:
    def test_windowed_delta_subtracts_cumulative_states(self):
        h = _fresh_hist("slotest.h_delta")
        h.observe(5.0)
        old = h.bucket_counts()
        for _ in range(4):
            h.observe(50.0)
        delta = slo.windowed_delta(old, h.bucket_counts())
        assert delta[2] == 4
        assert slo.fraction_over(delta, 25.0) == pytest.approx(1.0)

    def test_windowed_delta_counter_reset_restarts_from_zero(self):
        # the reset-correctness satellite: cumulative count SHRANK
        # between samples -> the window reports the post-reset state,
        # never a negative phantom
        old = (2, {10: 50}, 52, 100.0)
        cur = (0, {10: 3}, 3, 6.0)
        delta = slo.windowed_delta(old, cur)
        assert delta == cur
        assert delta[2] == 3

    def test_windowed_rate_and_reset(self):
        assert slo.windowed_rate(100.0, 160.0, 60.0) == pytest.approx(1.0)
        # reset: cur < old -> rate counts from zero, stays >= 0
        assert slo.windowed_rate(100.0, 30.0, 10.0) == pytest.approx(3.0)
        assert slo.windowed_rate(0.0, 0.0, 0.0) == 0.0

    def test_fraction_over_interpolates_crossing_bucket(self):
        h = _fresh_hist("slotest.h_frac")
        for _ in range(100):
            h.observe(10.0)
        delta = slo.windowed_delta((0, {}, 0, 0.0), h.bucket_counts())
        # threshold inside the bucket: fraction strictly between 0 and 1
        frac = slo.fraction_over(delta, 9.5)
        assert 0.0 < frac < 1.0
        # 10.0 is exactly the bucket's upper bound: nothing is OVER it
        assert slo.fraction_over(delta, 10.0) == 0.0
        assert slo.fraction_over(delta, 100.0) == 0.0
        assert slo.fraction_over(delta, 0.001) == pytest.approx(1.0)

    def test_windowed_quantile_matches_histogram_quantile_model(self):
        h = _fresh_hist("slotest.h_wq")
        for v in [1.0] * 90 + [100.0] * 10:
            h.observe(v)
        delta = slo.windowed_delta((0, {}, 0, 0.0), h.bucket_counts())
        p50 = slo.windowed_quantile(delta, 0.5)
        p99 = slo.windowed_quantile(delta, 0.99)
        assert p50 < 2.0
        assert p99 > 50.0
        assert slo.windowed_quantile((0, {}, 0, 0.0), 0.5) is None

    def test_burn_rate_is_violation_over_budget(self):
        assert slo.burn_rate(0.14, 0.99) == pytest.approx(14.0, rel=1e-6)
        assert slo.burn_rate(0.0, 0.99) == 0.0


# ----------------------------------------------------------------------
# the declarative grammar
# ----------------------------------------------------------------------
class TestParse:
    def test_quantile_spec(self):
        s = slo.parse_slo("lat", "serving.latency_ms p99 < 25 over 60s/300s")
        assert s.kind == "quantile" and s.q == pytest.approx(0.99)
        assert s.metric == "serving.latency_ms"
        assert s.threshold == 25.0 and s.fast_s == 60.0 and s.slow_s == 300.0
        assert "p99" in s.describe()

    def test_rate_spec_with_summed_counters(self):
        s = slo.parse_slo(
            "shed",
            "serving.shed_quota+serving.shed_queue / serving.requests "
            "rate < 0.01 over 60s",
        )
        assert s.kind == "rate"
        assert s.metrics == ("serving.shed_quota", "serving.shed_queue")
        assert s.denominators == ("serving.requests",)
        assert s.fast_s == 60.0

    def test_freshness_spec(self):
        s = slo.parse_slo("hb", "fit.heartbeat_ts fresh < 30s")
        assert s.kind == "freshness" and s.threshold == 30.0

    def test_bad_specs_raise(self):
        with pytest.raises(ValueError):
            slo.parse_slo("x", "serving.latency_ms 25")
        with pytest.raises(ValueError):
            slo.parse_slo("x", "serving.latency_ms q99 < 25")
        with pytest.raises(ValueError):
            slo.SLO("x", "nonsense", 1.0, metric="m")
        with pytest.raises(ValueError):
            slo.SLO("x", "rate", 1.0)  # no counters


# ----------------------------------------------------------------------
# burn-rate evaluation + alert lifecycle
# ----------------------------------------------------------------------
class TestBurnRateAlerting:
    def test_multiwindow_fire_and_resolve_with_exemplar(self):
        h = _fresh_hist("slotest.lat_ms")
        s = slo.parse_slo("lat", "slotest.lat_ms p99 < 25 over 60s/300s")
        slo.register_slo(s)
        t0 = 1_000_000.0
        slo.evaluate(now=t0)

        for _ in range(100):
            h.observe(5.0)
        r = slo.evaluate(now=t0 + 30)[0]
        assert not r["firing"] and r["burn_fast"] == 0.0

        # synthetic latency injection: violations with exemplars
        for i in range(100):
            h.observe(80.0, exemplar=f"{i:016x}")
        r = slo.evaluate(now=t0 + 60)[0]
        assert r["firing"], r
        assert r["burn_fast"] >= s.fast_burn and r["burn_slow"] >= s.slow_burn
        assert alerts.is_firing("slo:lat")
        a = alerts.active_alerts()[0]
        assert a["severity"] == "page"
        assert a["trace_id"] is not None  # nearest exemplar above 25

        # recovery: healthy traffic, fast window empties of violations
        for _ in range(2000):
            h.observe(2.0)
        slo.evaluate(now=t0 + 120)
        slo.evaluate(now=t0 + 190)
        assert not alerts.is_firing("slo:lat")
        ev = [e["event"] for e in alerts.alert_events() if e["name"] == "slo:lat"]
        assert ev == ["fired", "resolved"]

    def test_fast_spike_alone_does_not_page(self):
        # slow-window guard: a short burst burns the fast window hard,
        # but the slow window — mostly healthy history — stays under
        # its factor, so no page (the multi-window flap suppressor)
        h = _fresh_hist("slotest.spike_ms")
        s = slo.SLO("spike", "quantile", 25.0, metric="slotest.spike_ms",
                    q=0.99, fast_s=60, slow_s=300, fast_burn=14, slow_burn=6)
        slo.register_slo(s)
        t0 = 2_000_000.0
        slo.evaluate(now=t0)
        for _ in range(2000):  # 4 minutes of healthy traffic
            h.observe(5.0)
        slo.evaluate(now=t0 + 240)
        for _ in range(100):  # then a one-minute spike
            h.observe(80.0)
        r = slo.evaluate(now=t0 + 300)[0]
        # fast window holds only the spike; slow dilutes it under 6x
        assert r["burn_fast"] >= 14
        assert r["burn_slow"] < 6
        assert not r["firing"]
        assert not alerts.is_firing("slo:spike")

    def test_rate_slo_counter_reset_safe(self):
        shed = tm.counter("slotest.shed")
        total = tm.counter("slotest.total")
        shed.reset()
        total.reset()
        s = slo.SLO("shed", "rate", 0.01, metrics=("slotest.shed",),
                    denominators=("slotest.total",), fast_s=60, slow_s=300,
                    fast_burn=10, slow_burn=1)
        slo.register_slo(s)
        t0 = 3_000_000.0
        slo.evaluate(now=t0)
        total.inc(1000)
        shed.inc(500)  # 50% shed >> 1% objective
        r = slo.evaluate(now=t0 + 60)[0]
        assert r["firing"], r
        # counter RESET mid-flight: the next window must not go negative
        shed.reset()
        total.reset()
        total.inc(100)
        r = slo.evaluate(now=t0 + 120)[0]
        assert r["windows"]["fast"]["numerator"] >= 0.0
        r = slo.evaluate(now=t0 + 190)[0]
        assert not r["firing"]

    def test_freshness_slo(self):
        g = tm.gauge("slotest.hb_ts")
        g.set(0.0)
        s = slo.SLO("hb", "freshness", 30.0, metric="slotest.hb_ts",
                    severity="warn")
        slo.register_slo(s)
        now = time.time()
        r = slo.evaluate(now=now)[0]
        assert r["no_data"] and not r["firing"]  # never-beat: no verdict
        g.set(now - 10)
        r = slo.evaluate(now=now)[0]
        assert not r["firing"] and r["age_s"] == pytest.approx(10, abs=0.1)
        g.set(now - 120)
        r = slo.evaluate(now=now)[0]
        assert r["firing"]
        assert alerts.is_firing("slo:hb")
        a = [x for x in alerts.active_alerts() if x["name"] == "slo:hb"][0]
        assert a["severity"] == "warn"

    def test_default_slos_installed(self):
        names = slo.install_default_slos()
        assert "serving_latency" in names and "serving_shed" in names
        assert set(names) <= set(slo.registered_slos())
        # idempotent re-install keeps one instance per name
        slo.install_default_slos()
        assert slo.registered_slos().count("serving_latency") == 1

    def test_tick_thread_start_stop(self):
        h = _fresh_hist("slotest.tick_ms")
        slo.register_slo(
            slo.SLO("tick", "quantile", 25.0, metric="slotest.tick_ms", q=0.99)
        )
        evals0 = tm.counter("slo.evaluations").value
        assert slo.start_monitor(0.02)
        assert slo.start_monitor(0.02)  # idempotent
        time.sleep(0.15)
        slo.stop_monitor()
        assert tm.counter("slo.evaluations").value > evals0
        rep = slo.slo_report()
        assert rep["slos"] and rep["slos"][0]["name"] == "tick"

    def test_start_monitor_zero_tick_stays_manual(self):
        assert not slo.start_monitor(0)


# ----------------------------------------------------------------------
# the alert subsystem's own contract
# ----------------------------------------------------------------------
class TestAlerts:
    def test_dedup_refire_refreshes_without_new_event(self):
        assert alerts.fire("a1", "warn", "first", value=1.0)
        assert not alerts.fire("a1", "page", "second", value=2.0, trace_id="tt")
        assert len(alerts.alert_events()) == 1
        a = alerts.active_alerts()[0]
        assert a["value"] == 2.0 and a["severity"] == "page"
        assert a["trace_id"] == "tt"

    def test_labels_distinguish_alerts(self):
        alerts.fire("drift", labels={"model": "a"})
        alerts.fire("drift", labels={"model": "b"})
        assert len(alerts.active_alerts()) == 2
        assert alerts.resolve("drift", labels={"model": "a"})
        assert alerts.is_firing("drift", labels={"model": "b"})
        assert not alerts.is_firing("drift", labels={"model": "a"})

    def test_resolve_idempotent_and_transition_only_events(self):
        assert not alerts.resolve("never_fired")
        alerts.fire("flap")
        alerts.resolve("flap")
        alerts.fire("flap")
        alerts.resolve("flap")
        ev = [e["event"] for e in alerts.alert_events() if e["name"] == "flap"]
        assert ev == ["fired", "resolved", "fired", "resolved"]
        resolved = [e for e in alerts.alert_events() if e["event"] == "resolved"]
        assert all("active_s" in e for e in resolved)

    def test_event_ring_bounded(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_ALERT_RING", "4")
        alerts.refresh_env()
        try:
            for i in range(10):
                alerts.fire(f"e{i}")
                alerts.resolve(f"e{i}")
            assert len(alerts.alert_events()) == 4
        finally:
            monkeypatch.delenv("HEAT_TPU_ALERT_RING")
            alerts.refresh_env()

    def test_bad_severity_raises(self):
        with pytest.raises(ValueError):
            alerts.fire("x", severity="catastrophic")

    def test_severity_ordering_in_active_table(self):
        alerts.fire("low", severity="info")
        alerts.fire("high", severity="page")
        alerts.fire("mid", severity="warn")
        sevs = [a["severity"] for a in alerts.active_alerts()]
        assert sevs == ["page", "warn", "info"]

    def test_merge_alert_snapshots_deterministic(self):
        alerts.fire("s1", severity="page", labels={"model": "m"})
        snap = alerts.alerts_snapshot()
        merged_a = alerts.merge_alert_snapshots([("0", snap), ("1", snap)])
        merged_b = alerts.merge_alert_snapshots([("1", snap), ("0", snap)])
        assert merged_a == merged_b
        assert merged_a["active_count"] == 2  # one per replica: both burn
        assert merged_a["worst_severity"] == "page"

    def test_concurrent_fire_resolve_threads(self):
        # the TSAN-lane surface: alert mutations from many threads
        def worker(i):
            for j in range(50):
                alerts.fire(f"t{i}", value=float(j))
                alerts.resolve(f"t{i}")

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not alerts.active_alerts()


# ----------------------------------------------------------------------
# cross-worker + bundle plumbing
# ----------------------------------------------------------------------
class TestAggregation:
    def test_tag_snapshot_ships_alerts_and_merge_folds_them(self):
        alerts.fire("s1", severity="page", message="m", labels={"k": "v"})
        snap = aggregate.tag_snapshot()
        assert snap["alerts"]["active"]
        other = dict(snap, process_index=1)
        merged = aggregate.merge_snapshots([snap, other], publish=False)
        assert merged["alerts"]["active_count"] == 2
        workers = {a["worker"] for a in merged["alerts"]["active"]}
        assert workers == {"0", "1"}

    def test_flight_bundle_carries_alert_and_slo_sections(self):
        from heat_tpu.telemetry import flight_recorder

        h = _fresh_hist("slotest.bundle_ms")
        slo.register_slo(
            slo.SLO("bndl", "quantile", 25.0, metric="slotest.bundle_ms", q=0.9)
        )
        slo.evaluate()
        alerts.fire("bundle_alert", severity="warn", message="hello")
        doc = flight_recorder.build_bundle(reason="test")
        assert doc["alerts"]["active"][0]["name"] == "bundle_alert"
        assert any(s["name"] == "bndl" for s in doc["slo"]["slos"])
        from heat_tpu.telemetry.inspect import format_bundle

        txt = format_bundle(doc)
        assert "bundle_alert" in txt
        assert "slo verdicts" in txt


# ----------------------------------------------------------------------
# /sloz HTTP surface + escaping
# ----------------------------------------------------------------------
class TestSlozEndpoint:
    def test_sloz_json_and_html(self):
        import json as _json
        import urllib.request

        from heat_tpu.telemetry import server as tserver

        h = _fresh_hist("slotest.http_ms")
        slo.register_slo(
            slo.SLO("http", "quantile", 25.0, metric="slotest.http_ms", q=0.99)
        )
        slo.evaluate()
        tserver.stop_server()
        srv = tserver.start_server(0)
        try:
            doc = _json.loads(
                urllib.request.urlopen(srv.url + "/sloz?format=json", timeout=5).read()
            )
            assert any(s["name"] == "http" for s in doc["slos"])
            html = urllib.request.urlopen(srv.url + "/sloz", timeout=5).read().decode()
            assert "burn-rate" in html and "slotest.http_ms" in html
            root = urllib.request.urlopen(srv.url + "/", timeout=5).read().decode()
            assert "/sloz" in root and "/driftz" in root
        finally:
            tserver.stop_server()

    def test_sloz_html_escapes_hostile_names(self):
        evil = "<script>alert(1)</script>"
        slo.register_slo(
            slo.SLO(evil, "quantile", 25.0, metric="slotest.evil_ms", q=0.99)
        )
        _fresh_hist("slotest.evil_ms")
        slo.evaluate()
        alerts.fire(evil, severity="page", message=f"msg {evil}",
                    labels={"model": evil})
        html = slo.render_sloz_html()
        assert "<script>" not in html
        assert "&lt;script&gt;" in html
