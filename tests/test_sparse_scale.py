"""Scale-honesty of the r5 sparse programs (VERDICT r4 #2 / weak #4-6).

Pins the three r5 guarantees at the program level, not just by value:
the CSR SpMM never materializes a full replica of the dense operand (its
HLO carries a collective-permute ring and no all-gather of X), the
None<->split re-chunk runs on device (planes in, planes out, correct in
both directions), and sparse@sparse flows through the same programs (its
memory bound is the result's per-device dense row block, documented at
``_spgemm``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import heat_tpu as ht
from heat_tpu.sparse import _planes as _pl


@pytest.fixture(scope="module")
def mats():
    a = sp.random(400, 300, density=0.03, random_state=11, format="csr", dtype=np.float64)
    b = sp.random(300, 50, density=0.05, random_state=12, format="csr", dtype=np.float64)
    return a, b


def test_csr_ring_spmm_hlo_has_no_allgather(mats):
    a_sp, _ = mats
    a = ht.sparse.sparse_csr_matrix(a_sp, split=0)
    x = ht.random.randn(300, 50, split=0).astype(ht.float64)
    k_pad = a.comm.padded_extent(300)
    prog = _pl._spmm_comp_rows_ring_prog(
        a.comm, a._nshards, a._capacity, a._comp_pad, k_pad, 50
    )
    hlo = prog.lower(a._comp, a._other, a._val, x.larray_padded).compile().as_text()
    assert "all-gather" not in hlo, "ring SpMM must not gather X"
    assert "all-to-all" not in hlo
    assert "collective-permute" in hlo, "the X ring rides collective-permute"


def test_csr_ring_spmm_values(mats):
    a_sp, _ = mats
    a = ht.sparse.sparse_csr_matrix(a_sp, split=0)
    rng = np.random.default_rng(0)
    xh = rng.standard_normal((300, 50))
    for xsplit in (0, 1, None):
        x = ht.array(xh, split=xsplit)
        got = (a @ x).numpy()
        np.testing.assert_allclose(got, a_sp @ xh, rtol=1e-10)


def test_rechunk_round_trip(mats):
    a_sp, _ = mats
    for fmt, ctor in (("csr", ht.sparse.sparse_csr_matrix), ("csc", ht.sparse.sparse_csc_matrix)):
        src = a_sp.asformat(fmt)
        dist = ctor(src, split=0 if fmt == "csr" else 1)
        # split -> None on device
        from heat_tpu.sparse.arithmetics import _align_split

        rep = _align_split(dist, None)
        assert rep.split is None
        np.testing.assert_allclose(rep.toarray(), src.toarray())
        # planes replicated, sorted, no host numpy types
        assert isinstance(rep._comp, jax.Array)
        # None -> split on device
        back = _align_split(rep, dist.split)
        assert back.split == dist.split
        np.testing.assert_allclose(back.toarray(), src.toarray())
        assert back._lnnz_host == dist._lnnz_host
        np.testing.assert_array_equal(
            np.asarray(back._comp), np.asarray(dist._comp)
        )


def test_mixed_split_binary_on_device(mats):
    a_sp, _ = mats
    a0 = ht.sparse.sparse_csr_matrix(a_sp, split=0)
    an = ht.sparse.sparse_csr_matrix(1.5 * a_sp, split=None)
    res = a0 + an
    assert res.split == 0
    np.testing.assert_allclose(res.toarray(), (2.5 * a_sp).toarray(), rtol=1e-12)
    res2 = an + a0  # aligns a0 to None
    assert res2.split is None
    np.testing.assert_allclose(res2.toarray(), (2.5 * a_sp).toarray(), rtol=1e-12)


def test_spgemm_values_and_format(mats):
    a_sp, b_sp = mats
    want = (a_sp @ b_sp).toarray()
    a = ht.sparse.sparse_csr_matrix(a_sp, split=0)
    b = ht.sparse.sparse_csr_matrix(b_sp, split=0)
    c = a @ b
    assert isinstance(c, type(a))
    np.testing.assert_allclose(c.toarray(), want, rtol=1e-10)


def test_spgemm_wide_result_stays_sharded(mats):
    # the per-device bound is the RESULT row block (m/P x n), not m x n:
    # verify the intermediate/result planes stay sharded over the mesh
    a_sp = sp.random(800, 600, density=0.01, random_state=1, format="csr")
    b_sp = sp.random(600, 400, density=0.01, random_state=2, format="csr")
    a = ht.sparse.sparse_csr_matrix(a_sp, split=0)
    b = ht.sparse.sparse_csr_matrix(b_sp, split=0)
    c = a @ b
    assert len(c._val.sharding.device_set) == a.comm.size
    np.testing.assert_allclose(
        c.toarray(), (a_sp @ b_sp).toarray(), rtol=1e-5, atol=1e-6
    )
