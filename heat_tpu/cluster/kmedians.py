"""KMedians clustering, analog of heat/cluster/kmedians.py (kmedians.py:11).

Centers update to the per-cluster feature-wise median instead of the mean.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMedians"]


class KMedians(_KCluster):
    """K-Medians with manhattan assignment (kmedians.py:11)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        tol: float = 1e-4,
        random_state: Optional[int] = None,
    ):
        if init == "kmedians++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=tol,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Per-cluster median (kmedians.py:70-110).  The reference gathers
        per-cluster members rank-locally; here a masked global median per
        cluster is computed (k small)."""
        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        labels = matching_centroids._dense()
        old = self._cluster_centers._dense()
        new_centers = []
        for c in range(self.n_clusters):
            mask = labels == c
            cnt = jnp.sum(mask)
            masked = jnp.where(mask[:, None], dense, jnp.nan)
            med = jnp.nanmedian(masked, axis=0)
            new_centers.append(jnp.where(cnt > 0, med, old[c]))
        new = jnp.stack(new_centers)
        return DNDarray.from_dense(new, None, x.device, x.comm)

    def fit(self, x: DNDarray) -> "KMedians":
        """Iterate until median shift < tol (kmedians.py:~120)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        self._initialize_cluster_centers(x)

        for i in range(self.max_iter):
            matching_centroids = self._assign_to_cluster(x)
            new_cluster_centers = self._update_centroids(x, matching_centroids)
            shift = float(jnp.sum((new_cluster_centers._dense() - self._cluster_centers._dense()) ** 2))
            self._cluster_centers = new_cluster_centers
            if shift <= self.tol:
                break

        self._n_iter = i + 1
        self._labels = self._assign_to_cluster(x, eval_functional_value=True)
        return self
