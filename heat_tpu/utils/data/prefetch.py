"""Shard-aware device prefetch (overlap layer, docs/overlap.md).

The loaders in this package already overlap *disk* reads with compute
(:class:`~heat_tpu.utils.data.PartialH5DataLoaderIter`'s loader thread),
but batches still landed on the default device unsharded — the
host->device copy and any resharding were paid inside the consuming
train step.  :func:`prefetch_to_device` closes that gap: a
double-buffered iterator adapter that stages ``jax.device_put`` of batch
*i+1* — with the canonical split :class:`~jax.sharding.NamedSharding`
when one is given — while batch *i* computes.  Because JAX dispatch is
asynchronous, ``device_put`` on the staged batch returns immediately and
the transfer rides the device's copy engine behind the running step (the
same overlap the reference wins by handing converted batches to daemon
threads in ``heat/utils/data/partial_dataset.py``).

Counters: every batch handed out that was staged *ahead* of the consumer
counts a ``prefetch_hit``; a batch staged synchronously on demand (an
underrun) counts a ``prefetch_miss`` (shared overlap stats surface,
:func:`heat_tpu.utils.overlap.overlap_stats`).
"""

from __future__ import annotations

from collections import deque
from typing import Any, Iterable, Iterator, Optional

import jax
import jax.numpy as jnp

from ..overlap import _bump

__all__ = ["prefetch_to_device", "sharding_for_batch"]


def sharding_for_batch(batch_extent: int, comm=None, split: int = 0):
    """The canonical split sharding for a batch of ``batch_extent`` rows,
    or ``None`` when the extent does not tile the mesh (``device_put``
    would reject a ragged split; callers fall back to the default
    placement, exactly like the train-step staging paths)."""
    from ...parallel.comm import sanitize_comm

    comm = sanitize_comm(comm)
    if comm.size > 0 and batch_extent % comm.size == 0:
        return comm.sharding(split)
    return None


def _stage(batch: Any, sharding) -> Any:
    """Start the host->device copy of every array leaf of ``batch``
    (non-blocking: JAX async dispatch owns the transfer)."""

    def one(x):
        if not hasattr(x, "shape") and not hasattr(x, "dtype"):
            return x  # non-array payloads ride along untouched
        if sharding is not None:
            return jax.device_put(x, sharding)
        return jnp.asarray(x)

    return jax.tree_util.tree_map(one, batch)


class _DevicePrefetcher:
    """Bounded look-ahead buffer of device-staged batches."""

    def __init__(self, it: Iterator, size: int, sharding):
        self._it: Optional[Iterator] = iter(it)
        self._size = size
        self._sharding = sharding
        self._buf: "deque" = deque()
        self._fill()  # prime: batches 0..size-1 staged before first use

    def _fill(self) -> None:
        while self._it is not None and len(self._buf) < self._size:
            try:
                nxt = next(self._it)
            except StopIteration:
                self._it = None
                return
            self._buf.append(_stage(nxt, self._sharding))

    def __iter__(self) -> "_DevicePrefetcher":
        return self

    def __next__(self):
        if self._buf:
            _bump("prefetch_hits")
            out = self._buf.popleft()
        elif self._it is None:
            raise StopIteration
        else:  # underrun: stage synchronously (still correct, not overlapped)
            out = _stage(next(self._it), self._sharding)
            _bump("prefetch_misses")
        self._fill()  # restart the look-ahead immediately
        return out

    def close(self) -> None:
        """Release the underlying iterator WITHOUT draining it.

        A stream head is unbounded — iterating to exhaustion never
        terminates — so shutdown drops the staged look-ahead buffer and
        closes the source generator (``GeneratorExit`` runs its
        ``finally`` blocks) instead of consuming it.  Idempotent; the
        prefetcher raises ``StopIteration`` afterwards."""
        it, self._it = self._it, None
        self._buf.clear()
        closer = getattr(it, "close", None)
        if callable(closer):
            closer()

    # with-statement support: ``with prefetch_to_device(stream) as it:``
    # guarantees the stream head is released on any exit path
    def __enter__(self) -> "_DevicePrefetcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def prefetch_to_device(it: Iterable, size: int = 2, sharding=None) -> Iterator:
    """Wrap ``it`` so batches are staged on device ``size`` steps ahead.

    Parameters
    ----------
    it : iterable of batches
        Each batch is a pytree whose array leaves (numpy or jax) are
        staged; non-array leaves pass through.
    size : int
        Look-ahead depth (default 2 — classic double buffering: one
        batch computing, one in flight).
    sharding : jax.sharding.Sharding, optional
        Placement for the staged leaves (e.g. the canonical split
        ``NamedSharding`` from :meth:`Communication.sharding`, or
        :func:`sharding_for_batch`).  ``None`` stages to the default
        device.  The caller guarantees the sharding tiles every staged
        leaf (``sharding_for_batch`` returns ``None`` otherwise).

    Ordering is preserved exactly; ``StopIteration`` propagates after
    the last buffered batch is handed out.

    The returned iterator is closeable (and usable as a context
    manager): ``close()`` releases an *unbounded* source — a live
    stream head (docs/streaming.md) — by dropping the staged buffer and
    closing the underlying generator, never by draining it.
    """
    if size < 1:
        raise ValueError(f"prefetch size must be >= 1, got {size}")
    return _DevicePrefetcher(it, size, sharding)
