"""Canary decision plane: shadow mirroring, online comparison, evented
auto-promote/rollback (ISSUE 15).

The acceptance properties: the comparator grid honors the per-kind
``POLICIES`` contract (bitwise kinds exact, tolerance kinds within
budget, a deliberately-degraded canary detected), shadow traffic never
rides any caller's latency path and compiles nothing in steady state,
a degraded canary under live load is auto-rolled-back with **zero
failed client requests** while the decision lands as a retained event
(exemplar trace_id) on ``/canaryz`` and in a flight-recorder bundle,
and the fleet router rolls per-replica canary state into ``/fleetz``
with divergent-replica highlighting.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.core import dispatch
from heat_tpu.fleet import FleetRouter
from heat_tpu.serving import canary as cn
from heat_tpu.serving import model_io
from heat_tpu.telemetry import aggregate
from heat_tpu.telemetry import alerts as talerts
from heat_tpu.telemetry import flight_recorder
from heat_tpu.telemetry import inspect as tinspect
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

RNG = np.random.default_rng(7)
PTS = RNG.standard_normal((160, 6)).astype(np.float32)


@pytest.fixture(autouse=True)
def _clean_canary_state():
    cn.reset_canary_state()
    talerts.clear_alerts()
    yield
    cn.reset_canary_state()
    talerts.clear_alerts()


def _fit_kmeans():
    x = ht.array(PTS, split=0)
    return ht.cluster.KMeans(
        n_clusters=3, init="random", max_iter=5, random_state=0
    ).fit(x)


def _degrade_kmeans(est):
    """A deliberately-degraded copy: cluster centers permuted, so every
    predicted label moves — the canary a decision plane must catch."""
    bad = model_io.build_estimator(model_io.export_state(est))
    centers = np.asarray(bad._cluster_centers.numpy())
    bad._cluster_centers = ht.array(centers[::-1].copy(), split=None)
    return bad


@pytest.fixture
def model_dir(tmp_path):
    """v1 = the good model (active), v2 = the SAME model (a worthy
    canary), v3 = the degraded copy (a canary that must fail)."""
    est = _fit_kmeans()
    d = str(tmp_path / "km")
    serving.save_model(est, d, version=1, name="km")
    serving.save_model(est, d, version=2, name="km")
    serving.save_model(_degrade_kmeans(est), d, version=3, name="km")
    return d


@pytest.fixture
def make_service(model_dir):
    made = []

    def make(canary_version=None, fraction=1.0, min_rows=48, **kw):
        svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0, **kw)
        svc.load("km", model_dir, version=1)
        if canary_version is not None:
            svc.load("km", model_dir, version=canary_version, activate=False)
        svc.canary.fraction = fraction
        svc.canary.min_rows = min_rows
        made.append(svc)
        return svc

    yield make
    for svc in made:
        svc.close()


def _drive(svc, n=40, rows=8):
    for i in range(n):
        off = (i * 11) % 64
        svc.predict("km", PTS[off : off + rows])


# ----------------------------------------------------------------------
# the comparator grid
# ----------------------------------------------------------------------
class TestComparator:
    def test_bitwise_exact_pass(self):
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        out = cn.compare_batch("Lasso", a, a.copy())
        assert out == {"rows": 4, "mismatched": 0, "max_rel_err": 0.0, "mode": "bitwise"}

    def test_bitwise_single_row_mismatch(self):
        a = np.arange(12, dtype=np.float32).reshape(4, 3)
        b = a.copy()
        b[2, 1] += 1e-6  # one ULP-ish wiggle is already a violation
        out = cn.compare_batch("Lasso", a, b)
        assert out["mismatched"] == 1 and out["max_rel_err"] > 0.0

    def test_bitwise_dtype_change_fails_every_row(self):
        a = np.arange(8, dtype=np.float32).reshape(4, 2)
        out = cn.compare_batch("Lasso", a, a.astype(np.float64))
        assert out["mismatched"] == 4

    def test_shape_change_fails_every_row(self):
        a = np.arange(8, dtype=np.float32).reshape(4, 2)
        out = cn.compare_batch("KMeans", a, a[:, :1])
        assert out["mismatched"] == 4

    def test_tolerance_float_within_rtol(self):
        a = RNG.standard_normal((16, 4)).astype(np.float32)
        b = a * (1.0 + 1e-4)  # well inside KMeans rtol=0.02
        out = cn.compare_batch("KMeans", a, b)
        assert out["mode"] == "tolerance"
        assert out["mismatched"] == 0
        assert 0.0 < out["max_rel_err"] < 0.02

    def test_tolerance_float_beyond_rtol(self):
        a = np.ones((8, 2), np.float32)
        b = a.copy()
        b[:3] *= 1.5  # 50% off on 3 rows
        out = cn.compare_batch("KMeans", a, b)
        assert out["mismatched"] == 3

    def test_tolerance_integer_labels_disagreement(self):
        a = np.array([0, 1, 2, 0, 1], np.int32)
        b = np.array([0, 1, 2, 1, 1], np.int32)
        out = cn.compare_batch("KMeans", a, b)
        assert out["rows"] == 5 and out["mismatched"] == 1

    def test_nan_is_never_equal_enough(self):
        a = np.zeros((3, 2), np.float32)
        b = a.copy()
        b[1, 0] = np.nan
        out = cn.compare_batch("Lasso", a, b)
        assert out["mismatched"] == 1


# ----------------------------------------------------------------------
# registry canary-slot tracking
# ----------------------------------------------------------------------
class TestRegistryCanarySlot:
    def test_load_promote_unload_lifecycle(self, model_dir):
        reg = serving.ModelRegistry()
        reg.load("km", model_dir, version=1)
        assert reg.canary_version("km") is None
        reg.load("km", model_dir, version=2, activate=False)
        assert reg.canary_version("km") == 2
        assert reg.models()["km"]["canary"] == 2
        reg.promote("km", 2)
        assert reg.canary_version("km") is None  # the canary went live
        reg.load("km", model_dir, version=3, activate=False)
        assert reg.canary_version("km") == 3
        reg.unload("km", 3)
        assert reg.canary_version("km") is None

    def test_activating_load_clears_the_slot(self, model_dir):
        reg = serving.ModelRegistry()
        reg.load("km", model_dir, version=1)
        reg.load("km", model_dir, version=2, activate=False)
        reg.load("km", model_dir, version=2)  # explicit activation
        assert reg.canary_version("km") is None


# ----------------------------------------------------------------------
# shadow mirroring mechanics
# ----------------------------------------------------------------------
class TestShadowMirroring:
    def test_fraction_systematic_sampling(self, make_service):
        svc = make_service(canary_version=2, fraction=0.5, min_rows=10_000)
        s0 = tm.counter("canary.sampled").value
        o0 = tm.counter("canary.offered").value
        _drive(svc, n=12, rows=4)
        assert svc.canary.wait_idle(30)
        sampled = tm.counter("canary.sampled").value - s0
        offered = tm.counter("canary.offered").value - o0
        # systematic sampling: EXACTLY every second offered batch is
        # mirrored, however the 12 requests coalesced into batches
        assert offered >= 6
        assert sampled == offered // 2

    def test_no_canary_means_no_mirroring(self, make_service):
        svc = make_service(canary_version=None, fraction=1.0)
        s0 = tm.counter("canary.sampled").value
        _drive(svc, n=6)
        assert tm.counter("canary.sampled").value == s0
        assert cn.status("km") is None

    def test_shadowing_compiles_nothing_in_steady_state(self, make_service):
        """The finite-key-set property: the canary rides the SAME
        bucket-padded shapes, so shadow inference is pure cache hits."""
        svc = make_service(canary_version=2, fraction=0.0, min_rows=10_000)
        _drive(svc, n=4, rows=8)  # warm the primary's bucket
        stats0 = dispatch.cache_stats()
        svc.canary.fraction = 1.0
        _drive(svc, n=12, rows=8)
        assert svc.canary.wait_idle(30)
        stats1 = dispatch.cache_stats()
        assert stats1["misses"] == stats0["misses"], "shadowing must not compile"
        st = cn.status("km")
        assert st is not None and st["rows"] > 0


# ----------------------------------------------------------------------
# the decision engine
# ----------------------------------------------------------------------
class TestDecisions:
    def test_healthy_canary_auto_promotes(self, make_service):
        svc = make_service(canary_version=2, min_rows=48)
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["decision"]["action"] == "promoted"
        assert st["decision"]["verdict"] == "pass"
        assert svc.registry.active_version("km") == 2
        assert svc.registry.canary_version("km") is None
        assert not talerts.is_firing("canary:km", labels={"model": "km"})
        # the decision is a retained event with the exemplar trace
        decisions = [e for e in cn.canary_events() if e["kind"] == "decision"]
        assert decisions and decisions[-1]["action"] == "promoted"
        assert decisions[-1]["trace_id"]

    def test_degraded_canary_auto_rolls_back(self, make_service, tmp_path):
        flight_recorder.install(str(tmp_path / "bundles"))
        try:
            svc = make_service(canary_version=3, min_rows=48)
            _drive(svc, n=10, rows=8)
            assert svc.canary.wait_idle(30)
        finally:
            flight_recorder.uninstall()
        st = cn.status("km")
        assert st["decision"]["action"] == "rolled_back"
        assert st["decision"]["verdict"] == "fail"
        assert st["decision"]["reasons"]
        assert svc.registry.active_version("km") == 1  # primary untouched
        assert svc.registry.canary_version("km") is None
        with pytest.raises(KeyError):
            svc.registry.record("km", 3)  # the bad version is gone
        assert talerts.is_firing("canary:km", labels={"model": "km"})
        # the rollback wrote a forensic bundle carrying the canary section
        paths = sorted((tmp_path / "bundles").glob("flight_*.json"))
        assert paths
        doc = tinspect.load_bundle(str(paths[-1]))
        assert doc["reason"] == "canary_rollback:km"
        dec = doc["canary"]["models"]["km"]["decision"]
        assert dec["action"] == "rolled_back" and dec["reasons"]

    def test_observe_only_mode_records_without_acting(self, make_service):
        svc = make_service(canary_version=3, min_rows=48)
        svc.canary.auto = False
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["decision"]["verdict"] == "fail"
        assert st["decision"]["action"] == "observed"
        assert svc.registry.active_version("km") == 1
        assert svc.registry.canary_version("km") == 3  # still resident

    def test_drift_alert_vetoes_then_clears(self, make_service):
        talerts.fire("drift:km", severity="warn", message="synthetic drift",
                     labels={"model": "km"})
        svc = make_service(canary_version=2, min_rows=48)
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["verdict"] == "held" and st["decision"] is None
        assert any("drift" in v for v in st["vetoes"])
        held = [e for e in cn.canary_events()
                if e["kind"] == "decision" and e.get("action") == "held"]
        assert held, "the held verdict must be a retained event"
        # signal clears -> the next compared batch promotes
        talerts.resolve("drift:km", labels={"model": "km"})
        _drive(svc, n=4, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["decision"]["action"] == "promoted"

    def test_slo_alert_vetoes(self, make_service):
        talerts.fire("slo:latency_p99", severity="page", message="burning")
        svc = make_service(canary_version=2, min_rows=48)
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["verdict"] == "held"
        assert any("slo:latency_p99" in v for v in st["vetoes"])

    def test_latency_budget_clause(self, make_service):
        """_evaluate flags a canary whose per-row time blows the budget
        (synthetic window: the clause, isolated from the comparator)."""
        svc = make_service(canary_version=2, min_rows=10_000)
        st = cn._new_state("km", "KMeans", 2, 1, min_rows=10)
        st["rows"] = 20
        st["primary_ms"] = 10.0
        st["canary_ms"] = 10.0 * svc.canary.latency_x * 1.5
        verdict, reasons = svc.canary._evaluate(st)
        assert verdict == "fail" and any("latency" in r for r in reasons)
        st["canary_ms"] = 9.0
        assert svc.canary._evaluate(st) == ("pass", [])

    def test_bitwise_window_allows_zero_mismatches(self, make_service):
        svc = make_service(canary_version=2, min_rows=10_000)
        st = cn._new_state("lasso", "Lasso", 2, 1, min_rows=10)
        st["rows"], st["mismatched"] = 100, 1
        verdict, reasons = svc.canary._evaluate(st)
        assert verdict == "fail" and "bitwise" in reasons[0]

    def test_canary_inference_error_is_terminal(self, make_service):
        """A canary that RAISES is rolled back immediately — no window."""
        svc = make_service(canary_version=2, min_rows=10_000)

        class _Boom:
            def predict(self, x):
                raise RuntimeError("canary kernel exploded")

        # break the canary estimator in place: predict raises
        svc.registry.record("km", 2)["estimator"] = _Boom()
        _drive(svc, n=3, rows=8)
        assert svc.canary.wait_idle(30)
        st = cn.status("km")
        assert st["decision"]["action"] == "rolled_back"
        errors = [e for e in cn.canary_events() if e["kind"] == "error"]
        assert errors and errors[-1]["severity"] == "page"


# ----------------------------------------------------------------------
# surfaces: /healthz fields, /canaryz, /statusz, snapshots, bundles
# ----------------------------------------------------------------------
class TestSurfaces:
    def test_model_healthz_carries_canary_fields(self, make_service):
        svc = make_service(canary_version=2, min_rows=10_000)
        _drive(svc, n=4, rows=8)
        assert svc.canary.wait_idle(30)
        doc = svc.model_health("km")
        assert doc["canary_version"] == 2
        assert doc["shadow_sampled_rows"] > 0
        assert doc["last_canary_verdict"] == "collecting"

    def test_canaryz_routes_html_and_json(self, make_service):
        svc = make_service(canary_version=3, min_rows=48)
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        tserver.stop_server()
        srv = tserver.start_server(0)
        try:
            with urllib.request.urlopen(srv.url + "/canaryz?format=json") as r:
                doc = json.load(r)
            assert doc["models"]["km"]["decision"]["action"] == "rolled_back"
            assert doc["shadow"]["sampled"] > 0
            with urllib.request.urlopen(srv.url + "/canaryz") as r:
                html = r.read().decode()
            assert "km" in html and "rolled_back" in html
            with urllib.request.urlopen(srv.url + "/statusz") as r:
                status = json.load(r)
            assert status["canary"]["models"]["km"]["verdict"] == "fail"
        finally:
            tserver.stop_server()

    def test_canaryz_html_escapes_hostile_names(self, make_service):
        cn.record_event("<script>alert(1)</script>", "decision", "page",
                        "<img src=x onerror=alert(1)>")
        html = cn.render_canaryz_html()
        assert "<script>alert" not in html
        assert "&lt;script&gt;" in html

    def test_tagged_snapshot_and_divergence_merge(self, make_service):
        svc = make_service(canary_version=2, min_rows=10_000)
        _drive(svc, n=4, rows=8)
        assert svc.canary.wait_idle(30)
        snap = aggregate.tag_snapshot()
        assert snap["canary"]["models"]["km"]["canary_version"] == 2
        # two synthetic workers disagreeing on the verdict -> divergent
        s0 = dict(snap, process_index=0)
        s1 = json.loads(json.dumps(snap))
        s1["process_index"] = 1
        s1["canary"]["models"]["km"]["verdict"] = "fail"
        merged = aggregate.merge_snapshots([s0, s1], publish=False)
        entry = merged["canary"]["models"]["km"]
        assert entry["divergent"] is True
        assert set(entry["workers"]) == {"0", "1"}
        # agreeing workers are not divergent
        merged2 = aggregate.merge_snapshots([s0, dict(s0, process_index=1)],
                                            publish=False)
        assert merged2["canary"]["models"]["km"]["divergent"] is False

    def test_inspect_renders_canary_section_in_memory(self, make_service):
        svc = make_service(canary_version=3, min_rows=48)
        _drive(svc, n=10, rows=8)
        assert svc.canary.wait_idle(30)
        text = tinspect.format_bundle(flight_recorder.build_bundle())
        assert "canary decision plane" in text
        assert "rolled_back" in text and "km" in text


# ----------------------------------------------------------------------
# fleet rollup: /fleetz canary table with divergent highlighting
# ----------------------------------------------------------------------
class _FakeCanaryReplica:
    """Minimal replica speaking /readyz + /canaryz for the router's
    health poller."""

    def __init__(self, verdict, version=2):
        self.canary_doc = {
            "timestamp": time.time(),
            "shadow": {},
            "models": {
                "km": {
                    "canary_version": version, "verdict": verdict,
                    "rows": 64, "mismatch_pct": 0.0, "latency_ratio": 1.0,
                    "decision": None, "last_trace_id": "t-1",
                }
            },
            "events": [],
        }
        outer = self

        class _H(BaseHTTPRequestHandler):
            def log_message(self, *args):
                pass

            def _send(self, code, doc):
                body = json.dumps(doc).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/readyz":
                    self._send(200, {"ready": True, "state": "ready",
                                     "models": ["km"]})
                elif self.path.startswith("/canaryz"):
                    self._send(200, outer.canary_doc)
                elif self.path.startswith("/rooflinez"):
                    self._send(200, {"ledger": [], "ledger_total": 0})
                else:
                    self._send(404, {"error": "?"})

        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), _H)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fake-canary-replica",
            daemon=True,
        )
        self._thread.start()
        self.url = f"http://127.0.0.1:{self._httpd.server_address[1]}"

    def close(self):
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class TestFleetRollup:
    def test_fleetz_reports_divergent_replicas(self):
        r1 = _FakeCanaryReplica(verdict="pass")
        r2 = _FakeCanaryReplica(verdict="fail")
        router = FleetRouter(replicas=(r1.url, r2.url), health_period_s=30.0)
        try:
            router.poll_health()
            doc = router.fleetz_report()
            entry = doc["canary"]["km"]
            assert set(entry["replicas"]) == {r1.url, r2.url}
            assert entry["divergent"] is True
            assert sorted(entry["verdicts"]) == ["fail", "pass"]
            html = router.render_fleetz_html()
            assert "divergent" in html and "km" in html
        finally:
            router.close()
            r1.close()
            r2.close()

    def test_fleetz_agreeing_replicas_not_divergent(self):
        r1 = _FakeCanaryReplica(verdict="pass")
        r2 = _FakeCanaryReplica(verdict="pass")
        router = FleetRouter(replicas=(r1.url, r2.url), health_period_s=30.0)
        try:
            router.poll_health()
            assert router.fleetz_report()["canary"]["km"]["divergent"] is False
        finally:
            router.close()
            r1.close()
            r2.close()


# ----------------------------------------------------------------------
# the e2e acceptance scenario + the subprocess crash surface
# ----------------------------------------------------------------------
class TestEndToEnd:
    def test_degraded_canary_rolled_back_under_live_load(
        self, make_service, tmp_path
    ):
        """ISSUE 15 acceptance: a deliberately-degraded canary under
        concurrent live load is auto-rolled-back with ZERO failed client
        requests; the decision is a retained /canaryz event with an
        exemplar trace_id and a flight-recorder bundle records the
        failed comparison."""
        flight_recorder.install(str(tmp_path / "bundles"))
        tserver.stop_server()
        srv = tserver.start_server(0)
        errors = []
        try:
            svc = make_service(canary_version=3, min_rows=96)

            def client(worker):
                sizes = (3, 5, 8, 13)
                for i in range(40):
                    off = (worker * 31 + i * 7) % 64
                    n = sizes[(worker + i) % len(sizes)]
                    try:
                        out = svc.predict("km", PTS[off : off + n], timeout=30)
                        assert out.shape[0] == n
                    except Exception as e:  # pragma: no cover - the assertion target
                        errors.append(e)

            threads = [
                threading.Thread(target=client, args=(w,), daemon=True)
                for w in range(4)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            assert svc.canary.wait_idle(60)
            assert errors == [], f"live clients failed: {errors[:3]}"

            st = cn.status("km")
            assert st["decision"]["action"] == "rolled_back"
            assert svc.registry.active_version("km") == 1
            # the decision is retained on /canaryz with an exemplar trace
            with urllib.request.urlopen(srv.url + "/canaryz?format=json") as r:
                doc = json.load(r)
            decisions = [e for e in doc["events"] if e["kind"] == "decision"]
            assert decisions and decisions[-1]["action"] == "rolled_back"
            assert decisions[-1]["trace_id"], "decision must carry its exemplar"
            # the flight-recorder bundle records the failed comparison
            paths = sorted((tmp_path / "bundles").glob("flight_*.json"))
            assert paths
            bundle = tinspect.load_bundle(str(paths[-1]))
            assert bundle["reason"] == "canary_rollback:km"
            assert bundle["canary"]["models"]["km"]["mismatched_rows"] > 0
        finally:
            tserver.stop_server()
            flight_recorder.uninstall()

    def test_subprocess_rollback_bundle_and_inspect_cli(self, tmp_path):
        """The crash surface, end to end in a REAL process: the
        auto-rollback's bundle lands on disk checksum-valid with the
        canary section, and the inspect CLI renders it."""
        bundles = tmp_path / "bundles"
        child = f"""
import jax
jax.config.update('jax_platforms', 'cpu')
import numpy as np
import heat_tpu as ht
from heat_tpu import serving
from heat_tpu.serving import canary as cn, model_io

rng = np.random.default_rng(7)
pts = rng.standard_normal((160, 6)).astype(np.float32)
x = ht.array(pts, split=0)
km = ht.cluster.KMeans(n_clusters=3, init='random', max_iter=5, random_state=0).fit(x)
bad = model_io.build_estimator(model_io.export_state(km))
c = np.asarray(bad._cluster_centers.numpy())
bad._cluster_centers = ht.array(c[::-1].copy(), split=None)
d = {str(tmp_path / 'km')!r}
serving.save_model(km, d, version=1, name='km')
serving.save_model(bad, d, version=2, name='km')
svc = serving.InferenceService(max_batch=32, max_delay_ms=1.0)
svc.load('km', d, version=1)
svc.load('km', d, version=2, activate=False)
svc.canary.fraction = 1.0
svc.canary.min_rows = 48
for i in range(10):
    svc.predict('km', pts[(i * 11) % 64 : (i * 11) % 64 + 8])
assert svc.canary.wait_idle(60)
st = cn.status('km')
assert st['decision']['action'] == 'rolled_back', st
svc.close()
"""
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["HEAT_TPU_FLIGHT_RECORDER"] = str(bundles)
        proc = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True,
            cwd=REPO_ROOT, timeout=600,
        )
        assert proc.returncode == 0, proc.stderr.decode()[-3000:]
        paths = sorted(bundles.glob("flight_*.json"))
        assert len(paths) == 1
        doc = tinspect.load_bundle(str(paths[0]))  # CRC-verified
        assert doc["reason"] == "canary_rollback:km"
        km_doc = doc["canary"]["models"]["km"]
        assert km_doc["decision"]["action"] == "rolled_back"
        assert km_doc["mismatched_rows"] > 0
        assert any(e["kind"] == "comparison" for e in doc["canary"]["events"])
        # the inspect CLI renders the canary section end to end
        res = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.inspect", str(paths[0])],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, cwd=REPO_ROOT, timeout=300,
        )
        assert res.returncode == 0, res.stderr.decode()[-2000:]
        out = res.stdout.decode()
        assert "canary decision plane" in out
        assert "rolled_back" in out and "km" in out
