"""Framework-invariant lint gate: fail CI on any NEW violation.

Same gate pattern as ``perf_gate.py``: a committed record of the
accepted state (``scripts/lint_baseline.json`` — legacy violations that
predate their rule) is compared against a fresh run of the AST linter
(``heat_tpu/analysis/ast_lint.py``); any violation not in the baseline
fails the gate with its rule ID and ``file:line``, so new code cannot
re-introduce a class of bug the rules exist to prevent.  Violations
*fixed* since the baseline are reported as stale entries (the gate still
passes — run with ``--update`` to shrink the baseline).

    python scripts/lint_gate.py [--baseline scripts/lint_baseline.json]
                                [--paths heat_tpu/] [--update] [--fix-stale]

``--update`` rewrites the baseline to the CURRENT violation set
(accepting new violations — a deliberate act); ``--fix-stale`` only
PRUNES entries whose violation has been fixed, so the baseline
monotonically shrinks toward empty without ever accepting anything new.

Exit status: 0 = no new violations, 1 = new violations (printed).
"""

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_BASELINE = os.path.join(REPO, "scripts", "lint_baseline.json")


def _write_baseline(baseline_path, entries):
    with open(baseline_path, "w") as f:
        json.dump(
            {
                "comment": "accepted legacy lint violations; regenerate "
                           "with: python scripts/lint_gate.py --update",
                "violations": entries,
            },
            f, indent=1,
        )
        f.write("\n")


def run_gate(paths=None, baseline_path=DEFAULT_BASELINE, update=False,
             fix_stale=False, quiet=False):
    """Run the linter and compare to the baseline; returns a result dict
    (``new``/``fixed``/``total``/``baseline``) for embedding in CI
    summaries (``perf_ci.py`` reports it next to the perf metrics).

    ``update`` rewrites the baseline to the full current set (accepts
    new violations); ``fix_stale`` only prunes entries whose violation
    no longer exists — the baseline can shrink, never grow."""
    from heat_tpu.analysis.ast_lint import lint_paths, violations_to_json

    paths = paths or [os.path.join(REPO, "heat_tpu")]
    violations = lint_paths(paths, repo_root=REPO)

    baseline = []
    if os.path.exists(baseline_path):
        with open(baseline_path) as f:
            doc = json.load(f)
        baseline = doc["violations"] if isinstance(doc, dict) else doc
    baseline_keys = {(e["rule"], e["file"], e["line"]) for e in baseline}
    current_keys = {v.key() for v in violations}

    new = [v for v in violations if v.key() not in baseline_keys]
    fixed = sorted(k for k in baseline_keys if k not in current_keys)

    if update:
        _write_baseline(baseline_path, violations_to_json(violations))
        if not quiet:
            print(f"baseline updated: {len(violations)} accepted violation(s)")
    elif fix_stale and fixed:
        kept = [
            e for e in baseline
            if (e["rule"], e["file"], e["line"]) in current_keys
        ]
        _write_baseline(baseline_path, kept)
        if not quiet:
            print(
                f"baseline pruned: {len(fixed)} fixed entr"
                f"{'y' if len(fixed) == 1 else 'ies'} removed, "
                f"{len(kept)} kept"
            )

    return {
        "total": len(violations),
        "baseline": len(baseline),
        "new": violations_to_json(new),
        "new_count": len(new),
        "fixed": [{"rule": r, "file": f_, "line": l} for r, f_, l in fixed],
        "fixed_count": len(fixed),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--baseline", default=DEFAULT_BASELINE)
    ap.add_argument("--paths", nargs="*", default=None)
    ap.add_argument("--update", action="store_true",
                    help="rewrite the baseline to the current violation set")
    ap.add_argument("--fix-stale", action="store_true",
                    help="prune baseline entries whose violation has been "
                         "fixed (the baseline shrinks; nothing new is "
                         "accepted)")
    args = ap.parse_args()

    res = run_gate(paths=args.paths, baseline_path=args.baseline,
                   update=args.update, fix_stale=args.fix_stale)

    for e in res["fixed"]:
        print(f"stale baseline entry (fixed): {e['file']}:{e['line']} {e['rule']}")
    if args.update:
        # the freshly written baseline covers the current set by definition
        sys.exit(0)
    if res["new"]:
        print("\nLINT GATE FAILED — new violation(s):")
        for e in res["new"]:
            print(f"  - {e['file']}:{e['line']}: {e['rule']} {e['message']}")
        sys.exit(1)
    print(
        f"lint gate passed: {res['total']} violation(s), all accepted by "
        f"baseline ({res['fixed_count']} stale baseline entr{'y' if res['fixed_count'] == 1 else 'ies'})"
    )
    sys.exit(0)


if __name__ == "__main__":
    main()
