"""Input/output validation helpers, analog of heat/core/sanitation.py.

Much of the reference file deals with redistributing operands to matching
ragged lshape maps (sanitize_distribution, sanitation.py:32-158); under the
canonical pad-and-mask distribution two arrays with equal (gshape, split,
comm) are automatically co-located, so sanitize_distribution reduces to a
resplit.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from . import types
from .dndarray import DNDarray

__all__ = [
    "sanitize_distribution",
    "sanitize_in",
    "sanitize_in_nd_realfloating",
    "sanitize_in_tensor",
    "sanitize_infinity",
    "sanitize_lshape",
    "sanitize_out",
    "sanitize_sequence",
    "scalar_to_1d",
    "store_out",
]


def sanitize_infinity(x):
    """Largest representable value of the input's dtype (sanitation.py:177)."""
    import jax.numpy as jnp

    dtype = x.larray.dtype if hasattr(x, "larray") else jnp.asarray(x).dtype
    try:
        return jnp.finfo(dtype).max
    except ValueError:
        return jnp.iinfo(dtype).max


def sanitize_sequence(seq):
    """Validate a list/tuple sequence, returning a list (sanitation.py:314)."""
    if isinstance(seq, list):
        return seq
    if isinstance(seq, tuple):
        return list(seq)
    raise TypeError(f"seq must be a list or a tuple, got {type(seq)}")


def sanitize_distribution(*args: DNDarray, target: DNDarray, diff_map=None) -> Union[DNDarray, Tuple[DNDarray, ...]]:
    """Distribute all ``args`` like ``target`` (sanitation.py:32).

    Canonical distribution means matching (split, comm) suffices.
    """
    out = []
    for a in args:
        sanitize_in(a)
        if a.split != target.split and a.shape == target.shape:
            a = a.resplit(target.split)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def sanitize_in(x) -> None:
    """Assert DNDarray input (sanitation.py:159)."""
    if not isinstance(x, DNDarray):
        raise TypeError(f"input needs to be a DNDarray, but was {type(x)}")


def sanitize_in_nd_realfloating(x, name: str, allowed_dims: Sequence[int]) -> None:
    """Check dimensionality + real floating dtype (used by linalg)."""
    sanitize_in(x)
    if x.ndim not in allowed_dims:
        raise ValueError(f"{name} must be {allowed_dims}-dimensional, but is {x.ndim}-dimensional")
    if not types.heat_type_is_realfloating(x.dtype):
        raise TypeError(f"{name} must be real floating, got {x.dtype.__name__}")


def sanitize_in_tensor(x) -> None:
    """Assert raw jax array input (sanitation.py:195)."""
    import jax

    if not isinstance(x, (jax.Array, jnp.ndarray)):
        raise TypeError(f"input needs to be a jax array, but was {type(x)}")


def sanitize_lshape(array: DNDarray, tensor) -> None:
    """Check a local tensor fits the array's chunk (sanitation.py:213)."""
    tshape = tuple(tensor.shape)
    if tshape != array.lshape:
        raise ValueError(f"local tensor must have shape {array.lshape}, got {tshape}")


def sanitize_out(
    out: DNDarray,
    output_shape: Tuple[int, ...],
    output_split: Optional[int],
    output_device,
    output_comm=None,
) -> None:
    """Validate an ``out=`` buffer (sanitation.py:255)."""
    if not isinstance(out, DNDarray):
        raise TypeError(f"expected out to be None or a DNDarray, but was {type(out)}")
    if tuple(out.shape) != tuple(output_shape):
        raise ValueError(f"Expecting output buffer of shape {tuple(output_shape)}, got {tuple(out.shape)}")


def store_out(res: DNDarray, out: DNDarray) -> DNDarray:
    """Validate ``out`` and write ``res``'s values into it (dtype-cast),
    the shared tail of every ``out=`` path in the op wrappers.

    When the layouts line up (same split, same padded shape, non-complex)
    the store is ONE cached executable through :mod:`.dispatch`: any
    pending elementwise chain behind ``res``, plus the cast, compile
    together, and ``out``'s dead backing buffer is donated so XLA can
    reuse its allocation.  Otherwise it falls back to the generic
    dense-slice + re-pad path."""
    sanitize_out(out, res.shape, res.split, res.device)
    from . import dispatch

    jdt = out.dtype.jax_type()
    if (
        res.split == out.split
        and res._planar is None
        and out._planar is None
        and not jnp.issubdtype(jdt, jnp.complexfloating)
        and not types.heat_type_is_complexfloating(res.dtype)
        and res._padded_shape == out._padded_shape
    ):
        out._replace(
            dispatch.cast_store(
                out._donation_source(), res._fusion_source, jdt,
                out.comm.sharding(out.split),
            )
        )
        return out
    casted = res._dense().astype(jdt)
    out._replace(DNDarray.from_dense(casted, out.split, out.device, out.comm).larray_padded)
    return out


def scalar_to_1d(x: DNDarray) -> DNDarray:
    """Promote a 0-d DNDarray to 1-d (sanitation.py:338)."""
    if x.ndim != 0:
        return x
    return DNDarray.from_dense(x._dense().reshape(1), None, x.device, x.comm)
