"""Persistent on-disk AOT executable cache: cold-start elimination.

A fresh serving replica pays one XLA compile per executable-cache key
before its first request can ride a warm cache — tens of milliseconds
to seconds per bucket shape, multiplied by every (model, bucket)
combination a pre-warm pass touches.  This module makes that cost a
**one-time fleet cost** instead of a per-replica cost: compiled
executables are serialized (``jax.experimental.serialize_executable``)
into a directory keyed by the *same* operand-spec cache keys
``core/dispatch.py`` already uses, so a fresh process re-loads the
compiled artifact instead of re-compiling it.

Design points:

* **Same keys as the in-memory cache** — :func:`stable_key` renders a
  dispatch cache key (op callables, operand shape/dtype/sharding specs,
  static kwargs) into a deterministic string; the artifact filename is
  its SHA-256.  Keys containing callables without a stable qualified
  name (lambdas, locals, partials) are refused — two distinct lambdas
  both stringify as ``<lambda>`` and must never alias one persistent
  artifact.
* **Atomic + checksummed like every other writer** — artifacts go
  through :func:`~heat_tpu.resilience.atomic.atomic_write` (temp file,
  fsync, CRC32 sidecar, rename), and every load verifies the sidecar
  first: a torn or corrupted artifact is *dropped* and the caller falls
  back to a fresh compile — corruption can cost a compile, never a
  wrong program.
* **Fingerprint invalidation** — every artifact records the writing
  process's :func:`fingerprint` (jax/jaxlib version, backend, device
  kind and count, framework version).  A mismatching artifact is
  ignored (``aot.stale``): an upgraded jax or a different mesh size
  recompiles instead of loading an incompatible executable.
* **Fail-open everywhere** — any serialization/deserialization error is
  counted (``aot.errors``) and the dispatch path continues exactly as
  if the cache were cold.  The cache can accelerate a replica; it can
  never take one down.

Off by default.  Arm with ``HEAT_TPU_AOT_CACHE=<dir>`` (or
:func:`configure`); ``HEAT_TPU_AOT_SAVE=0`` makes an armed cache
read-only (replicas load the fleet's artifacts but only a designated
writer populates them).  The pre-warm *manifest* — which (model,
bucket) shapes to drive at startup so the cache is exercised before
the first request — is the serving layer's side
(:meth:`heat_tpu.serving.InferenceService.export_prewarm_manifest`);
see ``docs/fleet.md`` for the lifecycle.

Security note: artifacts embed pickled executable payloads; the cache
directory must be trusted (same bar as the model checkpoint store —
see SECURITY.md).
"""

from __future__ import annotations

import hashlib
import io
import os
import pickle
import time
from typing import Any, Optional, Tuple

from ..analysis import tsan as _tsan
from ..resilience.atomic import atomic_write, verify_checksum
from ..resilience.errors import ChecksumError
from ..resilience.faults import inject as _inject
from ..telemetry import metrics as _tm
from . import _env

__all__ = [
    "configure",
    "enabled",
    "fingerprint",
    "load",
    "save",
    "stable_key",
    "stats",
]

#: artifact format version — bumped on any layout change so old caches
#: read as stale instead of unpicklable
FORMAT_VERSION = 1

ARTIFACT_SUFFIX = ".aotx"

_HITS_C = _tm.counter("aot.hits", "dispatch keys loaded from the on-disk AOT cache")
_MISSES_C = _tm.counter("aot.misses", "armed AOT lookups that found no artifact")
_SAVES_C = _tm.counter("aot.saves", "compiled executables serialized to the AOT cache")
_STALE_C = _tm.counter(
    "aot.stale", "artifacts ignored for a jax/device fingerprint mismatch"
)
_ERRORS_C = _tm.counter(
    "aot.errors", "AOT artifacts dropped (corrupt, unpicklable, undeserializable)"
)
_UNKEYED_C = _tm.counter(
    "aot.unkeyed", "dispatch keys refused a stable persistent form (lambda/local ops)"
)

#: guards the module configuration (directory/save flag/fingerprint
#: memo): configure() runs on the main thread but lookups fire from any
#: thread that dispatches (batcher threads, HTTP handlers)
_LOCK = _tsan.register_lock("dispatch.aot")
_DIR: Optional[str] = None
_SAVE = True
_ENV_READ = False
_FP: Optional[str] = None


def fingerprint() -> str:
    """Compatibility fingerprint of this process's compile substrate:
    jax/jaxlib versions, backend, device kind and count, framework
    version.  An artifact written under a different fingerprint is
    never loaded."""
    global _FP
    with _LOCK:
        _tsan.note_access("dispatch.aot.state")
        if _FP is not None:
            return _FP
    import jax

    try:
        import jaxlib

        jaxlib_v = getattr(jaxlib, "__version__", "?")
    except Exception:  # lint: allow H501(jaxlib version is advisory; jax version still pins)
        jaxlib_v = "?"
    try:
        devs = jax.devices()
        backend = jax.default_backend()
        kind = devs[0].device_kind if devs else "?"
        count = len(devs)
    except Exception:  # lint: allow H501(no backend -> fingerprint still formed, never matches a real artifact)
        backend, kind, count = "?", "?", 0
    from .. import version

    fp = (
        f"jax={jax.__version__};jaxlib={jaxlib_v};backend={backend};"
        f"device={kind};n={count};heat={version.__version__};fmt={FORMAT_VERSION}"
    )
    with _LOCK:
        _tsan.note_access("dispatch.aot.state")
        _FP = fp
        return _FP


def configure(directory: Optional[str], save: Optional[bool] = None) -> Optional[str]:
    """Arm (or, with ``None``, disarm) the AOT cache at ``directory``;
    returns the previously configured directory.  ``save=False`` makes
    the cache read-only for this process."""
    global _DIR, _SAVE, _ENV_READ
    if directory is not None:
        directory = os.fspath(directory)
        os.makedirs(directory, exist_ok=True)
    with _LOCK:
        _tsan.note_access("dispatch.aot.state")
        prev, _DIR = _DIR, directory
        if save is not None:
            _SAVE = bool(save)
        _ENV_READ = True
    return prev


def _config() -> Tuple[Optional[str], bool]:
    """(directory, save) — reading ``HEAT_TPU_AOT_CACHE`` /
    ``HEAT_TPU_AOT_SAVE`` on first use so a replica can arm the cache
    from its environment without any code change."""
    global _DIR, _SAVE, _ENV_READ
    with _LOCK:
        _tsan.note_access("dispatch.aot.state")
        if not _ENV_READ:
            _ENV_READ = True
            d = _env.env_str("HEAT_TPU_AOT_CACHE")
            if d:
                _DIR = d
                try:
                    os.makedirs(d, exist_ok=True)
                except OSError:
                    _DIR = None  # unwritable dir: stay disarmed
            _SAVE = _env.env_flag("HEAT_TPU_AOT_SAVE")
        return _DIR, _SAVE


def enabled() -> bool:
    """Whether the on-disk AOT cache is armed for this process."""
    return _config()[0] is not None


def save_enabled() -> bool:
    """Whether this process may write artifacts (armed and not
    read-only)."""
    d, s = _config()
    return d is not None and s


# ----------------------------------------------------------------------
# stable key rendering
# ----------------------------------------------------------------------
def _stable_part(obj: Any, depth: int = 0) -> str:
    """Deterministic cross-process string form of one key element, or
    raise ``ValueError`` when none exists (anonymous callables)."""
    if depth > 8:
        raise ValueError("key nesting too deep for a stable form")
    if callable(obj) and not isinstance(obj, type):
        mod = getattr(obj, "__module__", None)
        # jnp ufunc objects carry __name__ but no __qualname__
        qual = getattr(obj, "__qualname__", None) or getattr(obj, "__name__", None)
        if not mod or not qual or "<lambda>" in qual or "<locals>" in qual:
            raise ValueError(f"no stable name for callable {obj!r}")
        return f"fn:{mod}.{qual}"
    if isinstance(obj, (tuple, list, frozenset)):
        items = sorted(obj) if isinstance(obj, frozenset) else obj
        inner = ",".join(_stable_part(o, depth + 1) for o in items)
        return f"{type(obj).__name__}({inner})"
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return repr(obj)
    # dtypes, shardings, jnp scalar types: their str/repr is stable for
    # a fixed jax version + topology, both of which the fingerprint pins
    return f"{type(obj).__name__}:{obj}"


def stable_key(key: Any) -> Optional[str]:
    """Deterministic string form of a dispatch cache key, or ``None``
    when the key has no stable cross-process identity (anonymous
    callables)."""
    try:
        return _stable_part(key)
    except Exception as e:  # lint: allow H501(unstable key -> skip persistence, in-memory path unaffected)
        if isinstance(e, ValueError):
            _UNKEYED_C.inc()
        return None


def _artifact_path(directory: str, key_str: str) -> str:
    digest = hashlib.sha256(key_str.encode("utf-8")).hexdigest()
    return os.path.join(directory, digest + ARTIFACT_SUFFIX)


# ----------------------------------------------------------------------
# load / save
# ----------------------------------------------------------------------
def load(key: Any) -> Optional[Any]:
    """The deserialized compiled executable for ``key``, or ``None`` on
    any miss (disarmed, unstable key, absent, corrupt, stale
    fingerprint, undeserializable).  A corrupt artifact is removed so
    the next save can heal it."""
    directory, _ = _config()
    if directory is None:
        return None
    key_str = stable_key(key)
    if key_str is None:
        return None
    path = _artifact_path(directory, key_str)
    if not os.path.exists(path):
        _MISSES_C.inc()
        return None
    _inject("aot.load", path=path)
    try:
        verify_checksum(path)
        with open(path, "rb") as f:
            doc = pickle.load(f)
        if not isinstance(doc, dict) or doc.get("format") != FORMAT_VERSION:
            _STALE_C.inc()
            return None
        if doc.get("fingerprint") != fingerprint():
            _STALE_C.inc()
            return None
        if doc.get("key") != key_str:
            # SHA collision or foreign file: never run a mismatched program
            _ERRORS_C.inc()
            return None
        from jax.experimental.serialize_executable import deserialize_and_load

        compiled = deserialize_and_load(
            doc["payload"], doc["in_tree"], doc["out_tree"]
        )
    except ChecksumError:
        _ERRORS_C.inc()
        _drop(path)
        return None
    except Exception:  # lint: allow H501(an unreadable artifact must cost a compile, never an error)
        _ERRORS_C.inc()
        _drop(path)
        return None
    _HITS_C.inc()
    return compiled


def save(key: Any, compiled: Any) -> bool:
    """Serialize ``compiled`` (a jax ``Compiled``) under ``key``;
    returns True when an artifact was written.  Never raises: a failed
    save is counted and the in-memory entry keeps serving."""
    directory, do_save = _config()
    if directory is None or not do_save:
        return False
    key_str = stable_key(key)
    if key_str is None:
        return False
    try:
        from jax.experimental.serialize_executable import serialize

        payload, in_tree, out_tree = serialize(compiled)
        doc = {
            "format": FORMAT_VERSION,
            "fingerprint": fingerprint(),
            "key": key_str,
            "saved_at": time.time(),
            "payload": payload,
            "in_tree": in_tree,
            "out_tree": out_tree,
        }
        buf = io.BytesIO()
        pickle.dump(doc, buf, protocol=pickle.HIGHEST_PROTOCOL)
        path = _artifact_path(directory, key_str)
        _inject("aot.save", path=path)
        with atomic_write(path, fault_site="io.write") as tmp:
            with open(tmp, "wb") as f:
                f.write(buf.getvalue())
    except Exception:  # lint: allow H501(a failed artifact write must never fail the dispatch that compiled)
        _ERRORS_C.inc()
        return False
    _SAVES_C.inc()
    return True


def _drop(path: str) -> None:
    for p in (path, path + ".crc32"):
        try:
            os.remove(p)
        except OSError:
            pass


def stats() -> dict:
    """Snapshot of the AOT-cache counters plus the armed directory — a
    thin view over the shared telemetry registry (``aot.*``)."""
    directory, do_save = _config()
    return {
        "directory": directory,
        "save": do_save,
        "hits": _HITS_C.value,
        "misses": _MISSES_C.value,
        "saves": _SAVES_C.value,
        "stale": _STALE_C.value,
        "errors": _ERRORS_C.value,
        "unkeyed": _UNKEYED_C.value,
    }
