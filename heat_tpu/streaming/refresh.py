"""Drift-triggered live model refresh (docs/streaming.md).

The last leg of the continuous-learning loop: when the drift monitor
fires ``drift:<model>`` (live traffic no longer matches the model's
training distribution), the refresh driver re-fits from the recent
stream, saves the result as the next model version **carrying a fresh
input baseline built from its own recent training window**, and loads
it as a canary (``activate=False``).  From there the PR 15 decision
plane takes over: shadow comparison runs under the live traffic, the
firing drift alert *vetoes* promotion (holds the verdict), and once the
re-warmed live sketch scores clean against the fresh baseline the alert
resolves, the held verdict re-evaluates, and the canary auto-promotes —
``promote`` re-attaches the same persisted baseline, so the alert stays
resolved instead of re-firing against the stale distribution.

Nothing here blocks serving: the fit/save/load work runs outside the
driver's lock, the canary loads hot, and promotion is the registry's
atomic pointer swap.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Tuple

from ..analysis import tsan as _tsan
from ..analysis.protocols import (
    ACTOR_ALERTS,
    ACTOR_REFRESH,
    ALERT_FIRE,
    REFRESH_TRIGGER,
)
from ..resilience.faults import inject
from ..telemetry import alerts as _alerts
from ..telemetry import journal as _journal
from ..telemetry import metrics as _tm
from ..telemetry.sketch import SKETCHES, ModelSketch, check_drift
from ..telemetry.spans import span as _span
from ..utils.checkpoint import Checkpointer

__all__ = ["RefreshDriver"]

_REFRESHES = _tm.counter("stream.refreshes")


class RefreshDriver:
    """Watches ``drift:<model>`` and answers it with a canary refresh.

    ``fitter`` is the caller's re-fit recipe: a zero-argument callable
    returning either a fitted streaming estimator (anything with
    ``to_estimator()`` and ``recent_window_`` — the online estimators)
    or an explicit ``(servable_estimator, recent_rows)`` pair.  The
    driver never owns the stream: the fitter decides what "recent"
    means (typically: resume the online fit to the head and hand back
    its last window).

    ``check()`` is the whole state machine and is safe to call from
    anywhere (the serving poll loop, a test, the built-in background
    thread started by :meth:`start`):

    * no firing drift alert -> ``"idle"``
    * a canary already resident, or inside the refresh cooldown
      (``HEAT_TPU_STREAM_REFRESH_MIN_S``) -> ``"pending"`` (the decision
      plane / clock owns the next transition)
    * otherwise -> re-fit, ``save_model(..., baseline=fresh)``, swap the
      live drift baseline to the fresh one, reset the live sketch (the
      alert resolves once re-warmed traffic scores clean), hot-load the
      canary -> ``"refreshed"``
    """

    def __init__(
        self,
        service,
        model: str,
        directory: str,
        fitter: Callable,
        min_interval_s: Optional[float] = None,
        comm=None,
    ):
        from ..core._env import env_float

        self.service = service
        self.model = str(model)
        self.directory = str(directory)
        self.fitter = fitter
        self.min_interval_s = float(
            min_interval_s if min_interval_s is not None
            else env_float("HEAT_TPU_STREAM_REFRESH_MIN_S", 0.0)
        )
        self.comm = comm
        self._lock = _tsan.register_lock("streaming.refresh")
        self._last_refresh_mono: Optional[float] = None
        self._in_flight = False
        self.last_version: Optional[int] = None
        self.refreshes = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- the state machine ----------------------------------------------
    def check(self) -> str:
        """One drift->refresh evaluation; returns what happened."""
        check_drift()  # refresh alert state from the live sketches first
        if not _alerts.is_firing(f"drift:{self.model}", labels={"model": self.model}):
            return "idle"
        now = time.monotonic()
        with self._lock:
            _tsan.note_access("streaming.refresh.state")
            if self._in_flight:
                return "pending"
            if self.service.registry.canary_version(self.model) is not None:
                return "pending"  # decision plane owns the next transition
            if (
                self._last_refresh_mono is not None
                and self.min_interval_s > 0
                and now - self._last_refresh_mono < self.min_interval_s
            ):
                return "pending"
            self._in_flight = True
        try:
            self._refresh()
        finally:
            with self._lock:
                _tsan.note_access("streaming.refresh.state")
                self._in_flight = False
                self._last_refresh_mono = time.monotonic()
        return "refreshed"

    def _next_version(self) -> int:
        saved = Checkpointer(self.directory).all_steps()
        reg = self.service.registry
        try:
            active = reg.active_version(self.model) or 0
        except KeyError:
            active = 0
        return max(max(saved, default=0), active, self.last_version or 0) + 1

    def _refresh(self) -> None:
        from ..serving.model_io import save_model

        with _span("stream.refresh", model=self.model) as sp:
            inject("stream.refresh", model=self.model)
            fitted = self.fitter()
            if isinstance(fitted, tuple):
                est, recent = fitted
            else:
                est = fitted.to_estimator(self.comm)
                recent = fitted.recent_window_
            if recent is None:
                raise ValueError(
                    "refresh fitter produced no recent window; the fresh "
                    "drift baseline must come from the refreshed model's "
                    "own training data"
                )
            # the fresh baseline: the refreshed model's OWN recent
            # training distribution, persisted with the version so a
            # later promote (or rollback) re-attaches exactly it
            sk = ModelSketch(self.model, recent.shape[1])
            sk.update(recent)
            fresh = sk.doc()
            version = self._next_version()
            save_model(est, self.directory, version=version,
                       name=self.model, baseline=fresh)
            # swap the live monitor onto the fresh distribution NOW (not
            # at promote): the firing alert resolves as soon as the
            # reset live sketch re-warms and scores clean, which is what
            # releases the decision plane's drift veto
            SKETCHES.set_baseline(self.model, fresh)
            SKETCHES.reset_live(self.model)
            self.service.load(
                self.model, self.directory, version=version, activate=False
            )
            with self._lock:
                _tsan.note_access("streaming.refresh.state")
                self.last_version = version
                self.refreshes += 1
            _REFRESHES.inc()
            sp.attrs.update(version=version)
            # causal link back to the drift page that triggered this
            # refresh (journal after our lock is released)
            cause = None
            for e in reversed(_journal.journal_events()):
                if (
                    e.get("actor") == ACTOR_ALERTS
                    and e.get("action") == ALERT_FIRE
                    and str(e.get("evidence", {}).get("alert", ""))
                    .startswith(f"drift:{self.model}")
                ):
                    cause = e["event_id"]
                    break
            _journal.emit(
                ACTOR_REFRESH, REFRESH_TRIGGER,
                model=self.model,
                severity="info",
                message=(
                    f"drift-triggered refresh fitted v{version} of "
                    f"{self.model} and staged it as canary"
                ),
                cause=cause,
                evidence={"version": version, "rows": int(recent.shape[0]),
                          "refreshes": self.refreshes},
            )

    # -- optional background poller -------------------------------------
    def start(self, poll_s: float = 1.0) -> "RefreshDriver":
        """Run :meth:`check` every ``poll_s`` seconds on a daemon thread
        until :meth:`close`."""
        if self._thread is not None:
            return self
        self._stop.clear()

        def _loop() -> None:
            while not self._stop.wait(poll_s):
                try:
                    self.check()
                except Exception:  # lint: allow H501(poller survives a failed refresh; next tick retries)
                    pass

        self._thread = threading.Thread(
            target=_loop, name=f"refresh-{self.model}", daemon=True
        )
        self._thread.start()
        return self

    def close(self) -> None:
        """Stop the background poller (if running).  Idempotent."""
        t, self._thread = self._thread, None
        self._stop.set()
        if t is not None:
            t.join(timeout=10.0)

    def __enter__(self) -> "RefreshDriver":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
