"""Distributed-sparse + redistribution continuous benchmarks (r4).

The reference's cb suite has no sparse workloads (its sparse layer has no
distributed compute); these track the r4 sharded-planes programs so nnz
scaling regressions surface on the dashboard like everything else.
"""

# flake8: noqa
import numpy as np

import heat_tpu as ht
from monitor import monitor


@monitor()
def sparse_spmm(smat, dense):
    return smat @ dense


@monitor()
def sparse_add(a, b):
    return a + b


@monitor()
def sparse_csc_contract(cmat, dense):
    return cmat @ dense


@monitor()
def ragged_redistribute(array, target):
    array.redistribute_(target_map=target)
    # materialize the physically-placed ragged buffer (it is lazy: without
    # a consumer the call is metadata-only and the bench would time a no-op)
    _, placed = array._ragged_layout
    array.balance_()
    return placed


def run_sparse_benchmarks(scale: float = 1.0):
    import scipy.sparse as sp

    n = max(int(100_000 * scale), 1024)
    m = max(int(20_000 * scale), 256)
    a_np = sp.random(n, m, density=0.001, random_state=0, format="csr", dtype=np.float32)
    b_np = sp.random(n, m, density=0.001, random_state=1, format="csr", dtype=np.float32)
    smat = ht.sparse.sparse_csr_matrix(a_np, split=0)
    bmat = ht.sparse.sparse_csr_matrix(b_np, split=0)
    dense = ht.random.randn(m, 32, split=0).astype(ht.float32)

    sparse_spmm(smat, dense)
    sparse_add(smat, bmat)

    cmat = ht.sparse.sparse_csc_matrix(a_np.tocsc(), split=1)
    sparse_csc_contract(cmat, dense)

    size = ht.get_comm().size
    if size > 1:
        rows = max(int(1_000_000 * scale), 4 * size)
        arr = ht.random.randn(rows, split=0).astype(ht.float32)
        target = np.zeros((size, 1), np.int64)
        # skewed layout: the first half of the ranks takes two thirds of
        # the rows; the last rank absorbs the remainder
        per_lo = (rows * 2 // 3) // (size // 2)
        target[: size // 2, 0] = per_lo
        target[-1, 0] = rows - int(target[:, 0].sum())
        ragged_redistribute(arr, target)
