"""Regression estimators (analog of heat/regression)."""

from .lasso import Lasso

__all__ = ["Lasso"]
