"""Benchmark driver: the full BASELINE grid on the attached chip.

Emits one JSON line per BASELINE config (smoke, KMeans, hSVD north star,
DP-SGD, 3-D FFT, dispatch-amortization, resilience counters, overlap-layer
stall/prefetch/bucket metrics, telemetry self-cost), then a final summary
line whose top-level fields are the
hSVD north star (so single-metric consumers keep working) with the whole
grid attached under ``"all"`` — BENCH_r{N}.json then records every config
each round and rounds stay comparable (BASELINE.md targets table).  Every
config record embeds the telemetry registry snapshot at its end
(``"telemetry"`` key, docs/observability.md).

Timing methodology (tunneled-chip aware): every measurement enqueues
``n_iter`` programs and fetches one scalar at the end — the device
executes in order, so one fetch bounds all iterations and the link
round-trip floor is amortized instead of being subtracted per call
(block_until_ready does not synchronize through the tunnel; RTT variance
can exceed an iteration's compute).

``vs_baseline`` for each config divides by the reference's per-process
compute path measured in-process: torch CPU doing the equivalent local
computation (the reference's per-rank torch kernels), on a subset where
the full size would be unreasonable on one CPU.  Every record carries
``vs_baseline_kind`` naming that baseline explicitly — the ratios are NOT
against BASELINE.json's "5x A100+MPI" north star (no A100-class baseline
exists in this repo).  A window that never clears the link-sync floor
raises :class:`MeasurementError` and is recorded as an error instead of a
number (the r2 DP-SGD 1e9 steps/s incident).

Roofline + dispersion (VERDICT r3 #2): the run opens with measured chip
anchors — peak f32/bf16 matmul GFLOP/s and streamed HBM GB/s — and every
record carries ``pct_of_peak_f32`` / ``pct_of_bw_*`` against them plus a
``timing`` block (windows, n_iter, per-window times, median/min spread),
so each number self-describes both its absolute quality and its noise.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import numpy as np


def _sync_floor() -> float:
    f = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(f(z))
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(z))
        best = min(best, time.perf_counter() - t0)
    return best


class MeasurementError(RuntimeError):
    """The timing window never rose above the link-sync floor — there is
    no measurement to report (publishing a clamp bound as throughput is
    exactly the r2 DP-SGD failure this type exists to prevent)."""


def _time_amortized(
    run_once,
    fetch_scalar,
    n_iter: int,
    sync_floor: float,
    windows: int = 3,
    min_floor_ratio: float = 50.0,
    max_iter: int = 4096,
):
    """(seconds per iteration, timing metadata): enqueue n_iter runs, one
    trailing fetch.

    Repeats the whole window ``windows`` times and keeps the best — the
    tunnel link's RTT variance between runs can exceed an iteration's
    compute, and the minimum is the standard noise-robust estimator.  The
    metadata carries every window's per-iteration time plus the
    median/min spread, so a published number self-describes its quality
    (VERDICT r3 weak #1: regression vs noise must be decidable from the
    artifacts alone).

    The window must dominate the sync floor: if ``elapsed`` is not at
    least ``min_floor_ratio`` floors, ``n_iter`` grows (x4) and the
    window re-runs, so the reported per-iteration time is a measurement
    rather than link noise.  If even ``max_iter`` iterations cannot clear
    the floor, raises :class:`MeasurementError` — the caller records an
    explicit error instead of a fabricated number."""
    def one_window():
        t0 = time.perf_counter()
        out = None
        for _ in range(n_iter):
            out = run_once()
        fetch_scalar(out)
        return time.perf_counter() - t0

    while True:
        # single probe window decides whether this n_iter clears the
        # floor; only a passing size pays for the full window set (on a
        # slow-link session the growth ladder otherwise multiplies the
        # whole bench by ~3x)
        probe = one_window()
        probe_window = max(probe - sync_floor, 0.0)
        under = probe_window < min_floor_ratio * sync_floor
        if under and n_iter < max_iter:
            n_iter = min(n_iter * 4, max_iter)
            continue
        # the passing probe is a regular window: seed the sample set with
        # it so the common no-growth case pays exactly `windows` windows
        samples = []
        if probe > sync_floor:
            samples.append(probe_window / n_iter)
        attempts = 0
        while len(samples) < windows and attempts < 3 * windows:
            attempts += 1
            elapsed = one_window()
            if elapsed > sync_floor:
                samples.append((elapsed - sync_floor) / n_iter)
            # a window at/below the sync floor is a link hiccup: skip it
            # and keep measuring (bounded retries — a dead link must not
            # loop forever, and an underfull sample set fails the floor
            # checks below rather than publishing a 1-window "spread")
        best = min(samples) if samples else float("inf")
        window = best * n_iter
        ok = samples and window >= min_floor_ratio * sync_floor
        capped_ok = n_iter >= max_iter and samples and window > 2.0 * sync_floor
        if ok or capped_ok:
            med = float(np.median(samples))
            meta = {
                "windows": len(samples),
                "n_iter": n_iter,
                "window_s": round(window, 4),
                "per_iter_s": [round(s, 6) for s in samples],
                "median_per_iter_s": round(med, 6),
                "spread_pct": round(100.0 * (med - best) / best, 1) if best else 0.0,
                "sync_floor_s": round(sync_floor, 4),
            }
            return best, meta
        if n_iter >= max_iter:
            raise MeasurementError(
                f"window of {n_iter} iterations ({window:.4f}s) never cleared "
                f"{min_floor_ratio}x the sync floor ({sync_floor:.4f}s)"
            )
        n_iter = min(n_iter * 4, max_iter)


#: every ``vs_baseline`` below divides by this baseline — label it so the
#: ratios cannot be misread as the BASELINE.json "5x A100+MPI" north star
#: (no A100-class measurement exists in this repo)
BASELINE_KIND = "torch_cpu_single_process_subset"


# ---------------------------------------------------------------- roofline


def bench_roofline(ht, sync_floor):
    """Chip roofline anchors, measured once per bench run (VERDICT r3 #2):
    peak matmul FLOP/s (f32 and bf16-input/f32-accumulate — the MXU
    paths) and streamed HBM bandwidth (read+write elementwise kernel).
    Every other record divides by these so "is X GFLOP/s good?" is
    answerable from the artifact alone."""
    n = 4096
    key = jax.random.PRNGKey(0)
    a = jax.random.normal(key, (n, n), jnp.float32)
    b = jax.random.normal(jax.random.PRNGKey(1), (n, n), jnp.float32)
    mm = jax.jit(lambda x, y: x @ y)  # DEFAULT policy: bf16 passes on TPU
    float(mm(a, b)[0, 0])
    per, meta_f32 = _time_amortized(lambda: mm(a, b), lambda o: float(o[0, 0]), 5, sync_floor)
    peak_f32 = 2.0 * n**3 / per / 1e9

    mmh = jax.jit(
        lambda x, y: jnp.matmul(x, y, precision=jax.lax.Precision.HIGHEST)
    )  # the 6-pass f32-accurate policy the linalg layer forces for f32
    float(mmh(a, b)[0, 0])
    per_h, meta_hi = _time_amortized(lambda: mmh(a, b), lambda o: float(o[0, 0]), 5, sync_floor)
    peak_f32_highest = 2.0 * n**3 / per_h / 1e9

    ab, bb = a.astype(jnp.bfloat16), b.astype(jnp.bfloat16)
    mmb = jax.jit(lambda x, y: jnp.matmul(x, y, preferred_element_type=jnp.float32))
    float(mmb(ab, bb)[0, 0])
    per_b, meta_bf16 = _time_amortized(lambda: mmb(ab, bb), lambda o: float(o[0, 0]), 5, sync_floor)
    peak_bf16 = 2.0 * n**3 / per_b / 1e9

    m = 1 << 27  # 512 MiB read + 512 MiB write in f32
    x = jax.random.normal(jax.random.PRNGKey(2), (m,), jnp.float32)
    stream = jax.jit(lambda v: v * 1.000001 + 0.5)
    float(stream(x)[0])
    per_s, meta_bw = _time_amortized(lambda: stream(x), lambda o: float(o[0]), 5, sync_floor)
    bw = 2.0 * 4.0 * m / per_s / 1e9

    # per-program dispatch floor: enqueued trivial programs do NOT overlap
    # through the tunnel, so this serial cost is the latency regime's
    # roofline — tiny-step metrics (dpsgd) anchor against it, not against
    # matmul peak (VERDICT r4 weak #8)
    f0 = jax.jit(lambda v: v + 1.0)
    z0 = jnp.zeros(())
    float(f0(z0))
    per_d, meta_disp = _time_amortized(lambda: f0(z0), lambda o: float(o), 256, sync_floor)

    return {
        "metric": "roofline",
        "value": round(peak_f32, 1),
        "unit": "GFLOP/s_f32_peak",
        "vs_baseline": 1.0,
        "vs_baseline_kind": "self",
        "peak_f32_matmul_gflops": round(peak_f32, 1),
        "peak_f32_highest_matmul_gflops": round(peak_f32_highest, 1),
        "peak_bf16_matmul_gflops": round(peak_bf16, 1),
        "hbm_stream_gbytes_per_s": round(bw, 1),
        "dispatch_floor_ms": round(per_d * 1e3, 4),
        "timing": {
            "f32": meta_f32, "f32_highest": meta_hi, "bf16": meta_bf16,
            "stream": meta_bw, "dispatch": meta_disp,
        },
    }


# ---------------------------------------------------------------- configs


def bench_smoke(ht, sync_floor, roofline=None):
    """Config 1: factory smoke — ht.arange on the mesh, ms per call."""
    n_iter = 20
    per, meta = _time_amortized(
        lambda: ht.arange(10, split=0),
        lambda a: float(a.sum()),
        n_iter,
        sync_floor,
    )
    return {
        "metric": "smoke_arange10_ms",
        "value": round(per * 1e3, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "vs_baseline_kind": "self",
        "timing": meta,
    }


def bench_kmeans(ht, sync_floor, roofline=None):
    """Config 2: KMeans throughput, points/s through the Lloyd loop.

    Carries 5 windows of dispersion metadata (VERDICT r3 weak #1: the
    r2->r3 4.26->1.84 Gpts/s swing was undecidable): the Lloyd code was
    unchanged between those rounds (git diff 876c1a7..4d9a94a touches
    only a property refactor), and the r2 harness subtracted the link
    sync floor from a 2-fit window without requiring floor dominance —
    a systematic inflation.  From r4 on, the window list in ``timing``
    settles regression-vs-noise questions directly.

    Honest scale (ISSUE 16): on an accelerator the point set fills HBM —
    2^27 x 16 f32 = 8 GiB, the reference's config-2 regime (the former
    2^22 probe measured 1/250th of it) — while CPU smoke sessions keep
    the 2^22 size so the grid stays runnable; the metric name carries
    the size, so the two regimes never mix in one trend series."""
    big = jax.default_backend() == "tpu"
    log_n = 27 if big else 22
    n, f, k = 1 << log_n, 16, 8
    ht.random.seed(1)
    x = ht.random.randn(n, f, split=0)
    x = x.astype(ht.float32)
    float(x.sum())

    def make_fit(iters):
        def fit():
            km = ht.cluster.KMeans(
                n_clusters=k, init="random", max_iter=iters, tol=-1.0, random_state=0
            )
            km.fit(x)
            return km

        return fit

    # convergence loop (VERDICT r4 #3): the fit window must dwarf the
    # dispatch floor AND the window spread must settle under 10% before
    # the number is publishable — r4's 40.5% / 143% same-round spreads
    # could not detect a 2x regression.  Lloyd iterations per fit grow
    # until both hold (rate is iteration-normalized, so the metric is
    # unchanged by the workload growth).
    iters = 100
    while True:
        fit = make_fit(iters)
        fit()  # compile this iteration count
        per, meta = _time_amortized(
            fit, lambda km: float(km.cluster_centers_.sum()), 1, sync_floor, windows=5
        )
        if meta["spread_pct"] < 10.0 or iters >= 800:
            break
        iters *= 2
    pts_per_s = n * iters / per

    # independent second measurement, INTERLEAVED with the first: eight
    # windows alternate between sample A and sample B, so a monotone
    # link-RTT drift (the tunnel's per-minute weather) degrades both
    # samples equally and the agreement flag tests PROGRAM
    # reproducibility — two sequential measurement blocks, the r5a
    # formulation, disagreed 7% on a 0.1%-spread metric purely because
    # the link shifted between the blocks.
    n_it = meta["n_iter"]
    wins_a, wins_b = [], []
    attempts = 0
    while (len(wins_a) < 4 or len(wins_b) < 4) and attempts < 16:
        attempts += 1
        t0 = time.perf_counter()
        out = None
        for _ in range(n_it):
            out = fit()
        float(out.cluster_centers_.sum())
        elapsed = time.perf_counter() - t0
        # every KEPT window must satisfy the same acceptance rule
        # _time_amortized enforces: 50x floor dominance, or — when the
        # first block itself passed via the capped path (n_iter at the
        # 4096 cap on a slow-link session) — the capped >2x bound; a
        # degenerate near-floor window would otherwise publish a wildly
        # inflated min (the r2 DP-SGD failure class), while demanding
        # 50x from a session that can only deliver 2x would burn all 16
        # attempts and guarantee an underfull repeat
        floor_ratio = 2.0 if n_it >= 4096 else 50.0
        if elapsed - sync_floor < floor_ratio * sync_floor:
            continue  # underfull / hiccup window, skip (bounded retries)
        (wins_a if attempts % 2 == 1 else wins_b).append(
            (elapsed - sync_floor) / n_it
        )
    all_wins = wins_a + wins_b
    underfull = not wins_a or not wins_b
    meta2 = {
        "windows_a": len(wins_a),
        "windows_b": len(wins_b),
        "interleaved": True,
        "underfull": underfull,
        "per_iter_s_a": [round(s, 6) for s in wins_a],
        "per_iter_s_b": [round(s, 6) for s in wins_b],
    }
    if underfull:
        # no second sample exists — a reproducibility claim must not
        # ship on the back of a fallback value (the first block's
        # number stands, flagged unconfirmed)
        agreement = False
        v2 = float("nan")
    else:
        v1, v2 = n * iters / min(wins_a), n * iters / min(wins_b)
        spread_ab = 100.0 * (float(np.median(all_wins)) - min(all_wins)) / min(all_wins)
        meta2["spread_pct"] = round(spread_ab, 1)
        # the tolerance absorbs BOTH samples' own dispersion (the old
        # sequential formulation used both blocks' spreads too)
        tol = max(meta["spread_pct"], spread_ab, 5.0) / 100.0
        agreement = abs(v1 - v2) <= tol * max(v1, v2)
        # publish from the interleaved windows so the shipped value is
        # the quantity the agreement flag actually covers (the first
        # block's role is the workload-convergence loop; a link drift
        # between it and the interleaved block must not ship an
        # unreproducible number)
        pts_per_s = n * iters / min(all_wins)

    # reference per-process path: torch CPU one Lloyd iteration (cdist+argmin
    # +scatter mean, cluster/kmeans.py torch kernels) on a subset
    import torch

    nb = 1 << 18
    xb = torch.randn(nb, f)
    cb = torch.randn(k, f)

    def lloyd_once():
        d = torch.cdist(xb, cb)
        lab = d.argmin(1)
        return torch.stack([xb[lab == i].mean(0) for i in range(k)])

    lloyd_once()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        c = lloyd_once()
        _ = c.sum().item()
        best = min(best, time.perf_counter() - t0)
    base_pts = nb / best
    rec = {
        "metric": f"kmeans_2^{log_n}x16_k8_pts_per_s",
        "value": round(pts_per_s / 1e9, 3),
        "unit": "Gpts/s",
        "vs_baseline": round(pts_per_s / base_pts, 2),
        "lloyd_iters_per_fit": iters,
        "repeat_value_gpts": None if underfull else round(v2 / 1e9, 3),
        "repeat_agreement": agreement,
        "timing": meta,
        "timing_repeat": meta2,
    }
    if roofline:
        # one Lloyd iteration reads the point set once (bandwidth bound:
        # n*f*4 bytes) and does ~2*n*k*f distance flops
        per_iter = per / iters
        rec["pct_of_bw_point_read_model"] = round(
            100.0 * (n * f * 4.0 / per_iter / 1e9) / roofline["hbm_stream_gbytes_per_s"], 1
        )
        rec["pct_of_peak_f32"] = round(
            100.0 * (2.0 * n * k * f / per_iter / 1e9) / roofline["peak_f32_matmul_gflops"], 1
        )
    return rec


def bench_hsvd(ht, sync_floor, roofline=None):
    """Config 3 (north star): hierarchical SVD GFLOP/s per chip.

    ``vs_baseline`` divides by a torch-CPU single-process subset (labeled
    below) — NOT the BASELINE.json "5x A100+MPI" target, for which no
    measurement exists in this repo; ``pct_of_peak_f32`` against the
    measured matmul roofline is the honest absolute yardstick
    (VERDICT r3 #9)."""
    n, f, rank = 1 << 22, 128, 10
    n_iter = 5
    ht.random.seed(0)
    x = ht.random.randn(n, f, split=0)
    float(x.sum())

    def factorize():
        u, s, v, err = ht.linalg.hsvd_rank(x, rank, compute_sv=True, safetyshift=5)
        return s

    float(factorize().sum())
    per, meta = _time_amortized(factorize, lambda s: float(s.sum()), n_iter, sync_floor)
    gflops = 2.0 * n * f * f / per / 1e9

    import torch

    n_b = 1 << 18
    xb = torch.randn(n_b, f)

    def tfact():
        u, s, v = torch.linalg.svd(xb, full_matrices=False)
        return u[:, :rank] * s[:rank]

    tfact()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        us = tfact()
        _ = us.sum().item()
        best = min(best, time.perf_counter() - t0)
    base = 2.0 * n_b * f * f / best / 1e9
    rec = {
        "metric": "hsvd_rank10_gflops_per_chip_2^22x128",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / base, 2),
        "timing": meta,
    }

    # Multi-level merge tree (ISSUE 16): its first measured number.  The
    # split=0 probe above runs p=1 — one truncated-Gram leaf, merge tree
    # never touched.  split=1 spreads the columns over the mesh, so the
    # factorization runs ``comm.size`` leaf blocks plus ceil(log) merge
    # levels; the A/B toggles HEAT_TPU_HSVD_BATCHED, which stacks the
    # equal-shape blocks of each level through ONE batched
    # gram+eigh+project instead of a sequential per-block loop
    # (numerically identical per block — svdtools._truncated_us_stacked).
    import os

    nm = 1 << 20
    xm = ht.random.randn(nm, f, split=1)
    float(xm.sum())

    def fact_tree():
        ut, st, vt, errt = ht.linalg.hsvd_rank(xm, rank, compute_sv=True, safetyshift=5)
        return st

    tree = {"leaves": int(xm.comm.size)}
    for label, flag in (("sequential", "0"), ("batched", "1")):
        os.environ["HEAT_TPU_HSVD_BATCHED"] = flag
        try:
            float(fact_tree().sum())  # retrace under the knob
            per_t, meta_t = _time_amortized(
                fact_tree, lambda st: float(st.sum()), n_iter, sync_floor
            )
        finally:
            os.environ.pop("HEAT_TPU_HSVD_BATCHED", None)
        tree[label] = {
            "gflops": round(2.0 * nm * f * f / per_t / 1e9, 1),
            "timing": meta_t,
        }
    seq_g = tree["sequential"]["gflops"]
    tree["batched_speedup"] = (
        round(tree["batched"]["gflops"] / seq_g, 3) if seq_g else None
    )
    rec["merge_tree_2^20x128_split1"] = tree
    if roofline:
        rec["pct_of_peak_f32"] = round(100.0 * gflops / roofline["peak_f32_matmul_gflops"], 1)
        # hsvd forces HIGHEST for f32 accuracy: the like-for-like ceiling
        rec["pct_of_peak_f32_highest"] = round(
            100.0 * gflops / roofline["peak_f32_highest_matmul_gflops"], 1
        )
    return rec


def bench_dpsgd(ht, sync_floor, roofline=None):
    """Config 4: data-parallel CNN training steps/s (examples/nn analog)."""
    import optax
    import flax.linen as lnn

    class CNN(lnn.Module):
        @lnn.compact
        def __call__(self, x):
            x = lnn.relu(lnn.Conv(16, (3, 3))(x))
            x = lnn.avg_pool(x, (2, 2), strides=(2, 2))
            x = lnn.relu(lnn.Conv(32, (3, 3))(x))
            x = lnn.avg_pool(x, (2, 2), strides=(2, 2))
            x = x.reshape((x.shape[0], -1))
            return lnn.Dense(10)(lnn.relu(lnn.Dense(64)(x)))

    batch = 256
    n_stack = 16  # steps per device program (train_steps scan)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.normal(size=(n_stack, batch, 28, 28, 1)), jnp.float32)
    ys = jnp.asarray(rng.integers(0, 10, size=(n_stack, batch)), jnp.int32)
    xb, yb = xs[0], ys[0]

    dp = ht.nn.DataParallel(CNN(), optimizer=optax.adam(1e-3))
    dp.init(jax.random.PRNGKey(0), xb)

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    # steady-state training stages a queue of batches in HBM and scans
    # them in ONE program (DataParallel.train_steps): per-step host
    # dispatch — pure link latency on a tunneled chip — amortizes over
    # the stack, so the metric measures the device, not the link
    dp.train_steps(loss_fn, xs, ys)  # compile + cache the scanned epoch
    xs, ys = dp._stage_stack(xs, ys)  # stage once; timed loop re-uses
    n_iter = 4

    def run_once():
        return dp.train_steps(loss_fn, xs, ys)

    per_stack, meta = _time_amortized(
        run_once, lambda l: float(l[-1]), n_iter, sync_floor
    )
    per = per_stack / n_stack
    steps_per_s = 1.0 / per
    try:  # XLA's own flop count for one scanned stack, if exposed
        cost = dp._epoch_fn.lower(
            dp.params, dp._opt_state, xs, ys
        ).compile().cost_analysis()
        step_flops = float(
            (cost[0] if isinstance(cost, (list, tuple)) else cost).get("flops", 0.0)
        ) / n_stack
    except Exception:
        step_flops = 0.0

    # reference per-process path: the same CNN step in torch on CPU
    import torch
    import torch.nn as tnn

    tmodel = tnn.Sequential(
        tnn.Conv2d(1, 16, 3, padding=1), tnn.ReLU(), tnn.AvgPool2d(2),
        tnn.Conv2d(16, 32, 3, padding=1), tnn.ReLU(), tnn.AvgPool2d(2),
        tnn.Flatten(), tnn.Linear(32 * 49, 64), tnn.ReLU(), tnn.Linear(64, 10),
    )
    topt = torch.optim.Adam(tmodel.parameters(), lr=1e-3)
    txb = torch.randn(batch, 1, 28, 28)
    tyb = torch.randint(0, 10, (batch,))

    def tstep():
        topt.zero_grad()
        loss = tnn.functional.cross_entropy(tmodel(txb), tyb)
        loss.backward()
        topt.step()
        return loss

    tstep()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        _ = tstep().item()
        best = min(best, time.perf_counter() - t0)
    rec = {
        "metric": "dpsgd_cnn_batch256_steps_per_s",
        "value": round(steps_per_s, 2),
        "unit": "steps/s",
        "vs_baseline": round(steps_per_s * best, 2),
        "steps_per_dispatch": n_stack,
        "timing": meta,
    }
    if roofline:
        # the scanned stack amortizes dispatch n_stack ways, so the step
        # is device-bound and pct_of_peak_f32 is the regime anchor.
        # pct_of_dispatch_floor (floor / amortized step) records how far
        # the metric now sits ABOVE the one-dispatch-per-step ceiling —
        # values > 100 mean the link no longer bounds it (r4 weak #8).
        if roofline.get("dispatch_floor_ms"):
            rec["pct_of_dispatch_floor"] = round(
                100.0 * (roofline["dispatch_floor_ms"] / 1e3) / per, 1
            )
        if step_flops:
            rec["pct_of_peak_f32"] = round(
                100.0 * (step_flops / per / 1e9) / roofline["peak_f32_matmul_gflops"], 1
            )
    return rec


def _fft_scalar(r) -> float:
    """One scalar that depends on the transform, without materializing a
    host complex array: planar-backed results read their planes."""
    if r._planar is not None:
        re, im = r._planar
        return float(jnp.sqrt(re[(0,) * re.ndim] ** 2 + im[(0,) * im.ndim] ** 2))
    return float(jnp.abs(r.larray_padded[(0,) * r.ndim]))


def bench_fft3d(ht, sync_floor, roofline=None):
    """Config 5: 3-D FFT throughput, standard 5 N log2 N flop count.

    Runs ON the chip via the planar (re, im) real-pair kernels even on
    complex-less runtimes (heat_tpu/fft/_planar.py).  512^3 so device
    compute dominates the tunnel's per-program dispatch floor; a Parseval
    check outside the timed region guards that the measured program is
    really the transform (the full spectrum is verified against
    np.fft.fftn at 128^3 in tests/test_io_random_fft.py)."""
    s = 512
    n = s**3
    ht.random.seed(2)
    x = ht.random.randn(s, s, s, split=0).astype(ht.float32)
    float(x.sum())

    def fft():
        return ht.fft.fftn(x)

    r = fft()
    on_chip = r._planar is not None or (
        next(iter(r.larray_padded.devices())).platform != "cpu"
    )
    # Parseval: sum|X|^2 == N * sum|x|^2 (on device, outside the timing)
    if r._planar is not None:
        re, im = r._planar
        spec_energy = float(jnp.sum(re * re + im * im))
    else:
        spec_energy = float(jnp.sum(jnp.abs(r.larray_padded) ** 2))
    sig_energy = float((x * x).sum())
    parseval = abs(spec_energy / (n * sig_energy) - 1.0)
    if parseval > 1e-2:
        raise MeasurementError(f"Parseval check failed: {parseval:.3e}")

    per, meta = _time_amortized(fft, _fft_scalar, 2, sync_floor)
    gflops = 5.0 * n * np.log2(n) / per / 1e9

    # Complex-input transform (ISSUE 16): fftn of the spectrum r — a full
    # complex 512^3 with nonzero planes — drives the pair-block leading
    # engine, which moves both planes through ONE relayout per stage
    # instead of two per-plane passes.  The acceptance yardstick is the
    # ratio to the real-input time above (was ~2.1x with the per-plane
    # stages; the pair-block path targets <= 1.3x).
    def fft_c():
        return ht.fft.fftn(r)

    float(_fft_scalar(fft_c()))
    per_c, meta_c = _time_amortized(fft_c, _fft_scalar, 2, sync_floor)
    complex_rec = {
        "gflops": round(5.0 * n * np.log2(n) / per_c / 1e9, 1),
        "ratio_vs_real": round(per_c / per, 3),
        "timing": meta_c,
    }

    import torch

    # GFLOP/s-normalized rates compare across sizes: the 128^3 subset
    # baseline avoids minutes of single-core 512^3 FFTs + ~2 GiB host RAM
    sb = 128
    xb = torch.randn(sb, sb, sb)
    torch.fft.fftn(xb)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        r2 = torch.fft.fftn(xb)
        _ = r2.real.sum().item()
        best = min(best, time.perf_counter() - t0)
    base = 5.0 * sb**3 * np.log2(sb**3) / best / 1e9
    rec = {
        "metric": "fft3d_512^3_gflops",
        "value": round(gflops, 1),
        "unit": "GFLOP/s",
        "vs_baseline": round(gflops / base, 2),
        "on_chip": on_chip,
        "parseval_err": round(parseval, 6),
        "timing": meta,
        "complex_input_512^3": complex_rec,
    }
    if roofline:
        # a 3-axis transform must touch both f32 planes at least once per
        # axis pass: >= 3 * (read+write) * (re+im) * 4 bytes = 48N bytes.
        # The achieved fraction of stream bandwidth under that minimal
        # model is the roofline tie (an FFT is bandwidth-, not flop-bound).
        # The MINIMAL model is the honest denominator: bandwidth on XLA's
        # scheduled bytes rewards wasteful schedules (VERDICT r4 weak #1),
        # so scheduled bytes are recorded as a diagnostic only.
        eff_bw = 48.0 * n / per / 1e9
        rec["eff_bw_gbytes_minimal_model"] = round(eff_bw, 1)
        rec["pct_of_bw_minimal_model"] = round(
            100.0 * eff_bw / roofline["hbm_stream_gbytes_per_s"], 1
        )
        try:
            from heat_tpu.fft.fft import _planar_prog

            prog = _planar_prog("fft", None, ((0, None), (1, None), (2, None)))
            re_in = x._dense()
            ca = prog.lower(re_in, None).compile().cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0]
            rec["bytes_scheduled_gb"] = round(float(ca.get("bytes accessed", 0.0)) / 1e9, 2)
        except Exception:
            pass
    return rec


def bench_dispatch(ht, sync_floor, roofline=None):
    """Config 6: dispatch-layer amortization smoke metrics (ISSUE 1).

    ``dispatch_cache_hit_rate`` — fraction of executable-cache lookups
    served without a retrace across two passes of a fixed mixed op
    sequence (the iterative-ML shape: identical shapes every pass;
    anything below ~0.5 here means repeated shapes are recompiling).
    ``dispatches_per_kmeans_iter`` — launches per Lloyd iteration for a
    20-iteration fit (the on-device while_loop should hold this far
    below 1.0).  ``fused_ops_per_dispatch`` — elementwise/reduce ops
    folded per launch for the fixed sequence; > 1 means chain fusion is
    collapsing op chains.  Emitted every round so BENCH_r{N}.json tracks
    dispatch amortization alongside throughput."""
    from heat_tpu.core import dispatch

    ht.random.seed(5)
    n = 1 << 16
    a = ht.random.randn(n, split=0).astype(ht.float32)
    b = ht.random.randn(n, split=0).astype(ht.float32)
    c = ht.random.randn(n, split=0).astype(ht.float32)

    def sequence():
        s1 = float(((a * b + c) / 2.0 - b).sum())
        s2 = float(ht.exp(a * 0.5).mean())
        return s1 + s2

    sequence()  # compile pass
    dispatch.reset_stats()
    sequence()  # measured pass: should be all hits
    seq = dispatch.cache_stats()

    # fused-chain latency through the warm cache (device-bound number)
    per, meta = _time_amortized(
        lambda: ((a * b + c) / 2.0 - b).sum(),
        lambda r: float(r),
        32,
        sync_floor,
    )

    x = ht.random.randn(1 << 12, 8, split=0).astype(ht.float32)
    km_iters = 20
    km = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=km_iters,
                           tol=-1.0, random_state=0)
    km.fit(x)  # compile
    dispatch.reset_stats()
    km = ht.cluster.KMeans(n_clusters=4, init="random", max_iter=km_iters,
                           tol=-1.0, random_state=0)
    km.fit(x)
    ks = dispatch.cache_stats()
    km_dispatches = ks["dispatches"] + ks["external_dispatches"]

    return {
        "metric": "dispatch_cache_hit_rate",
        "value": round(seq["hit_rate"], 3),
        "unit": "fraction",
        "vs_baseline": 1.0,
        "vs_baseline_kind": "self",
        "dispatch_cache_hit_rate": round(seq["hit_rate"], 3),
        "dispatches_per_kmeans_iter": round(km_dispatches / km_iters, 3),
        "kmeans_fit_dispatches": km_dispatches,
        "fused_ops_per_dispatch": round(
            seq["fused_ops"] / seq["dispatches"], 2
        ) if seq["dispatches"] else 0.0,
        "donations": seq["donations"],
        "fused_chain_5op_ms": round(per * 1e3, 4),
        "timing": meta,
    }


def bench_resilience(ht, sync_floor, roofline=None):
    """Config 7: resilience-layer counters + checkpoint overhead (ISSUE 2).

    ``checkpoint_save_ms``/``checkpoint_restore_ms`` — wall time of one
    filesystem-native Checkpointer save/restore of a representative
    (1k x 256 f32 centers + scalars) fit state, the per-chunk overhead a
    ``checkpoint_every=N`` fit pays; the perf gate watches these so a
    checkpoint-layer regression (lost atomicity batching, sidecar
    recomputation) is caught.  ``retries``/``faults_injected``/
    ``faults_survived`` — counters from a scripted transient-fault save
    (fault plan: one transient on ``io.write``), proving the retry path
    is live in the shipped wheel, not just under pytest.  The headline
    value is checkpoint_save_ms."""
    import os
    import shutil
    import tempfile

    from heat_tpu import resilience as rz
    from heat_tpu.utils.checkpoint import Checkpointer

    rz.reset_retry_stats()
    rz.reset_fault_stats()
    state = {
        "state": np.random.default_rng(0).standard_normal((1024, 256)).astype(np.float32),
        "n_iter": 17,
        "shift": 1e-3,
        "converged": False,
    }
    d = tempfile.mkdtemp(prefix="heat_tpu_bench_ck_")
    try:
        ck = Checkpointer(d)
        save_s = float("inf")
        for i in range(5):
            t0 = time.perf_counter()
            ck.save(i, state)
            save_s = min(save_s, time.perf_counter() - t0)
        restore_s = float("inf")
        for i in range(5):
            t0 = time.perf_counter()
            out = ck.restore(i)
            restore_s = min(restore_s, time.perf_counter() - t0)
        assert out["n_iter"] == 17

        # scripted transient save fault: one retry must absorb it
        os.environ["HEAT_TPU_RETRY_NO_SLEEP"] = "1"
        try:
            with rz.fault_plan({"io.write": [0]}):
                ht.save(
                    ht.arange(1024, dtype=ht.float32),
                    os.path.join(d, "fault_probe.npy"),
                )
        finally:
            os.environ.pop("HEAT_TPU_RETRY_NO_SLEEP", None)
        counters = rz.resilience_stats()

        # elastic worker-loss recovery (ISSUE 8): one subprocess fit
        # killed mid-fit by the fault plan, reshaped one device smaller,
        # resumed from the surviving checkpoint; the recorded latency is
        # loss detection -> resumed worker's first heartbeat (the same
        # quantity scripts/perf_ci.py gates with max_seconds)
        elastic_recovery_s = None
        elastic_world = None
        try:
            import json as _json
            import sys as _sys

            from heat_tpu.elastic.process import (
                ProcessSupervisor,
                kmeans_worker_source,
            )

            eck = os.path.join(d, "elastic")
            kill_plan = _json.dumps(
                {"plan": {"kmeans.iter": [{"at": 1, "kind": "kill", "exit_code": 137}]}}
            )

            def _ebuild(ws, resume, attempt):
                src = kmeans_worker_source(eck, resume_from=resume, x64=False)
                return (
                    [_sys.executable, "-c", src],
                    {"HEAT_TPU_FAULT_PLAN": kill_plan if attempt == 0 else ""},
                )

            eout = ProcessSupervisor(
                _ebuild, eck, world_size=4, shrink_by=1, max_recoveries=2,
                poll_s=0.2, attempt_timeout_s=280,
            ).run()
            elastic_recovery_s = round(eout["recovery_s"][0], 2)
            elastic_world = f"{4}->{eout['world_size']}"
        except Exception as e:  # lint: allow H501(optional bench section records its error)
            elastic_recovery_s = f"error: {type(e).__name__}: {e}"[:120]
    finally:
        shutil.rmtree(d, ignore_errors=True)

    return {
        "metric": "resilience_checkpoint_save_ms",
        "value": round(save_s * 1e3, 3),
        "unit": "ms",
        "vs_baseline": 1.0,
        "vs_baseline_kind": "self",
        "checkpoint_save_ms": round(save_s * 1e3, 3),
        "checkpoint_restore_ms": round(restore_s * 1e3, 3),
        "checkpoint_state_mb": round(state["state"].nbytes / 2**20, 1),
        "retries": counters["retries"],
        "faults_injected": counters["faults_injected"],
        "faults_survived": counters["faults_survived"],
        "retry_gave_up": counters["gave_up"],
        "elastic_recovery_s": elastic_recovery_s,
        "elastic_world": elastic_world,
    }


def bench_overlap(ht, sync_floor, roofline=None):
    """Config 8: overlap-layer metrics (ISSUE 3).

    ``ckpt_stall_ms`` — wall time the caller spends inside an async
    ``AsyncCheckpointer.save`` (snapshot + enqueue) for the
    representative 1024x256 f32 fit state, i.e. the per-chunk stall a
    ``checkpoint_every=N`` fit now pays, vs ``checkpoint_save_ms`` — the
    full synchronous write the fit used to pay; ``stall_vs_sync`` is
    their ratio (the acceptance gate wants < 0.3).  ``prefetch_hit_rate``
    — fraction of batches staged on device ahead of the consumer by
    ``prefetch_to_device`` over a synthetic windowed stream.
    ``grad_buckets`` — collective buckets a bucketed-schedule
    DataParallel step issues for a small MLP.  The headline value is the
    async stall."""
    import os
    import shutil
    import tempfile

    import optax

    from heat_tpu.utils import overlap as ov
    from heat_tpu.utils.checkpoint import Checkpointer
    from heat_tpu.utils.data import prefetch_to_device

    ov.reset_overlap_stats()
    state = {
        "state": np.random.default_rng(0).standard_normal((1024, 256)).astype(np.float32),
        "n_iter": 17,
        "shift": 1e-3,
        "converged": False,
    }
    d = tempfile.mkdtemp(prefix="heat_tpu_bench_ov_")
    try:
        ck = Checkpointer(os.path.join(d, "sync"))
        sync_s = float("inf")
        for i in range(5):
            t0 = time.perf_counter()
            ck.save(i, state)
            sync_s = min(sync_s, time.perf_counter() - t0)

        ack = Checkpointer(os.path.join(d, "async")).as_async()
        stall_s = float("inf")
        for i in range(5):
            t0 = time.perf_counter()
            ack.save(i, state)  # snapshot + enqueue: the loop-visible cost
            stall_s = min(stall_s, time.perf_counter() - t0)
            ack.wait()  # drain outside the stall window (the fit's chunk
            # compute covers this in production)
        ack.close()

        # prefetch hit rate over a synthetic windowed stream with a
        # small device op standing in for the consuming train step
        windows = (np.full((256, 8), i, np.float32) for i in range(32))
        consume = jax.jit(lambda b: b.sum())
        for b in prefetch_to_device(windows, size=2):
            consume(b)
        stats = ov.overlap_stats()

        # bucketed-schedule DataParallel step on a small MLP
        rng = np.random.default_rng(1)
        params = {
            "w1": jnp.asarray(rng.normal(size=(64, 128)) * 0.1, jnp.float32),
            "b1": jnp.zeros((128,), jnp.float32),
            "w2": jnp.asarray(rng.normal(size=(128, 8)) * 0.1, jnp.float32),
            "b2": jnp.zeros((8,), jnp.float32),
        }
        apply = lambda p, xb: jnp.tanh(xb @ p["w1"] + p["b1"]) @ p["w2"] + p["b2"]
        loss_fn = lambda pred, tgt: jnp.mean((pred - tgt) ** 2)
        xb = jnp.asarray(rng.normal(size=(64, 64)), jnp.float32)
        yb = jnp.asarray(rng.normal(size=(64, 8)), jnp.float32)
        os.environ["HEAT_TPU_GRAD_BUCKET_MB"] = "0.01"  # visible bucketing at toy scale
        try:
            dp = ht.nn.DataParallel(
                apply, optimizer=ht.optim.DataParallelOptimizer(optax.sgd(0.1))
            )
            dp.set_params(params)
            dp.step(loss_fn, xb, yb)
        finally:
            os.environ.pop("HEAT_TPU_GRAD_BUCKET_MB", None)
        grad_buckets = ov.overlap_stats()["grad_buckets"]
    finally:
        shutil.rmtree(d, ignore_errors=True)

    total = stats["prefetch_hits"] + stats["prefetch_misses"]
    return {
        "metric": "overlap_ckpt_stall_ms",
        "value": round(stall_s * 1e3, 3),
        "unit": "ms",
        "vs_baseline": round(sync_s / stall_s, 2) if stall_s else 0.0,
        "vs_baseline_kind": "sync_checkpoint_save_same_process",
        "ckpt_stall_ms": round(stall_s * 1e3, 3),
        "checkpoint_save_ms": round(sync_s * 1e3, 3),
        "stall_vs_sync": round(stall_s / sync_s, 3) if sync_s else 0.0,
        "async_saves": stats["async_saves"],
        "prefetch_hits": stats["prefetch_hits"],
        "prefetch_misses": stats["prefetch_misses"],
        "prefetch_hit_rate": round(stats["prefetch_hits"] / total, 3) if total else 0.0,
        "grad_buckets": grad_buckets,
    }


def bench_serving(ht, sync_floor, roofline=None):
    """Config 11: sustained-load serving (ISSUE 9).

    A fitted KMeans is saved, hot-loaded into an
    :class:`~heat_tpu.serving.InferenceService`, and hammered by client
    threads issuing requests of varied sizes while one over-quota tenant
    sheds against its token bucket.  Reported: admitted request rate and
    its p50/p99 latency, the coalesced batch-size distribution, the
    shed rate, and — the cache acceptance property — new executable
    compiles during steady state (must be 0: pad-to-bucket keeps the
    key set finite).  ``vs_baseline`` divides the served rate by the
    same request stream predicted *directly* (per-request shapes, no
    coalescing) — the naive serving loop the coalescer replaces."""
    import shutil
    import tempfile
    import threading

    from heat_tpu import serving as srv
    from heat_tpu.core import dispatch
    from heat_tpu.resilience import OverloadedError
    from heat_tpu.serving import model_io

    rng = np.random.default_rng(0)
    pts = rng.standard_normal((1 << 12, 16)).astype(np.float32)
    x = ht.array(pts, split=0)
    km = ht.cluster.KMeans(n_clusters=8, init="random", max_iter=5, random_state=0).fit(x)

    sizes = [1, 3, 7, 12, 18, 27, 33, 50, 64]
    n_requests = 400
    d = tempfile.mkdtemp(prefix="heat_tpu_bench_srv_")
    try:
        srv.save_model(km, d, version=1, name="km")
        svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
        svc.load("km", d)
        for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
            svc.predict("km", pts[:b])

        # baseline: the same request stream, predicted directly one
        # request at a time (per-request shapes -> per-shape compiles)
        t0 = time.perf_counter()
        for i in range(n_requests // 4):
            n = sizes[i % len(sizes)]
            model_io.infer(km, ht.array(pts[i % 64 : i % 64 + n], split=None)).numpy()
        direct_rate = (n_requests // 4) / (time.perf_counter() - t0)

        # sustained load: 4 client threads, varied sizes; one noisy
        # tenant hammers an over-quota bucket concurrently
        svc.set_quota("noisy", rate=2.0, burst=4.0)
        stop = threading.Event()
        noisy_counts = {"ok": 0, "shed": 0}

        def noisy():
            while not stop.is_set():
                try:
                    svc.predict("km", pts[:2], tenant="noisy", timeout=30)
                    noisy_counts["ok"] += 1
                except OverloadedError:
                    noisy_counts["shed"] += 1
                time.sleep(0.002)

        nt = threading.Thread(target=noisy, name="bench-noisy-tenant", daemon=True)
        s0 = dispatch.cache_stats()
        lat_lock = threading.Lock()
        latencies = []

        def client(worker):
            for i in range(n_requests // 4):
                n = sizes[(worker + i) % len(sizes)]
                off = (worker * 61 + i * 7) % 64
                t1 = time.perf_counter()
                svc.predict("km", pts[off : off + n], timeout=30)
                dt = time.perf_counter() - t1
                with lat_lock:
                    latencies.append(dt)

        nt.start()
        t0 = time.perf_counter()
        clients = [
            threading.Thread(target=client, args=(w,), name=f"bench-client-{w}", daemon=True)
            for w in range(4)
        ]
        for t in clients:
            t.start()
        for t in clients:
            t.join()
        wall = time.perf_counter() - t0
        stop.set()
        nt.join()
        s1 = dispatch.cache_stats()
        svc.close()

        lat = np.sort(np.asarray(latencies))
        batch_rows = ht.telemetry.metrics.histogram("serving.batch_rows")
        shed_total = noisy_counts["shed"]
        served_rate = len(latencies) / wall
        new_compiles = s1["misses"] - s0["misses"]
        steady_lookups = (s1["hits"] - s0["hits"]) + new_compiles
        return {
            "metric": "serving_req_per_s",
            "value": round(served_rate, 1),
            "unit": "req/s",
            "vs_baseline": round(served_rate / direct_rate, 2) if direct_rate else 0.0,
            "vs_baseline_kind": "uncoalesced_direct_predict_same_process",
            "p50_ms": round(float(lat[len(lat) // 2]) * 1e3, 3),
            "p99_ms": round(float(lat[int(len(lat) * 0.99)]) * 1e3, 3),
            "requests": len(latencies),
            "steady_state_new_compiles": new_compiles,
            "steady_state_hit_rate": round(
                (s1["hits"] - s0["hits"]) / steady_lookups, 4
            ) if steady_lookups else 1.0,
            "coalesced_batch_rows": {
                "count": batch_rows.count,
                "p50": batch_rows.quantile(0.5),
                "p99": batch_rows.quantile(0.99),
                "max": batch_rows.max,
            },
            "noisy_tenant_shed": shed_total,
            "noisy_tenant_admitted": noisy_counts["ok"],
            "shed_rate": round(
                shed_total / (shed_total + noisy_counts["ok"]), 3
            ) if (shed_total + noisy_counts["ok"]) else 0.0,
        }
    finally:
        shutil.rmtree(d, ignore_errors=True)


def bench_canary(ht, sync_floor, roofline=None):
    """Config 11b: the canary decision plane under a sustained stream
    (ISSUE 15).

    An identical canary (v2 == v1) is hot-loaded ``activate=False`` with
    ``HEAT_TPU_SHADOW_FRACTION`` at 1.0 while client requests stream at
    varied sizes.  Reported: the **time-to-verdict** — how long the
    decision engine takes to accumulate ``HEAT_TPU_CANARY_MIN_ROWS``
    shadow rows and auto-promote under this stream (the operational
    question: "how long does a canary bake?"), the shadow lane's
    batch/drop counters, the canary-vs-primary latency ratio measured on
    the same mirrored batches, and the steady-state compile count (must
    be 0: the shadow path rides the primary's bucket keys)."""
    import shutil
    import tempfile

    from heat_tpu import serving as srv
    from heat_tpu.core import dispatch
    from heat_tpu.serving import canary as cnry
    from heat_tpu.telemetry import metrics as tmet

    rng = np.random.default_rng(3)
    pts = rng.standard_normal((1 << 12, 16)).astype(np.float32)
    x = ht.array(pts, split=0)
    km = ht.cluster.KMeans(n_clusters=8, init="random", max_iter=5, random_state=0).fit(x)

    sizes = [1, 3, 7, 12, 18, 27, 33, 50, 64]
    d = tempfile.mkdtemp(prefix="heat_tpu_bench_canary_")
    try:
        srv.save_model(km, d, version=1, name="km")
        srv.save_model(km, d, version=2, name="km")
        svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
        svc.load("km", d, version=1)
        for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
            svc.predict("km", pts[:b])

        s0 = dispatch.cache_stats()
        c0 = {
            k: tmet.counter(f"canary.{k}").value
            for k in ("sampled", "sampled_rows", "dropped", "comparisons")
        }
        svc.load("km", d, version=2, activate=False)  # the canary
        svc.canary.fraction = 1.0
        svc.canary.min_rows = 256
        t0 = time.perf_counter()
        deadline = t0 + 60.0
        i = 0
        while time.perf_counter() < deadline:
            n = sizes[i % len(sizes)]
            svc.predict("km", pts[(i * 7) % 64 : (i * 7) % 64 + n])
            i += 1
            st = cnry.status("km")
            if st is not None and st["decision"] is not None:
                break
        decision_s = time.perf_counter() - t0
        svc.canary.wait_idle(30)
        st = cnry.status("km") or {}
        s1 = dispatch.cache_stats()
        c1 = {
            k: tmet.counter(f"canary.{k}").value
            for k in ("sampled", "sampled_rows", "dropped", "comparisons")
        }
        dec = st.get("decision") or {}
        svc.close()
        return {
            "metric": "canary_decision_s",
            "value": round(decision_s, 3),
            "unit": "s",
            "vs_baseline": 0.0,
            "vs_baseline_kind": "time_to_verdict_at_min_rows_256",
            "verdict": dec.get("verdict"),
            "action": dec.get("action"),
            "requests_to_verdict": i,
            "shadow_batches": c1["sampled"] - c0["sampled"],
            "shadow_rows": c1["sampled_rows"] - c0["sampled_rows"],
            "shadow_dropped": c1["dropped"] - c0["dropped"],
            "comparisons": c1["comparisons"] - c0["comparisons"],
            "mismatch_pct": st.get("mismatch_pct"),
            "canary_latency_ratio": st.get("latency_ratio"),
            "steady_state_new_compiles": s1["misses"] - s0["misses"],
        }
    finally:
        cnry.reset_canary_state()
        shutil.rmtree(d, ignore_errors=True)


def fleet_scenario(
    scale_window_s=4.0,
    clients=12,
    kill_window_s=3.0,
    kill_clients=4,
    queue_depth=3,
    delay_ms=60.0,
    steady_requests=40,
):
    """The fleet-serving measurement harness (shared by ``bench_fleet``
    and ``scripts/perf_ci.py``): real replica subprocesses behind a real
    :class:`~heat_tpu.fleet.FleetRouter`, four phases.

    * **scale-out** — closed-loop clients drive single-row predicts
      through the router at 1 then 4 replicas.  Each replica's capacity
      is its bounded admission queue over the coalescing residency
      (Little's law), so the aggregate rate measures the ROUTER's work —
      bounded-load spillover past the hash-favorite plus queue-shed
      failover — not the host's core count: a router that stops
      spreading pins the ratio to ~1x whatever the hardware.
    * **cold start** — a fresh replica boots from the AOT executable
      cache + pre-warm manifest the first replica populated; measured:
      artifact hits at ready, the FIRST request's latency vs the
      replica's own steady p99, and compiles after ready (must be 0 —
      executable-cache hit rate 1.0 from request one).
    * **replica kill** — SIGKILL the rendezvous-favorite replica under
      live load; every client request must still answer 200/429 (the
      router's bounded-retry failover absorbs the loss) — failed
      requests are the gated count, cap 0.
    * **drain** — SIGTERM one replica; it must finish in-flight work
      and exit 0.

    Returns the raw numbers dict; callers shape it into records/gates.
    """
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.request

    import heat_tpu as ht
    from heat_tpu import serving as srv
    from heat_tpu.fleet import FleetRouter, LocalReplicaSet

    base = tempfile.mkdtemp(prefix="heat_tpu_bench_fleet_")
    rng = np.random.default_rng(0)
    pts = rng.standard_normal((256, 16)).astype(np.float32)
    km = ht.cluster.KMeans(
        n_clusters=8, init="random", max_iter=5, random_state=0
    ).fit(ht.array(pts, split=0))
    mdir = f"{base}/km"
    srv.save_model(km, mdir, version=1, name="km")
    manifest = f"{base}/prewarm.json"
    with open(manifest, "w") as f:
        _json.dump({"version": 1, "entries": [
            {"model": "km", "bucket": b, "features": 16, "dtype": "float32"}
            for b in (1, 2, 4, 8, 16)
        ]}, f)
    body = _json.dumps({"model": "km", "inputs": pts[:1].tolist()}).encode()

    rs = LocalReplicaSet(
        {"km": mdir}, base, aot_cache=f"{base}/aot", prewarm=manifest,
        max_batch=64, max_delay_ms=delay_ms, queue_depth=queue_depth,
    )
    router = FleetRouter(health_period_s=0.25, load_factor=1.2)

    def drive(window_s, n_clients):
        stop = threading.Event()
        lock = threading.Lock()
        counts = {"ok": 0, "shed": 0, "failed": 0}

        def client():
            while not stop.is_set():
                status, _out, _ct, headers = router.handle(
                    "POST", "/v1/predict", body
                )
                with lock:
                    if status == 200:
                        counts["ok"] += 1
                    elif status == 429:
                        counts["shed"] += 1
                    else:
                        counts["failed"] += 1
                if status == 429:
                    ra = float(headers.get("Retry-After", 0.02) or 0.02)
                    time.sleep(min(ra, 0.2))

        threads = [
            threading.Thread(target=client, daemon=True) for _ in range(n_clients)
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        time.sleep(window_s)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        return counts, counts["ok"] / (time.perf_counter() - t0)

    def direct(url, n):
        lats = []
        for _ in range(n):
            t0 = time.perf_counter()
            req = urllib.request.Request(
                url + "/v1/predict", data=body, method="POST",
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=15) as resp:
                resp.read()
            lats.append((time.perf_counter() - t0) * 1e3)
        return np.sort(np.asarray(lats))

    out = {}
    try:
        # phase 1: first replica (compiles + populates the AOT cache)
        t0 = time.monotonic()
        u1 = rs.spawn()
        out["spawn_first_s"] = round(time.monotonic() - t0, 2)
        router.add_replica(u1)
        router.poll_health()
        counts1, rate1 = drive(scale_window_s, clients)
        out["rate_1_replica"] = round(rate1, 1)
        out["shed_1_replica"] = counts1["shed"]
        out["failed_1_replica"] = counts1["failed"]

        # phase 2: cold start from the populated AOT cache
        t0 = time.monotonic()
        u2 = rs.spawn()
        out["spawn_cold_s"] = round(time.monotonic() - t0, 2)
        ready_doc = _json.load(urllib.request.urlopen(u2 + "/readyz", timeout=10))
        out["cold_aot_hits"] = ready_doc["aot"]["hits"]
        misses_ready = ready_doc["dispatch"]["misses"]
        t0 = time.perf_counter()
        req = urllib.request.Request(
            u2 + "/v1/predict", data=body, method="POST",
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=15) as resp:
            resp.read()
        out["cold_first_request_ms"] = round((time.perf_counter() - t0) * 1e3, 2)
        steady = direct(u2, steady_requests)
        out["steady_p50_ms"] = round(float(steady[len(steady) // 2]), 2)
        out["steady_p99_ms"] = round(float(steady[int(len(steady) * 0.99)]), 2)
        out["cold_vs_steady_p99"] = round(
            out["cold_first_request_ms"] / out["steady_p99_ms"], 3
        )
        after = _json.load(urllib.request.urlopen(u2 + "/readyz", timeout=10))
        out["cold_compiles_after_ready"] = after["dispatch"]["misses"] - misses_ready

        # phase 3: scale out to 4 replicas, same offered load
        router.add_replica(u2)
        u3, u4 = rs.spawn(), rs.spawn()
        router.add_replica(u3)
        router.add_replica(u4)
        router.poll_health()
        counts4, rate4 = drive(scale_window_s, clients)
        out["rate_4_replicas"] = round(rate4, 1)
        out["shed_4_replicas"] = counts4["shed"]
        out["failed_4_replicas"] = counts4["failed"]
        out["scaleout_ratio"] = round(rate4 / rate1, 2) if rate1 else 0.0

        # phase 4: SIGKILL the hash-favorite under live load
        victim = router.preferred("km") or u1
        stop = threading.Event()
        lock = threading.Lock()
        kill_counts = {"ok": 0, "shed": 0, "failed": 0}

        def kill_client():
            while not stop.is_set():
                status, _o, _c, _h = router.handle("POST", "/v1/predict", body)
                with lock:
                    if status == 200:
                        kill_counts["ok"] += 1
                    elif status == 429:
                        kill_counts["shed"] += 1
                    else:
                        kill_counts["failed"] += 1

        threads = [
            threading.Thread(target=kill_client, daemon=True)
            for _ in range(kill_clients)
        ]
        failovers_before = router.statusz()["failovers"]
        for t in threads:
            t.start()
        time.sleep(kill_window_s / 3.0)
        rs.kill(victim)
        time.sleep(2.0 * kill_window_s / 3.0)
        stop.set()
        for t in threads:
            t.join(timeout=15)
        out["kill_requests_ok"] = kill_counts["ok"]
        out["kill_requests_shed"] = kill_counts["shed"]
        out["kill_failed_requests"] = kill_counts["failed"]
        out["kill_failovers"] = router.statusz()["failovers"] - failovers_before

        # phase 5: graceful drain must exit 0
        survivor = next(u for u in rs.urls())
        router.drain_replica(survivor)
        out["drain_rc"] = rs.drain_stop(survivor)
        return out
    finally:
        router.close()
        rs.close()
        shutil.rmtree(base, ignore_errors=True)


def bench_fleet(ht, sync_floor, roofline=None):
    """Config 12: fleet-scale serving (ISSUE 13).

    Real replica subprocesses behind the fleet router: req/s at 1 -> 4
    replicas (with the scale-out ratio the perf gate enforces at >= 3x),
    the AOT-cache cold start (fresh replica's first request vs its
    steady p99, compiles after ready), the replica-kill-under-live-load
    scenario (failed client requests, gated at 0), and the graceful
    drain exit code.  See :func:`fleet_scenario` for methodology."""
    raw = fleet_scenario()
    return {
        "metric": "fleet_req_per_s_4x",
        "value": raw["rate_4_replicas"],
        "unit": "req/s",
        "vs_baseline": raw["scaleout_ratio"],
        "vs_baseline_kind": "same_router_single_replica",
        **raw,
    }


def bench_telemetry(ht, sync_floor, roofline=None):
    """Config 9: telemetry-layer self-cost (ISSUE 4 + ISSUE 6).

    ``span_ns_enabled``/``span_ns_disabled`` — per-span wall cost of the
    host-side tracer with recording on vs off (disabled must be ~two
    attribute reads; enabled buys a ring append + TraceAnnotation).
    ``snapshot_us`` — cost of one full-registry ``telemetry.snapshot()``
    with every domain registered, the price a heartbeat scraper pays.
    Introspection-layer additions (ISSUE 6): ``scrape_metrics_us`` /
    ``scrape_varz_us`` — one full HTTP GET against the live endpoint on
    an ephemeral port (socket + handler + serialization, the cost ONE
    Prometheus scrape imposes on the process); ``recorder_overhead_ns``
    — per-span cost with the crash flight recorder ARMED vs not (the
    recorder is a passive excepthook, so this must be ~1.0x);
    ``cost_accounting_miss_us`` — per-miss dispatch cost with
    ``HEAT_TPU_COST_ANALYSIS`` on vs off, plus the recorded flops.
    Observatory additions (ISSUE 14): ``observatory_note_ns`` — the
    per-dispatch ledger-note tax on a warm cached key, armed vs
    disarmed; ``rooflinez_report_us`` — one full roofline-join report.
    The headline value is the enabled span cost — the number that bounds
    how densely the stack can afford to be instrumented."""
    import shutil
    import tempfile
    import urllib.request

    from heat_tpu import telemetry
    from heat_tpu.core import dispatch
    from heat_tpu.telemetry import flight_recorder
    from heat_tpu.telemetry import server as tserver

    def span_ns(n: int = 50_000) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            with telemetry.span("bench.telemetry.probe"):
                pass
        return (time.perf_counter() - t0) / n * 1e9

    prev = telemetry.set_tracing(True)
    try:
        span_ns(2_000)  # warm
        enabled_ns = min(span_ns() for _ in range(3))
        telemetry.set_tracing(False)
        disabled_ns = min(span_ns() for _ in range(3))
        # flight recorder armed vs not: the recorder is an excepthook +
        # bundle dir, so the steady-state delta must be noise (~1.0x)
        telemetry.set_tracing(True)
        d = tempfile.mkdtemp(prefix="heat_tpu_bench_fr_")
        try:
            flight_recorder.install(d)
            recorder_ns = min(span_ns() for _ in range(3))
        finally:
            flight_recorder.uninstall()
            shutil.rmtree(d, ignore_errors=True)
    finally:
        telemetry.set_tracing(prev)
        telemetry.clear_spans()

    n_snap = 500
    telemetry.snapshot()  # warm
    t0 = time.perf_counter()
    for _ in range(n_snap):
        telemetry.snapshot()
    snapshot_us = (time.perf_counter() - t0) / n_snap * 1e6

    # live-endpoint scrape cost: ephemeral port, same-process HTTP GET
    srv = tserver.start_server(0)
    try:
        def scrape_us(route: str, n: int = 50) -> float:
            urllib.request.urlopen(f"{srv.url}{route}", timeout=10).read()  # warm
            t0 = time.perf_counter()
            for _ in range(n):
                urllib.request.urlopen(f"{srv.url}{route}", timeout=10).read()
            return (time.perf_counter() - t0) / n * 1e6

        scrape_metrics_us = min(scrape_us("/metrics") for _ in range(3))
        scrape_varz_us = min(scrape_us("/varz") for _ in range(3))
    finally:
        tserver.stop_server()

    # per-executable cost accounting: dispatch-miss cost with the
    # analysis on vs off, and the flops it records
    import jax.numpy as jnp

    buf = jnp.ones((256,), jnp.float32)

    def miss_us(n: int = 32) -> float:
        dispatch.clear_cache()
        ops = [(lambda v: (lambda a, b: a + b * v))(i) for i in range(n)]
        t0 = time.perf_counter()
        for op in ops:
            dispatch.eager_apply(op, (buf, buf))
        return (time.perf_counter() - t0) / n * 1e6

    prev_cost = dispatch.set_cost_accounting(False)
    try:
        cost_off_us = min(miss_us() for _ in range(2))
        dispatch.set_cost_accounting(True)
        cost_on_us = min(miss_us() for _ in range(2))
        cost = dispatch.cost_summary()
        flops_recorded = cost["flops_total"]
    finally:
        dispatch.set_cost_accounting(prev_cost)
        dispatch.clear_cache()

    # roofline observatory (ISSUE 14): per-dispatch ledger-note cost on
    # a warm cached key, armed vs disarmed (the dispatch hot-path tax
    # the observatory_overhead perf gate bounds at <3% of a whole fit),
    # one /rooflinez scrape against the live report path, and the
    # fenced-sample share at the default HEAT_TPU_PERF_SYNC_EVERY
    from heat_tpu.telemetry import observatory as obsv

    buf2 = jnp.ones((512,), jnp.float32)
    dispatch.eager_apply(jnp.tanh, (buf2,))  # compile the probe key once

    def dispatch_ns(n: int = 20_000) -> float:
        t0 = time.perf_counter()
        for _ in range(n):
            dispatch.eager_apply(jnp.tanh, (buf2,))
        return (time.perf_counter() - t0) / n * 1e9

    prev_obs = obsv.set_enabled(True)
    prev_sync = obsv.set_sync_every(16)
    try:
        dispatch_ns(2_000)  # warm
        obs_on_ns = min(dispatch_ns() for _ in range(3))
        obsv.set_enabled(False)
        obs_off_ns = min(dispatch_ns() for _ in range(3))
        obsv.set_enabled(True)
        obsv.rooflinez_report(calibrate=False)  # warm
        t0 = time.perf_counter()
        for _ in range(50):
            obsv.rooflinez_report(calibrate=False)
        rooflinez_report_us = (time.perf_counter() - t0) / 50 * 1e6
        ledger_rows = len(obsv.ledger_report())
        sync_share = obsv.sync_every()
    finally:
        obsv.set_enabled(prev_obs)
        obsv.set_sync_every(prev_sync)
        obsv.reset()

    return {
        "metric": "telemetry_span_ns",
        "value": round(enabled_ns, 1),
        "unit": "ns",
        "vs_baseline": round(disabled_ns / enabled_ns, 4) if enabled_ns else 0.0,
        "vs_baseline_kind": "tracing_disabled_same_process",
        "span_ns_enabled": round(enabled_ns, 1),
        "span_ns_disabled": round(disabled_ns, 1),
        "snapshot_us": round(snapshot_us, 2),
        "metrics_registered": len(telemetry.REGISTRY.names()),
        "scrape_metrics_us": round(scrape_metrics_us, 1),
        "scrape_varz_us": round(scrape_varz_us, 1),
        "recorder_overhead_x": round(recorder_ns / enabled_ns, 3) if enabled_ns else 0.0,
        "cost_accounting_miss_us": round(cost_on_us, 2),
        "cost_accounting_off_miss_us": round(cost_off_us, 2),
        "cost_accounting_flops_recorded": flops_recorded,
        "observatory_note_ns": round(obs_on_ns - obs_off_ns, 1),
        "observatory_dispatch_ns_armed": round(obs_on_ns, 1),
        "observatory_dispatch_ns_disarmed": round(obs_off_ns, 1),
        "observatory_sync_every": sync_share,
        "observatory_ledger_rows": ledger_rows,
        "rooflinez_report_us": round(rooflinez_report_us, 1),
    }


def bench_analysis(ht, sync_floor, roofline=None):
    """Config 10: SPMD program-analyzer self-cost (ISSUE 5).

    ``analyze_off_miss_us``/``analyze_off_hit_ns`` — per-dispatch cost of
    the compile-path hook with ``HEAT_TPU_ANALYZE=0`` (the default): the
    off-mode hook is one lazy-import lookup + a string compare per cache
    MISS and provably nothing per hit (the ``if fresh`` guard), so both
    numbers track the plain dispatch floor.
    ``analyze_on_miss_ms`` — full analyzer cost per fresh compile in warn
    mode (re-lower + re-compile + HLO walk), the price a CI job pays to
    see J101-J105 diagnostics.  Headline value is the off-mode hit cost —
    the number that bounds what production dispatch pays for having the
    analyzer wired in at all."""
    import jax.numpy as jnp
    import numpy as np

    from heat_tpu import analysis
    from heat_tpu.analysis import diagnostics
    from heat_tpu.core import dispatch

    buf = jnp.ones((256,), jnp.float32)

    def miss_us(n=64):
        """Mean per-call cost of n distinct-key misses (fresh scalars)."""
        dispatch.clear_cache()
        ops = [(lambda v: (lambda a, b: a + b * v))(i) for i in range(n)]
        t0 = time.perf_counter()
        for op in ops:
            dispatch.eager_apply(op, (buf, buf))
        return (time.perf_counter() - t0) / n * 1e6

    def hit_ns(n=20_000):
        dispatch.eager_apply(jnp.add, (buf, buf))  # warm
        t0 = time.perf_counter()
        for _ in range(n):
            dispatch.eager_apply(jnp.add, (buf, buf))
        return (time.perf_counter() - t0) / n * 1e9

    prev = diagnostics.set_analysis_mode("0")
    try:
        off_miss = min(miss_us() for _ in range(3))
        off_hit = min(hit_ns() for _ in range(3))
        diagnostics.set_analysis_mode("warn")
        analysis.clear_diagnostics()
        import warnings

        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            on_miss = min(miss_us() for _ in range(2))
        diags = len(analysis.recent_diagnostics())
    finally:
        diagnostics.set_analysis_mode(prev)
        analysis.clear_diagnostics()
        dispatch.clear_cache()

    return {
        "metric": "analysis_off_hit_ns",
        "value": round(off_hit, 1),
        "unit": "ns",
        "vs_baseline": round(on_miss / off_miss, 2) if off_miss else 0.0,
        "vs_baseline_kind": "warn_mode_miss_vs_off_mode_miss",
        "analyze_off_hit_ns": round(off_hit, 1),
        "analyze_off_miss_us": round(off_miss, 2),
        "analyze_on_miss_ms": round(on_miss / 1e3, 3),
        "warn_mode_diags": diags,
        "analyzer_mode_default": diagnostics.analysis_mode(),
    }


def bench_streaming(ht, sync_floor, roofline=None):
    """Config 12b: streaming continuous learning (ISSUE 17).

    Two operational numbers.  **Sustained ingest** — a producer thread
    appends to a durable :class:`FileSegmentLog` while a streaming
    KMeans consumes full windows through the prefetched consumer with
    exactly-once offset commits riding every 8th window; reported MB/s
    is bytes folded into the model over the whole concurrent run
    (append + atomic segment commits + checksum-verified reads + device
    staging + minibatch update + offset checkpoints, end to end).
    **Model staleness** — how stale a served model gets before the
    continuous-learning loop replaces it: covariate drift is injected
    under live traffic and the clock runs from the first drifted batch
    served to the refreshed canary auto-promoting (drift detection +
    online re-fit + save with fresh baseline + shadow compare + promote).
    """
    import os
    import shutil
    import tempfile
    import threading

    from heat_tpu import serving as srv
    from heat_tpu.serving import canary as cnry
    from heat_tpu.streaming import FileSegmentLog, RefreshDriver, StreamingKMeans
    from heat_tpu.telemetry import alerts as _al
    from heat_tpu.telemetry import sketch as _sk

    # -- sustained ingest ------------------------------------------------
    window, feat, n_windows = 256, 16, 160
    total_bytes = n_windows * window * feat * 4
    d = tempfile.mkdtemp(prefix="heat_tpu_bench_streaming_")
    try:
        log = FileSegmentLog(os.path.join(d, "log"), segment_rows=2048)

        def produce():
            rng = np.random.default_rng(0)
            for _ in range(n_windows // 8):
                log.append(rng.standard_normal((window * 8, feat)).astype(np.float32))

        producer = threading.Thread(target=produce, daemon=True)
        ck = os.path.join(d, "ck")
        km = StreamingKMeans(n_clusters=8, window_rows=window, commit_every=8,
                             checkpoint_dir=ck, resume_from=ck)
        t0 = time.perf_counter()
        producer.start()
        while log.size < window:
            time.sleep(0.001)  # seed window: the init state peeks it
        while km.n_windows_ < n_windows:  # dry head pauses the fit; resume it
            before = km.n_windows_
            km.fit_stream(log, max_windows=n_windows)
            if km.n_windows_ == before:
                time.sleep(0.001)  # producer hasn't landed a full window yet
        ingest_s = time.perf_counter() - t0
        producer.join(timeout=30)
        ingest_mbs = total_bytes / 1e6 / ingest_s

        # -- model staleness ---------------------------------------------
        centers = np.array([[0.0] * feat, [40.0] * feat, [80.0] * feat], np.float32)

        def rows_of(n, rng, shift=0.0):
            labels = np.arange(n) % 3
            return (centers[labels]
                    + rng.standard_normal((n, feat)).astype(np.float32) * 0.5
                    + np.float32(shift)).astype(np.float32)

        log2 = FileSegmentLog(os.path.join(d, "log2"), segment_rows=1024)
        log2.append(rows_of(64 * 8, np.random.default_rng(1)))
        ck2 = os.path.join(d, "ck2")
        km2 = StreamingKMeans(n_clusters=3, window_rows=64, commit_every=1,
                              checkpoint_dir=ck2, resume_from=ck2)
        km2.fit_stream(log2)
        sk = _sk.ModelSketch("stream_km", feat)
        sk.update(km2.recent_window_)
        md = os.path.join(d, "models")
        srv.save_model(km2.to_estimator(), md, version=1, name="stream_km",
                       baseline=sk.doc())
        svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
        svc.load("stream_km", md, version=1)
        svc.canary.fraction = 1.0
        svc.canary.min_rows = 48

        def fitter():
            log2.append(rows_of(64 * 4, np.random.default_rng(2), shift=4.0))
            fresh = StreamingKMeans(n_clusters=3, window_rows=64, commit_every=1,
                                    checkpoint_dir=ck2, resume_from=ck2)
            return fresh.fit_stream(log2)

        drv = RefreshDriver(svc, "stream_km", md, fitter)
        rng = np.random.default_rng(9)
        t1 = time.perf_counter()
        deadline = t1 + 120.0
        refreshed_at = None
        while time.perf_counter() < deadline:
            svc.predict("stream_km", rows_of(8, rng, shift=4.0))
            out = drv.check()
            if out == "refreshed" and refreshed_at is None:
                refreshed_at = time.perf_counter() - t1
            if svc.registry.active_version("stream_km") == 2:
                break
        staleness_s = time.perf_counter() - t1
        promoted = svc.registry.active_version("stream_km") == 2
        svc.close()
        return {
            "metric": "streaming_ingest_mbs",
            "value": round(ingest_mbs, 2),
            "unit": "MB/s",
            "vs_baseline": 0.0,
            "vs_baseline_kind": "durable_log_to_model_sustained",
            "ingest_windows": n_windows,
            "ingest_bytes": total_bytes,
            "ingest_s": round(ingest_s, 3),
            "staleness_s": round(staleness_s, 3),
            "refresh_s": round(refreshed_at, 3) if refreshed_at is not None else None,
            "staleness_promoted": promoted,
        }
    finally:
        cnry.reset_canary_state()
        _al.clear_alerts()
        _sk.SKETCHES.clear()
        shutil.rmtree(d, ignore_errors=True)


def bench_qos(ht, sync_floor, roofline=None):
    """Config 13: multi-tenant QoS scheduling (ISSUE 18).

    A latency-class tenant's small-request stream is measured solo and
    then again with four batch-class clients flooding 64-row requests
    through the same service — the strict-priority depth gate plus the
    EDF batch pick must keep the latency tail pinned near its solo
    shape while the batch lane absorbs the shedding.  Reported: solo
    and contended latency p50/p99, the noisy-neighbor p99 inflation
    (``vs_baseline`` = contended p99 / solo p99 — the number the
    ``qos_noisy_neighbor`` CI gate caps at 1.10), latency-class sheds
    (must be 0), batch-lane admit/shed traffic, per-lane depth
    surfaces, and the per-tenant cost accounts folded by the request
    stream (``/tenantz``: the accounts must sum to the service total).
    """
    import shutil
    import tempfile
    import threading

    from heat_tpu import serving as srv
    from heat_tpu.resilience import OverloadedError
    from heat_tpu.telemetry import tenants as ttenants

    rng = np.random.default_rng(18)
    pts = rng.standard_normal((1 << 12, 16)).astype(np.float32)
    x = ht.array(pts, split=0)
    km = ht.cluster.KMeans(n_clusters=8, init="random", max_iter=5, random_state=0).fit(x)

    d = tempfile.mkdtemp(prefix="heat_tpu_bench_qos_")
    svc = None
    try:
        ttenants.reset()
        srv.save_model(km, d, version=1, name="km")
        svc = srv.InferenceService(max_delay_ms=1.0, max_batch=64)
        svc.load("km", d)
        svc.set_class("slo", "latency")
        svc.set_class("bulk", "batch")
        for b in (1, 2, 4, 8, 16, 32, 64):  # warm every bucket
            svc.predict("km", pts[:b])

        sizes = (1, 3, 7, 12)  # the latency-class small-request mix
        sheds = {"latency": 0, "batch_ok": 0, "batch_shed": 0}

        def lat_stream(n=200):
            lat = []
            for i in range(n):
                t0 = time.perf_counter()
                try:
                    svc.predict("km", pts[: sizes[i % len(sizes)]],
                                tenant="slo", timeout=30)
                except OverloadedError:
                    sheds["latency"] += 1
                    continue
                lat.append(time.perf_counter() - t0)
            return np.sort(np.asarray(lat))

        solo = lat_stream()

        stop = threading.Event()

        def bulk():
            while not stop.is_set():
                try:
                    svc.predict("km", pts[:64], tenant="bulk", timeout=30)
                    sheds["batch_ok"] += 1
                except OverloadedError as e:
                    # honor the lane-aware Retry-After hint (a batch
                    # client hammering a full lane measures its own
                    # retry storm, not the scheduler)
                    sheds["batch_shed"] += 1
                    time.sleep(min(max(e.retry_after_s or 0.01, 0.005), 0.05))

        floods = [threading.Thread(target=bulk, name=f"bench-qos-bulk-{i}",
                                   daemon=True) for i in range(4)]
        for t in floods:
            t.start()
        time.sleep(0.1)  # flood to steady state
        contended = lat_stream()
        lanes = svc.admission.lane_depths()
        stop.set()
        for t in floods:
            t.join()

        # drain the account hook (it fires on the batcher thread after
        # callers wake), then read the per-tenant cost ledger
        deadline = time.time() + 5.0
        rep = ttenants.tenantz_report()
        while time.time() < deadline:
            rep = ttenants.tenantz_report()
            by = {(r["tenant"], r["class"]) for r in rep["tenants"]}
            if ("slo", "latency") in by and ("bulk", "batch") in by:
                break
            time.sleep(0.01)
        acct_rows = sum(r["rows"] for r in rep["tenants"])
        solo_p99 = float(solo[int(len(solo) * 0.99)])
        cont_p99 = float(contended[int(len(contended) * 0.99)])
        return {
            "metric": "qos_latency_p99_ms",
            "value": round(cont_p99 * 1e3, 3),
            "unit": "ms",
            "vs_baseline": round(cont_p99 / solo_p99, 3) if solo_p99 else 0.0,
            "vs_baseline_kind": "same_stream_solo_no_batch_flood",
            "solo_p50_ms": round(float(solo[len(solo) // 2]) * 1e3, 3),
            "solo_p99_ms": round(solo_p99 * 1e3, 3),
            "contended_p50_ms": round(float(contended[len(contended) // 2]) * 1e3, 3),
            "contended_p99_ms": round(cont_p99 * 1e3, 3),
            "latency_shed": sheds["latency"],
            "batch_admitted": sheds["batch_ok"],
            "batch_shed": sheds["batch_shed"],
            "lane_limits": {c: lanes[c]["limit"] for c in lanes},
            "tenant_accounts": {
                f"{r['tenant']}/{r['class']}": r["rows"] for r in rep["tenants"]
            },
            "accounts_rows_total": acct_rows,
            "accounts_match_total": acct_rows == rep["total"]["rows"],
        }
    finally:
        if svc is not None:
            svc.close()
        ttenants.reset()
        shutil.rmtree(d, ignore_errors=True)


def main() -> None:
    import heat_tpu as ht

    sync_floor = _sync_floor()
    results = []
    try:
        roofline = bench_roofline(ht, sync_floor)
        results.append(roofline)
        print(json.dumps(roofline), flush=True)
    except Exception as e:  # anchors are advisory; keep the grid going
        roofline = None
        print(json.dumps({"metric": "roofline", "error": f"{type(e).__name__}: {e}"[:200]}), flush=True)
    for bench in (bench_smoke, bench_kmeans, bench_hsvd, bench_dpsgd, bench_fft3d,
                  bench_dispatch, bench_resilience, bench_overlap, bench_telemetry,
                  bench_analysis, bench_serving, bench_canary, bench_streaming,
                  bench_qos, bench_fleet):
        try:
            r = bench(ht, sync_floor, roofline)
            r.setdefault("vs_baseline_kind", BASELINE_KIND)
        except Exception as e:  # record the failure, keep the grid going
            r = {
                "metric": bench.__name__,
                "value": -1,
                "unit": "error",
                "vs_baseline": 0.0,
                "error": f"{type(e).__name__}: {e}"[:200],
            }
        # every config embeds the registry state at its end: the bench
        # artifact doubles as a telemetry regression record (comm bytes,
        # compile time, cache traffic per config)
        r["telemetry"] = ht.telemetry.snapshot(include_zero=False)
        results.append(r)
        print(json.dumps(r), flush=True)

    headline = next(r for r in results if r["metric"].startswith("hsvd"))
    summary = dict(headline)
    summary["all"] = results
    print(json.dumps(summary), flush=True)


if __name__ == "__main__":
    main()
