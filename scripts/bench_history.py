"""Perf-trajectory history: make the gate metrics visible BETWEEN runs.

``perf_gate.py`` answers "did this run regress vs the committed
record?"; nothing answered "how has sort_psrs moved over the last ten
PRs?" — the trajectory was invisible because every BENCH_CI regeneration
overwrites the previous one.  This script appends each BENCH_CI run's
headline gate numbers to ``BENCH_HISTORY.jsonl`` (one JSON record per
run, written through the resilience atomic+CRC32 writer so the log can
never tear) and renders the trend into ``docs/perf_history.md``:

    python scripts/perf_ci.py > BENCH_CI.json      # (CI does this)
    python scripts/bench_history.py                # append + render

Appends are idempotent: re-running against an unchanged BENCH_CI.json
(same metrics) is a no-op, so the history records *runs*, not
invocations.  Each record carries the run's git revision and UTC
timestamp.
"""

import argparse
import datetime
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: how many trailing runs the rendered markdown table shows per metric
SHOWN_RUNS = 8


def _git_rev() -> str:
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            cwd=REPO, capture_output=True, text=True, timeout=10,
        ).stdout.strip() or "?"
    except Exception:  # lint: allow H501(history works outside a git checkout)
        return "?"


def headline(rec: dict):
    """One number per gate metric — the quantity its gate kind watches:
    anchored kernels report ``rel_to_anchor``, overhead gates
    ``overhead_pct``, latency caps ``seconds``, count caps ``count``,
    anchors their ``value``; broken kernels record ``None``."""
    if not isinstance(rec, dict):
        return None
    for key in ("rel_to_anchor", "overhead_pct", "count", "value", "seconds"):
        if key in rec:
            return rec[key]
    return None  # error entry


def extract_record(bench: dict, rev: str, timestamp: str) -> dict:
    return {
        "recorded_at": timestamp,
        "git_rev": rev,
        "metrics": {
            name: headline(rec)
            for name, rec in sorted(bench.items())
            if isinstance(rec, dict)
        },
    }


def load_history(path: str) -> list:
    """Checksum-verified history records (empty when no log yet)."""
    from heat_tpu.resilience.atomic import verify_checksum

    if not os.path.exists(path):
        return []
    verify_checksum(path)
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                records.append(json.loads(line))
    return records


def append_history(path: str, record: dict) -> bool:
    """Append one run record (atomic rewrite + CRC sidecar); returns
    False when the last record already carries identical metrics (an
    idempotent re-run against the same BENCH_CI.json)."""
    from heat_tpu.resilience.atomic import atomic_write

    records = load_history(path)
    if records and records[-1].get("metrics") == record["metrics"]:
        return False
    records.append(record)
    with atomic_write(path) as tmp:
        with open(tmp, "w") as f:
            for rec in records:
                f.write(json.dumps(rec, sort_keys=True) + "\n")
    return True


def _fmt(v) -> str:
    if v is None:
        return "—"
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def render_markdown(records: list, out_path: str) -> None:
    """One row per gate metric, one column per trailing run (newest
    right), plus the latest-vs-previous delta."""
    shown = records[-SHOWN_RUNS:]
    names = sorted({n for r in shown for n in r.get("metrics", {})})
    lines = [
        "# Perf history",
        "",
        "Generated from `BENCH_HISTORY.jsonl` by `scripts/bench_history.py`"
        " — do not edit.  Each column is one BENCH_CI regeneration (the"
        " headline number of every gate metric: anchored ratio, overhead %,"
        " seconds, or count — see the gate kinds in `scripts/perf_gate.py`);"
        " `Δ` compares the two newest runs.",
        "",
        f"{len(records)} run(s) recorded; showing the last {len(shown)}.",
        "",
    ]
    header = ["metric"] + [
        f"{r.get('git_rev', '?')}<br>{str(r.get('recorded_at', '?'))[:10]}"
        for r in shown
    ] + ["Δ"]
    lines.append("| " + " | ".join(header) + " |")
    lines.append("|" + "---|" * len(header))
    for name in names:
        vals = [r.get("metrics", {}).get(name) for r in shown]
        delta = "—"
        nums = [v for v in vals if isinstance(v, (int, float))]
        if len(nums) >= 2 and isinstance(vals[-1], (int, float)):
            prev = next(
                (v for v in reversed(vals[:-1]) if isinstance(v, (int, float))), None
            )
            if prev is not None:
                d = vals[-1] - prev
                delta = f"{d:+.4g}" + (
                    f" ({100.0 * d / prev:+.1f}%)" if prev else ""
                )
        lines.append(
            "| `" + name + "` | " + " | ".join(_fmt(v) for v in vals)
            + f" | {delta} |"
        )
    lines += [
        "",
        "See also: [observability](observability.md), the committed gate"
        " record `BENCH_CI.json`, and `scripts/perf_gate.py` for the"
        " regression rules.",
        "",
    ]
    with open(out_path, "w") as f:
        f.write("\n".join(lines))


def main():
    ap = argparse.ArgumentParser(description=__doc__.split("\n", 1)[0])
    ap.add_argument("--bench", default=os.path.join(REPO, "BENCH_CI.json"))
    ap.add_argument("--history", default=os.path.join(REPO, "BENCH_HISTORY.jsonl"))
    ap.add_argument("--out", default=os.path.join(REPO, "docs", "perf_history.md"))
    ap.add_argument(
        "--render-only", action="store_true",
        help="re-render the markdown from the existing history, no append",
    )
    args = ap.parse_args()

    if not args.render_only:
        with open(args.bench) as f:
            bench = json.load(f)
        record = extract_record(
            bench,
            rev=_git_rev(),
            timestamp=datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"
            ),
        )
        if append_history(args.history, record):
            print(f"appended run {record['git_rev']} -> {args.history}")
        else:
            print("history unchanged (same metrics as the last record)")

    records = load_history(args.history)
    render_markdown(records, args.out)
    print(f"rendered {len(records)} run(s) -> {args.out}")


if __name__ == "__main__":
    main()
