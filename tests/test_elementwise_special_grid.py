"""Special-value width for the elementwise families: the analog of the
reference's test_trigonometrics.py / test_exponential.py /
test_rounding.py / test_logical.py special-case batteries — inf/nan/-0.0
propagation, domain edges, degree-radian conversions, logaddexp
stability, clip/round option grids, nan_to_num replacement grids —
table-compressed against numpy ground truth on the virtual mesh.
Complements tests/test_arithmetics_grid.py (finite-value op grids).
"""

import numpy as np
import pytest

import heat_tpu as ht

SPLITS = [None, 0]

SPECIAL = np.array(
    [0.0, -0.0, 1.0, -1.0, 0.5, -0.5, np.inf, -np.inf, np.nan, 1e30, -1e30],
    np.float32,
)


def _cmp(name, got, want, rtol=1e-5):
    np.testing.assert_allclose(
        got, want, rtol=rtol, atol=1e-6, equal_nan=True, err_msg=name
    )


# ------------------------------------------------------- trig special values

@pytest.mark.parametrize("split", SPLITS)
def test_trig_special_value_grid(split):
    x = ht.array(SPECIAL, split=split)
    with np.errstate(all="ignore"):
        for name in ("sin", "cos", "tan", "arcsin", "arccos", "arctan",
                     "sinh", "cosh", "tanh", "arcsinh", "arctanh"):
            _cmp(name, getattr(ht, name)(x).numpy(), getattr(np, name)(SPECIAL))
        # arccosh domain is [1, inf)
        dom = np.abs(SPECIAL) + 1.0
        _cmp("arccosh", ht.arccosh(ht.array(dom, split=split)).numpy(), np.arccosh(dom))


@pytest.mark.parametrize("split", SPLITS)
def test_degree_radian_conversions(split):
    deg = np.array([0.0, 30, 45, 90, 180, 270, 360, -90, 720], np.float32)
    x = ht.array(deg, split=split)
    _cmp("deg2rad", ht.deg2rad(x).numpy(), np.deg2rad(deg))
    _cmp("radians", ht.radians(x).numpy(), np.radians(deg))
    rad = np.deg2rad(deg)
    y = ht.array(rad, split=split)
    _cmp("rad2deg", ht.rad2deg(y).numpy(), np.rad2deg(rad))
    _cmp("degrees", ht.degrees(y).numpy(), np.degrees(rad))
    # round trip
    _cmp("roundtrip", ht.rad2deg(ht.deg2rad(x)).numpy(), deg, rtol=1e-5)


@pytest.mark.parametrize("split", SPLITS)
def test_arctan2_quadrant_grid(split):
    ys = np.array([1.0, 1.0, -1.0, -1.0, 0.0, 0.0, 1.0, -1.0], np.float32)
    xs = np.array([1.0, -1.0, 1.0, -1.0, 1.0, -1.0, 0.0, 0.0], np.float32)
    got = ht.arctan2(ht.array(ys, split=split), ht.array(xs, split=split))
    _cmp("arctan2", got.numpy(), np.arctan2(ys, xs))


# ------------------------------------------------ exponential special values

@pytest.mark.parametrize("split", SPLITS)
def test_exponential_special_value_grid(split):
    x = ht.array(SPECIAL, split=split)
    with np.errstate(all="ignore"):
        for name in ("exp", "expm1", "exp2", "sqrt", "square", "log",
                     "log2", "log10", "log1p"):
            _cmp(name, getattr(ht, name)(x).numpy(), getattr(np, name)(SPECIAL))


@pytest.mark.parametrize("split", SPLITS)
@pytest.mark.parametrize("name", ["logaddexp", "logaddexp2"])
def test_logaddexp_stability(split, name):
    # the naive exp-sum-log overflows on these; the stable form must not
    a = np.array([1000.0, -1000.0, 0.0, 88.0, -88.0], np.float32)
    b = np.array([1000.0, -999.0, 0.5, 87.0, -89.0], np.float32)
    got = getattr(ht, name)(ht.array(a, split=split), ht.array(b, split=split))
    _cmp(name, got.numpy(), getattr(np, name)(a, b), rtol=1e-5)
    assert np.isfinite(got.numpy()).all()


# ---------------------------------------------------- rounding option grids

@pytest.mark.parametrize("split", SPLITS)
def test_round_decimals_grid(split):
    vals = np.array([1.25, -1.25, 2.5, -2.5, 0.125, 123.456, -0.0005], np.float32)
    x = ht.array(vals, split=split)
    for dec in (0, 1, 2, -1, -2):
        _cmp(f"round({dec})", ht.round(x, decimals=dec).numpy(), np.round(vals, dec))


@pytest.mark.parametrize("split", SPLITS)
def test_floor_ceil_trunc_special(split):
    x = ht.array(SPECIAL, split=split)
    for name in ("floor", "ceil", "trunc"):
        _cmp(name, getattr(ht, name)(x).numpy(), getattr(np, name)(SPECIAL))
    # negative-zero signbit must survive trunc/floor of -0.0
    neg0 = ht.array(np.array([-0.0], np.float32), split=None)
    assert np.signbit(ht.trunc(neg0).numpy())[0]


@pytest.mark.parametrize("split", SPLITS)
def test_clip_variant_grid(split):
    vals = np.linspace(-5, 5, 11).astype(np.float32)
    x = ht.array(vals, split=split)
    for lo, hi in ((-2, 2), (None, 1.5), (-1.5, None), (0, 0)):
        got = ht.clip(x, lo, hi).numpy()
        _cmp(f"clip({lo},{hi})", got, np.clip(vals, lo, hi))
    with pytest.raises((ValueError, TypeError)):
        ht.clip(x, None, None)


@pytest.mark.parametrize("split", SPLITS)
def test_modf_frexp_roundtrip(split):
    vals = np.array([1.5, -2.25, 0.0, 3.75, -0.5, 1024.5], np.float32)
    x = ht.array(vals, split=split)
    frac, integ = ht.modf(x)
    nfrac, ninteg = np.modf(vals)
    _cmp("modf frac", frac.numpy(), nfrac)
    _cmp("modf int", integ.numpy(), ninteg)
    mant, expo = ht.frexp(x)
    _cmp("frexp recompose", mant.numpy() * np.exp2(expo.numpy().astype(np.float32)), vals)
    _cmp("ldexp", ht.ldexp(mant, expo).numpy(), vals)


@pytest.mark.parametrize("split", SPLITS)
def test_sign_sgn_abs_fabs(split):
    x = ht.array(SPECIAL, split=split)
    _cmp("sign", ht.sign(x).numpy(), np.sign(SPECIAL))
    _cmp("fabs", ht.fabs(x).numpy(), np.fabs(SPECIAL))
    _cmp("abs", ht.abs(x).numpy(), np.abs(SPECIAL))
    ints = np.array([-3, 0, 7], np.int32)
    np.testing.assert_array_equal(ht.sign(ht.array(ints, split=None)).numpy(), np.sign(ints))


# ------------------------------------------------------ logical / inf / nan

@pytest.mark.parametrize("split", SPLITS)
def test_inf_nan_predicates_grid(split):
    x = ht.array(SPECIAL, split=split)
    for name in ("isfinite", "isinf", "isnan", "isneginf", "isposinf", "signbit"):
        np.testing.assert_array_equal(
            getattr(ht, name)(x).numpy(), getattr(np, name)(SPECIAL), err_msg=name
        )


@pytest.mark.parametrize("split", SPLITS)
def test_nan_to_num_replacement_grid(split):
    x = ht.array(SPECIAL, split=split)
    _cmp("default", ht.nan_to_num(x).numpy(), np.nan_to_num(SPECIAL))
    got = ht.nan_to_num(x, nan=-1.0, posinf=99.0, neginf=-99.0).numpy()
    _cmp("custom", got, np.nan_to_num(SPECIAL, nan=-1.0, posinf=99.0, neginf=-99.0))


@pytest.mark.parametrize("split", SPLITS)
def test_logical_ops_with_nan_operands(split):
    # nan is truthy in logical context, exactly as numpy treats it
    a = np.array([0.0, 1.0, np.nan, np.inf, -0.0], np.float32)
    b = np.array([np.nan, 0.0, np.nan, 0.0, 1.0], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    for name in ("logical_and", "logical_or", "logical_xor"):
        np.testing.assert_array_equal(
            getattr(ht, name)(ha, hb).numpy(), getattr(np, name)(a, b), err_msg=name
        )
    np.testing.assert_array_equal(ht.logical_not(ha).numpy(), np.logical_not(a))


@pytest.mark.parametrize("split", SPLITS)
def test_isclose_allclose_nan_inf_modes(split):
    a = np.array([1.0, np.nan, np.inf, -np.inf, 1.0 + 1e-9], np.float32)
    b = np.array([1.0, np.nan, np.inf, np.inf, 1.0], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    np.testing.assert_array_equal(
        ht.isclose(ha, hb).numpy(), np.isclose(a, b))
    np.testing.assert_array_equal(
        ht.isclose(ha, hb, equal_nan=True).numpy(), np.isclose(a, b, equal_nan=True))
    assert not ht.allclose(ha, hb)
    assert bool(ht.allclose(ha, ha, equal_nan=True))


# ------------------------------------------------------- fmin/fmax vs nan

@pytest.mark.parametrize("split", SPLITS)
def test_fmin_fmax_nan_semantics(split):
    a = np.array([1.0, np.nan, 3.0, np.nan], np.float32)
    b = np.array([2.0, 2.0, np.nan, np.nan], np.float32)
    ha, hb = ht.array(a, split=split), ht.array(b, split=split)
    # fmin/fmax ignore a single nan; minimum/maximum propagate it
    _cmp("fmin", ht.fmin(ha, hb).numpy(), np.fmin(a, b))
    _cmp("fmax", ht.fmax(ha, hb).numpy(), np.fmax(a, b))
    _cmp("minimum", ht.minimum(ha, hb).numpy(), np.minimum(a, b))
    _cmp("maximum", ht.maximum(ha, hb).numpy(), np.maximum(a, b))


@pytest.mark.parametrize("split", SPLITS)
def test_misc_special_functions(split):
    vals = np.array([0.0, 0.5, -0.5, 2.0, -3.5], np.float32)
    x = ht.array(vals, split=split)
    _cmp("sinc", ht.sinc(x).numpy(), np.sinc(vals))
    _cmp("i0", ht.i0(x).numpy(), np.i0(vals), rtol=1e-4)
    h = np.array([0.5], np.float32)
    _cmp(
        "heaviside",
        ht.heaviside(x, ht.array(h, split=None)).numpy(),
        np.heaviside(vals, h),
    )
    _cmp("nextafter", ht.nextafter(x, ht.array(np.ones_like(vals), split=split)).numpy(),
         np.nextafter(vals, 1.0))
    _cmp("spacing", ht.spacing(x).numpy(), np.spacing(vals), rtol=1e-4)
