"""Control-plane decision journal: every autonomous action, explainable.

The framework runs eight autonomous control loops — the fleet
autoscaler, canary auto-promote/rollback, the drift refresh driver, QoS
preemption, the router's circuit breakers, elastic reshape, key-drift
resharding, and SLO/drift alerting — and before this module each kept
its own volatile ring (``/canaryz`` events, ``/sloz`` transitions,
autoscaler ``_last_decision``) with no causal links, no durability
across restart, and no shared timeline.  A postmortem for "why did the
canary roll back while a fit got preempted at 12:03" meant hand-
stitching six endpoints before their rings rotated.

This module is the one sink every controller reports into:

* a typed :class:`DecisionEvent` — event_id, wall + monotonic
  timestamps, the **actor** (which controller) and **action** (what it
  did), the model/tenant it acted on, an optional **cause** event_id
  (the upstream decision that triggered this one), the nearest exemplar
  ``trace_id``, and an **evidence** dict carrying the exact metric
  values the controller saw (plus, when the TSDB sampler is armed, the
  ``series`` names whose samples are resolvable via ``/queryz``);
* a bounded **hot ring** (``HEAT_TPU_JOURNAL_RING``) serving the live
  ``/decisionz`` endpoint, cross-replica snapshots and crash bundles;
* a **durable append-only segment log** (``HEAT_TPU_JOURNAL_DIR``)
  following the streaming layer's ``FileSegmentLog`` machinery
  (:mod:`heat_tpu.streaming.source`): immutable
  ``journal-<start:012d>-<count:08d>.jsonl`` segments committed by
  atomic rename with CRC32 sidecars, the start offset resumed from the
  committed filenames — so a restarted process appends after its
  predecessor and ``python -m heat_tpu.telemetry.replay <dir>``
  reconstructs the full incident timeline from the directory alone.

``/decisionz`` renders the timeline (HTML, ``?format=json`` for the
machine form) and ``?event_id=<id>`` walks the cause links both ways —
the "explain" view: the root evidence above, the consequences below.

Thread-safety: controllers emit from their own threads (SLO tick,
shadow thread, router poller, fit threads) while ``/decisionz`` handler
threads read — every structure below is only touched under the
registered ``telemetry.journal`` lock; the durable segment write runs
under it too (control-plane decision rates are a few events per
incident, not a hot path — the same trade the streaming segment log
makes).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..analysis import conformance as _conformance
from ..analysis import tsan as _tsan
from . import metrics as _metrics

__all__ = [
    "DecisionEvent",
    "causal_chain",
    "decisionz_report",
    "emit",
    "find_last",
    "get_event",
    "journal_dir",
    "journal_events",
    "journal_snapshot",
    "merge_journal_snapshots",
    "read_journal",
    "refresh_env",
    "render_decisionz_html",
    "reset_journal",
    "set_journal_dir",
]

# knobs ARE registered in core/_env.py KNOBS; read directly because this
# module loads at `heat_tpu.telemetry` import, before core._env is safe
_RING_SIZE = int(os.environ.get("HEAT_TPU_JOURNAL_RING", "256"))
_DIR: Optional[str] = os.environ.get("HEAT_TPU_JOURNAL_DIR") or None

_EMITTED_C = _metrics.counter("journal.events", "decision-journal events emitted")
_SEGMENTS_C = _metrics.counter(
    "journal.segments_written", "durable decision-journal segments committed"
)

#: durable segment names: ``journal-<start seq:012d>-<count:08d>.jsonl``
#: (the streaming segment-log naming scheme; the committed filenames ARE
#: the index, so a fresh process derives the next sequence number from a
#: directory listing alone)
_SEGMENT_RE = re.compile(r"^journal-(\d{12})-(\d{8})\.jsonl$")


class DecisionEvent:
    """One autonomous control-plane decision, causally linkable.

    ``event_id`` is unique across restarts and replicas (process epoch +
    sequence); ``cause`` is the ``event_id`` of the upstream decision
    that triggered this one (None for a root event); ``evidence`` holds
    the exact metric values the controller saw when it decided —
    including, by convention, a ``series`` list naming the TSDB series
    whose samples are resolvable via ``/queryz``."""

    __slots__ = ("event_id", "seq", "ts", "mono", "actor", "action", "model",
                 "tenant", "severity", "message", "cause", "trace_id",
                 "evidence")

    def __init__(self, event_id: str, seq: int, ts: float, mono: float,
                 actor: str, action: str, model: Optional[str],
                 tenant: Optional[str], severity: str, message: str,
                 cause: Optional[str], trace_id: Optional[str],
                 evidence: Dict[str, Any]):
        self.event_id = event_id
        self.seq = seq
        self.ts = ts
        self.mono = mono
        self.actor = actor
        self.action = action
        self.model = model
        self.tenant = tenant
        self.severity = severity
        self.message = message
        self.cause = cause
        self.trace_id = trace_id
        self.evidence = evidence

    def doc(self) -> Dict[str, Any]:
        return {
            "event_id": self.event_id,
            "seq": self.seq,
            "ts": self.ts,
            "mono": self.mono,
            "actor": self.actor,
            "action": self.action,
            "model": self.model,
            "tenant": self.tenant,
            "severity": self.severity,
            "message": self.message,
            "cause": self.cause,
            "trace_id": self.trace_id,
            "evidence": self.evidence,
        }


#: hot ring + durable-writer cursor, both under the registered lock.
#: The process epoch makes event_ids unique across restarts sharing one
#: journal directory (replay merges incarnations by event_id).
_LOCK = _tsan.register_lock("telemetry.journal")
_EVENTS: "deque[DecisionEvent]" = deque(maxlen=max(1, _RING_SIZE))
_EPOCH = f"{os.getpid():x}-{int(time.time() * 1000):x}"
_SEQ = 0
_NEXT_START: Optional[int] = None  # durable seq cursor; None = dir not scanned


def refresh_env() -> None:
    """Re-read ``HEAT_TPU_JOURNAL_RING`` / ``HEAT_TPU_JOURNAL_DIR``
    (tests that flip the env mid-process); resizes the hot ring keeping
    the newest events and re-anchors the durable writer."""
    global _RING_SIZE, _EVENTS, _DIR, _NEXT_START
    _RING_SIZE = int(os.environ.get("HEAT_TPU_JOURNAL_RING", "256"))
    with _LOCK:
        _tsan.note_access("telemetry.journal.state")
        _EVENTS = deque(_EVENTS, maxlen=max(1, _RING_SIZE))
        _DIR = os.environ.get("HEAT_TPU_JOURNAL_DIR") or None
        _NEXT_START = None


def set_journal_dir(directory: Optional[str]) -> None:
    """Arm (or disarm, with None) the durable journal programmatically —
    the non-env path tests and embedding services use."""
    global _DIR, _NEXT_START
    with _LOCK:
        _tsan.note_access("telemetry.journal.state")
        _DIR = str(directory) if directory else None
        _NEXT_START = None


def journal_dir() -> Optional[str]:
    """The armed durable-journal directory (None = hot ring only)."""
    with _LOCK:
        _tsan.note_access("telemetry.journal.state", write=False)
        return _DIR


def reset_journal() -> None:
    """Drop the hot ring and re-anchor the durable cursor (tests).  The
    durable directory's committed segments are never deleted — they are
    the record."""
    global _SEQ, _NEXT_START
    with _LOCK:
        _tsan.note_access("telemetry.journal.state")
        _EVENTS.clear()
        _SEQ = 0
        _NEXT_START = None
    # a fresh journal means fresh controllers: the protocol conformance
    # checker forgets its tracked machine instances too (outside our
    # lock — it takes its own leaf lock)
    _conformance.reset_conformance()


def _scan_next_start_locked(directory: str) -> int:
    """Next durable sequence number: end offset derived from the
    committed segment filenames (caller holds the lock)."""
    end = 0
    try:
        names = os.listdir(directory)
    except OSError:
        return 0
    for name in names:
        m = _SEGMENT_RE.match(name)
        if m:
            end = max(end, int(m.group(1)) + int(m.group(2)))
    return end


def _append_durable_locked(ev: DecisionEvent) -> None:
    """Commit one event as an immutable single-event segment (caller
    holds the lock).  Atomic rename + CRC sidecar via the resilience
    writer — a reader (or the replay CLI) can observe a committed
    segment or nothing, never a torn line."""
    global _NEXT_START
    directory = _DIR
    if not directory:
        return
    # lazy import: resilience imports telemetry.metrics at its top
    from ..resilience.atomic import atomic_write

    os.makedirs(directory, exist_ok=True)
    if _NEXT_START is None:
        _NEXT_START = _scan_next_start_locked(directory)
    path = os.path.join(
        directory, f"journal-{_NEXT_START:012d}-{1:08d}.jsonl"
    )
    with atomic_write(path, fault_site="io.write") as tmp:
        with open(tmp, "w") as f:
            f.write(json.dumps(ev.doc(), default=str) + "\n")
    _NEXT_START += 1
    _SEGMENTS_C.inc()


def emit(
    actor: str,
    action: str,
    model: Optional[str] = None,
    tenant: Optional[str] = None,
    severity: str = "info",
    message: str = "",
    cause: Optional[str] = None,
    trace_id: Optional[str] = None,
    evidence: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Record one control-plane decision; returns its document (callers
    chain the returned ``event_id`` into downstream ``cause`` links).

    ``evidence`` must be JSON-safe — it is exactly what the controller
    saw when it decided, and it travels verbatim into the durable log,
    snapshots and crash bundles."""
    global _SEQ
    now = time.time()
    mono = time.monotonic()
    with _LOCK:
        _tsan.note_access("telemetry.journal.state")
        _SEQ += 1
        ev = DecisionEvent(
            event_id=f"{_EPOCH}-{_SEQ:06d}",
            seq=_SEQ,
            ts=now,
            mono=mono,
            actor=str(actor),
            action=str(action),
            model=model,
            tenant=tenant,
            severity=str(severity),
            message=str(message),
            cause=cause,
            trace_id=trace_id,
            evidence=dict(evidence or {}),
        )
        _EVENTS.append(ev)
        try:
            _append_durable_locked(ev)
        except Exception:  # lint: allow H501(a durable-write failure degrades to hot-ring only, never breaks the deciding controller)
            pass
    _EMITTED_C.inc()
    doc = ev.doc()
    # protocol conformance hook — one module-global read when off; runs
    # strictly after our lock is released because a violation report
    # fires an alert, which legally re-enters emit() one level deep
    _conformance.note_emit(doc)
    return doc


def journal_events(limit: Optional[int] = None) -> List[Dict[str, Any]]:
    """The hot ring, oldest first (``limit`` trims to the newest)."""
    with _LOCK:
        _tsan.note_access("telemetry.journal.state", write=False)
        events = [e.doc() for e in _EVENTS]
    return events[-limit:] if limit else events


def get_event(event_id: str) -> Optional[Dict[str, Any]]:
    """One retained event by id (hot ring only; the replay CLI covers
    the durable log)."""
    with _LOCK:
        _tsan.note_access("telemetry.journal.state", write=False)
        for e in _EVENTS:
            if e.event_id == event_id:
                return e.doc()
    return None


def find_last(
    actor: Optional[str] = None,
    action: Optional[str] = None,
    model: Optional[str] = None,
) -> Optional[Dict[str, Any]]:
    """Newest retained event matching every given field — how a
    downstream controller locates its upstream cause (e.g. the refresh
    driver finding the ``drift:<model>`` alert-fire event)."""
    with _LOCK:
        _tsan.note_access("telemetry.journal.state", write=False)
        for e in reversed(_EVENTS):
            if actor is not None and e.actor != actor:
                continue
            if action is not None and e.action != action:
                continue
            if model is not None and e.model != model:
                continue
            return e.doc()
    return None


def causal_chain(
    event_id: str,
    events: Optional[Sequence[Dict[str, Any]]] = None,
) -> Dict[str, Any]:
    """The "explain" view of one event: its cause chain walked to the
    root (oldest first) plus its direct and transitive effects.

    Pure over ``events`` when given (the replay CLI passes the durable
    log); defaults to the hot ring.  Cycles and dangling cause ids
    terminate the walk instead of looping."""
    pool = list(events) if events is not None else journal_events()
    by_id = {e.get("event_id"): e for e in pool}
    target = by_id.get(event_id)
    if target is None:
        return {"event_id": event_id, "found": False, "chain": [], "effects": []}
    chain: List[Dict[str, Any]] = [target]
    seen = {event_id}
    cur = target
    while cur.get("cause") and cur["cause"] in by_id and cur["cause"] not in seen:
        cur = by_id[cur["cause"]]
        seen.add(cur["event_id"])
        chain.insert(0, cur)
    effects: List[Dict[str, Any]] = []
    frontier = {event_id}
    while frontier:
        nxt = set()
        for e in pool:
            eid = e.get("event_id")
            if e.get("cause") in frontier and eid not in seen:
                effects.append(e)
                seen.add(eid)
                nxt.add(eid)
        frontier = nxt
    effects.sort(key=lambda e: (e.get("ts", 0.0), e.get("event_id", "")))
    return {"event_id": event_id, "found": True, "chain": chain,
            "effects": effects}


# ----------------------------------------------------------------------
# durable log readers (the replay CLI's substrate)
# ----------------------------------------------------------------------
def read_journal(directory: str) -> List[Dict[str, Any]]:
    """Every event in the durable log, checksum-verified, ordered by
    segment sequence then timestamp, deduplicated by ``event_id`` —
    the record a postmortem reads after the process is gone."""
    from ..resilience.atomic import verify_checksum

    segs: List[Tuple[int, int, str]] = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            m = _SEGMENT_RE.match(name)
            if m:
                segs.append((int(m.group(1)), int(m.group(2)),
                             os.path.join(directory, name)))
    segs.sort()
    out: List[Dict[str, Any]] = []
    seen: set = set()
    for _start, _count, path in segs:
        verify_checksum(path)
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                ev = json.loads(line)
                eid = ev.get("event_id")
                if eid in seen:
                    continue
                seen.add(eid)
                out.append(ev)
    out.sort(key=lambda e: (e.get("ts", 0.0), e.get("event_id", "")))
    return out


# ----------------------------------------------------------------------
# reports: /decisionz, snapshots, crash bundles, fleet rollup
# ----------------------------------------------------------------------
def decisionz_report(limit: Optional[int] = None) -> Dict[str, Any]:
    """The machine form of ``/decisionz``: the hot ring plus the
    durable-log arming state."""
    with _LOCK:
        _tsan.note_access("telemetry.journal.state", write=False)
        directory = _DIR
    return {
        "timestamp": time.time(),
        "ring": _RING_SIZE,
        "dir": directory,
        "events": journal_events(limit),
    }


def journal_snapshot(limit: int = 64) -> Dict[str, Any]:
    """Compact journal state for cross-worker snapshots and crash
    bundles: the newest retained events."""
    return {"ring": _RING_SIZE, "events": journal_events(limit=limit)}


def merge_journal_snapshots(
    tagged: Sequence[Tuple[str, Optional[Dict[str, Any]]]]
) -> Dict[str, Any]:
    """Fold per-worker journal snapshots into one deterministic fleet
    timeline.  ``tagged`` is ``[(worker_index, journal_snapshot_doc),
    ...]``; events interleave ordered by ``(ts, worker, event_id)`` —
    pure function of its inputs (``aggregate.merge_snapshots`` and the
    fleet router's ``/fleetz`` rollup both call it)."""
    events: List[Dict[str, Any]] = []
    actors: Dict[str, int] = {}
    for ix, snap in sorted(tagged, key=lambda t: str(t[0])):
        for e in (snap or {}).get("events") or []:
            events.append(dict(e, worker=str(ix)))
    events.sort(key=lambda e: (e.get("ts", 0.0), e.get("worker", ""),
                               e.get("event_id", "")))
    for e in events:
        actors[e.get("actor", "?")] = actors.get(e.get("actor", "?"), 0) + 1
    return {
        "events": events,
        "event_count": len(events),
        "actors": dict(sorted(actors.items())),
    }


_SEV_COLOR = {"page": "#ffd6d6", "warn": "#ffe9c6", "info": ""}


def _evidence_summary(ev: Dict[str, Any], max_len: int = 160) -> str:
    parts = []
    for k in sorted(ev.get("evidence") or {}):
        v = ev["evidence"][k]
        parts.append(f"{k}={v}")
    s = ", ".join(parts)
    return s if len(s) <= max_len else s[: max_len - 1] + "…"


def _protocol_cell(ann: Optional[Dict[str, Any]], esc) -> str:
    """One table cell describing the event's declared protocol step —
    ``protocol: from → to`` — or the H805 violation it committed."""
    if ann is None:
        return "<td>—</td>"
    if ann.get("ok"):
        return (
            f"<td>{esc(ann.get('protocol'))}: {esc(ann.get('from'))} "
            f"&rarr; {esc(ann.get('to'))}</td>"
        )
    return (
        "<td style='background:#ffd6d6'><b>H805</b> "
        f"{esc(ann.get('message'))}</td>"
    )


def _event_rows_html(
    events: List[Dict[str, Any]],
    esc,
    annotations: Optional[Dict[str, Dict[str, Any]]] = None,
) -> List[str]:
    proto_th = "<th>protocol</th>" if annotations is not None else ""
    parts = [
        "<table><tr><th>ts</th><th>actor</th><th>action</th><th>model</th>"
        "<th>sev</th><th>message</th><th>evidence</th>" + proto_th +
        "<th>cause</th><th>exemplar</th><th>event</th></tr>"
    ]
    for e in events:
        tid = e.get("trace_id")
        cause = e.get("cause")
        proto_td = (
            _protocol_cell(annotations.get(str(e.get("event_id"))), esc)
            if annotations is not None else ""
        )
        parts.append(
            f"<tr style='background:{_SEV_COLOR.get(e.get('severity'), '')}'>"
            f"<td>{esc(round(e.get('ts', 0), 3))}</td>"
            f"<td>{esc(e.get('actor'))}</td><td>{esc(e.get('action'))}</td>"
            f"<td>{esc(e.get('model') or e.get('tenant') or '—')}</td>"
            f"<td>{esc(e.get('severity'))}</td>"
            f"<td>{esc(e.get('message'))}</td>"
            f"<td>{esc(_evidence_summary(e))}</td>"
            + proto_td
            + (
                f"<td><a href='/decisionz?event_id={esc(cause)}'>{esc(cause)}</a></td>"
                if cause else "<td>—</td>"
            )
            + (
                f"<td><a href='/tracez?trace_id={esc(tid)}'>{esc(tid)}</a></td>"
                if tid else "<td>—</td>"
            )
            + f"<td><a href='/decisionz?event_id={esc(e.get('event_id'))}'>"
            f"{esc(e.get('event_id'))}</a></td></tr>"
        )
    parts.append("</table>")
    return parts


def render_decisionz_html(event_id: Optional[str] = None) -> str:
    """The human form of ``/decisionz``: the decision timeline (newest
    first, severity-tinted, cause + exemplar linked), or — with
    ``event_id`` — the causal-chain "explain" view of one decision."""
    import html as _html

    def esc(v) -> str:
        return _html.escape(str(v), quote=True)

    rep = decisionz_report()
    parts = [
        "<html><head><title>/decisionz</title><style>"
        "table{border-collapse:collapse}td,th{border:1px solid #999;"
        "padding:3px 6px;font:12px monospace}</style></head><body>",
    ]
    if event_id is not None:
        doc = causal_chain(event_id)
        parts.append(f"<h1>/decisionz — explain {esc(event_id)}</h1>")
        if not doc["found"]:
            parts.append(
                f"<p>event {esc(event_id)} is not retained in the hot ring "
                "(try the durable log: python -m heat_tpu.telemetry.replay "
                f"{esc(rep['dir'] or '<dir>')})</p>"
            )
        else:
            # the explain view annotates every event with its declared
            # protocol transition (state before → after), flagging H805
            # violations inline — stepped over the whole retained ring
            # so tracked states are right even for mid-ring events
            annotations = _conformance.annotate(rep["events"])
            parts.append(
                f"<h2>causal chain ({len(doc['chain'])} event(s), root first)</h2>"
            )
            parts.extend(_event_rows_html(doc["chain"], esc, annotations))
            parts.append(f"<h2>downstream effects ({len(doc['effects'])})</h2>")
            if doc["effects"]:
                parts.extend(_event_rows_html(doc["effects"], esc, annotations))
            else:
                parts.append("<p>(none retained)</p>")
        parts.append("<p><a href='/decisionz'>full timeline</a></p>")
    else:
        parts.append("<h1>/decisionz — control-plane decision journal</h1>")
        parts.append(
            f"<p>{len(rep['events'])} event(s) retained (ring {rep['ring']}); "
            "durable log: "
            + (esc(rep["dir"]) if rep["dir"] else
               "off (set HEAT_TPU_JOURNAL_DIR)")
            + "</p>"
        )
        if rep["events"]:
            parts.extend(_event_rows_html(list(reversed(rep["events"])), esc))
        else:
            parts.append("<p>(no decisions journaled yet)</p>")
    parts.append("</body></html>")
    return "".join(parts)
