"""Gaussian naive Bayes, analog of heat/naive_bayes/gaussianNB.py
(gaussianNB.py:13).

Per-class mean/variance come from masked global reductions over the
sharded sample axis; ``partial_fit`` keeps the reference's incremental
moment-merge update (gaussianNB.py:180+).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core import types
from ..core.base import BaseEstimator, ClassificationMixin, lazy_scalar_property
from ..core.dndarray import DNDarray

__all__ = ["GaussianNB"]


@jax.jit
def _gnb_update(xd, yd, w, cls_arr, theta, var, counts, eps_applied, var_smoothing):
    """One fused moment-merge update over ALL classes.

    The per-class Python loop this replaces dispatched ~10 eager ops per
    class (hundreds of link round-trips on a tunneled chip); here the
    class axis is a (n, c) mask matrix and the per-class sums are two
    matmuls.  Within-class variances use the global-mean-shifted data so
    E[x^2]-mu^2 stays numerically benign."""
    var_old = var - eps_applied
    mask = (yd[:, None] == cls_arr[None, :]).astype(xd.dtype) * w[:, None]  # (n, c)
    n_new = mask.sum(axis=0)  # (c,)
    safe = jnp.maximum(n_new, 1e-30)
    xbar = jnp.mean(xd, axis=0)
    xc = xd - xbar[None, :]
    mu_c = (mask.T @ xc) / safe[:, None]  # (c, f), in shifted coords
    ex2_c = (mask.T @ (xc * xc)) / safe[:, None]
    var_new = jnp.maximum(ex2_c - mu_c**2, 0.0)
    mu_new = mu_c + xbar[None, :]

    n_old = counts
    n_tot = n_old + n_new
    safe_tot = jnp.maximum(n_tot, 1e-30)
    mu_tot = (n_old[:, None] * theta + n_new[:, None] * mu_new) / safe_tot[:, None]
    # merged second moment (gaussianNB.py ~_update_mean_variance)
    ssd = (
        n_old[:, None] * var_old
        + n_new[:, None] * var_new
        + ((n_old * n_new / safe_tot)[:, None]) * (theta - mu_new) ** 2
    )
    var_tot = ssd / safe_tot[:, None]
    keep = (n_tot > 0)[:, None]
    theta_out = jnp.where(keep, mu_tot, theta)
    var_out = jnp.where(keep, var_tot, var_old)
    eps = var_smoothing * jnp.max(jnp.var(xd, axis=0))
    return theta_out, var_out + eps, n_tot, eps


class GaussianNB(BaseEstimator, ClassificationMixin):
    """Gaussian likelihood naive Bayes classifier (gaussianNB.py:13)."""

    def __init__(self, priors: Optional[DNDarray] = None, var_smoothing: float = 1e-9):
        self.priors = priors
        self.var_smoothing = var_smoothing
        self.classes_ = None
        self.theta_ = None
        self.var_ = None
        self.class_count_ = None
        self.class_prior_ = None
        self._epsilon = None

    sigma_ = property(lambda self: self.var_)  # alias kept by the reference

    # fits store the device scalar so partial_fit never blocks on the
    # link; the host conversion happens (once) on first access
    epsilon_ = lazy_scalar_property("_epsilon", float)

    def fit(self, x: DNDarray, y: DNDarray, sample_weight: Optional[DNDarray] = None) -> "GaussianNB":
        """Estimate per-class Gaussian parameters (gaussianNB.py:120)."""
        self.classes_ = None
        self.theta_ = None
        return self.partial_fit(x, y, classes=None, sample_weight=sample_weight)

    def partial_fit(
        self,
        x: DNDarray,
        y: DNDarray,
        classes: Optional[DNDarray] = None,
        sample_weight: Optional[DNDarray] = None,
    ) -> "GaussianNB":
        """Incremental fit on a batch (gaussianNB.py:180), merging moments
        with the reference's count-weighted update."""
        if not isinstance(x, DNDarray) or not isinstance(y, DNDarray):
            raise TypeError("x and y need to be DNDarrays")
        if x.ndim != 2:
            raise ValueError(f"expected x to be 2D, got {x.ndim}D")
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        yd = y._dense().reshape(-1)  # native dtype: labels may be floats or wide ints
        if sample_weight is not None:
            w = sample_weight._dense().reshape(-1).astype(xd.dtype)
        else:
            w = jnp.ones((xd.shape[0],), xd.dtype)

        if self.classes_ is None:
            if classes is not None:
                cls = np.asarray(classes._dense() if isinstance(classes, DNDarray) else classes)
            else:
                cls = np.unique(np.asarray(yd))
            self.classes_ = DNDarray.from_dense(jnp.asarray(cls), None, x.device, x.comm)
            n_cls = len(cls)
            n_feat = xd.shape[1]
            self.theta_ = jnp.zeros((n_cls, n_feat), xd.dtype)
            self.var_ = jnp.zeros((n_cls, n_feat), xd.dtype)
            self.class_count_ = jnp.zeros((n_cls,), xd.dtype)

        cls_arr = self.classes_._dense()

        theta = jnp.asarray(self.theta_) if not isinstance(self.theta_, DNDarray) else self.theta_._dense()
        var = jnp.asarray(self.var_) if not isinstance(self.var_, DNDarray) else self.var_._dense()
        counts = jnp.asarray(self.class_count_) if not isinstance(self.class_count_, DNDarray) else self.class_count_._dense()
        eps_applied = getattr(self, "_eps_applied", None)
        if eps_applied is None:
            eps_applied = jnp.zeros((), xd.dtype)

        theta_n, var_n, counts_n, eps = _gnb_update(
            xd, yd, w, cls_arr.astype(yd.dtype), theta, var, counts,
            eps_applied, float(self.var_smoothing),
        )
        # the smoothing term stays a lazy device scalar: no host sync per
        # partial_fit (it is removed before the next merge, see _gnb_update)
        self._epsilon = eps
        self._eps_applied = eps
        if self.priors is not None:
            pri = self.priors._dense() if isinstance(self.priors, DNDarray) else jnp.asarray(self.priors)
        else:
            pri = counts_n / jnp.maximum(jnp.sum(counts_n), 1e-30)

        # public attributes are DNDarrays (reference parity)
        wrap = lambda a: DNDarray.from_dense(a, None, x.device, x.comm)
        self.theta_ = wrap(theta_n)
        self.var_ = wrap(var_n)
        self.class_count_ = wrap(counts_n)
        self.class_prior_ = wrap(pri)
        return self

    def _joint_log_likelihood(self, x: DNDarray) -> jnp.ndarray:
        """Per-class joint log likelihood (gaussianNB.py:320)."""
        xd = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            xd = xd.astype(jnp.float32)
        theta = self.theta_._dense() if isinstance(self.theta_, DNDarray) else jnp.asarray(self.theta_)
        var = self.var_._dense() if isinstance(self.var_, DNDarray) else jnp.asarray(self.var_)
        prior_a = (
            self.class_prior_._dense()
            if isinstance(self.class_prior_, DNDarray)
            else jnp.asarray(self.class_prior_)
        )
        # all classes at once with the quadratic form expanded into three
        # matmul-shaped terms: peak memory stays (n, c) instead of the
        # (n, c, f) broadcast tensor, and the contractions ride the MXU
        prior = jnp.log(jnp.maximum(prior_a, 1e-30))  # (c,)
        norm = -0.5 * jnp.sum(jnp.log(2.0 * jnp.pi * var), axis=1)  # (c,)
        hi = jax.lax.Precision.HIGHEST
        inv_var = 1.0 / var  # (c, f)
        t1 = jnp.matmul(xd * xd, inv_var.T, precision=hi)  # (n, c)
        t2 = jnp.matmul(xd, (theta * inv_var).T, precision=hi)  # (n, c)
        t3 = jnp.sum(theta * theta * inv_var, axis=1)  # (c,)
        quad = -0.5 * (t1 - 2.0 * t2 + t3[None, :])
        return prior[None, :] + norm[None, :] + quad

    def predict(self, x: DNDarray) -> DNDarray:
        """Most probable class per sample (gaussianNB.py:360)."""
        if self.theta_ is None:
            raise RuntimeError("fit needs to be called before predict")
        jll = self._joint_log_likelihood(x)
        cls = self.classes_._dense()
        pred = cls[jnp.argmax(jll, axis=1)]
        return DNDarray.from_dense(pred, x.split, x.device, x.comm)

    def predict_proba(self, x: DNDarray) -> DNDarray:
        """Class probabilities (gaussianNB.py:390)."""
        jll = self._joint_log_likelihood(x)
        log_prob = jll - jax_logsumexp(jll, axis=1, keepdims=True)
        return DNDarray.from_dense(jnp.exp(log_prob), x.split, x.device, x.comm)

    def predict_log_proba(self, x: DNDarray) -> DNDarray:
        jll = self._joint_log_likelihood(x)
        return DNDarray.from_dense(jll - jax_logsumexp(jll, axis=1, keepdims=True), x.split, x.device, x.comm)


def jax_logsumexp(a, axis=None, keepdims=False):
    from jax.scipy.special import logsumexp

    return logsumexp(a, axis=axis, keepdims=keepdims)
