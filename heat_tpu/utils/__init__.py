"""Utilities (analog of heat/utils)."""

from . import data

__all__ = ["data"]
