"""Atomic file writes with CRC32 sidecar checksums.

Every writer in the io and checkpoint layers funnels through
:func:`atomic_write`: the payload is written to a temp file in the
destination directory, fsynced, checksummed, and renamed over the final
path — so a reader can observe the old complete file or the new
complete file, never a torn intermediate.  A ``<path>.crc32`` sidecar
records the payload checksum; :func:`verify_checksum` (called by every
loader) streams the file and raises :class:`ChecksumError` on mismatch,
so silent corruption fails loudly instead of returning garbage.

Files without a sidecar (written by other tools) verify as "unknown"
and load normally — checksums harden our own writes without locking the
loaders onto them.
"""

from __future__ import annotations

import contextlib
import os
import uuid
import zlib
from typing import Optional

from .errors import ChecksumError
from .faults import inject

__all__ = [
    "atomic_write",
    "checksum_path",
    "crc32_file",
    "verify_checksum",
    "write_checksum",
]

_CHUNK = 1 << 20  # 1 MiB read blocks: bounded memory on multi-GB files

SIDECAR_SUFFIX = ".crc32"


def checksum_path(path: str) -> str:
    """Sidecar path holding ``path``'s CRC32 (``<path>.crc32``)."""
    return path + SIDECAR_SUFFIX


def crc32_file(path: str) -> int:
    """Streaming CRC32 of a file's bytes."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(_CHUNK)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def _fsync_path(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def _fsync_dir(path: str) -> None:
    # directory fsync makes the rename itself durable; some filesystems
    # refuse O_RDONLY fsync on directories — a failed dir sync degrades
    # durability, not atomicity, so it is best-effort
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # pragma: no cover
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover
        pass
    finally:
        os.close(fd)


def write_checksum(path: str, crc: Optional[int] = None) -> int:
    """Write (atomically) the CRC32 sidecar for ``path``; returns the crc."""
    if crc is None:
        crc = crc32_file(path)
    side = checksum_path(path)
    tmp = f"{side}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}"
    with open(tmp, "w") as f:
        f.write(f"{crc:08x}\n")
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, side)
    return crc


def read_checksum(path: str) -> Optional[int]:
    """The sidecar-recorded CRC32 of ``path``, or None if no sidecar."""
    side = checksum_path(path)
    if not os.path.exists(side):
        return None
    with open(side) as f:
        return int(f.read().strip(), 16)


def verify_checksum(path: str, required: bool = False) -> Optional[bool]:
    """Verify ``path`` against its sidecar.

    Returns True (verified), None (no sidecar; ``required=False``), or
    raises :class:`ChecksumError` on mismatch / :class:`FileNotFoundError`
    when ``required`` and no sidecar exists."""
    expected = read_checksum(path)
    if expected is None:
        if required:
            raise FileNotFoundError(f"no checksum sidecar for {path!r}")
        return None
    actual = crc32_file(path)
    if actual != expected:
        raise ChecksumError(path, expected, actual)
    return True


@contextlib.contextmanager
def atomic_write(path: str, checksum: bool = True, fault_site: str = "io.write"):
    """Context manager yielding a temp path to write; commits on exit.

    The body writes the full payload to the yielded temp path (same
    directory, so the final ``os.replace`` is a same-filesystem atomic
    rename).  On clean exit the temp file is fsynced, its CRC32 sidecar
    written, and the rename performed; on ANY failure the temp file is
    removed and the destination is untouched — a torn write is never
    visible.  ``fault_site`` is evaluated before the commit so injected
    transient faults exercise the retry path with no partial state."""
    path = os.fspath(path)
    dirname = os.path.dirname(os.path.abspath(path))
    os.makedirs(dirname, exist_ok=True)
    tmp = os.path.join(
        dirname,
        f".{os.path.basename(path)}.tmp-{os.getpid()}-{uuid.uuid4().hex[:8]}",
    )
    try:
        yield tmp
        inject(fault_site, path=path)
        if not os.path.exists(tmp):
            raise FileNotFoundError(
                f"atomic_write body did not create the temp file for {path!r}"
            )
        _fsync_path(tmp)
        crc = crc32_file(tmp) if checksum else None
        os.replace(tmp, path)
        if checksum:
            write_checksum(path, crc)
        _fsync_dir(dirname)
    except BaseException:
        with contextlib.suppress(OSError):
            os.remove(tmp)
        raise
