"""Utilities (analog of heat/utils, plus the TPU-build aux subsystems:
checkpoint/resume and profiling, SURVEY.md §5)."""

from . import checkpoint
from . import data
from . import overlap
from . import profiling
from . import vision_transforms

__all__ = ["checkpoint", "data", "overlap", "profiling", "vision_transforms"]
