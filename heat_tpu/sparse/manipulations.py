"""Sparse<->dense conversions, analog of heat/sparse/manipulations.py
(to_dense :105, to_sparse_csr/csc :51-104).

Sparse->sparse format changes take a TRIPLET-PRESERVING path (gather the
planes to replicated global COO, re-key by the other axis, re-chunk) —
O(gnnz) plane traffic, never a dense (m, n) buffer, so SpGEMM inputs
never densify on entry (ISSUE 16 satellite)."""

from __future__ import annotations

import jax
import numpy as np

from ..core.dndarray import DNDarray
from . import _planes as _pl
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix
from .factories import sparse_csc_matrix, sparse_csr_matrix

__all__ = ["to_dense", "to_sparse", "to_sparse_csc", "to_sparse_csr"]


def to_dense(sparse_matrix: DCSX_matrix, order=None, out=None) -> DNDarray:
    """Dense DNDarray from a sparse matrix (sparse/manipulations.py:105)."""
    if not isinstance(sparse_matrix, DCSX_matrix):
        raise TypeError(f"expected a sparse matrix, got {type(sparse_matrix)}")
    res = sparse_matrix.todense()
    if out is not None:
        out._replace(res.larray_padded)
        return out
    return res


def _convert_format(s: DCSX_matrix, cls, split):
    """CSR<->CSC re-compression without densifying: replicate the global
    triplets on device (``rechunk_planes``), swap the key roles and re-sort
    by the new compressed axis (``recompress_planes``), then re-chunk to
    the target split.  The only host traffic is the standard (P,)-int
    capacity re-sync."""
    from .arithmetics import _align_split

    extent_old = s.shape[s._compressed_axis]
    if s._dist:
        comp, other, val, _, _, _, _ = _pl.rechunk_planes(
            s._comp, s._other, s._val, s._lnnz_dev, s._lnnz_host,
            extent_old, False, s._nshards, s._capacity, s._comp_pad, s.comm,
        )
    else:
        comp, other, val = s._comp, s._other, s._val
    extent_new = s.shape[1 - s._compressed_axis]
    comp, other, val = _pl.recompress_planes(
        comp, other, val, extent_old, extent_new, s.comm
    )
    gnnz = s.gnnz
    lnnz_dev = jax.device_put(np.asarray([gnnz], np.int32), s.comm.sharding(None))
    mat = cls(
        (comp, other, val), lnnz_dev, (gnnz,), max(gnnz, 1), max(extent_new, 1),
        s.shape, s.dtype, None, s.device, s.comm,
    )
    if split is not None:
        mat = _align_split(mat, split)
    return mat


def to_sparse_csr(array) -> DCSR_matrix:
    """DCSR from a dense DNDarray (sparse/manipulations.py:51) or from a
    DCSC (triplet-preserving — the planes never round-trip a dense
    buffer)."""
    if isinstance(array, DCSR_matrix):
        return array
    if isinstance(array, DCSC_matrix):
        return _convert_format(array, DCSR_matrix, 0 if array.split is not None else None)
    if not isinstance(array, DNDarray):
        raise TypeError(f"expected a DNDarray or sparse matrix, got {type(array)}")
    return sparse_csr_matrix(array, split=0 if array.split == 0 else None, comm=array.comm)


def to_sparse_csc(array) -> DCSC_matrix:
    """DCSC from a dense DNDarray (sparse/manipulations.py:78) or from a
    DCSR (triplet-preserving)."""
    if isinstance(array, DCSC_matrix):
        return array
    if isinstance(array, DCSR_matrix):
        return _convert_format(array, DCSC_matrix, 1 if array.split is not None else None)
    if not isinstance(array, DNDarray):
        raise TypeError(f"expected a DNDarray or sparse matrix, got {type(array)}")
    return sparse_csc_matrix(array, split=1 if array.split == 1 else None, comm=array.comm)


to_sparse = to_sparse_csr
