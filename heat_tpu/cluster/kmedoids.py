"""KMedoids clustering, analog of heat/cluster/kmedoids.py (kmedoids.py:11).

Centers snap to the closest actual data point (medoid) after a
KMeans-style mean update, matching the reference's variant.
"""

from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ..core import types
from ..core.dndarray import DNDarray
from ..spatial import distance
from ._kcluster import _KCluster

__all__ = ["KMedoids"]


class KMedoids(_KCluster):
    """Manhattan-metric k-medoids (kmedoids.py:11)."""

    def __init__(
        self,
        n_clusters: int = 8,
        init: Union[str, DNDarray] = "random",
        max_iter: int = 300,
        random_state: Optional[int] = None,
    ):
        if init == "kmedoids++":
            init = "probability_based"
        super().__init__(
            metric=lambda x, y: distance.manhattan(x, y),
            n_clusters=n_clusters,
            init=init,
            max_iter=max_iter,
            tol=0.0,
            random_state=random_state,
        )

    def _update_centroids(self, x: DNDarray, matching_centroids: DNDarray) -> DNDarray:
        """Mean update then snap to the nearest sample (kmedoids.py:70+)."""
        dense = x._dense()
        if not types.heat_type_is_inexact(x.dtype):
            dense = dense.astype(jnp.float32)
        labels = matching_centroids._dense()
        old = self._cluster_centers._dense()
        new_centers = []
        for c in range(self.n_clusters):
            mask = labels == c
            cnt = jnp.sum(mask)
            mean = jnp.where(
                cnt > 0,
                jnp.sum(jnp.where(mask[:, None], dense, 0.0), axis=0) / jnp.maximum(cnt, 1),
                old[c],
            )
            # snap to closest member of the cluster (or global closest when empty)
            d = jnp.sum(jnp.abs(dense - mean[None, :]), axis=1)
            d = jnp.where(mask, d, jnp.inf)
            d = jnp.where(cnt > 0, d, jnp.sum(jnp.abs(dense - mean[None, :]), axis=1))
            new_centers.append(dense[jnp.argmin(d)])
        new = jnp.stack(new_centers)
        return DNDarray.from_dense(new, None, x.device, x.comm)

    def fit(self, x: DNDarray) -> "KMedoids":
        """Iterate until the medoids stop moving (kmedoids.py:~110)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        self._initialize_cluster_centers(x)

        for i in range(self.max_iter):
            matching_centroids = self._assign_to_cluster(x)
            new_cluster_centers = self._update_centroids(x, matching_centroids)
            shift = float(jnp.sum(jnp.abs(new_cluster_centers._dense() - self._cluster_centers._dense())))
            self._cluster_centers = new_cluster_centers
            if shift == 0.0:
                break

        self._n_iter = i + 1
        self._labels = self._assign_to_cluster(x, eval_functional_value=True)
        return self
