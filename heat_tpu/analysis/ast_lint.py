"""AST-level framework-invariant linter with stable rule IDs.

Four PRs of layered infrastructure gave the codebase conventions nothing
enforced: every writer goes through the atomic-rename layer, every
``HEAT_TPU_*`` knob is registered, every collective is accounted, every
fault site is named in the registry, resumable chunk bodies stay on
device, and broad exception handlers must not swallow the resilience
layer's non-retryable errors.  This module turns each convention into a
machine-checked rule over the Python AST of the sources:

==========  ==========================================================
H101        raw ``open(..., "w"/"wb"/"a"/...)`` write outside
            ``resilience/atomic.py`` and the two sanctioned telemetry
            dump paths, and not inside an ``atomic_write``/
            ``_atomic_out`` block — bypasses write-temp-fsync-rename +
            CRC32 (docs/resilience.md)
H201        ``os.environ`` / ``os.getenv`` read of a ``HEAT_TPU_*``
            name that is not registered in the central knob table
            (``core/_env.py KNOBS``) — typo'd or undocumented knob
H301        ``jax.lax`` collective in ``parallel/comm.py`` not
            lexically inside a ``_account(...)`` span — the comm-volume
            model would under-report
H302        fault-injection site name (``inject("...")`` /
            ``fault_site=...`` / ``site=...``) not registered in
            ``resilience/faults.py KNOWN_SITES`` — a fault plan
            targeting it could never be validated
H401        host-sync call (``.item()``, ``np.asarray``,
            ``jax.device_get``) inside a ``resumable_fit_loop`` chunk
            body — a device->host round trip per chunk iteration
H501        ``except Exception:`` / ``except BaseException:`` / bare
            ``except:`` whose body never re-raises — can swallow
            ``PermanentFault`` / ``ChecksumError``
H601        host-entropy seeding (``time.time`` inside a ``seed``
            function) — collision-prone across hosts; use
            ``heat_tpu.core.random.default_seed`` (os.urandom)
H701        module-global mutated from thread-reachable code (functions
            reachable from ``threading.Thread(target=...)``, excepthook
            registration, or an HTTP handler class) outside a ``with``
            over a lock registered in ``analysis/concurrency.py
            LOCK_REGISTRY``
H702        explicit ``.acquire()`` on a lock — leaks the lock when the
            guarded region raises; hold locks with ``with``
H703        ``threading.Thread`` created without an explicit ``daemon=``
            and no ``join()`` close path in the module — leaks a
            non-daemon thread (or silently truncates work) at exit
H704        blocking call (``queue.get`` / ``join`` /
            ``block_until_ready`` / ``time.sleep``) lexically inside a
            ``with`` over a registered lock — stalls every other thread
            contending for it
H705        ``time.sleep`` polling loop in a class that already owns a
            ``threading.Condition``/``Event`` — wait on the primitive
            instead of burning wakeups
H801        controller protocol state (a ``state_attrs`` attribute or
            ``state_keys`` subscript declared in
            ``analysis/protocols.py PROTOCOLS``) written outside a
            registered transition/silent function — an unjournaled,
            unverifiable state change
H802        registered transition function missing (or never emitting)
            its protocol's declared decision-journal event
H803        decision-journal ``emit`` whose literal ``(actor, action)``
            pair is not declared by any protocol — the conformance
            checker would flag it at runtime; declare it first
H804        ``PROTOCOLS`` registry self-inconsistency: non-literal
            table, transition from/to an undeclared state, or a
            declared-but-unreachable state
==========  ==========================================================

Suppressions: append ``# lint: allow H501(<reason>)`` to the flagged
line (rule ID must match; the reason is free text).  Accepted legacy
violations live in ``scripts/lint_baseline.json``; ``scripts/
lint_gate.py`` fails CI on any violation not in the baseline.

Run as ``python -m heat_tpu.analysis <paths...>``.  The linter is pure
stdlib (``ast`` + a static parse of the knob/site registries) — it
never imports the modules it checks.
"""

from __future__ import annotations

import ast
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

__all__ = [
    "RULES",
    "Violation",
    "lint_file",
    "lint_paths",
    "load_registered_knobs",
    "load_registered_sites",
    "load_lock_spellings",
    "load_protocols",
    "load_protocol_constants",
]

#: rule ID -> one-line description (the catalogue docs and the CLI share)
RULES = {
    "H101": "raw write-mode open() outside the atomic-write layer",
    "H201": "unregistered HEAT_TPU_* env knob (core/_env.py KNOBS)",
    "H301": "collective in parallel/comm.py without an accounting span",
    "H302": "fault-injection site not registered in resilience/faults.py",
    "H401": "host-sync call inside a resumable_fit_loop chunk body",
    "H501": "broad except that can swallow PermanentFault/ChecksumError",
    "H601": "host-entropy seeding; use core.random.default_seed",
    "H701": "thread-reachable module-global mutation outside a registered lock",
    "H702": "explicit lock acquire() outside a with statement (leak on exception)",
    "H703": "Thread without explicit daemon= and no join()/close path",
    "H704": "blocking call while holding a registered lock",
    "H705": "time.sleep polling loop where a Condition/Event exists in the class",
    "H801": "protocol state written outside a registered transition function",
    "H802": "transition function missing its declared journal emit",
    "H803": "journal emit (actor, action) not declared in analysis/protocols.py",
    "H804": "PROTOCOLS registry inconsistency (unreachable/undeclared state)",
}

#: repo-relative files whose explicit acquire() IS the sanctioned
#: implementation (the instrumented-lock proxy itself)
H702_SANCTIONED_FILES = (
    "heat_tpu/analysis/tsan.py",
)

#: repo-relative files whose raw writes are the sanctioned implementation
#: (the atomic layer itself; the telemetry dump paths now write through
#: it, so they are linted like everything else)
H101_SANCTIONED_FILES = (
    "heat_tpu/resilience/atomic.py",
)

_WRITE_MODES = re.compile(r"[wax]")

_SUPPRESS = re.compile(r"#\s*lint:\s*allow\s+(H\d{3})\b")


@dataclass(frozen=True)
class Violation:
    """One lint finding, stable across runs: (rule, file, line) is the
    identity the baseline gate compares."""

    rule: str
    file: str
    line: int
    col: int
    message: str

    def key(self) -> Tuple[str, str, int]:
        return (self.rule, self.file, self.line)

    def __str__(self) -> str:
        return f"{self.file}:{self.line}:{self.col}: {self.rule} {self.message}"


# ----------------------------------------------------------------------
# registry loading (static — ast.literal_eval, no imports)
# ----------------------------------------------------------------------
def _literal_assignment(path: str, name: str):
    """The literal value assigned to module-level ``name`` in ``path``."""
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    for node in tree.body:
        targets = []
        if isinstance(node, ast.Assign):
            targets = [t.id for t in node.targets if isinstance(t, ast.Name)]
        elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
            targets = [node.target.id]
        if name in targets and node.value is not None:
            return ast.literal_eval(node.value)
    raise LookupError(f"no literal assignment of {name!r} in {path}")


def load_registered_knobs(repo_root: str) -> Set[str]:
    """Knob names from ``core/_env.py KNOBS`` (static parse)."""
    path = os.path.join(repo_root, "heat_tpu", "core", "_env.py")
    return set(_literal_assignment(path, "KNOBS"))


def load_registered_sites(repo_root: str) -> Set[str]:
    """Fault-site names from ``resilience/faults.py KNOWN_SITES``."""
    path = os.path.join(repo_root, "heat_tpu", "resilience", "faults.py")
    return set(_literal_assignment(path, "KNOWN_SITES"))


def load_lock_spellings(repo_root: str) -> Set[str]:
    """Lexical ``with`` spellings of every registered lock, from
    ``analysis/concurrency.py LOCK_REGISTRY`` (static parse)."""
    path = os.path.join(repo_root, "heat_tpu", "analysis", "concurrency.py")
    table = _literal_assignment(path, "LOCK_REGISTRY")
    out: Set[str] = set()
    for rec in table.values():
        out.update(rec.get("spellings", ()))
    return out


def load_protocols(repo_root: str) -> Dict:
    """The ``PROTOCOLS`` table from ``analysis/protocols.py`` (static
    parse — the linter checks that module, so it must not import it)."""
    path = os.path.join(repo_root, "heat_tpu", "analysis", "protocols.py")
    return _literal_assignment(path, "PROTOCOLS")


def load_protocol_constants(repo_root: str) -> Dict[str, str]:
    """Module-level string constants of ``analysis/protocols.py`` (the
    centralized actor/action vocabulary) — lets the H802/H803 rules
    resolve ``_journal.emit(ACTOR_X, ACTION_Y, ...)`` spellings."""
    path = os.path.join(repo_root, "heat_tpu", "analysis", "protocols.py")
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)
    out: Dict[str, str] = {}
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            out[node.targets[0].id] = node.value.value
    return out


def _protocol_table_problems(table) -> List[str]:
    """Structural H804 defects of a PROTOCOLS-shaped literal (kept
    independent of protocols.registry_problems — the linter never
    imports the module it checks)."""
    problems: List[str] = []
    pair_owner: Dict[Tuple[str, str], str] = {}
    if not isinstance(table, dict):
        return ["PROTOCOLS must be a dict literal"]
    for name in sorted(table):
        rec = table[name]
        states = set(rec.get("states", ()))
        initial = rec.get("initial")
        if initial not in states:
            problems.append(
                f"{name}: initial state {initial!r} is not a declared state"
            )
        adjacency: Dict[str, Set[str]] = {s: set() for s in states}
        for t in rec.get("transitions", ()):
            for end, label in ((t.get("from"), "from"), (t.get("to"), "to")):
                if end not in states:
                    problems.append(
                        f"{name}: transition {t.get('action')!r} {label}-state "
                        f"{end!r} is not a declared state"
                    )
            if t.get("from") in states and t.get("to") in states:
                adjacency[t["from"]].add(t["to"])
            pair = (rec.get("actor"), t.get("action"))
            owner = pair_owner.setdefault(pair, name)
            if owner != name:
                problems.append(
                    f"{name}: journal pair {pair!r} is already declared by "
                    f"protocol {owner!r}"
                )
        if initial in states:
            seen = {initial}
            frontier = [initial]
            while frontier:
                for nxt in adjacency.get(frontier.pop(), ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            for s in sorted(states - seen):
                problems.append(
                    f"{name}: state {s!r} is unreachable from initial "
                    f"{initial!r} via the declared transitions"
                )
    return problems


def _find_repo_root(start: str) -> str:
    """Walk up from ``start`` to the directory containing ``heat_tpu/``."""
    d = os.path.abspath(start)
    if os.path.isfile(d):
        d = os.path.dirname(d)
    while True:
        if os.path.isdir(os.path.join(d, "heat_tpu")):
            return d
        parent = os.path.dirname(d)
        if parent == d:
            raise FileNotFoundError(
                f"cannot locate the repo root (heat_tpu/) above {start!r}"
            )
        d = parent


# ----------------------------------------------------------------------
# the visitor
# ----------------------------------------------------------------------
def _dotted(node: ast.AST) -> str:
    """Best-effort dotted name of a call target ('jax.lax.psum', 'open')."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
    return ".".join(reversed(parts))


_HOST_SYNC_CALLS = {"np.asarray", "numpy.asarray", "jax.device_get"}

_COMM_COLLECTIVES = {
    "psum", "pmax", "pmin", "all_gather", "all_to_all",
    "ppermute", "psum_scatter",
}


#: HTTP handler base classes: every method of a subclass runs on a
#: per-request server thread
_HANDLER_BASES = {
    "BaseHTTPRequestHandler", "SimpleHTTPRequestHandler",
    "StreamRequestHandler", "DatagramRequestHandler", "BaseRequestHandler",
}

#: mutating container methods: called directly on a module-global name
#: they rewrite shared state in place
_MUTATORS = {
    "append", "appendleft", "add", "clear", "update", "pop", "popitem",
    "extend", "insert", "remove", "discard", "setdefault", "move_to_end",
}


class _Linter(ast.NodeVisitor):
    def __init__(
        self,
        rel_path: str,
        source: str,
        knobs: Set[str],
        sites: Set[str],
        lock_spellings: Optional[Set[str]] = None,
        protocols: Optional[Dict] = None,
        protocol_constants: Optional[Dict[str, str]] = None,
    ):
        self.rel = rel_path
        self.lines = source.splitlines()
        self.knobs = knobs
        self.sites = sites
        self.lock_spellings = lock_spellings or set()
        self.protocols = protocols or {}
        self.protocol_constants = protocol_constants or {}
        self.violations: List[Violation] = []
        # lexical context stacks
        self._with_atomic = 0       # inside atomic_write/_atomic_out block
        self._with_account = 0      # inside *_account(...) span block
        self._with_lock = 0         # inside `with <registered lock>:`
        self._func_stack: List[str] = []
        self._global_decls: List[Set[str]] = []  # per-function `global` names
        self._class_stack: List[str] = []
        self._loop_depth = 0
        self._thread_depth = 0      # inside a thread-reachable function
        self._chunk_depth = 0       # inside a resumable chunk body
        self._chunk_fn_names: Set[str] = set()
        # thread-context pre-pass results
        self._module_globals: Set[str] = set()
        self._thread_reachable: Set[str] = set()
        self._module_has_join = False
        self._cond_classes: Set[str] = set()
        self._is_comm = rel_path.replace(os.sep, "/").endswith("parallel/comm.py")
        # protocol (H8xx) context: the protocols declared over THIS
        # module, their guarded state spellings and sanctioned writers
        rel_posix = rel_path.replace(os.sep, "/")
        self._proto_local = {
            name: rec for name, rec in self.protocols.items()
            if rel_posix.endswith(rec["module"])
        }
        self._proto_state_attrs: Set[str] = set()
        self._proto_state_keys: Set[str] = set()
        self._proto_sanctioned: Set[str] = set()
        for rec in self._proto_local.values():
            self._proto_state_attrs.update(rec["state_attrs"])
            self._proto_state_keys.update(rec["state_keys"])
            self._proto_sanctioned.update(rec["transition_fns"])
            self._proto_sanctioned.update(rec["silent_fns"])
        self._declared_pairs: Set[Tuple[str, str]] = {
            (rec["actor"], t["action"])
            for rec in self.protocols.values()
            for t in rec["transitions"]
        }
        self._str_consts: Dict[str, str] = {}
        self._is_protocols_mod = rel_posix.endswith("analysis/protocols.py")
        self._h101_sanctioned = any(
            self.rel.replace(os.sep, "/").endswith(p) for p in H101_SANCTIONED_FILES
        )
        self._h702_sanctioned = any(
            self.rel.replace(os.sep, "/").endswith(p) for p in H702_SANCTIONED_FILES
        )

    # -- plumbing -------------------------------------------------------
    def _add(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        if 1 <= line <= len(self.lines):
            m = _SUPPRESS.search(self.lines[line - 1])
            if m and m.group(1) == rule:
                return
        self.violations.append(Violation(
            rule=rule, file=self.rel, line=line,
            col=getattr(node, "col_offset", 0), message=message,
        ))

    # -- pre-pass: which local functions are resumable chunk bodies -----
    def collect_chunk_fns(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            name = _dotted(node.func)
            if not name.endswith(("resumable_fit_loop", "_run_resumable")):
                continue
            cand = None
            if node.args:
                cand = node.args[0]
            for kw in node.keywords:
                if kw.arg == "run_chunk":
                    cand = kw.value
            if isinstance(cand, ast.Name):
                self._chunk_fn_names.add(cand.id)
        self._chunk_fn_names.add("run_chunk")  # the estimator convention

    # -- pre-pass: thread reachability (H701), join/Condition inventory --
    def collect_thread_context(self, tree: ast.AST) -> None:
        """Seed the set of functions that can run on a non-main thread —
        ``threading.Thread(target=...)`` targets, excepthook
        registrations, every method of an HTTP handler class — and close
        it over the module's (name-based) call graph.  Also records the
        module-level global names (the H701 mutation targets), whether
        the module ever ``join()``\\ s a thread (H703), and which classes
        own a ``Condition``/``Event`` (H705)."""
        entries: Set[str] = set()
        call_graph: Dict[str, Set[str]] = {}
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [t for t in node.targets if isinstance(t, ast.Name)]
            elif isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name):
                targets = [node.target]
            self._module_globals.update(t.id for t in targets)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                callees = call_graph.setdefault(node.name, set())
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call):
                        callees.add(_dotted(sub.func).rsplit(".", 1)[-1])
            elif isinstance(node, ast.Call):
                name = _dotted(node.func)
                if name in ("threading.Thread", "Thread"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            tail = _dotted(kw.value).rsplit(".", 1)[-1]
                            if tail:
                                entries.add(tail)
                if (
                    _dotted(node.func).rsplit(".", 1)[-1] == "join"
                    and not node.args
                    and isinstance(node.func, ast.Attribute)
                ):
                    self._module_has_join = True
            elif isinstance(node, ast.Assign):
                # sys.excepthook = f / threading.excepthook = f
                for t in node.targets:
                    if _dotted(t) in ("sys.excepthook", "threading.excepthook"):
                        tail = _dotted(node.value).rsplit(".", 1)[-1]
                        if tail:
                            entries.add(tail)
            elif isinstance(node, ast.ClassDef):
                bases = {_dotted(b).rsplit(".", 1)[-1] for b in node.bases}
                if bases & _HANDLER_BASES:
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                            entries.add(item.name)
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and _dotted(sub.func).rsplit(
                        ".", 1
                    )[-1] in ("Condition", "Event"):
                        self._cond_classes.add(node.name)
                        break
        # transitive closure over the name-based call graph
        reachable = set(entries)
        frontier = list(entries)
        while frontier:
            fn = frontier.pop()
            for callee in call_graph.get(fn, ()):
                if callee in call_graph and callee not in reachable:
                    reachable.add(callee)
                    frontier.append(callee)
        self._thread_reachable = reachable

    # -- pre-pass: resolvable string constants (H802/H803) ----------------
    def collect_constants(self, tree: ast.AST) -> None:
        """Module-level ``NAME = "str"`` assignments plus names imported
        from ``analysis/protocols.py`` — the spellings under which emit
        sites may reference the journal vocabulary."""
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self._str_consts[node.targets[0].id] = node.value.value
            elif (
                isinstance(node, ast.ImportFrom)
                and node.module
                and node.module.rsplit(".", 1)[-1] == "protocols"
            ):
                for alias in node.names:
                    val = self.protocol_constants.get(alias.name)
                    if val is not None:
                        self._str_consts[alias.asname or alias.name] = val

    def _resolve_str(self, node: ast.AST) -> Optional[str]:
        """A call argument's string value, when statically resolvable:
        a literal, a known module constant, or ``mod.CONSTANT`` where
        CONSTANT is in the protocols vocabulary."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self._str_consts.get(node.id)
        if isinstance(node, ast.Attribute):
            return self.protocol_constants.get(node.attr)
        return None

    # -- H802/H804 post-passes -------------------------------------------
    def check_protocol_fns(self, tree: ast.AST) -> None:
        """H802: every registered transition function of this module's
        protocols exists and lexically contains a journal ``emit`` whose
        actor resolves to the protocol's declared actor."""
        if not self._proto_local:
            return
        required: Dict[str, Set[str]] = {}
        for rec in self._proto_local.values():
            for fn in rec["transition_fns"]:
                required.setdefault(fn, set()).add(rec["actor"])
        defs: Dict[str, List[ast.AST]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node.name in required:
                defs.setdefault(node.name, []).append(node)
        for fn, actors in sorted(required.items()):
            fnodes = defs.get(fn)
            if not fnodes:
                anchor = tree.body[0] if getattr(tree, "body", None) else None
                self._add(
                    "H802", anchor if anchor is not None else ast.Module(),
                    f"registered transition function {fn!r} "
                    "(analysis/protocols.py) is not defined in this module",
                )
                continue
            for fnode in fnodes:
                found: Set[str] = set()
                for sub in ast.walk(fnode):
                    if (
                        isinstance(sub, ast.Call)
                        and _dotted(sub.func).rsplit(".", 1)[-1] == "emit"
                        and len(sub.args) >= 2
                    ):
                        actor = self._resolve_str(sub.args[0])
                        if actor is not None:
                            found.add(actor)
                for actor in sorted(actors - found):
                    self._add(
                        "H802", fnode,
                        f"transition function {fn!r} never emits its "
                        f"declared decision-journal event (actor "
                        f"{actor!r}); the protocol transition would be "
                        "invisible to /decisionz and the conformance "
                        "checker",
                    )

    def check_protocols_registry(self, tree: ast.AST) -> None:
        """H804: registry self-consistency, anchored on the PROTOCOLS
        assignment when linting analysis/protocols.py itself."""
        if not self._is_protocols_mod:
            return
        for node in tree.body:
            if (
                isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "PROTOCOLS"
                        for t in node.targets)
            ):
                try:
                    table = ast.literal_eval(node.value)
                except (ValueError, SyntaxError, TypeError):
                    self._add(
                        "H804", node,
                        "PROTOCOLS must be a pure literal "
                        "(ast.literal_eval-parsable, the KNOBS idiom)",
                    )
                    return
                for problem in _protocol_table_problems(table):
                    self._add("H804", node, problem)
                return

    # -- with blocks ----------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        atomic = account = lock = False
        for item in node.items:
            ctx = item.context_expr
            if isinstance(ctx, ast.Call):
                name = _dotted(ctx.func)
                tail = name.rsplit(".", 1)[-1]
                if tail in ("atomic_write", "_atomic_out"):
                    atomic = True
                if tail.endswith("_account") or tail == "account_implicit":
                    account = True
            elif _dotted(ctx) in self.lock_spellings:
                lock = True
        self._with_atomic += atomic
        self._with_account += account
        self._with_lock += lock
        self.generic_visit(node)
        self._with_atomic -= atomic
        self._with_account -= account
        self._with_lock -= lock

    # -- function context (H401, H601, H701) -----------------------------
    def _visit_func(self, node) -> None:
        self._func_stack.append(node.name)
        self._global_decls.append(set())
        is_chunk = node.name in self._chunk_fn_names
        is_threaded = node.name in self._thread_reachable
        self._chunk_depth += is_chunk
        self._thread_depth += is_threaded
        for default in list(getattr(node.args, "defaults", ())) + list(
            getattr(node.args, "kw_defaults", ())
        ):
            self._check_site_default(node, default)
        self.generic_visit(node)
        self._chunk_depth -= is_chunk
        self._thread_depth -= is_threaded
        self._global_decls.pop()
        self._func_stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Global(self, node: ast.Global) -> None:
        if self._global_decls:
            self._global_decls[-1].update(node.names)

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def _visit_loop(self, node) -> None:
        self._loop_depth += 1
        self.generic_visit(node)
        self._loop_depth -= 1

    visit_While = _visit_loop
    visit_For = _visit_loop
    visit_AsyncFor = _visit_loop

    # -- H701: module-global mutation in thread-reachable code -----------
    def _check_global_mutation(self, target: ast.AST, node: ast.AST) -> None:
        if self._thread_depth <= 0 or self._with_lock > 0:
            return
        name = None
        if isinstance(target, ast.Name):
            # a bare-name store only hits module state under a `global`
            # declaration; the declaration alone marks it shared
            if any(target.id in g for g in self._global_decls):
                name = target.id
        elif isinstance(target, (ast.Subscript, ast.Attribute)):
            base = target.value
            if isinstance(base, ast.Name) and base.id in self._module_globals:
                name = base.id
        if name is not None:
            self._add(
                "H701", node,
                f"module-global {name!r} mutated from thread-reachable code "
                "without holding a lock registered in analysis/concurrency.py "
                "LOCK_REGISTRY — another thread can observe or corrupt the "
                "intermediate state",
            )

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._check_global_mutation(t, node)
            self._check_protocol_write(t, node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_global_mutation(node.target, node)
        self._check_protocol_write(node.target, node)
        self.generic_visit(node)

    # -- H801: protocol state written outside a registered transition ----
    def _check_protocol_write(self, target: ast.AST, node: ast.AST) -> None:
        if not self._proto_local:
            return
        spelled = None
        if isinstance(target, ast.Attribute) \
                and target.attr in self._proto_state_attrs:
            spelled = target.attr
        elif (
            isinstance(target, ast.Subscript)
            and isinstance(target.slice, ast.Constant)
            and target.slice.value in self._proto_state_keys
        ):
            spelled = f"[{target.slice.value!r}]"
        if spelled is None:
            return
        if any(f in self._proto_sanctioned for f in self._func_stack):
            return
        self._add(
            "H801", node,
            f"protocol state {spelled} written outside the registered "
            "transition/silent functions declared in analysis/protocols.py "
            "— the change is unjournaled and the conformance checker "
            "cannot see it; route it through a registered transition "
            "helper",
        )

    def _check_site_default(self, fn_node, default) -> None:
        # FunctionDef defaults for parameters named site/fault_site
        if not isinstance(default, ast.Constant) or not isinstance(default.value, str):
            return
        defaults = list(getattr(fn_node.args, "defaults", ()))
        kw_defaults = list(getattr(fn_node.args, "kw_defaults", ()))
        pos_args = list(getattr(fn_node.args, "args", ()))
        pairs = list(zip(pos_args[len(pos_args) - len(defaults):], defaults))
        pairs += [
            (a, d) for a, d in zip(getattr(fn_node.args, "kwonlyargs", ()), kw_defaults)
            if d is not None
        ]
        for arg, d in pairs:
            if d is default and arg.arg in ("site", "fault_site"):
                self._check_site_literal(default)

    def _check_site_literal(self, node: ast.Constant) -> None:
        site = node.value
        if site not in self.sites:
            self._add(
                "H302", node,
                f"fault site {site!r} is not registered in "
                "resilience/faults.py KNOWN_SITES — register it so fault "
                "plans targeting it can be validated",
            )

    # -- calls: H101, H201, H301, H302, H401, H601 ----------------------
    def visit_Call(self, node: ast.Call) -> None:
        name = _dotted(node.func)
        tail = name.rsplit(".", 1)[-1]

        # H101: write-mode open()
        if name == "open" and not self._h101_sanctioned and not self._with_atomic:
            mode = None
            if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant):
                mode = node.args[1].value
            for kw in node.keywords:
                if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
                    mode = kw.value.value
            if isinstance(mode, str) and _WRITE_MODES.search(mode):
                self._add(
                    "H101", node,
                    f"raw open(..., {mode!r}) bypasses the atomic "
                    "write-temp-fsync-rename + CRC32 layer; write through "
                    "resilience.atomic.atomic_write",
                )

        # H201: env reads of HEAT_TPU_* literals
        if name in ("os.getenv", "os.environ.get", "environ.get",
                    "os.environ.setdefault", "os.environ.pop"):
            if node.args and isinstance(node.args[0], ast.Constant):
                self._check_knob(node.args[0])

        # H301: unaccounted collective in parallel/comm.py
        if (
            self._is_comm
            and name.startswith("jax.lax.")
            and tail in _COMM_COLLECTIVES
            and not self._with_account
        ):
            self._add(
                "H301", node,
                f"jax.lax.{tail} in parallel/comm.py outside an "
                "_account(...) span — the collective would be invisible to "
                "the comm-volume model (docs/observability.md)",
            )

        # H302: inject("...") / fault_site="..." / site=... literals on the
        # fault-plumbing calls (a `site=` span attr elsewhere is not a
        # fault site)
        if tail in ("inject", "_inject") and node.args:
            if isinstance(node.args[0], ast.Constant) and isinstance(
                node.args[0].value, str
            ):
                self._check_site_literal(node.args[0])
        if tail in ("inject", "_inject", "atomic_write", "_atomic_out",
                    "resumable_fit_loop", "_run_resumable"):
            for kw in node.keywords:
                if (
                    kw.arg in ("fault_site", "site")
                    and isinstance(kw.value, ast.Constant)
                    and isinstance(kw.value.value, str)
                ):
                    self._check_site_literal(kw.value)
        if tail in ("resumable_fit_loop", "_run_resumable"):
            # positional site argument of the estimator helpers
            for arg in node.args:
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str) \
                        and arg.value.endswith((".iter", ".stage")):
                    self._check_site_literal(arg)

        # H401: host syncs inside chunk bodies
        if self._chunk_depth > 0:
            if name in _HOST_SYNC_CALLS or (
                tail == "item" and isinstance(node.func, ast.Attribute)
                and not node.args
            ):
                self._add(
                    "H401", node,
                    f"host-sync call {name or tail}() inside a "
                    "resumable_fit_loop chunk body — one device->host round "
                    "trip per chunk; keep the chunk on-device and sync only "
                    "at chunk boundaries",
                )

        # H701: mutating container method on a module-global from
        # thread-reachable code outside a registered lock
        if (
            self._thread_depth > 0
            and self._with_lock == 0
            and tail in _MUTATORS
            and isinstance(node.func, ast.Attribute)
            and isinstance(node.func.value, ast.Name)
            and node.func.value.id in self._module_globals
        ):
            self._add(
                "H701", node,
                f"module-global {node.func.value.id!r}.{tail}() from "
                "thread-reachable code without holding a lock registered in "
                "analysis/concurrency.py LOCK_REGISTRY",
            )

        # H702: explicit lock acquire — the guarded region leaks the lock
        # on an exception; `with` releases unconditionally
        if (
            tail == "acquire"
            and not self._h702_sanctioned
            and isinstance(node.func, ast.Attribute)
            and "lock" in _dotted(node.func.value).lower()
        ):
            self._add(
                "H702", node,
                f"{_dotted(node.func.value)}.acquire() outside a with "
                "statement leaks the lock when the guarded region raises; "
                "hold it with `with`",
            )

        # H703: Thread without explicit daemon= and no join close path
        if name in ("threading.Thread", "Thread"):
            has_daemon = any(kw.arg == "daemon" for kw in node.keywords)
            if not has_daemon and not self._module_has_join:
                self._add(
                    "H703", node,
                    "threading.Thread without an explicit daemon= and no "
                    "join() close path in this module — a non-daemon thread "
                    "blocks interpreter exit, a daemon one is silently "
                    "truncated; decide explicitly and join on the close path",
                )

        # H704: blocking call while holding a registered lock
        if self._with_lock > 0:
            blocking = (
                (tail == "join" and not node.args and isinstance(node.func, ast.Attribute))
                or tail == "block_until_ready"
                or (tail == "get" and not node.args and isinstance(node.func, ast.Attribute))
                or name == "time.sleep"
            )
            if blocking:
                self._add(
                    "H704", node,
                    f"blocking call {name or tail}() while holding a "
                    "registered lock — every thread contending for the lock "
                    "stalls behind this wait; move the wait outside the "
                    "critical section",
                )

        # H705: sleep-polling loop in a class that owns a Condition/Event
        if (
            name == "time.sleep"
            and self._loop_depth > 0
            and self._class_stack
            and self._class_stack[-1] in self._cond_classes
        ):
            self._add(
                "H705", node,
                f"time.sleep polling loop in class "
                f"{self._class_stack[-1]!r}, which already owns a "
                "threading.Condition/Event — wait on the primitive instead "
                "of burning periodic wakeups",
            )

        # H803: journal emit with an undeclared (actor, action) literal —
        # only when both args statically resolve to strings (dynamic
        # actions are the runtime conformance checker's job)
        if tail == "emit" and len(node.args) >= 2 and self._declared_pairs:
            actor = self._resolve_str(node.args[0])
            action = self._resolve_str(node.args[1])
            if (
                actor is not None
                and action is not None
                and (actor, action) not in self._declared_pairs
            ):
                self._add(
                    "H803", node,
                    f"journal emit ({actor!r}, {action!r}) is not declared "
                    "by any protocol in analysis/protocols.py PROTOCOLS — "
                    "declare the transition (and its states) so the model "
                    "checker and runtime conformance can verify it",
                )

        # H601: host-entropy seeding
        if name in ("time.time", "time.time_ns") and any(
            "seed" in f.lower() for f in self._func_stack
        ):
            self._add(
                "H601", node,
                "seeding from time.time() collides across hosts launched "
                "in the same tick; derive the default seed from "
                "heat_tpu.core.random.default_seed() (os.urandom-backed)",
            )

        self.generic_visit(node)

    # -- subscript env reads: os.environ["HEAT_TPU_X"] -------------------
    def visit_Subscript(self, node: ast.Subscript) -> None:
        if _dotted(node.value) in ("os.environ", "environ"):
            sl = node.slice
            if isinstance(sl, ast.Constant):
                self._check_knob(sl)
        self.generic_visit(node)

    def _check_knob(self, node: ast.Constant) -> None:
        name = node.value
        if isinstance(name, str) and name.startswith("HEAT_TPU_") \
                and name not in self.knobs:
            self._add(
                "H201", node,
                f"env knob {name!r} is not registered in core/_env.py "
                "KNOBS — register it (name, type, default, doc) so "
                "docs/env_vars.md and the typed accessors stay truthful",
            )

    # -- H501: broad except without re-raise -----------------------------
    def visit_Try(self, node: ast.Try) -> None:
        for handler in node.handlers:
            if self._is_broad(handler.type) and not self._reraises(handler):
                self._add(
                    "H501", handler,
                    "broad except without re-raise can swallow "
                    "PermanentFault/ChecksumError — narrow the exception "
                    "type, re-raise the non-retryables, or annotate a "
                    "deliberate catch-all with `# lint: allow H501(reason)`",
                )
        self.generic_visit(node)

    @staticmethod
    def _is_broad(type_node) -> bool:
        if type_node is None:
            return True  # bare except
        names = []
        if isinstance(type_node, ast.Tuple):
            names = [_dotted(t) for t in type_node.elts]
        else:
            names = [_dotted(type_node)]
        return any(n.rsplit(".", 1)[-1] in ("Exception", "BaseException")
                   for n in names)

    @staticmethod
    def _reraises(handler: ast.ExceptHandler) -> bool:
        return any(isinstance(n, ast.Raise) for n in ast.walk(handler))


# ----------------------------------------------------------------------
# driver
# ----------------------------------------------------------------------
def lint_file(
    path: str,
    repo_root: Optional[str] = None,
    knobs: Optional[Set[str]] = None,
    sites: Optional[Set[str]] = None,
    source: Optional[str] = None,
    rel_path: Optional[str] = None,
    lock_spellings: Optional[Set[str]] = None,
    protocols: Optional[Dict] = None,
    protocol_constants: Optional[Dict[str, str]] = None,
) -> List[Violation]:
    """Lint one Python file; returns its violations (suppressions
    applied).  ``source``/``rel_path`` let tests lint embedded fixture
    code without touching the filesystem."""
    if repo_root is None:
        repo_root = _find_repo_root(path)
    if knobs is None:
        knobs = load_registered_knobs(repo_root)
    if sites is None:
        sites = load_registered_sites(repo_root)
    if lock_spellings is None:
        lock_spellings = load_lock_spellings(repo_root)
    if protocols is None:
        protocols = load_protocols(repo_root)
    if protocol_constants is None:
        protocol_constants = load_protocol_constants(repo_root)
    if source is None:
        with open(path) as f:
            source = f.read()
    if rel_path is None:
        rel_path = os.path.relpath(os.path.abspath(path), repo_root)
    tree = ast.parse(source, filename=rel_path)
    linter = _Linter(rel_path, source, knobs, sites, lock_spellings,
                     protocols, protocol_constants)
    linter.collect_chunk_fns(tree)
    linter.collect_thread_context(tree)
    linter.collect_constants(tree)
    linter.visit(tree)
    linter.check_protocol_fns(tree)
    linter.check_protocols_registry(tree)
    return sorted(linter.violations, key=lambda v: (v.file, v.line, v.rule))


def lint_paths(
    paths: Sequence[str], repo_root: Optional[str] = None
) -> List[Violation]:
    """Lint every ``.py`` file under ``paths`` (files or directories)."""
    if repo_root is None:
        repo_root = _find_repo_root(paths[0] if paths else os.getcwd())
    knobs = load_registered_knobs(repo_root)
    sites = load_registered_sites(repo_root)
    spellings = load_lock_spellings(repo_root)
    protocols = load_protocols(repo_root)
    constants = load_protocol_constants(repo_root)
    out: List[Violation] = []
    for p in paths:
        if os.path.isfile(p):
            files = [p]
        else:
            files = sorted(
                os.path.join(dp, f)
                for dp, _dirs, fns in os.walk(p)
                for f in fns
                if f.endswith(".py")
            )
        for f in files:
            out.extend(lint_file(f, repo_root, knobs, sites,
                                 lock_spellings=spellings,
                                 protocols=protocols,
                                 protocol_constants=constants))
    return sorted(out, key=lambda v: (v.file, v.line, v.rule))


def violations_to_json(violations: Sequence[Violation]) -> List[Dict]:
    """JSON-serializable form (the baseline file format)."""
    return [
        {"rule": v.rule, "file": v.file, "line": v.line, "message": v.message}
        for v in violations
    ]
