"""Leading-contraction 3-D FFT engine (r5, second generation).

The r5 interleaved engine (``_planar._rfft3_interleaved``) pays two
"re-pair transposes" between its three DFT dots — ~9.4 ms of the 27.6 ms
512^3 transform on the bench v5e, pure relayout moving zero new
information.  This engine removes them entirely:

* every DFT stage contracts the LEADING dim of the operand
  (``dot_general`` with lhs contracting dim 0 — the grad-style
  transposed dot the MXU runs natively; measured at full speed, same
  scheduled bytes as a minor-dim dot), so the stage's output cycles the
  axis order and the next transform axis arrives in front without any
  transpose;
* the complex pair lives in SEPARATE re/im planes; each stage is two
  dots against the concatenated ``[W_re | W_im]`` matrix plus one fused
  elementwise combine (the column blocks are lane-aligned slices);
* the real-input transform halves axis 0 to ``m = n0 // 2`` bins
  (perfect tile alignment, unlike the 257-bin half spectrum) and
  carries the Nyquist bin through a tiny side chain;
* the Hermitian extension — pass-count-bound in XLA (measured 12.5 ms:
  roll/rev/concat each materialize) — is a Pallas kernel that emits one
  output row per grid step: lower rows are DMA copies, upper rows are
  the mirrored source row rev-rolled THROUGH THE MXU (one permutation
  matrix on each side, manual bf16x2 split since Mosaic lowers only
  DEFAULT/HIGHEST dot precision; the permutation matrix is exact in
  bf16, so the error is the 2^-17 split truncation, below the HIGH
  matmul policy's own 2.5e-5).  Measured 4.5 ms.

Measured end to end on the bench v5e at 512^3 f32 (same session):
22.7 ms vs 27.6 interleaved / 65.4 r4 — 9.7 GB scheduled vs 13.5 /
43.1 — ~43% of the 48 B/element minimal-model bandwidth.  Reference
semantics: heat/fft/fft.py:100-137 (fftn), verified against
``np.fft.fftn`` to ~2.7e-5 relative (HIGH default policy).

Norm scaling is folded into the exit-stage matrices (host f64
constants), so every norm mode ships at the default-path cost.
"""

from __future__ import annotations

import functools
import os
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "leading_eligible",
    "rfft3_leading",
    "cfft3_leading",
]


def _precision():
    from ._planar import _interleaved_precision

    return _interleaved_precision()


@functools.lru_cache(maxsize=64)
def _cs(n: int, inverse: bool):
    """Host f64 (cos, sign*sin) planes of the n-point DFT matrix."""
    j = np.arange(n, dtype=np.float64)
    jk = np.outer(j, j) % n
    ang = 2.0 * np.pi * jk / n
    sign = 1.0 if inverse else -1.0
    return np.cos(ang), sign * np.sin(ang)


@functools.lru_cache(maxsize=64)
def _w_entry_half(n: int, m: int, dt: str, part: str):
    """(n, m) real-input entry matrix for bins 0..m-1 (axis-0 halving)."""
    c, s = _cs(n, False)
    w = c if part == "re" else s
    return np.asarray(w[:, :m], dt)


@functools.lru_cache(maxsize=64)
def _w_cat(n: int, dt: str, inverse: bool, scale: float):
    """(n, 2n) ``[W_re | W_im] * scale`` stage matrix (scale folds the
    norm factor into the exit stage — no post-scaling pass)."""
    c, s = _cs(n, inverse)
    return np.asarray(np.concatenate([c, s], 1) * scale, dt)


@functools.lru_cache(maxsize=16)
def _perm_bf(n: int):
    """Exact-in-bf16 rev-roll permutation: P[a, b] = 1 iff a = (n-b) % n.

    Symmetric (the map is an involution), so one matrix serves both the
    sublane and the lane side of the extension kernel's MXU reversal."""
    p = np.zeros((n, n), np.float32)
    p[(n - np.arange(n)) % n, np.arange(n)] = 1.0
    return jnp.asarray(p, jnp.bfloat16)


def _dg0(a: jax.Array, w, prec) -> jax.Array:
    """Leading-dim contraction: (K, ...rest) x (K, N) -> (...rest, N)."""
    return jax.lax.dot_general(
        a, jnp.asarray(w), (((0,), (0,)), ((), ())), precision=prec
    )


def _stage(re, im, wcat, n: int, prec):
    """One complex DFT stage over the LEADING dim: two cat-dots + fused
    combine.  Output planes have the transformed axis's bins in the
    minor dim and the former trailing dims rotated to the front."""
    zr = _dg0(re, wcat, prec)
    zi = _dg0(im, wcat, prec)
    return zr[..., :n] - zi[..., n:], zr[..., n:] + zi[..., :n]


# ----------------------------------------------------------------------
# Hermitian extension kernel (axis 0): out rows 0..m-1 copy the half
# spectrum, row m is the Nyquist plane, rows m+1..n-1 are the mirrored
# source row with both trailing axes index-mapped k -> (n-k) % n.
#
# The fused variant consumes the exit stage's RAW cat-dot outputs
# (zr, zi of shape (m, n1, 2*n2)) and performs the plane combine
# (re = zr[..., :n2] - zi[..., n2:], im = zr[..., n2:] + zi[..., :n2])
# inside VMEM — deleting the 3.2 GB combine pass the XLA stage pays
# (measured −3 ms at 512^3 on the bench v5e).
# ----------------------------------------------------------------------
def _ext_fused_kernel_factory(m: int, n2: int):
    from jax.experimental import pallas as pl

    def kern(p1_ref, p2_ref, zr_ref, zi_ref, nyr_ref, nyi_ref, ore_ref, oim_ref):
        p = pl.program_id(0)

        def combined():
            zr = zr_ref[0]
            zi = zi_ref[0]
            return zr[:, :n2] - zi[:, n2:], zr[:, n2:] + zi[:, :n2]

        @pl.when(p < m)
        def _():
            cre, cim = combined()
            ore_ref[0] = cre
            oim_ref[0] = cim

        @pl.when(p == m)
        def _():
            ore_ref[0] = nyr_ref[...]
            oim_ref[0] = nyi_ref[...]

        @pl.when(p > m)
        def _():
            pj = p1_ref[...]
            pk = p2_ref[...]

            def d(a, b):
                return jax.lax.dot_general(
                    a, b, ((((1,), (0,))), ((), ())),
                    preferred_element_type=jnp.float32,
                )

            def revroll(s):
                hi = s.astype(jnp.bfloat16)
                lo = (s - hi.astype(jnp.float32)).astype(jnp.bfloat16)
                t_hi = d(hi, pk).astype(jnp.bfloat16)
                t_lo = d(lo, pk).astype(jnp.bfloat16)
                return d(pj, t_hi) + d(pj, t_lo)

            cre, cim = combined()
            ore_ref[0] = revroll(cre)
            oim_ref[0] = -revroll(cim)

    return kern


def _ext_fused_pallas(zr, zi, nyr, nyi):
    """Raw exit-dot planes (m, n1, 2*n2) + Nyquist -> full (2m, n1, n2)."""
    from jax.experimental import pallas as pl

    m, n1, n2t = (int(s) for s in zr.shape)
    n2 = n2t // 2
    n = 2 * m

    def src(pidx):
        return jnp.where(pidx < m, pidx, jnp.where(pidx == m, 0, n - pidx))

    return pl.pallas_call(
        _ext_fused_kernel_factory(m, n2),
        grid=(n,),
        in_specs=[
            pl.BlockSpec((n1, n1), lambda p: (0, 0)),
            pl.BlockSpec((n2, n2), lambda p: (0, 0)),
            pl.BlockSpec((1, n1, 2 * n2), lambda p: (src(p), 0, 0)),
            pl.BlockSpec((1, n1, 2 * n2), lambda p: (src(p), 0, 0)),
            pl.BlockSpec((n1, n2), lambda p: (0, 0)),
            pl.BlockSpec((n1, n2), lambda p: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, n1, n2), lambda p: (p, 0, 0)),
            pl.BlockSpec((1, n1, n2), lambda p: (p, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, n1, n2), zr.dtype),
            jax.ShapeDtypeStruct((n, n1, n2), zi.dtype),
        ],
        interpret=jax.default_backend() != "tpu",
    )(_perm_bf(n1), _perm_bf(n2), zr, zi, nyr, nyi)


def _ext_xla(ere, eim, nyr, nyi):
    """XLA fallback extension (roll/rev/concat — pass-count-bound but
    portable; used on CPU and for shapes the kernel's tiles dislike)."""
    from ._planar import hermitian_upper

    m = int(ere.shape[0])
    return (
        jnp.concatenate([ere, nyr[None], hermitian_upper(ere, m - 1)], 0),
        jnp.concatenate([eim, nyi[None], -hermitian_upper(eim, m - 1)], 0),
    )


def _use_pallas_ext(n1: int, n2: int) -> bool:
    if os.environ.get("HEAT_TPU_FFT_EXT_PALLAS", "1") != "1":
        return False
    if jax.default_backend() != "tpu":
        return False
    # one (1, n1, n2) row block per step: keep the tiles exact
    return n1 % 8 == 0 and n2 % 128 == 0 and n1 >= 8 and n2 >= 128


def leading_eligible(re: jax.Array, axes, im_present: bool) -> bool:
    """3-D all-axes f32 full-length transforms; the real path (no im)
    additionally halves axis 0, so n0 must be even."""
    if os.environ.get("HEAT_TPU_FFT_LEADING", "1") != "1":
        return False
    nd = re.ndim
    if nd != 3 or len(axes) != 3 or re.dtype != jnp.float32:
        return False
    if sorted(a % nd for a in axes) != list(range(nd)):
        return False
    if any(int(s) < 2 for s in re.shape):
        return False
    if not im_present and int(re.shape[0]) % 2 != 0:
        return False
    return True


def rfft3_leading(x: jax.Array, norm) -> Tuple[jax.Array, jax.Array]:
    """Full 3-D spectrum of a real (n0, n1, n2) array, all axes.

    Axis 0 is halved to m = n0//2 bins (the Nyquist bin rides a side
    chain), the three stages contract the leading dim in turn — the
    final stage lands the (k0, k1, k2) orientation with no transposes —
    and the Hermitian upper half is assembled by the extension kernel."""
    from ._planar import scale_factor

    n0, n1, n2 = (int(s) for s in x.shape)
    m = n0 // 2
    dt = str(x.dtype)
    prec = _precision()
    s = scale_factor([n0, n1, n2], norm, False)

    re = _dg0(x, _w_entry_half(n0, m, dt, "re"), prec)  # (n1, n2, m)
    im = _dg0(x, _w_entry_half(n0, m, dt, "im"), prec)
    wc1 = _w_cat(n1, dt, False, 1.0)
    wc2 = _w_cat(n2, dt, False, float(s))  # norm folded into the exit
    mre, mim = _stage(re, im, wc1, n1, prec)  # (n2, m, n1)
    fuse_ext = _use_pallas_ext(n1, n2)
    if fuse_ext:
        # leave the exit planes UNcombined — the extension kernel folds
        # the combine into its row pass (one fewer full-size HBM pass)
        zr2 = _dg0(mre, wc2, prec)  # (m, n1, 2n2)
        zi2 = _dg0(mim, wc2, prec)
    else:
        ere, eim = _stage(mre, mim, wc2, n2, prec)  # (m, n1, n2)

    # Nyquist side chain: bin n0/2 of the axis-0 DFT is the alternating
    # sum, then an ordinary 2-D transform of that (real) plane
    alt = jnp.asarray(
        np.where(np.arange(n0) % 2 == 0, 1.0, -1.0).astype(dt)
    )
    nyq = jnp.tensordot(alt, x, ((0,), (0,)))  # (n1, n2)
    a = _dg0(nyq, wc1, prec)  # (n2, 2n1)
    br = _dg0(a[:, :n1], wc2, prec)  # (n1, 2n2)
    bi = _dg0(a[:, n1:], wc2, prec)
    nyr = br[:, :n2] - bi[:, n2:]
    nyi = br[:, n2:] + bi[:, :n2]

    if fuse_ext:
        return _ext_fused_pallas(zr2, zi2, nyr, nyi)
    return _ext_xla(ere, eim, nyr, nyi)


def cfft3_leading(
    re: jax.Array, im: jax.Array, inverse: bool, norm
) -> Tuple[jax.Array, jax.Array]:
    """Full 3-D transform of a complex plane pair, all axes: three
    leading-contraction stages, no transposes, norm folded into the
    exit matrices.  Replaces the interleaved engine's entry/mid/exit +
    two re-pair transposes (measured 46.4 ms -> ~20 ms at 512^3)."""
    from ._planar import scale_factor

    n0, n1, n2 = (int(s) for s in re.shape)
    dt = str(re.dtype)
    prec = _precision()
    s = scale_factor([n0, n1, n2], norm, inverse)

    w0 = _w_cat(n0, dt, inverse, 1.0)
    w1 = _w_cat(n1, dt, inverse, 1.0)
    w2 = _w_cat(n2, dt, inverse, float(s))
    re, im = _stage(re, im, w0, n0, prec)  # (n1, n2, n0)
    re, im = _stage(re, im, w1, n1, prec)  # (n2, n0, n1)
    re, im = _stage(re, im, w2, n2, prec)  # (n0, n1, n2)
    return re, im
