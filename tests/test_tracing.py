"""Request-scoped distributed tracing (ISSUE 10 tentpole).

The contract under test (docs/observability.md "Request tracing"):

* a contextvars trace context stamps ``trace_id``/``span_id``/
  ``parent_id`` into every span opened under it, and the explicit
  handoff helpers carry it across the pipeline's thread hops — the
  coalescer's batcher thread, the introspection server's handler
  threads, and the async checkpoint-writer thread;
* one concurrent ``predict`` yields ONE trace_id shared by the full
  stage tree (admission → coalesce_wait → pad → dispatch → execute →
  scatter) spanning ≥ 2 threads, retained in the tail store even after
  the span ring rotates;
* histogram exemplars remember the most recent trace_id per bucket and
  render in OpenMetrics exemplar syntax;
* the tail store retains the slowest-k and **every** shed/errored
  request, bounded by ``HEAT_TPU_TRACE_KEEP``/``_MAX_SPANS``;
* cross-worker stitching by trace_id in ``aggregate.merge_snapshots``
  is deterministic and order-invariant;
* disabled mode (``HEAT_TPU_TRACE=0``) records nothing anywhere while
  still timing the request (one timing source).
"""

import collections
import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import telemetry
from heat_tpu.resilience import OverloadedError
from heat_tpu.serving.coalescer import ModelBatcher, observe_stage
from heat_tpu.telemetry import aggregate
from heat_tpu.telemetry import flight_recorder
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver
from heat_tpu.telemetry import spans as tspans
from heat_tpu.telemetry import tracing


@pytest.fixture(autouse=True)
def _clean_tracing():
    """Every test starts recording with a clean ring and empty store."""
    prev = telemetry.set_tracing(True)
    prev_ex = tracing.set_exemplars(True)
    telemetry.clear_spans()
    tracing.reset_store()
    yield
    telemetry.set_tracing(prev)
    tracing.set_exemplars(prev_ex)
    telemetry.clear_spans()
    tracing.reset_store()


# ----------------------------------------------------------------------
# context plumbing
# ----------------------------------------------------------------------
class TestContext:
    def test_trace_ids_unique_and_hex(self):
        ids = {tracing.new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        assert all(len(i) == 16 and int(i, 16) >= 0 for i in ids)

    def test_no_ambient_context_by_default(self):
        assert tracing.current_context() is None
        assert tracing.current_trace_id() is None

    def test_use_context_attach_and_restore(self):
        ctx = tracing.TraceContext("aa" * 8, 7)
        with tracing.use_context(ctx) as got:
            assert got == ctx
            assert tracing.current_trace_id() == ctx.trace_id
        assert tracing.current_context() is None
        # None context is a no-op, not an error
        with tracing.use_context(None):
            assert tracing.current_context() is None

    def test_bind_context_carries_across_thread(self):
        ctx = tracing.TraceContext("bb" * 8, 1)
        seen = {}

        def probe():
            seen["tid"] = tracing.current_trace_id()

        with tracing.use_context(ctx):
            bound = tracing.bind_context(probe)
        t = threading.Thread(target=bound, daemon=True)
        t.start()
        t.join()
        assert seen["tid"] == ctx.trace_id

    def test_spans_outside_trace_are_unstamped(self):
        with telemetry.span("plain"):
            pass
        rec = telemetry.get_spans()[-1]
        assert rec.trace_id is None and rec.span_id is None and rec.parent_id is None


# ----------------------------------------------------------------------
# span stamping + the request root
# ----------------------------------------------------------------------
class TestRequestSpan:
    def test_stamping_and_parent_chain(self):
        with tracing.request_span("/t/route") as req:
            with telemetry.span("child"):
                with telemetry.span("grandchild"):
                    pass
        recs = {r.name: r for r in telemetry.get_spans()}
        root, child, grand = recs["serve.request"], recs["child"], recs["grandchild"]
        assert root.trace_id == child.trace_id == grand.trace_id == req.trace_id
        assert root.parent_id == 0  # root of the trace
        assert child.parent_id == root.span_id
        assert grand.parent_id == child.span_id
        assert req.status == "ok" and req.duration_ms > 0

    def test_nested_request_span_joins_not_forks(self):
        with tracing.request_span("/outer") as outer:
            with tracing.request_span("/inner") as inner:
                assert inner.trace_id == outer.trace_id
        retained = tracing.retained_traces()
        # one trace finished, not two
        assert len(retained["recent"]) == 1
        assert retained["recent"][0]["route"] == "/outer"

    def test_status_classification_and_error_retention(self):
        with pytest.raises(ValueError):
            with tracing.request_span("/err") as req:
                raise ValueError("boom")
        assert req.status == "error"
        with pytest.raises(OverloadedError):
            with tracing.request_span("/shed") as req2:
                raise OverloadedError("full", tenant="t", cause="queue")
        assert req2.status == "shed"
        errors = tracing.retained_traces()["errors"]
        assert [e["status"] for e in errors] == ["error", "shed"]
        assert all(e["duration_ms"] is not None for e in errors)

    def test_record_span_explicit_timing(self):
        with tracing.request_span("/rs") as req:
            rec = telemetry.record_span("waited", 1000, 2000, rows=3)
        assert rec.trace_id == req.trace_id and rec.span_id is not None
        doc = tracing.get_trace(req.trace_id)
        assert "waited" in [s["name"] for s in doc["spans"]]

    def test_store_survives_ring_rotation(self, monkeypatch):
        monkeypatch.setattr(tspans, "_RING", collections.deque(maxlen=3))
        with tracing.request_span("/ring") as req:
            for i in range(8):
                with telemetry.span(f"stage{i}"):
                    pass
        assert len(telemetry.get_spans()) == 3  # ring rotated
        doc = tracing.get_trace(req.trace_id)
        assert doc["n_spans"] == 9  # 8 stages + serve.request, all retained


# ----------------------------------------------------------------------
# propagation across the coalescer's thread hop
# ----------------------------------------------------------------------
class TestCoalescerPropagation:
    def _batcher(self, max_delay_s=0.05):
        def infer(rows):
            # the service's stage notes, on the batcher thread (the same
            # buffered form InferenceService._infer_batch uses, so they
            # flush — and mirror — with the batch's own stage notes)
            t = time.perf_counter_ns()
            tspans.stage_note("serve.dispatch", t, 10, rows=int(rows.shape[0]))
            tspans.stage_note("serve.execute", t, 10)
            return rows * 2.0

        return ModelBatcher("tb", infer, max_batch=64, max_delay_s=max_delay_s)

    def test_one_trace_id_full_stage_tree_two_threads(self):
        mb = self._batcher()
        try:
            with tracing.request_span("/v1/predict/tb") as req:
                with telemetry.span("serve.admission"):
                    pass
                out = mb.submit(np.ones((3, 2), np.float32), timeout=30)
            assert np.array_equal(out, np.full((3, 2), 2.0, np.float32))
        finally:
            mb.close()
        doc = tracing.get_trace(req.trace_id)
        names = {s["name"] for s in doc["spans"]}
        assert {
            "serve.request", "serve.admission", "serve.coalesce_wait",
            "serve.pad", "serve.dispatch", "serve.execute", "serve.scatter",
        } <= names
        assert len(names) >= 6
        assert doc["n_threads"] >= 2  # caller + batcher thread
        assert doc["status"] == "ok"
        assert mb.last_batch_trace_id == req.trace_id

    def test_concurrent_requests_get_distinct_complete_traces(self):
        mb = self._batcher(max_delay_s=0.1)
        reqs = {}
        barrier = threading.Barrier(3)

        def client(i):
            barrier.wait()
            with tracing.request_span("/v1/predict/tb", client=i) as req:
                mb.submit(np.full((2, 2), float(i), np.float32), timeout=30)
            reqs[i] = req

        try:
            ts = [threading.Thread(target=client, args=(i,), daemon=True) for i in range(3)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        finally:
            mb.close()
        tids = {r.trace_id for r in reqs.values()}
        assert len(tids) == 3  # one trace per request, never shared
        for req in reqs.values():
            doc = tracing.get_trace(req.trace_id)
            names = {s["name"] for s in doc["spans"]}
            # co-batched requests get the batch spans MIRRORED into
            # their trace; solo batches run them as the primary
            assert {"serve.request", "serve.coalesce_wait", "serve.pad",
                    "serve.dispatch", "serve.execute", "serve.scatter"} <= names
            assert doc["n_threads"] >= 2

    def test_link_spans_restamps_per_trace(self):
        a = tracing._begin("aa" * 8, "/r")
        b = tracing._begin("bb" * 8, "/r")
        rec = tspans.SpanRecord("shared", 0, 10, 1, 0, {}, "aa" * 8, 5, 0)
        tracing.link_spans(["aa" * 8, "bb" * 8], [rec])
        assert b.spans[0].trace_id == "bb" * 8  # re-stamped copy
        assert a.spans == []  # primary already had it via _on_span path
        tracing._finish(a, "ok", 1.0)
        tracing._finish(b, "ok", 1.0)


# ----------------------------------------------------------------------
# async-writer / server-handler thread handoffs
# ----------------------------------------------------------------------
class TestAsyncHandoffs:
    def test_async_checkpoint_writer_inherits_trace(self, tmp_path):
        from heat_tpu.utils.checkpoint import Checkpointer

        ack = Checkpointer(str(tmp_path)).as_async()
        with tracing.request_span("/ckpt") as req:
            ack.save(1, {"w": np.ones(4, np.float32)})
            ack.wait()
        ack.close()
        doc = tracing.get_trace(req.trace_id)
        writes = [s for s in doc["spans"] if s["name"] == "checkpoint.async_write"]
        assert writes, [s["name"] for s in doc["spans"]]
        caller_spans = [s for s in doc["spans"] if s["name"] == "serve.request"]
        assert writes[0]["thread_id"] != caller_spans[0]["thread_id"]

    def test_tracez_endpoint_json_html_and_lookup(self):
        srv = tserver.start_server(0)
        try:
            with tracing.request_span("/v1/predict/m") as req:
                with telemetry.span("serve.admission"):
                    pass
            rep = json.loads(
                urllib.request.urlopen(f"{srv.url}/tracez?format=json", timeout=10).read()
            )
            assert "/v1/predict/m" in rep["routes"]
            assert rep["routes"]["/v1/predict/m"]["recent"][0]["trace_id"] == req.trace_id
            html = urllib.request.urlopen(f"{srv.url}/tracez", timeout=10).read().decode()
            assert req.trace_id in html and "coalesce_wait" in html
            one = json.loads(
                urllib.request.urlopen(
                    f"{srv.url}/tracez?trace_id={req.trace_id}", timeout=10
                ).read()
            )
            # spans sorted by start time: the root opened first
            assert [s["name"] for s in one["spans"]] == ["serve.request", "serve.admission"]
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(f"{srv.url}/tracez?trace_id=deadbeef", timeout=10)
            assert ei.value.code == 404
        finally:
            tserver.stop_server()


# ----------------------------------------------------------------------
# exemplars
# ----------------------------------------------------------------------
class TestExemplars:
    def test_bucket_exemplar_correctness(self):
        h = tm.Histogram("t.ex_ms")
        h.observe(10.4, exemplar="t1")
        h.observe(10.9, exemplar="t2")   # same geometric bucket: t2 wins
        h.observe(1000.0, exemplar="t3")
        h.observe(500.0)                 # no exemplar: bucket untouched
        ex = h.exemplars()
        assert len(ex) == 2
        by_tid = {rec["trace_id"]: le for le, rec in ex.items()}
        assert "t1" not in by_tid  # most recent wins within a bucket
        assert by_tid["t2"] >= 10.9 and by_tid["t3"] >= 1000.0
        for le, rec in ex.items():
            assert rec["value"] <= le

    def test_openmetrics_exposition(self):
        reg = tm.MetricsRegistry()
        h = reg.histogram("stage.x_ms")
        h.observe(3.0, exemplar="abcd")
        h.observe(7.0)
        text = reg.expose()
        lines = [l for l in text.splitlines() if "stage_x_ms" in l]
        assert "# TYPE heat_tpu_stage_x_ms histogram" in lines
        bucket_lines = [l for l in lines if "_bucket" in l]
        assert any('# {trace_id="abcd"} 3' in l for l in bucket_lines)
        assert bucket_lines[-1].startswith('heat_tpu_stage_x_ms_bucket{le="+Inf"} 2')
        # cumulative counts are non-decreasing
        counts = [int(l.split("} ")[1].split(" #")[0]) for l in bucket_lines]
        assert counts == sorted(counts)
        # histograms WITHOUT exemplars keep the summary exposition
        reg.histogram("plain_ms").observe(1.0)
        assert "# TYPE heat_tpu_plain_ms summary" in reg.expose()

    def test_snapshot_carries_exemplars_and_reset_clears(self):
        h = tm.Histogram("t.snap_ms")
        h.observe(5.0, exemplar="xyz")
        snap = h.snapshot()
        assert list(snap["exemplars"].values())[0]["trace_id"] == "xyz"
        h.reset()
        assert h.exemplars() == {} and "exemplars" not in h.snapshot()

    def test_observe_stage_respects_exemplar_toggle(self):
        h = tm.histogram("serving.stage.admission_ms")
        with tracing.use_context(tracing.TraceContext("cc" * 8, 0)):
            tracing.set_exemplars(False)
            observe_stage("admission", 1.0)
            before = dict(h.exemplars())
            tracing.set_exemplars(True)
            observe_stage("admission", 1.0)
        assert any(r["trace_id"] == "cc" * 8 for r in h.exemplars().values())
        assert not any(r["trace_id"] == "cc" * 8 for r in before.values())


# ----------------------------------------------------------------------
# tail store retention
# ----------------------------------------------------------------------
class TestTailStore:
    def _finished(self, duration_ms, status="ok", route="/r"):
        tr = tracing._begin(tracing.new_trace_id(), route)
        tracing._finish(tr, status, duration_ms)
        return tr

    def test_recent_is_bounded_newest_win(self, monkeypatch):
        monkeypatch.setattr(tracing, "_RECENT", collections.deque(maxlen=4))
        ids = [self._finished(1.0).trace_id for _ in range(10)]
        recent = tracing.retained_traces()["recent"]
        assert [t["trace_id"] for t in recent] == ids[-4:]

    def test_slowest_k_retained_after_rotation(self, monkeypatch):
        monkeypatch.setattr(tracing, "_KEEP", 3)
        monkeypatch.setattr(tracing, "_SLOWEST", [])
        monkeypatch.setattr(tracing, "_SLOWEST_DURS", [])
        slow_ids = []
        for i in range(20):
            dur = 1000.0 + i if i % 7 == 0 else 1.0
            tr = self._finished(dur)
            if dur > 100:
                slow_ids.append(tr.trace_id)
        slowest = tracing.retained_traces()["slowest"]
        assert len(slowest) == 3
        assert {t["trace_id"] for t in slowest} == set(slow_ids)
        # slowest first
        durs = [t["duration_ms"] for t in slowest]
        assert durs == sorted(durs, reverse=True)

    def test_shed_and_error_always_retained(self):
        shed = self._finished(0.1, status="shed")
        err = self._finished(0.2, status="error")
        for _ in range(50):
            self._finished(1.0)  # flood with ok traces
        errors = tracing.retained_traces()["errors"]
        assert {t["trace_id"] for t in errors} >= {shed.trace_id, err.trace_id}

    def test_per_trace_span_cap(self, monkeypatch):
        monkeypatch.setattr(tracing, "_MAX_SPANS", 4)
        with tracing.request_span("/cap") as req:
            for i in range(10):
                with telemetry.span(f"s{i}"):
                    pass
        doc = tracing.get_trace(req.trace_id)
        assert doc["n_spans"] == 4 and doc["dropped_spans"] == 7

    def test_refresh_env_resizes(self, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_TRACE_KEEP", "2")
        tracing.refresh_env()
        try:
            assert tracing._KEEP == 2
            for _ in range(5):
                self._finished(1.0)
            assert len(tracing.retained_traces()["recent"]) == 2
        finally:
            monkeypatch.delenv("HEAT_TPU_TRACE_KEEP")
            tracing.refresh_env()

    def test_reset_store(self):
        self._finished(1.0)
        tracing.reset_store()
        rt = tracing.retained_traces()
        assert all(v == [] for v in rt.values())


# ----------------------------------------------------------------------
# cross-worker stitching
# ----------------------------------------------------------------------
def _worker_snap(ix, traces):
    return {
        "process_index": ix,
        "process_count": 2,
        "pid": 100 + ix,
        "timestamp": 1.0,
        "metrics": {},
        "span_stats": {},
        "traces": traces,
    }


class TestStitching:
    def test_stitch_by_trace_id_deterministic(self):
        tid = "ab" * 8
        a = _worker_snap(0, [{"trace_id": tid, "route": "/r", "status": "ok",
                              "duration_ms": 5.0, "n_spans": 7, "n_threads": 2,
                              "stages": {"serve.dispatch": {"count": 1, "total_ms": 3.0}}}])
        b = _worker_snap(1, [{"trace_id": tid, "route": "/r", "status": "ok",
                              "duration_ms": 9.0, "n_spans": 3, "n_threads": 1,
                              "stages": {"comm.psum": {"count": 2, "total_ms": 1.0}}}])
        m1 = aggregate.merge_snapshots([a, b], publish=False)
        m2 = aggregate.merge_snapshots([b, a], publish=False)
        assert json.dumps(m1, sort_keys=True) == json.dumps(m2, sort_keys=True)
        st = m1["traces"][tid]
        assert set(st["workers"]) == {"0", "1"}
        assert st["span_count"] == 10
        assert st["duration_ms"] == 9.0  # the slowest worker's view
        assert st["workers"]["1"]["stages"]["comm.psum"]["count"] == 2

    def test_worst_status_wins(self):
        tid = "cd" * 8
        a = _worker_snap(0, [{"trace_id": tid, "route": "/r", "status": "ok",
                              "duration_ms": 1.0, "n_spans": 1, "n_threads": 1, "stages": {}}])
        b = _worker_snap(1, [{"trace_id": tid, "route": "/r", "status": "error",
                              "duration_ms": 1.0, "n_spans": 1, "n_threads": 1, "stages": {}}])
        assert aggregate.stitch_traces([a, b])[tid]["status"] == "error"

    def test_local_snapshot_carries_digests(self):
        with tracing.request_span("/v1/predict/m"):
            pass
        snap = aggregate.tag_snapshot()
        assert any(t["route"] == "/v1/predict/m" for t in snap["traces"])


# ----------------------------------------------------------------------
# crash bundle + inspect rendering
# ----------------------------------------------------------------------
class TestFlightRecorderTraces:
    def test_bundle_carries_in_flight_trace(self):
        req = tracing.request_span("/v1/predict/crash")
        req.__enter__()
        try:
            with telemetry.span("serve.admission"):
                pass
            doc = flight_recorder.build_bundle(RuntimeError("x"), reason="test")
            active = doc["traces"]["active"]
            assert [t["trace_id"] for t in active] == [req.trace_id]
            assert "serve.admission" in [s["name"] for s in active[0]["spans"]]
        finally:
            req.__exit__(None, None, None)
        # after the crash handler, the finished trace is retained
        doc2 = flight_recorder.build_bundle(None, reason="test")
        assert doc2["traces"]["active"] == []
        assert any(t["trace_id"] == req.trace_id for t in doc2["traces"]["recent"])

    def test_inspect_renders_traces_section(self):
        from heat_tpu.telemetry.inspect import format_bundle

        req = tracing.request_span("/v1/predict/crash")
        req.__enter__()
        try:
            doc = flight_recorder.build_bundle(RuntimeError("x"), reason="test")
        finally:
            req.__exit__(None, None, None)
        doc = json.loads(json.dumps(doc, default=str))  # the on-disk form
        text = format_bundle(doc)
        assert "request traces" in text and req.trace_id in text


# ----------------------------------------------------------------------
# disabled mode: zero writes, one timing source
# ----------------------------------------------------------------------
class TestDisabledMode:
    def test_zero_writes_everywhere(self):
        telemetry.set_tracing(False)
        snap_before = telemetry.snapshot()
        with tracing.request_span("/ghost") as req:
            with telemetry.span("stage"):
                pass
            telemetry.record_span("explicit", 0, 1)
        assert req.trace_id is None
        assert req.duration_ms > 0  # still the timing source
        assert req.status == "ok"
        assert telemetry.get_spans() == []
        rt = tracing.retained_traces()
        assert all(v == [] for v in rt.values())
        snap_after = telemetry.snapshot()
        tr_keys = [k for k in set(snap_before) | set(snap_after)
                   if k.startswith(("tracing.", "spans."))]
        for k in tr_keys:
            assert snap_after.get(k) == snap_before.get(k), k

    def test_disabled_spans_cost_no_context(self):
        telemetry.set_tracing(False)
        with tracing.use_context(tracing.TraceContext("ee" * 8, 0)):
            with telemetry.span("s"):
                # disabled span must not consume span ids / set context
                assert tracing.current_context().span_id == 0
