"""Control-plane protocol verifier tests (ISSUE 20 tentpole).

The contract under test (docs/static_analysis.md "Protocol rules"):

* ``analysis/protocols.py`` holds the pure-literal ``PROTOCOLS`` /
  ``ENVIRONMENT`` / ``PROPERTIES`` registries (``ast.literal_eval``
  verifiable), structurally sound (``registry_problems() == []``), and
  the centralized journal vocabulary constants match exactly the pairs
  the registry declares;
* the H801-H804 AST rules catch: controller state written outside a
  registered transition function, a transition function missing its
  declared journal emit, an emit with an undeclared ``(actor, action)``
  literal, and a malformed/unreachable registry;
* the bounded model checker runs clean on the shipped registry and
  produces counterexample journal chains for each seeded defect class
  (livelock, invariant breach, flap);
* runtime conformance (``HEAT_TPU_PROTOCOL_CHECK``) steps every live
  emit through the declared machines: legal controller flows are clean,
  illegal transitions surface as H805 + a ``protocol:<actor>`` alert,
  raise mode turns the first violation into ``ProgramLintError``;
* the real controllers (service lifecycle, preemption gate, alerts,
  router breaker, autoscaler) conform end to end with checking armed;
* ``python -m heat_tpu.telemetry.replay <dir> --check`` verdicts the
  durable log offline; ``/decisionz?event_id=`` annotates the explain
  view with declared transitions; the docs diagrams match the
  generator.
"""

import ast
import json
import os
import sys

import pytest

from heat_tpu.analysis import ast_lint
from heat_tpu.analysis import conformance as conf
from heat_tpu.analysis import model_check as mc
from heat_tpu.analysis import protocols as proto
from heat_tpu.analysis.diagnostics import ProgramLintError, clear_diagnostics, recent_diagnostics
from heat_tpu.telemetry import alerts as talerts
from heat_tpu.telemetry import journal as tjournal
from heat_tpu.telemetry import replay as treplay

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_state():
    tjournal.set_journal_dir(None)
    tjournal.reset_journal()
    talerts.clear_alerts()
    clear_diagnostics()
    conf.set_protocol_mode("0")
    yield
    tjournal.set_journal_dir(None)
    tjournal.reset_journal()
    talerts.clear_alerts()
    clear_diagnostics()
    conf.set_protocol_mode("0")


# ----------------------------------------------------------------------
# registry hygiene
# ----------------------------------------------------------------------
class TestRegistryHygiene:
    def _literal(self, name):
        src = open(os.path.join(REPO_ROOT, "heat_tpu/analysis/protocols.py")).read()
        for node in ast.parse(src).body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
            ):
                return ast.literal_eval(node.value)
        raise AssertionError(f"{name} not found at module level")

    def test_registries_are_pure_literals(self):
        # ast.literal_eval must reproduce the live objects exactly: no
        # computed values, no interpolation, no imports involved
        assert self._literal("PROTOCOLS") == proto.PROTOCOLS
        assert self._literal("ENVIRONMENT") == proto.ENVIRONMENT
        assert list(self._literal("PROPERTIES")) == list(proto.PROPERTIES)

    def test_registry_structurally_sound(self):
        assert proto.registry_problems() == []

    def test_registry_problems_catches_defects(self):
        import copy

        bad = copy.deepcopy(proto.PROTOCOLS)
        bad["preempt"]["states"] = ("idle", "raised", "orphan")
        assert any("orphan" in p for p in proto.registry_problems(bad))
        bad = copy.deepcopy(proto.PROTOCOLS)
        bad["preempt"]["initial"] = "nowhere"
        assert any("initial" in p for p in proto.registry_problems(bad))
        bad = copy.deepcopy(proto.PROTOCOLS)
        bad["preempt"]["actor"] = "alerts"
        bad["preempt"]["transitions"] = (
            dict(bad["preempt"]["transitions"][0], action="fire"),
        ) + tuple(bad["preempt"]["transitions"][1:])
        assert any("already declared" in p for p in proto.registry_problems(bad))

    def test_constants_match_declared_pairs(self):
        # the centralized vocabulary derives from PROTOCOLS: every
        # declared (actor, action) pair is reachable through the module
        # constants, and no constant names an undeclared actor
        consts = {
            name: getattr(proto, name)
            for name in dir(proto)
            if name.isupper() and isinstance(getattr(proto, name), str)
            and name not in ("ENVIRONMENT",)
        }
        actor_values = {v for k, v in consts.items() if k.startswith("ACTOR_")}
        action_values = {v for k, v in consts.items() if not k.startswith("ACTOR_")}
        declared = proto.declared_pairs()
        assert {a for a, _ in declared} == actor_values
        assert {a for _, a in declared} <= action_values

    def test_every_pair_owned_by_one_protocol(self):
        for actor, action in sorted(proto.declared_pairs()):
            owners = proto.protocol_for_pair(actor, action)
            assert len(owners) == 1, (actor, action, owners)

    def test_declared_modules_and_transition_fns_exist(self):
        for name, rec in sorted(proto.PROTOCOLS.items()):
            path = os.path.join(REPO_ROOT, rec["module"])
            assert os.path.isfile(path), (name, rec["module"])
            src = open(path).read()
            tree = ast.parse(src)
            defined = {
                n.name for n in ast.walk(tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            }
            for fn in rec["transition_fns"]:
                assert fn in defined, (name, rec["module"], fn)

    def test_transition_index_shape(self):
        idx = proto.transition_index()
        assert set(idx) == proto.declared_pairs()
        p, scope, edges = idx[("preempt", "raise")]
        assert p == "preempt" and scope == "gate"
        assert ("idle", "raised") in edges


# ----------------------------------------------------------------------
# AST rules H801-H804 (seeded-defect fixtures through lint_file)
# ----------------------------------------------------------------------
class TestAstRules:
    def test_repo_is_clean(self):
        # in-process (scripts/lint_gate.py's run_gate) — a subprocess
        # would re-pay interpreter + package import on every tier-1 run
        sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
        try:
            from lint_gate import run_gate
        finally:
            sys.path.pop(0)
        res = run_gate(quiet=True)
        assert res["new_count"] == 0, res["new"]

    def test_h801_state_write_outside_transition_fn(self):
        bad = (
            "class Replica:\n"
            "    def rogue(self):\n"
            "        self.cb_open = True\n"
        )
        v = ast_lint.lint_file("heat_tpu/fleet/router.py", source=bad)
        assert any(x.rule == "H801" for x in v)

    def test_h801_sanctioned_fn_is_clean(self):
        ok = (
            "class Replica:\n"
            "    def _cb_mark_probe(self):\n"
            "        self.cb_open = True\n"
        )
        v = ast_lint.lint_file("heat_tpu/fleet/router.py", source=ok)
        assert not any(x.rule == "H801" for x in v)

    def test_h801_subscript_state_key(self):
        bad = (
            "def rogue(st):\n"
            "    st['verdict'] = 'promoted'\n"
        )
        v = ast_lint.lint_file("heat_tpu/serving/canary.py", source=bad)
        assert any(x.rule == "H801" for x in v)

    def test_h802_transition_fn_missing_emit(self):
        bad = (
            "class R:\n"
            "    def _pick(self):\n"
            "        pass\n"
            "    def _report(self):\n"
            "        pass\n"
        )
        v = ast_lint.lint_file("heat_tpu/fleet/router.py", source=bad)
        assert any(x.rule == "H802" for x in v)

    def test_h803_undeclared_pair_literal(self):
        bad = (
            "from ..telemetry import journal as _journal\n"
            "def f():\n"
            "    _journal.emit('router', 'cb_explode')\n"
        )
        v = ast_lint.lint_file("heat_tpu/fleet/router.py", source=bad)
        assert any(x.rule == "H803" for x in v)

    def test_h803_declared_pair_is_clean(self):
        ok = (
            "from ..analysis.protocols import ACTOR_ROUTER, CB_TRIP\n"
            "from ..telemetry import journal as _journal\n"
            "def f():\n"
            "    _journal.emit(ACTOR_ROUTER, CB_TRIP)\n"
            "    _journal.emit('preempt', 'raise')\n"
        )
        v = ast_lint.lint_file("heat_tpu/fleet/router.py", source=ok)
        assert not any(x.rule == "H803" for x in v)

    def test_h804_unreachable_state(self):
        src = open(os.path.join(REPO_ROOT, "heat_tpu/analysis/protocols.py")).read()
        bad = src.replace(
            '"states": ("idle", "raised")',
            '"states": ("idle", "raised", "orphan")',
        )
        assert bad != src
        v = ast_lint.lint_file("heat_tpu/analysis/protocols.py", source=bad)
        assert any(x.rule == "H804" for x in v)

    def test_h804_impure_registry(self):
        src = open(os.path.join(REPO_ROOT, "heat_tpu/analysis/protocols.py")).read()
        bad = src.replace("PROTOCOLS = {", "PROTOCOLS = dict_maker() or {", 1)
        assert bad != src
        v = ast_lint.lint_file("heat_tpu/analysis/protocols.py", source=bad)
        assert any(x.rule == "H804" and "literal" in x.message for x in v)


# ----------------------------------------------------------------------
# bounded model checker
# ----------------------------------------------------------------------
class TestModelChecker:
    def test_shipped_registry_is_clean(self):
        assert mc.check_all() == []

    @pytest.mark.parametrize("defect,prop", [
        ("refresh_livelock", "refresh_no_livelock"),
        ("breaker_double_probe", "breaker_single_probe"),
        ("autoscaler_flap", "autoscaler_no_flap"),
    ])
    def test_seeded_defects_are_found(self, defect, prop):
        protocols, environment, properties = mc.seeded_defect(defect)
        hits = mc.check_all(protocols, environment, properties)
        assert prop in {h["property"] for h in hits}
        hit = next(h for h in hits if h["property"] == prop)
        chain = hit["counterexample"]
        # the counterexample is a synthetic causal journal chain: same
        # doc shape as telemetry/journal.py, each step cause-linked
        assert chain[0]["cause"] is None
        for prev, ev in zip(chain, chain[1:]):
            assert ev["cause"] == prev["event_id"]
        assert chain[-1]["actor"] == "model_check"
        assert chain[-1]["action"] == "violation"

    def test_livelock_cycle_contains_trigger_and_veto(self):
        protocols, environment, properties = mc.seeded_defect("refresh_livelock")
        hits = mc.check_all(protocols, environment, properties)
        hit = next(h for h in hits if h["property"] == "refresh_no_livelock")
        cycle_actions = {
            ev["action"] for ev in hit["counterexample"]
            if ev["evidence"].get("part") == "cycle"
        }
        assert {"trigger", "veto"} <= cycle_actions
        # the decisive canary verdicts never appear in the loop
        assert not ({"promoted", "rolled_back", "observed"} & cycle_actions)

    def test_state_bound_enforced(self):
        with pytest.raises(mc.ModelCheckError):
            mc.check_all(max_states=2)

    def test_cli_exit_codes(self, capsys):
        # main(argv) in-process: same entry point the console uses,
        # without a fresh interpreter per invocation
        assert mc.main([]) == 0
        capsys.readouterr()
        assert mc.main(["--seed-defect", "refresh_livelock", "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["violations"]


# ----------------------------------------------------------------------
# runtime conformance (H805)
# ----------------------------------------------------------------------
class TestRuntimeConformance:
    def test_off_by_default_records_nothing(self):
        assert conf.protocol_mode() == "off"
        tjournal.emit("preempt", "clear", evidence={"gate": "gX"})
        assert conf.conformance_report()["violations"] == 0

    def test_legal_flow_clean(self):
        conf.set_protocol_mode("warn")
        tjournal.emit("preempt", "raise", evidence={"gate": "g0"})
        tjournal.emit("preempt", "clear", evidence={"gate": "g0"})
        rep = conf.conformance_report()
        assert rep["violations"] == 0 and rep["tracked_instances"] >= 1

    def test_illegal_transition_reports_h805(self):
        conf.set_protocol_mode("warn")
        with pytest.warns(Warning):
            tjournal.emit("preempt", "clear", evidence={"gate": "g1"})
        rep = conf.conformance_report()
        assert rep["violations"] == 1
        v = rep["recent"][0]
        assert v["protocol"] == "preempt" and v["from"] == "idle"
        # surfaced as the H805 diagnostic + a protocol:<actor> alert
        assert any(d.rule == "H805" for d in recent_diagnostics())
        assert any(
            a["name"] == "protocol:preempt" for a in talerts.active_alerts()
        )

    def test_scope_isolates_instances(self):
        conf.set_protocol_mode("warn")
        tjournal.emit("preempt", "raise", evidence={"gate": "gA"})
        # gB never raised: its machine is still idle, so a clear there
        # is a violation even though gA's raise is outstanding
        with pytest.warns(Warning):
            tjournal.emit("preempt", "clear", evidence={"gate": "gB"})
        assert conf.conformance_report()["violations"] == 1

    def test_unknown_actor_ignored(self):
        conf.set_protocol_mode("warn")
        tjournal.emit("some_future_subsystem", "anything")
        assert conf.conformance_report()["violations"] == 0

    def test_undeclared_action_from_known_actor(self):
        conf.set_protocol_mode("warn")
        with pytest.warns(Warning):
            tjournal.emit("router", "cb_explode", evidence={"replica": "r"})
        rep = conf.conformance_report()
        assert rep["violations"] == 1
        assert "undeclared" in rep["recent"][0]["message"]

    def test_raise_mode(self):
        conf.set_protocol_mode("raise")
        with pytest.raises(ProgramLintError):
            tjournal.emit("preempt", "clear", evidence={"gate": "g9"})

    def test_resync_prevents_cascade(self):
        conf.set_protocol_mode("warn")
        with pytest.warns(Warning):
            tjournal.emit("preempt", "clear", evidence={"gate": "gR"})
        # after the resync the follow-up legal flow is clean again
        tjournal.emit("preempt", "raise", evidence={"gate": "gR"})
        tjournal.emit("preempt", "clear", evidence={"gate": "gR"})
        assert conf.conformance_report()["violations"] == 1

    def test_reset_journal_resets_conformance(self):
        conf.set_protocol_mode("warn")
        with pytest.warns(Warning):
            tjournal.emit("preempt", "clear", evidence={"gate": "gZ"})
        tjournal.reset_journal()
        assert conf.conformance_report()["violations"] == 0


# ----------------------------------------------------------------------
# real controllers conform end to end with checking armed
# ----------------------------------------------------------------------
class TestControllersConform:
    def test_preemption_gate_conforms(self):
        from heat_tpu.core.preempt import PreemptionGate

        conf.set_protocol_mode("warn")
        gate = PreemptionGate()
        gate.request("latency spike")
        gate.request("still spiking")  # level-triggered: no second raise
        gate.clear()
        gate.clear()  # idempotent: no second clear event
        assert conf.conformance_report()["violations"] == 0

    def test_alert_lifecycle_conforms(self):
        conf.set_protocol_mode("warn")
        talerts.fire("proto_test_alert", severity="warn", message="x")
        talerts.fire("proto_test_alert", severity="warn", message="x")
        talerts.resolve("proto_test_alert")
        assert conf.conformance_report()["violations"] == 0

    def test_service_lifecycle_conforms(self):
        from heat_tpu import serving

        conf.set_protocol_mode("warn")
        svc = serving.InferenceService()
        try:
            svc.set_state("warming")
            svc.set_state("ready")
            svc.set_state("draining")
        finally:
            svc.close()
        assert svc.state == "stopped"
        assert conf.conformance_report()["violations"] == 0

    def test_router_breaker_conforms(self):
        from heat_tpu.fleet.router import FleetRouter, _Replica

        conf.set_protocol_mode("warn")
        router = FleetRouter(cb_failures=2, cb_cooldown_s=0.0,
                             health_period_s=900.0)
        try:
            router.add_replica("http://127.0.0.1:1")
            with router._lock:
                r = next(iter(router._replicas.values()))
                r.ready = True
            # closed -> open (two consecutive failures)
            router._report(r, ok=False)
            router._report(r, ok=False)
            assert r.cb_open and not r.probing
            # open -> half_open (cooldown over: _pick admits the probe)
            picked = router._pick("")
            assert picked is r and r.probing
            # half_open -> open (failed probe: the cb_reopen defect fix)
            router._report(r, ok=False)
            assert r.cb_open and not r.probing
            # around again, probe succeeds: half_open -> closed
            picked = router._pick("")
            assert picked is r
            router._report(r, ok=True)
            assert not r.cb_open
        finally:
            router.close()
        actions = [
            e["action"] for e in tjournal.journal_events()
            if e["actor"] == "router"
        ]
        assert actions == ["cb_trip", "cb_half_open", "cb_reopen",
                           "cb_half_open", "cb_readmit"]
        assert conf.conformance_report()["violations"] == 0

    def test_stale_success_while_open_does_not_readmit(self):
        # the real defect this PR fixed: a success landing while the
        # breaker is open with NO probe out must not skip the half-open
        # protocol (previously it readmitted immediately)
        from heat_tpu.fleet.router import FleetRouter

        conf.set_protocol_mode("warn")
        router = FleetRouter(cb_failures=2, cb_cooldown_s=60.0,
                             health_period_s=900.0)
        try:
            router.add_replica("http://127.0.0.1:1")
            with router._lock:
                r = next(iter(router._replicas.values()))
                r.ready = True
            router._report(r, ok=False)
            router._report(r, ok=False)
            assert r.cb_open
            router._report(r, ok=True)  # stale pre-trip response
            assert r.cb_open, "stale success must not readmit an open breaker"
        finally:
            router.close()
        assert conf.conformance_report()["violations"] == 0


# ----------------------------------------------------------------------
# replay --check + /decisionz explain
# ----------------------------------------------------------------------
class TestOfflineChecking:
    def test_replay_check_clean_and_violating(self, tmp_path):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        tjournal.emit("preempt", "raise", evidence={"gate": "g0"})
        tjournal.emit("preempt", "clear", evidence={"gate": "g0"})
        doc = treplay.replay_report(d, check=True)
        assert doc["check"]["violation_count"] == 0
        assert doc["check"]["stepped"] >= 2

        tjournal.emit("preempt", "clear", evidence={"gate": "gBad"})
        doc = treplay.replay_report(d, check=True)
        assert doc["check"]["violation_count"] == 1
        assert "illegal" in doc["check"]["violations"][0]["message"]

    def test_replay_check_cli_exit_code(self, tmp_path, capsys):
        d = str(tmp_path / "journal")
        tjournal.set_journal_dir(d)
        tjournal.emit("preempt", "clear", evidence={"gate": "gBad"})
        rc = treplay.main([d, "--check"])
        out = capsys.readouterr().out
        assert rc == 2, out
        assert "H805" in out

    def test_annotate_resets_on_epoch_change(self):
        # a restarted process's controllers legitimately start over: the
        # same scope key in a new epoch begins from the initial state
        events = [
            {"event_id": "aaa-111-000001", "actor": "preempt",
             "action": "raise", "evidence": {"gate": "g"}},
            {"event_id": "bbb-222-000001", "actor": "preempt",
             "action": "raise", "evidence": {"gate": "g"}},
        ]
        ann = conf.annotate(events)
        assert ann["aaa-111-000001"]["ok"]
        assert ann["bbb-222-000001"]["ok"]

    def test_decisionz_explain_annotates_transitions(self):
        ev = tjournal.emit("preempt", "raise", evidence={"gate": "g0"})
        tjournal.emit("preempt", "clear", cause=ev["event_id"],
                      evidence={"gate": "g0"})
        html = tjournal.render_decisionz_html(event_id=ev["event_id"])
        assert "<th>protocol</th>" in html
        assert "idle" in html and "raised" in html

    def test_decisionz_explain_flags_violations(self):
        ev = tjournal.emit("preempt", "clear", evidence={"gate": "gBad"})
        html = tjournal.render_decisionz_html(event_id=ev["event_id"])
        assert "H805" in html and "illegal" in html

    def test_timeline_view_has_no_protocol_column(self):
        tjournal.emit("preempt", "raise", evidence={"gate": "g0"})
        html = tjournal.render_decisionz_html()
        assert "<th>protocol</th>" not in html


# ----------------------------------------------------------------------
# docs stay generated
# ----------------------------------------------------------------------
class TestDocs:
    def test_observability_diagrams_match_generator(self):
        text = open(os.path.join(REPO_ROOT, "docs", "observability.md")).read()
        begin = "<!-- protocol-diagrams:begin -->"
        end = "<!-- protocol-diagrams:end -->"
        assert begin in text and end in text
        embedded = text.split(begin, 1)[1].split(end, 1)[0].strip()
        assert embedded == proto.render_diagrams_markdown().strip()

    def test_static_analysis_documents_rules(self):
        text = open(os.path.join(REPO_ROOT, "docs", "static_analysis.md")).read()
        for rule in ("H801", "H802", "H803", "H804", "H805"):
            assert rule in text
