"""Clustering estimators (analog of heat/cluster)."""

from ._kcluster import _KCluster
from .kmeans import KMeans
from .kmedians import KMedians
from .kmedoids import KMedoids
from .spectral import Spectral
from .batchparallelclustering import BatchParallelKMeans, BatchParallelKMedians

__all__ = [
    "KMeans",
    "KMedians",
    "KMedoids",
    "Spectral",
    "BatchParallelKMeans",
    "BatchParallelKMedians",
]
