"""NN / optimizer / data-tooling tests (reference:
heat/nn/tests/test_data_parallel.py, heat/optim/tests)."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture(scope="module")
def mlp():
    import flax.linen as lnn

    class MLP(lnn.Module):
        @lnn.compact
        def __call__(self, x):
            x = x.reshape((x.shape[0], -1))
            x = lnn.Dense(32)(x)
            x = lnn.relu(x)
            return lnn.Dense(2)(x)

    return MLP()


def test_nn_fallthrough():
    import flax.linen as lnn

    assert ht.nn.Dense is lnn.Dense
    import jax.nn

    assert ht.nn.functional.relu is jax.nn.relu
    import optax

    assert ht.optim.SGD is optax.sgd
    assert ht.optim.Adam is optax.adam


def test_data_parallel_forward(mlp):
    import jax

    dp = ht.nn.DataParallel(mlp)
    x = ht.random.randn(16, 4, split=0)
    dp.init(jax.random.PRNGKey(0), x)
    out = dp(x)
    assert out.shape == (16, 2)
    assert out.split == 0


def test_data_parallel_training(mlp):
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(0)
    X = rng.standard_normal((64, 4)).astype(np.float32)
    w = np.array([1.0, -2.0, 0.5, 3.0], dtype=np.float32)
    y = (X @ w > 0).astype(np.int32)

    dp = ht.nn.DataParallel(mlp, optimizer=optax.adam(1e-2))
    dp.init(jax.random.PRNGKey(0), ht.array(X, split=0))

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    xs = ht.array(X, split=0)
    ys = ht.array(y, split=0)
    losses = [dp.step(loss_fn, xs, ys) for _ in range(60)]
    assert losses[-1] < losses[0] * 0.3, f"loss did not drop: {losses[0]} -> {losses[-1]}"
    pred = np.argmax(dp(xs).numpy(), axis=1)
    assert np.mean(pred == y) > 0.9


def test_daso_step(mlp):
    import jax
    import optax

    params = {"w": np.ones((4,), dtype=np.float32)}
    daso = ht.optim.DASO(local_optimizer=optax.sgd(0.1), total_epochs=10, warmup_epochs=1, cooldown_epochs=1)
    grads = {"w": np.full((4,), 0.5, dtype=np.float32)}
    p = params
    for _ in range(5):
        p = daso.step(p, grads)
    assert p["w"].shape == (4,)
    assert float(np.asarray(p["w"])[0]) < 1.0
    # phase logic moves skips
    daso.epoch = 5
    daso.epoch_loss_logic(1.0)
    assert daso.global_skip > 0
    st = daso.get_state()
    daso.set_state(st)
    p = daso.last_batch(p)


def test_dp_optimizer():
    import optax

    opt = ht.optim.DataParallelOptimizer(optax.sgd(0.5))
    params = {"a": np.array([2.0], dtype=np.float32)}
    grads = {"a": np.array([1.0], dtype=np.float32)}
    new = opt.step(params, grads)
    np.testing.assert_allclose(np.asarray(new["a"]), [1.5])


def test_detect_plateau():
    d = ht.optim.DetectMetricPlateau(patience=2)
    assert not d.test_if_improving(1.0)
    assert not d.test_if_improving(1.0)
    assert not d.test_if_improving(1.0)
    assert d.test_if_improving(1.0)  # patience exceeded -> plateau signal


def test_dataset_dataloader():
    x = ht.arange(20, dtype=ht.float32, split=0).reshape((10, 2))
    y = ht.arange(10, split=0)
    ds = ht.utils.data.Dataset([x, y])
    assert len(ds) == 10
    loader = ht.utils.data.DataLoader(ds, batch_size=4, shuffle=True, drop_last=False)
    seen = []
    for xb, yb in loader:
        assert xb.shape[1] == 2
        seen.extend(np.asarray(yb).tolist())
    assert sorted(seen) == list(range(10))
    ht.utils.data.dataset_shuffle(ds)
    assert sorted(ds.arrays[1].numpy().tolist()) == list(range(10))


def test_matrixgallery():
    g = ht.utils.data.matrixgallery
    q = g.random_orthogonal(12, 4)
    np.testing.assert_allclose(q.numpy().T @ q.numpy(), np.eye(4), atol=1e-5)
    A, (U, S, V) = g.random_known_singularvalues(10, 8, [3.0, 2.0, 1.0])
    np.testing.assert_allclose(
        np.linalg.svd(A.numpy(), compute_uv=False)[:3], [3.0, 2.0, 1.0], rtol=1e-4
    )
    A2, _ = g.random_known_rank(10, 8, 3)
    assert np.linalg.matrix_rank(A2.numpy(), tol=1e-4) == 3
    p = g.parter(5)
    assert p.shape == (5, 5)
    h = g.hermitian(6, dtype=ht.float32, positive_definite=True)
    ev = np.linalg.eigvalsh(h.numpy())
    assert ev.min() > 0


def test_spherical_generators():
    d = ht.utils.data.create_spherical_dataset(10, radius=0.5, offset=3.0)
    assert d.shape == (40, 3)
    c = ht.utils.data.create_clusters(30, 2, 3, np.zeros((3, 2)), np.ones((3, 2)))
    assert c.shape == (30, 2)


def test_synthetic_mnist_and_partial_h5(tmp_path):
    x, y = ht.utils.data.synthetic_mnist(64)
    assert x.shape == (64, 28, 28, 1)
    assert y.shape == (64,)

    import h5py

    f = tmp_path / "part.h5"
    with h5py.File(f, "w") as h:
        h.create_dataset("data", data=np.arange(100.0).reshape(25, 4))
    ds = ht.utils.data.PartialH5Dataset(str(f), dataset_names=["data"], load_length=10)
    chunks = list(iter(ds))
    assert len(chunks) == 3
    total = np.concatenate([np.asarray(c) for c in chunks])
    np.testing.assert_allclose(total, np.arange(100.0).reshape(25, 4))


def test_func_getattr():
    # reference nn/functional.py:9 — falls through to the substrate's functional ns
    import jax.numpy as jnp

    from heat_tpu.nn.functional import func_getattr

    relu = func_getattr("relu")
    np.testing.assert_allclose(np.asarray(relu(jnp.array([-1.0, 2.0]))), [0.0, 2.0])
    with pytest.raises(AttributeError):
        func_getattr("definitely_not_a_function")


def test_dataset_ishuffle_irecv_cycle():
    # reference datatools.py:305/:344 — start/complete split of the epoch shuffle
    x = ht.random.randn(12, 3, split=0)
    before = x.numpy().copy()
    ds = ht.utils.data.Dataset(x, ishuffle=True)
    ht.utils.data.dataset_ishuffle(ds)
    assert ds._pending_shuffle is not None
    ht.utils.data.dataset_irecv(ds)
    assert ds._pending_shuffle is None
    after = ds.arrays[0].numpy()
    # same multiset of rows, (almost surely) different order
    np.testing.assert_allclose(np.sort(before, axis=0), np.sort(after, axis=0))
    # irecv with nothing pending is a no-op
    ht.utils.data.dataset_irecv(ds)


def test_tfrecord_index_tools(tmp_path):
    # reference _utils.py:13 — offset/length index over TFRecord framing
    import struct

    from heat_tpu.utils.data._utils import dali_tfrecord2idx, tfrecord_index

    train = tmp_path / "train"
    val = tmp_path / "val"
    ti, vi = tmp_path / "ti", tmp_path / "vi"
    train.mkdir()
    val.mkdir()
    for d, name in ((train, "train-0"), (val, "val-0")):
        with open(d / name, "wb") as f:
            for payload in (b"abc", b"defgh", b"x" * 11):
                f.write(struct.pack("<Q", len(payload)) + b"\0" * 4 + payload + b"\0" * 4)
    spans = tfrecord_index(str(train / "train-0"))
    assert spans == [(0, 19), (19, 21), (40, 27)]
    dali_tfrecord2idx(str(train), str(ti), str(val), str(vi))
    assert (ti / "train-0").read_text().splitlines() == ["0 19", "19 21", "40 27"]
    assert (vi / "val-0").read_text().splitlines()[0] == "0 19"


def test_types_complex_alias():
    # reference types.py:368 names the abstract complex class plain `complex`
    assert ht.complex is ht.types.complexfloating
    assert issubclass(ht.complex64, ht.complex)


def test_partial_h5_error_propagation_and_early_break(tmp_path):
    import h5py

    f = tmp_path / "err.h5"
    with h5py.File(f, "w") as h:
        h.create_dataset("data", data=np.arange(40.0).reshape(10, 4))

    def bad_transform(x):
        raise ValueError("boom")

    ds = ht.utils.data.PartialH5Dataset(str(f), load_length=3, transforms=bad_transform)
    with pytest.raises(ValueError, match="boom"):
        next(iter(ds))

    # breaking out early retires the worker thread instead of leaking it
    ds2 = ht.utils.data.PartialH5Dataset(str(f), load_length=3)
    it = iter(ds2)
    next(it)
    it.close()
    with pytest.raises(StopIteration):
        next(it)


def test_train_steps_matches_sequential_steps(mlp):
    """The scanned multi-step program must walk the identical parameter
    trajectory as K sequential step() dispatches over the same batches."""
    import jax
    import jax.numpy as jnp
    import optax

    rng = np.random.default_rng(7)
    n_steps, batch = 5, 16
    xs = rng.standard_normal((n_steps, batch, 4)).astype(np.float32)
    ys = (xs @ np.array([1.0, -2.0, 0.5, 3.0], np.float32) > 0).astype(np.int32)

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    dp_seq = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    dp_seq.init(jax.random.PRNGKey(1), jnp.asarray(xs[0]))
    seq_losses = [dp_seq.step(loss_fn, xs[k], ys[k]) for k in range(n_steps)]

    dp_scan = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    dp_scan.init(jax.random.PRNGKey(1), jnp.asarray(xs[0]))
    losses = dp_scan.train_steps(loss_fn, xs, ys)

    assert losses.shape == (n_steps,)
    np.testing.assert_allclose(np.asarray(losses), seq_losses, rtol=1e-5, atol=1e-6)
    for a, b in zip(
        jax.tree_util.tree_leaves(dp_seq.params),
        jax.tree_util.tree_leaves(dp_scan.params),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
    # the scanned state stays usable for further single steps
    more = dp_scan.step(loss_fn, xs[0], ys[0])
    assert np.isfinite(more)


def test_train_steps_stack_is_batch_sharded(mlp):
    """The staged batch stack must shard over the mesh axis (axis 1), not
    the step axis — the scan slices steps, the mesh splits each batch."""
    import jax
    import jax.numpy as jnp
    import optax

    dp = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    n_dev = dp.comm.size
    xs = jnp.ones((3, 2 * n_dev, 4), jnp.float32)
    ys = jnp.zeros((3, 2 * n_dev), jnp.int32)
    dp.init(jax.random.PRNGKey(0), xs[0])

    def loss_fn(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    losses = dp.train_steps(loss_fn, xs, ys)
    assert losses.shape == (3,)
    # the arrays the program actually consumes carry the stack sharding
    xd, yd = dp._stage_stack(xs, ys)
    assert xd.sharding == dp._stack_sharding
    assert yd.sharding == dp._stack_sharding
    assert dp._stack_sharding.spec == jax.sharding.PartitionSpec(
        None, dp.comm.axis_name
    )
    # already-staged arrays pass through without another transfer
    xd2, _ = dp._stage_stack(xd, yd)
    assert xd2 is xd
    with pytest.raises(ValueError):
        dp.train_steps(loss_fn, xs, ys[:2])


def test_step_rebuilds_on_new_loss_fn(mlp):
    """A different loss_fn must recompile the cached programs, not silently
    train against the first one's closure."""
    import jax
    import jax.numpy as jnp
    import optax

    dp = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    x = jnp.ones((16, 4), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    dp.init(jax.random.PRNGKey(0), x)

    def xent(pred, target):
        return optax.softmax_cross_entropy_with_integer_labels(pred, target).mean()

    def big_constant(pred, target):
        return jnp.float32(42.0) + 0.0 * xent(pred, target)

    l1 = dp.step(xent, x, y)
    l2 = dp.step(big_constant, x, y)
    assert abs(l2 - 42.0) < 1e-5, "second loss_fn was ignored by the cache"
    losses = dp.train_steps(big_constant, jnp.ones((2, 16, 4)), jnp.zeros((2, 16), jnp.int32))
    np.testing.assert_allclose(np.asarray(losses), 42.0, rtol=1e-6)
    losses = dp.train_steps(xent, jnp.ones((2, 16, 4)), jnp.zeros((2, 16), jnp.int32))
    assert float(losses[0]) != 42.0
    assert l1 != 42.0


def test_loss_cache_reuses_closure_free_lambdas(mlp):
    """Fresh closure-free lambdas with the same code must hit the compiled
    program cache (keyed on __code__), not re-trace every step."""
    import jax
    import jax.numpy as jnp
    import optax

    dp = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    x = jnp.ones((16, 4), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    dp.init(jax.random.PRNGKey(0), x)
    builds = []
    for _ in range(3):
        dp.step(lambda pred, target: (pred * 0.0).sum() + 0.0 * target.sum(), x, y)
        builds.append(dp._train_step)
    assert builds[0] is builds[1] is builds[2]


def test_multigpu_train_steps_guard(mlp):
    """Hierarchical DASO training cannot ride one scanned program; the
    subclass must say so instead of silently bypassing the sync protocol."""
    import jax
    import jax.numpy as jnp
    import optax
    from heat_tpu.parallel import HierarchicalCommunication

    size = ht.get_comm().size
    if size % 4 != 0:
        pytest.skip("needs a mesh divisible into (n/4 x 4) nodes")
    hc = HierarchicalCommunication(grid=(size // 4, 4))
    daso = ht.optim.DASO(
        local_optimizer=optax.sgd(1e-2), total_epochs=2, comm=hc,
        warmup_epochs=0, cooldown_epochs=0,
    )
    dpm = ht.nn.DataParallelMultiGPU(mlp, daso=daso)
    dpm.set_params(mlp.init(jax.random.PRNGKey(0), jnp.ones((8, 4))))
    with pytest.raises(NotImplementedError):
        dpm.train_steps(
            lambda p, t: p.sum() * 0.0, jnp.ones((2, 8, 4)), jnp.zeros((2, 8), jnp.int32)
        )


def test_loss_cache_kwdefaults_and_alternation(mlp):
    """Keyword-only defaults are captured state (distinct programs), and
    alternating between two losses dispatches from the program cache."""
    import jax
    import jax.numpy as jnp
    import optax

    dp = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    x = jnp.ones((16, 4), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    dp.init(jax.random.PRNGKey(0), x)

    def mk(w):
        return lambda pred, target, *, s=w: (pred * 0.0).sum() + s + 0.0 * target.sum()

    la, lb = mk(jnp.float32(1.0)), mk(jnp.float32(41.0))
    assert abs(dp.step(la, x, y) - 1.0) < 1e-5
    assert abs(dp.step(lb, x, y) - 41.0) < 1e-5, "kwdefault state was ignored"
    prog_a = dp._programs[dp._loss_key(la)[0]][0]
    prog_b = dp._programs[dp._loss_key(lb)[0]][0]
    assert abs(dp.step(la, x, y) - 1.0) < 1e-5
    assert abs(dp.step(lb, x, y) - 41.0) < 1e-5
    assert dp._programs[dp._loss_key(la)[0]][0] is prog_a
    assert dp._programs[dp._loss_key(lb)[0]][0] is prog_b


def test_loss_cache_pins_captured_state(mlp):
    """The cache entry pins the objects whose ids form the key: rebinding
    the enclosing variable must not let a recycled address alias a stale
    entry (the id lives in the key; the pin keeps it valid)."""
    import gc
    import jax
    import jax.numpy as jnp
    import optax

    dp = ht.nn.DataParallel(mlp, optimizer=optax.sgd(1e-2))
    x = jnp.ones((16, 4), jnp.float32)
    y = jnp.zeros((16,), jnp.int32)
    dp.init(jax.random.PRNGKey(0), x)

    losses = []
    for i in range(3):
        w = jnp.float32(float(i))  # rebinding frees the previous object...
        out = dp.step(
            lambda pred, target: (pred * 0.0).sum() + w + 0.0 * target.sum(), x, y
        )
        losses.append(out)
        gc.collect()
        # ...but every entry's key ids stay pinned by the entry itself
        for key, entry in dp._programs.items():
            pinned_ids = {id(o) for o in entry[1][4]}  # closure pins
            for cid in key[4]:
                assert cid in pinned_ids
    assert losses == [0.0, 1.0, 2.0], "a stale program served a new capture"
