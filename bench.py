"""Benchmark driver: hierarchical SVD GFLOP/s per chip (the north star).

BASELINE config 3: "heat.decomposition hierarchical SVD on 200GB
tall-skinny matrix".  One chip factorizes a 2^22 x 128 f32 split-0 matrix
(2 GiB) to rank 10 via ``ht.linalg.hsvd_rank`` — on a pod the same call
scales the sample axis over the mesh, so per-chip GFLOP/s is the number
that multiplies out to the 200 GB configuration.

FLOP accounting is the standard 2*n*f^2 for a tall-skinny factorization;
``vs_baseline`` divides by the reference's per-process compute path (the
same truncated factorization in torch on CPU, measured on a subset), so
>1 means one chip beats one reference process on this host.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
Synchronization is a device->host scalar fetch minus the measured
round-trip floor — block_until_ready does not synchronize through a
tunneled remote chip.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


def _measure_sync_floor() -> float:
    f = jax.jit(lambda x: x + 1.0)
    z = jnp.zeros(())
    float(f(z))
    best = float("inf")
    for _ in range(5):
        t0 = time.perf_counter()
        float(f(z))
        best = min(best, time.perf_counter() - t0)
    return best


def _measure_reference_baseline(f: int, rank: int) -> float:
    """GFLOP/s of the reference's per-process compute path: torch CPU
    doing the same truncated factorization (its hsvd leaves are
    torch.linalg.svd of the local block, svdtools.py:474), measured on a
    2^18-row subset."""
    import torch

    n_b = 1 << 18
    xb = torch.randn(n_b, f)

    def factorize():
        u, s, v = torch.linalg.svd(xb, full_matrices=False)
        return u[:, :rank] * s[:rank]

    factorize()
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        us = factorize()
        _ = us.sum().item()
        best = min(best, time.perf_counter() - t0)
    return 2.0 * n_b * f * f / best / 1e9


def main() -> None:
    import heat_tpu as ht

    n, f, rank = 1 << 22, 128, 10  # 2 GiB f32 tall-skinny
    n_iter = 5

    ht.random.seed(0)
    x = ht.random.randn(n, f, split=0)
    float(x.sum())  # materialize

    def factorize():
        u, s, v, err = ht.linalg.hsvd_rank(x, rank, compute_sv=True, safetyshift=5)
        return s

    float(factorize().sum())  # warmup/compile
    sync_floor = _measure_sync_floor()

    # enqueue all iterations and fetch once: the device executes programs
    # in order, so one final fetch bounds all of them, and the link
    # round-trip floor is amortized across n_iter instead of being
    # subtracted per call (tunnel RTT variance can exceed one iteration's
    # compute, which would drive a per-call measurement negative)
    t0 = time.perf_counter()
    for _ in range(n_iter):
        s = factorize()
    float(s.sum())
    per = max((time.perf_counter() - t0 - sync_floor) / n_iter, 1e-9)

    gflops = 2.0 * n * f * f / per / 1e9
    baseline = _measure_reference_baseline(f, rank)

    print(
        json.dumps(
            {
                "metric": "hsvd_rank10_gflops_per_chip_2^22x128",
                "value": round(gflops, 1),
                "unit": "GFLOP/s",
                "vs_baseline": round(gflops / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
