"""CLI for the AST-level framework-invariant linter and the J2/J3 batch.

    python -m heat_tpu.analysis heat_tpu/ [more paths...]
        [--baseline scripts/lint_baseline.json] [--no-baseline]
        [--format text|json] [--list-rules]

    python -m heat_tpu.analysis --rules J2,J3 [--format text|json]

The default mode runs the AST linter.  Exit status: 0 when every
violation is covered by the baseline (or none exist), 1 when new
violations are present.  With no ``--baseline`` argument the checked-in
``scripts/lint_baseline.json`` next to the repo root is used when it
exists — so ``python -m heat_tpu.analysis heat_tpu/`` run from a
checkout gates exactly like CI.

``--rules`` selects the **program batch mode** instead: every served
estimator kind is fitted on a tiny synthetic set and its predict
program driven through the REAL dispatch analyze hook (warn mode,
fresh executable cache) under its precision-policy scope — the same
choke point production hits — then the diagnostics matching the given
rule prefixes (``J2`` = dtype flow J201-J204, ``J3`` = peak-HBM J301;
``J1`` also accepted) are reported with each program's predicted peak
HBM.  Exit status: 0 when no matching diagnostic fired, 1 otherwise.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .ast_lint import (
    RULES,
    lint_paths,
    violations_to_json,
    _find_repo_root,
)


def _program_batch(rules: str, fmt: str) -> int:
    """Fit the served estimator kinds and run their predict programs
    through the armed dispatch hook; report rule-filtered diagnostics."""
    import numpy as np

    import heat_tpu as ht
    from ..core import dispatch
    from ..serving import model_io
    from . import diagnostics, memory_model, precision_policy
    from .program_lint import reset_dispatch_state

    prefixes = tuple(p.strip() for p in rules.split(",") if p.strip())

    rng = np.random.default_rng(0)
    xf = ht.array(rng.standard_normal((64, 8)).astype(np.float32), split=None)
    yf = ht.array((rng.standard_normal((64,)) > 0).astype(np.int32), split=None)
    xr = ht.array(rng.standard_normal((64, 8)).astype(np.float32), split=None)

    def fitted():
        km = ht.cluster.KMeans(n_clusters=3, init="random", max_iter=3,
                               random_state=0)
        km.fit(xf)
        kmed = ht.cluster.KMedians(n_clusters=3, init="random", max_iter=3,
                                   random_state=0)
        kmed.fit(xf)
        kmedo = ht.cluster.KMedoids(n_clusters=3, init="random", max_iter=3,
                                    random_state=0)
        kmedo.fit(xf)
        pca = ht.decomposition.PCA(n_components=3)
        pca.fit(xf)
        lasso = ht.regression.Lasso(max_iter=5)
        lasso.fit(xf, ht.array(rng.standard_normal((64,)).astype(np.float32)))
        knn = ht.classification.KNeighborsClassifier(n_neighbors=3)
        knn.fit(xf, yf)
        return [km, kmed, kmedo, pca, lasso, knn]

    estimators = fitted()
    prev_mode = diagnostics.set_analysis_mode("off")
    report = {}
    rc = 0
    try:
        for est in estimators:
            kind = type(est).__name__
            diagnostics.clear_diagnostics()
            reset_dispatch_state()
            memory_model.reset_estimates()
            dispatch.clear_cache()
            diagnostics.set_analysis_mode("warn")
            import warnings

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")
                model_io.infer(est, xr)
            diagnostics.set_analysis_mode("off")
            diags = [
                d for d in diagnostics.recent_diagnostics()
                if any(d.rule.startswith(p) for p in prefixes)
            ]
            peaks = memory_model.peak_summary()["estimates"]
            peak = max(
                (rec["per_device_bytes"] for rec in peaks.values()), default=0
            )
            report[kind] = {
                "policy": precision_policy.policy_for(kind),
                "compute_dtype": precision_policy.compute_dtype(kind),
                "predicted_peak_bytes": peak,
                "diagnostics": [
                    {"rule": d.rule, "location": d.location,
                     "message": d.message}
                    for d in diags
                ],
            }
            if diags:
                rc = 1
    finally:
        diagnostics.set_analysis_mode(prev_mode)
        diagnostics.clear_diagnostics()
        reset_dispatch_state()
        dispatch.clear_cache()

    if fmt == "json":
        print(json.dumps({"rules": prefixes, "programs": report}, indent=1))
    else:
        for kind, rec in report.items():
            pol = rec["policy"]
            mode = pol["mode"] if pol else "undeclared"
            print(
                f"{kind}: policy={mode} compute={rec['compute_dtype']} "
                f"predicted_peak={rec['predicted_peak_bytes']}B "
                f"{len(rec['diagnostics'])} diagnostic(s)"
            )
            for d in rec["diagnostics"]:
                print(f"  - {d['rule']} [{d['location']}]: {d['message']}")
        total = sum(len(r["diagnostics"]) for r in report.values())
        print(
            f"program batch ({rules}): {total} diagnostic(s) over "
            f"{len(report)} estimator predict program(s)",
            file=sys.stderr,
        )
    return rc


def _load_baseline(path: str):
    with open(path) as f:
        doc = json.load(f)
    entries = doc["violations"] if isinstance(doc, dict) else doc
    return {(e["rule"], e["file"], e["line"]) for e in entries}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m heat_tpu.analysis",
        description="heat_tpu framework-invariant AST linter",
    )
    ap.add_argument("paths", nargs="*", default=None,
                    help="files or directories to lint (default: heat_tpu/)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of accepted violations "
                         "(default: <repo>/scripts/lint_baseline.json if present)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every violation, ignoring any baseline")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the rule catalogue and exit")
    ap.add_argument("--rules", default=None, metavar="J2,J3",
                    help="program batch mode: fit the served estimators and "
                         "run their predict programs through the armed "
                         "dispatch hook, reporting diagnostics whose rule "
                         "matches the given prefixes")
    args = ap.parse_args(argv)

    if args.list_rules:
        for rule, desc in sorted(RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    if args.rules:
        return _program_batch(args.rules, args.format)

    paths = args.paths
    repo_root = _find_repo_root(paths[0] if paths else os.getcwd())
    if not paths:
        paths = [os.path.join(repo_root, "heat_tpu")]

    violations = lint_paths(paths, repo_root=repo_root)

    baseline = set()
    if not args.no_baseline:
        bpath = args.baseline
        if bpath is None:
            cand = os.path.join(repo_root, "scripts", "lint_baseline.json")
            bpath = cand if os.path.exists(cand) else None
        if bpath is not None:
            baseline = _load_baseline(bpath)

    new = [v for v in violations if v.key() not in baseline]
    accepted = len(violations) - len(new)

    if args.format == "json":
        print(json.dumps({
            "violations": violations_to_json(new),
            "accepted_baseline": accepted,
            "total": len(violations),
        }, indent=1))
    else:
        for v in new:
            print(v)
        note = f" ({accepted} accepted by baseline)" if accepted else ""
        print(
            f"lint: {len(new)} new violation(s), {len(violations)} total{note}",
            file=sys.stderr,
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
