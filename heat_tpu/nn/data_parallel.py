"""Data-parallel NN training, analog of heat/nn/data_parallel.py.

The reference's ``DataParallel`` (data_parallel.py:22) wraps a torch module
and registers per-parameter backward hooks that Allreduce gradients —
blocking (``_blocking_hook`` :220) or non-blocking with just-in-time Waits
(``_nonblocking_hook`` :240, ``_forward_hook`` :278) — plus a fixed shared
seed so every rank starts from identical parameters (:105-106, :299-311).

TPU-native inversion: parameters live REPLICATED on the mesh and the batch
is sharded along the mesh axis; the gradient of a mean loss then *is* the
cross-replica average, with XLA inserting (and overlapping) the psum in the
backward pass.  The blocking/non-blocking distinction, the per-layer hook
ordering, and the identical-initialization dance all disappear: one jit'd
train step is the whole protocol.  Any flax ``linen.Module`` (or a bare
``apply(params, x)`` function) can be wrapped.

Explicit gradient-reduction schedules (overlap layer, docs/overlap.md):
the implicit schedule above leaves the collective placement entirely to
XLA.  :func:`reduce_gradients` is the explicit alternative — local
per-device gradients reduced by hand-placed psums inside a
``shard_map`` body, in **byte-bounded buckets issued in reverse layer
order** (``HEAT_TPU_GRAD_BUCKET_MB``, default 4) so the collective for
the last layers' gradients — ready first in the backward pass — is in
flight while the first layers' backward still computes: the TPU-native
transcription of the reference's ``_nonblocking_hook`` per-layer
``Iallreduce`` pipeline (data_parallel.py:240).  On a hierarchical mesh
each bucket reduces in two stages — ICI ``'node'`` psum, then DCN
``'global'`` psum — through
:class:`~heat_tpu.parallel.HierarchicalCommunication`.
``blocking=True`` selects the single fused psum of the whole flat
gradient (the reference's ``_blocking_hook``, :220); both schedules sum
the same elements across the same participants and produce identical
updates.  :class:`DataParallel` selects a schedule per instance — pass a
:class:`~heat_tpu.optim.DataParallelOptimizer` (its ``blocking`` flag
routes fused-vs-bucketed) or ``grad_reduction=`` directly; a bare optax
transform keeps the implicit schedule.
"""

from __future__ import annotations

import os
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..core.dndarray import DNDarray
from ..parallel.comm import Communication, HierarchicalCommunication, sanitize_comm

__all__ = [
    "DataParallel",
    "DataParallelMultiGPU",
    "bucket_partition",
    "reduce_gradients",
]

#: default collective bucket size for the bucketed schedule, MiB
DEFAULT_GRAD_BUCKET_MB = 4.0


def _grad_bucket_bytes() -> int:
    return int(
        float(os.environ.get("HEAT_TPU_GRAD_BUCKET_MB", str(DEFAULT_GRAD_BUCKET_MB)))
        * 2**20
    )


def bucket_partition(
    leaves: Sequence, bucket_bytes: Optional[int]
) -> List[List[int]]:
    """Partition gradient leaves into collective buckets.

    Returns lists of leaf indices in **reverse layer order** (the order
    gradients become ready in the backward pass), each bucket bounded by
    ``bucket_bytes`` (``None`` = unbounded, i.e. the fused schedule) and
    containing a single dtype (buckets are concatenated into one buffer
    per collective, which cannot mix dtypes).  A leaf larger than the
    bound gets its own bucket — leaves are never split."""
    buckets: List[List[int]] = []
    cur: List[int] = []
    cur_bytes = 0
    cur_dtype = None
    for i in reversed(range(len(leaves))):
        leaf = leaves[i]
        nbytes = int(leaf.size) * jnp.dtype(leaf.dtype).itemsize
        over = bucket_bytes is not None and cur_bytes + nbytes > bucket_bytes
        if cur and (over or leaf.dtype != cur_dtype):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = leaf.dtype
    if cur:
        buckets.append(cur)
    return buckets


def reduce_gradients(
    grads: Any,
    comm: Optional[Communication] = None,
    blocking: bool = False,
    bucket_bytes: Optional[int] = None,
):
    """Cross-device mean of a local-gradient pytree — call INSIDE a
    ``shard_map`` body (it issues named-axis psums).

    ``blocking=False`` (default): one psum per byte-bounded bucket in
    reverse layer order, so XLA can overlap each bucket's collective
    with the remaining backward compute.  ``blocking=True``: a single
    fused psum of the whole flattened gradient (per dtype).  On a
    :class:`HierarchicalCommunication` each bucket reduces in two
    stages: psum over the ``'node'`` (ICI) axis, then over the
    ``'global'`` (DCN) axis.  Both schedules sum identical elements
    across identical participants, so the averaged gradients — and the
    optimizer updates they produce — are identical.

    The number of buckets issued is added to the shared overlap-stats
    counter ``grad_buckets`` at trace time."""
    from ..utils.overlap import _bump

    comm = sanitize_comm(comm)
    leaves, treedef = jax.tree_util.tree_flatten(grads)
    if not leaves:
        return grads
    if blocking:
        buckets = bucket_partition(leaves, None)
    else:
        buckets = bucket_partition(
            leaves, _grad_bucket_bytes() if bucket_bytes is None else bucket_bytes
        )
    _bump("grad_buckets", len(buckets))
    hier = isinstance(comm, HierarchicalCommunication)
    inv = 1.0 / comm.size
    sizes = [int(l.size) for l in leaves]
    out: List[Any] = [None] * len(leaves)
    for bucket in buckets:
        flat = [jnp.ravel(leaves[i]) for i in bucket]
        buf = flat[0] if len(flat) == 1 else jnp.concatenate(flat)
        if hier:
            # two-stage: node-local reduce rides ICI, then one smaller
            # cross-node reduce rides DCN (the reference's DDP-then-MPI
            # hierarchy, heat/optim/dp_optimizer.py:450)
            buf = comm.psum(buf, comm.node_axis)
            buf = comm.psum(buf, comm.global_axis)
        else:
            buf = comm.psum(buf)
        buf = buf * jnp.asarray(inv, buf.dtype)
        offset = 0
        for i in bucket:
            out[i] = jax.lax.slice(buf, (offset,), (offset + sizes[i],)).reshape(
                leaves[i].shape
            )
            offset += sizes[i]
    return jax.tree_util.tree_unflatten(treedef, out)


class DataParallel:
    """Distributed data-parallel wrapper (data_parallel.py:22).

    Parameters
    ----------
    module : flax.linen.Module or Callable
        A flax module, or an ``apply(params, x)`` function.
    comm : Communication, optional
        Mesh over which the batch is sharded (default: world).
    optimizer : optional
        An optax gradient transformation, or a
        :class:`~heat_tpu.optim.DataParallelOptimizer` wrapping one — the
        wrapper's ``blocking`` flag then selects the explicit gradient
        schedule (``True`` -> fused single psum, ``False`` -> bucketed
        overlapped psums).  Enables :meth:`step`.
    blocking_parameter_updates : bool
        ``True`` selects the explicit single fused gradient psum (the
        reference's ``_blocking_hook``, :220).  ``False`` (default)
        keeps the implicit schedule, where XLA places and overlaps the
        reduction itself (the compiler-native analog of the :240
        non-blocking pipeline).
    grad_reduction : str, optional
        Explicit schedule override: ``"implicit"`` (XLA-placed),
        ``"bucketed"`` (reverse-order byte-bounded psums, see
        :func:`reduce_gradients`) or ``"fused"`` (one flat psum).
        Unknown values raise.  Default: derived from ``optimizer`` /
        ``blocking_parameter_updates`` as above.
    """

    def __init__(
        self,
        module: Any,
        comm: Optional[Communication] = None,
        optimizer: Any = None,
        blocking_parameter_updates: bool = False,
        grad_reduction: Optional[str] = None,
    ):
        from ..optim.dp_optimizer import DataParallelOptimizer

        self.module = module
        self.comm = sanitize_comm(comm)
        self.blocking_parameter_updates = blocking_parameter_updates
        if isinstance(optimizer, DataParallelOptimizer):
            if grad_reduction is None:
                grad_reduction = optimizer.schedule
            optimizer = optimizer.optimizer
        if grad_reduction is None:
            grad_reduction = "fused" if blocking_parameter_updates else "implicit"
        if grad_reduction not in ("implicit", "bucketed", "fused"):
            raise ValueError(
                "grad_reduction must be 'implicit', 'bucketed' or 'fused', "
                f"got {grad_reduction!r}"
            )
        self.grad_reduction = grad_reduction
        self._optimizer = optimizer
        self._opt_state = None
        self.params = None
        self._apply = module.apply if hasattr(module, "apply") else module
        self._train_step = None
        self._train_step_explicit = None
        self._epoch_fn = None
        self._programs = {}

    # ------------------------------------------------------------------
    def init(self, key, sample_input) -> "DataParallel":
        """Initialize parameters, replicated on the mesh (the analog of the
        reference's shared-seed ``_reset_parameters``, :299)."""
        if isinstance(sample_input, DNDarray):
            sample_input = sample_input._dense()
        if hasattr(self.module, "init"):
            params = self.module.init(key, sample_input)
        else:
            raise TypeError("module has no .init; pass explicit params to set_params")
        self.set_params(params)
        return self

    def set_params(self, params) -> None:
        rep = NamedSharding(self.comm.mesh, P())
        self.params = jax.device_put(params, rep)
        if self._optimizer is not None:
            self._opt_state = jax.device_put(self._optimizer.init(self.params), rep)
        self._train_step = None
        self._train_step_explicit = None
        self._epoch_fn = None
        self._programs = {}

    # ------------------------------------------------------------------
    def _forward_params(self):
        """Parameter pytree used for inference (hook for subclasses)."""
        return self.params

    def __call__(self, x):
        """Forward pass on a (batch-sharded) input (data_parallel.py:150)."""
        if self.params is None:
            raise RuntimeError("call init() or set_params() first")
        wrap = isinstance(x, DNDarray)
        xd = x._dense() if wrap else x
        out = self._apply(self._forward_params(), xd)
        if wrap:
            return DNDarray.from_dense(out, x.split, x.device, x.comm)
        return out

    forward = __call__

    # ------------------------------------------------------------------
    def value_and_grad(self, loss_fn: Callable, x, y) -> Tuple[jnp.ndarray, Any]:
        """Loss and cross-replica-averaged parameter gradients.

        ``loss_fn(pred, target) -> scalar`` must reduce with a mean over the
        batch; the mean over the sharded batch axis is exactly the
        reference's Allreduce(SUM)/size per-layer hook (:220), emitted once
        by XLA instead of per tensor.
        """
        xd = x._dense() if isinstance(x, DNDarray) else x
        yd = y._dense() if isinstance(y, DNDarray) else y

        def total_loss(params):
            return loss_fn(self._apply(params, xd), yd)

        return jax.value_and_grad(total_loss)(self.params)

    @staticmethod
    def _loss_key(loss_fn: Callable):
        """``(key, pins)`` for a loss function: the code object plus the
        IDENTITY of every piece of captured state (closure cells, default
        args, a bound method's ``__self__``).  A fresh lambda per loop
        iteration capturing the same objects reuses the compiled program;
        a lambda capturing *different* state (``lambda p, t, w=w: ...``
        with a new ``w``) rebuilds instead of silently evaluating the old
        trace.  ``pins`` holds the exact objects whose ids appear in the
        key — the cache entry must keep it alive, because the function
        object alone pins its closure CELLS, not their historical
        contents: rebinding the enclosing variable frees the old contents
        and a later object at the recycled address would alias the stale
        key.  Callables without a code object (``functools.partial``, C
        callables) key on their own identity — recreate them per call and
        each call retraces.  Like ``jax.jit`` itself, IN-PLACE mutation of
        a captured object (``obj.w = 2.0`` behind a bound method) is not
        observable: traced state is baked at compile time; rebind a new
        function/object to change it."""
        fn = getattr(loss_fn, "__func__", loss_fn)
        code = getattr(fn, "__code__", None)
        if code is None:
            return (id(loss_fn),), (loss_fn,)

        cells = []
        for c in fn.__closure__ or ():
            try:
                cells.append(c.cell_contents)
            except ValueError:  # empty cell (e.g. unbound recursive name)
                cells.append(c)
        bound_self = getattr(loss_fn, "__self__", None)
        defaults = tuple(fn.__defaults__ or ())
        kwdefaults = sorted((fn.__kwdefaults__ or {}).items())
        key = (
            code,
            id(bound_self),
            tuple(id(d) for d in defaults),
            tuple((k, id(v)) for k, v in kwdefaults),
            tuple(id(c) for c in cells),
        )
        pins = (loss_fn, bound_self, defaults, tuple(v for _, v in kwdefaults), tuple(cells))
        return key, pins

    _PROGRAM_CACHE_SIZE = 8

    def _cached_program(self, cache: dict, loss_fn: Callable, build: Callable):
        """Shared keyed-FIFO program cache (``_build`` and the
        hierarchical ``step``): returns ``build()``'s value, cached under
        :meth:`_loss_key` with the key's referent objects pinned for the
        entry's lifetime."""
        key, pins = self._loss_key(loss_fn)
        cached = cache.get(key)
        if cached is not None:
            return cached[0]
        value = build()
        cache[key] = (value, pins)
        while len(cache) > self._PROGRAM_CACHE_SIZE:
            cache.pop(next(iter(cache)))
        return value

    def _build(self, loss_fn: Callable) -> None:
        """Compile (and cache) the fused step body and the scanned epoch
        over it.  A small FIFO dict keyed by :meth:`_loss_key` holds the
        last few losses' programs, so alternating objectives (task/aux,
        GAN-style) dispatch from cache instead of retracing every call; a
        genuinely new loss rebuilds instead of silently reusing the old
        closure."""
        def build():
            apply = self._apply
            optimizer = self._optimizer
            comm = self.comm
            schedule = self.grad_reduction
            import optax

            def body(params, opt_state, xb, yb):
                def total_loss(p):
                    return loss_fn(apply(p, xb), yb)

                loss, grads = jax.value_and_grad(total_loss)(params)
                updates, opt_state = optimizer.update(grads, opt_state, params)
                return loss, optax.apply_updates(params, updates), opt_state

            body_explicit = None
            if schedule in ("bucketed", "fused"):
                # explicit schedule: per-device local gradients inside a
                # shard_map, reduced by hand-placed psums (bucketed
                # reverse-order or one fused collective) — the loss mean
                # over equal shards equals the global batch mean, so the
                # update matches the implicit schedule mathematically
                from ..core._compat import shard_map

                spec = P(comm.axis_name)
                blocking = schedule == "fused"

                def local_step(params, xl, yl):
                    def local_loss(p):
                        return loss_fn(apply(p, xl), yl)

                    loss, grads = jax.value_and_grad(local_loss)(params)
                    grads = reduce_gradients(grads, comm, blocking=blocking)
                    loss = comm.psum(loss) / comm.size
                    return loss, grads

                def explicit_body(params, opt_state, xb, yb):
                    loss, grads = shard_map(
                        local_step,
                        mesh=comm.mesh,
                        in_specs=(P(), spec, spec),
                        out_specs=(P(), P()),
                    )(params, xb, yb)
                    updates, opt_state = optimizer.update(grads, opt_state, params)
                    return loss, optax.apply_updates(params, updates), opt_state

                body_explicit = jax.jit(explicit_body)

            @jax.jit
            def epoch(params, opt_state, xs, ys):
                def scan_body(carry, batch):
                    loss, p, s = body(*carry, *batch)
                    return (p, s), loss

                (params, opt_state), losses = jax.lax.scan(
                    scan_body, (params, opt_state), (xs, ys)
                )
                return params, opt_state, losses

            self._batch_sharding = NamedSharding(
                self.comm.mesh, P(self.comm.axis_name)
            )
            self._stack_sharding = NamedSharding(
                self.comm.mesh, P(None, self.comm.axis_name)
            )
            return jax.jit(body), epoch, body_explicit

        self._train_step, self._epoch_fn, self._train_step_explicit = (
            self._cached_program(self._programs, loss_fn, build)
        )

    def step(self, loss_fn: Callable, x, y) -> float:
        """One fused train step: forward, backward, optimizer update —
        compiled once and cached (the whole of the reference's hook
        machinery plus DataParallelOptimizer.step, dp_optimizer.py:851)."""
        if self._optimizer is None:
            raise RuntimeError("construct DataParallel with an optimizer to use step()")
        self._build(loss_fn)

        xd = x._dense() if isinstance(x, DNDarray) else jnp.asarray(x)
        yd = y._dense() if isinstance(y, DNDarray) else jnp.asarray(y)
        divisible = xd.shape[0] % self.comm.size == 0
        if divisible:
            xd = jax.device_put(xd, self._batch_sharding)
            yd = jax.device_put(yd, self._batch_sharding)
        # explicit schedules run as a shard_map, which needs the batch to
        # tile the mesh; ragged batches fall back to the implicit body
        step_fn = (
            self._train_step_explicit
            if (self._train_step_explicit is not None and divisible)
            else self._train_step
        )
        loss, self.params, self._opt_state = step_fn(self.params, self._opt_state, xd, yd)
        return float(loss)

    def train_steps(self, loss_fn: Callable, xs, ys) -> jnp.ndarray:
        """Run a whole stack of train steps as ONE device program.

        ``xs``/``ys`` carry a leading step axis: ``xs[k]`` is step *k*'s
        batch (each batch sharded over the mesh axis exactly as in
        :meth:`step`).  A ``lax.scan`` threads (params, opt_state) through
        the fused forward/backward/update body, so per-step host dispatch
        — the dominant cost of tiny steps on a remote or tunneled link —
        is paid once per *stack* instead of once per step.  This is the
        TPU-native replacement for the reference's per-iteration python
        loop over ``DataParallel`` (data_parallel.py:150) +
        ``DataParallelOptimizer.step`` (dp_optimizer.py:851): steady-state
        training stages a queue of batches in HBM and scans them.

        Returns the per-step losses (a device-resident ``(n_steps,)``
        array; fetch at epoch boundaries, not per step).

        The scanned epoch always uses the implicit gradient schedule —
        inside one compiled scan XLA already owns collective placement
        end to end; explicit bucketed/fused schedules apply to
        :meth:`step`.
        """
        if self._optimizer is None:
            raise RuntimeError("construct DataParallel with an optimizer to use train_steps()")
        if self.params is None:
            raise RuntimeError("call init() or set_params() first")
        self._build(loss_fn)
        xd, yd = self._stage_stack(xs, ys)
        self.params, self._opt_state, losses = self._epoch_fn(
            self.params, self._opt_state, xd, yd
        )
        return losses

    def _stage_stack(self, xs, ys):
        """Place a (n_steps, batch, ...) stack with each batch sharded over
        the mesh axis.  Already-staged arrays pass through untouched, so a
        caller looping epochs over the same stack pays the transfer once."""
        xd = xs._dense() if isinstance(xs, DNDarray) else jnp.asarray(xs)
        yd = ys._dense() if isinstance(ys, DNDarray) else jnp.asarray(ys)
        if xd.shape[0] != yd.shape[0]:
            raise ValueError(
                f"step axes disagree: xs has {xd.shape[0]} batches, ys {yd.shape[0]}"
            )
        if (
            xd.ndim >= 2
            and yd.ndim >= 2
            and xd.shape[1] % self.comm.size == 0
            and yd.shape[1] % self.comm.size == 0
        ):
            if getattr(xd, "sharding", None) != self._stack_sharding:
                xd = jax.device_put(xd, self._stack_sharding)
            if getattr(yd, "sharding", None) != self._stack_sharding:
                yd = jax.device_put(yd, self._stack_sharding)
        return xd, yd


class DataParallelMultiGPU(DataParallel):
    """Hierarchical DP (data_parallel.py:313): torch-DDP-intra-node + DASO
    inter-node in the reference.

    TPU-native topology: the batch is sharded over BOTH axes of a
    :class:`~heat_tpu.parallel.HierarchicalCommunication` mesh — each node
    gets a contiguous batch slab (axis 'global'), further sharded within the
    node (axis 'node').  Parameters are per-node replicas (a stacked pytree,
    leading node dim sharded over 'global', managed by
    :class:`heat_tpu.optim.DASO`): the per-node gradient is a ``vmap`` over
    the node dimension, inside which the mean-loss gradient psums over
    'node' — the reference's intra-node DDP allreduce (:220).  Cross-node
    averaging happens only when DASO decides to sync, as a bf16 all-reduce
    over 'global' (the reference's ``_global_sync``, dp_optimizer.py:450).
    """

    def __init__(
        self,
        module,
        comm: Optional[Communication] = None,
        optimizer: Any = None,
        daso: Optional["Any"] = None,
    ):
        from ..parallel.comm import HierarchicalCommunication
        from ..optim.dp_optimizer import DASO

        if daso is not None:
            # DASO owns the hierarchy; a conflicting explicit comm would
            # shard the batch on one mesh and sync params on another
            if comm is not None and comm != daso.comm:
                raise ValueError(
                    "pass either comm or daso, not both: the DASO instance's "
                    "communication defines the (node x local) grid"
                )
            if not daso.hierarchical:
                raise ValueError(
                    "DataParallelMultiGPU requires a DASO built on a "
                    "HierarchicalCommunication (e.g. DASO(..., comm="
                    "HierarchicalCommunication(grid=(n_node, per_node)))); "
                    "a plain-comm DASO has no node axis to sync across"
                )
            comm = daso.comm
        if not isinstance(comm, HierarchicalCommunication):
            comm = HierarchicalCommunication(devices=comm.devices if comm else None)
        super().__init__(module, comm=comm, optimizer=optimizer)
        if daso is None and optimizer is not None:
            daso = DASO(local_optimizer=optimizer, total_epochs=1, comm=comm,
                        warmup_epochs=0, cooldown_epochs=0)
        self.daso = daso
        self._hier_step = None
        self._hier_programs = {}

    # -- per-node replica parameter state ------------------------------
    def set_params(self, params) -> None:
        if self.daso is None or not self.daso.hierarchical:
            super().set_params(params)
            self._hier_step = None
            self._hier_programs = {}
            return
        self.params = self.daso.replicate(params)
        self._hier_step = None
        self._hier_programs = {}

    def _forward_params(self):
        # inference runs on the node-0 replica (identical everywhere after
        # a sync; representative between syncs)
        if self.daso is not None and self.daso.hierarchical:
            return jax.tree_util.tree_map(lambda p: p[0], self.params)
        return self.params

    def step(self, loss_fn: Callable, x, y) -> float:
        """One hierarchical step: per-node grads (vmap over node replicas,
        psum over 'node' inside) + DASO's skipped/delayed global sync."""
        if self.daso is None or not self.daso.hierarchical:
            return super().step(loss_fn, x, y)
        comm = self.comm
        n_node = comm.num_nodes
        # own cache slots: the base _build programs have a different
        # signature, and mixing step()/train_steps() must not collide
        def build():
            apply = self._apply

            @jax.jit
            def grad_step(stacked, xn, yn):
                def node_loss(p, xi, yi):
                    return loss_fn(apply(p, xi), yi)

                losses, grads = jax.vmap(jax.value_and_grad(node_loss))(stacked, xn, yn)
                return losses.mean(), grads

            self._hier_sharding = NamedSharding(
                comm.mesh, P(comm.global_axis, comm.node_axis)
            )
            return grad_step

        self._hier_step = self._cached_program(self._hier_programs, loss_fn, build)

        xd = x._dense() if isinstance(x, DNDarray) else jnp.asarray(x)
        yd = y._dense() if isinstance(y, DNDarray) else jnp.asarray(y)
        b = xd.shape[0]
        if b % n_node != 0:
            raise ValueError(f"batch {b} not divisible by {n_node} nodes")
        xn = xd.reshape((n_node, b // n_node) + xd.shape[1:])
        yn = yd.reshape((n_node, b // n_node) + yd.shape[1:])
        if (b // n_node) % comm.node_size == 0:
            xn = jax.device_put(xn, self._hier_sharding)
            yn = jax.device_put(yn, self._hier_sharding)
        loss, grads = self._hier_step(self.params, xn, yn)
        self.params = self.daso.step(self.params, grads)
        return float(loss)

    def train_steps(self, loss_fn: Callable, xs, ys) -> jnp.ndarray:
        """Always raises: DASO's skipped/delayed global sync is host-side
        control flow between steps and cannot ride inside one scanned
        program (and every constructible instance with an optimizer owns a
        hierarchical DASO)."""
        raise NotImplementedError(
            "train_steps does not drive the DASO hierarchical sync "
            "protocol; call step() per batch (DASO decides syncs between "
            "steps), or use a plain DataParallel for scanned epochs"
        )

    def collect_params(self):
        """One coherent (node-0) parameter pytree (after :meth:`DASO.last_batch`
        the replicas are identical up to bf16 transport)."""
        if self.daso is not None and self.daso.hierarchical:
            return self.daso.collect(self.params)
        return self.params
