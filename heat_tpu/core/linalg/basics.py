"""Distributed linear algebra basics, analog of heat/core/linalg/basics.py.

The reference's ``matmul`` (basics.py:422-1168) is a ~750-line case
analysis over (a.split, b.split) with hand-rolled block-streamed SUMMA
(``__mm_c_block_setter`` :2040).  Under GSPMD a single ``jnp.matmul`` over
sharded operands emits the same collective-matmul schedule (all-gather /
psum placement chosen by XLA) — the biggest "delete code" win of the
TPU-native design (SURVEY.md §3.4).  What remains here is split
bookkeeping and pad masking.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from ..stride_tricks import sanitize_axis

__all__ = [
    "cross",
    "det",
    "dot",
    "inv",
    "matmul",
    "matrix_norm",
    "norm",
    "outer",
    "projection",
    "trace",
    "transpose",
    "tril",
    "triu",
    "vdot",
    "vecdot",
    "vector_norm",
]


def matmul_precision(dtype) -> Optional[jax.lax.Precision]:
    """Precision policy: accuracy follows the dtype.

    TPU MXUs natively multiply in bf16; XLA's default lowers f32 matmuls to
    bf16 passes, which breaks NumPy-parity accuracy expectations.  Policy:
    f32/f64 inputs get ``Precision.HIGHEST`` (full-precision passes on the
    MXU); bf16/f16 inputs run at native MXU speed — users opt into speed by
    choosing the dtype, as everywhere else in this framework.
    """
    if dtype in (jnp.bfloat16, jnp.float16) or np.dtype(dtype).itemsize <= 2:
        return None
    return jax.lax.Precision.HIGHEST


def cross(a: DNDarray, b: DNDarray, axisa: int = -1, axisb: int = -1, axisc: int = -1, axis: int = -1) -> DNDarray:
    """Cross product of 3-element vectors (basics.py:48)."""
    sanitize_in(a)
    sanitize_in(b)
    result = jnp.cross(a._dense(), b._dense(), axisa=axisa, axisb=axisb, axisc=axisc)
    split = a.split if a.split is not None and a.split < result.ndim else None
    return DNDarray.from_dense(result, split, a.device, a.comm)


def det(a: DNDarray) -> DNDarray:
    """Determinant via LU (basics.py:159).

    2-D split matrices on a mesh run the distributed blocked LU with
    partial pivoting (factorizations.py) — the matrix stays row-sharded,
    matching the reference's hand-distributed Gaussian elimination
    (basics.py:212-240); batched/replicated inputs use XLA's LU."""
    sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise RuntimeError("Last two dimensions of the array must be square")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    from .factorizations import det_dist, supports_dist_factor

    if supports_dist_factor(a):
        return det_dist(a)
    result = jnp.linalg.det(a._dense())
    split = a.split if a.split is not None and a.split < max(a.ndim - 2, 0) else None
    return DNDarray.from_dense(result, split, a.device, a.comm)


def dot(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None) -> Union[DNDarray, float]:
    """NumPy dot semantics (basics.py:245)."""
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim == 1 and b.ndim == 1:
        result = jnp.dot(a._dense(), b._dense(), precision=matmul_precision(a._dense().dtype))
        res = DNDarray.from_dense(result, None, a.device, a.comm)
        if out is not None:
            out._replace(res.larray_padded)
            return out
        return res
    if a.ndim <= 2 and b.ndim <= 2:
        res = matmul(a, b)
        if out is not None:
            out._replace(res.larray_padded)
            return out
        return res
    raise NotImplementedError("ht.dot supports 1-D and 2-D operands")


def inv(a: DNDarray) -> DNDarray:
    """Matrix inverse (basics.py:311).

    2-D split matrices run the distributed LU + blocked substitution
    against the sharded identity (the reference's distributed
    Gauss-Jordan, basics.py:421+); batched/replicated inputs use XLA."""
    sanitize_in(a)
    if a.ndim < 2 or a.shape[-1] != a.shape[-2]:
        raise RuntimeError("Last two dimensions of the array must be square")
    if not types.heat_type_is_inexact(a.dtype):
        a = a.astype(types.float32)
    from .factorizations import inv_dist, supports_dist_factor

    if supports_dist_factor(a):
        return inv_dist(a)
    result = jnp.linalg.inv(a._dense())
    return DNDarray.from_dense(result, a.split, a.device, a.comm)


def matmul(a: DNDarray, b: DNDarray, allow_resplit: bool = False) -> DNDarray:
    """Matrix product with batch support (basics.py:422).

    Output split policy mirrors the reference's case table: a row-split
    left operand keeps its split; a column-split right operand keeps its;
    inner-split operands reduce to it via the (GSPMD-inserted) psum.
    """
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim == 0 or b.ndim == 0:
        raise ValueError("matmul requires at least 1-dimensional inputs")
    promoted = types.promote_types(a.dtype, b.dtype)
    ad = a._dense().astype(promoted.jax_type())
    bd = b._dense().astype(promoted.jax_type())
    result = jnp.matmul(ad, bd, precision=matmul_precision(ad.dtype))

    out_ndim = result.ndim
    out_split: Optional[int] = None
    if a.ndim >= 2 and b.ndim >= 2:
        batch_ndim = out_ndim - 2
        if a.split is not None:
            a_batch = a.ndim - 2
            if a.split < a_batch:  # batch-split stays (reference :594-601)
                out_split = a.split + (batch_ndim - a_batch)
            elif a.split == a.ndim - 2:  # row split -> output row split
                out_split = out_ndim - 2
            # a split along inner dim -> psum, replicated output
        if out_split is None and b.split is not None:
            b_batch = b.ndim - 2
            if b.split < b_batch:
                out_split = b.split + (batch_ndim - b_batch)
            elif b.split == b.ndim - 1:  # column split -> output col split
                out_split = out_ndim - 1
    elif a.ndim == 1 and b.ndim >= 2:
        if b.split == b.ndim - 1 and out_ndim > 0:
            out_split = out_ndim - 1
    elif b.ndim == 1 and a.ndim >= 2:
        if a.split == a.ndim - 2 and out_ndim > 0:
            out_split = out_ndim - 1
    if result.ndim == 0:
        out_split = None
    return DNDarray.from_dense(result, out_split, a.device, a.comm)


def matrix_norm(x: DNDarray, axis: Optional[Tuple[int, int]] = None, keepdims: bool = False, ord=None) -> DNDarray:
    """Matrix norm over a pair of axes (basics.py:1182)."""
    sanitize_in(x)
    if axis is None:
        if x.ndim != 2:
            raise ValueError("input is not a matrix; specify axis")
        axis = (0, 1)
    if not (isinstance(axis, tuple) and len(axis) == 2):
        raise TypeError("axis must be a 2-tuple")
    result = jnp.linalg.norm(
        x._dense().astype(jnp.float32 if not types.heat_type_is_inexact(x.dtype) else x.dtype.jax_type()),
        ord=ord if ord is not None else "fro",
        axis=axis,
        keepdims=keepdims,
    )
    return DNDarray.from_dense(result, None, x.device, x.comm)


def norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector/matrix norm dispatch (basics.py:1310)."""
    sanitize_in(x)
    dense = x._dense()
    if not types.heat_type_is_inexact(x.dtype):
        dense = dense.astype(jnp.float32)
    result = jnp.linalg.norm(dense, ord=ord, axis=axis, keepdims=keepdims)
    split = None
    if axis is not None and x.split is not None:
        axes = axis if isinstance(axis, tuple) else (sanitize_axis(x.shape, axis),)
        axes = tuple(sanitize_axis(x.shape, ax) for ax in axes)
        if x.split not in axes:
            split = x.split - sum(1 for ax in axes if ax < x.split) if not keepdims else x.split
    return DNDarray.from_dense(result, split, x.device, x.comm)


def outer(a: DNDarray, b: DNDarray, out: Optional[DNDarray] = None, split: Optional[int] = None) -> DNDarray:
    """Outer product of two vectors (basics.py:1459; the reference's ring
    exchange is an all-gather GSPMD inserts on demand)."""
    sanitize_in(a)
    sanitize_in(b)
    result = jnp.outer(a._dense(), b._dense())
    if split is None:
        split = 0 if (a.split is not None or b.split is not None) else None
    return DNDarray.from_dense(result, split, a.device, a.comm)


def projection(a: DNDarray, b: DNDarray) -> DNDarray:
    """Projection of a onto b (basics.py:1688)."""
    sanitize_in(a)
    sanitize_in(b)
    if a.ndim != 1 or b.ndim != 1:
        raise RuntimeError(f"projection requires 1-D vectors, got {a.ndim}-D and {b.ndim}-D")
    bd = b._dense()
    coeff = jnp.dot(a._dense(), bd) / jnp.dot(bd, bd)
    return DNDarray.from_dense(coeff * bd, b.split, b.device, b.comm)


def trace(a: DNDarray, offset: int = 0, axis1: int = 0, axis2: int = 1, dtype=None, out=None) -> Union[DNDarray, float]:
    """Sum along diagonals (basics.py:1710)."""
    sanitize_in(a)
    result = jnp.trace(a._dense(), offset=offset, axis1=axis1, axis2=axis2)
    if dtype is not None:
        result = result.astype(types.canonical_heat_type(dtype).jax_type())
    res = DNDarray.from_dense(result, None, a.device, a.comm)
    if out is not None:
        out._replace(res.larray_padded)
        return out
    if res.ndim == 0:
        return res.item()
    return res


def transpose(a: DNDarray, axes: Optional[Sequence[int]] = None) -> DNDarray:
    """Permute dimensions (basics.py:2126).

    Operates directly on the padded buffer: the permutation carries the
    split axis (and its padding) to its new position; only the sharding
    annotation moves — no data copy beyond XLA's relayout.
    """
    sanitize_in(a)
    if axes is None:
        perm = tuple(reversed(range(a.ndim)))
    else:
        perm = tuple(sanitize_axis(a.shape, ax) for ax in axes)
        if len(perm) != a.ndim or len(set(perm)) != a.ndim:
            raise ValueError(f"axes must be a permutation of dimensions, got {axes}")
    permuted = jnp.transpose(a.larray_padded, perm)
    new_split = perm.index(a.split) if a.split is not None else None
    new_gshape = tuple(a.shape[p] for p in perm)
    return DNDarray(
        jax.device_put(permuted, a.comm.sharding(new_split)),
        new_gshape,
        a.dtype,
        new_split,
        a.device,
        a.comm,
    )


def _tri_op(m: DNDarray, k: int, op) -> DNDarray:
    """Shared tril/triu implementation (basics.py:2196 ``__tri_op``);
    padding is at the end of the split axis so diagonal indexing on the
    padded buffer matches the dense indexing."""
    sanitize_in(m)
    if m.ndim == 1:
        dense = m._dense()
        result = op(jnp.broadcast_to(dense, (dense.shape[0], dense.shape[0])), k=k)
        split = 0 if m.split is not None else None
        return DNDarray.from_dense(result, split, m.device, m.comm)
    result = op(m.larray_padded, k=k)
    return DNDarray(
        jax.device_put(result, m.comm.sharding(m.split)),
        m.shape,
        m.dtype,
        m.split,
        m.device,
        m.comm,
    )


def tril(m: DNDarray, k: int = 0) -> DNDarray:
    """Lower triangle (basics.py:2263)."""
    return _tri_op(m, k, jnp.tril)


def triu(m: DNDarray, k: int = 0) -> DNDarray:
    """Upper triangle (basics.py:2287)."""
    return _tri_op(m, k, jnp.triu)


def vdot(x1: DNDarray, x2: DNDarray) -> DNDarray:
    """Conjugated dot product (basics.py:2311)."""
    sanitize_in(x1)
    sanitize_in(x2)
    result = jnp.vdot(x1._dense(), x2._dense(), precision=matmul_precision(x1._dense().dtype))
    return DNDarray.from_dense(result, None, x1.device, x1.comm)


def vecdot(x1: DNDarray, x2: DNDarray, axis: Optional[int] = None, keepdims: bool = False) -> DNDarray:
    """Vector dot along an axis (basics.py:2347)."""
    sanitize_in(x1)
    sanitize_in(x2)
    ax = -1 if axis is None else axis
    result = jnp.vecdot(x1._dense(), x2._dense(), axis=ax, precision=matmul_precision(x1._dense().dtype))
    if keepdims:
        result = jnp.expand_dims(result, ax)
    split = None
    if x1.split is not None and x1.split < result.ndim:
        split = x1.split
    return DNDarray.from_dense(result, split, x1.device, x1.comm)


def vector_norm(x: DNDarray, axis=None, keepdims: bool = False, ord=None) -> DNDarray:
    """Vector norm (basics.py:2384)."""
    sanitize_in(x)
    if axis is not None and isinstance(axis, tuple) and len(axis) > 1:
        raise TypeError("axis must be an integer or 1-tuple for vector_norm")
    dense = x._dense()
    if not types.heat_type_is_inexact(x.dtype):
        dense = dense.astype(jnp.float32)
    if axis is None:
        dense = dense.ravel()
        axis_n = 0
    else:
        axis_n = sanitize_axis(x.shape, axis if not isinstance(axis, tuple) else axis[0])
    result = jnp.linalg.norm(dense, ord=2 if ord is None else ord, axis=axis_n, keepdims=keepdims)
    split = None
    if axis is not None and x.split is not None and x.split != axis_n:
        split = x.split - (1 if axis_n < x.split and not keepdims else 0)
    return DNDarray.from_dense(result, split, x.device, x.comm)
