"""QR decomposition, analog of heat/core/linalg/qr.py (qr.py:17-310).

Reference algorithms: split=0 tall-skinny -> TS-QR with a tree merge of
stacked R factors (procs_to_merge fan-in, Demmel et al. 2012, qr.py:64);
split=1 -> block-wise stabilized Gram-Schmidt with Bcasts of the current
column block (qr.py:125-310).

TPU-native:

* split=0: the TS-QR tree is expressed as a shard_map collective program —
  each shard takes a local QR, all-gathers the small R factors over ICI,
  and (redundantly, replicated across shards) merges them with one more
  QR; the local Q is then corrected by its block of the merge Q.  One ICI
  all-gather of p×(n×n) floats replaces the reference's log-p rounds of
  paired send/recvs.  Ragged extents (m % p != 0) are handled by zeroing
  the canonical padding rows inside the kernel — the zero rows drop out of
  both the local QR and the merge, so no gather-and-recompute fallback is
  needed.
* split=1: block modified Gram-Schmidt as a shard_map program.  Round i
  broadcasts device i's freshly orthonormalized column block (a psum of a
  masked operand — the collective form of the reference's Bcast), and
  every later device immediately projects it out of its own columns
  (right-looking update = block MGS, the stabilized ordering).  Padded
  columns are masked to zero so they contribute no spurious projections.

Falls back to a global XLA QR only for wide (m < n) split=1 inputs,
batched inputs, and single-device meshes.
"""

from __future__ import annotations

import collections
from typing import Optional

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from .. import types
from ..dndarray import DNDarray
from ..sanitation import sanitize_in
from .._compat import shard_map as _shard_map

__all__ = ["qr"]

QR = collections.namedtuple("QR", "Q, R")

_HI = jax.lax.Precision.HIGHEST


def qr(
    A: DNDarray,
    mode: str = "reduced",
    procs_to_merge: int = 2,
) -> QR:
    """Reduced QR decomposition of a 2-D (or batched) array.

    Returns the namedtuple ``QR(Q, R)``; with ``mode='r'`` the Q factor is
    ``None`` (matching qr.py:33-40).
    """
    sanitize_in(A)
    if mode not in ("reduced", "r"):
        raise ValueError(f"mode must be 'reduced' or 'r', got {mode!r}")
    if A.ndim < 2:
        raise ValueError(f"Array A must be at least two-dimensional, but is {A.ndim}-dimensional")
    if not types.heat_type_is_realfloating(A.dtype) and not types.heat_type_is_complexfloating(A.dtype):
        A = A.astype(types.float32)

    m, n = A.shape[-2], A.shape[-1]
    comm = A.comm
    p = comm.size

    use_tsqr = (
        A.ndim == 2
        and A.split == 0
        and p > 1
        and (comm.padded_extent(m) // p) >= n
    )
    if use_tsqr:
        q_pad, r = _tsqr_shard_map(A, compute_q=(mode == "reduced"))
        R = DNDarray.from_dense(r, None, A.device, A.comm)
        if mode == "r":
            return QR(None, R)
        Q = DNDarray(
            jax.device_put(q_pad, comm.sharding(0)),
            (m, n),
            A.dtype,
            0,
            A.device,
            A.comm,
        )
        return QR(Q, R)

    use_bgs = A.ndim == 2 and A.split == 1 and p > 1 and m >= n
    if use_bgs:
        q_pad, r_pad = _bgs_fn(comm, n, A.larray_padded.shape[1] // p)(A.larray_padded)
        R = DNDarray(
            jax.device_put(r_pad, comm.sharding(1)), (n, n), A.dtype, 1, A.device, A.comm
        )
        if mode == "r":
            return QR(None, R)
        Q = DNDarray(
            jax.device_put(q_pad, comm.sharding(1)), (m, n), A.dtype, 1, A.device, A.comm
        )
        return QR(Q, R)

    # general path: XLA's QR over the (sharded) dense view — wide split=1
    # matrices, batched inputs, and single-device meshes
    dense = A._dense()
    if mode == "r":
        r = jnp.linalg.qr(dense, mode="r")
        return QR(None, DNDarray.from_dense(r, None if A.ndim == 2 else A.split, A.device, A.comm))
    q, r = jnp.linalg.qr(dense, mode="reduced")
    q_split = A.split
    r_split = None if A.ndim == 2 and A.split == 0 else A.split
    if A.ndim == 2 and A.split == 1:
        r_split = 1
    return QR(
        DNDarray.from_dense(q, q_split, A.device, A.comm),
        DNDarray.from_dense(r, r_split, A.device, A.comm),
    )


def _tsqr_shard_map(A: DNDarray, compute_q: bool = True):
    """Single-level TS-QR as a shard_map collective (see module docstring).

    Requires padded_m/p >= n (caller checks).  Ragged true extents are
    masked inside the kernel; fully-padded shards contribute zero R rows
    and produce zero Q rows.
    """
    comm = A.comm
    m = A.shape[0]
    # padding rows are don't-care bytes (zero at creation, but elementwise
    # ops may have mapped them); mask only when padding exists
    m_true = m if comm.pad_amount(m) else 0
    q, r = _tsqr_fn(comm, compute_q, m_true)(A.larray_padded)
    # r is replicated identically on all shards; take it as the global R
    return q, r


@functools.lru_cache(maxsize=64)
def _tsqr_fn(comm, compute_q: bool, m_true: int):
    """Jitted, cached TS-QR executable — rebuilding the shard_map per call
    would retrace (and through a remote compile service, recompile) on
    every invocation.  ``m_true > 0`` enables masking of canonical padding
    rows (the ragged case); 0 means the extent divides evenly."""
    mesh = comm.mesh
    axis = comm.axis_name

    def body(a_loc):
        # a_loc: (padded_m/p, n) local block
        rows, n = a_loc.shape
        idx = jax.lax.axis_index(axis)
        if m_true:
            grow = idx * rows + jnp.arange(rows)
            a_loc = jnp.where((grow < m_true)[:, None], a_loc, 0)
        q1, r1 = jnp.linalg.qr(a_loc, mode="reduced")  # (rows, n), (n, n)
        r_all = jax.lax.all_gather(r1, axis, axis=0, tiled=True)  # (p*n, n)
        q2, r2 = jnp.linalg.qr(r_all, mode="reduced")  # (p*n, n), (n, n)
        q2_block = jax.lax.dynamic_slice_in_dim(q2, idx * n, n, axis=0)  # (n, n)
        q_loc = jnp.matmul(q1, q2_block, precision=_HI) if compute_q else q1
        return q_loc, r2

    return jax.jit(
        _shard_map(
            body,
            mesh=mesh,
            in_specs=P(axis, None),
            out_specs=(P(axis, None), P(None, None)),
            # r2 is computed redundantly from the all-gathered R stack, so it
            # is replicated by construction; the static analyzer cannot see
            # through the QR call to prove it
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=64)
def _bgs_fn(comm, n_true: int, nb: int):
    """Jitted, cached split=1 block modified Gram-Schmidt executable.

    The reference's column-block loop (qr.py:220+: current rank takes a
    local QR of its block, Bcasts the Q panel, later ranks subtract the
    projection) becomes p rounds inside one shard_map program:

      round i: every shard runs the local QR (only shard i's result is
      kept), shard i's orthonormal panel Qi is broadcast as
      psum(where(idx==i, Qi, 0)), and shards j>i update
      A_j -= Qi (Qi^T A_j) immediately — the right-looking (block-MGS)
      ordering that keeps the process stabilized.

    Outputs the padded Q (m, p*nb) and R (n_true, p*nb), both split=1.
    """
    mesh = comm.mesh
    axis = comm.axis_name
    p = comm.size

    def body(a_loc):
        # a_loc: (m, nb) local column block
        idx = jax.lax.axis_index(axis)
        gcol = idx * nb + jnp.arange(nb)
        colmask = (gcol < n_true).astype(a_loc.dtype)  # (nb,)
        a_loc = a_loc * colmask[None, :]

        def round_i(i, carry):
            a_cur, q_loc, r_loc = carry
            qi_cand, rii = jnp.linalg.qr(a_cur, mode="reduced")  # (m, nb), (nb, nb)
            # padded input columns give zero R columns, but arbitrary
            # orthonormal Q columns — zero them so they project nothing
            qi_cand = qi_cand * colmask[None, :]
            is_me = (idx == i).astype(a_cur.dtype)
            # Bcast of shard i's panel as a collective sum of masked operands
            qi = jax.lax.psum(qi_cand * is_me, axis)  # (m, nb)
            q_loc = jnp.where(idx == i, qi_cand, q_loc)
            r_loc = jnp.where(
                idx == i,
                jax.lax.dynamic_update_slice_in_dim(r_loc, rii * colmask[None, :], i * nb, 0),
                r_loc,
            )
            # later shards subtract the projection onto Qi right away
            rij = jnp.matmul(qi.T, a_cur, precision=_HI)  # (nb, nb)
            later = idx > i
            rij = jnp.where(later, rij, 0.0)
            a_cur = a_cur - jnp.matmul(qi, rij, precision=_HI)
            r_loc = jnp.where(
                later,
                jax.lax.dynamic_update_slice_in_dim(r_loc, rij, i * nb, 0),
                r_loc,
            )
            return a_cur, q_loc, r_loc

        r0 = jnp.zeros((p * nb, nb), a_loc.dtype)
        _, q_loc, r_loc = jax.lax.fori_loop(
            0, p, round_i, (a_loc, jnp.zeros_like(a_loc), r0)
        )
        # R rows beyond the true column count are zero by construction;
        # drop them so the unsplit row dim has the exact global extent
        return q_loc, r_loc[:n_true]

    return jax.jit(
        _shard_map(
            body,
            mesh=mesh,
            in_specs=P(None, axis),
            out_specs=(P(None, axis), P(None, axis)),
            check_vma=False,
        )
    )
