"""Distributed FFT module (analog of heat/fft)."""

from .fft import *
