"""Shared byte-bounded LRU for the FFT weight/twiddle matrices.

The DFT weight matrices scale as n^2 (a 1024-point f64 (cos, sin) pair
is 16 MB; the (n, 2n) cat matrices and their bf16 splits likewise), so
an entry-count-bounded ``lru_cache`` over varied transform sizes can pin
hundreds of MB to ~1 GB of host RAM for the process lifetime (ADVICE
round 5).  Every weight builder in ``_leading.py`` **and**
``_planar.py`` therefore shares ONE insertion-ordered LRU keyed by
``(builder name, args)`` and bounded by BYTES
(``HEAT_TPU_FFT_WEIGHT_CACHE_MB``, default 256): inserts evict
least-recently-used entries until the total fits, so sweeping sizes
recomputes cold weights instead of growing without bound.

Evictions are counted into the telemetry registry
(``fft.weight_cache.evictions``) and the live byte total is a callback
gauge (``fft.weight_cache.nbytes``), so a workload thrashing the weight
cache is visible from ``telemetry.snapshot()`` / the ``/varz`` endpoint
instead of only as mysterious recompute time.
"""

from __future__ import annotations

import functools
import os

from ..telemetry import metrics as _tm

__all__ = [
    "byte_lru",
    "weight_cache_clear",
    "weight_cache_stats",
]

_WEIGHT_CACHE_BUDGET = int(
    float(os.environ.get("HEAT_TPU_FFT_WEIGHT_CACHE_MB", "256")) * (1 << 20)
)
_weight_cache: "dict" = {}  # insertion-ordered; move-to-end on hit
_weight_cache_nbytes = 0

_EVICTIONS = _tm.counter(
    "fft.weight_cache.evictions",
    "FFT weight-cache entries evicted by the shared byte budget",
)
_tm.gauge(
    "fft.weight_cache.nbytes",
    "live bytes held by the shared FFT weight cache",
    fn=lambda: _weight_cache_nbytes,
)


def _entry_nbytes(val) -> int:
    if isinstance(val, tuple):
        return sum(_entry_nbytes(v) for v in val)
    return int(getattr(val, "nbytes", 0))


def byte_lru(fn):
    """lru_cache analog bounded by the shared byte budget."""
    tag = fn.__name__

    @functools.wraps(fn)
    def wrapper(*args):
        global _weight_cache_nbytes
        key = (tag, args)
        if key in _weight_cache:
            val = _weight_cache.pop(key)  # re-insert: most recently used
            _weight_cache[key] = val
            return val
        val = fn(*args)
        _weight_cache[key] = val
        _weight_cache_nbytes += _entry_nbytes(val)
        while _weight_cache_nbytes > _WEIGHT_CACHE_BUDGET and len(_weight_cache) > 1:
            old = _weight_cache.pop(next(iter(_weight_cache)))
            _weight_cache_nbytes -= _entry_nbytes(old)
            _EVICTIONS.inc()
        return val

    return wrapper


def weight_cache_stats() -> dict:
    """Size/budget snapshot of the shared weight cache (test surface)."""
    return {
        "entries": len(_weight_cache),
        "nbytes": _weight_cache_nbytes,
        "budget_nbytes": _WEIGHT_CACHE_BUDGET,
        "evictions": _EVICTIONS.value,
    }


def weight_cache_clear() -> None:
    global _weight_cache_nbytes
    _weight_cache.clear()
    _weight_cache_nbytes = 0
