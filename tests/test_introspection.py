"""Runtime-introspection layer tests (ISSUE 6 tentpole).

The contract under test (docs/observability.md):

* the HTTP endpoint (``telemetry/server.py``) serves ``/metrics``
  ``/varz`` ``/healthz`` ``/trace`` ``/statusz`` on an ephemeral port,
  scrapeable WHILE a real resumable KMeans fit runs in the process;
* ``/healthz`` reports the fit heartbeat + last durable checkpoint step
  and flips to 503 when the heartbeat is older than
  ``HEAT_TPU_HEALTH_MAX_AGE_S``;
* a subprocess crashed by an injected ``PermanentFault`` leaves a
  checksum-valid, schema-complete crash bundle that
  ``python -m heat_tpu.telemetry.inspect`` renders;
* cross-worker snapshot merging is a deterministic pure function and the
  ``telemetry.straggler_score`` gauge fires on synthetic 2-worker skew;
* per-executable cost accounting records XLA flops/bytes per dispatch
  cache key when enabled and stays inert when disabled;
* every knob this layer introduced is registered in the central table
  (H201-clean by construction).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import heat_tpu as ht
from heat_tpu import telemetry
from heat_tpu.telemetry import aggregate, flight_recorder
from heat_tpu.telemetry import inspect as tinspect
from heat_tpu.telemetry import metrics as tm
from heat_tpu.telemetry import server as tserver

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _tracing_on():
    prev = telemetry.set_tracing(True)
    telemetry.clear_spans()
    yield
    telemetry.set_tracing(prev)
    telemetry.clear_spans()


@pytest.fixture
def live_server():
    srv = tserver.start_server(0)
    yield srv
    tserver.stop_server()


def _get(srv, route):
    with urllib.request.urlopen(f"{srv.url}{route}", timeout=10) as r:
        return r.status, r.read().decode("utf-8")


def _data():
    ht.random.seed(7)
    return ht.random.randn(240, 6, split=0).astype(ht.float32)


# ----------------------------------------------------------------------
# HTTP endpoints against a live resumable fit
# ----------------------------------------------------------------------
class TestEndpoints:
    def test_all_routes_serve_during_live_fit(self, live_server, tmp_path):
        """Every route answers 200 while a resumable KMeans fit is
        actually running in this process (scraper thread polls /healthz
        concurrently with the fit)."""
        codes = []
        stop = threading.Event()

        def scrape():
            while not stop.is_set():
                try:
                    codes.append(_get(live_server, "/healthz")[0])
                except OSError:  # server busy starting; keep polling
                    pass
                time.sleep(0.005)

        t = threading.Thread(target=scrape, daemon=True)
        t.start()
        try:
            km = ht.cluster.KMeans(
                n_clusters=4, init="random", max_iter=20, tol=-1.0,
                random_state=0, checkpoint_every=2, checkpoint_dir=str(tmp_path / "ck"),
            ).fit(_data())
        finally:
            stop.set()
            t.join(timeout=10)
        assert km.cluster_centers_ is not None
        assert codes and all(c == 200 for c in codes)

        status, body = _get(live_server, "/metrics")
        assert status == 200
        assert "heat_tpu_fit_iter_rate" in body
        assert "# TYPE" in body

        status, body = _get(live_server, "/varz")
        doc = json.loads(body)
        assert status == 200
        assert doc["pid"] == os.getpid()
        assert doc["metrics"]["fit.heartbeat_ts"] > 0

        status, body = _get(live_server, "/trace")
        trace = json.loads(body)
        assert status == 200
        assert any(e["name"] == "fit.chunk" for e in trace["traceEvents"])

        status, body = _get(live_server, "/statusz")
        statusz = json.loads(body)
        assert status == 200
        assert "HEAT_TPU_HTTP_PORT" in statusz["knobs"]
        assert statusz["runtime"]["jax"] is not None
        assert statusz["dispatch"] is not None
        assert 0.0 <= statusz["dispatch"]["hit_rate"] <= 1.0
        assert isinstance(statusz["dispatch"]["cache_keys"], list)

        status, doc = _get(live_server, "/healthz")
        health = json.loads(doc)
        assert status == 200
        assert health["status"] == "ok"
        assert health["heartbeat_age_s"] is not None
        assert health["checkpoint"]["last_step"] is not None

    def test_unknown_route_404_and_root_index(self, live_server):
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(live_server, "/nope")
        assert exc.value.code == 404
        status, body = _get(live_server, "/")
        assert status == 200 and "/statusz" in body

    def test_start_is_idempotent_and_stop_clears(self):
        a = tserver.start_server(0)
        b = tserver.start_server(0)
        assert a is b and tserver.server_running()
        tserver.stop_server()
        assert not tserver.server_running()
        tserver.stop_server()  # second stop is a no-op

    def test_healthz_flips_unhealthy_on_stale_heartbeat(self, live_server, monkeypatch):
        tm.gauge("fit.heartbeat_ts").set(time.time() - 60.0)
        monkeypatch.setenv("HEAT_TPU_HEALTH_MAX_AGE_S", "5")
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(live_server, "/healthz")
        assert exc.value.code == 503
        doc = json.loads(exc.value.read().decode("utf-8"))
        assert doc["status"] == "stale"
        # a fresh heartbeat restores health without restarting anything
        tm.gauge("fit.heartbeat_ts").set(time.time())
        status, body = _get(live_server, "/healthz")
        assert status == 200 and json.loads(body)["status"] == "ok"

    def test_healthz_idle_before_any_fit(self, live_server, monkeypatch):
        monkeypatch.setenv("HEAT_TPU_HEALTH_MAX_AGE_S", "5")
        prev = tm.gauge("fit.heartbeat_ts").value
        tm.gauge("fit.heartbeat_ts").set(0.0)
        try:
            status, body = _get(live_server, "/healthz")
            assert status == 200 and json.loads(body)["status"] == "idle"
        finally:
            tm.gauge("fit.heartbeat_ts").set(prev)


# ----------------------------------------------------------------------
# flight recorder
# ----------------------------------------------------------------------
BUNDLE_KEYS = {
    "schema", "reason", "timestamp", "pid", "exception", "knobs",
    "metrics", "spans", "dispatch", "checkpoint", "runtime",
}


class TestFlightRecorder:
    def test_manual_bundle_schema_and_checksum(self, tmp_path):
        with telemetry.span("probe.crash", step=3):
            pass
        tm.counter("probe.fr").inc(2)
        path = flight_recorder.dump_bundle(
            ValueError("manual probe"), reason="manual", directory=str(tmp_path)
        )
        doc = tinspect.load_bundle(path)  # checksum-verified load
        assert BUNDLE_KEYS <= set(doc)
        assert doc["schema"] == flight_recorder.BUNDLE_SCHEMA
        assert doc["exception"]["type"] == "ValueError"
        assert any(s["name"] == "probe.crash" for s in doc["spans"])
        assert doc["metrics"]["probe.fr"] >= 2
        assert "HEAT_TPU_FLIGHT_RECORDER" in doc["knobs"]
        text = tinspect.format_bundle(doc)
        assert "ValueError: manual probe" in text and "probe.crash" in text

    def test_corrupt_bundle_fails_loudly(self, tmp_path):
        from heat_tpu.resilience.errors import ChecksumError

        path = flight_recorder.dump_bundle(
            RuntimeError("x"), reason="manual", directory=str(tmp_path)
        )
        with open(path, "a") as f:  # deliberate corruption (tests are not linted)
            f.write(" ")
        with pytest.raises(ChecksumError):
            tinspect.load_bundle(path)

    def test_install_uninstall_hooks(self, tmp_path):
        prev_hook = sys.excepthook
        d = flight_recorder.install(str(tmp_path))
        try:
            assert flight_recorder.installed() and d == str(tmp_path)
            assert sys.excepthook is not prev_hook
            flight_recorder.install(str(tmp_path))  # idempotent
        finally:
            flight_recorder.uninstall()
        assert not flight_recorder.installed()
        assert sys.excepthook is prev_hook

    def test_subprocess_crash_leaves_valid_bundle(self, tmp_path):
        """A child killed by an injected PermanentFault mid-fit leaves a
        checksum-valid, schema-complete bundle that the inspect CLI
        renders."""
        bundles = tmp_path / "bundles"
        child = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "jax.config.update('jax_enable_x64', True)\n"
            "import heat_tpu as ht\n"
            "ht.random.seed(11)\n"
            "x = ht.random.randn(240, 6, split=0).astype(ht.float32)\n"
            "ht.cluster.KMeans(n_clusters=4, init='random', max_iter=40,\n"
            "                  tol=-1.0, random_state=2, checkpoint_every=2,\n"
            f"                  checkpoint_dir={str(tmp_path / 'ck')!r}).fit(x)\n"
        )
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["HEAT_TPU_FLIGHT_RECORDER"] = str(bundles)
        env["HEAT_TPU_FAULT_PLAN"] = json.dumps(
            {"plan": {"kmeans.iter": [{"at": 2, "kind": "permanent"}]}}
        )
        proc = subprocess.run(
            [sys.executable, "-c", child], env=env, capture_output=True,
            cwd=REPO_ROOT, timeout=300,
        )
        assert proc.returncode != 0
        assert b"PermanentFault" in proc.stderr
        paths = sorted(bundles.glob("flight_*.json"))
        assert len(paths) == 1
        doc = tinspect.load_bundle(str(paths[0]))  # CRC-verified
        assert BUNDLE_KEYS <= set(doc)
        assert doc["reason"] == "unhandled_exception"
        assert doc["exception"]["type"] == "PermanentFault"
        assert doc["exception"]["site"] == "kmeans.iter"
        assert any(s["name"] == "fit.chunk" for s in doc["spans"])
        # boundaries: inject#0(total=2)->save(2), inject#1(4)->save(4),
        # inject#2(6) raises before save(6) -> last durable step is 4
        assert doc["checkpoint"]["last_step"] == 4
        assert doc["knobs"]["HEAT_TPU_FAULT_PLAN"]["set"] is True

        # the inspect CLI renders it end to end
        res = subprocess.run(
            [sys.executable, "-m", "heat_tpu.telemetry.inspect", str(paths[0])],
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
            capture_output=True, cwd=REPO_ROOT, timeout=300,
        )
        assert res.returncode == 0, res.stderr.decode()[-2000:]
        out = res.stdout.decode()
        assert "PermanentFault" in out and "fit.chunk" in out
        assert "last durable step: 4" in out


# ----------------------------------------------------------------------
# cross-worker aggregation
# ----------------------------------------------------------------------
def _synthetic_worker(ix, chunk_mean_ms, comm_total_ms=5.0):
    return {
        "process_index": ix,
        "process_count": 2,
        "pid": 1000 + ix,
        "timestamp": 1.0,
        "metrics": {"dispatch.hits": 10 * (ix + 1), "fit.iter_rate": 100.0 / (ix + 1)},
        "span_stats": {
            "fit.chunk": {
                "count": 4,
                "total_ms": 4 * chunk_mean_ms,
                "mean_ms": chunk_mean_ms,
                "max_ms": chunk_mean_ms * 1.2,
            },
            "comm.psum": {
                "count": 2,
                "total_ms": comm_total_ms,
                "mean_ms": comm_total_ms / 2,
                "max_ms": comm_total_ms,
            },
        },
    }


class TestAggregate:
    def test_tag_snapshot_identity(self):
        snap = aggregate.tag_snapshot()
        assert snap["process_index"] == 0 and snap["process_count"] >= 1
        assert snap["pid"] == os.getpid()
        assert isinstance(snap["metrics"], dict)

    def test_span_stats_digest(self):
        telemetry.clear_spans()
        for _ in range(3):
            with telemetry.span("agg.probe"):
                pass
        ss = aggregate.span_stats()
        assert ss["agg.probe"]["count"] == 3
        assert ss["agg.probe"]["total_ms"] >= ss["agg.probe"]["max_ms"]

    def test_merge_is_deterministic_and_order_invariant(self):
        a, b = _synthetic_worker(0, 10.0), _synthetic_worker(1, 30.0)
        m1 = aggregate.merge_snapshots([a, b], publish=False)
        m2 = aggregate.merge_snapshots([b, a], publish=False)
        assert json.dumps(m1, sort_keys=True) == json.dumps(m2, sort_keys=True)
        assert m1["merged"]["dispatch.hits"]["sum"] == 30
        assert m1["merged"]["fit.iter_rate"]["per_worker"] == {"0": 100.0, "1": 50.0}

    def test_straggler_gauge_fires_on_synthetic_skew(self):
        snaps = [_synthetic_worker(0, 10.0, 2.0), _synthetic_worker(1, 30.0, 9.0)]
        merged = aggregate.merge_snapshots(snaps)
        # means [10, 30]: median 20 -> (30 - 20) / 20 = 0.5
        assert merged["skew"]["straggler_score"] == pytest.approx(0.5)
        assert merged["skew"]["chunk_spread"] == pytest.approx(1.0)
        assert merged["skew"]["comm_imbalance"] > 0
        assert tm.gauge("telemetry.straggler_score").value == pytest.approx(0.5)
        assert float(tm.gauge("telemetry.straggler_score").value) != 0.0

    def test_balanced_workers_score_zero(self):
        snaps = [_synthetic_worker(0, 10.0), _synthetic_worker(1, 10.0)]
        merged = aggregate.merge_snapshots(snaps, publish=False)
        assert merged["skew"]["straggler_score"] == 0.0
        assert merged["skew"]["chunk_spread"] == 0.0

    def test_dead_worker_scores_capped_inf(self):
        dead = _synthetic_worker(1, 30.0)
        dead["span_stats"].pop("fit.chunk")
        merged = aggregate.merge_snapshots(
            [_synthetic_worker(0, 10.0), dead], publish=False
        )
        assert merged["skew"]["straggler_score"] == pytest.approx(1e9)

    def test_file_transport_roundtrip(self, tmp_path):
        d = str(tmp_path / "snaps")
        path = aggregate.write_worker_snapshot(d)
        assert os.path.exists(path) and os.path.exists(path + ".crc32")
        snaps = aggregate.read_worker_snapshots(d)
        assert len(snaps) == 1 and snaps[0]["pid"] == os.getpid()
        # single-process gather short-circuits to the local snapshot
        gathered = aggregate.gather_snapshots()
        assert len(gathered) == 1 and gathered[0]["process_index"] == 0


# ----------------------------------------------------------------------
# dispatch cost accounting
# ----------------------------------------------------------------------
class TestCostAccounting:
    def test_records_flops_per_cache_key(self):
        from heat_tpu.core import dispatch

        prev = dispatch.set_cost_accounting(True)
        dispatch.clear_cache()
        try:
            x = ht.arange(64, split=0).astype(ht.float32)
            float((x * 2.0 + 1.0).sum())
            cs = dispatch.cost_summary()
            assert cs["enabled"] and cs["executables"] >= 1
            assert cs["flops_total"] > 0
            assert tm.counter("dispatch.flops_total").value > 0
            rec = next(iter(cs["per_key"].values()))
            assert rec["flops"] >= 0 and "bytes_accessed" in rec
            assert len(dispatch.cache_keys()) >= len(cs["per_key"])
        finally:
            dispatch.set_cost_accounting(prev)
            dispatch.clear_cache()

    def test_disabled_records_nothing(self):
        from heat_tpu.core import dispatch

        prev = dispatch.set_cost_accounting(False)
        dispatch.clear_cache()
        try:
            x = ht.arange(32, split=0).astype(ht.float32)
            float((x + 1.0).sum())
            cs = dispatch.cost_summary()
            assert not cs["enabled"]
            assert cs["executables"] == 0 and cs["per_key"] == {}
        finally:
            dispatch.set_cost_accounting(prev)
            dispatch.clear_cache()

    def test_statusz_carries_cost_summary(self):
        doc = tserver.statusz_report()
        cost = doc["dispatch"]["cost"]
        assert set(cost) >= {"enabled", "executables", "flops_total", "bytes_total"}


# ----------------------------------------------------------------------
# knobs + satellites riding along
# ----------------------------------------------------------------------
class TestKnobsAndSatellites:
    def test_new_knobs_registered(self):
        from heat_tpu.core._env import KNOBS, env_flag, env_float, env_int, env_str

        for name in (
            "HEAT_TPU_HTTP_PORT",
            "HEAT_TPU_HEALTH_MAX_AGE_S",
            "HEAT_TPU_FLIGHT_RECORDER",
            "HEAT_TPU_COST_ANALYSIS",
        ):
            assert name in KNOBS, name
        assert env_int("HEAT_TPU_HTTP_PORT") == 0
        assert env_float("HEAT_TPU_HEALTH_MAX_AGE_S") == 0.0
        assert env_str("HEAT_TPU_FLIGHT_RECORDER") == ""
        assert env_flag("HEAT_TPU_COST_ANALYSIS") is False

    def test_metrics_dump_writes_crc_sidecar(self, tmp_path):
        path = str(tmp_path / "dump.json")
        telemetry.dump_json(path)
        assert os.path.exists(path + ".crc32")
        from heat_tpu.resilience.atomic import verify_checksum

        assert verify_checksum(path) is True
        doc = json.loads(open(path).read())
        assert "metrics" in doc

    def test_chrome_trace_export_is_atomic_no_sidecar(self, tmp_path):
        with telemetry.span("trace.probe"):
            pass
        path = str(tmp_path / "trace.json")
        n = telemetry.export_chrome_trace(path)
        assert n >= 1
        doc = json.loads(open(path).read())
        assert any(e["name"] == "trace.probe" for e in doc["traceEvents"])
        assert not os.path.exists(path + ".crc32")  # perfetto-facing artifact

    def test_weight_cache_eviction_counter(self, monkeypatch):
        from heat_tpu.fft import _leading, _weight_cache

        monkeypatch.setattr(_weight_cache, "_WEIGHT_CACHE_BUDGET", 1 << 20)
        _weight_cache.weight_cache_clear()
        before = tm.counter("fft.weight_cache.evictions").value
        try:
            for n in (64, 128, 192, 256, 320):
                _leading._w_cat(n, "float32", False, 1.0)
            assert tm.counter("fft.weight_cache.evictions").value > before
            s = _weight_cache.weight_cache_stats()
            assert s["nbytes"] <= s["budget_nbytes"] or s["entries"] == 1
            assert "evictions" in s
        finally:
            _weight_cache.weight_cache_clear()

    def test_planar_weight_builders_share_byte_cache(self):
        from heat_tpu.fft import _planar, _weight_cache

        _weight_cache.weight_cache_clear()
        try:
            _planar._dft_w(32, False, "float32")
            _planar._twiddle(8, 4, 32, False, "float32")
            s = _weight_cache.weight_cache_stats()
            assert s["entries"] >= 2 and s["nbytes"] > 0
        finally:
            _weight_cache.weight_cache_clear()


# ----------------------------------------------------------------------
# ISSUE 19 satellite: every server-owned route scrapes clean
# ----------------------------------------------------------------------
SERVER_ROUTES = [r for r in tserver.BUILTIN_ROUTES if r["owner"] == "server"]


def _get_full(srv, route):
    with urllib.request.urlopen(f"{srv.url}{route}", timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


class TestAllRoutesScrape:
    def test_route_registry_covers_every_server_route(self):
        assert len(SERVER_ROUTES) >= 15
        assert len({r["route"] for r in tserver.BUILTIN_ROUTES}) == len(
            tserver.BUILTIN_ROUTES
        )
        for entry in tserver.BUILTIN_ROUTES:
            assert entry["purpose"] and entry["owner"], entry["route"]

    @pytest.mark.parametrize(
        "entry", SERVER_ROUTES, ids=[r["route"] for r in SERVER_ROUTES]
    )
    def test_route_scrapes_clean(self, live_server, entry):
        route = entry["route"]
        status, ctype, body = _get_full(live_server, route)
        assert status == 200, route
        assert body
        if route == "/metrics":
            assert ctype.startswith("application/openmetrics-text")
            assert body.rstrip().endswith("# EOF")
        elif entry["html"]:
            assert "text/html" in ctype
            sep = "&" if "?" in route else "?"
            jstatus, jctype, jbody = _get_full(
                live_server, f"{route}{sep}format=json"
            )
            assert jstatus == 200 and "application/json" in jctype
            json.loads(jbody)
        else:
            assert "application/json" in ctype
            json.loads(body)

    def test_hostile_names_are_escaped(self, live_server):
        from heat_tpu.telemetry import alerts as talerts
        from heat_tpu.telemetry import journal as tjournal

        hostile = "<script>alert(1)</script>"
        tjournal.reset_journal()
        talerts.clear_alerts()
        try:
            ev = tjournal.emit(
                "canary", "rolled_back", model=hostile,
                tenant=f"t-{hostile}", severity="page",
                message=f"bad {hostile} news",
                evidence={"reason": hostile},
            )
            talerts.fire(
                f"canary:{hostile}", severity="page",
                message=f"alert {hostile}", labels={"model": hostile},
            )
            for route in ("/decisionz", f"/decisionz?event_id={ev['event_id']}"):
                status, _ctype, body = _get_full(live_server, route)
                assert status == 200
                assert "<script>" not in body, route
                assert "&lt;script&gt;" in body, route
        finally:
            tjournal.reset_journal()
            talerts.clear_alerts()
