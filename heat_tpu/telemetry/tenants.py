"""Per-tenant cost metering: who spent the chips, in FLOPs and bytes.

Multi-tenant QoS scheduling (docs/serving.md) makes tenants with
different contracts share one device pool — which makes "which tenant
cost what" a first-class question.  This module is the accountant: the
serving coalescer's ``on_account`` hook settles every coalesced batch
into a per-tenant ledger, attributing the batch's **analyzed** cost
(the dispatch layer's XLA cost-analysis FLOPs/bytes, metered over the
batch's inference by :func:`heat_tpu.core.dispatch.meter_costs`) and
its device time **pro rata by rows** — a tenant that contributed 3 of
a 12-row batch is billed a quarter of the batch, pad rows included, so
the tenant accounts always sum to the work actually dispatched.

Published as ``/tenantz`` (HTML + ``?format=json``) by the telemetry
server, rolled up across replicas by the fleet router's poller
(``/fleetz`` machinery, :func:`heat_tpu.telemetry.aggregate.
merge_tenant_accounts`), and included in the metrics dump bundle.

Totals are *derived* — :func:`tenantz_report` sums the tenant rows —
so "accounts sum to the total" holds by construction; the interesting
invariant (asserted by the QoS tests) is that the total matches the
fleet-wide work the observatory saw.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..analysis import tsan as _tsan
from . import metrics as _metrics

__all__ = [
    "note_batch",
    "render_tenantz_html",
    "reset",
    "tenantz_report",
]

#: tenant -> account; every field is a lifetime sum except ``class``
#: (last seen) and ``models`` (distinct models served)
_ACCOUNTS: Dict[str, dict] = {}
_STARTED_AT = time.time()
_LOCK = _tsan.register_lock("telemetry.tenants")

_ROWS_C = _metrics.counter("tenants.rows", "rows served across all tenants")
_BATCHES_C = _metrics.counter("tenants.batches", "coalesced batches settled")


def note_batch(
    model: str,
    parts: Sequence[Tuple[str, str, int]],
    flops: float = 0.0,
    bytes_accessed: float = 0.0,
    device_ms: float = 0.0,
) -> None:
    """Settle one coalesced batch into the tenant ledger.

    ``parts`` is ``[(tenant, cls, rows), ...]`` — the batch's true
    membership from the coalescer; ``flops``/``bytes_accessed`` are the
    batch's metered analyzed cost and ``device_ms`` its inference wall
    time.  Split pro rata by rows (the pad overhead lands on the riders
    proportionally), so summing tenant accounts reproduces the batch
    totals exactly up to float addition."""
    total_rows = sum(max(int(n), 0) for _, _, n in parts)
    if total_rows <= 0:
        return
    with _LOCK:
        _tsan.note_access("telemetry.tenants.accounts")
        for tenant, cls, n in parts:
            n = max(int(n), 0)
            if n == 0:
                continue
            share = n / total_rows
            acct = _ACCOUNTS.get(tenant)
            if acct is None:
                acct = _ACCOUNTS[tenant] = {
                    "class": cls,
                    "requests": 0,
                    "rows": 0,
                    "flops": 0.0,
                    "bytes_accessed": 0.0,
                    "device_ms": 0.0,
                    "batches": 0,
                    "models": set(),
                }
            acct["class"] = cls
            acct["requests"] += 1
            acct["rows"] += n
            acct["flops"] += flops * share
            acct["bytes_accessed"] += bytes_accessed * share
            acct["device_ms"] += device_ms * share
            acct["batches"] += 1
            acct["models"].add(model)
    _ROWS_C.inc(total_rows)
    _BATCHES_C.inc()


def reset() -> None:
    """Forget every account (test hook)."""
    with _LOCK:
        _tsan.note_access("telemetry.tenants.accounts")
        _ACCOUNTS.clear()


def tenantz_report(limit: Optional[int] = None) -> dict:
    """The /tenantz document: per-tenant accounts plus derived totals.

    ``{"timestamp", "uptime_s", "tenants": [...], "total": {...}}`` —
    tenants sorted by FLOPs descending (the cost question is "who is
    expensive", not alphabet), capped at ``limit`` with the remainder
    still counted in ``total`` (no silent truncation of the sum)."""
    with _LOCK:
        _tsan.note_access("telemetry.tenants.accounts", write=False)
        rows: List[dict] = [
            {
                "tenant": tenant,
                "class": a["class"],
                "requests": a["requests"],
                "rows": a["rows"],
                "flops": a["flops"],
                "bytes_accessed": a["bytes_accessed"],
                "device_ms": round(a["device_ms"], 3),
                "batches": a["batches"],
                "models": sorted(a["models"]),
            }
            for tenant, a in _ACCOUNTS.items()
        ]
    rows.sort(key=lambda r: (-r["flops"], r["tenant"]))
    total = {
        "tenants": len(rows),
        "requests": sum(r["requests"] for r in rows),
        "rows": sum(r["rows"] for r in rows),
        "flops": sum(r["flops"] for r in rows),
        "bytes_accessed": sum(r["bytes_accessed"] for r in rows),
        "device_ms": round(sum(r["device_ms"] for r in rows), 3),
    }
    if limit is not None:
        rows = rows[: max(int(limit), 0)]
    return {
        "timestamp": time.time(),
        "uptime_s": round(time.time() - _STARTED_AT, 1),
        "tenants": rows,
        "total": total,
    }


def _fmt_count(v: float) -> str:
    for unit in ("", "K", "M", "G", "T", "P"):
        if abs(v) < 1000.0:
            return f"{v:.1f}{unit}" if unit else f"{v:.0f}"
        v /= 1000.0
    return f"{v:.1f}E"


def render_tenantz_html() -> str:
    """Human-readable /tenantz (same data as the JSON form)."""
    rep = tenantz_report()
    rows = "".join(
        "<tr><td>{tenant}</td><td>{cls}</td><td align=right>{reqs}</td>"
        "<td align=right>{rows}</td><td align=right>{flops}</td>"
        "<td align=right>{byts}</td><td align=right>{dms:.1f}</td>"
        "<td>{models}</td></tr>".format(
            tenant=r["tenant"],
            cls=r["class"],
            reqs=r["requests"],
            rows=r["rows"],
            flops=_fmt_count(r["flops"]),
            byts=_fmt_count(r["bytes_accessed"]),
            dms=r["device_ms"],
            models=", ".join(r["models"]),
        )
        for r in rep["tenants"]
    )
    t = rep["total"]
    return (
        "<html><head><title>tenantz</title></head><body>"
        "<h1>Per-tenant cost accounts</h1>"
        f"<p>{t['tenants']} tenants · {t['rows']} rows · "
        f"{_fmt_count(t['flops'])} FLOPs · "
        f"{_fmt_count(t['bytes_accessed'])} bytes · "
        f"{t['device_ms']:.1f} device-ms · uptime {rep['uptime_s']}s</p>"
        "<table border=1 cellpadding=4><tr><th>tenant</th><th>class</th>"
        "<th>requests</th><th>rows</th><th>FLOPs</th><th>bytes</th>"
        "<th>device-ms</th><th>models</th></tr>"
        f"{rows}</table>"
        "<p><a href='/tenantz?format=json'>json</a> · "
        "accounts sum to the totals by construction (pro-rata split)</p>"
        "</body></html>"
    )


_metrics.register_dump_section("tenants", lambda: tenantz_report(limit=64))
