"""NumPy API extensions beyond the reference's checklist.

The reference's coverage_tables.md stops at 185 NumPy functions; everything
here widens the surface further so a NumPy user finds what they expect.
All functions follow the library's standard recipe: operate on the dense
global view (XLA/GSPMD distributes), wrap results with a
distribution-preserving split: preserved when the shape (or the split
axis's extent) survives, re-split along the largest axis for large
grown/stacked outputs (kron, tensordot, histogram2d), replicated only for
small results.  Mirrors the reference ops layer's keep-it-distributed
behavior (heat/core/_operations.py:22-229).
"""

from __future__ import annotations

import builtins
from typing import Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dndarray import DNDarray
from . import types

__all__ = [
    "amax",
    "amin",
    "array2string",
    "array_repr",
    "array_str",
    "asanyarray",
    "asarray_chkfinite",
    "ascontiguousarray",
    "asfarray",
    "asfortranarray",
    "base_repr",
    "binary_repr",
    "block",
    "correlate",
    "diagflat",
    "einsum_path",
    "format_float_positional",
    "format_float_scientific",
    "packbits",
    "unpackbits",
    "append",
    "argpartition",
    "argsort",
    "argwhere",
    "array_equal",
    "array_equiv",
    "array_split",
    "atleast_1d",
    "atleast_2d",
    "atleast_3d",
    "copyto",
    "corrcoef",
    "count_nonzero",
    "delete",
    "dstack",
    "einsum",
    "extract",
    "flatnonzero",
    "fmax",
    "fmin",
    "histogram2d",
    "histogram_bin_edges",
    "histogramdd",
    "inner",
    "insert",
    "iscomplexobj",
    "isrealobj",
    "isscalar",
    "kron",
    "lexsort",
    "mgrid",
    "nanargmax",
    "nanargmin",
    "nanmax",
    "nanmean",
    "nanmedian",
    "nanmin",
    "nanpercentile",
    "nanquantile",
    "nanstd",
    "nanvar",
    "ogrid",
    "partition",
    "ptp",
    "quantile",
    "resize",
    "rollaxis",
    "searchsorted",
    "sort_complex",
    "tensordot",
    "tri",
    "trim_zeros",
    "vander",
    "asmatrix",
    "bmat",
    "broadcast",
    "from_dlpack",
    "isfortran",
    "isnat",
    "mat",
    "require",
]


def _d(x):
    """Dense global view of a DNDarray / array-like."""
    if isinstance(x, DNDarray):
        return x._dense()
    return jnp.asarray(x)


def _ref(*xs) -> Optional[DNDarray]:
    for x in xs:
        if isinstance(x, DNDarray):
            return x
    return None


def _pick(*xs):
    """First DNDarray among xs, else the first operand (never uses ``or``,
    which would invoke DNDarray.__bool__)."""
    r = _ref(*xs)
    return r if r is not None else xs[0]


def _auto_split(result, ref) -> Optional[int]:
    """Distribution-preserving output split for a dense result derived from
    a split operand.

    Policy (the ops-layer behavior of the reference,
    heat/core/_operations.py:22-229, expressed as a placement rule):

    1. same shape -> same split (elementwise / shape-preserving);
    2. the split axis's extent survived at the same position -> same split
       (batch-style ops that reshape other dims);
    3. large grown/stacked outputs (kron, tensordot, histogram2d, outer)
       -> split along the largest axis, provided that axis has at least one
       row per device — the result stays distributed instead of being
       silently replicated on every device;
    4. small results -> replicated.
    """
    if ref.split is None:
        return None
    if result.shape == ref.shape:
        return ref.split
    if ref.split < result.ndim and result.shape[ref.split] == ref.shape[ref.split]:
        return ref.split
    if result.ndim:
        axis = int(np.argmax(result.shape))
        if result.shape[axis] >= ref.comm.size:
            return axis
    return None


def _wrap(result, *operands, split="auto"):
    """Wrap a dense result with a distribution-preserving split (see
    :func:`_auto_split`); pass ``split=`` explicitly to override."""
    ref = _ref(*operands)
    if ref is None:
        return DNDarray.from_dense(result, None, None, None)
    if split == "auto":
        split = _auto_split(result, ref)
    return DNDarray.from_dense(result, split, ref.device, ref.comm)


# ---------------------------------------------------------------- sorting


def argsort(a, axis: int = -1, descending: bool = False):
    """Indices that would sort ``a`` along ``axis``."""
    idx = jnp.argsort(_d(a), axis=axis, descending=descending)
    return _wrap(idx, a)


def partition(a, kth: int, axis: int = -1):
    """Partial sort: element ``kth`` in final position along ``axis``."""
    return _wrap(jnp.partition(_d(a), kth, axis=axis), a)


def argpartition(a, kth: int, axis: int = -1):
    return _wrap(jnp.argpartition(_d(a), kth, axis=axis), a)


def lexsort(keys, axis: int = -1):
    """Indirect sort with multiple keys (last key is primary)."""
    dense_keys = tuple(_d(k) for k in keys)
    return _wrap(jnp.lexsort(dense_keys, axis=axis), *list(keys))


def searchsorted(a, v, side: str = "left", sorter=None):
    """Insertion indices keeping ``a`` sorted."""
    ad = _d(a)
    if sorter is not None:
        ad = jnp.take(ad, _d(sorter))
    return _wrap(jnp.searchsorted(ad, _d(v), side=side), _pick(v, a))


def sort_complex(a):
    """Sort by real part, ties broken by imaginary part; complex output."""
    ad = _d(a)
    if not jnp.issubdtype(ad.dtype, jnp.complexfloating):
        ad = ad.astype(jnp.complex64)
    order = jnp.lexsort((jnp.imag(ad), jnp.real(ad)))
    return _wrap(jnp.take(ad, order), a)


# ------------------------------------------------------------- nan family


def _nan_reduce(fn, a, axis=None, keepdims=False, ddof=None):
    kwargs = {"axis": axis, "keepdims": keepdims}
    if ddof is not None:
        kwargs["ddof"] = ddof
    d = _d(a)
    if not types.heat_type_is_inexact(a.dtype) if isinstance(a, DNDarray) else not jnp.issubdtype(d.dtype, jnp.inexact):
        d = d.astype(jnp.float32)
    return _wrap(fn(d, **kwargs), a)


def nanmax(a, axis=None, keepdims=False):
    return _nan_reduce(jnp.nanmax, a, axis, keepdims)


def nanmin(a, axis=None, keepdims=False):
    return _nan_reduce(jnp.nanmin, a, axis, keepdims)


def nanmean(a, axis=None, keepdims=False):
    return _nan_reduce(jnp.nanmean, a, axis, keepdims)


def nanmedian(a, axis=None, keepdims=False):
    return _nan_reduce(jnp.nanmedian, a, axis, keepdims)


def nanstd(a, axis=None, ddof: int = 0, keepdims=False):
    return _nan_reduce(jnp.nanstd, a, axis, keepdims, ddof=ddof)


def nanvar(a, axis=None, ddof: int = 0, keepdims=False):
    return _nan_reduce(jnp.nanvar, a, axis, keepdims, ddof=ddof)


def nanargmax(a, axis=None):
    return _wrap(jnp.nanargmax(_d(a), axis=axis), a)


def nanargmin(a, axis=None):
    return _wrap(jnp.nanargmin(_d(a), axis=axis), a)


def quantile(a, q, axis=None, interpolation: str = "linear", keepdims=False):
    d = _d(a)
    if not jnp.issubdtype(d.dtype, jnp.inexact):
        d = d.astype(jnp.float32)
    return _wrap(jnp.quantile(d, jnp.asarray(q, d.dtype), axis=axis, method=interpolation, keepdims=keepdims), a)


def nanquantile(a, q, axis=None, interpolation: str = "linear", keepdims=False):
    d = _d(a)
    if not jnp.issubdtype(d.dtype, jnp.inexact):
        d = d.astype(jnp.float32)
    return _wrap(jnp.nanquantile(d, jnp.asarray(q, d.dtype), axis=axis, method=interpolation, keepdims=keepdims), a)


def nanpercentile(a, q, axis=None, interpolation: str = "linear", keepdims=False):
    d = _d(a)
    if not jnp.issubdtype(d.dtype, jnp.inexact):
        d = d.astype(jnp.float32)
    return _wrap(
        jnp.nanpercentile(d, jnp.asarray(q, d.dtype), axis=axis, method=interpolation, keepdims=keepdims),
        a,
    )


# ------------------------------------------------------------- statistics


def ptp(a, axis=None, keepdims=False):
    """Peak-to-peak (max - min)."""
    return _wrap(jnp.ptp(_d(a), axis=axis, keepdims=keepdims), a)


def corrcoef(x, y=None, rowvar: bool = True):
    xd = _d(x)
    if not jnp.issubdtype(xd.dtype, jnp.inexact):
        xd = xd.astype(jnp.float32)
    yd = None if y is None else _d(y)
    if yd is not None and not jnp.issubdtype(yd.dtype, jnp.inexact):
        yd = yd.astype(jnp.float32)
    return _wrap(jnp.corrcoef(xd, yd, rowvar=rowvar), x)


def histogram2d(x, y, bins=10, range=None, density=None, weights=None):
    h, xe, ye = jnp.histogram2d(_d(x), _d(y), bins=bins, range=range, density=density, weights=None if weights is None else _d(weights))
    return _wrap(h, x), _wrap(xe, x), _wrap(ye, x)


def histogramdd(sample, bins=10, range=None, density=None, weights=None):
    h, edges = jnp.histogramdd(_d(sample), bins=bins, range=range, density=density, weights=None if weights is None else _d(weights))
    return _wrap(h, sample), [_wrap(e, sample) for e in edges]


def histogram_bin_edges(a, bins=10, range=None, weights=None):
    return _wrap(jnp.histogram_bin_edges(_d(a), bins=bins, range=range, weights=weights), a)


def count_nonzero(a, axis=None, keepdims=False):
    return _wrap(jnp.count_nonzero(_d(a), axis=axis, keepdims=keepdims), a)


# ------------------------------------------------------------ manipulations


def append(arr, values, axis=None):
    return _wrap(jnp.append(_d(arr), _d(values), axis=axis), _pick(arr, values))


def _index_obj(obj):
    """numpy-compatible index argument: scalars and slices pass through,
    sequences/DNDarrays become arrays (jnp rejects bare lists)."""
    if isinstance(obj, DNDarray):
        return _d(obj)
    if isinstance(obj, (list, tuple, np.ndarray)):
        arr = np.asarray(obj)
        if arr.size == 0:  # numpy treats [] as an empty INDEX list
            arr = arr.astype(np.intp)
        return jnp.asarray(arr)
    return obj


def delete(arr, obj, axis=None):
    return _wrap(jnp.delete(_d(arr), _index_obj(obj), axis=axis), arr)


def insert(arr, obj, values, axis=None):
    return _wrap(jnp.insert(_d(arr), _index_obj(obj), _d(values), axis=axis), arr)


def resize(a, new_shape):
    return _wrap(jnp.resize(_d(a), new_shape), a)


def rollaxis(a, axis: int, start: int = 0):
    return _wrap(jnp.rollaxis(_d(a), axis, start), a)


def trim_zeros(filt, trim: str = "fb"):
    # data-dependent output shape: host-side trim (eager semantics)
    arr = np.asarray(filt.numpy() if isinstance(filt, DNDarray) else filt)
    trimmed = np.trim_zeros(arr, trim)
    return _wrap(jnp.asarray(trimmed), filt)


def array_split(ary, indices_or_sections, axis: int = 0):
    parts = jnp.array_split(_d(ary), indices_or_sections, axis=axis)
    ref = _ref(ary)
    return [_wrap(p, ary) for p in parts]


def dstack(tup):
    return _wrap(jnp.dstack([_d(t) for t in tup]), *list(tup))


def atleast_1d(*arys):
    out = [_wrap(jnp.atleast_1d(_d(a)), a) for a in arys]
    return out[0] if len(out) == 1 else out


def atleast_2d(*arys):
    out = [_wrap(jnp.atleast_2d(_d(a)), a) for a in arys]
    return out[0] if len(out) == 1 else out


def atleast_3d(*arys):
    out = [_wrap(jnp.atleast_3d(_d(a)), a) for a in arys]
    return out[0] if len(out) == 1 else out


def copyto(dst, src, where=True):
    """Copy ``src`` into ``dst`` in place (broadcasting, optional mask)."""
    if not isinstance(dst, DNDarray):
        raise TypeError("copyto destination must be a DNDarray")
    sd = jnp.broadcast_to(_d(src), dst.shape).astype(dst.dtype.jax_type())
    wd = where if isinstance(where, bool) else jnp.broadcast_to(_d(where), dst.shape)
    new = jnp.where(wd, sd, dst._dense()) if wd is not True else sd
    dst._replace_local(new)


# ---------------------------------------------------------------- indexing


def argwhere(a):
    return _wrap(jnp.argwhere(_d(a)), a)


def flatnonzero(a):
    return _wrap(jnp.flatnonzero(_d(a)), a)


def extract(condition, arr):
    return _wrap(jnp.extract(_d(condition), _d(arr)), _pick(arr, condition))


# --------------------------------------------------------------- predicates


def isscalar(element) -> bool:
    if isinstance(element, DNDarray):
        return False
    return bool(np.isscalar(element))


def iscomplexobj(x) -> bool:
    if isinstance(x, DNDarray):
        return types.heat_type_is_complexfloating(x.dtype)
    return bool(np.iscomplexobj(x))


def isrealobj(x) -> bool:
    return not iscomplexobj(x)


# --------------------------------------------------------- elementwise pair


def fmax(x1, x2):
    """Elementwise maximum ignoring NaNs."""
    return _wrap(jnp.fmax(_d(x1), _d(x2)), _pick(x1, x2))


def fmin(x1, x2):
    return _wrap(jnp.fmin(_d(x1), _d(x2)), _pick(x1, x2))


# ------------------------------------------------------------------ linalg


def inner(a, b):
    return _wrap(jnp.inner(_d(a), _d(b)), _pick(a, b))


def tensordot(a, b, axes=2):
    return _wrap(jnp.tensordot(_d(a), _d(b), axes=axes), _pick(a, b))


def kron(a, b):
    return _wrap(jnp.kron(_d(a), _d(b)), _pick(a, b))


# ---------------------------------------------------------------- factories


def tri(N: int, M: Optional[int] = None, k: int = 0, dtype=None, split=None, device=None, comm=None):
    d = types.canonical_heat_type(dtype or "float32").jax_type()
    return DNDarray.from_dense(jnp.tri(N, M, k, dtype=d), split, device, comm)


def vander(x, N: Optional[int] = None, increasing: bool = False):
    return _wrap(jnp.vander(_d(x), N=N, increasing=increasing), x)


def einsum(subscripts: str, *operands, precision=None):
    """Einstein summation over DNDarray operands (jnp.einsum under GSPMD —
    the collective-matmul path the reference hand-writes per case)."""
    dense_ops = [_d(o) for o in operands]
    out = jnp.einsum(subscripts, *dense_ops, precision=precision)
    return _wrap(out, *list(operands))


def array_equal(a1, a2) -> bool:
    """True when shapes and all elements match."""
    d1, d2 = _d(a1), _d(a2)
    if d1.shape != d2.shape:
        return False
    return bool(jnp.array_equal(d1, d2))


def array_equiv(a1, a2) -> bool:
    """True when broadcast-compatible and all elements match."""
    return bool(jnp.array_equiv(_d(a1), _d(a2)))


# -------------------------------------------------- second extension batch


def amax(a, axis=None, keepdims=False):
    """Alias of max (NumPy parity)."""
    from . import statistics

    return statistics.max(a, axis=axis, keepdims=keepdims)


def amin(a, axis=None, keepdims=False):
    from . import statistics

    return statistics.min(a, axis=axis, keepdims=keepdims)


def diagflat(v, k: int = 0):
    """2-D array with the flattened input on the k-th diagonal."""
    return _wrap(jnp.diagflat(_d(v), k=k), v)


def correlate(a, v, mode: str = "valid"):
    """1-D cross-correlation (np.correlate semantics)."""
    return _wrap(jnp.correlate(_d(a), _d(v), mode=mode), _pick(a, v))


def block(arrays):
    """Assemble an array from nested lists of blocks."""
    def conv(obj):
        if isinstance(obj, list):
            return [conv(o) for o in obj]
        return _d(obj)

    def first(obj):
        if isinstance(obj, list):
            for o in obj:
                r = first(o)
                if r is not None:
                    return r
            return None
        return obj if isinstance(obj, DNDarray) else None

    ref = first(arrays)
    out = jnp.block(conv(arrays))
    return _wrap(out, *( [ref] if ref is not None else [] ))


def packbits(a, axis=None, bitorder: str = "big"):
    return _wrap(jnp.packbits(_d(a), axis=axis, bitorder=bitorder), a)


def unpackbits(a, axis=None, count=None, bitorder: str = "big"):
    return _wrap(jnp.unpackbits(_d(a), axis=axis, count=count, bitorder=bitorder), a)


def base_repr(number: int, base: int = 2, padding: int = 0) -> str:
    return np.base_repr(int(number), base=base, padding=padding)


def binary_repr(num: int, width=None) -> str:
    return np.binary_repr(int(num), width=width)


def format_float_positional(x, *args, **kwargs) -> str:
    if isinstance(x, DNDarray):
        x = x.item()
    return np.format_float_positional(x, *args, **kwargs)


def format_float_scientific(x, *args, **kwargs) -> str:
    if isinstance(x, DNDarray):
        x = x.item()
    return np.format_float_scientific(x, *args, **kwargs)


def einsum_path(subscripts, *operands, optimize="greedy"):
    """Contraction-order plan (host-side np.einsum_path over shape dummies —
    no device data is transferred)."""
    dummies = [np.empty(_d(o).shape, dtype=np.dtype(_d(o).dtype)) for o in operands]
    return np.einsum_path(subscripts, *dummies, optimize=optimize)


def array2string(a, *args, **kwargs) -> str:
    return np.array2string(a.numpy() if isinstance(a, DNDarray) else np.asarray(a), *args, **kwargs)


def array_repr(arr, *args, **kwargs) -> str:
    return np.array_repr(arr.numpy() if isinstance(arr, DNDarray) else np.asarray(arr), *args, **kwargs)


def array_str(a, *args, **kwargs) -> str:
    return np.array_str(a.numpy() if isinstance(a, DNDarray) else np.asarray(a), *args, **kwargs)


def asfarray(a, dtype=None):
    """Convert to a floating-point DNDarray."""
    from . import factories, types as _t

    out = factories.asarray(a, dtype=dtype)
    if not _t.heat_type_is_inexact(out.dtype):
        out = out.astype(_t.float32)
    return out


def ascontiguousarray(a, dtype=None):
    """C-contiguity is XLA's native layout; an asarray alias here."""
    from . import factories

    return factories.asarray(a, dtype=dtype)


def asfortranarray(a, dtype=None):
    """Fortran order maps to the memory-layout machinery (memory.py);
    logically a dtype-honoring asarray."""
    from . import factories

    if isinstance(a, DNDarray):
        return a if dtype is None else a.astype(dtype)
    return factories.asarray(a, dtype=dtype, order="F")


def asanyarray(a, dtype=None):
    from . import factories

    return factories.asarray(a, dtype=dtype)


def asarray_chkfinite(a, dtype=None):
    from . import factories

    out = factories.asarray(a, dtype=dtype)
    if not bool(jnp.all(jnp.isfinite(_d(out)))):
        raise ValueError("array must not contain infs or NaNs")
    return out


class _GridProxy:
    """np.mgrid / np.ogrid analogs: index with slices, get DNDarrays."""

    def __init__(self, dense: bool):
        self._dense_grid = dense

    def __getitem__(self, key):
        src = jnp.mgrid if self._dense_grid else jnp.ogrid
        out = src[key]
        if isinstance(out, (list, tuple)):
            return [DNDarray.from_dense(o, None, None, None) for o in out]
        return DNDarray.from_dense(out, None, None, None)


mgrid = _GridProxy(True)
ogrid = _GridProxy(False)


# ----------------------------------------------- final parity stragglers


def from_dlpack(x):
    """Import an array through the DLPack protocol."""
    return DNDarray.from_dense(jnp.from_dlpack(x), None, None, None)


def isfortran(a) -> bool:
    """XLA arrays are row-major; Fortran order exists only as a logical
    layout tag (memory.py), so this is always False."""
    return False


def isnat(x):
    """NaT detection needs datetime dtypes, which the framework (like the
    reference) does not provide."""
    raise TypeError("isnat: datetime64/timedelta64 dtypes are not supported")


def require(a, dtype=None, requirements=None):
    """np.require analog: dtype conversion; layout requirement flags are
    no-ops on the XLA substrate (always C-contiguous, aligned, writeable
    copies)."""
    from . import factories

    out = factories.asarray(a, dtype=dtype)
    return out


class broadcast:
    """np.broadcast analog: the broadcast shape/metadata of the operands."""

    def __init__(self, *arrays):
        shapes = [tuple((_d(a)).shape) for a in arrays]
        self.shape = tuple(np.broadcast_shapes(*shapes))
        self.ndim = len(self.shape)
        self.nd = self.ndim
        self.size = int(np.prod(self.shape)) if self.shape else 1
        self.numiter = len(arrays)


def asmatrix(data, dtype=None):
    """Legacy matrix API: returns a 2-D DNDarray (no matrix subclass)."""
    from . import factories

    out = factories.asarray(data, dtype=dtype)
    d = _d(out)
    if d.ndim < 2:
        d = jnp.atleast_2d(d)
        return DNDarray.from_dense(d, None, out.device, out.comm)
    if d.ndim > 2:
        raise ValueError("matrix must be 2-dimensional")
    return out


mat = asmatrix


def bmat(obj):
    """Legacy block-matrix builder: 2-D `block` (string form unsupported)."""
    if isinstance(obj, str):
        raise NotImplementedError("string-form bmat is not supported; pass nested lists")
    return asmatrix(block(obj))
