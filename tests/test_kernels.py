"""Pallas kernel tests (core/kernels.py) — run through the Pallas
interpreter on the virtual CPU mesh, same code path as Mosaic on TPU."""

import numpy as np
import pytest

import jax.numpy as jnp


def _numpy_lloyd(x, c):
    d = ((x[:, None, :] - c[None, :, :]) ** 2).sum(-1)
    lbl = d.argmin(1)
    new = np.stack([x[lbl == j].mean(0) if (lbl == j).any() else c[j] for j in range(c.shape[0])])
    return new, d.min(1).sum()


@pytest.mark.parametrize(
    "n,f,k",
    [(1003, 16, 8), (517, 8, 5), (130, 4, 7), (999, 16, 12), (96, 128, 8), (64, 64, 2)],
)
def test_lloyd_kernel_single(ht, n, f, k):
    from heat_tpu.core import kernels

    assert kernels.lloyd_supported(f, k)
    rng = np.random.default_rng(0)
    x = rng.standard_normal((n, f)).astype(np.float32)
    c = rng.standard_normal((k, f)).astype(np.float32)
    npad = -(-n // 32) * 32
    xp = np.zeros((npad, f), np.float32)
    xp[:n] = x
    new, shift, inertia = kernels._lloyd_single(jnp.asarray(xp), jnp.asarray(c), n)
    ref, ref_inertia = _numpy_lloyd(x, c)
    np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5)
    np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)


def test_lloyd_kernel_sharded(ht):
    from heat_tpu.core import kernels

    ht.random.seed(5)
    x = ht.random.randn(1003, 16, split=0)  # uneven over 8 devices
    rng = np.random.default_rng(1)
    c = rng.standard_normal((8, 16)).astype(np.float32)
    new, shift, inertia = kernels.lloyd_update(x, jnp.asarray(c))
    ref, ref_inertia = _numpy_lloyd(x.numpy().astype(np.float32), c)
    np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5)
    np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)


def test_lloyd_unsupported_shapes(ht):
    from heat_tpu.core import kernels

    assert not kernels.lloyd_supported(17, 8)  # f does not divide 128
    assert not kernels.lloyd_supported(4, 30)  # packed space too wide
    assert not kernels.lloyd_supported(0, 8)


def test_kmeans_kernel_flag_end_to_end(ht, monkeypatch):
    """KMeans produces the same clustering through both step paths."""
    from heat_tpu.core import kernels

    ht.random.seed(7)
    x = ht.random.randn(500, 16, split=0)
    km_xla = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=30, random_state=0)
    km_xla.fit(x)
    monkeypatch.setattr(kernels, "LLOYD_KERNEL", True)
    km_pal = ht.cluster.KMeans(n_clusters=4, init="kmeans++", max_iter=30, random_state=0)
    km_pal.fit(x)
    np.testing.assert_allclose(
        km_xla.cluster_centers_.numpy(), km_pal.cluster_centers_.numpy(), atol=1e-4
    )


class TestLloydKernelProperties:
    """Property tests across the packed (f, k) space (VERDICT: the packed
    argmin/unscramble logic needs coverage across lane/slot combinations,
    including the lloyd_supported boundary)."""

    def test_supported_boundary_exhaustive(self):
        """lloyd_supported must be exactly 'f divides 128 and packed width
        r*next_pow2_widened(k) <= 512' — checked against first principles
        over the full small (f, k) grid."""
        from heat_tpu.core import kernels

        for f in list(range(1, 130)) + [256]:
            for k in range(1, 40):
                want = False
                if f > 0 and 128 % f == 0:
                    r = 128 // f
                    kp = 1
                    while kp < k:
                        kp *= 2
                    while r * kp < 128:
                        kp *= 2
                    want = r * kp <= 512
                assert kernels.lloyd_supported(f, k) == want, (f, k)

    @pytest.mark.parametrize(
        "f,k",
        [
            (128, 4),   # one point per lane row, kp == 4 (min widening)
            (128, 13),  # non-pow2 k, kp = 16
            (64, 2),    # r=2, kp widened 2 -> 64 to fill lanes
            (32, 8),    # r=4, kp widened to 32
            (16, 3),    # r=8, kp widened 4 -> 16
            (8, 9),     # r=16, kp=16: r*kp = 256 (multi-row packed space)
            (4, 16),    # r=32, kp=16: r*kp = 512 (exactly at the bound)
            (2, 2),     # r=64, minimum feature width
            (1, 4),     # r=128: scalar features
        ],
    )
    def test_packed_space_sweep(self, f, k):
        """Every lane/slot packing shape reproduces the numpy Lloyd update."""
        from heat_tpu.core import kernels

        assert kernels.lloyd_supported(f, k), (f, k)
        rng = np.random.default_rng(f * 100 + k)
        n = 517  # not a multiple of the 32-row padding quantum
        x = rng.standard_normal((n, f)).astype(np.float32)
        c = rng.standard_normal((k, f)).astype(np.float32)
        npad = -(-n // 32) * 32
        xp = np.zeros((npad, f), np.float32)
        xp[:n] = x
        new, shift, inertia = kernels._lloyd_single(jnp.asarray(xp), jnp.asarray(c), n)
        ref, ref_inertia = _numpy_lloyd(x, c)
        np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5, err_msg=f"f={f} k={k}")
        np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)

    def test_empty_cluster_keeps_center(self):
        """A cluster that captures no points must keep its center (the
        _postprocess where-guard), not collapse to NaN."""
        from heat_tpu.core import kernels

        x = np.zeros((64, 16), np.float32)  # every point at the origin
        c = np.stack([np.zeros(16), np.full(16, 100.0)]).astype(np.float32)
        new, shift, inertia = kernels._lloyd_single(jnp.asarray(x), jnp.asarray(c), 64)
        got = np.asarray(new)
        assert not np.isnan(got).any()
        np.testing.assert_allclose(got[1], c[1], atol=1e-6)  # empty cluster frozen
        np.testing.assert_allclose(got[0], 0.0, atol=1e-6)

    def test_padding_rows_excluded(self):
        """Padded rows beyond n_true must contribute nothing — compare a
        64-row buffer holding 40 true points against the direct 40-point
        numpy update, with garbage (not zeros) in the padding."""
        from heat_tpu.core import kernels

        rng = np.random.default_rng(9)
        n, f, k = 40, 16, 5
        x = rng.standard_normal((n, f)).astype(np.float32)
        c = rng.standard_normal((k, f)).astype(np.float32)
        xp = np.full((64, f), 1e6, np.float32)  # poison padding
        xp[:n] = x
        new, shift, inertia = kernels._lloyd_single(jnp.asarray(xp), jnp.asarray(c), n)
        ref, ref_inertia = _numpy_lloyd(x, c)
        np.testing.assert_allclose(np.asarray(new), ref, atol=5e-5)
        np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-4)

    def test_multi_tile_grid(self):
        """n above the tile quantum exercises the multi-step grid
        accumulation path."""
        from heat_tpu.core import kernels

        rng = np.random.default_rng(10)
        n, f, k = 40000, 64, 3  # r=2 -> g=2048 rows/tile -> ~10 tiles
        x = rng.standard_normal((n, f)).astype(np.float32)
        c = rng.standard_normal((k, f)).astype(np.float32)
        npad = -(-n // 32) * 32
        xp = np.zeros((npad, f), np.float32)
        xp[:n] = x
        new, shift, inertia = kernels._lloyd_single(jnp.asarray(xp), jnp.asarray(c), n)
        ref, ref_inertia = _numpy_lloyd(x, c)
        np.testing.assert_allclose(np.asarray(new), ref, atol=5e-4)
        np.testing.assert_allclose(float(inertia), ref_inertia, rtol=1e-3)


class TestSyrk:
    """gram_syrk: the one-read Gram kernel behind hsvd (r5)."""

    def test_values_with_remainder_tail(self, ht):
        from heat_tpu.core import kernels

        rng = np.random.default_rng(3)
        m = 2 * kernels._SYRK_TILE + 137  # exercises kernel + XLA tail
        x = rng.standard_normal((m, 128)).astype(np.float32)
        assert kernels.syrk_supported(m, 128, jnp.float32)
        g = np.asarray(kernels.gram_syrk(jnp.asarray(x)))
        want = x.astype(np.float64).T @ x.astype(np.float64)
        rel = np.linalg.norm(g - want) / np.linalg.norm(want)
        assert rel < 5e-5, rel  # compensated bf16x3 + Kahan accumulation
        np.testing.assert_allclose(g, g.T, rtol=1e-5, atol=1e-4)

    def test_unsupported_shapes(self, ht):
        from heat_tpu.core import kernels

        assert not kernels.syrk_supported(100, 128, jnp.float32)  # too short
        assert not kernels.syrk_supported(10000, 100, jnp.float32)  # lanes
        assert not kernels.syrk_supported(10000, 128, jnp.float64)  # dtype

    def test_hsvd_uses_it_and_matches(self, ht):
        import heat_tpu as htm
        from heat_tpu.core.linalg.svdtools import _hsvd_rank_jit

        rng = np.random.default_rng(4)
        m = 3 * 2048 + 11
        xh = rng.standard_normal((m, 64)).astype(np.float32)
        x = htm.array(xh, split=0)
        # public API on the multi-device mesh (syrk gated OFF there:
        # pallas_call is not GSPMD-partitionable)
        u, s, v, err = htm.linalg.hsvd_rank(x, 10, compute_sv=True)
        want_s = np.linalg.svd(xh, compute_uv=False)[:10]
        np.testing.assert_allclose(np.asarray(s.numpy()), want_s, rtol=1e-3)
        un = u.numpy()
        np.testing.assert_allclose(un.T @ un, np.eye(10), atol=1e-3)
        # the single-device jit WITH the kernel path matches the same truth
        u2, s2, v2, e2 = _hsvd_rank_jit(
            jnp.asarray(xh), 15, 1, 2, 10, True, "float32", syrk_ok=True
        )
        np.testing.assert_allclose(np.asarray(s2), want_s, rtol=1e-3)
