"""Spectral clustering, analog of heat/cluster/spectral.py (spectral.py:12).

Pipeline (matching the reference): similarity -> graph Laplacian ->
Lanczos eigen-embedding -> KMeans on the leading eigenvectors.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax.numpy as jnp

from ..core import types
from ..core.base import BaseEstimator, ClusteringMixin
from ..core.dndarray import DNDarray
from ..graph import Laplacian
from ..spatial import distance
from .kmeans import KMeans

__all__ = ["Spectral"]


def _make_similarity(metric: str, gamma: float):
    if metric == "rbf":
        sigma = float(jnp.sqrt(1.0 / (2.0 * gamma)))
        return lambda x: distance.rbf(x, sigma=sigma)
    if metric == "euclidean":
        # expanded form: one MXU matmul instead of an O(n^2 f) VPU reduce
        return lambda x: distance.cdist(x, quadratic_expansion=True)
    raise NotImplementedError(
        f"Other kernels than rbf and euclidean are currently not supported, got {metric!r}"
    )


@functools.lru_cache(maxsize=32)
def _embed_fn(metric: str, gamma: float, mode: str, boundary: str, threshold: float):
    """Fused spectral-embedding program, cached per Laplacian config so
    every Spectral instance with the same settings reuses one compilation
    (an instance-level cache would recompile on every fresh estimator)."""
    from ..core import fusion
    from ..core.linalg import solver

    laplacian = Laplacian(
        _make_similarity(metric, gamma), definition="norm_sym", mode=mode,
        threshold_key=boundary, threshold_value=threshold,
    )

    @fusion.jit
    def embed(xx, vv, m):
        L = laplacian.construct(xx)
        vd = vv._dense()
        vn = vd / jnp.linalg.norm(vd)
        V, T = solver.lanczos(L, m, v0=DNDarray.from_dense(vn, None, xx.device, xx.comm))
        evals, evecs_T = jnp.linalg.eigh(T._dense())
        # eigenvectors of L approx V @ eigenvectors(T)
        embedding = V._dense() @ evecs_T
        return evals, DNDarray.from_dense(embedding, xx.split, xx.device, xx.comm)

    return embed


class Spectral(BaseEstimator, ClusteringMixin):
    """Spectral clustering on a similarity graph (spectral.py:12)."""

    def __init__(
        self,
        n_clusters: Optional[int] = None,
        gamma: float = 1.0,
        metric: str = "rbf",
        laplacian: str = "fully_connected",
        threshold: float = 1.0,
        boundary: str = "upper",
        n_lanczos: int = 300,
        assign_labels: str = "kmeans",
        **params,
    ):
        self.n_clusters = n_clusters
        self.gamma = gamma
        self.metric = metric
        self.laplacian = laplacian
        self.threshold = threshold
        self.boundary = boundary
        self.n_lanczos = n_lanczos
        self.assign_labels = assign_labels

        # kept for API parity / introspection only — the fit path goes
        # through _embed_fn, which derives an IDENTICAL Laplacian from the
        # same (metric, gamma, mode, boundary, threshold) config so fused
        # compilations are shared across estimator instances
        self._laplacian = Laplacian(
            _make_similarity(metric, gamma), definition="norm_sym", mode=laplacian,
            threshold_key=boundary, threshold_value=threshold,
        )
        if assign_labels == "kmeans":
            self._cluster = KMeans(n_clusters=n_clusters, init="kmeans++") if n_clusters else KMeans(init="kmeans++")
        else:
            raise NotImplementedError(f"Other clustering methods than kmeans are currently not supported, got {assign_labels!r}")
        self._labels = None
        self._eigenvectors = None

    @property
    def labels_(self) -> DNDarray:
        return self._labels

    def _spectral_embedding(self, x: DNDarray):
        """Laplacian + Lanczos eigendecomposition (spectral.py:120+).

        The whole pipeline (similarity, Laplacian, Krylov loop, small
        eigh, embedding matmul) runs as ONE ht.jit program — dispatched
        eagerly it is ~20 ops, each a link round-trip on a tunneled chip.
        The Lanczos start vector is drawn OUTSIDE the trace so the library
        RNG stream advances per fit instead of being baked into the cache.
        """
        from ..core import random as ht_random

        n = x.shape[0]
        m = min(self.n_lanczos, n)
        v0 = ht_random.randn(n, comm=x.comm)
        embed = _embed_fn(
            self.metric, float(self.gamma), self.laplacian, self.boundary, float(self.threshold)
        )
        return embed(x, v0, m)

    def fit(self, x: DNDarray) -> "Spectral":
        """Embed and cluster (spectral.py:172)."""
        if not isinstance(x, DNDarray):
            raise ValueError(f"input needs to be a DNDarray, but was {type(x)}")
        if x.ndim != 2:
            raise ValueError(f"input needs to be 2D, but was {x.ndim}D")
        evals, evecs = self._spectral_embedding(x)

        if self.n_clusters is None:
            # eigengap heuristic (spectral.py:190)
            diffs = jnp.diff(evals)
            self.n_clusters = int(jnp.argmax(diffs[: min(50, diffs.shape[0])])) + 1
            self._cluster.n_clusters = self.n_clusters

        components = DNDarray.from_dense(
            evecs._dense()[:, : self.n_clusters], x.split, x.device, x.comm
        )
        self._cluster.fit(components)
        self._labels = self._cluster.labels_
        self._eigenvectors = evecs
        return self

    def predict(self, x: DNDarray) -> DNDarray:
        """Labels for the fitted data (spectral.py:230; like the reference,
        prediction is only defined on the training data)."""
        return self._labels
