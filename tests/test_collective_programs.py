"""Collective-level tests — the analog of the reference's
test_communication.py (2,494 LoC): the explicit collective wrappers and
the shard_map programs built on them (halo ring, PSRS exchange, pencil
all_to_all, ring cdist, distributed factorizations) exercised DIRECTLY
on the 8-device mesh, not only through the ops layer.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

import heat_tpu as ht
from heat_tpu.core._compat import shard_map as _compat_shard_map


@pytest.fixture(scope="module")
def comm():
    return ht.get_comm()


def _smap(comm, body, n_in=1, out=None):
    spec = P(comm.axis_name)
    return jax.jit(
        _compat_shard_map(
            body, mesh=comm.mesh, in_specs=(spec,) * n_in,
            out_specs=out if out is not None else spec,
        )
    )


class TestCollectiveWrappers:
    def test_psum(self, comm):
        p = comm.size
        x = jnp.arange(p, dtype=jnp.float32)
        got = _smap(comm, lambda v: comm.psum(v))(x)
        np.testing.assert_allclose(np.asarray(got), np.full(p, np.arange(p).sum()))

    def test_pmax_pmin(self, comm):
        p = comm.size
        x = jnp.arange(p, dtype=jnp.float32) * jnp.where(jnp.arange(p) % 2 == 0, 1.0, -1.0)
        gmax = _smap(comm, lambda v: comm.pmax(v))(x)
        gmin = _smap(comm, lambda v: comm.pmin(v))(x)
        assert float(gmax[0]) == float(np.max(np.asarray(x)))
        assert float(gmin[0]) == float(np.min(np.asarray(x)))

    def test_all_gather_tiled(self, comm):
        p = comm.size
        x = jnp.arange(2 * p, dtype=jnp.float32)  # 2 rows per shard
        got = _smap(comm, lambda v: comm.all_gather(v))(x)
        # every shard holds the full vector after the gather
        assert got.shape == (p * 2 * p,)
        np.testing.assert_allclose(np.asarray(got)[: 2 * p], np.arange(2 * p))

    def test_all_to_all_roundtrip(self, comm):
        p = comm.size
        x = jnp.arange(p * p, dtype=jnp.float32).reshape(p * p)

        def body(v):  # (p,) per shard
            t = comm.all_to_all(v.reshape(p, 1), split_axis=0, concat_axis=1)
            return comm.all_to_all(t, split_axis=1, concat_axis=0).reshape(p)

        got = _smap(comm, body)(x)
        np.testing.assert_allclose(np.asarray(got), np.asarray(x))

    def test_ppermute_ring_shift(self, comm):
        p = comm.size
        x = jnp.arange(p, dtype=jnp.float32)
        got = _smap(comm, lambda v: comm.ring_shift(v, 1))(x)
        np.testing.assert_allclose(np.asarray(got), np.roll(np.arange(p), 1))
        got2 = _smap(comm, lambda v: comm.ring_shift(v, -2))(x)
        np.testing.assert_allclose(np.asarray(got2), np.roll(np.arange(p), -2))

    def test_axis_index(self, comm):
        p = comm.size
        got = _smap(comm, lambda v: v + comm.axis_index().astype(jnp.float32))(
            jnp.zeros(p, jnp.float32)
        )
        np.testing.assert_allclose(np.asarray(got), np.arange(p))

    def test_subcomm_split(self, comm):
        sub = comm.split(list(range(comm.size // 2)))
        assert sub.size == comm.size // 2
        a = ht.arange(10, split=0, comm=sub)
        assert float(a.sum()) == 45.0

    def test_lshape_map_edges(self, comm):
        p = comm.size
        # extent < size: high devices empty
        m = comm.lshape_map((3,), 0)
        assert m[:, 0].sum() == 3 and (m[3:, 0] == 0).all()
        # extent 0
        z = comm.lshape_map((0, 4), 0)
        assert z[:, 0].sum() == 0
        # divisible
        d = comm.lshape_map((2 * p,), 0)
        assert (d[:, 0] == 2).all()


class TestHaloProgram:
    def test_halo_exchange_ring(self, comm):
        from heat_tpu.parallel.halo import halo_exchange

        p = comm.size
        x = jnp.arange(3 * p, dtype=jnp.float32)

        def body(v):  # (3,) per shard
            prev, nxt = halo_exchange(comm, v, 1)
            return jnp.concatenate([prev, v, nxt])

        spec = P(comm.axis_name)
        got = jax.jit(
            _compat_shard_map(body, mesh=comm.mesh, in_specs=(spec,), out_specs=spec)
        )(x)
        blocks = np.asarray(got).reshape(p, 5)
        for r in range(p):
            want_prev = 3 * r - 1 if r > 0 else 0.0
            want_next = 3 * (r + 1) if r < p - 1 else 0.0
            assert blocks[r, 0] == want_prev
            np.testing.assert_allclose(blocks[r, 1:4], np.arange(3 * r, 3 * r + 3))
            assert blocks[r, 4] == want_next

    def test_dndarray_halo_matches_reference_semantics(self, comm):
        x = np.arange(4 * comm.size, dtype=np.float32).reshape(-1, 1)
        a = ht.array(x, split=0)
        a.get_halo(2)
        # single-controller: halos of the local (= global) block are edges
        assert a.halo_prev is None or a.halo_prev.shape[0] == 2


class TestProgramHLOs:
    """The shard_map programs move data with the intended collectives."""

    def _text(self, fn, *args):
        return fn.lower(*args).compile().as_text()

    def test_ring_cdist_uses_ppermute_not_gather(self, comm):
        from heat_tpu.spatial import distance as dist_mod

        p = comm.size
        bn = bm = 2  # per-device block rows
        f = 4
        fn = dist_mod._ring_cdist_fn(comm, "euclidean", False, bn, bm, f, "float32")
        shp = jax.ShapeDtypeStruct((p * bn, f), np.float32)
        txt = self._text(fn, shp, shp)
        assert "collective-permute" in txt
        assert "all-gather" not in txt

    def test_pencil_uses_all_to_all(self, comm):
        import importlib

        fft_mod = importlib.import_module("heat_tpu.fft.fft")
        fn = fft_mod._pencil_planar_kind_fn(comm, "fft", 0, 1, 16, None, 2, None, True)
        shp = jax.ShapeDtypeStruct((comm.padded_extent(16), comm.size), np.float32)
        txt = self._text(fn, shp, shp)
        assert "all-to-all" in txt and "all-gather" not in txt

    def test_psrs_collective_budget(self, comm):
        """PSRS: exactly two big all_to_all exchange pairs, no array gather."""
        from heat_tpu.core import sample_sort as ss

        n = 1 << 15
        b = comm.padded_extent(n) // comm.size
        fn = ss._psrs_fn(comm, n, b, (), "float32", False)
        txt = self._text(fn, jax.ShapeDtypeStruct((comm.padded_extent(n),), np.float32))
        assert txt.count("all-to-all") >= 2
        for m in __import__("re").finditer(r"=\s*\(?[a-z0-9]+\[([0-9,]*)\][^)]*\)?\s*all-gather", txt):
            count = int(np.prod([int(d) for d in m.group(1).split(",") if d]))
            assert count <= max(comm.size**2 * 4, 1024)

    def test_sparse_csc_spmm_uses_reduce_scatter(self, comm):
        """The CSC contraction meets in a psum_scatter, not a gather of X."""
        from heat_tpu.sparse import _planes as pl

        p = comm.size
        fn = pl._spmm_comp_inner_prog(comm, p, 4, 2, 2 * p, 3, True)
        ishp = jax.ShapeDtypeStruct((p * 4,), np.int32)
        vshp = jax.ShapeDtypeStruct((p * 4,), np.float32)
        xshp = jax.ShapeDtypeStruct((2 * p, 3), np.float32)
        txt = self._text(fn, ishp, ishp, vshp, xshp)
        assert "reduce-scatter" in txt or "all-reduce" in txt
        assert "all-gather" not in txt


class TestHierarchical:
    def test_two_level_axes(self):
        import jax
        import pytest

        from heat_tpu.parallel.comm import HierarchicalCommunication

        n = jax.device_count()
        if n % 2:  # a 2-level grid needs an even device count (mesh-3 CI lane)
            pytest.skip("hierarchical grid needs an even device count")
        h = HierarchicalCommunication(grid=(2, n // 2))
        assert h.size == n
        a = ht.arange(16, split=0, comm=h)
        assert float(a.sum()) == 120.0
