"""ML-layer tests (reference: heat/cluster/tests, heat/decomposition/tests,
heat/preprocessing/tests, ...)."""

import numpy as np
import pytest

import heat_tpu as ht


@pytest.fixture
def blobs():
    rng = np.random.default_rng(0)
    c = np.array([[0.0, 0.0], [6.0, 6.0], [0.0, 7.0]], dtype=np.float32)
    pts = np.concatenate([rng.normal(c[i], 0.4, size=(40, 2)) for i in range(3)]).astype(np.float32)
    labels = np.repeat(np.arange(3), 40)
    perm = rng.permutation(len(pts))
    return pts[perm], labels[perm]


def _cluster_accuracy(true, pred, k=3):
    # best label matching accuracy
    from itertools import permutations

    best = 0.0
    for p in permutations(range(k)):
        mapped = np.array([p[int(t)] for t in true])
        best = max(best, float(np.mean(mapped == pred)))
    return best


def test_cdist_rbf():
    rng = np.random.default_rng(1)
    x = rng.standard_normal((10, 3)).astype(np.float32)
    y = rng.standard_normal((7, 3)).astype(np.float32)
    from scipy.spatial.distance import cdist as sp_cdist

    d = ht.spatial.cdist(ht.array(x, split=0), ht.array(y))
    np.testing.assert_allclose(d.numpy(), sp_cdist(x, y), rtol=1e-4, atol=1e-4)
    m = ht.spatial.manhattan(ht.array(x, split=0), ht.array(y))
    np.testing.assert_allclose(m.numpy(), sp_cdist(x, y, "cityblock"), rtol=1e-4, atol=1e-4)
    k = ht.spatial.rbf(ht.array(x, split=0), sigma=2.0)
    expected = np.exp(-sp_cdist(x, x) ** 2 / 8.0)
    np.testing.assert_allclose(k.numpy(), expected, rtol=1e-4, atol=1e-4)


@pytest.mark.parametrize("Cls", ["KMeans", "KMedians", "KMedoids"])
def test_kcluster(blobs, Cls):
    pts, labels = blobs
    x = ht.array(pts, split=0)
    model = getattr(ht.cluster, Cls)(n_clusters=3, init="kmeans++" if Cls == "KMeans" else "random", random_state=42)
    model.fit(x)
    assert model.cluster_centers_.shape == (3, 2)
    pred = model.labels_.numpy()
    acc = _cluster_accuracy(labels, pred)
    assert acc > 0.9, f"{Cls} accuracy {acc}"
    # predict on the same data matches labels_
    np.testing.assert_array_equal(model.predict(x).numpy(), pred)


def test_batchparallel_kmeans(blobs):
    pts, labels = blobs
    x = ht.array(pts, split=0)
    model = ht.cluster.BatchParallelKMeans(n_clusters=3, random_state=1)
    model.fit(x)
    acc = _cluster_accuracy(labels, model.labels_.numpy())
    assert acc > 0.85, f"BatchParallelKMeans accuracy {acc}"


def test_spectral(blobs):
    pts, labels = blobs
    x = ht.array(pts, split=0)
    model = ht.cluster.Spectral(n_clusters=3, gamma=0.5, n_lanczos=30)
    model.fit(x)
    acc = _cluster_accuracy(labels, model.labels_.numpy())
    assert acc > 0.8, f"Spectral accuracy {acc}"


def test_knn(blobs):
    pts, labels = blobs
    x = ht.array(pts[:100], split=0)
    y = ht.array(labels[:100].astype(np.int32), split=0)
    clf = ht.classification.KNeighborsClassifier(n_neighbors=5)
    clf.fit(x, y)
    pred = clf.predict(ht.array(pts[100:], split=0)).numpy()
    assert np.mean(pred == labels[100:]) > 0.9


@pytest.mark.parametrize("solver", ["full", "hierarchical", "randomized"])
def test_pca(solver):
    rng = np.random.default_rng(3)
    basis = rng.standard_normal((3, 10)).astype(np.float32)
    coef = rng.standard_normal((200, 3)).astype(np.float32)
    data = (coef @ basis + 0.01 * rng.standard_normal((200, 10))).astype(np.float32)
    x = ht.array(data, split=0)
    pca = ht.decomposition.PCA(n_components=3, svd_solver=solver, random_state=0)
    t = pca.fit_transform(x)
    assert t.shape == (200, 3)
    rec = pca.inverse_transform(t)
    rel = np.linalg.norm(rec.numpy() - data) / np.linalg.norm(data)
    assert rel < 0.05, f"{solver} reconstruction rel err {rel}"
    assert pca.total_explained_variance_ratio_ > 0.95


def test_gaussian_nb(blobs):
    pts, labels = blobs
    x = ht.array(pts, split=0)
    y = ht.array(labels.astype(np.int32), split=0)
    nb = ht.naive_bayes.GaussianNB()
    nb.fit(x, y)
    pred = nb.predict(x).numpy()
    assert np.mean(pred == labels) > 0.95
    proba = nb.predict_proba(x).numpy()
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-4)
    # partial_fit in two halves approximates the single fit
    nb2 = ht.naive_bayes.GaussianNB()
    nb2.partial_fit(ht.array(pts[:60], split=0), ht.array(labels[:60].astype(np.int32)), classes=ht.array(np.arange(3, dtype=np.int32)))
    nb2.partial_fit(ht.array(pts[60:], split=0), ht.array(labels[60:].astype(np.int32)))
    assert np.mean(nb2.predict(x).numpy() == labels) > 0.95


def test_scalers():
    rng = np.random.default_rng(4)
    data = (rng.standard_normal((50, 4)) * np.array([1, 5, 0.1, 10]) + np.array([0, 3, -2, 7])).astype(np.float32)
    x = ht.array(data, split=0)

    s = ht.preprocessing.StandardScaler().fit(x)
    t = s.transform(x)
    np.testing.assert_allclose(t.numpy().mean(axis=0), 0.0, atol=1e-5)
    np.testing.assert_allclose(t.numpy().std(axis=0), 1.0, atol=1e-4)
    np.testing.assert_allclose(s.inverse_transform(t).numpy(), data, rtol=1e-4, atol=1e-4)

    mm = ht.preprocessing.MinMaxScaler().fit(x)
    t = mm.transform(x)
    np.testing.assert_allclose(t.numpy().min(axis=0), 0.0, atol=1e-6)
    np.testing.assert_allclose(t.numpy().max(axis=0), 1.0, atol=1e-6)
    np.testing.assert_allclose(mm.inverse_transform(t).numpy(), data, rtol=1e-4, atol=1e-4)

    nrm = ht.preprocessing.Normalizer().fit_transform(x)
    np.testing.assert_allclose(np.linalg.norm(nrm.numpy(), axis=1), 1.0, rtol=1e-5)

    ma = ht.preprocessing.MaxAbsScaler().fit(x)
    t = ma.transform(x)
    assert np.abs(t.numpy()).max() <= 1.0 + 1e-6

    rs = ht.preprocessing.RobustScaler().fit(x)
    t = rs.transform(x)
    np.testing.assert_allclose(np.median(t.numpy(), axis=0), 0.0, atol=1e-5)


def test_lasso():
    rng = np.random.default_rng(5)
    X = rng.standard_normal((100, 5)).astype(np.float32)
    true_coef = np.array([2.0, 0.0, -3.0, 0.0, 1.0], dtype=np.float32)
    y = (X @ true_coef + 0.5 + 0.01 * rng.standard_normal(100)).astype(np.float32)
    model = ht.regression.Lasso(lam=0.01, max_iter=200)
    model.fit(ht.array(X, split=0), ht.array(y[:, None], split=0))
    pred = model.predict(ht.array(X, split=0))
    rmse = model.rmse(ht.array(y[:, None]), pred)
    assert rmse < 0.1, f"lasso rmse {rmse}"
    coefs = model.coef_.numpy().ravel()
    np.testing.assert_allclose(coefs, true_coef, atol=0.1)


def test_laplacian(blobs):
    pts, _ = blobs
    x = ht.array(pts[:20], split=0)
    lap = ht.graph.Laplacian(lambda z: ht.spatial.rbf(z, sigma=1.0), definition="norm_sym")
    L = lap.construct(x)
    Ln = L.numpy()
    np.testing.assert_allclose(np.diag(Ln), 1.0, atol=1e-5)
    np.testing.assert_allclose(Ln, Ln.T, atol=1e-5)
    ev = np.linalg.eigvalsh(Ln)
    assert ev.min() > -1e-4


def test_fft_suite():
    rng = np.random.default_rng(6)
    x = rng.standard_normal((8, 16)).astype(np.float32)
    for split in (None, 0, 1):
        a = ht.array(x, split=split)
        np.testing.assert_allclose(ht.fft.fft(a).numpy(), np.fft.fft(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(ht.fft.fft2(a).numpy(), np.fft.fft2(x), rtol=1e-4, atol=1e-3)
        np.testing.assert_allclose(ht.fft.rfft(a).numpy(), np.fft.rfft(x), rtol=1e-4, atol=1e-4)
        np.testing.assert_allclose(
            ht.fft.irfft(ht.fft.rfft(a)).numpy(), x, rtol=1e-4, atol=1e-5
        )
        np.testing.assert_allclose(ht.fft.fftshift(a).numpy(), np.fft.fftshift(x), rtol=1e-6)
    np.testing.assert_allclose(ht.fft.fftfreq(10, 0.1).numpy(), np.fft.fftfreq(10, 0.1).astype(np.float32), rtol=1e-6)
    # 3-D pencil FFT (BASELINE config 5 shape, tiny)
    vol = rng.standard_normal((8, 8, 8)).astype(np.float32)
    v = ht.array(vol, split=0)
    np.testing.assert_allclose(ht.fft.fftn(v).numpy(), np.fft.fftn(vol), rtol=1e-3, atol=1e-3)


def test_convolve():
    sig = np.array([0.0, 1.0, 2.0, 3.0, 2.0, 1.0, 0.0], dtype=np.float32)
    ker = np.array([1.0, 2.0, 1.0], dtype=np.float32)
    for mode in ("full", "same", "valid"):
        res = ht.convolve(ht.array(sig, split=0), ht.array(ker), mode=mode)
        np.testing.assert_allclose(res.numpy(), np.convolve(sig, ker, mode=mode), rtol=1e-5)


def test_vmap():
    x = np.arange(24.0, dtype=np.float32).reshape(6, 4)
    a = ht.array(x, split=0)
    f = ht.vmap(lambda row: row * 2.0)
    np.testing.assert_allclose(f(a).numpy(), x * 2)


def test_cdist_direct_vs_expanded():
    """quadratic_expansion=False is the exact broadcast-subtract path
    (reference distance.py:17-40); it must beat the expanded form on
    near-duplicate points where cancellation hurts."""
    import heat_tpu as ht
    import numpy as np
    from scipy.spatial.distance import cdist as sp_cdist

    rng = np.random.default_rng(3)
    base = rng.standard_normal((9, 5)) * 100.0
    x = base
    y = base + 1e-7  # near-duplicates: expanded form loses precision here
    direct = ht.spatial.cdist(ht.array(x, split=0), ht.array(y)).numpy()
    truth = sp_cdist(x, y)
    np.testing.assert_allclose(direct, truth, rtol=1e-5, atol=1e-9)
    exp = ht.spatial.cdist(
        ht.array(x, split=0), ht.array(y), quadratic_expansion=True
    ).numpy()
    assert np.abs(direct - truth).max() <= np.abs(exp - truth).max()


def test_gnb_noninteger_class_labels():
    """Float-valued class labels must stay distinct (an int32 cast used to
    collapse 1.2 and 1.7 into one class)."""
    X = ht.array(
        np.concatenate([np.full((10, 2), 0.0), np.full((10, 2), 10.0)]).astype(np.float32),
        split=0,
    )
    y = ht.array(np.array([1.2] * 10 + [1.7] * 10), split=0)
    g = ht.naive_bayes.GaussianNB().fit(X, y)
    assert g.classes_.shape == (2,)
    np.testing.assert_allclose(np.asarray(g.theta_.numpy())[:, 0], [0.0, 10.0], atol=1e-5)
    pred = np.asarray(g.predict(X).numpy())
    np.testing.assert_allclose(pred[:10], 1.2)
    np.testing.assert_allclose(pred[10:], 1.7)


class TestRingDistance:
    """Memory-bounded ppermute ring cdist + fused top-k (VERDICT r2 #3;
    reference heat/spatial/distance.py:209-747)."""

    def test_ring_matches_scipy(self, ht):
        from scipy.spatial.distance import cdist as sp_cdist

        rng = np.random.default_rng(0)
        p = ht.get_comm().size
        for n, m in ((4 * p, 3 * p), (4 * p + 1, 3 * p - 1), (17, 11)):
            x = rng.standard_normal((n, 5)).astype(np.float32)
            y = rng.standard_normal((m, 5)).astype(np.float32)
            X, Y = ht.array(x, split=0), ht.array(y, split=0)
            d = ht.spatial.cdist(X, Y)
            assert d.split == 0 and d.shape == (n, m)
            np.testing.assert_allclose(d.numpy(), sp_cdist(x, y), rtol=1e-4, atol=1e-4)
            np.testing.assert_allclose(
                ht.spatial.manhattan(X, Y).numpy(),
                sp_cdist(x, y, "cityblock"),
                rtol=1e-4,
                atol=1e-4,
            )

    def test_ring_symmetric_half_rounds(self, ht):
        from scipy.spatial.distance import cdist as sp_cdist

        rng = np.random.default_rng(1)
        p = ht.get_comm().size
        for n in (4 * p, 4 * p + 3):
            x = rng.standard_normal((n, 4)).astype(np.float32)
            X = ht.array(x, split=0)
            d = ht.spatial.cdist(X)  # Y=None: symmetry-exploiting path
            np.testing.assert_allclose(d.numpy(), sp_cdist(x, x), rtol=1e-4, atol=1e-4)
            r = ht.spatial.rbf(X, sigma=1.5)
            np.testing.assert_allclose(
                r.numpy(), np.exp(-sp_cdist(x, x) ** 2 / 4.5), rtol=1e-4, atol=1e-4
            )

    def test_ring_compiles_to_collective_permute(self, ht):
        from heat_tpu.spatial import distance as dist_mod

        p = ht.get_comm().size
        if p == 1:
            pytest.skip("needs a mesh")
        comm = ht.get_comm()
        fn = dist_mod._ring_cdist_fn(comm, "euclidean", True, 4, 4, 3, "float32")
        import jax.numpy as jnp

        txt = fn.lower(
            jnp.zeros((4 * p, 3), jnp.float32), jnp.zeros((4 * p, 3), jnp.float32)
        ).compile().as_text()
        assert "collective-permute" in txt
        assert "all-gather" not in txt  # one standing block, never the matrix

    def test_topk_fusion_matches_dense(self, ht):
        from scipy.spatial.distance import cdist as sp_cdist

        rng = np.random.default_rng(2)
        p = ht.get_comm().size
        n, m, k = 3 * p + 1, 5 * p - 2, 4
        x = rng.standard_normal((n, 6)).astype(np.float32)
        y = rng.standard_normal((m, 6)).astype(np.float32)
        vals, idx = ht.spatial.distance.cdist_topk(
            ht.array(x, split=0), ht.array(y, split=0), k
        )
        assert vals.shape == (n, k) and idx.shape == (n, k)
        truth = sp_cdist(x, y)
        order = np.sort(truth, axis=1)[:, :k]
        np.testing.assert_allclose(np.sort(vals.numpy(), axis=1), order, rtol=1e-3, atol=1e-3)
        # indices actually point at the k closest rows
        np.testing.assert_allclose(
            np.sort(np.take_along_axis(truth, idx.numpy(), axis=1), axis=1),
            order,
            rtol=1e-3,
            atol=1e-3,
        )

    def test_knn_predict_via_fused_ring(self, ht):
        rng = np.random.default_rng(3)
        p = ht.get_comm().size
        n = 8 * p
        x = np.concatenate([rng.normal(-3, 0.5, (n // 2, 3)), rng.normal(3, 0.5, (n // 2, 3))]).astype(np.float32)
        yl = np.concatenate([np.zeros(n // 2, np.int32), np.ones(n // 2, np.int32)])
        clf = ht.classification.KNeighborsClassifier(n_neighbors=3)
        clf.fit(ht.array(x, split=0), ht.array(yl, split=0))
        pred = clf.predict(ht.array(x, split=0)).numpy()
        assert (pred == yl).mean() == 1.0
