"""Naive Bayes estimators (analog of heat/naive_bayes)."""

from .gaussianNB import GaussianNB

__all__ = ["GaussianNB"]
