"""Matrix decomposition estimators (analog of heat/decomposition)."""

from .pca import PCA

__all__ = ["PCA"]
