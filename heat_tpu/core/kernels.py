"""Pallas TPU kernels for HBM-bound hot loops.

The framework's compute path is XLA-compiled jnp; these kernels exist only
where fusing beats what GSPMD/XLA emit.  First case: the KMeans Lloyd
iteration (the reference's cdist ring + argmin + one-hot-matmul update,
cluster/kmeans.py + spatial/distance.py:209).  XLA runs it as several
passes over the point set (distance matmul, argmin, one-hot segment sums)
plus (N, k) intermediates; the kernel below makes it ONE pass: each tile
of points is read once from HBM and its distances, assignments, centroid
partial sums, counts and inertia are all produced in VMEM.

Layout is the whole trick.  Points are tall-and-skinny (f ≈ 16 features),
and a (TILE, f) VMEM tile wastes 1 - f/128 of every lane row.  So the
kernel packs R = 128//f points into each 128-lane row — the (N, f) array
is *viewed* as (N/R, 128) with zero data movement — and computes all R
points' cluster distances with one MXU matmul against a block-diagonal
``kron(I_R, centers.T)`` matrix.  Per-point argmin is an in-group circular
lane-roll fold, and the centroid sums come out of a second packed matmul
whose (R*kp, 128) result is unscrambled outside the kernel.  Every lane
does real work and HBM traffic is exactly one read of x per iteration.

On non-TPU backends the same kernel runs through the Pallas interpreter,
so the test suite (virtual CPU mesh) exercises the identical code path.

**Measured outcome (v5e, 2^24 x 16 f32, k=8)**: the kernel is *correct*
but VPU-bound — the in-lane argmin folds cost ~25 full-tile VPU ops per
tile against a ~1.3 us/tile DMA floor, landing at ~73 ms/iteration, while
the trimmed two-pass XLA program (cluster/kmeans.py `_lloyd_update`)
runs at ~3.5 ms.  On this chip the VPU:HBM ratio leaves a budget of only
~5 VPU ops per element-lane, so single-pass fusion cannot pay for an
exact packed argmin.  The kernel is therefore OPT-IN
(``HEAT_TPU_LLOYD_KERNEL=1``): kept as the correctness-tested skeleton
for hardware with a different compute:bandwidth balance, and as the
honest record of why the default stays with XLA — exactly the
"Pallas only if profiling demands" policy the design docs call for.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.sharding import PartitionSpec as P

from ._compat import shard_map

try:  # pallas TPU backend (present in all jax>=0.4.30 installs)
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

__all__ = [
    "lloyd_update",
    "lloyd_supported",
    "LLOYD_KERNEL",
    "gram_syrk",
    "syrk_supported",
]

import os

#: opt-in switch for the fused kernel (see module docstring for why the
#: default is the XLA path)
LLOYD_KERNEL = os.environ.get("HEAT_TPU_LLOYD_KERNEL", "0") == "1"

_LANES = 128
_TILE_POINTS = 16384  # points per grid step; G = _TILE_POINTS // R lane rows


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _next_pow2(k: int) -> int:
    p = 1
    while p < k:
        p *= 2
    return p


def _slots_per_point(f: int, k: int) -> int:
    """Cluster slots per point: next_pow2(k), then widened until the packed
    cluster space r*kp is lane-aligned (Mosaic's dynamic_rotate rejects
    vectors narrower than one 128-lane row)."""
    r = _LANES // f
    kp = _next_pow2(k)
    while r * kp < _LANES:
        kp *= 2
    return kp


def lloyd_supported(f: int, k: int) -> bool:
    """Packed-kernel applicability: whole points per lane row (f | 128) and
    the packed cluster space within a small multiple of the lane width."""
    if f <= 0 or k <= 0 or _LANES % f != 0:
        return False
    r = _LANES // f
    return r * _slots_per_point(f, k) <= 512


def _roll_right(x: jax.Array, t) -> jax.Array:
    """Circular right-shift along lanes: out[l] = x[l - t] (t may be traced)."""
    if _interpret():
        return jnp.roll(x, t, 1)
    return pltpu.roll(x, t, 1)


def _group_shift(x: jax.Array, t, kp: int, slot: jax.Array) -> jax.Array:
    """out[l] = x[group(l)*kp + (slot(l)+t) % kp] — circular shift inside
    each kp-lane group, built from two whole-row rolls and a select.
    ``t`` may be a traced int in [1, kp)."""
    cols = x.shape[1]
    left = _roll_right(x, cols - t)  # out[l] = x[l + t]
    right = _roll_right(x, kp - t)  # out[l] = x[l - (kp - t)]
    return jnp.where(slot < kp - t, left, right)


def _lloyd_kernel(f: int, kp: int, nt_ref, x_ref, ck_ref, c2_ref, accs_ref, accc_ref, acci_ref):
    """One packed tile of the fused Lloyd iteration.

    R = 128//f points per lane row; G lane rows per tile.  Inputs:
    x_ref (G, 128) — R points' features per row; ck_ref (128, R*kp) —
    kron(I_R, centers.T), zero-padded from k to kp columns per point slot;
    c2_ref (1, R*kp) — |c_j|^2 per slot, +inf in pad slots.  Outputs
    (accumulated over the sequential grid): accs_ref (R*kp, 128) —
    onehot.T @ x, unscrambled outside; accc_ref (1, R*kp) — member counts
    per slot; acci_ref (1, 128) — inertia partials (sum |x|^2 over the
    x-lane space plus sum of per-point min distances over the slot space,
    both reduced to scalars outside).
    """
    r = _LANES // f
    g = x_ref.shape[0]
    i = pl.program_id(0)

    xb = x_ref[:].astype(jnp.float32)  # (G, 128)

    # zero out invalid points (shard padding / ragged final tile): lane l
    # holds a feature of point (base + lane//f)
    xlane = jax.lax.broadcasted_iota(jnp.int32, (g, _LANES), 1)
    xrow = (i * g + jax.lax.broadcasted_iota(jnp.int32, (g, _LANES), 0)) * r
    x_valid = (xrow + xlane // f) < nt_ref[0]
    xb = jnp.where(x_valid, xb, 0.0)

    # all R points x all k centers in one MXU pass; HIGHEST keeps f32
    # mantissas (the default bf16 passes would put ~2^-9 relative error on
    # the centroid sums).  The kernel is DMA-bound, the extra passes are free.
    xc = jnp.dot(
        xb, ck_ref[:], preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST
    )  # (G, R*kp)
    half = c2_ref[0][None, :] - 2.0 * xc  # |c|^2 - 2 x.c ; +inf in pad slots

    cols = r * kp
    slot = jax.lax.broadcasted_iota(jnp.int32, (g, cols), 1) % kp

    # per-point argmin with first-index tie-break, entirely in lane space:
    # fold the group minimum, then the smallest slot attaining it.
    # fori_loop (not an unrolled python loop) keeps the live-buffer count
    # O(1); unrolled folds blow the Mosaic VMEM stack at useful tile sizes.
    vmin = jax.lax.fori_loop(
        1, kp, lambda t, vm: jnp.minimum(vm, _group_shift(half, t, kp, slot)), half
    )
    jsel = jnp.where(half == vmin, slot, kp)
    jmin = jax.lax.fori_loop(
        1, kp, lambda t, jm: jnp.minimum(jm, _group_shift(jsel, t, kp, slot)), jsel
    )

    # one-hot over valid points; slot column c belongs to point base+c//kp
    crow = (i * g + jax.lax.broadcasted_iota(jnp.int32, (g, cols), 0)) * r
    clane = jax.lax.broadcasted_iota(jnp.int32, (g, cols), 1)
    c_valid = (crow + clane // kp) < nt_ref[0]
    oh = ((slot == jmin) & c_valid).astype(jnp.float32)  # (G, R*kp)

    # inertia partials: sum|x|^2 (x already zeroed when invalid) plus the
    # per-point min half-distance, counted once per point at slot 0
    x2_part = jnp.sum(xb * xb, axis=0)  # (128,)
    v_part = jnp.sum(jnp.where((slot == 0) & c_valid, vmin, 0.0), axis=0)  # (cols,)

    @pl.when(i == 0)
    def _():
        accs_ref[:] = jnp.zeros_like(accs_ref)
        accc_ref[:] = jnp.zeros_like(accc_ref)
        acci_ref[:] = jnp.zeros_like(acci_ref)

    accs_ref[:] += jnp.dot(
        oh.T, xb, preferred_element_type=jnp.float32, precision=jax.lax.Precision.HIGHEST
    )
    accc_ref[0, :] += jnp.sum(oh, axis=0)
    acci_ref[0, :] += x2_part + _pad_lanes(v_part, _LANES)


def _pad_lanes(v: jax.Array, lanes: int) -> jax.Array:
    """Fold a (cols,) vector into (lanes,) by summing lane-width chunks
    (cols is a multiple or divisor of lanes by construction).  Static
    slicing only — lane->sublane reshapes don't lower well in Mosaic."""
    cols = v.shape[0]
    if cols == lanes:
        return v
    if cols > lanes:
        acc = v[:lanes]
        for i in range(1, cols // lanes):
            acc = acc + v[i * lanes : (i + 1) * lanes]
        return acc
    return jnp.pad(v, (0, lanes - cols))


def _build_operands(centers: jax.Array, f: int, k: int, kp: int):
    """Host-side constants: the block-diagonal kron matrix and slot |c|^2."""
    r = _LANES // f
    c32 = centers.astype(jnp.float32)
    ck = jnp.zeros((_LANES, r * kp), jnp.float32)
    for ri in range(r):
        ck = ck.at[ri * f : (ri + 1) * f, ri * kp : ri * kp + k].set(c32.T)
    c2 = jnp.sum(c32 * c32, axis=1)
    c2slot = jnp.full((r * kp,), jnp.inf, jnp.float32)
    for ri in range(r):
        c2slot = c2slot.at[ri * kp : ri * kp + k].set(c2)
    return ck, c2slot[None, :]


def _unscramble(accs, accc, acci, f: int, k: int, kp: int):
    """(R*kp, 128) packed sums -> (k, f) sums, (k,) counts, scalar inertia."""
    r = _LANES // f
    sums = jnp.zeros((k, f), jnp.float32)
    counts = jnp.zeros((k,), jnp.float32)
    for ri in range(r):
        sums = sums + accs[ri * kp : ri * kp + k, ri * f : (ri + 1) * f]
        counts = counts + accc[0, ri * kp : ri * kp + k]
    inertia = jnp.sum(acci)
    return sums, counts, inertia


def _lloyd_acc(xp: jax.Array, centers: jax.Array, n_true) -> tuple:
    """Fused pass over one device's rows.  ``n_true`` may be traced.
    Returns (sums (k,f), counts (k,), inertia scalar) as float32."""
    n, f = xp.shape
    k = centers.shape[0]
    kp = _slots_per_point(f, k)
    r = _LANES // f
    # tile G lane-rows: bounded in points AND in lane-rows (a (G, 128) f32
    # buffer is G*512 bytes and ~8 of them are live in the kernel)
    g = min(max(_TILE_POINTS // r, 8), 2048)

    rows_packed = n // r if n % r == 0 else n // r + 1
    xv = xp.reshape(n // r, _LANES) if n % r == 0 else None
    if xv is None:
        # pad to a whole number of packed rows (rare: shard sizes are
        # padded to mesh multiples well above R)
        pad = rows_packed * r - n
        xv = jnp.pad(xp, ((0, pad), (0, 0))).reshape(rows_packed, _LANES)

    ck, c2 = _build_operands(centers, f, k, kp)
    nt = jnp.asarray(n_true, jnp.int32).reshape(1)
    grid = (pl.cdiv(rows_packed, g),)
    kernel = functools.partial(_lloyd_kernel, f, kp)
    cols = r * kp
    out_shapes = (
        jax.ShapeDtypeStruct((cols, _LANES), jnp.float32),
        jax.ShapeDtypeStruct((1, cols), jnp.float32),
        jax.ShapeDtypeStruct((1, _LANES), jnp.float32),
    )
    in_specs = [
        pl.BlockSpec((g, _LANES), lambda i, *_: (i, 0)),
        pl.BlockSpec((_LANES, cols), lambda i, *_: (0, 0)),
        pl.BlockSpec((1, cols), lambda i, *_: (0, 0)),
    ]
    out_specs = (
        pl.BlockSpec((cols, _LANES), lambda i, *_: (0, 0)),
        pl.BlockSpec((1, cols), lambda i, *_: (0, 0)),
        pl.BlockSpec((1, _LANES), lambda i, *_: (0, 0)),
    )
    if pltpu is not None and not _interpret():
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=grid, in_specs=in_specs, out_specs=out_specs
        )
        accs, accc, acci = pl.pallas_call(kernel, out_shape=out_shapes, grid_spec=grid_spec)(
            nt, xv, ck, c2
        )
    else:
        accs, accc, acci = pl.pallas_call(
            kernel,
            out_shape=out_shapes,
            grid=grid,
            in_specs=[pl.BlockSpec((1,), lambda i, *_: (0,))] + in_specs,
            out_specs=out_specs,
            interpret=True,
        )(nt, xv, ck, c2)
    return _unscramble(accs, accc, acci, f, k, kp)


def _postprocess(sums, counts, inertia, centers):
    new = jnp.where(
        counts[:, None] > 0,
        sums / jnp.maximum(counts, 1.0)[:, None],
        centers.astype(jnp.float32),
    ).astype(centers.dtype)
    shift = jnp.sum((new.astype(jnp.float32) - centers.astype(jnp.float32)) ** 2)
    return new, shift, inertia


@functools.partial(jax.jit, static_argnames=("n_true",))
def _lloyd_single(xp, centers, n_true):
    sums, counts, inertia = _lloyd_acc(xp, centers, n_true)
    return _postprocess(sums, counts, inertia, centers)


@functools.cache
def _lloyd_sharded(mesh, axis_name: str, n_true: int):
    """Jitted multi-device step: per-shard fused pass, psum of the tiny
    (k, f+2)-sized accumulators, replicated postprocess."""

    def body(xs, c):
        rank = jax.lax.axis_index(axis_name)
        local_rows = xs.shape[0]
        nt_local = jnp.clip(n_true - rank * local_rows, 0, local_rows)
        sums, counts, inertia = _lloyd_acc(xs, c, nt_local)
        return jax.lax.psum((sums, counts, inertia), axis_name)

    @jax.jit
    def step(xp, centers):
        sums, counts, inertia = shard_map(
            body,
            mesh=mesh,
            in_specs=(P(axis_name), P()),
            out_specs=(P(), P(), P()),
            # pallas_call outputs don't carry vma metadata for the new
            # shard_map varying-axes check
            check_vma=False,
        )(xp, centers)
        return _postprocess(sums, counts, inertia, centers)

    return step


def lloyd_update(x, centers: jax.Array):
    """One fused Lloyd iteration on a DNDarray of points.

    Returns ``(new_centers, shift, inertia)``; does NOT compute labels (the
    fit loop only needs them after convergence — assignment stays a
    separate cheap pass in the caller).
    """
    xp = x.larray_padded
    if x.split == 0 and x.comm.size > 1:
        step = _lloyd_sharded(x.comm.mesh, x.comm.axis_name, x.shape[0])
        return step(xp, centers)
    return _lloyd_single(xp, centers, x.shape[0])


# ----------------------------------------------------------------------
# syrk: G = x.T @ x with ONE HBM read of x (hsvd's Gram pass).
#
# XLA lowers the Gram matmul as a generic dot whose lhs (x.T) and rhs (x)
# are independent operand streams — the r5 profile measured it at
# ~5.7 ms for (2^22, 128) f32 where one read of x at stream bandwidth is
# ~3.3 ms (no syrk/symmetric-rank-k optimization in the TPU backend).
# This kernel tiles x over rows, reads each (TILE, n) block once into
# VMEM, and accumulates blk.T @ blk into a VMEM-resident (n, n) output
# with explicit compensated bf16x3 passes (hi/lo split, three MXU dots:
# the HIGH policy's arithmetic, ~1e-6 relative on G — see
# linalg/svdtools._gram_precision for why that is enough for hsvd).
# ----------------------------------------------------------------------
_SYRK_TILE = 2048


def syrk_supported(m: int, n: int, dtype) -> bool:
    """f32 tall blocks with lane-aligned width; rows need no alignment
    (the caller splits off the row remainder)."""
    return (
        jnp.dtype(dtype) == jnp.float32
        and n % _LANES == 0
        and 0 < n <= 512
        and m >= _SYRK_TILE
    )


def _syrk_kernel(x_ref, o_ref, comp_ref):
    """Per-tile bf16x3 rank-k update with Kahan-compensated accumulation:
    a plain sequential f32 sum over the ~2k grid steps costs ~grid*eps
    (measured 1.5e-4 on G at 2^22 rows); the compensation buffer brings
    it back to ~1e-6 for free (VPU work against a DMA-bound kernel)."""
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        o_ref[...] = jnp.zeros_like(o_ref)
        comp_ref[...] = jnp.zeros_like(comp_ref)

    blk = x_ref[...]
    hi = blk.astype(jnp.bfloat16)
    lo = (blk - hi.astype(jnp.float32)).astype(jnp.bfloat16)
    dims = (((0,), (0,)), ((), ()))
    dot = lambda a, b: jax.lax.dot_general(
        a, b, dims, preferred_element_type=jnp.float32
    )
    # (hi+lo)^T (hi+lo) dropping the lo^T lo term (below f32 eps)
    contrib = dot(hi, hi) + dot(hi, lo) + dot(lo, hi)
    acc = o_ref[...]
    y = contrib - comp_ref[...]
    t = acc + y
    comp_ref[...] = (t - acc) - y
    o_ref[...] = t


def gram_syrk(x: jax.Array) -> jax.Array:
    """``x.T @ x`` for tall f32 ``x`` reading x once; the row remainder
    past the last full tile goes through a plain XLA dot and is added."""
    m, n = x.shape
    m0 = (m // _SYRK_TILE) * _SYRK_TILE
    if m0 == 0:  # public guard: short input is just the tail dot
        return jnp.matmul(x.T, x, precision=jax.lax.Precision.HIGH)
    head = x[:m0]
    grid = (m0 // _SYRK_TILE,)
    call = pl.pallas_call(
        _syrk_kernel,
        out_shape=jax.ShapeDtypeStruct((n, n), jnp.float32),
        grid=grid,
        in_specs=[pl.BlockSpec((_SYRK_TILE, n), lambda i: (i, 0))],
        out_specs=pl.BlockSpec((n, n), lambda i: (0, 0)),
        scratch_shapes=[pltpu.VMEM((n, n), jnp.float32)],
        interpret=_interpret(),
    )
    g = call(head)
    if m0 < m:
        tail = x[m0:]
        g = g + jnp.matmul(tail.T, tail, precision=jax.lax.Precision.HIGH)
    return g
