"""Type-system sweep (reference: test_types.py) and linalg basics sweep
(reference: test_basics.py, 2265 LoC) against numpy ground truth."""

import numpy as np
import pytest

import heat_tpu as ht


# ------------------------------------------------------------------ types


def test_promote_types_table():
    cases = [
        (ht.uint8, ht.uint8, ht.uint8),
        (ht.uint8, ht.int8, ht.int16),
        (ht.int32, ht.int64, ht.int64),
        # jnp promotion lattice by design (TPU-first: int64+f32 stays f32,
        # unlike numpy's value-safe f64 — see types.promote_types docstring)
        (ht.int64, ht.float32, ht.float32),
        (ht.float32, ht.float64, ht.float64),
        (ht.bool, ht.int8, ht.int8),
        (ht.float32, ht.complex64, ht.complex64),
        (ht.float64, ht.complex64, ht.complex128),
    ]
    for a, b, want in cases:
        assert ht.promote_types(a, b) == want, (a, b)
        assert ht.promote_types(b, a) == want


def test_can_cast_rules():
    assert ht.can_cast(ht.int8, ht.int16)
    # default mode is the reference's 'intuitive' (same_kind-like), so a
    # narrowing int cast passes by default but fails under 'safe'
    assert ht.can_cast(ht.int16, ht.int8)
    assert not ht.can_cast(ht.int16, ht.int8, casting="safe")
    assert ht.can_cast(ht.int16, ht.int8, casting="same_kind")
    assert not ht.can_cast(ht.float32, ht.int32, casting="same_kind")
    assert ht.can_cast(ht.float64, ht.float32, casting="same_kind")
    assert ht.can_cast(ht.float32, ht.complex64)


def test_result_type_and_heat_type_of():
    assert ht.result_type(ht.array([1]), ht.array([1.5])) in (ht.float32, ht.float64)
    assert ht.heat_type_of(np.float32(1.0)) == ht.float32
    assert ht.issubdtype(ht.float32, ht.floating)
    fi = ht.finfo(ht.float32)
    assert fi.eps > 0 and fi.max > 1e38
    ii = ht.iinfo(ht.int16)
    assert ii.max == 32767


# ----------------------------------------------------------------- linalg


@pytest.fixture(scope="module")
def sq():
    rng = np.random.default_rng(0)
    a = rng.standard_normal((6, 6))
    return a + 6 * np.eye(6)  # well-conditioned


@pytest.mark.parametrize("split", [None, 0, 1])
def test_det_inv(sq, split):
    a = ht.array(sq, split=split)
    np.testing.assert_allclose(float(ht.linalg.det(a)), np.linalg.det(sq), rtol=1e-8)
    np.testing.assert_allclose(ht.linalg.inv(a).numpy(), np.linalg.inv(sq), rtol=1e-8, atol=1e-10)


@pytest.mark.parametrize("split", [None, 0])
def test_norms(sq, split):
    a = ht.array(sq, split=split)
    np.testing.assert_allclose(float(ht.linalg.norm(a)), np.linalg.norm(sq), rtol=1e-10)
    np.testing.assert_allclose(
        float(ht.linalg.matrix_norm(a, ord=1)), np.linalg.norm(sq, 1), rtol=1e-10
    )
    v = ht.array(sq[0], split=split)
    np.testing.assert_allclose(
        float(ht.linalg.vector_norm(v, ord=3)), np.linalg.norm(sq[0], 3), rtol=1e-8
    )


def test_outer_vdot_vecdot_trace_cross(sq):
    u, w = sq[0], sq[1]
    hu, hw = ht.array(u, split=0), ht.array(w, split=0)
    np.testing.assert_allclose(ht.linalg.outer(hu, hw).numpy(), np.outer(u, w), rtol=1e-12)
    np.testing.assert_allclose(float(ht.linalg.vdot(hu, hw)), np.vdot(u, w), rtol=1e-12)
    np.testing.assert_allclose(float(ht.linalg.vecdot(hu, hw)), np.vecdot(u, w), rtol=1e-12)
    a = ht.array(sq, split=0)
    np.testing.assert_allclose(float(ht.linalg.trace(a)), np.trace(sq), rtol=1e-12)
    u3, w3 = ht.array(u[:3]), ht.array(w[:3])
    np.testing.assert_allclose(ht.cross(u3, w3).numpy(), np.cross(u[:3], w[:3]), rtol=1e-12)


def test_tril_triu_transpose(sq):
    for split in (None, 0, 1):
        a = ht.array(sq, split=split)
        np.testing.assert_allclose(ht.tril(a).numpy(), np.tril(sq))
        np.testing.assert_allclose(ht.triu(a, k=1).numpy(), np.triu(sq, 1))
        np.testing.assert_allclose(ht.linalg.transpose(a).numpy(), sq.T)


def test_solve_triangular(sq):
    # upper-triangular systems, matching the reference (solver.py:275)
    U = np.triu(sq)
    b = np.arange(6.0).reshape(6, 1)
    want = np.linalg.solve(U, b)
    got = ht.linalg.solve_triangular(ht.array(U, split=0), ht.array(b, split=0))
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-8, atol=1e-10)


def test_cg_matches_direct(sq):
    spd = sq @ sq.T + 6 * np.eye(6)
    b = np.arange(6.0)
    want = np.linalg.solve(spd, b)
    x0 = ht.zeros(6)
    got = ht.linalg.cg(ht.array(spd, split=0), ht.array(b, split=0), x0)
    np.testing.assert_allclose(got.numpy(), want, rtol=1e-6, atol=1e-8)


def test_matmul_batched_and_mixed_splits():
    rng = np.random.default_rng(1)
    A = rng.standard_normal((3, 5, 4))
    B = rng.standard_normal((3, 4, 6))
    for sa in (None, 0):
        for sb in (None, 0):
            got = ht.matmul(ht.array(A, split=sa), ht.array(B, split=sb))
            np.testing.assert_allclose(got.numpy(), A @ B, rtol=1e-10)
    # 2-D mixed splits incl. inner-split
    M = rng.standard_normal((7, 5))
    N = rng.standard_normal((5, 9))
    for sa in (None, 0, 1):
        for sb in (None, 0, 1):
            got = ht.matmul(ht.array(M, split=sa), ht.array(N, split=sb))
            np.testing.assert_allclose(got.numpy(), M @ N, rtol=1e-10, err_msg=f"{sa},{sb}")


# ----------------------------------------------------------------- random


def test_random_state_roundtrip():
    ht.random.seed(99)
    a = ht.random.rand(8, split=0).numpy()
    state = ht.random.get_state()
    b = ht.random.rand(8, split=0).numpy()
    ht.random.set_state(state)
    b2 = ht.random.rand(8, split=0).numpy()
    np.testing.assert_array_equal(b, b2)
    ht.random.seed(99)
    np.testing.assert_array_equal(ht.random.rand(8, split=0).numpy(), a)


def test_random_distributions_shapes_and_ranges():
    ht.random.seed(1)
    r = ht.random.randint(3, 9, size=(100,), split=0).numpy()
    assert r.min() >= 3 and r.max() < 9
    p = ht.random.permutation(10).numpy()
    assert sorted(p.tolist()) == list(range(10))
    rp = ht.random.randperm(10).numpy()
    assert sorted(rp.tolist()) == list(range(10))
    n = ht.random.normal(2.0, 0.5, (2000,), split=0).numpy()
    assert abs(n.mean() - 2.0) < 0.1
    s = ht.random.standard_normal((50,), split=0)
    assert s.shape == (50,)
    u = ht.random.random_sample((5, 5)).numpy()
    assert (u >= 0).all() and (u < 1).all()


# ----------------------------------------------------------------- signal


def test_convolve_distributed_kernel():
    # the reference broadcasts kernel chunks in turn when the kernel itself
    # is split (signal.py:267+)
    sig = np.arange(30.0)
    ker = np.array([0.25, 0.5, 1.0, 0.5, 0.25])
    a = ht.array(sig, split=0)
    v = ht.array(ker, split=0)  # split kernel
    for mode in ("full", "same", "valid"):
        np.testing.assert_allclose(
            ht.convolve(a, v, mode=mode).numpy(), np.convolve(sig, ker, mode=mode), rtol=1e-10
        )


# ---------------------------------------------------------------- printing


def test_printing_modes(capsys):
    a = ht.arange(10, split=0)
    print(a)
    out = capsys.readouterr().out
    assert "DNDarray" in out
    ht.local_printing()
    print(a)
    ht.global_printing()
    ht.print0("hello")
    out = capsys.readouterr().out
    assert "hello" in out
    orig = ht.get_printoptions()["precision"]
    try:
        ht.set_printoptions(precision=2)
        b = ht.array([1.23456789])
        s = str(b)
        assert "1.23456789" not in s
        ht.set_printoptions(precision=8)
        assert "1.2345679" in str(b)
    finally:
        ht.set_printoptions(precision=orig)
