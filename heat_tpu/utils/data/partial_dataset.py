"""Out-of-core HDF5 dataset, analog of heat/utils/data/partial_dataset.py.

The reference's ``PartialH5Dataset`` (partial_dataset.py:32) threads HDF5
chunk reads and overlaps load/convert with training via a custom loader
iterator (:224) fed by daemon threads running :func:`queue_thread`
(partial_dataset.py:20).  Here the same structure holds — a loader thread
reads the next HDF5 slab while the device executes the previous batch —
and JAX's asynchronous dispatch overlaps the host→device copy as well.
"""

from __future__ import annotations

import queue
import threading
from typing import Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.dndarray import DNDarray

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter", "queue_thread"]

try:
    import h5py

    _H5 = True
except ImportError:  # pragma: no cover
    _H5 = False


def queue_thread(q: "queue.Queue") -> None:
    """Worker loop for loader threads (partial_dataset.py:20): pop either a
    ``(func, *args)`` tuple or a bare callable off the queue, run it, and
    mark the item done.  ``None`` shuts the worker down."""
    while True:
        items = q.get()
        if items is None:
            q.task_done()
            return
        if isinstance(items, tuple):
            items[0](*items[1:])
        else:
            items()
        q.task_done()


class PartialH5Dataset:
    """Stream a large HDF5 dataset in windows (partial_dataset.py:32)."""

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names: Optional[List[str]] = None,
        initial_load: int = 7000,
        load_length: int = 1000,
        use_gpu: bool = True,
        np_buffer: bool = True,
        np_buffer_dataset_names: Optional[List[str]] = None,
        transforms=None,
    ):
        if not _H5:
            raise RuntimeError("h5py is not available")
        self.file = file
        self.dataset_names = dataset_names or ["data"]
        self.initial_load = initial_load
        self.load_length = load_length
        self.transforms = transforms
        with h5py.File(file, "r") as f:
            self.length = f[self.dataset_names[0]].shape[0]

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> "PartialH5DataLoaderIter":
        return PartialH5DataLoaderIter(self)


class PartialH5DataLoaderIter:
    """Windowed loader iterator (partial_dataset.py:224).

    A daemon thread running :func:`queue_thread` reads window ``i+1`` from
    the HDF5 file while window ``i`` is being consumed, so disk latency
    hides behind compute the way the reference's loader/convert threads do.
    """

    def __init__(self, dataset: PartialH5Dataset):
        self._ds = dataset
        self._pos = 0
        self._work: "queue.Queue" = queue.Queue()
        self._ready: "queue.Queue" = queue.Queue(maxsize=2)
        self._thread = threading.Thread(target=queue_thread, args=(self._work,), daemon=True)
        self._thread.start()
        self._windows_queued = 0
        self._queue_next_read()  # prime the pipeline

    def _read_window(self, start: int, stop: int) -> None:
        try:
            out = []
            with h5py.File(self._ds.file, "r") as f:
                for name in self._ds.dataset_names:
                    chunk = np.asarray(f[name][start:stop])
                    arr = jnp.asarray(chunk)
                    if self._ds.transforms is not None and callable(self._ds.transforms):
                        arr = self._ds.transforms(arr)
                    out.append(arr)
            self._ready.put(out[0] if len(out) == 1 else tuple(out))
        except BaseException as e:  # surface loader errors on the consumer side
            self._ready.put(e)

    def _queue_next_read(self) -> None:
        if self._pos >= self._ds.length:
            return
        stop = min(self._pos + self._ds.load_length, self._ds.length)
        self._work.put((self._read_window, self._pos, stop))
        self._pos = stop
        self._windows_queued += 1

    def close(self) -> None:
        """Retire the worker thread (safe to call more than once)."""
        if self._thread is not None:
            self._work.put(None)
            self._thread = None

    def __del__(self):
        self.close()

    def __iter__(self):
        return self

    def __next__(self):
        if self._windows_queued == 0 or self._thread is None:
            self.close()
            raise StopIteration
        batch = self._ready.get()
        self._windows_queued -= 1
        if isinstance(batch, BaseException):
            self.close()
            raise batch
        self._queue_next_read()  # overlap the next read with consumption
        return batch
