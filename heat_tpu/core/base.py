"""Estimator base classes, analog of heat/core/base.py (base.py:13-321)."""

from __future__ import annotations

import inspect
from typing import Dict, List, Optional

__all__ = [
    "BaseEstimator",
    "ClassificationMixin",
    "ClusteringMixin",
    "RegressionMixin",
    "TransformMixin",
    "is_classifier",
    "is_estimator",
    "is_clusterer",
    "is_regressor",
    "is_transformer",
    "lazy_scalar_property",
]


def lazy_scalar_property(attr: str, kind: type = float, doc: Optional[str] = None) -> property:
    """Property converting a stored device scalar to a host ``kind`` lazily.

    Fits store 0-d device values in ``attr`` so they never block on the
    device link; the host conversion happens once, on first access, and the
    converted value is cached back.  Shared by the cluster/PCA/Lasso/
    GaussianNB estimators (one pattern, one implementation)."""

    def fget(self):
        v = getattr(self, attr)
        if v is not None and not isinstance(v, kind):
            v = kind(v)
            setattr(self, attr, v)
        return v

    def fset(self, value):
        setattr(self, attr, value)

    return property(fget, fset, doc=doc or f"Lazy host {kind.__name__} of ``{attr}``.")


class BaseEstimator:
    """sklearn-compatible estimator base (base.py:13-95)."""

    @classmethod
    def _parameter_names(cls) -> List[str]:
        init = cls.__init__
        if init is object.__init__:
            return []
        sig = inspect.signature(init)
        return [
            name
            for name, p in sig.parameters.items()
            if name != "self" and p.kind not in (p.VAR_POSITIONAL, p.VAR_KEYWORD)
        ]

    def get_params(self, deep: bool = True) -> Dict:
        """Parameters of this estimator (base.py:30)."""
        params = {}
        for key in self._parameter_names():
            value = getattr(self, key, None)
            if deep and hasattr(value, "get_params"):
                for sub_key, sub_value in value.get_params().items():
                    params[f"{key}__{sub_key}"] = sub_value
            params[key] = value
        return params

    def set_params(self, **params) -> "BaseEstimator":
        """Set estimator parameters (base.py:60)."""
        if not params:
            return self
        valid = self.get_params(deep=True)
        for key, value in params.items():
            key, _, sub_key = key.partition("__")
            if key not in valid:
                raise ValueError(f"Invalid parameter {key} for estimator {self}.")
            if sub_key:
                valid[key].set_params(**{sub_key: value})
            else:
                setattr(self, key, value)
        return self

    def __repr__(self, indent: int = 1) -> str:
        params = ", ".join(f"{k}={v!r}" for k, v in self.get_params(deep=False).items())
        return f"{self.__class__.__name__}({params})"


class ClassificationMixin:
    """fit/predict protocol for classifiers (base.py:96)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


class TransformMixin:
    """fit/transform protocol (base.py:143)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_transform(self, x):
        return self.fit(x).transform(x)

    def transform(self, x):
        raise NotImplementedError()


class ClusteringMixin:
    """fit/fit_predict protocol for clusterers (base.py:184)."""

    def fit(self, x):
        raise NotImplementedError()

    def fit_predict(self, x):
        self.fit(x)
        return self.predict(x)


class RegressionMixin:
    """fit/predict protocol for regressors (base.py:215)."""

    def fit(self, x, y):
        raise NotImplementedError()

    def fit_predict(self, x, y):
        self.fit(x, y)
        return self.predict(x)

    def predict(self, x):
        raise NotImplementedError()


def is_classifier(estimator) -> bool:
    """True for classifiers (base.py:260)."""
    return isinstance(estimator, ClassificationMixin)


def is_estimator(estimator) -> bool:
    """True for estimators (base.py:275)."""
    return isinstance(estimator, BaseEstimator)


def is_clusterer(estimator) -> bool:
    """True for clusterers (base.py:290)."""
    return isinstance(estimator, ClusteringMixin)


def is_regressor(estimator) -> bool:
    """True for regressors (base.py:305)."""
    return isinstance(estimator, RegressionMixin)


def is_transformer(estimator) -> bool:
    """True for transformers (base.py:320)."""
    return isinstance(estimator, TransformMixin)
