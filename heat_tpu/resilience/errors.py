"""Typed failure taxonomy of the resilience layer.

The reference framework has exactly one failure mode: any raised
exception aborts the whole SPMD program.  This module splits failure
into classes the rest of the layer can act on mechanically:

* :class:`TransientFault` — a failure that a bounded retry is expected
  to clear (flaky filesystem, preempted bootstrap, injected test
  fault).  Subclasses ``OSError`` so the io retry filters treat real
  POSIX errors and injected transients identically.
* :class:`PermanentFault` — a failure retrying cannot fix.  The retry
  machinery re-raises it immediately, whatever the policy's filter
  says.
* :class:`ChecksumError` — a file's content does not match its CRC32
  sidecar: a torn or corrupted write that must fail loudly instead of
  returning garbage.  Never retried (the bytes on disk will not
  change).
* :class:`DivergenceError` — an iterative fit produced non-finite
  values.  Carries the last finite iterate and its iteration index so
  a caller can degrade gracefully (restart from ``last_good``, shrink
  the step, report a usable partial result).
"""

from __future__ import annotations

from typing import Any, Optional

__all__ = [
    "ResilienceError",
    "TransientFault",
    "PermanentFault",
    "ChecksumError",
    "DivergenceError",
]


class ResilienceError(Exception):
    """Base of every failure type the resilience layer raises."""


class TransientFault(ResilienceError, OSError):
    """A retryable failure (also raised by the fault injector for
    ``kind='transient'`` plan entries)."""

    def __init__(self, message: str = "transient fault", site: Optional[str] = None, index: Optional[int] = None):
        super().__init__(message)
        self.site = site
        self.index = index


class PermanentFault(ResilienceError, RuntimeError):
    """A non-retryable failure: the retry machinery re-raises it
    immediately (also raised for ``kind='permanent'`` plan entries)."""

    def __init__(self, message: str = "permanent fault", site: Optional[str] = None, index: Optional[int] = None):
        super().__init__(message)
        self.site = site
        self.index = index


class ChecksumError(ResilienceError, OSError):
    """File content disagrees with its CRC32 sidecar.  Excluded from
    retry: re-reading corrupt bytes yields the same corrupt bytes."""

    def __init__(self, path: str, expected: int, actual: int):
        super().__init__(
            f"checksum mismatch for {path!r}: sidecar records crc32 "
            f"{expected:#010x} but the file hashes to {actual:#010x} — "
            "the file is torn or corrupted; restore it from a replica "
            "or delete the sidecar to force an unverified load"
        )
        self.path = path
        self.expected = expected
        self.actual = actual


class DivergenceError(ResilienceError, ArithmeticError):
    """An iterative fit produced NaN/Inf.

    ``iteration`` is the first iteration at which non-finite values were
    observed; ``last_good`` is the most recent finite iterate (host
    numpy/None), so callers can resume or report it instead of silently
    converging to NaN.
    """

    def __init__(
        self,
        message: str,
        iteration: Optional[int] = None,
        last_good: Any = None,
        last_good_iteration: Optional[int] = None,
    ):
        super().__init__(message)
        self.iteration = iteration
        self.last_good = last_good
        self.last_good_iteration = last_good_iteration
