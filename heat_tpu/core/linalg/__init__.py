"""Distributed linear algebra (analog of heat/core/linalg)."""

from .basics import *
from .qr import *
from .svd import *
from .svdtools import *
from .solver import *
from .extras import *
