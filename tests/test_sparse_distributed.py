"""Distributed-sparse guarantees (VERDICT r3 #1): the nnz planes are
sharded over the mesh aligned to the compressed-axis chunks, accessors are
device programs (no host numpy), per-shard storage is the local share of
nnz (a matrix bigger than one device's budget can exist), and the CSC
layout computes natively at split=1.

Reference parity: heat/sparse/dcsx_matrix.py:19-423 (per-rank chunks +
nnz Exscan), heat/sparse/_operations.py:17-209 (split-aware binary ops).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import scipy.sparse as sp

import heat_tpu as ht


@pytest.fixture(scope="module")
def big():
    rng = np.random.default_rng(7)
    m = sp.random(1000, 700, density=0.02, random_state=3, format="csr", dtype=np.float64)
    return m


def test_planes_sharded_over_mesh(big):
    s = ht.sparse.sparse_csr_matrix(big, split=0)
    ndev = s.comm.size
    assert ndev == jax.device_count() > 1  # conftest virtual mesh (8 or 3)
    for plane in (s._comp, s._other, s._val, s._lnnz_dev):
        assert isinstance(plane, jax.Array)
        assert len(plane.sharding.device_set) == ndev
    # per-shard capacity is the max local share, NOT the global nnz:
    # storage per device is capacity, so a matrix whose nnz exceeds one
    # device's budget fits as long as nnz/P does.
    assert s._capacity < s.gnnz
    counts, displs = s.counts_displs_nnz()
    assert s._capacity == max(counts)
    assert sum(counts) == s.gnnz == big.nnz


def test_accessors_are_device_programs(big):
    s = ht.sparse.sparse_csr_matrix(big, split=0)
    for name in ("indptr", "indices", "data", "lindptr", "lindices", "ldata"):
        got = getattr(s, name)
        assert isinstance(got, jax.Array), f"{name} left the device"
    truth = big.tocsr()
    np.testing.assert_array_equal(np.asarray(s.indptr), truth.indptr)
    np.testing.assert_array_equal(np.asarray(s.indices), truth.indices)
    np.testing.assert_allclose(np.asarray(s.data), truth.data)


def test_ops_stay_sharded(big):
    other = sp.random(1000, 700, density=0.015, random_state=5, format="csr", dtype=np.float64)
    a = ht.sparse.sparse_csr_matrix(big, split=0)
    b = ht.sparse.sparse_csr_matrix(other, split=0)
    c = a + b
    assert len(c._val.sharding.device_set) == jax.device_count()
    np.testing.assert_allclose(c.toarray(), (big + other).toarray(), rtol=1e-12)
    d = a * b
    np.testing.assert_allclose(d.toarray(), big.multiply(other).toarray(), rtol=1e-12)
    # intersection compacts capacity to <= min of the operands'
    assert d._capacity <= min(a._capacity, b._capacity) + 1


def test_csr_spmm_distributed(big):
    rng = np.random.default_rng(11)
    x = rng.standard_normal((700, 40))
    s = ht.sparse.sparse_csr_matrix(big, split=0)
    out = s @ ht.array(x, split=0)
    assert out.split == 0
    np.testing.assert_allclose(out.numpy(), big @ x, rtol=1e-10)
    # matrix @ vector
    v = rng.standard_normal(700)
    got = s @ ht.array(v)
    np.testing.assert_allclose(got.numpy(), big @ v, rtol=1e-10)


def test_csc_native_split1_compute(big):
    csc = big.tocsc()
    s = ht.sparse.sparse_csc_matrix(csc, split=1)
    assert s.split == 1
    assert len(s._val.sharding.device_set) == jax.device_count()
    truth = csc
    np.testing.assert_array_equal(np.asarray(s.indptr), truth.indptr)
    np.testing.assert_array_equal(np.asarray(s.indices), truth.indices)
    np.testing.assert_allclose(np.asarray(s.data), truth.data)
    # A @ X contracts against the co-chunked dense rows + psum_scatter
    rng = np.random.default_rng(13)
    x = rng.standard_normal((700, 16))
    out = s @ ht.array(x, split=0)
    assert out.split == 0
    np.testing.assert_allclose(out.numpy(), big @ x, rtol=1e-10)
    # E @ A keeps whole output columns per shard (no collective)
    e = rng.standard_normal((9, 1000))
    out2 = ht.sparse.matmul(e, s)
    assert out2.split == 1
    np.testing.assert_allclose(out2.numpy(), e @ big.toarray(), rtol=1e-10)
    # reductions
    np.testing.assert_allclose(float(s.sum()), big.sum(), rtol=1e-12)
    np.testing.assert_allclose(s.sum(axis=0).numpy(), np.asarray(big.sum(0)).ravel(), rtol=1e-10)
    np.testing.assert_allclose(s.sum(axis=1).numpy(), np.asarray(big.sum(1)).ravel(), rtol=1e-10)
    # elementwise at split=1
    o = sp.random(1000, 700, density=0.01, random_state=9, format="csc", dtype=np.float64)
    b = ht.sparse.sparse_csc_matrix(o, split=1)
    np.testing.assert_allclose((s + b).toarray(), (big + o).toarray(), rtol=1e-12)
    np.testing.assert_allclose((s * b).toarray(), big.multiply(o).toarray(), rtol=1e-12)


def test_mixed_split_aligns(big):
    a = ht.sparse.sparse_csr_matrix(big, split=0)
    b = ht.sparse.sparse_csr_matrix(big)  # split=None
    c = a + b
    assert c.split == 0
    np.testing.assert_allclose(c.toarray(), (2 * big).toarray(), rtol=1e-12)


def test_scalar_mul(big):
    a = ht.sparse.sparse_csr_matrix(big, split=0)
    c = a * 2.5
    assert c.gnnz == a.gnnz
    np.testing.assert_allclose(c.toarray(), (big * 2.5).toarray(), rtol=1e-12)
    np.testing.assert_allclose((0.5 * a).toarray(), (big * 0.5).toarray(), rtol=1e-12)
    # float scalar on an integer matrix promotes (dense numpy semantics)
    imat = sp.csr_matrix(np.array([[2, 0], [0, 3]], np.int32))
    got = ht.sparse.sparse_csr_matrix(imat, split=0) * 1.5
    assert got.dtype in (ht.float32, ht.float64)
    np.testing.assert_allclose(got.toarray(), [[3.0, 0.0], [0.0, 4.5]])


def test_spgemm_distributed(big):
    other = sp.random(700, 300, density=0.02, random_state=21, format="csr", dtype=np.float64)
    a = ht.sparse.sparse_csr_matrix(big, split=0)
    b = ht.sparse.sparse_csr_matrix(other, split=0)
    c = a @ b
    assert isinstance(c, ht.sparse.DCSR_matrix)
    assert c.split == 0
    np.testing.assert_allclose(c.toarray(), (big @ other).toarray(), rtol=1e-10)


def test_transpose_is_metadata(big):
    s = ht.sparse.sparse_csr_matrix(big, split=0)
    t = s.T
    assert isinstance(t, ht.sparse.DCSC_matrix) and t.split == 1
    # the planes are shared, not copied or re-communicated
    assert t._val is s._val and t._comp is s._comp
    np.testing.assert_allclose(t.toarray(), big.T.toarray(), rtol=1e-12)
    tt = t.T
    assert isinstance(tt, ht.sparse.DCSR_matrix) and tt.split == 0


def test_empty_and_tiny():
    z = sp.csr_matrix((6, 4))
    s = ht.sparse.sparse_csr_matrix(z, split=0)
    assert s.gnnz == 0
    np.testing.assert_allclose(s.toarray(), np.zeros((6, 4)))
    np.testing.assert_array_equal(np.asarray(s.indptr), np.zeros(7, np.int64))
    one = sp.csr_matrix(np.eye(3, dtype=np.float32))
    so = ht.sparse.sparse_csr_matrix(one, split=0)
    np.testing.assert_allclose((so + so).toarray(), 2 * np.eye(3))
