"""Sparse factories, analog of heat/sparse/factories.py
(sparse_csr_matrix/sparse_csc_matrix, factories.py:25-376)."""

from __future__ import annotations

from typing import Optional, Type, Union

import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core import types
from ..core.devices import sanitize_device
from ..core.dndarray import DNDarray
from ..parallel.comm import sanitize_comm
from .dcsx_matrix import DCSC_matrix, DCSR_matrix, DCSX_matrix

__all__ = ["sparse_csr_matrix", "sparse_csc_matrix"]


def _ingest(obj, dtype):
    """Accept dense arrays/DNDarrays, scipy sparse, torch sparse, or jax
    BCOO/BCSR (the reference accepts torch/scipy, factories.py:60-200)."""
    if isinstance(obj, DCSX_matrix):
        return obj.larray
    if isinstance(obj, jsparse.BCOO):
        return obj
    if isinstance(obj, jsparse.BCSR):
        return obj.to_bcoo()
    if isinstance(obj, DNDarray):
        return jsparse.BCOO.fromdense(obj._dense())
    # scipy sparse
    if hasattr(obj, "tocoo") and callable(obj.tocoo):
        coo = obj.tocoo()
        idx = jnp.stack([jnp.asarray(coo.row), jnp.asarray(coo.col)], axis=1)
        return jsparse.BCOO((jnp.asarray(coo.data), idx), shape=coo.shape)
    # torch sparse
    if hasattr(obj, "is_sparse") and getattr(obj, "is_sparse", False):
        coo = obj.coalesce()
        idx = jnp.asarray(np.asarray(coo.indices()).T)
        return jsparse.BCOO((jnp.asarray(np.asarray(coo.values())), idx), shape=tuple(obj.shape))
    if hasattr(obj, "layout"):  # torch CSR/CSC
        dense = np.asarray(obj.to_dense())
        return jsparse.BCOO.fromdense(jnp.asarray(dense))
    arr = jnp.asarray(np.asarray(obj))
    return jsparse.BCOO.fromdense(arr)


def _make(
    cls: Type[DCSX_matrix],
    obj,
    dtype=None,
    split: Optional[int] = None,
    is_split: Optional[int] = None,
    device=None,
    comm=None,
) -> DCSX_matrix:
    comm = sanitize_comm(comm)
    device = sanitize_device(device)
    if split is not None and is_split is not None:
        raise ValueError("split and is_split are mutually exclusive")
    split = split if split is not None else is_split
    allowed = 0 if cls is DCSR_matrix else 1
    if split is not None and split != allowed:
        raise ValueError(
            f"{cls.__name__} only supports split={allowed} or None, got {split} "
            "(matching the reference, dcsx_matrix.py:30)"
        )
    bcoo = _ingest(obj, dtype)
    if bcoo.ndim != 2:
        raise ValueError(f"sparse matrices must be 2-dimensional, got {bcoo.ndim}")
    if dtype is not None:
        dtype = types.canonical_heat_type(dtype)
        bcoo = jsparse.BCOO((bcoo.data.astype(dtype.jax_type()), bcoo.indices), shape=bcoo.shape)
    else:
        dtype = types.canonical_heat_type(bcoo.data.dtype)
    bcoo = jsparse.bcoo_sum_duplicates(jsparse.bcoo_sort_indices(bcoo))
    gnnz = int(bcoo.nse)
    return cls(bcoo, gnnz, tuple(bcoo.shape), dtype, split, device, comm)


def sparse_csr_matrix(obj, dtype=None, copy=None, ndmin: int = 0, order=None, split=None, is_split=None, device=None, comm=None) -> DCSR_matrix:
    """Create a DCSR_matrix (factories.py:25)."""
    return _make(DCSR_matrix, obj, dtype, split, is_split, device, comm)


def sparse_csc_matrix(obj, dtype=None, copy=None, ndmin: int = 0, order=None, split=None, is_split=None, device=None, comm=None) -> DCSC_matrix:
    """Create a DCSC_matrix (factories.py:200)."""
    return _make(DCSC_matrix, obj, dtype, split, is_split, device, comm)
