"""Tile decompositions, analog of heat/core/tiling.py.

The reference uses these classes to derive MPI subarray datatypes for the
one-shot ``Alltoallw`` resplit (``SplitTiles.get_subarray_params``
tiling.py:331) and for the legacy tile-wise QR/Cholesky algorithms
(``SquareDiagTiles`` tiling.py:415).  On TPU the resplit is a single
``device_put`` with a new ``NamedSharding`` (XLA emits the all-to-all), so
the subarray machinery disappears; what remains useful — and is kept here —
is the *metadata*: the theoretical per-participant tile grid in every
dimension, tile lookups, and the square-diagonal decomposition.

Data access happens against the global dense array (single-controller SPMD:
every participant can address any tile); ``tile_locations`` still reports
the owning participant so collective algorithms can be written against the
same grid the reference uses.
"""

from __future__ import annotations

from typing import List, Optional, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from .dndarray import DNDarray

__all__ = ["SplitTiles", "SquareDiagTiles"]


def _even_chunks(size: int, parts: int) -> np.ndarray:
    """Remainder-spread chunk sizes, used to carve tile rows *within* one
    participant's block (tiling.py:~650 column creation)."""
    chunk, rem = divmod(size, parts)
    out = np.full(parts, chunk, dtype=np.int64)
    out[:rem] += 1
    return out


def _addressable(arr: DNDarray, owners) -> bool:
    """Whether the calling process controls any of the owning participants.

    The reference gates tile access on ``comm.rank`` because every MPI rank
    is its own process; here a participant is a mesh device, so the analog
    is "one of my devices owns this tile" — in single-controller mode that
    is every tile."""
    comm = arr.comm
    me = jax.process_index()
    return any(comm.devices[int(o)].process_index == me for o in np.atleast_1d(owners).ravel())


class SplitTiles:
    """Tiles of a DNDarray: the chunk grid obtained by chunking *every*
    dimension over ``comm.size`` (tiling.py:17-370).

    The split dimension uses the array's actual local shapes; every other
    dimension uses the theoretical remainder-spread chunking.
    """

    def __init__(self, arr: DNDarray) -> None:
        self.__arr = arr
        lshape_map = arr.lshape_map
        ndim, size = arr.ndim, arr.comm.size
        # one chunk policy for every dimension — the canonical (padded)
        # distribution the comm layer actually uses — so the grid is
        # identical however the array is currently split.  The split axis
        # follows the REPORTED layout (ragged-aware), keeping the tile grid
        # consistent with lshape_map/__partitioned__ after redistribute_.
        tile_dims = np.zeros((ndim, size), dtype=np.int64)
        for ax in range(ndim):
            if ax == arr.split:
                tile_dims[ax] = lshape_map[:, ax]
            else:
                tile_dims[ax] = arr.comm.lshape_map(arr.gshape, ax)[:, ax]
        self.__tile_dims = tile_dims
        self.__tile_ends_g = np.cumsum(tile_dims, axis=1).astype(np.int64)
        self.__tile_locations = self.set_tile_locations(arr.split, tile_dims, arr)
        self.__lshape_map = lshape_map

    @staticmethod
    def set_tile_locations(split: Optional[int], tile_dims: np.ndarray, arr: DNDarray) -> np.ndarray:
        """Grid (size ^ ndim) of owning participant per tile (tiling.py:111)."""
        grid_shape = [tile_dims[d].size for d in range(arr.ndim)]
        locations = np.zeros(grid_shape, dtype=np.int64)
        if split is None:
            locations += arr.comm.rank
            return locations
        sl = [slice(None)] * arr.ndim
        for pr in range(1, arr.comm.size):
            sl[split] = pr
            locations[tuple(sl)] = pr
        return locations

    @property
    def arr(self) -> DNDarray:
        """The tiled DNDarray (tiling.py:140)."""
        return self.__arr

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, ndim) local shapes (tiling.py:147)."""
        return self.__lshape_map

    @property
    def tile_locations(self) -> np.ndarray:
        """Owning participant of each tile (tiling.py:154)."""
        return self.__tile_locations

    @property
    def tile_ends_g(self) -> np.ndarray:
        """Global end index of each tile per dimension (tiling.py:165)."""
        return self.__tile_ends_g

    @property
    def tile_dimensions(self) -> np.ndarray:
        """Tile extents per dimension (tiling.py:176)."""
        return self.__tile_dims

    def __tile_slices(self, key) -> Tuple[slice, ...]:
        """Convert tile-grid indices to global index slices."""
        arr = self.__arr
        if isinstance(key, (int, np.integer, slice)):
            key = (key,)
        key = tuple(key) + (slice(None),) * (arr.ndim - len(key))
        out = []
        for d, k in enumerate(key):
            ends = self.__tile_ends_g[d]
            if isinstance(k, (int, np.integer)):
                if k < 0:
                    k += ends.size
                start = int(ends[k - 1]) if k > 0 else 0
                stop = int(ends[k])
            elif isinstance(k, slice):
                idx = np.arange(ends.size)[k]
                if idx.size == 0:
                    start = stop = 0
                else:
                    start = int(ends[idx[0] - 1]) if idx[0] > 0 else 0
                    stop = int(ends[idx[-1]])
            else:
                raise TypeError(f"key type not supported: {type(k)}")
            out.append(slice(start, stop))
        return tuple(out)

    def get_tile_size(self, key) -> Tuple[int, ...]:
        """Extent of the tile(s) selected by ``key`` (tiling.py:285)."""
        return tuple(sl.stop - sl.start for sl in self.__tile_slices(key))

    def __getitem__(self, key) -> Optional[jnp.ndarray]:
        """The tile's data (tiling.py:182) — global indexing against the
        dense array; ``None`` when none of this process's devices own any
        part of it."""
        if not _addressable(self.__arr, self.__tile_locations[key]):
            return None
        return self.__arr._dense()[self.__tile_slices(key)]

    def __setitem__(self, key, value) -> None:
        """Overwrite the tile's data (tiling.py:300)."""
        if jax.process_count() > 1:  # pragma: no cover - multi-host
            # every controller must issue identical updates on the shared
            # global array; a rank-gated write would diverge the replicas
            raise NotImplementedError("tile writes across hosts: use global __setitem__")
        if not _addressable(self.__arr, self.__tile_locations[key]):
            return
        sl = self.__tile_slices(key)
        dense = self.__arr._dense()
        value = jnp.asarray(value, dense.dtype)
        new = dense.at[sl].set(jnp.broadcast_to(value, dense[sl].shape))
        from .dndarray import _pad_to_canonical

        self.__arr._replace(_pad_to_canonical(new, self.__arr.gshape, self.__arr.split, self.__arr.comm))


class SquareDiagTiles:
    """Tile decomposition with square tiles on the diagonal
    (tiling.py:371-1100), the layout used by tile-wise QR/Cholesky.

    ``tiles_per_proc`` row-tiles are carved from every participant's row
    block; column boundaries mirror the row boundaries up to ``min(m, n)``
    so diagonal tiles are square, with one remainder column tile.
    Only 2-D arrays with ``split in (0, 1)`` are supported (as in the
    reference, tiling.py:430-447).
    """

    def __init__(self, arr: DNDarray, tiles_per_proc: int = 2) -> None:
        if not isinstance(tiles_per_proc, int) or tiles_per_proc < 1:
            raise ValueError(f"tiles_per_proc must be a positive int, got {tiles_per_proc}")
        if arr.ndim != 2:
            raise ValueError(f"SquareDiagTiles requires a 2-D DNDarray, got {arr.ndim}-D")
        if arr.split not in (0, 1):
            raise ValueError(f"SquareDiagTiles requires split 0 or 1, got {arr.split}")
        m, n = arr.gshape
        lshape_map = arr.lshape_map
        split = arr.split

        # row/col boundaries: tiles_per_proc tiles per participant block
        # along the split dim; the other dim mirrors them to stay square on
        # the diagonal, with a single remainder tile past min(m, n).
        block_sizes = lshape_map[:, split]
        bounds: List[int] = []
        pos = 0
        for b in block_sizes:
            for c in _even_chunks(int(b), tiles_per_proc):
                if c > 0:
                    pos += int(c)
                    bounds.append(pos)
        split_idx = bounds
        diag_len = min(m, n)
        split_len = m if split == 0 else n
        other_len = n if split == 0 else m

        def _diag_cut(cuts: List[int], extent: int) -> List[int]:
            """Keep cuts inside the diagonal block, force a cut exactly at
            the diagonal edge, and one remainder tile past it — so every
            diagonal tile is square (the invariant tile-wise QR/Cholesky
            needs; reference redistributes rows for the same effect,
            tiling.py:589-646)."""
            out = [b for b in cuts if b < diag_len] + [diag_len]
            if extent > diag_len:
                out.append(extent)
            return out

        if split == 0:
            row_bounds = _diag_cut(split_idx, split_len) if m > n else split_idx
            col_bounds = _diag_cut(split_idx, other_len)
        else:
            col_bounds = _diag_cut(split_idx, split_len) if n > m else split_idx
            row_bounds = _diag_cut(split_idx, other_len)
        self.__row_inds = [0] + row_bounds[:-1]
        self.__col_inds = [0] + col_bounds[:-1]
        self.__row_bounds = row_bounds
        self.__col_bounds = col_bounds
        self.__arr = arr
        self.__lshape_map = lshape_map

        # tile_map[r, c] = (row_start, col_start, owner)
        nrows, ncols = len(row_bounds), len(col_bounds)
        tmap = np.zeros((nrows, ncols, 3), dtype=np.int64)
        ends = np.cumsum(block_sizes)
        for r in range(nrows):
            for c in range(ncols):
                rs = self.__row_inds[r]
                cs = self.__col_inds[c]
                along = rs if split == 0 else cs
                owner = int(np.searchsorted(ends, along, side="right"))
                tmap[r, c] = (rs, cs, owner)
        self.__tile_map = tmap
        per_proc = np.zeros(arr.comm.size, dtype=np.int64)
        starts = [t[2] for t in tmap[:, 0]] if split == 0 else [t[2] for t in tmap[0, :]]
        for o in starts:
            per_proc[o] += 1
        self.__tiles_per_proc = per_proc
        diag_bound = next((i for i, b in enumerate(ends) if b >= diag_len), arr.comm.size - 1)
        self.__last_diag_pr = diag_bound

    @property
    def arr(self) -> DNDarray:
        """The tiled DNDarray (tiling.py:763)."""
        return self.__arr

    @property
    def col_indices(self) -> List[int]:
        """Global start column of each tile column (tiling.py:770)."""
        return list(self.__col_inds)

    @property
    def row_indices(self) -> List[int]:
        """Global start row of each tile row (tiling.py:792)."""
        return list(self.__row_inds)

    @property
    def lshape_map(self) -> np.ndarray:
        """(size, 2) local shapes (tiling.py:777)."""
        return self.__lshape_map

    @property
    def last_diagonal_process(self) -> int:
        """Rank of the last participant holding diagonal tiles (tiling.py:785)."""
        return self.__last_diag_pr

    @property
    def tile_columns(self) -> int:
        """Number of tile columns (tiling.py:799)."""
        return len(self.__col_bounds)

    @property
    def tile_columns_per_process(self) -> List[int]:
        """Tile columns owned per participant (tiling.py:806)."""
        if self.__arr.split == 1:
            return [int(x) for x in self.__tiles_per_proc]
        return [self.tile_columns] * self.__arr.comm.size

    @property
    def tile_map(self) -> np.ndarray:
        """(rows, cols, 3) array of (row_start, col_start, owner) (tiling.py:813)."""
        return self.__tile_map

    @property
    def tile_rows(self) -> int:
        """Number of tile rows (tiling.py:849)."""
        return len(self.__row_bounds)

    @property
    def tile_rows_per_process(self) -> List[int]:
        """Tile rows owned per participant (tiling.py:856)."""
        if self.__arr.split == 0:
            return [int(x) for x in self.__tiles_per_proc]
        return [self.tile_rows] * self.__arr.comm.size

    def get_start_stop(self, key) -> Tuple[int, int, int, int]:
        """(row_start, row_stop, col_start, col_stop) in *global* indices for
        the tile(s) at ``key`` (tiling.py:862; the reference returns
        process-local indices — global is the single-controller analog)."""
        r, c = key if isinstance(key, tuple) else (key, slice(None))

        def _bounds(k, inds, bounds):
            if isinstance(k, (int, np.integer)):
                if k < 0:
                    k += len(bounds)
                return inds[k], bounds[k]
            idx = list(range(len(bounds)))[k]
            return inds[idx[0]], bounds[idx[-1]]

        r0, r1 = _bounds(r, self.__row_inds, self.__row_bounds)
        c0, c1 = _bounds(c, self.__col_inds, self.__col_bounds)
        return r0, r1, c0, c1

    def __getitem__(self, key) -> Optional[jnp.ndarray]:
        """Tile data on the owning participant, else ``None`` (tiling.py:928)."""
        r0, r1, c0, c1 = self.get_start_stop(key)
        if not _addressable(self.__arr, self.__owners(key)):
            return None
        return self.__arr._dense()[r0:r1, c0:c1]

    def __owners(self, key) -> np.ndarray:
        r, c = key if isinstance(key, tuple) else (key, slice(None))
        return np.atleast_1d(self.__tile_map[r, c][..., 2]).ravel()

    def local_get(self, key) -> jnp.ndarray:
        """Tile data addressed in this participant's local tile grid
        (tiling.py:975) — single-controller: same global grid."""
        r0, r1, c0, c1 = self.get_start_stop(key)
        return self.__arr._dense()[r0:r1, c0:c1]

    def local_set(self, key, value) -> None:
        """Set a tile addressed in the local grid (tiling.py:995)."""
        self.__setitem__(key, value)

    def local_to_global(self, key, rank: int) -> Tuple[int, int]:
        """Translate a participant-local tile index into the global tile
        grid (tiling.py:1058)."""
        r, c = key if isinstance(key, tuple) else (key, 0)
        if self.__arr.split == 0:
            offset = int(np.sum(self.__tiles_per_proc[:rank]))
            return r + offset, c
        offset = int(np.sum(self.__tiles_per_proc[:rank]))
        return r, c + offset

    def __setitem__(self, key, value) -> None:
        """Overwrite tile data (tiling.py:1246)."""
        if jax.process_count() > 1:  # pragma: no cover - multi-host
            raise NotImplementedError("tile writes across hosts: use global __setitem__")
        r0, r1, c0, c1 = self.get_start_stop(key)
        dense = self.__arr._dense()
        value = jnp.asarray(value, dense.dtype)
        new = dense.at[r0:r1, c0:c1].set(jnp.broadcast_to(value, dense[r0:r1, c0:c1].shape))
        from .dndarray import _pad_to_canonical

        self.__arr._replace(_pad_to_canonical(new, self.__arr.gshape, self.__arr.split, self.__arr.comm))
