"""Exponential/logarithmic operations, analog of heat/core/exponential.py."""

from __future__ import annotations

import jax.numpy as jnp

from ._operations import __binary_op as _binary_op
from ._operations import __local_op as _local_op

__all__ = [
    "cbrt",
    "exp",
    "expm1",
    "exp2",
    "frexp",
    "ldexp",
    "log",
    "log2",
    "log10",
    "log1p",
    "logaddexp",
    "logaddexp2",
    "nextafter",
    "reciprocal",
    "spacing",
    "sqrt",
    "square",
]


def exp(x, out=None):
    """e**x (exponential.py:15)."""
    return _local_op(jnp.exp, x, out)


def expm1(x, out=None):
    """e**x - 1 (exponential.py:51)."""
    return _local_op(jnp.expm1, x, out)


def exp2(x, out=None):
    """2**x (exponential.py:87)."""
    return _local_op(jnp.exp2, x, out)


def log(x, out=None):
    """Natural logarithm (exponential.py:123)."""
    return _local_op(jnp.log, x, out)


def log2(x, out=None):
    """Base-2 logarithm (exponential.py:161)."""
    return _local_op(jnp.log2, x, out)


def log10(x, out=None):
    """Base-10 logarithm (exponential.py:199)."""
    return _local_op(jnp.log10, x, out)


def log1p(x, out=None):
    """log(1 + x) (exponential.py:237)."""
    return _local_op(jnp.log1p, x, out)


def logaddexp(t1, t2):
    """log(exp(t1) + exp(t2)) (exponential.py:275)."""
    return _binary_op(jnp.logaddexp, t1, t2)


def logaddexp2(t1, t2):
    """log2(2**t1 + 2**t2) (exponential.py:297)."""
    return _binary_op(jnp.logaddexp2, t1, t2)


def sqrt(x, out=None):
    """Square root (exponential.py:318)."""
    return _local_op(jnp.sqrt, x, out)


def square(x, out=None):
    """x*x (exponential.py:282 analog)."""
    return _local_op(jnp.square, x, out, no_cast=True)


def pow_scalar_base(base, exponent):
    """base ** exponent for scalar base (helper for logspace)."""
    from . import arithmetics

    return arithmetics.pow(base, exponent)


def cbrt(x, out=None):
    """Cube root (numpy extension beyond the reference's checklist)."""
    return _local_op(jnp.cbrt, x, out)


def reciprocal(x, out=None):
    """1/x elementwise (numpy extension beyond the reference)."""
    return _local_op(jnp.reciprocal, x, out)


def frexp(x, out=None):
    """Decompose x into mantissa and twos exponent (numpy extension).

    Returns ``(mantissa, exponent)`` DNDarrays with the input's split."""
    if out is not None:
        raise NotImplementedError("frexp does not support out=")
    from . import types
    from .dndarray import DNDarray

    if not isinstance(x, DNDarray):
        raise TypeError(f"expected x to be a DNDarray, but was {type(x)}")
    arr = x.larray_padded
    if not types.heat_type_is_inexact(x.dtype):
        arr = arr.astype(jnp.float32)
    mant, expo = jnp.frexp(arr)

    def _wrap(r):
        return DNDarray(r, x.shape, types.canonical_heat_type(r.dtype), x.split, x.device, x.comm)

    return _wrap(mant), _wrap(expo)


def ldexp(t1, t2):
    """t1 * 2**t2 (numpy extension beyond the reference)."""
    return _binary_op(jnp.ldexp, t1, t2)


def nextafter(t1, t2):
    """Next representable float after t1 towards t2 (numpy extension)."""
    return _binary_op(jnp.nextafter, t1, t2)


def spacing(x, out=None):
    """Distance to the nearest adjacent float (numpy extension)."""
    return _local_op(jnp.spacing, x, out)
