"""Out-of-core HDF5 dataset, analog of heat/utils/data/partial_dataset.py.

The reference's ``PartialH5Dataset`` (partial_dataset.py:32) threads HDF5
chunk reads and overlaps load/convert with training via a custom loader
iterator (:224).  Here the same overlap comes from JAX's asynchronous
dispatch: each `__iter__` round reads the next HDF5 slab on host while the
device still executes the previous batch.
"""

from __future__ import annotations

from typing import Iterator, List, Optional

import jax.numpy as jnp
import numpy as np

from ...core.dndarray import DNDarray

__all__ = ["PartialH5Dataset", "PartialH5DataLoaderIter"]

try:
    import h5py

    _H5 = True
except ImportError:  # pragma: no cover
    _H5 = False


class PartialH5Dataset:
    """Stream a large HDF5 dataset in windows (partial_dataset.py:32)."""

    def __init__(
        self,
        file: str,
        comm=None,
        dataset_names: Optional[List[str]] = None,
        initial_load: int = 7000,
        load_length: int = 1000,
        use_gpu: bool = True,
        np_buffer: bool = True,
        np_buffer_dataset_names: Optional[List[str]] = None,
        transforms=None,
    ):
        if not _H5:
            raise RuntimeError("h5py is not available")
        self.file = file
        self.dataset_names = dataset_names or ["data"]
        self.initial_load = initial_load
        self.load_length = load_length
        self.transforms = transforms
        with h5py.File(file, "r") as f:
            self.length = f[self.dataset_names[0]].shape[0]

    def __len__(self) -> int:
        return self.length

    def __iter__(self) -> "PartialH5DataLoaderIter":
        return PartialH5DataLoaderIter(self)


class PartialH5DataLoaderIter:
    """Windowed loader iterator (partial_dataset.py:224)."""

    def __init__(self, dataset: PartialH5Dataset):
        self._ds = dataset
        self._pos = 0

    def __iter__(self):
        return self

    def __next__(self):
        if self._pos >= self._ds.length:
            raise StopIteration
        stop = min(self._pos + self._ds.load_length, self._ds.length)
        out = []
        with h5py.File(self._ds.file, "r") as f:
            for name in self._ds.dataset_names:
                chunk = np.asarray(f[name][self._pos : stop])
                arr = jnp.asarray(chunk)
                if self._ds.transforms is not None and callable(self._ds.transforms):
                    arr = self._ds.transforms(arr)
                out.append(arr)
        self._pos = stop
        return out[0] if len(out) == 1 else tuple(out)
